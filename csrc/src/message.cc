// Binary (de)serialization for the controller wire protocol
// (role of reference horovod/common/message.cc + wire/message.fbs).
//
// Format: little-endian, length-prefixed strings, u32 counts. Both ends are
// this same library, so no cross-version compatibility machinery is needed.

#include "hvd/common.h"

#include <cstring>
#include <sstream>

namespace hvd {

std::string TensorShape::DebugString() const {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < dims_.size(); ++i) {
    if (i) os << ", ";
    os << dims_[i];
  }
  os << "]";
  return os.str();
}

const char* Request::TypeName(int t) {
  switch (t) {
    case ALLREDUCE: return "ALLREDUCE";
    case ALLGATHER: return "ALLGATHER";
    case BROADCAST: return "BROADCAST";
    case JOIN: return "JOIN";
    case ADASUM: return "ADASUM";
    case ALLTOALL: return "ALLTOALL";
    case REDUCESCATTER: return "REDUCESCATTER";
    case BARRIER: return "BARRIER";
  }
  return "UNKNOWN";
}

const char* Response::TypeName(int t) {
  switch (t) {
    case ALLREDUCE: return "ALLREDUCE";
    case ALLGATHER: return "ALLGATHER";
    case BROADCAST: return "BROADCAST";
    case JOIN: return "JOIN";
    case ADASUM: return "ADASUM";
    case ALLTOALL: return "ALLTOALL";
    case REDUCESCATTER: return "REDUCESCATTER";
    case BARRIER: return "BARRIER";
    case ERROR: return "ERROR";
  }
  return "UNKNOWN";
}

namespace {

class Writer {
 public:
  explicit Writer(std::string* out) : out_(out) {}
  void U32(uint32_t v) { Raw(&v, sizeof(v)); }
  void I32(int32_t v) { Raw(&v, sizeof(v)); }
  void I64(int64_t v) { Raw(&v, sizeof(v)); }
  void F64(double v) { Raw(&v, sizeof(v)); }
  void B(bool v) {
    uint8_t b = v ? 1 : 0;
    Raw(&b, 1);
  }
  void Str(const std::string& s) {
    U32(static_cast<uint32_t>(s.size()));
    out_->append(s);
  }
  void Shape(const TensorShape& s) {
    U32(static_cast<uint32_t>(s.ndim()));
    for (auto d : s.dims()) I64(d);
  }

 private:
  void Raw(const void* p, size_t n) {
    out_->append(reinterpret_cast<const char*>(p), n);
  }
  std::string* out_;
};

class Reader {
 public:
  Reader(const char* data, size_t len) : p_(data), end_(data + len) {}
  bool U32(uint32_t* v) { return Raw(v, sizeof(*v)); }
  bool I32(int32_t* v) { return Raw(v, sizeof(*v)); }
  bool I64(int64_t* v) { return Raw(v, sizeof(*v)); }
  bool F64(double* v) { return Raw(v, sizeof(*v)); }
  bool B(bool* v) {
    uint8_t b;
    if (!Raw(&b, 1)) return false;
    *v = b != 0;
    return true;
  }
  bool Str(std::string* s) {
    uint32_t n;
    if (!U32(&n)) return false;
    if (p_ + n > end_) return false;
    s->assign(p_, n);
    p_ += n;
    return true;
  }
  // Read a element count and sanity-bound it against the bytes actually
  // left in the buffer (each element costs >= min_elem bytes): a corrupted
  // count like 0xFFFFFFFF must fail fast, not drive a multi-GB resize.
  bool Count(uint32_t* n, size_t min_elem) {
    if (!U32(n)) return false;
    return static_cast<size_t>(*n) <= Remaining() / min_elem;
  }
  size_t Remaining() const { return static_cast<size_t>(end_ - p_); }

  bool Shape(TensorShape* s) {
    uint32_t n;
    if (!Count(&n, sizeof(int64_t))) return false;
    std::vector<int64_t> dims(n);
    for (uint32_t i = 0; i < n; ++i) {
      if (!I64(&dims[i])) return false;
    }
    *s = TensorShape(std::move(dims));
    return true;
  }

 private:
  bool Raw(void* v, size_t n) {
    if (p_ + n > end_) return false;
    std::memcpy(v, p_, n);
    p_ += n;
    return true;
  }
  const char* p_;
  const char* end_;
};

}  // namespace

void SerializeRequestList(const RequestList& in, std::string* out) {
  Writer w(out);
  w.B(in.shutdown);
  w.U32(static_cast<uint32_t>(in.requests.size()));
  for (const auto& r : in.requests) {
    w.I32(r.request_rank);
    w.I32(r.request_type);
    w.I32(r.tensor_type);
    w.I32(r.root_rank);
    w.I32(r.reduce_op);
    w.Str(r.tensor_name);
    w.Str(r.axis_name);
    w.Shape(r.tensor_shape);
    w.F64(r.prescale_factor);
    w.F64(r.postscale_factor);
  }
}

bool ParseRequestList(const char* data, size_t len, RequestList* out) {
  Reader rd(data, len);
  uint32_t n;
  // min request wire size: 5xI32 + 2 empty Str + empty Shape + 2xF64
  if (!rd.B(&out->shutdown) || !rd.Count(&n, 48)) return false;
  out->requests.resize(n);
  for (uint32_t i = 0; i < n; ++i) {
    Request& r = out->requests[i];
    if (!rd.I32(&r.request_rank) || !rd.I32(&r.request_type) ||
        !rd.I32(&r.tensor_type) || !rd.I32(&r.root_rank) ||
        !rd.I32(&r.reduce_op) || !rd.Str(&r.tensor_name) ||
        !rd.Str(&r.axis_name) ||
        !rd.Shape(&r.tensor_shape) || !rd.F64(&r.prescale_factor) ||
        !rd.F64(&r.postscale_factor)) {
      return false;
    }
  }
  return true;
}

void SerializeResponseList(const ResponseList& in, std::string* out) {
  Writer w(out);
  w.B(in.shutdown);
  w.F64(in.tuned_cycle_time_ms);
  w.I64(in.tuned_fusion_threshold);
  w.I32(in.tuned_cache_enabled);
  w.U32(static_cast<uint32_t>(in.responses.size()));
  for (const auto& r : in.responses) {
    w.I32(r.response_type);
    w.U32(static_cast<uint32_t>(r.tensor_names.size()));
    for (const auto& s : r.tensor_names) w.Str(s);
    w.Str(r.error_message);
    w.U32(static_cast<uint32_t>(r.tensor_sizes.size()));
    for (auto v : r.tensor_sizes) w.I64(v);
    w.U32(static_cast<uint32_t>(r.tensor_dtypes.size()));
    for (auto v : r.tensor_dtypes) w.I32(v);
    w.U32(static_cast<uint32_t>(r.tensor_output_elements.size()));
    for (auto v : r.tensor_output_elements) w.I64(v);
    w.U32(static_cast<uint32_t>(r.tensor_shapes.size()));
    for (const auto& s : r.tensor_shapes) w.Shape(s);
    w.I32(r.tensor_type);
    w.I32(r.root_rank);
    w.I32(r.reduce_op);
    w.Str(r.axis_name);
    w.F64(r.prescale_factor);
    w.F64(r.postscale_factor);
  }
  // optional tail (see ResponseList): hierarchical toggles
  w.I32(in.tuned_hier_allreduce);
  w.I32(in.tuned_hier_allgather);
}

bool ParseResponseList(const char* data, size_t len, ResponseList* out) {
  Reader rd(data, len);
  uint32_t n;
  if (!rd.B(&out->shutdown) || !rd.F64(&out->tuned_cycle_time_ms) ||
      !rd.I64(&out->tuned_fusion_threshold) ||
      !rd.I32(&out->tuned_cache_enabled) ||
      // min response wire size: 4xI32 + 6 empty counts/Str + Str + 2xF64
      !rd.Count(&n, 60)) {
    return false;
  }
  out->responses.resize(n);
  for (uint32_t i = 0; i < n; ++i) {
    Response& r = out->responses[i];
    uint32_t names, sizes;
    if (!rd.I32(&r.response_type) || !rd.Count(&names, 4)) return false;
    r.tensor_names.resize(names);
    for (uint32_t j = 0; j < names; ++j) {
      if (!rd.Str(&r.tensor_names[j])) return false;
    }
    if (!rd.Str(&r.error_message) || !rd.Count(&sizes, 8)) return false;
    r.tensor_sizes.resize(sizes);
    for (uint32_t j = 0; j < sizes; ++j) {
      if (!rd.I64(&r.tensor_sizes[j])) return false;
    }
    uint32_t dtypes;
    if (!rd.Count(&dtypes, 4)) return false;
    r.tensor_dtypes.resize(dtypes);
    for (uint32_t j = 0; j < dtypes; ++j) {
      if (!rd.I32(&r.tensor_dtypes[j])) return false;
    }
    uint32_t totals;
    if (!rd.Count(&totals, 8)) return false;
    r.tensor_output_elements.resize(totals);
    for (uint32_t j = 0; j < totals; ++j) {
      if (!rd.I64(&r.tensor_output_elements[j])) return false;
    }
    uint32_t nshapes;
    if (!rd.Count(&nshapes, 4)) return false;
    r.tensor_shapes.resize(nshapes);
    for (uint32_t j = 0; j < nshapes; ++j) {
      if (!rd.Shape(&r.tensor_shapes[j])) return false;
    }
    if (!rd.I32(&r.tensor_type) || !rd.I32(&r.root_rank) ||
        !rd.I32(&r.reduce_op) || !rd.Str(&r.axis_name) ||
        !rd.F64(&r.prescale_factor) ||
        !rd.F64(&r.postscale_factor)) {
      return false;
    }
  }
  // optional tail: hierarchical toggles (absent on pre-round-5 payloads)
  if (!rd.I32(&out->tuned_hier_allreduce) ||
      !rd.I32(&out->tuned_hier_allgather)) {
    out->tuned_hier_allreduce = -1;
    out->tuned_hier_allgather = -1;
  }
  return true;
}

}  // namespace hvd
