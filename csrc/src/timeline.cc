#include "hvd/timeline.h"

#include <chrono>

namespace hvd {

void Timeline::Initialize(const std::string& path, int rank) {
  if (initialized_.load()) return;
  file_ = std::fopen(path.c_str(), "w");
  if (file_ == nullptr) return;
  rank_ = rank;
  t0_ = std::chrono::steady_clock::now();
  std::fputs("[\n", file_);
  shutdown_.store(false);
  writer_ = std::thread([this] { WriterLoop(); });
  initialized_.store(true);
}

void Timeline::Shutdown() {
  if (!initialized_.load()) return;
  {
    std::lock_guard<std::mutex> lk(mu_);
    shutdown_.store(true);
  }
  cv_.notify_all();
  if (writer_.joinable()) writer_.join();
  std::fputs("\n]\n", file_);
  std::fclose(file_);
  file_ = nullptr;
  initialized_.store(false);
}

int64_t Timeline::NowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - t0_)
      .count();
}

void Timeline::Enqueue(Event e) {
  if (!initialized_.load()) return;
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push_back(std::move(e));
  }
  cv_.notify_one();
}

void Timeline::NegotiateStart(const std::string& tensor, int request_type) {
  Enqueue({'B', tensor, "NEGOTIATE", "", NowUs()});
  (void)request_type;
}

void Timeline::NegotiateRankReady(const std::string& tensor, int rank) {
  Enqueue({'i', tensor, "rank " + std::to_string(rank) + " ready", "",
           NowUs()});
}

void Timeline::NegotiateEnd(const std::string& tensor) {
  Enqueue({'E', tensor, "NEGOTIATE", "", NowUs()});
}

void Timeline::Start(const std::string& tensor, const std::string& op_name) {
  Enqueue({'B', tensor, op_name, "", NowUs()});
}

void Timeline::ActivityStart(const std::string& tensor,
                             const std::string& activity) {
  Enqueue({'B', tensor, activity, "", NowUs()});
}

void Timeline::ActivityEnd(const std::string& tensor) {
  Enqueue({'E', tensor, "", "", NowUs()});
}

void Timeline::End(const std::string& tensor, int64_t bytes) {
  Enqueue({'E', tensor, "",
           bytes >= 0 ? "\"bytes\": " + std::to_string(bytes) : "", NowUs()});
}

void Timeline::MarkCycleStart() {
  Enqueue({'i', "cycle", "CYCLE_START", "", NowUs()});
}

void Timeline::MarkFusedLaunch(const std::string& op_name, size_t n_tensors,
                               size_t n_dtypes) {
  Enqueue({'i', "fusion",
           "FUSED_" + op_name + " x" + std::to_string(n_tensors) + " (" +
               std::to_string(n_dtypes) + " dtypes)",
           "", NowUs()});
}

void Timeline::WriterLoop() {
  while (true) {
    std::deque<Event> batch;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return shutdown_.load() || !queue_.empty(); });
      batch.swap(queue_);
      if (batch.empty() && shutdown_.load()) return;
    }
    for (const auto& e : batch) {
      if (!first_event_) std::fputs(",\n", file_);
      first_event_ = false;
      // chrome tracing event: pid = rank, tid = tensor lane
      std::fprintf(file_,
                   "{\"ph\": \"%c\", \"pid\": %d, \"tid\": \"%s\", "
                   "\"ts\": %lld%s%s%s%s}",
                   e.phase, rank_, e.tid.c_str(),
                   static_cast<long long>(e.ts_us),
                   e.name.empty() ? "" : ", \"name\": \"",
                   e.name.empty() ? "" : e.name.c_str(),
                   e.name.empty() ? "" : "\"",
                   e.args.empty() ? "" : (", \"args\": {" + e.args + "}").c_str());
    }
    std::fflush(file_);
  }
}

}  // namespace hvd
