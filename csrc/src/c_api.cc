// extern "C" API + background negotiation loop
// (reference horovod/common/operations.cc:604-954: InitializeHorovodOnce,
// BackgroundThreadLoop, RunLoopOnce, EnqueueTensor*, horovod_* C API).
//
// The Python runtime registers an *execution callback*: each cycle the
// background thread computes the ResponseList and invokes the callback once
// per (possibly fused) Response with a compact description; Python launches
// the corresponding XLA collective on the registered device arrays and marks
// the per-tensor handles done. The C++ side never sees tensor data — the
// device data plane belongs to XLA (HBM), exactly the inversion of the
// reference where the core owns the fusion buffer memcpys.

#include <atomic>
#include <cctype>
#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <set>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "hvd/common.h"
#include "hvd/controller.h"
#include "hvd/parameter_manager.h"
#include "hvd/response_cache.h"
#include "hvd/stall_inspector.h"
#include "hvd/tcp_controller.h"
#include "hvd/tensor_queue.h"
#include "hvd/timeline.h"

namespace hvd {
namespace {

// Serialized Response handed to Python: see horovod_tpu/core.py for the
// mirrored decoding.
using ExecCallback = void (*)(const char* response_bytes, int len,
                              const int64_t* handles, int n_handles);
using LogCallback = void (*)(int level, const char* msg);

struct GlobalState {
  // reference HorovodGlobalState (global_state.h:42-122)
  std::atomic<bool> initialized{false};
  std::atomic<bool> shutdown_requested{false};
  std::atomic<bool> shutdown_complete{false};
  int rank = 0;
  int size = 1;
  double cycle_time_ms = 5.0;  // reference operations.cc:427
  TensorQueue tensor_queue;
  ResponseCache response_cache;
  StallInspector stall_inspector;
  Timeline timeline;
  ParameterManager parameter_manager;
  std::unique_ptr<Controller> controller;
  std::thread background;
  ExecCallback exec_cb = nullptr;
  LogCallback log_cb = nullptr;
  // hierarchical toggles as currently applied job-wide (-1 = never tuned):
  // attached to every exec-callback payload so the Python data plane flips
  // its strategy at the same cycle boundary on every rank
  std::atomic<int> hier_allreduce_applied{-1};
  std::atomic<int> hier_allgather_applied{-1};
  std::mutex init_mu_;
};

GlobalState g;

void Log(int level, const std::string& msg) {
  if (g.log_cb != nullptr) g.log_cb(level, msg.c_str());
}

int64_t ExecuteResponse(const Response& resp) {
  // collect python handles for every tensor in this (fused) response;
  // returns the bytes moved (autotune scoring signal)
  std::vector<int64_t> handles;
  int64_t bytes = 0;
  handles.reserve(resp.tensor_names.size());
  if (resp.tensor_names.size() > 1) {
    std::set<int32_t> dtypes(resp.tensor_dtypes.begin(),
                             resp.tensor_dtypes.end());
    g.timeline.MarkFusedLaunch(Response::TypeName(resp.response_type),
                               resp.tensor_names.size(),
                               dtypes.empty() ? 1 : dtypes.size());
  }
  for (const auto& name : resp.tensor_names) {
    TensorTableEntry e;
    if (g.tensor_queue.PopEntry(name, &e)) {
      handles.push_back(e.handle);
      bytes += e.meta.tensor_shape.num_elements() *
               DataTypeSize(static_cast<DataType>(e.meta.tensor_type));
      g.timeline.NegotiateEnd(name);
      g.timeline.Start(name, Response::TypeName(resp.response_type));
    } else {
      handles.push_back(-1);
    }
  }
  if (g.exec_cb != nullptr) {
    std::string payload;
    SerializeResponseList(
        [&] {
          ResponseList l;
          l.responses.push_back(resp);
          l.tuned_hier_allreduce = g.hier_allreduce_applied.load();
          l.tuned_hier_allgather = g.hier_allgather_applied.load();
          return l;
        }(),
        &payload);
    g.exec_cb(payload.data(), static_cast<int>(payload.size()),
              handles.data(), static_cast<int>(handles.size()));
  }
  for (const auto& name : resp.tensor_names) {
    g.timeline.End(name, -1);
  }
  return bytes;
}

void RunLoopOnce(std::chrono::steady_clock::time_point& last_cycle) {
  // sleep out the remainder of the cycle (reference operations.cc:550-560)
  auto target = last_cycle + std::chrono::duration_cast<
                                 std::chrono::steady_clock::duration>(
                                 std::chrono::duration<double, std::milli>(
                                     g.cycle_time_ms));
  std::this_thread::sleep_until(target);
  last_cycle = std::chrono::steady_clock::now();
  g.timeline.MarkCycleStart();

  ResponseList list =
      g.controller->ComputeResponseList(g.shutdown_requested.load());
  // apply coordinator-tuned parameters (no-op unless autotuning; identical
  // on the coordinator, the broadcast value on workers)
  if (list.tuned_cycle_time_ms > 0) g.cycle_time_ms = list.tuned_cycle_time_ms;
  if (list.tuned_fusion_threshold >= 0) {
    g.controller->SetFusionThresholdBytes(list.tuned_fusion_threshold);
  }
  if (list.tuned_cache_enabled >= 0) {
    if (std::getenv("HVD_DEBUG_CACHE") != nullptr &&
        g.controller->cache_enabled() != (list.tuned_cache_enabled != 0)) {
      std::fprintf(stderr, "[hvddbg r%d] cache toggle -> %d\n", g.rank,
                   (int)(list.tuned_cache_enabled != 0));
    }
    g.controller->SetCacheEnabled(list.tuned_cache_enabled != 0);
  }
  if (list.tuned_hier_allreduce >= 0) {
    g.hier_allreduce_applied.store(list.tuned_hier_allreduce != 0 ? 1 : 0);
  }
  if (list.tuned_hier_allgather >= 0) {
    g.hier_allgather_applied.store(list.tuned_hier_allgather != 0 ? 1 : 0);
  }
  int64_t bytes = 0;
  for (const auto& resp : list.responses) {
    bytes += ExecuteResponse(resp);
  }
  if (g.rank == 0 && g.parameter_manager.IsAutoTuning()) {
    g.parameter_manager.Update(bytes);
    // Do NOT apply the new choice here: tuned values ride the next cycle's
    // ResponseList, which every rank (coordinator included) applies at the
    // same point above — applying immediately would let rank 0 bin-pack one
    // cycle with a different fusion threshold than the workers and launch
    // mismatched grouped collectives (cross-process deadlock).
    g.controller->SetAutotunedParams(
        g.parameter_manager.cycle_time_ms(),
        g.parameter_manager.fusion_threshold(),
        g.parameter_manager.cache_enabled() ? 1 : 0,
        g.parameter_manager.hier_allreduce() ? 1 : 0,
        g.parameter_manager.hier_allgather() ? 1 : 0);
  }
  if (list.shutdown) {
    g.shutdown_requested.store(true);
  }
}

void BackgroundThreadLoop() {
  auto last_cycle = std::chrono::steady_clock::now();
  while (!g.shutdown_requested.load()) {
    RunLoopOnce(last_cycle);
  }
  // abort everything still pending with shutdown error
  // (reference operations.cc:526-532)
  auto handles = g.tensor_queue.DrainAllHandles();
  if (g.exec_cb != nullptr && !handles.empty()) {
    ResponseList l;
    Response r;
    r.response_type = Response::ERROR;
    std::string cause =
        g.controller != nullptr ? g.controller->lost_peer_detail() : "";
    r.error_message =
        cause.empty()
            ? "Horovod background loop shut down; pending collective aborted."
            : "Horovod background loop shut down (" + cause +
                  "); pending collective aborted.";
    l.responses.push_back(r);
    l.shutdown = true;
    std::string payload;
    SerializeResponseList(l, &payload);
    g.exec_cb(payload.data(), static_cast<int>(payload.size()),
              handles.data(), static_cast<int>(handles.size()));
  }
  g.timeline.Shutdown();
  g.shutdown_complete.store(true);
}

}  // namespace
}  // namespace hvd

extern "C" {

// init for single-process (local controller) or multi-process (tcp).
// coordinator_host may be null/empty for local mode.
int hvd_core_init(int rank, int size, const char* coordinator_host,
                  int coordinator_port, double cycle_time_ms,
                  int64_t fusion_threshold_bytes, int cache_capacity,
                  double stall_warning_s, double stall_shutdown_s,
                  const char* timeline_path) {
  using namespace hvd;
  std::lock_guard<std::mutex> lk(g.init_mu_);
  if (g.initialized.load()) return 0;
  g.rank = rank;
  g.size = size;
  g.cycle_time_ms = cycle_time_ms > 0 ? cycle_time_ms : 5.0;
  g.shutdown_requested.store(false);
  g.shutdown_complete.store(false);
  // the .so (and its globals) outlives init/shutdown cycles in one
  // process: a previous session's tuned toggles must not leak into a
  // fresh session as "already applied"
  g.hier_allreduce_applied.store(-1);
  g.hier_allgather_applied.store(-1);
  g.response_cache.set_capacity(
      cache_capacity >= 0 ? static_cast<size_t>(cache_capacity) : 1024);
  g.stall_inspector.set_warning_seconds(stall_warning_s > 0 ? stall_warning_s
                                                            : 60.0);
  g.stall_inspector.set_shutdown_seconds(stall_shutdown_s);
  g.stall_inspector.set_log_fn(
      [](const std::string& m) { Log(2, m); });
  if (timeline_path != nullptr && timeline_path[0] != '\0' && rank == 0) {
    g.timeline.Initialize(timeline_path, rank);
  }
  // autotune knobs from env (reference operations.cc:470-500 reads
  // HOROVOD_AUTOTUNE / HOROVOD_AUTOTUNE_LOG / warmup+sample counts)
  {
    const char* at = std::getenv("HOROVOD_AUTOTUNE");
    bool autotune = at != nullptr && at[0] != '\0' && std::strcmp(at, "0") != 0;
    auto env_int = [](const char* name, int dflt) {
      const char* v = std::getenv(name);
      return (v != nullptr && v[0] != '\0') ? std::atoi(v) : dflt;
    };
    auto env_f = [](const char* name, double dflt) {
      const char* v = std::getenv(name);
      return (v != nullptr && v[0] != '\0') ? std::atof(v) : dflt;
    };
    const char* log = std::getenv("HOROVOD_AUTOTUNE_LOG");
    auto env_on = [](const char* name) {
      // accept the same spellings as the Python data plane's _env_on
      // (ops/hierarchical.py): 1/true/yes/on, case-insensitive
      const char* v = std::getenv(name);
      if (v == nullptr || v[0] == '\0') return false;
      std::string s(v);
      for (auto& c : s) c = static_cast<char>(std::tolower(c));
      return s == "1" || s == "true" || s == "yes" || s == "on";
    };
    g.parameter_manager.Initialize(
        g.cycle_time_ms,
        fusion_threshold_bytes >= 0 ? fusion_threshold_bytes
                                    : 64ll * 1024 * 1024,
        env_int("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3),
        env_int("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10),
        env_int("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20),
        env_f("HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8),
        (rank == 0 && log != nullptr) ? log : "",
        // seed the search from the user's explicit strategy choice
        // (reference operations.cc:455-469 reads the same env pair)
        env_on("HOROVOD_HIERARCHICAL_ALLREDUCE"),
        env_on("HOROVOD_HIERARCHICAL_ALLGATHER"));
    // only the coordinator runs the search (workers apply broadcast values),
    // so only its status surface reports "tuning"
    g.parameter_manager.SetAutoTuning(autotune && rank == 0);
  }
  if (size > 1 && coordinator_host != nullptr && coordinator_host[0] != '\0') {
    auto* tcp = new TcpController(rank, size, coordinator_host,
                                  coordinator_port, g.tensor_queue,
                                  g.response_cache, g.stall_inspector);
    Status s = tcp->Initialize();
    if (!s.ok()) {
      Log(3, "controller init failed: " + s.reason());
      delete tcp;
      return -1;
    }
    g.controller.reset(tcp);
  } else {
    g.controller.reset(new LocalController(rank, size, g.tensor_queue,
                                           g.response_cache,
                                           g.stall_inspector));
  }
  if (fusion_threshold_bytes >= 0) {
    g.controller->SetFusionThresholdBytes(fusion_threshold_bytes);
  }
  g.background = std::thread(BackgroundThreadLoop);
  g.initialized.store(true);
  return 0;
}

void hvd_core_set_exec_callback(void (*cb)(const char*, int, const int64_t*,
                                           int)) {
  hvd::g.exec_cb = cb;
}

void hvd_core_set_log_callback(void (*cb)(int, const char*)) {
  hvd::g.log_cb = cb;
}

int hvd_core_enqueue(const char* name, int request_type, int dtype,
                     const int64_t* dims, int ndim, int root_rank,
                     int reduce_op, double prescale, double postscale,
                     int64_t handle, const char* axis_name) {
  using namespace hvd;
  if (!g.initialized.load()) return -1;
  TensorTableEntry e;
  e.handle = handle;
  e.meta.request_rank = g.rank;
  e.meta.request_type = request_type;
  e.meta.tensor_type = dtype;
  e.meta.root_rank = root_rank;
  e.meta.reduce_op = reduce_op;
  e.meta.prescale_factor = prescale;
  e.meta.postscale_factor = postscale;
  e.meta.tensor_name = name;
  e.meta.axis_name = axis_name != nullptr ? axis_name : "";
  std::vector<int64_t> d(dims, dims + ndim);
  e.meta.tensor_shape = TensorShape(std::move(d));
  g.timeline.NegotiateStart(e.meta.tensor_name, request_type);
  Status s = g.tensor_queue.AddToTensorQueue(e);
  return s.ok() ? 0 : 1;  // 1 = duplicate name
}

int hvd_core_pending(void) {
  return static_cast<int>(hvd::g.tensor_queue.pending_count());
}

void hvd_core_shutdown(void) {
  using namespace hvd;
  std::lock_guard<std::mutex> lk(g.init_mu_);
  if (!g.initialized.load()) return;
  g.shutdown_requested.store(true);
  if (g.background.joinable()) g.background.join();
  g.controller.reset();
  g.response_cache.clear();
  g.initialized.store(false);
}

int hvd_core_initialized(void) { return hvd::g.initialized.load() ? 1 : 0; }
int hvd_core_rank(void) { return hvd::g.rank; }
int hvd_core_size(void) { return hvd::g.size; }

double hvd_core_cycle_time_ms(void) { return hvd::g.cycle_time_ms; }
void hvd_core_set_cycle_time_ms(double ms) {
  if (ms > 0) hvd::g.cycle_time_ms = ms;
}
int64_t hvd_core_fusion_threshold(void) {
  return hvd::g.controller ? hvd::g.controller->fusion_threshold_bytes() : -1;
}

// autotuner observability (tests + Python-side status surface)
int hvd_core_autotune_active(void) {
  return hvd::g.parameter_manager.IsAutoTuning() ? 1 : 0;
}
int hvd_core_autotune_samples(void) {
  return hvd::g.parameter_manager.num_samples();
}
double hvd_core_autotune_best_score(void) {
  return hvd::g.parameter_manager.best_score();
}
int hvd_core_cache_enabled(void) {
  return hvd::g.controller && hvd::g.controller->cache_enabled() ? 1 : 0;
}
void hvd_core_set_cache_enabled(int enabled) {
  if (hvd::g.controller) hvd::g.controller->SetCacheEnabled(enabled != 0);
}
void hvd_core_set_fusion_threshold(int64_t bytes) {
  if (hvd::g.controller && bytes >= 0) {
    hvd::g.controller->SetFusionThresholdBytes(bytes);
  }
}

uint64_t hvd_core_cache_hit_count(void) {
  return hvd::g.controller ? hvd::g.controller->cache_hit_count() : 0;
}

// hierarchical toggles as applied job-wide this cycle (-1 = never tuned)
int hvd_core_hier_allreduce(void) {
  return hvd::g.hier_allreduce_applied.load();
}
int hvd_core_hier_allgather(void) {
  return hvd::g.hier_allgather_applied.load();
}

// Coordinator-side manual injection into the tuned broadcast: the values
// ride the NEXT cycle's ResponseList and every rank (coordinator included)
// applies them at the same cycle boundary — the collectively-safe way to
// retune mid-run without HOROVOD_AUTOTUNE (also the np=2 toggle test's
// entry point). No-op on workers.
void hvd_core_set_autotuned_params(double cycle_ms, int64_t fusion_bytes,
                                   int cache_enabled, int hier_allreduce,
                                   int hier_allgather) {
  using namespace hvd;
  if (!g.controller || g.rank != 0) return;
  g.controller->SetAutotunedParams(cycle_ms, fusion_bytes, cache_enabled,
                                   hier_allreduce, hier_allgather);
}

}  // extern "C"
