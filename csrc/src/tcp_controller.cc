#include "hvd/tcp_controller.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <thread>

namespace hvd {

namespace {

bool SendAll(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool RecvAll(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

}  // namespace

TcpController::~TcpController() {
  for (int fd : worker_fds_) {
    if (fd >= 0) ::close(fd);
  }
  if (coord_fd_ >= 0) ::close(coord_fd_);
  if (listen_fd_ >= 0) ::close(listen_fd_);
}

bool TcpController::SendFrame(int fd, uint8_t tag, const std::string& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  return SendAll(fd, &len, sizeof(len)) && SendAll(fd, &tag, 1) &&
         (payload.empty() || SendAll(fd, payload.data(), payload.size()));
}

bool TcpController::RecvFrame(int fd, uint8_t* tag, std::string* payload) {
  uint32_t len;
  if (!RecvAll(fd, &len, sizeof(len)) || !RecvAll(fd, tag, 1)) return false;
  payload->resize(len);
  return len == 0 || RecvAll(fd, payload->data(), len);
}

Status TcpController::Initialize(double timeout_s) {
  if (is_coordinator()) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return Status::UnknownError("socket() failed");
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      return Status::UnknownError("bind() failed on port " +
                                  std::to_string(port_));
    }
    ::listen(listen_fd_, size_);
    worker_fds_.assign(size_ - 1, -1);
    for (int i = 0; i < size_ - 1; ++i) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return Status::UnknownError("accept() failed");
      int nd = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
      uint8_t tag;
      std::string payload;
      if (!RecvFrame(fd, &tag, &payload) || tag != HELLO ||
          payload.size() != sizeof(int32_t)) {
        return Status::UnknownError("bad hello from worker");
      }
      int32_t r;
      std::memcpy(&r, payload.data(), sizeof(r));
      if (r < 1 || r >= size_ || worker_fds_[r - 1] != -1) {
        return Status::UnknownError("bad worker rank in hello");
      }
      worker_fds_[r - 1] = fd;
    }
  } else {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    while (true) {
      coord_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port_));
      hostent* he = ::gethostbyname(host_.c_str());
      if (he == nullptr) return Status::UnknownError("unknown host " + host_);
      std::memcpy(&addr.sin_addr, he->h_addr, he->h_length);
      if (::connect(coord_fd_, reinterpret_cast<sockaddr*>(&addr),
                    sizeof(addr)) == 0) {
        break;
      }
      ::close(coord_fd_);
      coord_fd_ = -1;
      if (std::chrono::steady_clock::now() > deadline) {
        return Status::UnknownError("timed out connecting to coordinator " +
                                    host_ + ":" + std::to_string(port_));
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
    int nd = 1;
    ::setsockopt(coord_fd_, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
    int32_t r = rank_;
    std::string hello(reinterpret_cast<char*>(&r), sizeof(r));
    if (!SendFrame(coord_fd_, HELLO, hello)) {
      return Status::UnknownError("failed to send hello");
    }
  }
  return Status::OK();
}

void TcpController::MarkLostCoordinator() {
  if (lost_peer_.empty()) {
    lost_peer_ = "connection to coordinator (process rank 0) lost — the "
                 "coordinator process likely died";
  }
}

void TcpController::MarkLostWorker(int rank) {
  if (lost_peer_.empty()) {
    lost_peer_ =
        "connection to worker rank " + std::to_string(rank) + " lost";
  }
}

std::vector<RequestList> TcpController::GatherReadyTensors(
    const RequestList& mine) {
  std::vector<RequestList> all;
  if (is_coordinator()) {
    all.resize(size_);
    all[0] = mine;
    for (int r = 1; r < size_; ++r) {
      uint8_t tag;
      std::string payload;
      if (!RecvFrame(worker_fds_[r - 1], &tag, &payload) || tag != REQUESTS ||
          !ParseRequestList(payload.data(), payload.size(), &all[r])) {
        MarkLostWorker(r);
        all[r].shutdown = true;  // lost worker => job shutdown
      }
    }
  } else {
    std::string payload;
    SerializeRequestList(mine, &payload);
    if (!SendFrame(coord_fd_, REQUESTS, payload)) {
      // coordinator gone: BroadcastResponseList's failed recv flips
      // shutdown this same cycle; record the cause now
      MarkLostCoordinator();
    }
  }
  return all;
}

void TcpController::BroadcastResponseList(ResponseList* list) {
  if (is_coordinator()) {
    std::string payload;
    SerializeResponseList(*list, &payload);
    for (int fd : worker_fds_) SendFrame(fd, RESPONSES, payload);
  } else {
    uint8_t tag;
    std::string payload;
    if (!RecvFrame(coord_fd_, &tag, &payload) || tag != RESPONSES ||
        !ParseResponseList(payload.data(), payload.size(), list)) {
      MarkLostCoordinator();
      list->responses.clear();
      list->shutdown = true;  // lost coordinator => shutdown
    }
  }
}

void TcpController::BitReduce(std::vector<uint64_t>& bits, uint8_t tag) {
  const size_t bytes = bits.size() * sizeof(uint64_t);
  if (is_coordinator()) {
    std::vector<uint64_t> other(bits.size());
    for (int r = 1; r < size_; ++r) {
      uint8_t t;
      std::string payload;
      if (RecvFrame(worker_fds_[r - 1], &t, &payload) &&
          payload.size() == bytes) {
        std::memcpy(other.data(), payload.data(), bytes);
        for (size_t i = 0; i < bits.size(); ++i) {
          bits[i] = (tag == BITS_AND) ? (bits[i] & other[i])
                                      : (bits[i] | other[i]);
        }
      } else {
        MarkLostWorker(r);
        if (tag == BITS_AND) {
          std::fill(bits.begin(), bits.end(), 0);  // lost worker: no agreement
        }
      }
    }
    std::string payload(reinterpret_cast<char*>(bits.data()), bytes);
    for (int fd : worker_fds_) SendFrame(fd, tag, payload);
  } else {
    std::string payload(reinterpret_cast<const char*>(bits.data()), bytes);
    SendFrame(coord_fd_, tag, payload);
    uint8_t t;
    std::string back;
    if (RecvFrame(coord_fd_, &t, &back) && back.size() == bytes) {
      std::memcpy(bits.data(), back.data(), bytes);
    } else {
      MarkLostCoordinator();
      std::fill(bits.begin(), bits.end(), 0);
    }
  }
}

void TcpController::CrossRankBitwiseAnd(std::vector<uint64_t>& bits) {
  BitReduce(bits, BITS_AND);
}

void TcpController::CrossRankBitwiseOr(std::vector<uint64_t>& bits) {
  BitReduce(bits, BITS_OR);
}

void TcpController::Barrier() {
  std::vector<uint64_t> bits(1, 0);
  BitReduce(bits, BITS_AND);
}

}  // namespace hvd
