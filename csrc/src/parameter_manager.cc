#include "hvd/parameter_manager.h"

#include <algorithm>
#include <cmath>
#include <cstring>

namespace hvd {

// ---------------------------------------------------------------- GP

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0.0;
  for (size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return std::exp(-d2 / (2.0 * length_scale_ * length_scale_));
}

void GaussianProcess::Fit(const std::vector<std::vector<double>>& x,
                          const std::vector<double>& y) {
  x_ = x;
  size_t n = x.size();
  // normalize targets so the unit-variance kernel prior fits
  y_mean_ = 0.0;
  for (double v : y) y_mean_ += v;
  y_mean_ /= n;
  double var = 0.0;
  for (double v : y) var += (v - y_mean_) * (v - y_mean_);
  y_std_ = n > 1 ? std::sqrt(var / (n - 1)) : 1.0;
  if (y_std_ < 1e-12) y_std_ = 1.0;

  std::vector<double> k(n * n);
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      double v = Kernel(x[i], x[j]);
      if (i == j) v += noise_ * noise_;
      k[i * n + j] = v;
      k[j * n + i] = v;
    }
  }
  // Cholesky: K = L L^T (K is SPD: RBF gram + noise ridge)
  chol_.assign(n * n, 0.0);
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      double sum = k[i * n + j];
      for (size_t m = 0; m < j; ++m) sum -= chol_[i * n + m] * chol_[j * n + m];
      if (i == j) {
        chol_[i * n + i] = std::sqrt(std::max(sum, 1e-12));
      } else {
        chol_[i * n + j] = sum / chol_[j * n + j];
      }
    }
  }
  // alpha = K^-1 (y - mean)/std via two triangular solves
  std::vector<double> z(n);
  for (size_t i = 0; i < n; ++i) {
    double sum = (y[i] - y_mean_) / y_std_;
    for (size_t m = 0; m < i; ++m) sum -= chol_[i * n + m] * z[m];
    z[i] = sum / chol_[i * n + i];
  }
  alpha_.assign(n, 0.0);
  for (size_t ii = n; ii-- > 0;) {
    double sum = z[ii];
    for (size_t m = ii + 1; m < n; ++m) sum -= chol_[m * n + ii] * alpha_[m];
    alpha_[ii] = sum / chol_[ii * n + ii];
  }
}

void GaussianProcess::Predict(const std::vector<double>& x, double* mu,
                              double* var) const {
  size_t n = x_.size();
  if (n == 0) {
    *mu = 0.0;
    *var = 1.0;
    return;
  }
  std::vector<double> kstar(n);
  for (size_t i = 0; i < n; ++i) kstar[i] = Kernel(x, x_[i]);
  double m = 0.0;
  for (size_t i = 0; i < n; ++i) m += kstar[i] * alpha_[i];
  *mu = m * y_std_ + y_mean_;
  // v = L^-1 k*; var = k(x,x) - v.v
  std::vector<double> v(n);
  for (size_t i = 0; i < n; ++i) {
    double sum = kstar[i];
    for (size_t j = 0; j < i; ++j) sum -= chol_[i * n + j] * v[j];
    v[i] = sum / chol_[i * n + i];
  }
  double vv = 0.0;
  for (size_t i = 0; i < n; ++i) vv += v[i] * v[i];
  double raw = Kernel(x, x) - vv;
  *var = std::max(raw, 1e-12) * y_std_ * y_std_;
}

// ---------------------------------------------------------------- BO

void BayesianOptimization::AddSample(const std::vector<double>& x, double y) {
  x_.push_back(x);
  y_.push_back(y);
  gp_.Fit(x_, y_);
}

double BayesianOptimization::ExpectedImprovement(const std::vector<double>& x,
                                                 double best) const {
  double mu, var;
  gp_.Predict(x, &mu, &var);
  double sigma = std::sqrt(var);
  if (sigma < 1e-12) return 0.0;
  const double xi = 0.01 * std::abs(best);  // exploration margin
  double z = (mu - best - xi) / sigma;
  double phi = std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
  double cdf = 0.5 * std::erfc(-z / std::sqrt(2.0));
  return (mu - best - xi) * cdf + sigma * phi;
}

std::vector<double> BayesianOptimization::NextSample() {
  std::uniform_real_distribution<double> uni(0.0, 1.0);
  if (x_.empty()) {
    return std::vector<double>(dims_, 0.5);
  }
  double best = *std::max_element(y_.begin(), y_.end());
  std::vector<double> best_x(dims_, 0.5);
  double best_ei = -1.0;
  for (int c = 0; c < 1000; ++c) {
    std::vector<double> cand(dims_);
    for (int d = 0; d < dims_; ++d) cand[d] = uni(rng_);
    double ei = ExpectedImprovement(cand, best);
    if (ei > best_ei) {
      best_ei = ei;
      best_x = std::move(cand);
    }
  }
  return best_x;
}

// ---------------------------------------------------------------- PM

void ParameterManager::Initialize(double initial_cycle_ms,
                                  int64_t initial_fusion, int warmup_samples,
                                  int steps_per_sample, int max_samples,
                                  double gp_noise,
                                  const std::string& log_path,
                                  bool initial_hier_allreduce,
                                  bool initial_hier_allgather) {
  current_ = {initial_cycle_ms, initial_fusion, true, initial_hier_allreduce,
              initial_hier_allgather};
  best_ = current_;
  best_score_ = 0.0;
  warmup_samples_ = warmup_samples > 0 ? warmup_samples : 3;
  steps_per_sample_ = steps_per_sample > 0 ? steps_per_sample : 10;
  max_samples_ = max_samples > 0 ? max_samples : 20;
  sample_count_ = 0;
  accum_bytes_ = 0;
  steps_in_sample_ = 0;
  sample_started_ = false;
  bayes_ = BayesianOptimization(5, gp_noise > 0 ? gp_noise : 0.8);
  if (!log_path.empty()) {
    log_.open(log_path, std::ios::out | std::ios::trunc);
    if (log_.is_open()) {
      log_ << "sample,cycle_time_ms,fusion_threshold_bytes,cache_enabled,"
              "hier_allreduce,hier_allgather,score_bytes_per_sec"
           << std::endl;  // reference autotune CSV (parameter_manager.cc:76-81)
    }
  }
}

ParameterManager::Params ParameterManager::FromUnit(
    const std::vector<double>& x) const {
  Params p;
  p.fusion_threshold = static_cast<int64_t>(x[0] * kMaxFusion);
  p.cycle_time_ms = kMinCycleMs + x[1] * (kMaxCycleMs - kMinCycleMs);
  // categorical dims embedded as thresholds on the unit interval (the
  // GP smooths over them; the reference embeds its binary toggles the same
  // way, parameter_manager.h CategoricalParameter)
  p.cache_enabled = x[2] >= 0.5;
  p.hier_allreduce = x[3] >= 0.5;
  p.hier_allgather = x[4] >= 0.5;
  return p;
}

std::vector<double> ParameterManager::ToUnit(const Params& p) const {
  return {static_cast<double>(p.fusion_threshold) / kMaxFusion,
          (p.cycle_time_ms - kMinCycleMs) / (kMaxCycleMs - kMinCycleMs),
          p.cache_enabled ? 1.0 : 0.0,
          p.hier_allreduce ? 1.0 : 0.0,
          p.hier_allgather ? 1.0 : 0.0};
}

void ParameterManager::LogSample(const Params& p, double score) {
  if (log_.is_open()) {
    log_ << sample_count_ << "," << p.cycle_time_ms << ","
         << p.fusion_threshold << "," << (p.cache_enabled ? 1 : 0) << ","
         << (p.hier_allreduce ? 1 : 0) << "," << (p.hier_allgather ? 1 : 0)
         << "," << score << std::endl;
  }
}

bool ParameterManager::Update(int64_t bytes) {
  if (!active_ || bytes <= 0) return false;
  auto now = std::chrono::steady_clock::now();
  if (!sample_started_) {
    sample_started_ = true;
    sample_start_ = now;
    accum_bytes_ = 0;
    steps_in_sample_ = 0;
  }
  accum_bytes_ += bytes;
  steps_in_sample_++;
  if (steps_in_sample_ < steps_per_sample_) return false;

  double secs =
      std::chrono::duration<double>(now - sample_start_).count();
  double score = secs > 0 ? static_cast<double>(accum_bytes_) / secs : 0.0;
  sample_started_ = false;
  sample_count_++;
  LogSample(current_, score);

  if (sample_count_ <= warmup_samples_) {
    return false;  // discard warmup scores, keep current params
  }
  if (score > best_score_) {
    best_score_ = score;
    best_ = current_;
  }
  bayes_.AddSample(ToUnit(current_), score);
  if (sample_count_ >= warmup_samples_ + max_samples_) {
    // search exhausted: lock in the best configuration
    current_ = best_;
    active_ = false;
    if (log_.is_open()) {
      log_ << "best," << best_.cycle_time_ms << "," << best_.fusion_threshold
           << "," << (best_.cache_enabled ? 1 : 0) << ","
           << (best_.hier_allreduce ? 1 : 0) << ","
           << (best_.hier_allgather ? 1 : 0) << "," << best_score_
           << std::endl;
      log_.close();
    }
    return true;
  }
  current_ = FromUnit(bayes_.NextSample());
  return true;
}

}  // namespace hvd
