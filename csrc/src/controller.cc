#include "hvd/controller.h"

#include <algorithm>
#include <cstdio>
#include <cstdlib>

namespace hvd {

namespace {

bool DebugCache() {
  static bool on = std::getenv("HVD_DEBUG_CACHE") != nullptr;
  return on;
}

// Fusable: elementwise reductions and allgathers on the same axis with the
// same op and scaling (the reference also fuses allgathers,
// controller.cc:700-755). Dtype is deliberately NOT compared: the XLA data
// plane launches grouped collectives where every array keeps its own dtype
// (there is no shared fusion buffer to homogenize), so fp32+bf16 gradients
// pack into ONE fused response — the reference's fusion buffer is
// single-dtype and its look-ahead can only skip *past* dtype breaks
// (controller.cc:640-761).
bool CanFuse(const Response& a, const Response& b) {
  if (a.response_type != b.response_type) return false;
  if (a.response_type != Response::ALLREDUCE &&
      a.response_type != Response::ADASUM &&
      a.response_type != Response::ALLGATHER) {
    return false;
  }
  if (a.axis_name != b.axis_name) return false;
  return a.reduce_op == b.reduce_op &&
         a.prescale_factor == b.prescale_factor &&
         a.postscale_factor == b.postscale_factor;
}

int64_t ResponseBytes(const Response& r) {
  DataType dt = static_cast<DataType>(
      r.tensor_dtypes.empty() ? r.tensor_type : r.tensor_dtypes[0]);
  if (!r.tensor_output_elements.empty()) {
    return r.tensor_output_elements[0] * DataTypeSize(dt);
  }
  if (r.tensor_sizes.empty()) return 0;
  return r.tensor_sizes[0] * DataTypeSize(dt);
}

}  // namespace

bool Controller::IncrementTensorCount(const Request& req, int source_rank) {
  auto it = message_table_.find(req.tensor_name);
  if (it == message_table_.end()) {
    MessageTableEntry e;
    e.first_seen = std::chrono::steady_clock::now();
    it = message_table_.emplace(req.tensor_name, std::move(e)).first;
  }
  MessageTableEntry& entry = it->second;
  entry.by_rank.emplace(source_rank, req);
  // joined ranks count as ready with zero contributions
  // (reference controller.cc:219-307)
  size_t effective = entry.by_rank.size();
  for (int jr : joined_ranks_) {
    if (!entry.by_rank.count(jr)) effective++;
  }
  return effective >= static_cast<size_t>(size_);
}

Response Controller::ConstructResponse(const std::string& name) {
  // cross-rank validation (reference controller.cc:378-611)
  auto it = message_table_.find(name);
  Response resp;
  resp.tensor_names = {name};
  MessageTableEntry& entry = it->second;
  const Request& first = entry.by_rank.begin()->second;

  auto error = [&](const std::string& msg) {
    resp.response_type = Response::ERROR;
    resp.error_message = msg;
    return resp;
  };

  for (auto rit = std::next(entry.by_rank.begin()); rit != entry.by_rank.end();
       ++rit) {
    const Request& r = rit->second;
    if (r.request_type != first.request_type) {
      return error("Mismatched collective types for tensor " + name + ": " +
                   Request::TypeName(first.request_type) + " vs " +
                   Request::TypeName(r.request_type));
    }
    if (r.tensor_type != first.tensor_type) {
      return error("Mismatched data types for tensor " + name);
    }
    if (r.request_type == Request::ALLREDUCE ||
        r.request_type == Request::ADASUM ||
        r.request_type == Request::BROADCAST ||
        r.request_type == Request::REDUCESCATTER ||
        r.request_type == Request::ALLTOALL) {
      if (r.tensor_shape != first.tensor_shape) {
        return error("Mismatched shapes for tensor " + name + ": " +
                     first.tensor_shape.DebugString() + " vs " +
                     r.tensor_shape.DebugString());
      }
    } else if (r.request_type == Request::ALLGATHER) {
      // dim0 may differ per rank; trailing dims must match
      // (reference controller.cc allgather validation)
      if (r.tensor_shape.ndim() != first.tensor_shape.ndim()) {
        return error("Mismatched ranks for allgather tensor " + name);
      }
      for (int d = 1; d < r.tensor_shape.ndim(); ++d) {
        if (r.tensor_shape.dim(d) != first.tensor_shape.dim(d)) {
          return error("Mismatched trailing shapes for allgather tensor " +
                       name);
        }
      }
    }
    if (r.request_type == Request::BROADCAST &&
        r.root_rank != first.root_rank) {
      return error("Mismatched root ranks for broadcast tensor " + name);
    }
    if (r.reduce_op != first.reduce_op) {
      return error("Mismatched reduce ops for tensor " + name);
    }
    if (r.prescale_factor != first.prescale_factor ||
        r.postscale_factor != first.postscale_factor) {
      return error("Mismatched prescale/postscale factors for tensor " + name);
    }
    if (r.axis_name != first.axis_name) {
      return error("Mismatched mesh axes for tensor " + name + ": '" +
                   first.axis_name + "' vs '" + r.axis_name + "'");
    }
  }

  switch (first.request_type) {
    case Request::ALLREDUCE: resp.response_type = Response::ALLREDUCE; break;
    case Request::ADASUM: resp.response_type = Response::ADASUM; break;
    case Request::ALLGATHER: resp.response_type = Response::ALLGATHER; break;
    case Request::BROADCAST: resp.response_type = Response::BROADCAST; break;
    case Request::ALLTOALL: resp.response_type = Response::ALLTOALL; break;
    case Request::REDUCESCATTER:
      resp.response_type = Response::REDUCESCATTER;
      break;
    case Request::BARRIER: resp.response_type = Response::BARRIER; break;
    case Request::JOIN: resp.response_type = Response::JOIN; break;
  }
  resp.tensor_type = first.tensor_type;
  resp.tensor_dtypes = {first.tensor_type};
  // true shape (validated identical across ranks for elementwise types):
  // lets a joined rank cache under the same shape key as live ranks
  resp.tensor_shapes = {first.tensor_shape};
  resp.root_rank = first.root_rank;
  resp.reduce_op = first.reduce_op;
  resp.axis_name = first.axis_name;
  resp.prescale_factor = first.prescale_factor;
  resp.postscale_factor = first.postscale_factor;
  if (first.request_type == Request::ALLGATHER) {
    // per-rank dim0 sizes in rank order for displacement math
    // (joined ranks keep 0: they contribute nothing)
    resp.tensor_sizes.resize(size_, 0);
    int64_t total = 0;
    for (const auto& kv : entry.by_rank) {
      resp.tensor_sizes[kv.first] =
          kv.second.tensor_shape.ndim() > 0 ? kv.second.tensor_shape.dim(0)
                                            : 1;
      total += kv.second.tensor_shape.num_elements();
    }
    resp.tensor_output_elements = {total};
  } else {
    resp.tensor_sizes = {first.tensor_shape.num_elements()};
    resp.tensor_output_elements = {first.tensor_shape.num_elements()};
  }
  return resp;
}

void Controller::EmitReady(const std::string& name, ResponseList* out) {
  auto it = message_table_.find(name);
  const MessageTableEntry& entry = it->second;
  bool backfilled = entry.by_rank.size() < static_cast<size_t>(size_);
  const Request& first = entry.by_rank.begin()->second;
  Response resp;
  if (backfilled && first.request_type != Request::ALLREDUCE &&
      first.request_type != Request::ADASUM) {
    resp.response_type = Response::ERROR;
    resp.tensor_names = {name};
    resp.error_message = std::string(Request::TypeName(first.request_type)) +
                         " is not supported with join() for tensor " + name;
  } else {
    resp = ConstructResponse(name);
  }
  message_table_.erase(it);
  out->responses.push_back(std::move(resp));
}

void Controller::FuseResponses(std::vector<Response>& in, ResponseList* out) {
  // Deterministic order: negotiation already ordered by coordinator arrival;
  // sort by (type, axis) then bin-pack to the fusion threshold with bounded
  // look-ahead — a non-fusable or threshold-overflowing entry is skipped
  // (up to one threshold's worth of skipped bytes), not a bin break, so
  // mixed streams still pack densely without going quadratic. Matches the
  // reference's skip-ahead bound (controller.cc:640-761), and because
  // CanFuse ignores dtype, fp32+bf16 land in one response. Every rank runs
  // this same deterministic pass on the same broadcast list, so execution
  // order stays identical job-wide.
  std::stable_sort(in.begin(), in.end(), [](const Response& a,
                                            const Response& b) {
    if (a.response_type != b.response_type)
      return a.response_type < b.response_type;
    return a.axis_name < b.axis_name;
  });
  std::vector<bool> used(in.size(), false);
  for (size_t i = 0; i < in.size(); ++i) {
    if (used[i]) continue;
    Response fused = in[i];
    int64_t bytes = ResponseBytes(fused);
    if (fused.tensor_dtypes.empty()) {
      fused.tensor_dtypes.assign(fused.tensor_names.size(),
                                 fused.tensor_type);
    }
    if (fused.tensor_shapes.empty() && !fused.tensor_output_elements.empty()) {
      // defensive: keep tensor_shapes parallel to tensor_names even for a
      // head response constructed without shapes (per-tensor flat stand-in)
      for (size_t k = 0; k < fused.tensor_names.size(); ++k) {
        int64_t n = k < fused.tensor_output_elements.size()
                        ? fused.tensor_output_elements[k]
                        : fused.tensor_output_elements[0];
        fused.tensor_shapes.push_back(TensorShape({n}));
      }
    }
    // tensor_output_elements is always populated by ConstructResponse and
    // the wire parser, so no tensor_sizes[0] fallback here — for ALLGATHER
    // that value is rank 0's dim-0 count, not an element total.
    int64_t skipped = 0;  // look-ahead budget (reference skipped_size bound)
    int skipped_entries = 0;
    for (size_t j = i + 1; j < in.size(); ++j) {
      if (used[j]) continue;
      // sorted by (type, axis): past the group boundary nothing can fuse
      if (in[j].response_type != fused.response_type ||
          in[j].axis_name != fused.axis_name) {
        break;
      }
      int64_t nbytes = ResponseBytes(in[j]);
      if (!CanFuse(fused, in[j]) || bytes + nbytes > fusion_threshold_) {
        // Look past it. Tensors that could never fit any bin (alone above
        // the threshold) don't consume the byte budget — they go solo
        // regardless — but every skip counts against a flat entry cap so
        // a long tail keeps this pass linear-ish per cycle.
        if (nbytes <= fusion_threshold_) skipped += nbytes;
        if (skipped > fusion_threshold_ || ++skipped_entries > 64) break;
        continue;
      }
      fused.tensor_names.push_back(in[j].tensor_names[0]);
      // allgather responses carry size_ per-rank entries each; append the
      // whole block so a fused response holds tensor-count x size_ entries
      fused.tensor_sizes.insert(fused.tensor_sizes.end(),
                                in[j].tensor_sizes.begin(),
                                in[j].tensor_sizes.end());
      fused.tensor_dtypes.push_back(in[j].tensor_dtypes.empty()
                                        ? in[j].tensor_type
                                        : in[j].tensor_dtypes[0]);
      fused.tensor_output_elements.push_back(
          in[j].tensor_output_elements[0]);
      fused.tensor_shapes.push_back(
          in[j].tensor_shapes.empty()
              ? TensorShape({in[j].tensor_output_elements[0]})
              : in[j].tensor_shapes[0]);
      bytes += nbytes;
      used[j] = true;
    }
    out->responses.push_back(std::move(fused));
  }
}

ResponseList Controller::ComputeResponseList(
    bool this_process_requested_shutdown) {
  debug_cycle_++;
  if (pending_cache_clear_.exchange(false)) {
    // deferred from SetCacheEnabled (user-thread-safe); see controller.h
    response_cache_.clear();
    hit_requeues_.clear();
  }
  // 1. pop locally-ready tensors (reference controller.cc:77-113)
  std::vector<Request> ready;
  tensor_queue_.PopMessagesFromQueue(&ready);

  // 2. response-cache fast path: steady-state tensors negotiate via two
  // bitvector reductions instead of name lists
  // (reference CoordinateCacheAndState, controller.cc:613-638).
  size_t words = (response_cache_.capacity() + 63) / 64;
  std::vector<uint64_t> hit_bits(words, 0);
  // invalid and proposed bits are both OR-reduced; pack them into one
  // doubled-width sync so join support costs no extra round trip
  std::vector<uint64_t> or_bits(2 * words, 0);
  uint64_t* invalid_bits = or_bits.data();
  uint64_t* proposed_bits = or_bits.data() + words;
  std::vector<Request> negotiate;
  std::map<uint32_t, Request> my_hits;  // ordered: deterministic exec order
  for (auto& req : ready) {
    req.request_rank = rank_;
    if (req.request_type == Request::JOIN) {
      local_joined_ = true;
      negotiate.push_back(req);
      continue;
    }
    if (!cache_enabled_) {  // autotuned off: everything negotiates fully
      negotiate.push_back(req);
      continue;
    }
    auto state = response_cache_.cached(req);
    if (DebugCache()) {
      std::fprintf(stderr, "[hvddbg r%d c%lu] pop %s state=%d en=%d\n",
                   rank_, (unsigned long)debug_cycle_, req.tensor_name.c_str(),
                   (int)state, (int)cache_enabled_);
    }
    if (state == ResponseCache::HIT &&
        hit_requeues_[req.tensor_name] >= kHitRequeueLimit) {
      // the hit has spun without global agreement for many cycles: some
      // rank is on the name path for this tensor (e.g. it popped across a
      // cache-toggle window). Escalate to the OR-synced invalidation so
      // every rank drops the entry at the same cycle and the name
      // negotiation can complete.
      uint32_t bit = response_cache_.peek_cache_bit(req);
      invalid_bits[bit / 64] |= 1ull << (bit % 64);
      hit_requeues_.erase(req.tensor_name);
      negotiate.push_back(req);
    } else if (state == ResponseCache::HIT) {
      uint32_t bit = response_cache_.peek_cache_bit(req);
      hit_bits[bit / 64] |= 1ull << (bit % 64);
      proposed_bits[bit / 64] |= 1ull << (bit % 64);
      my_hits.emplace(bit, req);
    } else {
      if (state == ResponseCache::INVALID) {
        uint32_t bit = response_cache_.peek_cache_bit(req);
        invalid_bits[bit / 64] |= 1ull << (bit % 64);
      }
      negotiate.push_back(req);
    }
  }
  if (local_joined_) {
    // a joined rank agrees to whatever the live ranks hit (reference
    // CacheCoordinator joined handling); it proposes nothing itself, so the
    // executed set stays = bits every live rank hit AND someone proposed
    std::fill(hit_bits.begin(), hit_bits.end(), ~0ull);
  }
  CrossRankBitwiseAnd(hit_bits);  // globally-agreed hits
  CrossRankBitwiseOr(or_bits);    // any-rank invalidations + proposals

  std::vector<Response> cached_responses;
  std::vector<Request> requeue;
  for (auto& kv : my_hits) {
    uint32_t bit = kv.first;
    bool invalidated = (invalid_bits[bit / 64] >> (bit % 64)) & 1;
    bool agreed = (hit_bits[bit / 64] >> (bit % 64)) & 1;
    if (invalidated) {
      response_cache_.erase_response(bit);
      hit_requeues_.erase(kv.second.tensor_name);
      negotiate.push_back(kv.second);
    } else if (agreed) {
      cache_hit_count_++;
      // joined: pushed below in one global ascending sweep instead, so the
      // execution order matches the live ranks' exactly
      if (!local_joined_) {
        cached_responses.push_back(response_cache_.get_response(bit));
      }
      hit_requeues_.erase(kv.second.tensor_name);
    } else {
      // other ranks not ready yet: retry next cycle without negotiating
      // (bounded by kHitRequeueLimit, see the pop loop)
      hit_requeues_[kv.second.tensor_name]++;
      requeue.push_back(kv.second);
    }
  }
  if (local_joined_) {
    // execute the agreed set with zero contributions: caches are identical
    // on every rank, so bits the live ranks agreed on resolve locally too
    for (size_t w = 0; w < words; ++w) {
      uint64_t bits = hit_bits[w] & proposed_bits[w] & ~invalid_bits[w];
      while (bits) {
        uint32_t bit = static_cast<uint32_t>(w * 64 + __builtin_ctzll(bits));
        bits &= bits - 1;
        cached_responses.push_back(response_cache_.get_response(bit));
      }
    }
  }
  // drop entries other ranks invalidated even if we did not touch them
  for (size_t w = 0; w < words; ++w) {
    uint64_t bits = invalid_bits[w];
    while (bits) {
      uint32_t bit = static_cast<uint32_t>(w * 64 + __builtin_ctzll(bits));
      bits &= bits - 1;
      if (!my_hits.count(bit)) response_cache_.erase_response(bit);
    }
  }
  if (!requeue.empty()) tensor_queue_.PushMessagesToQueue(std::move(requeue));

  // 3. full negotiation for the rest
  RequestList my_list;
  my_list.shutdown = this_process_requested_shutdown;
  for (auto& r : negotiate) {
    if (r.request_type != Request::JOIN) {
      sent_requests_[r.tensor_name] = r;
    }
    my_list.requests.push_back(std::move(r));
  }

  std::vector<RequestList> all = GatherReadyTensors(my_list);

  ResponseList negotiated;  // unfused; broadcast so caches stay identical
  if (is_coordinator()) {
    bool shutdown = false;
    for (int r = 0; r < static_cast<int>(all.size()); ++r) {
      shutdown |= all[r].shutdown;
      for (const auto& req : all[r].requests) {
        if (req.request_type == Request::JOIN) {
          RecordJoin(r);
          continue;
        }
        if (IncrementTensorCount(req, r)) {
          EmitReady(req.tensor_name, &negotiated);
        }
      }
    }
    if (!joined_ranks_.empty()) {
      // joins this cycle may have unblocked tensors negotiated earlier
      // (reference controller.cc:219-307): sweep the table for entries
      // where every missing rank has joined
      std::vector<std::string> ready_names;
      for (const auto& kv : message_table_) {
        size_t effective = kv.second.by_rank.size();
        for (int jr : joined_ranks_) {
          if (!kv.second.by_rank.count(jr)) effective++;
        }
        if (effective >= static_cast<size_t>(size_)) {
          ready_names.push_back(kv.first);
        }
      }
      for (const auto& n : ready_names) EmitReady(n, &negotiated);
      if (static_cast<int>(joined_ranks_.size()) == size_) {
        // everyone joined: complete every rank's join() handle, reporting
        // the last rank to join (reference torch/mpi_ops.py:511-524)
        Response jr;
        jr.response_type = Response::JOIN;
        jr.tensor_names = {kJoinTensorName};
        jr.root_rank = last_joined_rank_;
        negotiated.responses.push_back(std::move(jr));
        joined_ranks_.clear();
      }
    }
    if (stall_inspector_.CheckForStalledTensors(message_table_, size_)) {
      shutdown = true;
    }
    negotiated.shutdown = shutdown;
    negotiated.tuned_cycle_time_ms = tuned_cycle_ms_;
    negotiated.tuned_fusion_threshold = tuned_fusion_;
    negotiated.tuned_cache_enabled = tuned_cache_;
    negotiated.tuned_hier_allreduce = tuned_hier_allreduce_;
    negotiated.tuned_hier_allgather = tuned_hier_allgather_;
  }
  BroadcastResponseList(&negotiated);

  // 4. every rank updates its cache identically from the negotiated list.
  // Puts are unconditional: a joined rank that never enqueued the tensor
  // still caches it (with a request reconstructed from the response — the
  // response carries the TRUE shape, so the reconstructed key matches the
  // live ranks' and the post-rejoin enqueue cache-HITs; pinned by
  // tests/test_multiprocess_scale.py rejoin test).
  for (const auto& resp : negotiated.responses) {
    if (resp.response_type == Response::JOIN) {
      local_joined_ = false;  // the whole job joined; we are live again
    }
    if (cache_enabled_ &&
        resp.response_type != Response::ERROR &&
        resp.response_type != Response::JOIN &&
        resp.response_type != Response::BARRIER &&
        resp.tensor_names.size() == 1) {
      auto it = sent_requests_.find(resp.tensor_names[0]);
      if (DebugCache()) {
        std::fprintf(stderr, "[hvddbg r%d c%lu] put %s sent=%d en=%d\n",
                     rank_, (unsigned long)debug_cycle_,
                     resp.tensor_names[0].c_str(),
                     (int)(it != sent_requests_.end()), (int)cache_enabled_);
      }
      if (it != sent_requests_.end()) {
        response_cache_.put(resp, it->second);
      } else {
        Request r;
        r.tensor_name = resp.tensor_names[0];
        r.request_type = resp.response_type;  // type tags are shared 0-7
        r.tensor_type = resp.tensor_type;
        r.root_rank = resp.root_rank;
        r.reduce_op = resp.reduce_op;
        r.axis_name = resp.axis_name;
        r.prescale_factor = resp.prescale_factor;
        r.postscale_factor = resp.postscale_factor;
        // the response carries the TRUE shape, so this joined-rank entry
        // caches under the same key as the live ranks' and the post-rejoin
        // enqueue cache-HITs (ConstructResponse always fills tensor_shapes;
        // the flat branch is pure defense for a hand-built Response)
        r.tensor_shape =
            !resp.tensor_shapes.empty()
                ? resp.tensor_shapes[0]
                : TensorShape(
                      {resp.tensor_sizes.empty() ? 0 : resp.tensor_sizes[0]});
        response_cache_.put(resp, r);
      }
    }
    for (const auto& n : resp.tensor_names) sent_requests_.erase(n);
  }

  // 5. deterministic combined order (cached first, by bit), then fuse
  std::vector<Response> final_responses = std::move(cached_responses);
  for (auto& r : negotiated.responses) final_responses.push_back(std::move(r));
  ResponseList result;
  result.shutdown = negotiated.shutdown;
  result.tuned_cycle_time_ms = negotiated.tuned_cycle_time_ms;
  result.tuned_fusion_threshold = negotiated.tuned_fusion_threshold;
  result.tuned_cache_enabled = negotiated.tuned_cache_enabled;
  result.tuned_hier_allreduce = negotiated.tuned_hier_allreduce;
  result.tuned_hier_allgather = negotiated.tuned_hier_allgather;
  FuseResponses(final_responses, &result);
  return result;
}

}  // namespace hvd
