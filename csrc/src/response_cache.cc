#include "hvd/response_cache.h"

namespace hvd {

bool ResponseCache::SameParams(const Request& a, const Request& b) {
  return a.request_type == b.request_type && a.tensor_type == b.tensor_type &&
         a.root_rank == b.root_rank && a.reduce_op == b.reduce_op &&
         a.tensor_shape == b.tensor_shape &&
         a.prescale_factor == b.prescale_factor &&
         a.postscale_factor == b.postscale_factor;
}

ResponseCache::CacheState ResponseCache::cached(const Request& req) const {
  auto it = name_to_bit_.find(req.tensor_name);
  if (it == name_to_bit_.end()) return MISS;
  const Entry& e = entries_.at(it->second);
  return SameParams(e.request, req) ? HIT : INVALID;
}

uint32_t ResponseCache::peek_cache_bit(const Request& req) const {
  return name_to_bit_.at(req.tensor_name);
}

void ResponseCache::put(const Response& resp, const Request& req) {
  if (capacity_ == 0) return;
  auto it = name_to_bit_.find(req.tensor_name);
  if (it != name_to_bit_.end()) {
    uint32_t bit = it->second;
    entries_[bit] = Entry{resp, req, bit};
    touch(bit);
    return;
  }
  if (entries_.size() >= capacity_) {
    uint32_t victim = lru_.front();
    lru_.pop_front();
    lru_pos_.erase(victim);
    name_to_bit_.erase(entries_.at(victim).request.tensor_name);
    entries_.erase(victim);
    free_bits_.push_back(victim);
  }
  uint32_t bit = alloc_bit();
  entries_[bit] = Entry{resp, req, bit};
  name_to_bit_[req.tensor_name] = bit;
  lru_.push_back(bit);
  lru_pos_[bit] = std::prev(lru_.end());
}

uint32_t ResponseCache::alloc_bit() {
  if (!free_bits_.empty()) {
    uint32_t b = free_bits_.back();
    free_bits_.pop_back();
    return b;
  }
  return next_bit_++;
}

const Response& ResponseCache::get_response(uint32_t bit) {
  touch(bit);
  return entries_.at(bit).response;
}

const Response& ResponseCache::peek_response(uint32_t bit) const {
  return entries_.at(bit).response;
}

void ResponseCache::erase_response(uint32_t bit) {
  auto it = entries_.find(bit);
  if (it == entries_.end()) return;
  name_to_bit_.erase(it->second.request.tensor_name);
  auto pos = lru_pos_.find(bit);
  if (pos != lru_pos_.end()) {
    lru_.erase(pos->second);
    lru_pos_.erase(pos);
  }
  entries_.erase(it);
  free_bits_.push_back(bit);
}

void ResponseCache::clear() {
  entries_.clear();
  name_to_bit_.clear();
  lru_.clear();
  lru_pos_.clear();
  free_bits_.clear();
  next_bit_ = 0;
}

std::vector<uint32_t> ResponseCache::valid_bits() const {
  return std::vector<uint32_t>(lru_.begin(), lru_.end());
}

void ResponseCache::touch(uint32_t bit) {
  auto pos = lru_pos_.find(bit);
  if (pos == lru_pos_.end()) return;
  lru_.erase(pos->second);
  lru_.push_back(bit);
  lru_pos_[bit] = std::prev(lru_.end());
}

}  // namespace hvd
