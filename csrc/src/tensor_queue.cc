#include "hvd/tensor_queue.h"

namespace hvd {

Status TensorQueue::AddToTensorQueue(const TensorTableEntry& entry) {
  std::lock_guard<std::mutex> lk(mu_);
  const std::string& name = entry.meta.tensor_name;
  if (table_.count(name)) {
    return Status::InvalidArgument(
        "Duplicate tensor name: " + name +
        "; a collective with this name is already pending.");
  }
  table_.emplace(name, entry);
  message_queue_.push_back(entry.meta);
  return Status::OK();
}

void TensorQueue::PopMessagesFromQueue(std::vector<Request>* out) {
  std::lock_guard<std::mutex> lk(mu_);
  out->assign(message_queue_.begin(), message_queue_.end());
  message_queue_.clear();
}

void TensorQueue::PushMessagesToQueue(std::vector<Request> msgs) {
  std::lock_guard<std::mutex> lk(mu_);
  // preserve original order ahead of newer messages
  for (auto it = msgs.rbegin(); it != msgs.rend(); ++it) {
    message_queue_.push_front(std::move(*it));
  }
}

bool TensorQueue::PopEntry(const std::string& name, TensorTableEntry* out) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = table_.find(name);
  if (it == table_.end()) return false;
  *out = it->second;
  table_.erase(it);
  return true;
}

std::vector<int64_t> TensorQueue::DrainAllHandles() {
  std::lock_guard<std::mutex> lk(mu_);
  std::vector<int64_t> handles;
  handles.reserve(table_.size());
  for (auto& kv : table_) handles.push_back(kv.second.handle);
  table_.clear();
  message_queue_.clear();
  return handles;
}

size_t TensorQueue::pending_count() const {
  std::lock_guard<std::mutex> lk(mu_);
  return table_.size();
}

}  // namespace hvd
