// Chrome-tracing (chrome://tracing / perfetto) timeline writer
// (reference horovod/common/timeline.{h,cc}): per-tensor NEGOTIATING /
// top-level op / nested activity phases, written by a dedicated thread so
// the negotiation loop never blocks on disk. The reference feeds it through
// a boost lock-free SPSC ring; a mutexed deque + condvar is enough at
// control-plane event rates (hundreds/sec) and drops the vendored dep.

#ifndef HVD_TIMELINE_H
#define HVD_TIMELINE_H

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

namespace hvd {

class Timeline {
 public:
  ~Timeline() { Shutdown(); }

  void Initialize(const std::string& path, int rank);
  bool Initialized() const { return initialized_.load(); }
  void Shutdown();

  // phase events (reference timeline.h: NegotiateStart/End, Start/End,
  // ActivityStart/End)
  void NegotiateStart(const std::string& tensor, int request_type);
  void NegotiateRankReady(const std::string& tensor, int rank);
  void NegotiateEnd(const std::string& tensor);
  void Start(const std::string& tensor, const std::string& op_name);
  void ActivityStart(const std::string& tensor, const std::string& activity);
  void ActivityEnd(const std::string& tensor);
  void End(const std::string& tensor, int64_t bytes);
  void MarkCycleStart();
  // instant marker on the "fusion" lane for a fused launch: tensor count +
  // distinct dtype count (mixed-dtype bins are a TPU-native capability the
  // reference's single-dtype fusion buffer lacks)
  void MarkFusedLaunch(const std::string& op_name, size_t n_tensors,
                       size_t n_dtypes);

 private:
  struct Event {
    char phase;  // 'B' begin, 'E' end, 'i' instant
    std::string tid;   // per-tensor lane
    std::string name;
    std::string args;  // pre-rendered json fragment or empty
    int64_t ts_us;
  };

  void Enqueue(Event e);
  void WriterLoop();
  int64_t NowUs() const;

  std::atomic<bool> initialized_{false};
  std::atomic<bool> shutdown_{false};
  FILE* file_ = nullptr;
  int rank_ = 0;
  bool first_event_ = true;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Event> queue_;
  std::thread writer_;
  std::chrono::steady_clock::time_point t0_;
};

}  // namespace hvd

#endif  // HVD_TIMELINE_H
