// TCP transport for the controller protocol: coordinator (process rank 0)
// listens, workers connect. Plays the role of the reference's
// MPIController/GlooController transports (mpi_controller.cc:87-220,
// gloo_controller.cc): gather of serialized RequestLists, broadcast of
// ResponseLists, bitvector AND/OR reductions, barrier.
//
// Wire: length-prefixed frames (u32 length + u8 tag + payload). One
// persistent connection per worker; the coordinator services them from its
// own background-loop thread each cycle (all processes call the collective
// methods in lockstep, like MPI).

#ifndef HVD_TCP_CONTROLLER_H
#define HVD_TCP_CONTROLLER_H

#include <string>
#include <vector>

#include "hvd/controller.h"

namespace hvd {

class TcpController : public Controller {
 public:
  TcpController(int rank, int size, std::string coordinator_host,
                int coordinator_port, TensorQueue& queue, ResponseCache& cache,
                StallInspector& stall)
      : Controller(rank, size, queue, cache, stall),
        host_(std::move(coordinator_host)), port_(coordinator_port) {}
  ~TcpController() override;

  // Establish the full star topology; blocks until all workers connected.
  Status Initialize(double timeout_s = 60.0);

  std::vector<RequestList> GatherReadyTensors(const RequestList& mine) override;
  void BroadcastResponseList(ResponseList* list) override;
  void CrossRankBitwiseAnd(std::vector<uint64_t>& bits) override;
  void CrossRankBitwiseOr(std::vector<uint64_t>& bits) override;
  void Barrier() override;

  std::string lost_peer_detail() const override { return lost_peer_; }

 private:
  // frame tags
  enum Tag : uint8_t {
    HELLO = 0,
    REQUESTS = 1,
    RESPONSES = 2,
    BITS_AND = 3,
    BITS_OR = 4,
    BARRIER_T = 5,
  };

  bool SendFrame(int fd, uint8_t tag, const std::string& payload);
  bool RecvFrame(int fd, uint8_t* tag, std::string* payload);
  void BitReduce(std::vector<uint64_t>& bits, uint8_t tag);

  void MarkLostCoordinator();
  void MarkLostWorker(int rank);

  std::string host_;
  int port_;
  std::string lost_peer_;
  int listen_fd_ = -1;
  // coordinator: worker_fds_[r] for ranks 1..size-1 (index r-1);
  // worker: single fd to coordinator
  std::vector<int> worker_fds_;
  int coord_fd_ = -1;
};

}  // namespace hvd

#endif  // HVD_TCP_CONTROLLER_H
