// Core types for the native control plane.
//
// TPU-native rebuild of the reference's common layer
// (reference horovod/common/common.h:105-251): Status, DataType,
// TensorShape, Request/Response messages. Unlike the reference, the core
// never touches tensor *data* — device buffers live in HBM under XLA's
// control; the core negotiates metadata (which named tensors are ready on
// which process) and hands fused execution plans back to the runtime.

#ifndef HVD_COMMON_H
#define HVD_COMMON_H

#include <cstdint>
#include <string>
#include <vector>

namespace hvd {

enum class StatusType : int {
  OK = 0,
  UNKNOWN_ERROR = 1,
  PRECONDITION_ERROR = 2,
  ABORTED = 3,
  INVALID_ARGUMENT = 4,
  IN_PROGRESS = 5,
};

class Status {
 public:
  Status() = default;
  static Status OK() { return Status(); }
  static Status UnknownError(std::string msg) {
    return Status(StatusType::UNKNOWN_ERROR, std::move(msg));
  }
  static Status PreconditionError(std::string msg) {
    return Status(StatusType::PRECONDITION_ERROR, std::move(msg));
  }
  static Status Aborted(std::string msg) {
    return Status(StatusType::ABORTED, std::move(msg));
  }
  static Status InvalidArgument(std::string msg) {
    return Status(StatusType::INVALID_ARGUMENT, std::move(msg));
  }
  static Status InProgress() { return Status(StatusType::IN_PROGRESS, ""); }

  bool ok() const { return type_ == StatusType::OK; }
  bool in_progress() const { return type_ == StatusType::IN_PROGRESS; }
  StatusType type() const { return type_; }
  const std::string& reason() const { return reason_; }

 private:
  Status(StatusType type, std::string reason)
      : type_(type), reason_(std::move(reason)) {}
  StatusType type_ = StatusType::OK;
  std::string reason_;
};

// dtype tags shared with the Python side (horovod_tpu/core.py keeps the
// mirror table); sizes matter only for fusion bin-packing.
enum class DataType : int {
  HVD_UINT8 = 0,
  HVD_INT8 = 1,
  HVD_UINT16 = 2,
  HVD_INT16 = 3,
  HVD_INT32 = 4,
  HVD_INT64 = 5,
  HVD_FLOAT16 = 6,
  HVD_BFLOAT16 = 7,
  HVD_FLOAT32 = 8,
  HVD_FLOAT64 = 9,
  HVD_BOOL = 10,
};

inline int DataTypeSize(DataType dt) {
  switch (dt) {
    case DataType::HVD_UINT8:
    case DataType::HVD_INT8:
    case DataType::HVD_BOOL:
      return 1;
    case DataType::HVD_UINT16:
    case DataType::HVD_INT16:
    case DataType::HVD_FLOAT16:
    case DataType::HVD_BFLOAT16:
      return 2;
    case DataType::HVD_INT32:
    case DataType::HVD_FLOAT32:
      return 4;
    case DataType::HVD_INT64:
    case DataType::HVD_FLOAT64:
      return 8;
  }
  return 4;
}

class TensorShape {
 public:
  TensorShape() = default;
  explicit TensorShape(std::vector<int64_t> dims) : dims_(std::move(dims)) {}
  void AddDim(int64_t d) { dims_.push_back(d); }
  int ndim() const { return static_cast<int>(dims_.size()); }
  int64_t dim(int i) const { return dims_[i]; }
  const std::vector<int64_t>& dims() const { return dims_; }
  int64_t num_elements() const {
    int64_t n = 1;
    for (auto d : dims_) n *= d;
    return n;
  }
  bool operator==(const TensorShape& o) const { return dims_ == o.dims_; }
  bool operator!=(const TensorShape& o) const { return dims_ != o.dims_; }
  std::string DebugString() const;

 private:
  std::vector<int64_t> dims_;
};

// Request: worker -> coordinator "tensor X is ready on my rank"
// (reference horovod/common/message.h:47-120).
struct Request {
  enum Type : int {
    ALLREDUCE = 0,
    ALLGATHER = 1,
    BROADCAST = 2,
    JOIN = 3,
    ADASUM = 4,
    ALLTOALL = 5,
    REDUCESCATTER = 6,
    BARRIER = 7,
  };
  static const char* TypeName(int t);

  int32_t request_rank = 0;
  int32_t request_type = ALLREDUCE;
  int32_t tensor_type = 0;  // DataType
  int32_t root_rank = -1;   // broadcast only
  int32_t reduce_op = 0;    // ReduceOp (average/sum/adasum), allreduce only
  std::string tensor_name;
  // mesh axis the collective runs over ("" = the default data axis); the
  // core treats it as an opaque token: cross-rank validated, fused only
  // within one axis, and echoed in the Response so a join()ed process can
  // zero-backfill on the right axis
  std::string axis_name;
  TensorShape tensor_shape;
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
};

struct RequestList {
  std::vector<Request> requests;
  bool shutdown = false;
};

// Response: coordinator -> all "execute this (possibly fused) op now"
// (reference horovod/common/message.h:125-221).
struct Response {
  enum Type : int {
    ALLREDUCE = 0,
    ALLGATHER = 1,
    BROADCAST = 2,
    JOIN = 3,
    ADASUM = 4,
    ALLTOALL = 5,
    REDUCESCATTER = 6,
    BARRIER = 7,
    ERROR = 8,
  };
  static const char* TypeName(int t);

  int32_t response_type = ALLREDUCE;
  std::vector<std::string> tensor_names;
  std::string error_message;
  // per-tensor sizes (elements) for allgather displacement math and fusion
  std::vector<int64_t> tensor_sizes;
  // per-tensor dtypes, parallel to tensor_names. The XLA data plane launches
  // grouped collectives with each array keeping its own dtype (there is no
  // shared fusion buffer to homogenize), so one fused response may carry
  // mixed dtypes — the reference can only look *past* dtype breaks
  // (controller.cc:640-761); it cannot pack them together.
  std::vector<int32_t> tensor_dtypes;
  // per-tensor TOTAL output element count, parallel to tensor_names
  // (allreduce: the tensor's element count; allgather: summed over ranks).
  // Fusion bin-packing accounts bytes with this — tensor_sizes holds
  // per-RANK dim0 entries for allgather displacement math and cannot double
  // as a byte measure (reference TotalByteSizeOfAllgatherOutput).
  std::vector<int64_t> tensor_output_elements;
  // per-tensor TRUE shapes, parallel to tensor_names: lets a joined rank
  // cache a tensor it never enqueued under the same shape key as the live
  // ranks, so its post-rejoin enqueue cache-HITs instead of invalidating
  // and renegotiating (reference response_cache.h:45-167 keys on shape)
  std::vector<TensorShape> tensor_shapes;
  int32_t tensor_type = 0;  // dtype of tensor 0 (legacy single-dtype field)
  int32_t root_rank = -1;
  int32_t reduce_op = 0;
  std::string axis_name;  // echo of Request::axis_name
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
};

struct ResponseList {
  std::vector<Response> responses;
  bool shutdown = false;
  // autotuned parameters chosen by the coordinator this cycle; every process
  // applies them so tunables stay identical job-wide (reference
  // SynchronizeParameters, controller.cc:33-47). 0 / -1 = "no change".
  double tuned_cycle_time_ms = 0.0;
  int64_t tuned_fusion_threshold = -1;
  int32_t tuned_cache_enabled = -1;  // -1 no change, 0 off, 1 on
  // hierarchical-collective strategy toggles (reference tunes these too,
  // parameter_manager.cc:44-60); applied by the Python data plane. Wire
  // format: OPTIONAL trailing pair after the responses (absent = -1), so
  // older parsers keep working.
  int32_t tuned_hier_allreduce = -1;
  int32_t tuned_hier_allgather = -1;
};

// --- serialization (compact hand-rolled binary; the reference uses
// FlatBuffers, common/wire/message.fbs — a vendored dependency we do not
// need for fixed, versioned internal wire traffic) ---
void SerializeRequestList(const RequestList& in, std::string* out);
bool ParseRequestList(const char* data, size_t len, RequestList* out);
void SerializeResponseList(const ResponseList& in, std::string* out);
bool ParseResponseList(const char* data, size_t len, ResponseList* out);

}  // namespace hvd

#endif  // HVD_COMMON_H
