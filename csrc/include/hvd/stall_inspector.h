// Hang detection (reference horovod/common/stall_inspector.{h,cc}):
// the coordinator warns when a tensor has been ready on a subset of ranks
// longer than the warning interval (default 60 s), and optionally aborts the
// job after a shutdown interval.

#ifndef HVD_STALL_INSPECTOR_H
#define HVD_STALL_INSPECTOR_H

#include <chrono>
#include <functional>
#include <set>
#include <string>
#include <vector>

namespace hvd {

class StallInspector {
 public:
  using Clock = std::chrono::steady_clock;

  void set_warning_seconds(double s) { warn_s_ = s; }
  void set_shutdown_seconds(double s) { shutdown_s_ = s; }  // 0 = disabled
  double warning_seconds() const { return warn_s_; }
  double shutdown_seconds() const { return shutdown_s_; }

  // Log sink (wired to the runtime's logger by the C API).
  void set_log_fn(std::function<void(const std::string&)> fn) {
    log_fn_ = std::move(fn);
  }

  struct StalledTensor {
    std::string name;
    std::vector<int> ready_ranks;
    std::vector<int> missing_ranks;
    double stalled_seconds;
  };

  // Scan the coordinator's message table; returns true if the job should be
  // shut down (stall exceeded shutdown interval)
  // (reference CheckForStalledTensors, stall_inspector.cc).
  template <typename Table>
  bool CheckForStalledTensors(const Table& table, int size) {
    auto now = Clock::now();
    bool abort = false;
    std::vector<StalledTensor> stalled;
    for (const auto& kv : table) {
      double age =
          std::chrono::duration<double>(now - kv.second.first_seen).count();
      if (age < warn_s_) continue;
      StalledTensor st;
      st.name = kv.first;
      st.stalled_seconds = age;
      for (int r = 0; r < size; ++r) {
        if (kv.second.by_rank.count(r)) {
          st.ready_ranks.push_back(r);
        } else {
          st.missing_ranks.push_back(r);
        }
      }
      if (shutdown_s_ > 0 && age >= shutdown_s_) abort = true;
      stalled.push_back(std::move(st));
    }
    double now_s = std::chrono::duration<double>(now.time_since_epoch()).count();
    if (!stalled.empty() && log_fn_ && now_s - last_warn_s_ >= warn_s_) {
      last_warn_s_ = now_s;
      for (const auto& st : stalled) {
        std::string msg = "Stalled collective: " + st.name + " waited " +
                          std::to_string(st.stalled_seconds) +
                          "s; missing ranks:";
        for (int r : st.missing_ranks) msg += " " + std::to_string(r);
        log_fn_(msg);
      }
    }
    return abort;
  }

 private:
  double warn_s_ = 60.0;      // reference stall_inspector.h:75
  double shutdown_s_ = 0.0;   // reference stall_inspector.h:77-80 (disabled)
  double last_warn_s_ = 0.0;
  std::function<void(const std::string&)> log_fn_;
};

}  // namespace hvd

#endif  // HVD_STALL_INSPECTOR_H
