// LRU cache of negotiated responses, bit-indexed so steady-state steps
// coordinate with a couple of bitvector AND-reductions instead of
// re-negotiating tensor names (reference horovod/common/response_cache.h:45-167).

#ifndef HVD_RESPONSE_CACHE_H
#define HVD_RESPONSE_CACHE_H

#include <cstdint>
#include <list>
#include <string>
#include <unordered_map>
#include <vector>

#include "hvd/common.h"

namespace hvd {

class ResponseCache {
 public:
  enum CacheState : int { MISS = 0, HIT = 1, INVALID = 2 };

  void set_capacity(size_t cap) { capacity_ = cap; }
  size_t capacity() const { return capacity_; }
  size_t size() const { return entries_.size(); }

  // HIT iff an identical request (name+type+shape+op params) was negotiated
  // before; INVALID if the name is cached but parameters changed (forces
  // re-negotiation and eviction, reference response_cache.cc).
  CacheState cached(const Request& req) const;

  // Insert/refresh after a successful negotiation.
  void put(const Response& resp, const Request& req);

  uint32_t peek_cache_bit(const Request& req) const;
  const Response& get_response(uint32_t bit);
  const Response& peek_response(uint32_t bit) const;
  void erase_response(uint32_t bit);
  void clear();

  // Bits currently valid, most-recently-used last (for stall invalidation).
  std::vector<uint32_t> valid_bits() const;

 private:
  struct Entry {
    Response response;
    Request request;
    uint32_t bit;
  };
  size_t capacity_ = 1024;  // reference default, global_state.h:88
  // LRU list of cache bits; front = LRU victim
  std::list<uint32_t> lru_;
  std::unordered_map<uint32_t, std::list<uint32_t>::iterator> lru_pos_;
  std::unordered_map<uint32_t, Entry> entries_;
  std::unordered_map<std::string, uint32_t> name_to_bit_;
  // bits stay in [0, capacity): freed bits are reused so the coordination
  // bitvector has a fixed width on every rank
  std::vector<uint32_t> free_bits_;
  uint32_t next_bit_ = 0;

  uint32_t alloc_bit();
  void touch(uint32_t bit);
  static bool SameParams(const Request& a, const Request& b);
};

}  // namespace hvd

#endif  // HVD_RESPONSE_CACHE_H
