// Pending-tensor table + message queue
// (reference horovod/common/tensor_queue.h:28-63).
//
// The Python runtime enqueues named tensor *metadata* (the device arrays
// themselves stay registered on the Python side keyed by the same name);
// the background loop pops messages each cycle and feeds the controller.

#ifndef HVD_TENSOR_QUEUE_H
#define HVD_TENSOR_QUEUE_H

#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "hvd/common.h"

namespace hvd {

struct TensorTableEntry {
  Request meta;
  int64_t handle = -1;  // Python-side handle id for completion callbacks
};

class TensorQueue {
 public:
  // Rejects duplicate names among pending tensors
  // (DUPLICATE_NAME_ERROR, reference common/common.h:161-164).
  Status AddToTensorQueue(const TensorTableEntry& entry);

  // Pop all queued messages for this cycle
  // (reference PopMessagesFromQueue, tensor_queue.cc).
  void PopMessagesFromQueue(std::vector<Request>* out);

  // Push back messages that missed coordination this cycle (cache-miss
  // requeue, reference PushMessagesToQueue).
  void PushMessagesToQueue(std::vector<Request> msgs);

  // Remove finished tensors and return their handles.
  bool PopEntry(const std::string& name, TensorTableEntry* out);

  // Abort everything pending with `status` (shutdown propagation,
  // reference FinalizeTensorQueue + SHUT_DOWN_ERROR common.h:154-159).
  std::vector<int64_t> DrainAllHandles();

  size_t pending_count() const;

 private:
  mutable std::mutex mu_;
  std::unordered_map<std::string, TensorTableEntry> table_;
  std::deque<Request> message_queue_;
};

}  // namespace hvd

#endif  // HVD_TENSOR_QUEUE_H
