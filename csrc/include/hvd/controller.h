// The coordination protocol (reference horovod/common/controller.{h,cc}).
//
// Each background cycle, every process pops its locally-ready named tensors
// and the controller decides which collectives the whole job executes this
// cycle, in a deterministic order, with cross-rank validation:
//
//   - coordinator/worker negotiation over a pluggable transport
//     (reference controller.h:58-98 master/worker docs);
//   - response-cache bitvector sync for steady-state steps
//     (reference CoordinateCacheAndState, controller.cc:613-638);
//   - readiness counting (IncrementTensorCount, controller.cc:789-812);
//   - response construction with dtype/shape/op/root validation producing
//     ERROR responses on mismatch (ConstructResponse, controller.cc:378-611);
//   - fusion bin-packing (FuseResponses, controller.cc:640-761);
//   - join bookkeeping and shutdown propagation.
//
// Transport virtuals mirror the reference's (controller.h:44-143), minus
// data-plane ops: the data plane is XLA's.

#ifndef HVD_CONTROLLER_H
#define HVD_CONTROLLER_H

#include <atomic>
#include <chrono>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#include "hvd/common.h"
#include "hvd/response_cache.h"
#include "hvd/stall_inspector.h"
#include "hvd/tensor_queue.h"

namespace hvd {

// Well-known tensor name carried by JOIN responses so every process can
// complete its local join() handle (mirrored in horovod_tpu/core.py).
constexpr const char* kJoinTensorName = "__hvd_join__";

class Controller {
 public:
  Controller(int rank, int size, TensorQueue& queue, ResponseCache& cache,
             StallInspector& stall)
      : rank_(rank), size_(size), tensor_queue_(queue), response_cache_(cache),
        stall_inspector_(stall) {}
  virtual ~Controller() = default;

  int rank() const { return rank_; }
  int size() const { return size_; }
  bool is_coordinator() const { return rank_ == 0; }

  // One negotiation cycle. `this_process_requested_shutdown` folds the local
  // shutdown flag into the job-wide decision (OR across ranks).
  ResponseList ComputeResponseList(bool this_process_requested_shutdown);

  void SetFusionThresholdBytes(int64_t b) { fusion_threshold_ = b; }
  int64_t fusion_threshold_bytes() const { return fusion_threshold_; }

  // Cache toggle (autotuned; reference tunes cache capacity on/off,
  // parameter_manager.cc:44-60). Applied by every rank at the same cycle
  // boundary via the broadcast ResponseList; the bitvector transport rounds
  // still run when disabled so the transport sequence never diverges.
  //
  // Re-enabling CLEARS the cache on every rank at the same cycle boundary:
  // tensors pop on client-timed cycles, so across a toggle window one rank
  // can have negotiated a name (popped while OFF) that another later
  // cache-hits (popped after ON) — the name path then waits for all ranks'
  // names while the hit ranks wait for all ranks' bits, a deadlock. A
  // synchronized clear makes every post-toggle pop MISS and rebuilds all
  // caches identically from broadcasts. The clear itself is DEFERRED to the
  // top of the next ComputeResponseList: this setter is reachable from the
  // user thread (hvd_core_set_cache_enabled) while the cycle thread owns
  // the containers, so only a flag flips here.
  void SetCacheEnabled(bool e) {
    if (e && !cache_enabled_) {
      pending_cache_clear_.store(true);
    }
    cache_enabled_ = e;
  }
  bool cache_enabled() const { return cache_enabled_; }
  // steady-state observability: globally-agreed cache hits this process
  // proposed (a rejoin that renegotiates shows up as a hit-count stall)
  uint64_t cache_hit_count() const { return cache_hit_count_.load(); }

  void RecordJoin(int rank) {
    joined_ranks_.insert(rank);
    last_joined_rank_ = rank;
  }

  // Non-empty once the transport has detected a dead peer (closed socket):
  // a human-readable detail the shutdown abort surfaces instead of the
  // generic "background loop shut down" message, so a worker whose
  // coordinator died fails fast with the cause (reference analog: the
  // launcher kills the job on any rank exit, gloo_run.py:294-304).
  virtual std::string lost_peer_detail() const { return {}; }

  // Coordinator-side: attach autotuned parameters to the next broadcast
  // ResponseList (reference SynchronizeParameters, controller.cc:33-47).
  // The hierarchical toggles mirror the reference's
  // hierarchical_allreduce/allgather tunables (parameter_manager.cc:44-60);
  // they are applied by the PYTHON data plane at the same cycle boundary
  // (the C core only transports them).
  void SetAutotunedParams(double cycle_ms, int64_t fusion_bytes,
                          int cache_enabled = -1, int hier_allreduce = -1,
                          int hier_allgather = -1) {
    tuned_cycle_ms_ = cycle_ms;
    tuned_fusion_ = fusion_bytes;
    tuned_cache_ = cache_enabled;
    tuned_hier_allreduce_ = hier_allreduce;
    tuned_hier_allgather_ = hier_allgather;
  }

  // --- transport virtuals ---
  // worker -> coordinator: my ready requests; returns all ranks' lists on
  // the coordinator (index = rank).
  virtual std::vector<RequestList> GatherReadyTensors(
      const RequestList& mine) = 0;
  // coordinator -> all: the final decisions.
  virtual void BroadcastResponseList(ResponseList* list) = 0;
  // AND/OR-reduce a fixed-size bitvector across ranks (cache coordination:
  // AND for agreed hits, OR for invalidations — reference
  // CacheCoordinator.sync, response_cache.h:45-167).
  virtual void CrossRankBitwiseAnd(std::vector<uint64_t>& bits) = 0;
  virtual void CrossRankBitwiseOr(std::vector<uint64_t>& bits) = 0;
  virtual void Barrier() = 0;

 protected:
  // Count tensor readiness; true once all non-joined ranks reported
  // (reference IncrementTensorCount).
  bool IncrementTensorCount(const Request& req, int source_rank);
  Response ConstructResponse(const std::string& name);
  // Emit the response for a fully-ready tensor and drop its table entry.
  // Readiness reached via join backfill is only legal for elementwise
  // reductions (reference controller.cc:454-457: allgather/broadcast are
  // unsupported with join) — other types produce an ERROR response.
  void EmitReady(const std::string& name, ResponseList* out);
  void FuseResponses(std::vector<Response>& in, ResponseList* out);

  int rank_;
  int size_;
  TensorQueue& tensor_queue_;
  ResponseCache& response_cache_;
  StallInspector& stall_inspector_;
  int64_t fusion_threshold_ = 64 * 1024 * 1024;  // reference operations.cc:419
  // atomic: SetCacheEnabled is reachable from the user thread while the
  // cycle thread reads it in ComputeResponseList (single-process direct
  // calls; multi-process toggles must still ride the tuned broadcast so all
  // ranks switch at the same cycle — see core.py set_cache_enabled)
  std::atomic<bool> cache_enabled_{true};
  uint64_t debug_cycle_ = 0;  // HVD_DEBUG_CACHE diagnostics only
  // atomics: written by the cycle thread (autotune Update) AND by the user
  // thread via hvd_core_set_autotuned_params; read by the cycle thread in
  // ComputeResponseList. Same cross-thread pattern as cache_enabled_.
  std::atomic<double> tuned_cycle_ms_{0.0};
  std::atomic<int64_t> tuned_fusion_{-1};
  std::atomic<int> tuned_cache_{-1};
  std::atomic<int> tuned_hier_allreduce_{-1};
  std::atomic<int> tuned_hier_allgather_{-1};
  std::set<int> joined_ranks_;
  int last_joined_rank_ = -1;
  // This process called join() and is waiting for the rest of the job: it
  // agrees to every cache hit (all-ones AND contribution) and executes the
  // agreed set with zero contributions (reference CacheCoordinator joined
  // handling + tensor_queue.cc zero substitution).
  bool local_joined_ = false;

  struct MessageTableEntry {
    std::map<int, Request> by_rank;  // reporting rank -> its request
    std::chrono::steady_clock::time_point first_seen;
  };
  // coordinator-side readiness table (reference MessageTable)
  std::unordered_map<std::string, MessageTableEntry> message_table_;
  // worker-side copy of requests sent for negotiation, so the local cache can
  // be updated when the response arrives (all ranks keep identical caches).
  std::unordered_map<std::string, Request> sent_requests_;
  // consecutive cycles a cache hit has been proposed without global
  // agreement; past kHitRequeueLimit the hit escalates to the OR-synced
  // invalidation path so every rank erases the entry at the same cycle and
  // renegotiates by name (local-only erasure would desync bit assignment)
  std::unordered_map<std::string, int> hit_requeues_;
  // atomic: incremented on the cycle thread, read from the user thread
  // via hvd_core_cache_hit_count (same pattern as cache_enabled_)
  std::atomic<uint64_t> cache_hit_count_{0};
  static constexpr int kHitRequeueLimit = 200;
  std::atomic<bool> pending_cache_clear_{false};
};

// Single-process controller: every locally-ready tensor is globally ready
// (the degenerate size-1 mode every Horovod test exercises, plus the
// single-controller multi-chip TPU mode where chip-parallelism lives inside
// XLA programs, not across processes).
class LocalController : public Controller {
 public:
  using Controller::Controller;
  std::vector<RequestList> GatherReadyTensors(const RequestList& mine) override {
    return {mine};
  }
  void BroadcastResponseList(ResponseList*) override {}
  void CrossRankBitwiseAnd(std::vector<uint64_t>&) override {}
  void CrossRankBitwiseOr(std::vector<uint64_t>&) override {}
  void Barrier() override {}
};

}  // namespace hvd

#endif  // HVD_CONTROLLER_H
