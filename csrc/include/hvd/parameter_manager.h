// Autotuner: Bayesian-optimization search over the core's tunables
// (reference horovod/common/parameter_manager.{h,cc} C9 +
// common/optim/bayesian_optimization.{h,cc} C10).
//
// Tunables (reference parameter_manager.cc:44-60 bounds):
//   - tensor fusion threshold: 0 .. 64 MB
//   - background cycle time:   1 .. 100 ms
//   - response cache enabled:  binary
//   - hierarchical allreduce / allgather: binary pair, same as the
//     reference's hierarchical tunables. On TPU these select the explicit
//     (cross, local) two-level decomposition (ops/hierarchical.py) over the
//     flat multi-axis psum; the tuned values ride the broadcast and the
//     PYTHON data plane applies them at the cycle boundary.
//
// Scoring: bytes negotiated per second over a sample window
// (reference parameter_manager.cc Update/Tune). Only the coordinator tunes;
// chosen parameters ride the ResponseList broadcast each cycle so every
// process applies identical values (reference SynchronizeParameters,
// controller.cc:33-47).
//
// The optimizer is Gaussian-process regression with an RBF kernel fit by
// Cholesky factorization plus expected-improvement acquisition maximized
// over a random candidate set (the reference uses Eigen + L-BFGS on the
// acquisition; a dense random search is equally effective in 2-D and needs
// no vendored linear-algebra library).

#ifndef HVD_PARAMETER_MANAGER_H
#define HVD_PARAMETER_MANAGER_H

#include <chrono>
#include <cstdint>
#include <fstream>
#include <random>
#include <string>
#include <vector>

namespace hvd {

// Small dense GP on normalized inputs in [0,1]^d.
class GaussianProcess {
 public:
  explicit GaussianProcess(double noise = 0.8, double length_scale = 0.25)
      : noise_(noise), length_scale_(length_scale) {}

  void Fit(const std::vector<std::vector<double>>& x,
           const std::vector<double>& y);
  // posterior mean and variance at x
  void Predict(const std::vector<double>& x, double* mu, double* var) const;

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;

  double noise_;
  double length_scale_;
  std::vector<std::vector<double>> x_;
  std::vector<double> alpha_;     // K^-1 y (via Cholesky)
  std::vector<double> chol_;      // lower-triangular factor, row-major n x n
  double y_mean_ = 0.0;
  double y_std_ = 1.0;
};

class BayesianOptimization {
 public:
  BayesianOptimization(int dims, double gp_noise, unsigned seed = 0x5eed)
      : dims_(dims), gp_(gp_noise), rng_(seed) {}

  void AddSample(const std::vector<double>& x, double y);
  // next point in [0,1]^dims maximizing expected improvement
  std::vector<double> NextSample();
  size_t num_samples() const { return x_.size(); }

 private:
  double ExpectedImprovement(const std::vector<double>& x, double best) const;

  int dims_;
  GaussianProcess gp_;
  std::mt19937 rng_;
  std::vector<std::vector<double>> x_;
  std::vector<double> y_;
};

class ParameterManager {
 public:
  struct Params {
    double cycle_time_ms;
    int64_t fusion_threshold;
    bool cache_enabled;
    // hierarchical collective strategies (reference tunes the same pair,
    // parameter_manager.cc:44-60); transported by the tuned broadcast and
    // applied Python-side (ops/hierarchical.set_hierarchical*)
    bool hier_allreduce = false;
    bool hier_allgather = false;
  };

  // bounds (reference parameter_manager.cc:49-50)
  static constexpr double kMaxCycleMs = 100.0;
  static constexpr double kMinCycleMs = 1.0;
  static constexpr int64_t kMaxFusion = 64ll * 1024 * 1024;

  void Initialize(double initial_cycle_ms, int64_t initial_fusion,
                  int warmup_samples, int steps_per_sample, int max_samples,
                  double gp_noise, const std::string& log_path,
                  bool initial_hier_allreduce = false,
                  bool initial_hier_allgather = false);
  void SetAutoTuning(bool active) { active_ = active; }
  bool IsAutoTuning() const { return active_; }

  // One background cycle executed `bytes` of collective traffic. Returns
  // true when the tunables changed (caller re-broadcasts them).
  bool Update(int64_t bytes);

  double cycle_time_ms() const { return current_.cycle_time_ms; }
  int64_t fusion_threshold() const { return current_.fusion_threshold; }
  bool cache_enabled() const { return current_.cache_enabled; }
  bool hier_allreduce() const { return current_.hier_allreduce; }
  bool hier_allgather() const { return current_.hier_allgather; }
  double best_score() const { return best_score_; }
  int num_samples() const { return sample_count_; }

 private:
  Params FromUnit(const std::vector<double>& x) const;
  std::vector<double> ToUnit(const Params& p) const;
  void LogSample(const Params& p, double score);

  bool active_ = false;
  Params current_{5.0, kMaxFusion, true, false, false};
  Params best_{5.0, kMaxFusion, true, false, false};
  double best_score_ = 0.0;
  int warmup_samples_ = 3;     // reference: discarded while pipelines warm up
  int steps_per_sample_ = 10;  // cycles aggregated into one score
  int max_samples_ = 20;
  int sample_count_ = 0;

  int64_t accum_bytes_ = 0;
  int steps_in_sample_ = 0;
  std::chrono::steady_clock::time_point sample_start_{};
  bool sample_started_ = false;

  BayesianOptimization bayes_{5, 0.8};
  std::ofstream log_;
};

}  // namespace hvd

#endif  // HVD_PARAMETER_MANAGER_H
