// Native unit tests for the control-plane core, run via `make -C csrc test`
// (and from pytest, tests/test_native_unit.py). The reference has NO C++
// unit layer — its core is only exercised through Python bindings
// (SURVEY.md §4); this binary guards the pieces where a silent C++ bug
// would surface as a cross-process hang rather than a stack trace: the
// wire format, fusion bin-packing, the response cache, and the autotuner.
//
// Deliberately framework-free (assert-style): no gtest in the image.

#include <cassert>
#include <cmath>
#include <cstdio>
#include <string>
#include <vector>

#include "hvd/common.h"
#include "hvd/controller.h"
#include "hvd/parameter_manager.h"
#include "hvd/response_cache.h"
#include "hvd/tensor_queue.h"

namespace hvd {
namespace {

int g_checks = 0;

#define CHECK(cond)                                                       \
  do {                                                                    \
    ++g_checks;                                                           \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__,      \
                   #cond);                                                \
      return false;                                                      \
    }                                                                     \
  } while (0)

Request MakeRequest(const std::string& name, int type, int dtype,
                    std::vector<int64_t> dims, int reduce_op = 1) {
  Request r;
  r.request_rank = 0;
  r.request_type = type;
  r.tensor_type = dtype;
  r.reduce_op = reduce_op;
  r.tensor_name = name;
  r.tensor_shape = TensorShape(std::move(dims));
  return r;
}

Response MakeAllreduceResponse(const std::string& name, int dtype,
                               int64_t elements,
                               const std::string& axis = "",
                               int reduce_op = 1) {
  Response r;
  r.response_type = Response::ALLREDUCE;
  r.tensor_names = {name};
  r.tensor_sizes = {elements};
  r.tensor_dtypes = {dtype};
  r.tensor_output_elements = {elements};
  r.tensor_type = dtype;
  r.reduce_op = reduce_op;
  r.axis_name = axis;
  return r;
}

bool TestWireRoundTrip() {
  RequestList req_in;
  req_in.shutdown = true;
  Request q = MakeRequest("grad/w:0", Request::ADASUM,
                          static_cast<int>(DataType::HVD_BFLOAT16), {3, 4});
  q.request_rank = 2;
  q.root_rank = 1;
  q.axis_name = "data";
  q.prescale_factor = 0.5;
  q.postscale_factor = 2.0;
  req_in.requests = {q};
  std::string buf;
  SerializeRequestList(req_in, &buf);
  RequestList req_out;
  CHECK(ParseRequestList(buf.data(), buf.size(), &req_out));
  CHECK(req_out.shutdown);
  CHECK(req_out.requests.size() == 1);
  const Request& p = req_out.requests[0];
  CHECK(p.tensor_name == "grad/w:0");
  CHECK(p.request_type == Request::ADASUM);
  CHECK(p.tensor_type == static_cast<int>(DataType::HVD_BFLOAT16));
  CHECK(p.request_rank == 2 && p.root_rank == 1);
  CHECK(p.axis_name == "data");
  CHECK(p.tensor_shape == TensorShape({3, 4}));
  CHECK(p.prescale_factor == 0.5 && p.postscale_factor == 2.0);

  ResponseList rsp_in;
  rsp_in.shutdown = false;
  rsp_in.tuned_cycle_time_ms = 7.5;
  rsp_in.tuned_fusion_threshold = 1 << 20;
  rsp_in.tuned_cache_enabled = 0;
  rsp_in.tuned_hier_allreduce = 1;
  rsp_in.tuned_hier_allgather = 0;
  Response a = MakeAllreduceResponse("x", 8, 12, "data");
  a.tensor_names.push_back("y");
  a.tensor_sizes.push_back(5);
  a.tensor_dtypes.push_back(7);
  a.tensor_output_elements.push_back(5);
  Response err;
  err.response_type = Response::ERROR;
  err.tensor_names = {"bad"};
  err.error_message = "Mismatched data types for tensor bad";
  rsp_in.responses = {a, err};
  buf.clear();
  SerializeResponseList(rsp_in, &buf);
  ResponseList rsp_out;
  CHECK(ParseResponseList(buf.data(), buf.size(), &rsp_out));
  CHECK(rsp_out.tuned_cycle_time_ms == 7.5);
  CHECK(rsp_out.tuned_fusion_threshold == (1 << 20));
  CHECK(rsp_out.tuned_cache_enabled == 0);
  CHECK(rsp_out.tuned_hier_allreduce == 1);
  CHECK(rsp_out.tuned_hier_allgather == 0);
  CHECK(rsp_out.responses.size() == 2);
  const Response& o = rsp_out.responses[0];
  CHECK(o.tensor_names == std::vector<std::string>({"x", "y"}));
  CHECK(o.tensor_sizes == std::vector<int64_t>({12, 5}));
  CHECK(o.tensor_dtypes == std::vector<int32_t>({8, 7}));
  CHECK(o.tensor_output_elements == std::vector<int64_t>({12, 5}));
  CHECK(rsp_out.responses[1].error_message ==
        "Mismatched data types for tensor bad");
  // truncated buffers must fail cleanly, never read past the end
  for (size_t cut = 0; cut < buf.size(); cut += 7) {
    ResponseList junk;
    ParseResponseList(buf.data(), cut, &junk);
  }
  return true;
}

// expose the protected fusion pass
struct FuseHarness : LocalController {
  FuseHarness(TensorQueue& q, ResponseCache& c, StallInspector& s)
      : LocalController(0, 1, q, c, s) {}
  ResponseList Fuse(std::vector<Response> in) {
    ResponseList out;
    FuseResponses(in, &out);
    return out;
  }
};

bool TestFusion() {
  TensorQueue q;
  ResponseCache cache;
  StallInspector stall;
  FuseHarness h(q, cache, stall);
  h.SetFusionThresholdBytes(64 * 1024 * 1024);

  // mixed dtypes pack into ONE response (fp32 + bf16)
  auto out = h.Fuse({MakeAllreduceResponse("a", 8, 10),
                     MakeAllreduceResponse("b", 7, 20)});
  CHECK(out.responses.size() == 1);
  CHECK(out.responses[0].tensor_names.size() == 2);
  CHECK(out.responses[0].tensor_dtypes ==
        std::vector<int32_t>({8, 7}));

  // different axes never fuse
  out = h.Fuse({MakeAllreduceResponse("a", 8, 10, "data"),
                MakeAllreduceResponse("b", 8, 10, "model")});
  CHECK(out.responses.size() == 2);

  // different reduce ops never fuse
  out = h.Fuse({MakeAllreduceResponse("a", 8, 10, "", 1),
                MakeAllreduceResponse("b", 8, 10, "", 2)});
  CHECK(out.responses.size() == 2);

  // threshold look-ahead: an oversized middle tensor is skipped, the two
  // small ones still share a bin
  h.SetFusionThresholdBytes(100);  // bytes
  out = h.Fuse({MakeAllreduceResponse("s1", 8, 10),    // 40 B
                MakeAllreduceResponse("big", 8, 1000), // 4 kB
                MakeAllreduceResponse("s2", 8, 10)});  // 40 B
  CHECK(out.responses.size() == 2);
  bool found_pair = false;
  for (const auto& r : out.responses) {
    if (r.tensor_names.size() == 2) {
      found_pair = true;
      CHECK(r.tensor_names[0] == "s1" && r.tensor_names[1] == "s2");
    }
  }
  CHECK(found_pair);

  // allgather responses fuse with per-rank size blocks concatenated
  h.SetFusionThresholdBytes(64 * 1024 * 1024);
  Response g1, g2;
  g1.response_type = g2.response_type = Response::ALLGATHER;
  g1.tensor_names = {"g1"};
  g1.tensor_sizes = {2, 3};  // per-rank dim0, size 2 job
  g1.tensor_dtypes = {8};
  g1.tensor_output_elements = {15};
  g2.tensor_names = {"g2"};
  g2.tensor_sizes = {1, 1};
  g2.tensor_dtypes = {7};
  g2.tensor_output_elements = {6};
  out = h.Fuse({g1, g2});
  CHECK(out.responses.size() == 1);
  CHECK(out.responses[0].tensor_names.size() == 2);
  CHECK(out.responses[0].tensor_sizes ==
        std::vector<int64_t>({2, 3, 1, 1}));
  CHECK(out.responses[0].tensor_output_elements ==
        std::vector<int64_t>({15, 6}));

  // broadcasts never fuse
  Response b1, b2;
  b1.response_type = b2.response_type = Response::BROADCAST;
  b1.tensor_names = {"b1"};
  b1.tensor_sizes = {4};
  b1.tensor_dtypes = {8};
  b1.tensor_output_elements = {4};
  b2 = b1;
  b2.tensor_names = {"b2"};
  out = h.Fuse({b1, b2});
  CHECK(out.responses.size() == 2);
  return true;
}

bool TestResponseCache() {
  ResponseCache cache;
  cache.set_capacity(2);

  Request r1 = MakeRequest("t1", Request::ALLREDUCE, 8, {4});
  Response p1 = MakeAllreduceResponse("t1", 8, 4);
  CHECK(cache.cached(r1) == ResponseCache::MISS);
  cache.put(p1, r1);
  CHECK(cache.cached(r1) == ResponseCache::HIT);

  // same name, different shape -> INVALID (forces renegotiation)
  Request r1b = MakeRequest("t1", Request::ALLREDUCE, 8, {5});
  CHECK(cache.cached(r1b) == ResponseCache::INVALID);

  // LRU eviction at capacity 2: inserting a third evicts the oldest
  Request r2 = MakeRequest("t2", Request::ALLREDUCE, 8, {4});
  cache.put(MakeAllreduceResponse("t2", 8, 4), r2);
  Request r3 = MakeRequest("t3", Request::ALLREDUCE, 8, {4});
  cache.put(MakeAllreduceResponse("t3", 8, 4), r3);
  CHECK(cache.size() == 2);
  CHECK(cache.cached(r1) == ResponseCache::MISS);  // evicted
  CHECK(cache.cached(r2) == ResponseCache::HIT);
  CHECK(cache.cached(r3) == ResponseCache::HIT);
  // bits stay within [0, capacity) so the sync bitvector width is fixed
  CHECK(cache.peek_cache_bit(r2) < 2 && cache.peek_cache_bit(r3) < 2);
  return true;
}

bool TestTensorQueue() {
  TensorQueue q;
  TensorTableEntry e;
  e.handle = 1;
  e.meta = MakeRequest("dup", Request::ALLREDUCE, 8, {4});
  CHECK(q.AddToTensorQueue(e).ok());
  TensorTableEntry e2 = e;
  e2.handle = 2;
  CHECK(!q.AddToTensorQueue(e2).ok());  // duplicate name rejected
  std::vector<Request> ready;
  q.PopMessagesFromQueue(&ready);
  CHECK(ready.size() == 1 && ready[0].tensor_name == "dup");
  TensorTableEntry out;
  CHECK(q.PopEntry("dup", &out) && out.handle == 1);
  CHECK(!q.PopEntry("dup", &out));  // gone
  return true;
}

bool TestGaussianProcessAndAutotune() {
  // GP must interpolate a smooth 1-D function near its samples
  GaussianProcess gp(0.05, 0.25);
  std::vector<std::vector<double>> xs;
  std::vector<double> ys;
  for (double x = 0.0; x <= 1.0; x += 0.25) {
    xs.push_back({x});
    ys.push_back(std::sin(3.0 * x));
  }
  gp.Fit(xs, ys);
  double mu, var;
  gp.Predict({0.5}, &mu, &var);
  CHECK(std::fabs(mu - std::sin(1.5)) < 0.1);
  gp.Predict({0.9}, &mu, &var);
  CHECK(std::fabs(mu - std::sin(2.7)) < 0.25);

  // the manager samples, scores, and locks in a best configuration
  ParameterManager pm;
  pm.Initialize(5.0, 1 << 20, /*warmup=*/1, /*steps_per_sample=*/2,
                /*max_samples=*/4, 0.8, "");
  pm.SetAutoTuning(true);
  int updates = 0;
  for (int i = 0; i < 64 && pm.IsAutoTuning(); ++i) {
    if (pm.Update(1 << 16)) ++updates;
  }
  CHECK(!pm.IsAutoTuning());  // search finished and locked in
  CHECK(updates >= 3);
  CHECK(pm.cycle_time_ms() >= 1.0 && pm.cycle_time_ms() <= 100.0);
  CHECK(pm.fusion_threshold() >= 0 &&
        pm.fusion_threshold() <= 64ll * 1024 * 1024);
  CHECK(pm.best_score() > 0);
  return true;
}


// ---- randomized wire-format roundtrip + truncation robustness ----------
//
// The hand-written binary format has no schema compiler guarding it (the
// flatbuffers dep was deliberately dropped); a seeded fuzz roundtrip pins
// serialize(parse(x)) == x across the field space, and truncated buffers
// must FAIL parsing, never crash or succeed partially.

uint64_t g_rng_state = 0x9e3779b97f4a7c15ull;
uint64_t NextRand() {  // splitmix64: deterministic, no <random> needed
  uint64_t z = (g_rng_state += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}
int64_t RandInt(int64_t lo, int64_t hi) {  // inclusive
  return lo + static_cast<int64_t>(NextRand() % (hi - lo + 1));
}
std::string RandString(int max_len) {
  int n = static_cast<int>(RandInt(0, max_len));
  std::string s;
  for (int i = 0; i < n; ++i)
    s.push_back(static_cast<char>(RandInt(0, 255)));
  return s;
}

bool RequestEq(const Request& a, const Request& b) {
  return a.request_rank == b.request_rank &&
         a.request_type == b.request_type &&
         a.tensor_type == b.tensor_type && a.root_rank == b.root_rank &&
         a.reduce_op == b.reduce_op && a.tensor_name == b.tensor_name &&
         a.axis_name == b.axis_name && a.tensor_shape == b.tensor_shape &&
         a.prescale_factor == b.prescale_factor &&
         a.postscale_factor == b.postscale_factor;
}

bool ResponseEq(const Response& a, const Response& b) {
  return a.response_type == b.response_type &&
         a.tensor_names == b.tensor_names &&
         a.error_message == b.error_message &&
         a.tensor_sizes == b.tensor_sizes &&
         a.tensor_dtypes == b.tensor_dtypes &&
         a.tensor_output_elements == b.tensor_output_elements &&
         a.tensor_shapes == b.tensor_shapes &&
         a.tensor_type == b.tensor_type && a.root_rank == b.root_rank &&
         a.reduce_op == b.reduce_op && a.axis_name == b.axis_name &&
         a.prescale_factor == b.prescale_factor &&
         a.postscale_factor == b.postscale_factor;
}

bool TestWireFuzzRoundTrip() {
  for (int iter = 0; iter < 200; ++iter) {
    RequestList rl;
    rl.shutdown = NextRand() & 1;
    int nreq = static_cast<int>(RandInt(0, 5));
    for (int i = 0; i < nreq; ++i) {
      Request r;
      r.request_rank = static_cast<int32_t>(RandInt(0, 1 << 20));
      r.request_type = static_cast<int32_t>(RandInt(0, 7));
      r.tensor_type = static_cast<int32_t>(RandInt(0, 12));
      r.root_rank = static_cast<int32_t>(RandInt(-1, 64));
      r.reduce_op = static_cast<int32_t>(RandInt(0, 2));
      r.tensor_name = RandString(40);
      r.axis_name = RandString(12);
      std::vector<int64_t> dims;
      int nd = static_cast<int>(RandInt(0, 4));
      for (int d = 0; d < nd; ++d) dims.push_back(RandInt(0, 1 << 30));
      r.tensor_shape = TensorShape(std::move(dims));
      r.prescale_factor = static_cast<double>(RandInt(-8, 8)) / 4.0;
      r.postscale_factor = static_cast<double>(RandInt(-8, 8)) / 4.0;
      rl.requests.push_back(std::move(r));
    }
    std::string buf;
    SerializeRequestList(rl, &buf);
    RequestList out;
    CHECK(ParseRequestList(buf.data(), buf.size(), &out));
    CHECK(out.shutdown == rl.shutdown);
    CHECK(out.requests.size() == rl.requests.size());
    for (size_t i = 0; i < rl.requests.size(); ++i)
      CHECK(RequestEq(out.requests[i], rl.requests[i]));
    // every strict prefix must fail cleanly (no crash, no false success)
    if (!buf.empty()) {
      size_t cut = static_cast<size_t>(RandInt(0, buf.size() - 1));
      RequestList trunc;
      CHECK(!ParseRequestList(buf.data(), cut, &trunc));
    }

    ResponseList sl;
    sl.shutdown = NextRand() & 1;
    sl.tuned_cycle_time_ms = static_cast<double>(RandInt(0, 100));
    sl.tuned_fusion_threshold = RandInt(-1, 1 << 26);
    sl.tuned_cache_enabled = static_cast<int32_t>(RandInt(-1, 1));
    sl.tuned_hier_allreduce = static_cast<int32_t>(RandInt(-1, 1));
    sl.tuned_hier_allgather = static_cast<int32_t>(RandInt(-1, 1));
    int nrsp = static_cast<int>(RandInt(0, 4));
    for (int i = 0; i < nrsp; ++i) {
      Response r;
      r.response_type = static_cast<int32_t>(RandInt(0, 8));
      int nt = static_cast<int>(RandInt(0, 6));
      for (int j = 0; j < nt; ++j) {
        r.tensor_names.push_back(RandString(24));
        r.tensor_sizes.push_back(RandInt(0, 1ll << 40));
        r.tensor_dtypes.push_back(static_cast<int32_t>(RandInt(0, 12)));
        r.tensor_output_elements.push_back(RandInt(0, 1ll << 40));
        std::vector<int64_t> sdims;
        int snd = static_cast<int>(RandInt(0, 3));
        for (int d = 0; d < snd; ++d) sdims.push_back(RandInt(0, 1 << 20));
        r.tensor_shapes.push_back(TensorShape(std::move(sdims)));
      }
      r.error_message = RandString(60);
      r.tensor_type = static_cast<int32_t>(RandInt(0, 12));
      r.root_rank = static_cast<int32_t>(RandInt(-1, 64));
      r.reduce_op = static_cast<int32_t>(RandInt(0, 2));
      r.axis_name = RandString(12);
      r.prescale_factor = static_cast<double>(RandInt(-8, 8)) / 4.0;
      r.postscale_factor = static_cast<double>(RandInt(-8, 8)) / 4.0;
      sl.responses.push_back(std::move(r));
    }
    std::string sbuf;
    SerializeResponseList(sl, &sbuf);
    ResponseList sout;
    CHECK(ParseResponseList(sbuf.data(), sbuf.size(), &sout));
    CHECK(sout.shutdown == sl.shutdown);
    CHECK(sout.tuned_cycle_time_ms == sl.tuned_cycle_time_ms);
    CHECK(sout.tuned_fusion_threshold == sl.tuned_fusion_threshold);
    CHECK(sout.tuned_cache_enabled == sl.tuned_cache_enabled);
    CHECK(sout.tuned_hier_allreduce == sl.tuned_hier_allreduce);
    CHECK(sout.tuned_hier_allgather == sl.tuned_hier_allgather);
    CHECK(sout.responses.size() == sl.responses.size());
    for (size_t i = 0; i < sl.responses.size(); ++i)
      CHECK(ResponseEq(sout.responses[i], sl.responses[i]));
    if (!sbuf.empty()) {
      size_t cut = static_cast<size_t>(RandInt(0, sbuf.size() - 1));
      ResponseList strunc;
      bool ok = ParseResponseList(sbuf.data(), cut, &strunc);
      if (cut < sbuf.size() - 8) {
        // cut into the mandatory body: must fail cleanly
        CHECK(!ok);
      } else {
        // cut inside the OPTIONAL hierarchical-toggle tail: the body is
        // complete, so parse succeeds with the toggles defaulted (the
        // backward-compat contract with pre-round-5 payload producers)
        CHECK(ok);
        CHECK(strunc.tuned_hier_allreduce == -1);
        CHECK(strunc.tuned_hier_allgather == -1);
      }
    }

    // corruption: flip one random byte — parse may fail or still succeed
    // (string bytes are opaque) but must return, not crash or over-allocate
    if (!buf.empty()) {
      std::string corrupt = buf;
      corrupt[NextRand() % corrupt.size()] ^=
          static_cast<char>(1 + (NextRand() % 255));
      RequestList junk;
      (void)ParseRequestList(corrupt.data(), corrupt.size(), &junk);
    }
    if (!sbuf.empty()) {
      std::string corrupt = sbuf;
      corrupt[NextRand() % corrupt.size()] ^=
          static_cast<char>(1 + (NextRand() % 255));
      ResponseList junk;
      (void)ParseResponseList(corrupt.data(), corrupt.size(), &junk);
    }
  }

  // a maliciously huge count must fail fast, not resize(4 billion): header
  // (shutdown byte) + count 0xFFFFFFFF and nothing behind it
  {
    std::string evil;
    evil.push_back(0);
    uint32_t huge = 0xFFFFFFFFu;
    evil.append(reinterpret_cast<const char*>(&huge), 4);
    RequestList junk;
    CHECK(!ParseRequestList(evil.data(), evil.size(), &junk));
  }
  return true;
}

}  // namespace
}  // namespace hvd

int main() {
  using namespace hvd;
  struct {
    const char* name;
    bool (*fn)();
  } tests[] = {
      {"wire_round_trip", TestWireRoundTrip},
      {"wire_fuzz_round_trip", TestWireFuzzRoundTrip},
      {"fusion", TestFusion},
      {"response_cache", TestResponseCache},
      {"tensor_queue", TestTensorQueue},
      {"gp_autotune", TestGaussianProcessAndAutotune},
  };
  int failed = 0;
  for (const auto& t : tests) {
    if (t.fn()) {
      std::printf("PASS %s\n", t.name);
    } else {
      std::printf("FAIL %s\n", t.name);
      ++failed;
    }
  }
  std::printf("%d checks, %d test(s) failed\n", g_checks, failed);
  return failed == 0 ? 0 : 1;
}
