"""Two-level (cross × local) collective tests on the 8-CPU virtual mesh.

Covers VERDICT r3 item 4: the claim "XLA subsumes NCCL-hierarchical"
(reference ``common/ops/nccl_operations.cc:162-354``) is demonstrated by
building a 2×4 ``(cross, local)`` mesh, running per-axis and two-level
collectives, and asserting equivalence with the flat path.
"""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import collective, hierarchical
from horovod_tpu.ops.hierarchical import (
    hier_allreduce, hier_allgather, hierarchical_allreduce,
    set_hierarchical, set_hierarchical_allgather,
)
from horovod_tpu.parallel.mesh import build_host_mesh, CROSS_AXIS, LOCAL_AXIS


@pytest.fixture()
def hvd24():
    """hvd initialised over a 2×4 (cross, local) host-hierarchy mesh."""
    mesh = build_host_mesh(local=4)
    assert mesh.shape == {"cross": 2, "local": 4}
    hvd.init(mesh=mesh)
    yield hvd
    hvd.shutdown()
    set_hierarchical(None)
    set_hierarchical_allgather(None)


def _stacked24(mesh, x):
    """Place [8, ...] x with dim0 sharded over (cross, local)."""
    return jax.device_put(
        jnp.asarray(x), NamedSharding(mesh, P((CROSS_AXIS, LOCAL_AXIS)))
    )


def test_host_mesh_shape_and_order():
    mesh = build_host_mesh(local=4)
    # cross outermost: each "host" owns a contiguous block of 4 devices
    assert mesh.axis_names == ("cross", "local")
    assert mesh.devices.shape == (2, 4)
    flat = [d.id for d in mesh.devices.flat]
    assert flat == sorted(flat)


@pytest.mark.parametrize("shape", [(8, 5), (8, 7, 3), (8, 1)])
def test_hier_allreduce_matches_flat(hvd24, shape):
    """Decomposed local-RS → cross-AR → local-AG == flat psum over both axes,
    including shapes whose element count is not divisible by local size."""
    mesh = hvd.mesh()
    rng = np.random.RandomState(0)
    x = rng.randn(*shape).astype(np.float32)
    xs = _stacked24(mesh, x)

    def flat_fn(v):
        v = jnp.squeeze(v, axis=0)
        return lax.psum(v, (CROSS_AXIS, LOCAL_AXIS))

    def hier_fn(v):
        v = jnp.squeeze(v, axis=0)
        return hier_allreduce(v)

    smap = collective._smap
    spec = P((CROSS_AXIS, LOCAL_AXIS))
    flat = jax.jit(smap(flat_fn, mesh, (spec,), P()))(xs)
    hier = jax.jit(smap(hier_fn, mesh, (spec,), P()))(xs)
    # reduction-order differs between the decompositions -> fp32 ulp noise
    np.testing.assert_allclose(np.asarray(hier), np.asarray(flat),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(flat), x.sum(axis=0), rtol=1e-5)


def test_per_axis_collectives_oracle(hvd24):
    """psum over `local` reduces within each host block; over `cross` reduces
    the same slot across hosts — the LOCAL/CROSS communicator semantics
    (reference ``common/common.h:111-115``)."""
    mesh = hvd.mesh()
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    xs = _stacked24(mesh, x)
    spec = P((CROSS_AXIS, LOCAL_AXIS))
    smap = collective._smap

    def local_sum(v):
        return lax.psum(jnp.squeeze(v, 0), LOCAL_AXIS)[None]

    def cross_sum(v):
        return lax.psum(jnp.squeeze(v, 0), CROSS_AXIS)[None]

    out_l = np.asarray(jax.jit(smap(local_sum, mesh, (spec,), spec))(xs))
    out_c = np.asarray(jax.jit(smap(cross_sum, mesh, (spec,), spec))(xs))

    blocks = x.reshape(2, 4, 3)
    want_l = np.repeat(blocks.sum(axis=1, keepdims=True), 4, axis=1).reshape(8, 3)
    want_c = np.tile(blocks.sum(axis=0, keepdims=True), (2, 1, 1)).reshape(8, 3)
    np.testing.assert_allclose(out_l, want_l, rtol=1e-6)
    np.testing.assert_allclose(out_c, want_c, rtol=1e-6)


def test_hier_allgather_order_matches_flat(hvd24):
    """Two-level gather (local then cross) preserves flat rank order because
    global rank = cross·L + local on the row-major mesh."""
    mesh = hvd.mesh()
    x = np.arange(8 * 2, dtype=np.float32).reshape(8, 2)
    xs = _stacked24(mesh, x)
    spec = P((CROSS_AXIS, LOCAL_AXIS))
    smap = collective._smap

    def flat_fn(v):
        # v: [1, 2] — this rank's row; gather rows in global rank order
        return lax.all_gather(v, (CROSS_AXIS, LOCAL_AXIS), axis=0, tiled=True)

    def hier_fn(v):
        return hier_allgather(v)

    flat = np.asarray(jax.jit(smap(flat_fn, mesh, (spec,), P()))(xs))
    hier = np.asarray(jax.jit(smap(hier_fn, mesh, (spec,), P()))(xs))
    np.testing.assert_array_equal(hier, flat)
    np.testing.assert_array_equal(hier, x)


def test_eager_hierarchical_allreduce(hvd24):
    mesh = hvd.mesh()
    rng = np.random.RandomState(1)
    x = rng.randn(8, 6).astype(np.float32)
    xs = _stacked24(mesh, x)
    out = hierarchical_allreduce(xs, hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), x.sum(axis=0), rtol=1e-5)
    avg = hierarchical_allreduce(xs)
    np.testing.assert_allclose(np.asarray(avg), x.mean(axis=0), rtol=1e-5)


def test_eager_requires_host_axes(hvd):
    with pytest.raises(ValueError, match="has no 'cross' axis"):
        hierarchical_allreduce(np.ones((4,), np.float32))


def test_allreduce_tuple_axis_strategy_toggle(hvd24, monkeypatch):
    """hvd.allreduce(axis=("cross","local")) gives identical numerics flat vs
    hierarchical, and the toggle actually routes through the decomposed path."""
    mesh = hvd.mesh()
    rng = np.random.RandomState(2)
    x = rng.randn(8, 4).astype(np.float32)
    xs = _stacked24(mesh, x)
    spec = P((CROSS_AXIS, LOCAL_AXIS))
    smap = collective._smap

    def step(v):
        return hvd.allreduce(jnp.squeeze(v, 0), hvd.Sum,
                             axis=(CROSS_AXIS, LOCAL_AXIS))

    set_hierarchical(False)
    flat = np.asarray(jax.jit(smap(step, mesh, (spec,), P()))(xs))

    calls = []
    real = hierarchical.hier_allreduce
    monkeypatch.setattr(hierarchical, "hier_allreduce",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    set_hierarchical(True)
    hier = np.asarray(jax.jit(smap(step, mesh, (spec,), P()))(xs))
    assert calls, "hierarchical path was not taken with the toggle on"
    np.testing.assert_allclose(hier, flat, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(flat, x.sum(axis=0), rtol=1e-5)


def test_allgather_tuple_axis_strategy_toggle(hvd24, monkeypatch):
    """hvd.allgather(axis=("cross","local")) routes through the two-level
    gather when HOROVOD_HIERARCHICAL_ALLGATHER is on, identical result."""
    mesh = hvd.mesh()
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    xs = _stacked24(mesh, x)
    spec = P((CROSS_AXIS, LOCAL_AXIS))
    smap = collective._smap

    def step(v):
        return hvd.allgather(v, axis=(CROSS_AXIS, LOCAL_AXIS))

    set_hierarchical_allgather(False)
    flat = np.asarray(jax.jit(smap(step, mesh, (spec,), P()))(xs))

    calls = []
    real = hierarchical.hier_allgather
    monkeypatch.setattr(hierarchical, "hier_allgather",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    set_hierarchical_allgather(True)
    hier = np.asarray(jax.jit(smap(step, mesh, (spec,), P()))(xs))
    assert calls, "hierarchical allgather path was not taken"
    np.testing.assert_array_equal(hier, flat)
    np.testing.assert_array_equal(hier, x)


def test_eager_allgather_toggle(hvd24, monkeypatch):
    """Eager (non-tracer) tuple-axis allgather honors the toggle too."""
    mesh = hvd.mesh()
    x = np.arange(8 * 2, dtype=np.float32).reshape(8, 2)
    xs = _stacked24(mesh, x)
    set_hierarchical_allgather(True)
    calls = []
    real = hierarchical.hier_allgather
    monkeypatch.setattr(hierarchical, "hier_allgather",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    out = np.asarray(hvd.allgather(xs, axis=(CROSS_AXIS, LOCAL_AXIS)))
    assert calls, "eager hierarchical allgather path was not taken"
    # each rank's contribution is its squeezed [2] row; dim-0 concat in
    # global rank order (same semantics as the flat eager path)
    np.testing.assert_array_equal(out, x.reshape(-1))


def test_host_mesh_default_axis_is_global(hvd24):
    """On a (cross, local) mesh the DEFAULT collective axis must be the
    full pair — defaulting to one axis would silently reduce over hosts
    (or chips) only, a partial sum masquerading as the Horovod GLOBAL
    exchange."""
    assert hvd.size() == 8  # product, not one axis
    mesh = hvd.mesh()
    rng = np.random.RandomState(5)
    x = rng.randn(8, 4).astype(np.float32)
    xs = _stacked24(mesh, x)

    # eager default-axis allreduce covers every rank
    out = np.asarray(hvd.allreduce(xs, hvd.Sum))
    np.testing.assert_allclose(out, x.sum(axis=0), rtol=1e-5)

    # eager default-axis allgather covers every rank, rank-ordered
    g = np.asarray(hvd.allgather(xs))
    np.testing.assert_allclose(g, x.reshape(-1), rtol=1e-6)

    # eager broadcast from a root in the SECOND host's block
    b = _stacked24(mesh, np.arange(8, dtype=np.float32)[:, None])
    got = np.asarray(hvd.broadcast(b, root_rank=5))
    np.testing.assert_allclose(got, [5.0])

    # in-jit default axis: psum over both axes
    spec = P((CROSS_AXIS, LOCAL_AXIS))
    fn = jax.jit(collective._smap(
        lambda v: hvd.allreduce(jnp.squeeze(v, 0), hvd.Sum),
        mesh, (spec,), P()))
    np.testing.assert_allclose(np.asarray(fn(xs)), x.sum(axis=0), rtol=1e-5)

    # Adasum cannot run on a tuple axis: clear error, not silent wrongness
    with pytest.raises(ValueError, match="tuple"):
        hvd.allreduce(xs, hvd.Adasum)


def test_host_mesh_loader_and_sharding_helpers(hvd24):
    """ShardedLoader and the ZeRO/FSDP dim-0 sharding helpers must accept
    the tuple default axis (they index the mesh by axis name internally)."""
    import optax

    from horovod_tpu.data import ShardedLoader
    from horovod_tpu.training import fsdp_shard_params, zero_shard_opt_state

    xs = np.arange(32 * 3, dtype=np.float32).reshape(32, 3)
    loader = ShardedLoader(xs, batch_size=16, shuffle=False)
    batches = [np.asarray(b) for b in loader]
    assert len(batches) == 2 and batches[0].shape == (16, 3)
    np.testing.assert_array_equal(batches[0], xs[:16])

    params = {"w": jnp.ones((16, 4)), "b": jnp.ones((3,))}
    sharded = fsdp_shard_params(params)
    spec_w = sharded["w"].sharding.spec
    assert spec_w[0] == (CROSS_AXIS, LOCAL_AXIS), spec_w
    opt = zero_shard_opt_state(optax.adam(1e-3).init(params))
    assert opt is not None


def test_env_toggle(monkeypatch):
    set_hierarchical(None)
    monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLREDUCE", raising=False)
    assert not hierarchical.enabled()
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
    assert hierarchical.enabled()
    set_hierarchical(False)
    assert not hierarchical.enabled()  # explicit set wins over env
    set_hierarchical(None)
