"""DistributedOptimizer / tape tests (reference optimizer test patterns in
test/test_tensorflow.py:381-455 gradient checks)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P


def test_distributed_optimizer_averages_grads(hvd):
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    params = {"w": jnp.ones(4)}
    state = opt.init(params)
    # eager: stacked per-rank grads
    n = hvd.size()
    g = np.stack([np.full(4, float(r)) for r in range(n)]).astype(np.float32)
    grads = {"w": jax.device_put(g, NamedSharding(hvd.mesh(), P(hvd.data_axis())))}
    updates, state = opt.update(grads, state, params)
    expect = -g.mean(axis=0)
    np.testing.assert_allclose(np.asarray(updates["w"]), expect, rtol=1e-6)


def test_tape_value_and_grad(hvd):
    def loss(p, x):
        return jnp.sum(p * x)

    tape = hvd.DistributedGradientTape(jax.value_and_grad(loss))
    v, g = tape(jnp.ones(3), jnp.arange(3.0))
    assert float(v) == 3.0
    np.testing.assert_allclose(np.asarray(g), np.arange(3.0))


def test_tape_multi_argnums_not_misclassified(hvd):
    # jax.grad with argnums=(0,1) returns a 2-tuple of grads; both must be
    # reduced, neither treated as the loss value
    def loss(a, b):
        return jnp.sum(a) + 2 * jnp.sum(b)

    tape = hvd.DistributedGradientTape(jax.grad(loss, argnums=(0, 1)))
    ga, gb = tape(jnp.ones(2), jnp.ones(2))
    np.testing.assert_allclose(np.asarray(ga), np.ones(2))
    np.testing.assert_allclose(np.asarray(gb), 2 * np.ones(2))


def test_backward_passes_per_step(hvd):
    opt = hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    params = {"w": jnp.zeros(2)}
    state = opt.init(params)
    g = {"w": jnp.ones(2)}
    u1, state = opt.update(g, state, params)
    np.testing.assert_allclose(np.asarray(u1["w"]), 0.0)  # accumulating
    u2, state = opt.update(g, state, params)
    # second call applies the averaged accumulated gradient
    np.testing.assert_allclose(np.asarray(u2["w"]), -1.0)


def test_broadcast_parameters_tree(hvd):
    params = {"a": jnp.ones(3), "b": {"c": jnp.zeros(2)}}
    out = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["a"]), 1.0)
    np.testing.assert_allclose(np.asarray(out["b"]["c"]), 0.0)


def test_fp16_compression_roundtrip(hvd):
    from horovod_tpu.compression import Compression

    n = hvd.size()
    x = np.tile(np.linspace(-1, 1, 8, dtype=np.float32), (n, 1))
    xs = jax.device_put(x, NamedSharding(hvd.mesh(), P(hvd.data_axis())))
    out = hvd.allreduce(xs, op=hvd.Average, compression=Compression.fp16)
    assert np.asarray(out).dtype == np.float32
    np.testing.assert_allclose(np.asarray(out), x[0], atol=1e-2)


def test_distributed_optimizer_adasum_fused(hvd):
    """op=Adasum on the optax frontend rides the fused group butterfly; with
    replicated gradients adasum is the identity, so the wrapped optimizer
    must track the plain one exactly (the same invariant the torch/TF
    Adasum optimizer tests assert)."""
    import optax

    from horovod_tpu.ops import adasum as adasum_mod

    tx_plain = optax.sgd(0.1)
    tx_ada = hvd.DistributedOptimizer(optax.sgd(0.1), op=hvd.Adasum)

    params = {"w": jnp.ones((4, 3)), "b": jnp.zeros((3,))}
    grads = {
        "w": jnp.full((4, 3), 0.5),
        "b": jnp.full((3,), -0.25),
    }
    s_plain = tx_plain.init(params)
    s_ada = tx_ada.init(params)

    calls = []
    orig = adasum_mod.grouped_adasum_allreduce

    def spy(tensors, **kw):
        calls.append(len(list(tensors)))
        return orig(tensors, **kw)

    adasum_mod.grouped_adasum_allreduce = spy
    try:
        u_ada, _ = tx_ada.update(grads, s_ada, params)
    finally:
        adasum_mod.grouped_adasum_allreduce = orig
    u_plain, _ = tx_plain.update(grads, s_plain, params)

    assert calls == [2], "gradient tree not routed through ONE fused group"
    for k in params:
        np.testing.assert_allclose(
            np.asarray(u_ada[k]), np.asarray(u_plain[k]), rtol=1e-5
        )


def test_tape_adasum_fused(hvd):
    """DistributedGradientTape(op=Adasum) also rides the fused group
    butterfly (one call for the whole gradient tree)."""
    from horovod_tpu.ops import adasum as adasum_mod

    calls = []
    orig = adasum_mod.grouped_adasum_allreduce

    def spy(tensors, **kw):
        calls.append(len(list(tensors)))
        return orig(tensors, **kw)

    def loss(p):
        return (p["a"] ** 2).sum() + (p["b"] ** 2).sum()

    tape = hvd.DistributedGradientTape(
        jax.value_and_grad(loss), op=hvd.Adasum
    )
    adasum_mod.grouped_adasum_allreduce = spy
    try:
        value, grads = tape({"a": jnp.ones((3,)), "b": jnp.ones((2, 2))})
    finally:
        adasum_mod.grouped_adasum_allreduce = orig
    assert calls == [2]
    # replicated grads: adasum is the identity
    np.testing.assert_allclose(np.asarray(grads["a"]), 2.0)


def test_error_feedback_requires_lossy_compression(hvd):
    from horovod_tpu.compression import Compression

    with pytest.raises(ValueError, match="lossy"):
        hvd.DistributedOptimizer(optax.sgd(0.1), error_feedback=True)
    with pytest.raises(ValueError, match="Adasum"):
        hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Adasum,
            compression=Compression.fp16, error_feedback=True)


def test_error_feedback_residual_exact(hvd):
    """After one update the residual must equal exactly g - bf16(g)."""
    import jax.numpy as jnp
    from horovod_tpu.compression import Compression

    g = np.float32(1.0) + np.float32(2e-4)  # rounds to 1.0 in bf16
    grads = {"w": jnp.full((3,), g)}
    params = {"w": jnp.zeros(3)}
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.1), compression=Compression.fp16, error_feedback=True)
    state = tx.init(params)
    _, state = tx.update(grads, state, params)
    expect = np.full((3,), g, np.float32) - np.asarray(
        jnp.full((3,), g).astype(jnp.bfloat16).astype(jnp.float32))
    np.testing.assert_array_equal(np.asarray(state.residual["w"]), expect)
    assert expect[0] != 0.0  # the test only means something if bf16 rounded


def test_error_feedback_recovers_lost_mass(hvd):
    """A gradient component below the bf16 ULP vanishes every step without
    EF; with EF the residual accumulates until it transmits. Over N steps the
    applied update mass must approach the true N*g."""
    import jax.numpy as jnp
    from horovod_tpu.compression import Compression

    eps = np.float32(2e-3)  # ~1/4 ULP at 1.0 in bf16
    g = {"w": jnp.full((4,), 1.0 + eps)}
    params = {"w": jnp.zeros(4)}
    N = 40

    def total_applied(error_feedback):
        tx = hvd.DistributedOptimizer(
            optax.sgd(1.0), compression=Compression.fp16,
            error_feedback=error_feedback)
        p, s = dict(params), tx.init(params)
        for _ in range(N):
            u, s = tx.update(g, s, p)
            p = optax.apply_updates(p, u)
        return -float(np.asarray(p["w"])[0])  # sgd(1.0): p = -sum(updates)

    true_mass = N * (1.0 + float(eps))
    without = total_applied(False)
    with_ef = total_applied(True)
    assert abs(without - N * 1.0) < 1e-3      # eps lost every step
    assert abs(with_ef - true_mass) < 0.02    # EF recovered it


def test_error_feedback_tracks_predivide_rounding(hvd):
    """With gradient_predivide_factor, the wire carries bf16(g/f); the
    residual must be measured against that (f=3 makes /3 itself lossy)."""
    import jax.numpy as jnp
    from horovod_tpu.compression import Compression

    f = 3.0
    g = {"w": jnp.full((3,), 0.7)}
    params = {"w": jnp.zeros(3)}
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.1), compression=Compression.fp16,
        gradient_predivide_factor=f, error_feedback=True)
    state = tx.init(params)
    _, state = tx.update(g, state, params)
    wire = np.asarray(
        (jnp.full((3,), 0.7) / f).astype(jnp.bfloat16).astype(jnp.float32)) * f
    np.testing.assert_allclose(
        np.asarray(state.residual["w"]), np.full((3,), 0.7) - wire, atol=1e-7)
