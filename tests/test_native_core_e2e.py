"""End-to-end native-core tests: 2 launched processes drive named async
collectives through the C++ control plane (TCP negotiation, fusion, response
cache, timeline) with a REAL cross-process XLA data plane — the
``horovodrun -np 2`` + named-op pattern of the reference test suite
(SURVEY.md §4), plus join() zero-backfill semantics (reference
``tensor_queue.cc`` ``GetTensorEntriesFromResponse``,
``controller.cc:219-307``, ``torch/mpi_ops.py:511-524``)."""

import os

import numpy as np

from horovod_tpu.run import runner

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


def _worker_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [_REPO_ROOT, _TESTS_DIR, env.get("PYTHONPATH", "")]
    )
    return env


class _plan_spy:
    """Record every execution plan the core hands back while active:
    appends ``fn(resp)`` for each response. One restore discipline for all
    plan-observing workers in this file."""

    def __init__(self, fn):
        self.fn = fn
        self.plans = []

    def __enter__(self):
        from horovod_tpu import core as core_mod

        self._mod = core_mod
        self._orig = core_mod.NativeCore._execute_one
        record, fn = self.plans, self.fn

        def spy(inner_self, resp, handles):
            record.append(fn(resp))
            return self._orig(inner_self, resp, handles)

        core_mod.NativeCore._execute_one = spy
        return self.plans

    def __exit__(self, *exc):
        self._mod.NativeCore._execute_one = self._orig
        return False


def _setup_worker():
    """Common per-worker setup: CPU platform, fast cycles, timeline on."""
    import os
    import tempfile

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["HOROVOD_CYCLE_TIME"] = "2"
    timeline = os.path.join(
        tempfile.gettempdir(),
        f"hvd_core_e2e_timeline_{os.environ['HOROVOD_RANK']}.json",
    )
    os.environ["HOROVOD_TIMELINE"] = timeline
    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    assert hvd.basics._state.core is not None, "native core not attached"
    return hvd, timeline


def _native_core_steps():
    import numpy as np

    hvd, timeline = _setup_worker()
    r = hvd.process_rank()
    out = {"rank": r}

    # 1. several named tensors in flight at once: the controller bin-packs
    # them into one fused response -> one grouped XLA launch
    hs = [
        hvd.allreduce_async(
            np.full((4,), float(r + 1) * (i + 1), np.float32),
            hvd.Sum,
            name=f"g{i}",
        )
        for i in range(4)
    ]
    out["fused"] = [np.asarray(h.wait(timeout=90)).tolist() for h in hs]

    # 2. steady state: the same name over steps rides the response cache
    # (bitvector sync) with real cross-process values
    for step in range(5):
        h = hvd.allreduce_async(
            np.full((2,), float(r), np.float32), hvd.Average, name="grad"
        )
        res = h.wait(timeout=90)
    out["cached"] = np.asarray(res).tolist()
    out["timeline_exists"] = os.path.exists(timeline)
    return out


def test_native_core_cross_process_data_plane():
    out = runner.run(
        _native_core_steps,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=300,
    )
    for res in out:
        # sum over both processes: (1 + 2) * (i + 1)
        assert res["fused"] == [[3.0 * (i + 1)] * 4 for i in range(4)]
        # average of (0, 1) across processes
        assert res["cached"] == [0.5, 0.5]
    # timeline written on the coordinator rank only (reference
    # operations.cc:404-411)
    assert out[0]["timeline_exists"]
    assert not out[1]["timeline_exists"]


def _native_core_mixed_dtype():
    import numpy as np

    hvd, timeline = _setup_worker()
    import jax.numpy as jnp

    # long cycles so one round sees both enqueues (the env knob is fixed at
    # init by _setup_worker; the live property is the launcher/autotune path)
    hvd.basics._state.core.cycle_time_ms = 150

    out = {"rank": hvd.process_rank(), "fp32": None, "bf16": None}
    r = out["rank"]
    with _plan_spy(
        lambda resp: (list(resp.tensor_names), list(resp.tensor_dtypes))
    ) as plans:
        # retry with fresh names if a cycle boundary split an attempt's two
        # enqueues into different negotiation rounds (timing, not logic)
        for attempt in range(4):
            hf = hvd.allreduce_async(
                np.full((4,), float(r + 1), np.float32),
                hvd.Sum,
                name=f"a{attempt}_fp32",
            )
            hb = hvd.allreduce_async(
                jnp.full((4,), float(r + 1), jnp.bfloat16),
                hvd.Sum,
                name=f"a{attempt}_bf16",
            )
            out["fp32"] = np.asarray(hf.wait(timeout=90)).tolist()
            out["bf16"] = np.asarray(
                hb.wait(timeout=90), np.float32
            ).tolist()
            if any(len(names) > 1 for names, _ in plans):
                break
    out["plans"] = plans
    hvd.shutdown()
    if r == 0:
        with open(timeline) as f:
            out["timeline"] = f.read()
    return out


def test_native_core_mixed_dtype_fusion():
    """fp32 + bf16 gradients fuse into ONE response (per-tensor dtypes ride
    the wire; the XLA grouped launch keeps each array's dtype) — the
    reference's single-dtype fusion buffer can only look *past* dtype breaks
    (reference controller.cc:640-761)."""
    out = runner.run(
        _native_core_mixed_dtype,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=300,
    )
    for res in out:
        assert res["fp32"] == [3.0] * 4
        assert res["bf16"] == [3.0] * 4
        fused = [
            (names, dtypes)
            for names, dtypes in res["plans"]
            if len(names) == 2
            and {n.split("_", 1)[1] for n in names} == {"fp32", "bf16"}
        ]
        assert fused, f"no mixed-dtype fused plan on rank {res['rank']}: " \
                      f"{res['plans']}"
        names, dtypes = fused[0]
        # dtype tags parallel to names: 8 = fp32, 7 = bf16
        assert sorted(dtypes) == [7, 8]
    r0 = out[0] if out[0]["rank"] == 0 else out[1]
    assert "FUSED_ALLREDUCE x2 (2 dtypes)" in r0["timeline"]


def _native_core_torch_optimizer():
    """Torch frontend through the C++ control plane: the hook-based
    DistributedOptimizer's named per-parameter async allreduces negotiate
    via TCP, fuse into grouped responses, and cross processes — the
    reference's torch + background-cycle integration path."""
    import numpy as np
    import torch

    hvd, _ = _setup_worker()
    import horovod_tpu.torch as thvd
    hvd.basics._state.core.cycle_time_ms = 100

    with _plan_spy(lambda resp: len(resp.tensor_names)) as plans:
        r = hvd.process_rank()
        torch.manual_seed(0)  # identical init on both ranks
        model = torch.nn.Sequential(
            torch.nn.Linear(6, 8), torch.nn.Tanh(), torch.nn.Linear(8, 2)
        )
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
        )
        rng = np.random.RandomState(42)
        X = torch.from_numpy(rng.randn(16, 6).astype(np.float32))
        Y = torch.from_numpy(rng.randn(16, 2).astype(np.float32))
        Xl, Yl = X[r::2], Y[r::2]  # per-rank data halves
        for _ in range(3):
            opt.zero_grad()
            loss = ((model(Xl) - Yl) ** 2).mean()
            loss.backward()
            opt.step()
        wsum = float(
            sum(p.detach().abs().sum() for p in model.parameters())
        )
    return {
        "rank": r,
        "wsum": wsum,
        "max_fused": max(plans) if plans else 0,
    }


def test_native_core_torch_optimizer_cross_process():
    out = runner.run(
        _native_core_torch_optimizer,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=300,
    )
    # identical params on both ranks despite disjoint data halves: the
    # gradient exchange crossed processes through the C++ core
    assert abs(out[0]["wsum"] - out[1]["wsum"]) < 1e-5, out
    # the 4 per-parameter named grads fused into grouped responses
    assert max(o["max_fused"] for o in out) >= 2, out


def _native_core_join():
    import numpy as np

    hvd, _ = _setup_worker()
    r = hvd.process_rank()
    out = {"rank": r}

    # cold-negotiation path: unique name per step. rank 1 exhausts its data
    # after 1 step and joins; rank 0 keeps reducing for 2 more steps, which
    # must complete with rank 1 backfilled as zeros.
    steps = 3 if r == 0 else 1
    sums = []
    for i in range(steps):
        h = hvd.allreduce_async(
            np.full((3,), float(r + 1), np.float32), hvd.Sum, name=f"step{i}"
        )
        sums.append(np.asarray(h.wait(timeout=90)).tolist())
    out["sums"] = sums
    out["last_joined"] = hvd.join()
    return out


def test_native_core_join_zero_backfill():
    out = runner.run(
        _native_core_join,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=300,
    )
    r0, r1 = (out[0], out[1]) if out[0]["rank"] == 0 else (out[1], out[0])
    # step 0: both alive -> 1 + 2 = 3; steps 1-2: rank 1 joined -> zeros
    assert r0["sums"] == [[3.0] * 3, [1.0] * 3, [1.0] * 3]
    assert r1["sums"] == [[3.0] * 3]
    # rank 0 joins last (it still had data when rank 1 joined)
    assert r0["last_joined"] == 0
    assert r1["last_joined"] == 0


def _native_core_join_cached():
    import numpy as np

    hvd, _ = _setup_worker()
    r = hvd.process_rank()

    # steady-state join: the SAME name over steps, so the collective runs
    # from the response cache when rank 1 joins — exercising the joined
    # rank's all-ones bitvector agreement + cached zero-backfill
    steps = 5 if r == 0 else 2
    sums = []
    for i in range(steps):
        h = hvd.allreduce_async(
            np.full((2,), float(r + 1), np.float32), hvd.Sum, name="grad"
        )
        sums.append(np.asarray(h.wait(timeout=90)).tolist())
    last = hvd.join()
    return {"rank": r, "sums": sums, "last_joined": last}


def test_native_core_join_cached_path():
    out = runner.run(
        _native_core_join_cached,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=300,
    )
    r0, r1 = (out[0], out[1]) if out[0]["rank"] == 0 else (out[1], out[0])
    assert r0["sums"] == [[3.0] * 2] * 2 + [[1.0] * 2] * 3
    assert r1["sums"] == [[3.0] * 2] * 2
    assert r0["last_joined"] == 0
    assert r1["last_joined"] == 0


def _native_core_join_nonbackfillable_errors():
    """join() + any non-zero-backfillable op (allgather/alltoall/
    reducescatter) must produce a coordinator ERROR on the live rank — not
    a hang (controller.cc EmitReady rejects every backfilled type except
    ALLREDUCE/ADASUM)."""
    import numpy as np

    hvd, _ = _setup_worker()
    r = hvd.process_rank()
    out = {"rank": r, "errors": []}
    if r == 0:
        for fn, name in (
            (lambda: hvd.alltoall_async(
                np.ones((2, 1), np.float32), name="j.a2a"), "ALLTOALL"),
            (lambda: hvd.reducescatter_async(
                np.ones((2, 1), np.float32), hvd.Sum, name="j.rs"),
             "REDUCESCATTER"),
        ):
            try:
                fn().wait(timeout=90)
                out["errors"].append(None)
            except RuntimeError as e:
                out["errors"].append((name, str(e)))
    out["last_joined"] = hvd.join()
    return out


def test_native_core_join_nonbackfillable_errors():
    out = runner.run(
        _native_core_join_nonbackfillable_errors,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=300,
    )
    r0 = out[0] if out[0]["rank"] == 0 else out[1]
    assert len(r0["errors"]) == 2
    for name, msg in r0["errors"]:
        assert "not supported with join" in msg, (name, msg)


def _native_core_join_allgather_error():
    import numpy as np

    hvd, _ = _setup_worker()
    r = hvd.process_rank()
    out = {"rank": r, "error": None}
    if r == 0:
        # rank 1 joins immediately; allgather cannot be zero-backfilled
        # (reference controller.cc:454-457) -> coordinator ERROR response
        h = hvd.allgather_async(
            np.full((2, 2), 7.0, np.float32), name="ag"
        )
        try:
            h.wait(timeout=90)
        except RuntimeError as e:
            out["error"] = str(e)
    out["last_joined"] = hvd.join()
    return out


def test_native_core_join_allgather_error():
    out = runner.run(
        _native_core_join_allgather_error,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=300,
    )
    r0 = out[0] if out[0]["rank"] == 0 else out[1]
    assert r0["error"] is not None
    assert "not supported with join" in r0["error"]


def _native_core_reorder_soak():
    """Negotiation soak: both ranks enqueue the SAME 40 named tensors in
    DIFFERENT random orders, twice (second round exercises the response
    cache). Reordering across ranks is the controller's whole job
    (reference controller.h:58-98 coordinator protocol); every op must
    complete with the correct cross-rank sum regardless of order."""
    import numpy as np

    hvd, _ = _setup_worker()
    r = hvd.process_rank()
    n_tensors, rounds = 40, 2
    out = {"rank": r, "bad": []}
    for rnd in range(rounds):
        order = np.random.RandomState(100 * rnd + r).permutation(n_tensors)
        handles = {}
        for i in order:
            # varied shapes/dtypes; rank-dependent values
            shape = [(3,), (2, 2), (5,), (1,)][i % 4]
            dtype = [np.float32, np.float32, np.int32, np.float32][i % 4]
            val = np.full(shape, (r + 1) * (i + 1) * (rnd + 1), dtype)
            # same names in round 2 -> the cached-response fast path
            handles[int(i)] = hvd.allreduce_async(
                val, op=hvd.Sum, name=f"soak.{i}"
            )
        for i, h in handles.items():
            got = np.asarray(h.wait(timeout=120))
            expect = np.full(
                [(3,), (2, 2), (5,), (1,)][i % 4],
                3 * (i + 1) * (rnd + 1),  # (1 + 2) * (i+1) * round-fresh
                [np.float32, np.float32, np.int32, np.float32][i % 4],
            )
            if not np.array_equal(got, expect):
                out["bad"].append((int(i), got.tolist()))
    return out


def test_native_core_reorder_soak():
    out = runner.run(
        _native_core_reorder_soak,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=420,
    )
    for res in out:
        assert res["bad"] == [], res


def _native_core_alltoall():
    """Named async alltoall through the C++ control plane: negotiation +
    cross-process block exchange (response type 5, previously only covered
    by the direct hostlocal path)."""
    import numpy as np

    hvd, _ = _setup_worker()
    r = hvd.process_rank()
    # process r sends [r*10, r*10+1]: row j goes to process j
    x = np.asarray([[r * 10.0], [r * 10.0 + 1.0]], np.float32)
    h = hvd.alltoall_async(x, name="a2a")
    out = {"rank": r, "got": np.asarray(h.wait(timeout=90)).tolist()}
    return out


def test_native_core_alltoall():
    out = runner.run(
        _native_core_alltoall,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=300,
    )
    for res in out:
        r = res["rank"]
        # block r of every process, in process order
        assert res["got"] == [[0.0 + r], [10.0 + r]], res


def _native_core_reducescatter():
    """Named async reduce-scatter through the control plane (response type
    6): process r receives block r of the cross-process sum."""
    import numpy as np

    hvd, _ = _setup_worker()
    r = hvd.process_rank()
    x = np.asarray([[1.0 + r], [10.0 + r]], np.float32)  # 2 blocks
    h = hvd.reducescatter_async(x, hvd.Sum, name="rs")
    return {"rank": r, "got": np.asarray(h.wait(timeout=90)).tolist()}


def test_native_core_reducescatter():
    out = runner.run(
        _native_core_reducescatter,
        np=2,
        env=_worker_env(),
        use_native_core=True,
        timeout_s=300,
    )
    for res in out:
        r = res["rank"]
        # block r of the cross-process sum: block0 = 1+2, block1 = 10+11
        assert res["got"] == [[3.0], [21.0]][r : r + 1], res
