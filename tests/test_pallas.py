"""Pallas kernels for the int8 wire hot path, Adasum, and the fused
ZeRO-1 Adam shard update (``horovod_tpu.ops.pallas_kernels``,
``HOROVOD_PALLAS``).

Acceptance pins (ISSUE 12) on the 8-device CPU mesh, all via Pallas
INTERPRET mode (the equivalence harness — no TPU hardware needed):

1. the fused quantize kernel is BIT-identical to the discrete HLO
   ``compression.quantize_blockwise`` (odd lengths, exact block
   boundaries, all-zero blocks, bf16-scale rounding, per-bucket
   ``BucketPlan`` shapes);
2. the fused dequant-accumulate(-requantize) epilogues are bit-identical
   to the discrete sum → divide → requantize sequence;
3. int8+EF ZeRO-1 trajectories are BIT-identical across
   ``HOROVOD_PALLAS=0/1`` and Adasum trajectories match within the
   chunked-reduction tolerance;
4. the fused Adam kernel matches optax within a few ULP at the update
   scale and its state checkpoints are bit-stable across the knob;
5. every pinned schedule-fingerprint cell (16 monolithic + 4 overlap +
   the hierarchical 8) is byte-identical with ``HOROVOD_PALLAS=1`` —
   Pallas replaces elementwise HLO, never collectives.
"""

import json
import os
import pathlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu.compression import (
    Compression,
    INT8_BLOCK,
    _pad_to_block,
    dequantize_blockwise,
    quantize_blockwise,
    quantize_chunked,
    quantize_roundtrip_chunked,
)
from horovod_tpu.ops import pallas_kernels as pk
from horovod_tpu.ops.collective import _smap, allreduce, Average

pytestmark = pytest.mark.pallas

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FINGERPRINT_FILE = (
    pathlib.Path(__file__).parent / "data" / "schedule_fingerprints.json"
)


@pytest.fixture()
def pallas_on(monkeypatch):
    monkeypatch.setenv("HOROVOD_PALLAS", "1")


def _rng(seed=0):
    return np.random.RandomState(seed)


# --------------------------------------------------------------------------
# knob semantics


def test_knob_semantics(monkeypatch):
    monkeypatch.delenv("HOROVOD_PALLAS", raising=False)
    # auto on the CPU harness: kernels off (TPU only)
    assert pk.enabled() is False and pk.interpret() is False
    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    assert pk.enabled() is True
    assert pk.interpret() is True  # CPU backend -> interpret harness
    monkeypatch.setenv("HOROVOD_PALLAS", "0")
    assert pk.enabled() is False
    assert pk.cache_key() == (False, False)
    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    assert pk.cache_key() == (True, True)
    monkeypatch.setenv("HOROVOD_PALLAS", "bogus")
    with pytest.raises(ValueError, match="HOROVOD_PALLAS"):
        pk.enabled()


# --------------------------------------------------------------------------
# quantize kernel: bit-equivalence vs the discrete HLO reference


@pytest.mark.parametrize("length", [
    256,      # exactly one block
    2048,     # exact block boundary, multi-tile
    1111,     # odd length -> shared tail pad
    255,      # below one block
    4096 + 3, # tail beside full tiles
])
def test_quantize_bit_equal(pallas_on, length):
    flat = jnp.asarray(_rng(length).randn(length).astype(np.float32))
    q_hlo, s_hlo = quantize_blockwise(flat, use_pallas=False)
    q_pl, s_pl = quantize_blockwise(flat)  # knob dispatches to Pallas
    assert (np.asarray(q_hlo) == np.asarray(q_pl)).all()
    assert (np.asarray(s_hlo) == np.asarray(s_pl)).all()
    # and both consume the SAME shared pad layout
    assert q_pl.shape[0] == _pad_to_block(flat, INT8_BLOCK).shape[0]


def test_quantize_all_zero_blocks(pallas_on):
    """A zero block must emit scale 0 and q 0 (not NaN from 0/0) on both
    paths."""
    flat = jnp.concatenate([
        jnp.zeros((256,), jnp.float32),
        jnp.asarray(_rng(1).randn(256).astype(np.float32)),
        jnp.zeros((256,), jnp.float32),
    ])
    q_hlo, s_hlo = quantize_blockwise(flat, use_pallas=False)
    q_pl, s_pl = quantize_blockwise(flat)
    assert (np.asarray(q_pl) == np.asarray(q_hlo)).all()
    assert (np.asarray(s_pl) == np.asarray(s_hlo)).all()
    assert np.asarray(s_pl)[0] == 0 and np.asarray(q_pl)[:256].sum() == 0


def test_quantize_bf16_scale_rounding(pallas_on):
    """Scales are rounded to bf16 BEFORE the divide; amax values chosen
    to straddle bf16 rounding boundaries must still agree bitwise."""
    base = np.linspace(0.9, 1.1, 256).astype(np.float32)
    rows = []
    for amax in (1.0, 1.0 + 2 ** -9, 127.0 * (1 + 2 ** -8), 3e-5, 1e37):
        r = base.copy()
        r[17] = amax
        rows.append(r / r.max() * amax)
    flat = jnp.asarray(np.concatenate(rows))
    q_hlo, s_hlo = quantize_blockwise(flat, use_pallas=False)
    q_pl, s_pl = quantize_blockwise(flat)
    assert (np.asarray(q_pl) == np.asarray(q_hlo)).all()
    assert (np.asarray(s_pl) == np.asarray(s_hlo)).all()


def test_quantize_bucketplan_shapes(pallas_on):
    """Every per-bucket flat length a BucketPlan partition produces (leaf
    splits, mixed sizes, padded Lp) quantizes bit-identically — the
    shapes the bucketed ZeRO-1 exchange actually feeds the kernel."""
    from horovod_tpu.ops.overlap import BucketPlan

    leaves = [
        jax.ShapeDtypeStruct((40, 30), jnp.float32),
        jax.ShapeDtypeStruct((33,), jnp.float32),
        jax.ShapeDtypeStruct((64, 64), jnp.float32),
        jax.ShapeDtypeStruct((7,), jnp.float32),
    ]
    plan = BucketPlan.build(leaves, n=8, bucket_bytes=4096)
    assert len(plan.buckets) >= 2
    for i, b in enumerate(plan.buckets):
        flat = jnp.asarray(_rng(100 + i).randn(b.Lp).astype(np.float32))
        q_hlo, s_hlo = quantize_blockwise(flat, use_pallas=False)
        q_pl, s_pl = quantize_blockwise(flat)
        assert (np.asarray(q_pl) == np.asarray(q_hlo)).all()
        assert (np.asarray(s_pl) == np.asarray(s_hlo)).all()


def test_quantize_roundtrip_fused_one_pass(pallas_on):
    """The fused (q, scales, deq) triple equals the discrete quantize +
    dequantize pair bit-for-bit, for the chunked wire layout error
    feedback consumes."""
    flat = jnp.asarray(_rng(7).randn(2048).astype(np.float32))
    q0, s0, rt0 = quantize_chunked(flat, 8, use_pallas=False)
    q1, s1, rt1 = quantize_chunked(flat, 8)
    assert (np.asarray(q0) == np.asarray(q1)).all()
    assert (np.asarray(s0) == np.asarray(s1)).all()
    assert (np.asarray(rt0) == np.asarray(rt1)).all()
    # the public roundtrip helper rides the same path
    assert (np.asarray(quantize_roundtrip_chunked(flat, 8))
            == np.asarray(rt0)).all()


# --------------------------------------------------------------------------
# dequant-accumulate(-requantize) epilogues


def _wire_image(n, sp, seed=3):
    r = _rng(seed)
    qr = jnp.asarray(r.randint(-127, 128, (n, sp)).astype(np.int8))
    scr = jnp.asarray(
        (np.abs(r.randn(n, sp // INT8_BLOCK)) * 0.01).astype(np.float32)
    ).astype(jnp.bfloat16)
    return qr, scr


def test_dequant_accumulate_bit_equal(pallas_on):
    n, sp = 8, 1536
    qr, scr = _wire_image(n, sp)
    ref = dequantize_blockwise(
        qr.reshape(-1), scr.reshape(-1), jnp.float32).reshape(n, sp) \
        .sum(axis=0)
    out = pk.dequant_accumulate(qr, scr, jnp.float32, INT8_BLOCK)
    assert (np.asarray(out) == np.asarray(ref)).all()


@pytest.mark.parametrize("divisor", [None, 8])
def test_dequant_accumulate_requantize_bit_equal(pallas_on, divisor):
    n, sp = 8, 2048
    qr, scr = _wire_image(n, sp, seed=4)
    shard = dequantize_blockwise(
        qr.reshape(-1), scr.reshape(-1), jnp.float32).reshape(n, sp) \
        .sum(axis=0)
    if divisor is not None:
        shard = shard / divisor
    q_ref, s_ref = quantize_blockwise(shard, use_pallas=False)
    q2, s2 = pk.dequant_accumulate_requantize(
        qr, scr, jnp.float32, INT8_BLOCK, divisor=divisor)
    assert (np.asarray(q2) == np.asarray(q_ref)).all()
    assert (np.asarray(s2) == np.asarray(s_ref)).all()


# --------------------------------------------------------------------------
# Adasum combine kernels


def _ref_pair_combine(a, b):
    dot = jnp.vdot(a, b).real.astype(jnp.float32)
    na = jnp.vdot(a, a).real.astype(jnp.float32)
    nb = jnp.vdot(b, b).real.astype(jnp.float32)
    ca = jnp.where(na == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(na, 1e-30)))
    cb = jnp.where(nb == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(nb, 1e-30)))
    return (ca * a.astype(jnp.float32)
            + cb * b.astype(jnp.float32)).astype(a.dtype)


@pytest.mark.parametrize("shape", [(1200,), (40, 30), (3000,), (8,)])
def test_adasum_pair_combine_matches(pallas_on, shape):
    r = _rng(11)
    a = jnp.asarray(r.randn(*shape).astype(np.float32))
    b = jnp.asarray(r.randn(*shape).astype(np.float32))
    out = pk.adasum_pair_combine(a, b)
    ref = _ref_pair_combine(a, b)
    assert out.shape == ref.shape and out.dtype == ref.dtype
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


def test_adasum_pair_combine_zero_operands(pallas_on):
    """``|a|² == 0`` zeroes the coefficient (the reference's guard), so
    combine(0, b) == cb·b and combine(0, 0) == 0 — no NaNs from 0/0."""
    z = jnp.zeros((600,), jnp.float32)
    b = jnp.asarray(_rng(12).randn(600).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(pk.adasum_pair_combine(z, b)),
        np.asarray(_ref_pair_combine(z, b)), rtol=2e-5, atol=2e-6)
    assert np.all(np.asarray(pk.adasum_pair_combine(z, z)) == 0)


def test_adasum_segment_combine_matches(pallas_on):
    """Per-segment combine over an unaligned concat layout (incl. a
    length-1 segment and a segment spanning a chunk boundary) tracks the
    discrete segment_sum reference."""
    sizes = [1000, 1, 500, 1571]
    L = sum(sizes)
    r = _rng(13)
    a = jnp.asarray(r.randn(L).astype(np.float32))
    b = jnp.asarray(r.randn(L).astype(np.float32))
    seg = jnp.asarray(np.repeat(np.arange(len(sizes)), sizes))
    out = pk.adasum_segment_combine(a, b, seg, len(sizes))
    dot = jax.ops.segment_sum(a * b, seg, num_segments=len(sizes))
    na = jax.ops.segment_sum(a * a, seg, num_segments=len(sizes))
    nb = jax.ops.segment_sum(b * b, seg, num_segments=len(sizes))
    ca = jnp.where(na == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(na, 1e-30)))
    cb = jnp.where(nb == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(nb, 1e-30)))
    ref = ca[seg] * a + cb[seg] * b
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


def test_adasum_allreduce_knob_equivalence(hvd, monkeypatch):
    """The eager VHDD butterfly (stacked per-rank values) produces the
    same reduction with kernels on and off, and the compiled-program
    cache cannot leak across the knob flip."""
    ax = hvd.data_axis()
    from horovod_tpu.ops.adasum import adasum_allreduce

    vals = jnp.asarray(_rng(14).randn(8, 500).astype(np.float32))
    vs = jax.device_put(vals, NamedSharding(hvd.mesh(), P(ax)))
    monkeypatch.setenv("HOROVOD_PALLAS", "0")
    off = adasum_allreduce(vs, axis=ax)
    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    on = adasum_allreduce(vs, axis=ax)
    np.testing.assert_allclose(
        np.asarray(on), np.asarray(off), rtol=2e-5, atol=2e-6)


def test_grouped_adasum_knob_equivalence(hvd, monkeypatch):
    ax = hvd.data_axis()
    from horovod_tpu.ops.adasum import grouped_adasum_allreduce

    r = _rng(15)
    ts = [
        jax.device_put(
            jnp.asarray(r.randn(8, 40, 30).astype(np.float32)),
            NamedSharding(hvd.mesh(), P(ax))),
        jax.device_put(
            jnp.asarray(r.randn(8, 7).astype(np.float32)),
            NamedSharding(hvd.mesh(), P(ax))),
    ]
    monkeypatch.setenv("HOROVOD_PALLAS", "0")
    off = grouped_adasum_allreduce(ts, axis=ax)
    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    on = grouped_adasum_allreduce(ts, axis=ax)
    for x, y in zip(on, off):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=2e-5, atol=2e-6)


# --------------------------------------------------------------------------
# fused Adam kernel


def test_fused_adam_kernel_vs_reference_ops(pallas_on):
    """The kernel against the identical jnp expression sequence: within
    ~1 ULP elementwise (interpret-mode jit may contract the moment
    multiply-add into an FMA — tolerance is ULP-at-operand-scale, the
    tightest bound FMA contraction admits)."""
    r = _rng(21)
    g = jnp.asarray(r.randn(1200).astype(np.float32))
    mu = jnp.asarray((r.randn(1200) * 0.01).astype(np.float32))
    nu = jnp.asarray((np.abs(r.randn(1200)) * 1e-4).astype(np.float32))
    b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-3
    cnt = jnp.asarray(3, jnp.int32)
    b1c = 1 - b1 ** cnt
    b2c = 1 - b2 ** cnt
    mu_ref = (1 - b1) * g + b1 * mu
    nu_ref = (1 - b2) * (g ** 2) + b2 * nu
    u_ref = -lr * ((mu_ref / b1c) / (jnp.sqrt(nu_ref / b2c) + eps))
    u, m, v = pk.fused_adam_update(
        g, mu, nu, b1c, b2c, lr=lr, b1=b1, b2=b2, eps=eps)

    def ulp_close(a, b, scale, ulps=2):
        a, b = np.asarray(a), np.asarray(b)
        tol = ulps * np.spacing(
            np.maximum(np.maximum(np.abs(a), np.abs(b)), scale)
            .astype(np.float32))
        assert (np.abs(a - b) <= tol).all(), np.abs(a - b).max()

    ulp_close(m, mu_ref, scale=np.abs(np.asarray(g)).max())
    ulp_close(v, nu_ref, scale=float(np.asarray(nu_ref).max()))
    ulp_close(u, u_ref, scale=lr)


def test_fused_adam_matches_optax(pallas_on):
    """Drop-in parity with ``optax.adam``: identical state treedef, and
    updates/moments within a few ULP at the update scale over several
    steps (optax's own jitted bias-correction rewrites set the floor)."""
    from horovod_tpu.optim import fused_adam

    r = _rng(22)
    p = {"w": jnp.asarray(r.randn(40, 30).astype(np.float32)),
         "b": jnp.asarray(r.randn(30).astype(np.float32))}
    ref = optax.adam(1e-3)
    fa = fused_adam(1e-3)
    s0, s1 = ref.init(p), fa.init(p)
    assert jax.tree_util.tree_structure(s0) == \
        jax.tree_util.tree_structure(s1)
    for i in range(5):
        g = {"w": jnp.asarray(r.randn(40, 30).astype(np.float32)),
             "b": jnp.asarray(r.randn(30).astype(np.float32))}
        u0, s0 = ref.update(g, s0, p)
        u1, s1 = fa.update(g, s1, p)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(u1[k]), np.asarray(u0[k]),
                rtol=5e-5, atol=5e-8)


def test_fused_adam_knob_off_is_optax_bitwise(monkeypatch):
    """With the kernels off the transformation IS optax.adam, bit for
    bit — the contract the 0/1 checkpoint interchange rests on."""
    from horovod_tpu.optim import fused_adam

    monkeypatch.setenv("HOROVOD_PALLAS", "0")
    r = _rng(23)
    p = {"w": jnp.asarray(r.randn(40, 30).astype(np.float32))}
    g = {"w": jnp.asarray(r.randn(40, 30).astype(np.float32))}
    ref, fa = optax.adam(1e-3), fused_adam(1e-3)
    s0, s1 = ref.init(p), fa.init(p)
    for _ in range(3):
        u0, s0 = ref.update(g, s0, p)
        u1, s1 = fa.update(g, s1, p)
    assert (np.asarray(u0["w"]) == np.asarray(u1["w"])).all()
    assert (np.asarray(s0[0].mu["w"]) == np.asarray(s1[0].mu["w"])).all()


def test_fused_adam_rejects_schedule():
    from horovod_tpu.optim import fused_adam

    with pytest.raises(ValueError, match="static float"):
        fused_adam(optax.linear_schedule(1e-3, 1e-4, 10))


def test_fused_adam_requantize_epilogue(pallas_on):
    """With compression on, the kernel also emits the blockwise-int8
    wire image of the update shard in the SAME pass — bit-identical to
    quantizing the emitted update separately."""
    r = _rng(24)
    g = jnp.asarray(r.randn(1200).astype(np.float32))
    mu = jnp.zeros((1200,), jnp.float32)
    nu = jnp.zeros((1200,), jnp.float32)
    cnt = jnp.asarray(1, jnp.int32)
    b1c = 1 - 0.9 ** cnt
    b2c = 1 - 0.999 ** cnt
    u, m, v, (q, s) = pk.fused_adam_update(
        g, mu, nu, b1c, b2c, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
        requant_block=INT8_BLOCK)
    q_ref, s_ref = quantize_blockwise(u, use_pallas=False)
    assert (np.asarray(q) == np.asarray(q_ref)).all()
    assert (np.asarray(s) == np.asarray(s_ref)).all()


# --------------------------------------------------------------------------
# mesh trajectories: the knob must not move the math


_SHAPE = (40, 30)


def _params():
    r = _rng(31)
    return {"w": jnp.asarray(r.randn(*_SHAPE).astype(np.float32) * 0.1),
            "b": jnp.zeros((_SHAPE[1],), jnp.float32)}


def _batch(n):
    r = _rng(32)
    x = jnp.asarray(r.randn(2 * n, _SHAPE[0]), jnp.float32)
    y = jnp.asarray(r.randn(2 * n, _SHAPE[1]), jnp.float32)
    return x, y


def _loss(p, x, y):
    return jnp.mean((x @ p["w"] + p["b"][None] - y) ** 2)


def _run_zero1(hvd, inner, steps=6, compression=None, error_feedback=True):
    from horovod_tpu.training import shard_batch

    ax = hvd.data_axis()
    mesh = hvd.mesh()
    dtx = hvd.DistributedOptimizer(
        inner, compression=compression or Compression.int8,
        error_feedback=error_feedback, shard_optimizer=True)
    p = jax.tree_util.tree_map(jnp.array, _params())
    s = dtx.init(p)

    def step(pp, ss, xx, yy):
        l, g = jax.value_and_grad(_loss)(pp, xx, yy)
        u, ss = dtx.update(g, ss, pp)
        pp = optax.apply_updates(pp, u)
        return pp, ss, allreduce(l, Average, axis=ax)

    sm = jax.jit(_smap(
        step, mesh, (P(), P(ax), P(ax), P(ax)), (P(), P(ax), P())))
    x, y = _batch(hvd.size())
    xs, ys = shard_batch(x), shard_batch(y)
    for _ in range(steps):
        p, s, l = sm(p, s, xs, ys)
    return p, s, float(l)


def test_zero1_int8_ef_trajectory_bit_identical(hvd, monkeypatch):
    """The acceptance trajectory: ZeRO-1 + int8 + error feedback on the
    8-mesh, 6 steps — BIT-identical across HOROVOD_PALLAS=0/1 (the
    quantize kernels are bit-equal and the accumulate order matches, so
    nothing may move; this also covers the fused one-pass EF
    residual/wire reuse)."""
    monkeypatch.setenv("HOROVOD_PALLAS", "0")
    p0, s0, l0 = _run_zero1(hvd, optax.adam(1e-2))
    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    p1, s1, l1 = _run_zero1(hvd, optax.adam(1e-2))
    assert l0 == l1
    for k in ("w", "b"):
        assert (np.asarray(p0[k]) == np.asarray(p1[k])).all()
    r0 = np.asarray(s0.residual["float32"])
    r1 = np.asarray(s1.residual["float32"])
    assert (r0 == r1).all()


def test_zero1_fused_adam_trajectory_close(hvd, monkeypatch):
    """fused_adam as the ZeRO-1 inner optimizer: the knob=1 trajectory
    tracks knob=0 (== optax.adam bitwise) at ULP-accumulation level."""
    from horovod_tpu.optim import fused_adam

    monkeypatch.setenv("HOROVOD_PALLAS", "0")
    p0, _, _ = _run_zero1(hvd, fused_adam(1e-2))
    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    p1, _, _ = _run_zero1(hvd, fused_adam(1e-2))
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(p1[k]), np.asarray(p0[k]), rtol=1e-5, atol=1e-7)


def test_fused_adam_checkpoint_bit_stable_across_knob(hvd, monkeypatch,
                                                      tmp_path):
    """The acceptance pin: a fused-Adam ZeRO-1 state saved under
    HOROVOD_PALLAS=1 restores BIT-identically (same treedef, same bytes)
    and continues training under HOROVOD_PALLAS=0 — and vice versa. The
    state pytree is optax.adam's, so the checkpoint carries no trace of
    which kernel wrote it."""
    from horovod_tpu.optim import fused_adam

    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    p1, s1, _ = _run_zero1(hvd, fused_adam(1e-2), steps=3)
    leaves, treedef = jax.tree_util.tree_flatten((p1, s1))
    path = tmp_path / "state.npz"
    np.savez(path, **{str(i): np.asarray(l) for i, l in enumerate(leaves)})
    loaded = np.load(path)
    restored = jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(loaded[str(i)]) for i in range(len(leaves))])
    rp, rs = restored
    for a, b in zip(jax.tree_util.tree_leaves((p1, s1)),
                    jax.tree_util.tree_leaves((rp, rs))):
        assert (np.asarray(a) == np.asarray(b)).all()

    # continue under the OTHER knob from the restored state: the step
    # must accept the state unchanged (structure + shapes) and train
    from horovod_tpu.training import shard_batch

    monkeypatch.setenv("HOROVOD_PALLAS", "0")
    ax = hvd.data_axis()
    dtx = hvd.DistributedOptimizer(
        fused_adam(1e-2), compression=Compression.int8,
        error_feedback=True, shard_optimizer=True)

    def step(pp, ss, xx, yy):
        l, g = jax.value_and_grad(_loss)(pp, xx, yy)
        u, ss = dtx.update(g, ss, pp)
        return optax.apply_updates(pp, u), ss, allreduce(
            l, Average, axis=ax)

    sm = jax.jit(_smap(
        step, hvd.mesh(), (P(), P(ax), P(ax), P(ax)), (P(), P(ax), P())))
    x, y = _batch(hvd.size())
    xs, ys = shard_batch(x), shard_batch(y)
    p2, s2, l2 = sm(rp, rs, xs, ys)
    assert np.isfinite(float(l2))
    # the continued trajectory matches continuing under knob=1 within ULP
    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    p3, s3, l3 = _run_zero1(hvd, fused_adam(1e-2), steps=4)
    np.testing.assert_allclose(
        np.asarray(p2["w"]), np.asarray(p3["w"]), rtol=1e-5, atol=1e-7)


def test_eager_quant_kernels_rekey_on_knob_flip(hvd, monkeypatch):
    """Flipping HOROVOD_PALLAS between eager int8 collectives of the
    SAME signature must rebuild the compiled program (the knob is part
    of the cache key), never replay a stale one — and the results stay
    bit-identical either way."""
    from horovod_tpu.ops.collective import _eager_quant_allreduce_fn

    x = jnp.asarray(_rng(41).randn(2000).astype(np.float32))
    monkeypatch.setenv("HOROVOD_PALLAS", "0")
    a0 = allreduce(x, Average, compression=Compression.int8)
    before = _eager_quant_allreduce_fn.cache_info()
    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    a1 = allreduce(x, Average, compression=Compression.int8)
    after = _eager_quant_allreduce_fn.cache_info()
    assert after.misses == before.misses + 1, (before, after)
    assert (np.asarray(a0) == np.asarray(a1)).all()


# --------------------------------------------------------------------------
# schedule-fingerprint regression gate: HOROVOD_PALLAS=1 must not move
# a single pinned cell (Pallas replaces elementwise HLO, not collectives)


def _build_cell(sync: str, comp_name: str, overlap: bool = False):
    """Compact mirror of tests/test_schedule.py::_build_cell — the same
    cells, rebuilt here under HOROVOD_PALLAS=1."""
    comps = {
        "none": lambda: Compression.none,
        "fp16": lambda: Compression.fp16,
        "int8": lambda: Compression.int8,
        "powersgd": lambda: Compression.powersgd(2),
    }
    comp = comps[comp_name]()
    ef = comp_name != "none"
    kw = dict(overlap=True, bucket_bytes=4096) if overlap else \
        dict(overlap=False)
    dtx = hvd_mod.DistributedOptimizer(
        optax.adam(1e-2), compression=comp, error_feedback=ef,
        shard_optimizer=(sync == "zero1"), **kw)
    rng = np.random.RandomState(0)
    p = {"w": jnp.asarray(rng.randn(64, 32).astype(np.float32) * 0.1),
         "b": jnp.zeros((32,), jnp.float32)}
    s = dtx.init(p)
    ax = hvd_mod.data_axis()
    mesh = hvd_mod.mesh()
    opt_spec = P(ax) if sync == "zero1" else P()

    def loss(pp, x, y):
        return jnp.mean((x @ pp["w"] + pp["b"][None] - y) ** 2)

    def step(pp, ss, x, y):
        l, g = jax.value_and_grad(loss)(pp, x, y)
        u, ss = dtx.update(g, ss, pp)
        pp = optax.apply_updates(pp, u)
        return pp, ss, allreduce(l, Average, axis=ax)

    sm = _smap(
        step, mesh, (P(), opt_spec, P(ax), P(ax)), (P(), opt_spec, P()))
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, 64), jnp.float32)
    y = jnp.asarray(rng.randn(16, 32), jnp.float32)
    return sm, (p, s, x, y)


def _pins():
    with open(FINGERPRINT_FILE, encoding="utf-8") as f:
        return json.load(f)


def test_fingerprints_flat_and_overlap_invariant_under_pallas(
        hvd, monkeypatch):
    """All 8 flat monolithic cells + the 4 overlap cells re-derived with
    HOROVOD_PALLAS=1 fingerprint byte-identically to the pinned matrix:
    kernel substitution may not add, drop, reorder, reshape or re-dtype
    ONE collective."""
    from horovod_tpu.analysis import collective_schedule

    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    pins = _pins()
    for sync in ("allreduce", "zero1"):
        for comp in ("none", "fp16", "int8", "powersgd"):
            fn, args = _build_cell(sync, comp)
            sched = collective_schedule(fn, *args)
            key = f"{sync}|{comp}|flat"
            assert sched.fingerprint() == pins[key]["fingerprint"], (
                f"cell {key} moved under HOROVOD_PALLAS=1"
            )
    for sync in ("allreduce", "zero1"):
        for comp in ("none", "int8"):
            fn, args = _build_cell(sync, comp, overlap=True)
            sched = collective_schedule(fn, *args)
            key = f"{sync}|{comp}|flat|overlap"
            assert sched.fingerprint() == pins[key]["fingerprint"], (
                f"overlap cell {key} moved under HOROVOD_PALLAS=1"
            )


def test_fingerprints_hierarchical_invariant_under_pallas(monkeypatch):
    """The 8 hierarchical cells (2×4 host mesh, cross-hop compression)
    under HOROVOD_PALLAS=1 — byte-identical to the pins."""
    from horovod_tpu.analysis import collective_schedule
    from horovod_tpu.ops.hierarchical import set_hierarchical
    from horovod_tpu.parallel.mesh import build_host_mesh

    monkeypatch.setenv("HOROVOD_PALLAS", "1")
    hvd_mod.init(mesh=build_host_mesh(local=4))
    set_hierarchical(True)
    try:
        pins = _pins()
        for sync in ("allreduce", "zero1"):
            for comp in ("none", "fp16", "int8", "powersgd"):
                fn, args = _build_cell(sync, comp)
                sched = collective_schedule(fn, *args)
                key = f"{sync}|{comp}|hier"
                assert sched.fingerprint() == pins[key]["fingerprint"], (
                    f"hier cell {key} moved under HOROVOD_PALLAS=1"
                )
    finally:
        set_hierarchical(None)
        hvd_mod.shutdown()


# --------------------------------------------------------------------------
# analytic HBM model + bench rung


def test_pallas_hot_path_byte_model():
    import sys

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    from scaling_projection import pallas_hot_path_bytes

    m = pallas_hot_path_bytes([(784, 64), (64,)], 8)
    # fusing can only remove HBM round-trips, never add them
    assert m["fused_bytes"] < m["discrete_bytes"]
    assert 0.0 < m["savings_ratio"] < 1.0
    # the wire bytes match the int8 compressor's pricing of the buffer
    from horovod_tpu.compression import Int8Compressor

    assert m["wire_bytes"] == Int8Compressor.wire_bytes(
        (m["elems"],), jnp.float32)
    # EF off drops the discrete roundtrip pass AND the fused rt write
    m_no_ef = pallas_hot_path_bytes(
        [(784, 64), (64,)], 8, error_feedback=False)
    assert m_no_ef["discrete_bytes"] < m["discrete_bytes"]
    assert m_no_ef["fused_bytes"] < m["fused_bytes"]
    # allreduce epilogue adds the requantize stage to both sides
    m_ar = pallas_hot_path_bytes([(784, 64), (64,)], 8,
                                 epilogue="allreduce")
    assert m_ar["discrete_bytes"] > m["discrete_bytes"]
    with pytest.raises(ValueError, match="epilogue"):
        pallas_hot_path_bytes([(8,)], 8, epilogue="bogus")


@pytest.mark.slow
def test_bench_pallas_ab_rung():
    """bench.py --pallas-ab on the 8-device CPU mesh: ONE JSON line with
    the measured (interpret-mode) ratio, both arms' billed wire bytes
    matching each other and the ring model (the gauges price the wire at
    trace time — compiled-wire invariance itself is pinned by the
    fingerprint tests above), and the analytic HBM model."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    env.pop("HOROVOD_PALLAS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--pallas-ab", "--iters", "3", "--no-probe"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    d = json.loads(line)
    assert d["metric"] == "pallas_ab_step_ratio"
    if not d.get("skipped"):
        assert d["value"] > 0
        b = d["grad_sync_bytes_per_step"]
        # measured byte parity across arms AND vs the ring model
        assert b["fused"] == b["discrete"]
        assert b["fused"] == pytest.approx(b["ring_model"])
        assert d["interpret"] is True
    assert d["pallas_model"]["fused_bytes"] < \
        d["pallas_model"]["discrete_bytes"]
