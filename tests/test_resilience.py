"""Fault-tolerance subsystem tests (ISSUE 2): health state machine,
retry/backoff policy math, chaos-injected KV drops recovered by retry, and
the SIGTERM → drain → emergency checkpoint → restore round trip.

No reference analog — upstream Horovod's failure story is "stall, then die"
(``HOROVOD_STALL_*``); the classify/retry/checkpoint layer is this
rebuild's addition. Tier-1: single process, CPU mesh, deterministic chaos
(counted injections, seeded jitter, no sleeps > 0.2s)."""

import json
import os
import signal
import threading
import time
import urllib.request
import urllib.error
from unittest import mock

import numpy as np
import pytest

from horovod_tpu import checkpoint as ckpt
from horovod_tpu.observability import exporters, metrics
from horovod_tpu.resilience import chaos, health, loop, retry
from horovod_tpu.resilience.health import HealthMonitor, HealthState
from horovod_tpu.resilience.retry import RetryError, RetryPolicy, TransientError
from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer


@pytest.fixture(autouse=True)
def _fresh_resilience():
    """Every test sees a HEALTHY monitor, an empty registry, and no chaos."""
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    yield
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.reset()


def _fast_policy(scope="test", **kw):
    kw.setdefault("max_attempts", 5)
    kw.setdefault("base_delay", 0.005)
    kw.setdefault("max_delay", 0.02)
    kw.setdefault("jitter", 0.0)
    kw.setdefault("seed", 0)
    return RetryPolicy(scope=scope, **kw)


# ------------------------------------------------------- health state machine


class TestHealthMachine:
    def test_fresh_monitor_is_healthy(self):
        m = HealthMonitor()
        assert m.state() == HealthState.HEALTHY
        assert m.snapshot()["state"] == "HEALTHY"

    def test_stall_suspects_then_beat_recovers(self):
        m = HealthMonitor()
        m.record_stall("grad/w0", 60.0)
        assert m.state() == HealthState.SUSPECT
        assert "grad/w0" in m.reason()
        m.beat()
        assert m.state() == HealthState.HEALTHY

    def test_strikes_without_progress_degrade(self):
        m = HealthMonitor()
        for _ in range(m.escalate_after):
            m.record_stall("grad/w0")
        assert m.state() == HealthState.DEGRADED

    def test_degraded_needs_sustained_beats(self):
        m = HealthMonitor()
        for _ in range(m.escalate_after):
            m.record_timeout("grad/w0")
        assert m.state() == HealthState.DEGRADED
        for _ in range(m.recovery_beats - 1):
            m.beat()
        assert m.state() == HealthState.DEGRADED
        m.beat()
        assert m.state() == HealthState.HEALTHY

    def test_retry_exhaustion_degrades_directly(self):
        m = HealthMonitor()
        m.record_retry_exhausted("kv")
        assert m.state() == HealthState.DEGRADED
        assert "kv" in m.reason()

    def test_fatal_is_terminal(self):
        m = HealthMonitor()
        m.record_fatal("coordinator gone")
        for _ in range(10):
            m.beat()
            m.record_stall("x")
        assert m.state() == HealthState.FATAL
        assert m.reason() == "coordinator gone"

    def test_states_are_ordered(self):
        assert HealthState.HEALTHY < HealthState.SUSPECT
        assert HealthState.SUSPECT < HealthState.DEGRADED
        assert HealthState.DEGRADED < HealthState.FATAL

    def test_transitions_mirrored_into_registry(self):
        health.record_stall("grad/w0")
        assert metrics.value("resilience_health_state") == float(
            HealthState.SUSPECT
        )
        assert (
            metrics.value(
                "resilience_health_transitions",
                **{"from": "HEALTHY", "to": "SUSPECT"},
            )
            == 1.0
        )


# ------------------------------------------------------- retry/backoff policy


class TestRetryPolicy:
    def test_delays_exponential_capped(self):
        p = RetryPolicy(max_attempts=5, base_delay=0.05, max_delay=0.3,
                        multiplier=2.0, jitter=0.0)
        assert list(p.delays()) == [0.05, 0.1, 0.2, 0.3]

    def test_seeded_jitter_is_deterministic(self):
        a = RetryPolicy(max_attempts=6, jitter=0.5, seed=42)
        b = RetryPolicy(max_attempts=6, jitter=0.5, seed=42)
        da, db = list(a.delays()), list(b.delays())
        assert da == db
        # jitter only ever lengthens the base schedule, within the bound
        base = RetryPolicy(max_attempts=6, jitter=0.0)
        for with_j, without in zip(da, base.delays()):
            assert without <= with_j < without * 1.5

    def test_call_retries_then_succeeds(self):
        attempts = []
        slept = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise TransientError("not yet")
            return "ok"

        out = _fast_policy().call(flaky, sleep=slept.append)
        assert out == "ok"
        assert len(attempts) == 3
        assert slept == [0.005, 0.01]
        assert metrics.value("resilience_retries", scope="test") == 2.0

    def test_non_retriable_propagates_immediately(self):
        calls = []

        def boom():
            calls.append(1)
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            _fast_policy().call(boom, sleep=lambda _: None)
        assert len(calls) == 1

    def test_exhaustion_raises_retry_error_and_degrades(self):
        def always():
            raise TransientError("still down")

        p = _fast_policy(max_attempts=3)
        with pytest.raises(RetryError) as ei:
            p.call(always, sleep=lambda _: None)
        assert ei.value.attempts == 3
        assert isinstance(ei.value.__cause__, TransientError)
        assert metrics.value(
            "resilience_retry_exhausted", scope="test"
        ) == 1.0
        assert health.health_state() == HealthState.DEGRADED

    def test_deadline_stops_before_sleeping_past_it(self):
        def always():
            raise TransientError("still down")

        p = RetryPolicy(scope="dl", max_attempts=10, base_delay=10.0,
                        deadline=0.05, jitter=0.0)
        t0 = time.monotonic()
        with pytest.raises(RetryError) as ei:
            p.call(always)
        assert time.monotonic() - t0 < 1.0  # never slept the 10s backoff
        assert ei.value.attempts == 1

    def test_predicate_retriable(self):
        seen = []

        def flaky():
            seen.append(1)
            if len(seen) == 1:
                raise OSError("EHOSTUNREACH")
            return 7

        out = _fast_policy().call(
            flaky, retriable=lambda e: isinstance(e, OSError),
            sleep=lambda _: None,
        )
        assert out == 7

    def test_policy_from_env_overrides(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_RETRY_KV_MAX_ATTEMPTS", "9")
        monkeypatch.setenv("HOROVOD_RETRY_BASE_DELAY", "0.125")
        p = retry.policy_from_env("kv", max_attempts=3, base_delay=0.5,
                                  max_delay=1.0)
        assert p.max_attempts == 9  # scoped beats default
        assert p.base_delay == 0.125  # generic beats builder default
        assert p.max_delay == 1.0  # untouched builder default survives


# ------------------------------------------------------------ chaos harness


class TestChaos:
    def test_parse_spec(self):
        cfg = chaos.parse_spec("kv_drop=2, collective_delay=0.05,"
                               "sigterm_at_step=3")
        assert cfg == {"kv_drop": 2, "collective_delay": 0.05,
                       "sigterm_at_step": 3}
        assert chaos.parse_spec("") == {}

    def test_unknown_site_fails_loudly(self):
        with pytest.raises(ValueError, match="unknown chaos site"):
            chaos.parse_spec("kv_dorp=2")

    @pytest.mark.chaos
    def test_counted_charges_are_consumed(self):
        chaos.configure("kv_drop=2")
        assert chaos.enabled()
        assert chaos.should_fail("kv_drop")
        assert chaos.should_fail("kv_drop")
        assert not chaos.should_fail("kv_drop")
        assert metrics.value(
            "resilience_chaos_injected", site="kv_drop"
        ) == 2.0

    @pytest.mark.chaos
    def test_inject_failure_raises_while_charged(self):
        chaos.configure({"collective_fail": 1})
        with pytest.raises(TransientError, match="collective_fail"):
            chaos.inject_failure("collective_fail")
        chaos.inject_failure("collective_fail")  # spent: no-op


# ----------------------------------------------- KV client under chaos/retry


def _client(server, **policy_kw):
    return KVStoreClient(
        "127.0.0.1", server.port,
        retry_policy=_fast_policy("kv", **policy_kw),
    )


@pytest.mark.chaos
def test_kv_drop_recovered_by_retry():
    """The acceptance path: a chaos-injected transient KV failure is
    retried into success, with the retry counters visible in the registry."""
    server = KVStoreServer()
    server.start()
    try:
        chaos.configure("kv_drop=2")
        c = _client(server)
        c.put("rank0", b"addr:1234")  # burns both injected drops
        assert c.get("rank0") == b"addr:1234"
        assert metrics.value("resilience_retries", scope="kv") == 2.0
        assert metrics.value(
            "resilience_chaos_injected", site="kv_drop"
        ) == 2.0
        assert health.health_state() == HealthState.HEALTHY
    finally:
        server.stop()


@pytest.mark.chaos
def test_kv_drop_exhaustion_surfaces_retry_error():
    server = KVStoreServer()
    server.start()
    try:
        chaos.configure("kv_drop=10")
        c = _client(server, max_attempts=2)
        with pytest.raises(RetryError):
            c.get("anything")
        assert health.health_state() == HealthState.DEGRADED
    finally:
        server.stop()


def test_kv_retries_real_startup_race():
    """put() against a not-yet-listening port succeeds once the server
    comes up — the actual bootstrap race, no chaos involved."""
    probe = KVStoreServer()
    probe.start()
    port = probe.port
    probe.stop()  # now refusing connections on a known-free port

    server = KVStoreServer(port=port)

    def _late_start():
        time.sleep(0.05)
        server.start()

    t = threading.Thread(target=_late_start)
    t.start()
    try:
        c = KVStoreClient(
            "127.0.0.1", port,
            retry_policy=_fast_policy("kv", max_attempts=20,
                                      base_delay=0.01, max_delay=0.02),
        )
        c.put("k", b"v")
        assert c.get("k") == b"v"
    finally:
        t.join()
        server.stop()


def test_wait_for_respects_total_deadline():
    """No server at all: transient errors inside the poll burn the one
    shared deadline instead of spinning forever."""
    probe = KVStoreServer()
    probe.start()
    port = probe.port
    probe.stop()
    c = KVStoreClient("127.0.0.1", port)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="0.2s"):
        c.wait_for("never", timeout=0.2, interval=0.01)
    assert time.monotonic() - t0 < 2.0


def test_wait_for_returns_when_key_appears():
    server = KVStoreServer()
    server.start()
    try:
        threading.Timer(0.05, server.put, ("late", b"here")).start()
        c = _client(server)
        assert c.wait_for("late", timeout=5.0, interval=0.01) == b"here"
    finally:
        server.stop()


# ------------------------------------------------ corrupt-checkpoint fallback


class TestCheckpointFallback:
    def test_skips_missing_treedef(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save(d, 1, {"w": np.ones(3)})
        os.makedirs(os.path.join(d, "step_2"))  # no tree.pkl, no arrays.npz
        assert ckpt.latest_step(d) == 1
        out = ckpt.restore(d)
        np.testing.assert_allclose(np.asarray(out["w"]), 1.0)

    def test_skips_truncated_npz(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save(d, 1, {"w": np.ones(3)})
        ckpt.save(d, 2, {"w": np.full(3, 2.0)})
        npz = os.path.join(d, "step_2", "arrays.npz")
        with open(npz, "r+b") as f:
            f.truncate(os.path.getsize(npz) // 2)
        assert not ckpt.is_valid_checkpoint(os.path.join(d, "step_2"))
        assert ckpt.latest_step(d) == 1
        out = ckpt.restore(d)
        np.testing.assert_allclose(np.asarray(out["w"]), 1.0)

    def test_skips_truncated_treedef(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save(d, 1, {"w": np.ones(3)})
        ckpt.save(d, 2, {"w": np.full(3, 2.0)})
        tree = os.path.join(d, "step_2", "tree.pkl")
        with open(tree, "r+b") as f:
            f.truncate(os.path.getsize(tree) // 2)  # nonzero but torn
        assert not ckpt.is_valid_checkpoint(os.path.join(d, "step_2"))
        assert ckpt.latest_step(d) == 1

    def test_all_corrupt_is_no_checkpoints(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        os.makedirs(os.path.join(d, "step_3"))
        assert ckpt.latest_step(d) is None
        with pytest.raises(FileNotFoundError):
            ckpt.restore(d)

    def test_valid_steps_ordering(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        for s in (4, 1, 9):
            ckpt.save(d, s, {"w": np.zeros(1)})
        os.makedirs(os.path.join(d, "step_12"))
        assert ckpt.valid_steps(d) == [1, 4, 9]


# ---------------------------------------------------- attributable timeouts


def test_core_handle_timeout_is_attributable():
    from horovod_tpu.core import CoreHandle

    h = CoreHandle("grad/dense0")
    with pytest.raises(TimeoutError) as ei:
        h.wait(timeout=0.01)
    e = ei.value
    assert e.tensor_name == "grad/dense0"
    assert e.health_state == HealthState.SUSPECT  # first strike
    assert "grad/dense0" in str(e)
    assert "SUSPECT" in str(e)
    assert metrics.value("resilience_wait_timeouts") == 1.0


# ------------------------------------------- preemption-aware training loop


def _count_step(state, step):
    return {"w": state["w"] + 1.0}


class TestPreemptionLoop:
    def test_plain_run_completes(self, tmp_path):
        out = loop.run(_count_step, {"w": np.zeros(2)}, num_steps=4)
        np.testing.assert_allclose(out["w"], 4.0)
        assert health.health_state() == HealthState.HEALTHY

    @pytest.mark.chaos
    def test_sigterm_checkpoint_restore_roundtrip(self, hvd, tmp_path):
        """The acceptance path: a delivered SIGTERM drains, writes an
        emergency checkpoint, exits resumable; the relaunched run resumes
        from it and completes, counters visible in the registry."""
        d = str(tmp_path / "ck")
        chaos.configure("sigterm_at_step=2")
        with pytest.raises(loop.Preempted) as ei:
            loop.run(_count_step, {"w": np.zeros(2)}, num_steps=5,
                     checkpoint_dir=d)
        e = ei.value
        assert e.code == loop.RESUMABLE_EXIT_CODE == 75
        assert e.step == 2
        assert e.signum == signal.SIGTERM
        assert ckpt.latest_step(d) == 2
        assert metrics.value("resilience_preemptions") == 1.0
        assert metrics.value("resilience_emergency_checkpoints") == 1.0
        assert metrics.value(
            "resilience_chaos_injected", site="sigterm_at_step"
        ) == 1.0

        # "relaunch": fresh loop, same checkpoint dir, no chaos
        chaos.configure(None)
        out = loop.run(_count_step, {"w": np.zeros(2)}, num_steps=5,
                       checkpoint_dir=d)
        np.testing.assert_allclose(out["w"], 5.0)  # 2 before + 3 after
        assert metrics.value("resilience_resumes") == 1.0

    def test_preempted_is_resumable_system_exit(self):
        p = loop.Preempted(3, "/ck/step_3", signal.SIGTERM)
        assert isinstance(p, SystemExit)
        assert p.code == 75
        assert "step 3" in str(p)

    def test_periodic_checkpoints(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        loop.run(_count_step, {"w": np.zeros(1)}, num_steps=6,
                 checkpoint_dir=d, checkpoint_every=2)
        # steps 2 and 4 checkpointed; 6 is the (uncheckpointed) finish
        assert ckpt.valid_steps(d) == [2, 4]

    def test_resume_state_empty_dir(self, tmp_path):
        assert loop.resume_state(str(tmp_path / "none")) is None

    @pytest.mark.chaos
    def test_preempt_checkpoints_without_init(self, tmp_path):
        """resilience.run supports uninitialized single-process use: the
        emergency checkpoint must not require hvd.init()."""
        import horovod_tpu as hvd_mod

        assert not hvd_mod.is_initialized()
        d = str(tmp_path / "ck")
        chaos.configure("sigterm_at_step=1")
        with pytest.raises(loop.Preempted) as ei:
            loop.run(_count_step, {"w": np.zeros(2)}, num_steps=3,
                     checkpoint_dir=d)
        assert ei.value.checkpoint_path is not None
        assert ckpt.latest_step(d) == 1
        chaos.configure(None)
        out = loop.run(_count_step, {"w": np.zeros(2)}, num_steps=3,
                       checkpoint_dir=d)
        np.testing.assert_allclose(out["w"], 3.0)

    def test_signal_restored_after_run(self):
        before = signal.getsignal(signal.SIGTERM)
        loop.run(_count_step, {"w": np.zeros(1)}, num_steps=1)
        assert signal.getsignal(signal.SIGTERM) is before

    def test_callbacks_fire(self, tmp_path):
        from horovod_tpu.callbacks import Callback

        seen = []

        class Spy(Callback):
            def on_train_begin(self, logs=None):
                seen.append("begin")

            def on_batch_end(self, batch, logs=None):
                seen.append(batch)

            def on_train_end(self, logs=None):
                seen.append("end")

        loop.run(_count_step, {"w": np.zeros(1)}, num_steps=2,
                 callbacks=[Spy()])
        assert seen == ["begin", 0, 1, "end"]


# ------------------------------------------------- launcher bounded restarts


def test_launch_job_restarts_preempted_worker(monkeypatch):
    """A slot exiting RESUMABLE_EXIT_CODE is restarted in place (bounded),
    and the restart counter lands in the registry."""
    from horovod_tpu.run import hosts, runner

    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_BASE_DELAY", "0.01")
    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_MAX_DELAY", "0.02")
    slots = hosts.allocate(hosts.parse_hosts("localhost:1"), 1)
    rcs = iter([loop.RESUMABLE_EXIT_CODE, 0])

    def fake_execute(argv, env=None, stdout_handler=None,
                     stderr_handler=None, event=None, shell=False):
        return next(rcs)

    with mock.patch.object(runner.safe_exec, "execute", fake_execute):
        codes = runner.launch_job(slots, ["python", "train.py"], {},
                                  max_restarts=1)
    assert codes == [0]
    assert metrics.value(
        "resilience_worker_restarts", host="localhost"
    ) == 1.0


def test_launch_job_preemptions_do_not_strike_host(monkeypatch):
    """Exit-75 preemptions are the healthy path: they must not burn the
    host's strike budget (a mass preemption would otherwise blacklist the
    host out of the very restarts the feature exists for)."""
    from horovod_tpu.run import hosts, runner

    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_BASE_DELAY", "0.01")
    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_MAX_DELAY", "0.02")
    monkeypatch.setenv("HOROVOD_HOST_STRIKE_LIMIT", "1")
    slots = hosts.allocate(hosts.parse_hosts("localhost:1"), 1)
    rcs = iter([loop.RESUMABLE_EXIT_CODE, loop.RESUMABLE_EXIT_CODE, 0])

    def fake_execute(argv, env=None, stdout_handler=None,
                     stderr_handler=None, event=None, shell=False):
        return next(rcs)

    with mock.patch.object(runner.safe_exec, "execute", fake_execute):
        codes = runner.launch_job(slots, ["python", "train.py"], {},
                                  max_restarts=2)
    # strike limit 1 would have blacklisted after the first 75 — it didn't
    assert codes == [0]


def test_launch_job_host_blacklisted_after_strikes(monkeypatch):
    from horovod_tpu.run import hosts, runner

    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_BASE_DELAY", "0.01")
    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_MAX_DELAY", "0.02")
    monkeypatch.setenv("HOROVOD_HOST_STRIKE_LIMIT", "2")
    slots = hosts.allocate(hosts.parse_hosts("localhost:1"), 1)
    calls = []

    def fake_execute(argv, env=None, stdout_handler=None,
                     stderr_handler=None, event=None, shell=False):
        calls.append(1)
        return 1  # keeps dying

    with mock.patch.object(runner.safe_exec, "execute", fake_execute):
        codes = runner.launch_job(slots, ["python", "train.py"], {},
                                  max_restarts=10)
    # first failure never strikes; the 2 failed RESTARTS hit the limit and
    # beat the 10-restart budget: 3 attempts total, then stop
    assert len(calls) == 3
    assert codes == [1]


def test_restart_count_pinned_to_max_restarts(monkeypatch):
    """HOROVOD_RETRY_WORKER_RESTART_* tunes backoff shape only; a stray
    MAX_ATTEMPTS override must neither add restarts nor starve them."""
    from horovod_tpu.run import hosts, runner

    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_MAX_ATTEMPTS", "1")
    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_BASE_DELAY", "0.01")
    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_MAX_DELAY", "0.02")
    slots = hosts.allocate(hosts.parse_hosts("localhost:1"), 1)
    rcs = iter([1, 1, 0])

    def fake_execute(argv, env=None, stdout_handler=None,
                     stderr_handler=None, event=None, shell=False):
        return next(rcs)

    with mock.patch.object(runner.safe_exec, "execute", fake_execute):
        codes = runner.launch_job(slots, ["python", "train.py"], {},
                                  max_restarts=2)
    assert codes == [0]  # both restarts happened despite MAX_ATTEMPTS=1


def test_health_callback_abort_on_suspect():
    """abort_on=SUSPECT must fire on the state the batch produced — the
    progress beat happens after the check, not before."""
    from horovod_tpu.callbacks import HealthCallback

    cb = HealthCallback(printer=lambda m: None,
                        abort_on=HealthState.SUSPECT)
    health.record_stall("grad/w0")  # mid-batch anomaly
    with pytest.raises(RuntimeError, match="SUSPECT"):
        cb.on_batch_end(0)


def test_health_callback_beats_recover():
    from horovod_tpu.callbacks import HealthCallback

    seen = []
    cb = HealthCallback(printer=seen.append)  # default abort_on=FATAL
    health.record_stall("grad/w0")
    cb.on_batch_end(0)  # logs the transition, no abort, then beats
    assert health.health_state() == HealthState.HEALTHY
    assert any("SUSPECT" in m for m in seen)


def test_host_strikes_forgiveness():
    from horovod_tpu.run.runner import HostStrikes

    s = HostStrikes(limit=2)
    assert s.strike("h1") == 1
    assert not s.blacklisted("h1")
    assert s.strike("h1") == 2
    assert s.blacklisted("h1")
    s.forgive("h1")
    assert not s.blacklisted("h1")


# --------------------------------------------------------- /health endpoint


def test_health_endpoint_serves_state():
    server = exporters.start_http_server(0)
    try:
        port = server.server_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health"
        ) as r:
            assert r.status == 200
            snap = json.loads(r.read())
        assert snap["state"] == "HEALTHY"

        health.record_retry_exhausted("kv")  # DEGRADED
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health")
        assert ei.value.code == 503
        snap = json.loads(ei.value.read())
        assert snap["state"] == "DEGRADED"
    finally:
        exporters.stop_http_server()


def test_basics_health_surface():
    import horovod_tpu as hvd_mod

    assert hvd_mod.health_state() == HealthState.HEALTHY
    health.record_stall("grad/w0")
    assert hvd_mod.health_state() == HealthState.SUSPECT
    snap = hvd_mod.health()
    assert snap["state"] == "SUSPECT"
    assert "grad/w0" in snap["reason"]


# -------------------------------------------------- eager dispatch guarded


@pytest.mark.chaos
def test_chaos_collective_fail_retried(hvd):
    """An injected transient failure on the eager dispatch path is retried
    into success (single-process: unilateral retry is safe)."""
    import jax.numpy as jnp

    x = jnp.ones(8)
    hvd.allreduce(x, op=hvd.Average)  # warm the compile cache
    chaos.configure("collective_fail=1")
    out = hvd.allreduce(x, op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out), 1.0)
    assert metrics.value(
        "resilience_retries", scope="collective_dispatch"
    ) == 1.0
    assert metrics.value(
        "resilience_chaos_injected", site="collective_fail"
    ) == 1.0
