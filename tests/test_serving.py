"""Streaming weight publication (ISSUE 6): KV write-ahead log + restart,
background sweep, commit-last publish protocol, int8 delta chains with
keyframe resync, staleness contract, elastic composition, preemption-drain
final flush.

The acceptance pin: a trainer publishing 5+ generations of int8 deltas
under ``HOROVOD_CHAOS=publish_fail=1,kv_restart_at_step=3`` with a mid-run
8→6 elastic shrink never exposes a torn generation — the subscriber
reconstructs the trainer's consolidated weights allclose, including a
keyframe re-root + resync after the KV restart. Tier-1: single process,
deterministic chaos, no sleeps > 0.2s; the >=20-generation soaks are
``slow``.
"""

import json
import os
import time

import numpy as np
import pytest

from horovod_tpu.observability import metrics
from horovod_tpu.resilience import chaos, health, loop
from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer
from horovod_tpu.serving import (
    ChainError,
    PublishAborted,
    WeightPublisher,
    WeightSubscriber,
    subscribe_weights,
)
from horovod_tpu.serving import protocol

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    from horovod_tpu.serving import publisher as _pub_mod

    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()  # no flush-registry leakage across tests
    yield
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.reset()
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()


def _tree(seed=0, big=2048, small=7):
    rng = np.random.RandomState(seed)
    return {
        "dense": {"kernel": rng.randn(big).astype(np.float32).reshape(-1, 64)},
        "bias": rng.randn(small).astype(np.float32),
        "step_count": np.int32(seed),
    }


def _drift(tree, seed, scale=0.01):
    rng = np.random.RandomState(seed)

    def one(x):
        x = np.asarray(x)
        if x.dtype.kind == "f":
            return x + scale * rng.randn(*x.shape).astype(x.dtype)
        return x

    import jax

    return jax.tree_util.tree_map(one, tree)


# ------------------------------------------------------------ wire protocol


@pytest.mark.serving
class TestProtocol:
    def test_keyframe_roundtrip_exact(self):
        t = _tree(0)
        payload, info = protocol.encode(t)
        assert info["kind"] == "key"
        out = protocol.decode(payload)
        import jax

        for got, want in zip(jax.tree_util.tree_leaves(out),
                             jax.tree_util.tree_leaves(t)):
            np.testing.assert_array_equal(got, want)

    def test_delta_chain_tracks_reconstruction_exactly(self):
        """The EF argument: each delta is measured against the decode of
        the previous wire, so publisher and subscriber reconstructions are
        bit-identical at ANY chain length, and quantization error never
        accumulates (stays within one delta's quantization of the truth)."""
        truth = _tree(0)
        payload, _ = protocol.encode(truth)
        recon_pub = protocol.decode(payload)
        recon_sub = protocol.decode(payload)
        for s in range(1, 8):
            truth = _drift(truth, s)
            payload, info = protocol.encode(truth, recon_pub)
            assert info["kind"] == "delta"
            recon_pub = protocol.decode(payload, recon_pub)
            recon_sub = protocol.decode(payload, recon_sub)
            np.testing.assert_array_equal(
                recon_sub["dense"]["kernel"], recon_pub["dense"]["kernel"])
            # bounded by ONE quantization error, not s of them
            np.testing.assert_allclose(
                recon_sub["dense"]["kernel"], truth["dense"]["kernel"],
                atol=2e-4)

    def test_delta_quantize_floor_and_int_passthrough(self):
        """Sub-floor leaves (the 7-elt bias) and integer leaves ride raw —
        the delta is then EXACT for them, same floor rule as the
        collective wire."""
        base = _tree(0)
        new = _drift(base, 1)
        payload, info = protocol.encode(new, base)
        out = protocol.decode(payload, base)
        np.testing.assert_array_equal(out["bias"], new["bias"])  # raw delta
        assert out["step_count"] == new["step_count"]
        # the big leaf IS quantized: close but not exact
        k = out["dense"]["kernel"] - new["dense"]["kernel"]
        assert 0 < np.abs(k).max() < 2e-4

    def test_bool_leaf_rides_full_in_delta(self):
        """numpy bool subtraction raises; a bool mask leaf (and any other
        non-subtractable dtype) must ride as its FULL value inside a
        delta instead of crashing the encode."""
        base = {"w": np.ones(2048, np.float32),
                "mask": np.array([True, False, True])}
        new = {"w": base["w"] + 0.1,
               "mask": np.array([False, False, True])}
        payload, _ = protocol.encode(new, base)
        out = protocol.decode(payload, base)
        np.testing.assert_array_equal(out["mask"], new["mask"])
        np.testing.assert_allclose(out["w"], new["w"], atol=2e-3)

    def test_delta_base_treedef_mismatch(self):
        with pytest.raises(ValueError, match="treedef"):
            protocol.encode(_tree(0), {"other": np.zeros(3)})
        payload, _ = protocol.encode(_tree(0))
        with pytest.raises(ChainError):
            protocol.decode(
                protocol.encode(_tree(1), _tree(0))[0], base=None)

    def test_chunks_and_crc(self):
        payload = os.urandom(1000)
        chunks = protocol.split_chunks(payload, 256)
        assert len(chunks) == 4 and b"".join(chunks) == payload
        assert protocol.split_chunks(b"", 256) == [b""]
        m = protocol.parse_manifest(protocol.build_manifest(
            generation=3, step=30, kind="delta", keyframe=1,
            chunks=chunks, payload=payload, wire_bytes=900,
            elastic_generation=None, published_at=time.time()))
        assert m["generation"] == 3 and m["base"] == 2
        assert m["chunk_crc"][1] == protocol.crc(chunks[1])
        assert m["payload_crc"] == protocol.crc(payload)
        with pytest.raises(ChainError):
            protocol.parse_manifest(b"not json")
        with pytest.raises(ChainError):
            protocol.parse_manifest(json.dumps({"version": 99}).encode())

    def test_wire_bytes_match_analytic_model(self):
        """Model == gauge: the encoder's wire accounting equals
        scaling_projection.publish_bytes leaf for leaf."""
        import sys

        sys.path.insert(0, os.path.join(_REPO, "tools"))
        from scaling_projection import publish_bytes

        shapes = [(784, 512), (512,), (512, 512), (512,), (512, 10), (10,)]
        rng = np.random.RandomState(0)
        tree = [rng.randn(*s).astype(np.float32) for s in shapes]
        model = publish_bytes(shapes, keyframe_every=8)
        _, key_info = protocol.encode(tree)
        assert key_info["wire_bytes"] == model["keyframe_bytes"]
        base = protocol.decode(protocol.encode(tree)[0])
        _, delta_info = protocol.encode(
            [t + 0.01 for t in tree], base)
        assert delta_info["wire_bytes"] == model["delta_bytes"]
        assert model["delta_ratio_vs_checkpoint"] < 0.3  # the ~4x win


# ------------------------------------------------------- KV durability (WAL)


@pytest.mark.serving
class TestKVWal:
    def test_restart_replays_state(self, tmp_path):
        s = KVStoreServer(wal_path=str(tmp_path / "kv.wal"))
        s.put("/elastic/gen", b'{"generation": 3}')
        s.put("/serving/head", b"7")
        s.put("/hb/2", b"1", ttl=30.0)
        s.delete("/hb/5", tombstone=True)
        s.restart()
        assert s.get("/elastic/gen") == b'{"generation": 3}'
        assert s.get("/serving/head") == b"7"
        assert s.get("/hb/2") == b"1"  # TTL lease re-armed
        assert "/hb/5" in s.dead_keys()  # tombstone survived
        assert metrics.value("rendezvous_wal_replayed") > 0
        assert metrics.value("rendezvous_restarts") == 1.0
        s.close()

    def test_fresh_server_on_same_wal(self, tmp_path):
        wal = str(tmp_path / "kv.wal")
        s = KVStoreServer(wal_path=wal)
        s.put("/a", b"x")
        s.prune("/gone")  # prune of nothing: no record
        s.put("/gone/1", b"y")
        s.prune("/gone")
        s.close()
        s2 = KVStoreServer(wal_path=wal)
        assert s2.get("/a") == b"x"
        assert s2.get("/gone/1") is None
        s2.close()

    def test_restart_without_replay_truncates(self, tmp_path):
        s = KVStoreServer(wal_path=str(tmp_path / "kv.wal"))
        s.put("/a", b"x")
        s.restart(replay=False)  # the disk died with the process
        assert s.get("/a") is None
        s.put("/b", b"y")
        s.restart()  # the new WAL reflects only post-loss state
        assert s.get("/a") is None and s.get("/b") == b"y"
        s.close()

    def test_torn_tail_record_tolerated(self, tmp_path):
        wal = str(tmp_path / "kv.wal")
        s = KVStoreServer(wal_path=wal)
        s.put("/a", b"x")
        s.put("/b", b"y")
        s.close()
        with open(wal, "ab") as f:
            f.write(b'{"op": "put", "k": "/c", "v"')  # died mid-append
        s2 = KVStoreServer(wal_path=wal)
        assert s2.get("/a") == b"x" and s2.get("/b") == b"y"
        assert s2.get("/c") is None
        s2.close()

    def test_compaction_bounds_the_log(self, tmp_path):
        wal = str(tmp_path / "kv.wal")
        s = KVStoreServer(wal_path=wal)
        for i in range(50):
            s.put("/hot", str(i).encode())  # 50 records, 1 live key
        s.close()
        s2 = KVStoreServer(wal_path=wal)  # open compacts
        assert s2.get("/hot") == b"49"
        assert s2._wal_records == 1
        s2.close()

    def test_second_server_on_live_wal_fails_fast(self, tmp_path):
        """Found by the 3-process drive: a second server on the same WAL
        (operator error, a restart racing the old process) compacted the
        LIVE server's log before its port bind even failed — silently
        truncating committed generations. The WAL lock makes the loser
        fail fast instead."""
        wal = str(tmp_path / "kv.wal")
        s = KVStoreServer(wal_path=wal)
        s.put("/serving/head", b"9")
        with pytest.raises(RuntimeError, match="locked by another"):
            KVStoreServer(wal_path=wal)
        # the live server's log was never touched
        s.put("/a", b"x")
        s.close()
        s2 = KVStoreServer(wal_path=wal)  # lock released on close
        assert s2.get("/serving/head") == b"9" and s2.get("/a") == b"x"
        s2.close()

    def test_no_wal_restart_loses_everything(self):
        s = KVStoreServer()
        s.put("/a", b"x")
        s.restart()
        assert s.get("/a") is None
        s.close()

    def test_restart_preserves_port_and_http(self):
        s = KVStoreServer(secret="sek")
        port = s.start()
        c = KVStoreClient("127.0.0.1", port, secret="sek")
        c.put("k1", b"v1")
        s.restart()
        assert s.port == port
        c.put("k2", b"v2")  # same address keeps working
        assert c.get("k2") == b"v2"
        assert c.get("k1") is None  # no WAL: lost
        s.close()

    def test_client_delete_tombstone_over_http(self):
        from horovod_tpu.run.rendezvous import DeadRankError

        s = KVStoreServer(secret="sek")
        port = s.start()
        c = KVStoreClient("127.0.0.1", port, secret="sek")
        c.put("/serving/manifest/3", b"m")
        assert c.delete("/serving/manifest/3", tombstone=True)
        with pytest.raises(DeadRankError):
            c.get("/serving/manifest/3")
        assert not c.delete("/never")  # 404 → False, no raise
        s.close()


@pytest.mark.serving
class TestKVSweep:
    def test_background_sweep_expires_without_access(self):
        s = KVStoreServer(sweep_interval=0.03, tombstone_ttl=300)
        s.put("/hb/1", b"1", ttl=0.05)
        time.sleep(0.15)  # nobody reads the key; the timer must reap it
        with s._lock:
            gone = "/hb/1" not in s._store
            dead = "/hb/1" in s._dead
        assert gone and dead
        assert metrics.value("rendezvous_keys_swept", kind="expired") == 1.0
        s.close()

    def test_tombstone_gc_bounds_memory(self):
        s = KVStoreServer(sweep_interval=0.03, tombstone_ttl=0.05)
        for i in range(5):
            s.delete(f"/hb/{i}", tombstone=True)
        time.sleep(0.2)
        assert s.dead_keys() == []
        assert metrics.value(
            "rendezvous_keys_swept", kind="tombstone") == 5.0
        s.close()

    def test_lazy_access_never_drops_tombstones(self):
        s = KVStoreServer(tombstone_ttl=0.01)  # no sweep timer
        s.delete("/hb/9", tombstone=True)
        time.sleep(0.05)
        assert "/hb/9" in s.dead_keys()  # access sweeps TTLs, not stones
        s.close()


# -------------------------------------------------------------- publisher


@pytest.mark.serving
class TestPublisher:
    def test_commit_last_ordering(self):
        """chunks → manifest → head, never any other order."""
        order = []
        s = KVStoreServer()
        real_put = s.put

        def spy(key, value, ttl=None):
            order.append(key)
            real_put(key, value, ttl=ttl)

        s.put = spy
        pub = WeightPublisher(s, chunk_bytes=512, register=False)
        pub.publish({"params": _tree(0)}, 1)
        assert order[-1] == "/serving/head"
        assert order[-2] == "/serving/manifest/1"
        assert all("/chunks/" in k for k in order[:-2]) and len(order) > 3
        s.close()

    @pytest.mark.chaos
    def test_publish_fail_retries_and_never_tears(self):
        """With publish_fail armed, chunk 0 lands and the attempt dies; a
        subscriber polling at that exact torn moment sees NOTHING (head
        unmoved), and the retried attempt commits the full generation."""
        from unittest import mock

        s = KVStoreServer()
        pub = WeightPublisher(s, register=False)
        sub = WeightSubscriber(s)
        chaos.configure("publish_fail=1")

        seen_mid_failure = []
        real_inject = chaos.inject_failure

        def probing_inject(site, exc_factory=None):
            try:
                real_inject(site, exc_factory)
            except BaseException:
                seen_mid_failure.append(sub.poll())  # torn moment: poll now
                raise

        with mock.patch(
                "horovod_tpu.resilience.chaos.inject_failure",
                probing_inject):
            gen = pub.publish({"params": _tree(0)}, 1)
        assert gen == 1
        assert seen_mid_failure == [None]  # the tear was never visible
        assert sub.generation == 0
        assert metrics.value(
            "resilience_chaos_injected", site="publish_fail") == 1.0
        assert sub.poll() is not None and sub.generation == 1
        s.close()
        chaos.configure(None)

    def test_gc_retires_back_to_keyframe(self):
        s = KVStoreServer()
        pub = WeightPublisher(s, keyframe_every=3, register=False)
        t = _tree(0)
        for i in range(1, 8):  # keyframes at 1, 4, 7
            t = _drift(t, i)
            pub.publish({"params": t}, i)
        assert pub.keyframe_generation == 7
        live = s.live_keys("/serving/manifest/")
        assert live == ["/serving/manifest/7"]
        # GC'd manifests are tombstoned, not vanished
        assert "/serving/manifest/4" in s.dead_keys()
        assert s.live_keys("/serving/chunks/1/") == []
        assert metrics.value("serving_generations_gc") == 6.0
        s.close()

    def test_fence_abort_is_clean(self):
        s = KVStoreServer()
        calls = {"n": 0}

        def fence():
            calls["n"] += 1
            return 1 if calls["n"] == 1 else 2

        pub = WeightPublisher(s, register=False, fence_fn=fence)
        with pytest.raises(PublishAborted):
            pub.publish({"params": _tree(0)}, 1)
        assert pub.generation == 0
        assert s.get("/serving/head") is None
        assert s.live_keys("/serving/chunks/") == []
        assert metrics.value("serving_publish_aborts") == 1.0
        # next publish with a stable fence commits normally
        pub.fence_fn = lambda: 2
        assert pub.publish({"params": _tree(0)}, 2) == 1
        s.close()

    def test_kv_restart_chaos_rearms_keyframe(self):
        """kv_restart_at_step fires inside publish(); without a WAL the
        store comes back empty and the publisher re-roots the chain with a
        keyframe instead of emitting an unchainable delta."""
        s = KVStoreServer()
        pub = WeightPublisher(s, keyframe_every=100, register=False)
        t = _tree(0)
        pub.publish({"params": t}, 1)
        t = _drift(t, 1)
        pub.publish({"params": t}, 2)  # a delta
        chaos.configure("kv_restart_at_step=3")
        t = _drift(t, 2)
        pub.publish({"params": t}, 3)
        assert metrics.value(
            "resilience_chaos_injected", site="kv_restart_at_step") == 1.0
        assert pub.keyframe_generation == 3  # re-rooted
        sub = WeightSubscriber(s)
        out = sub.poll()
        assert out is not None and sub.generation == 3
        np.testing.assert_allclose(
            out["dense"]["kernel"], t["dense"]["kernel"], atol=2e-4)
        s.close()
        chaos.configure(None)

    def test_kv_restart_with_wal_keeps_the_chain(self, tmp_path):
        """Same chaos charge with a WAL'd KV: the generations survive the
        restart, the chain continues with deltas (no re-root)."""
        s = KVStoreServer(wal_path=str(tmp_path / "kv.wal"))
        pub = WeightPublisher(s, keyframe_every=100, register=False)
        t = _tree(0)
        pub.publish({"params": t}, 1)
        chaos.configure("kv_restart_at_step=2")
        t = _drift(t, 1)
        pub.publish({"params": t}, 2)
        assert pub.keyframe_generation == 1  # still the original keyframe
        assert metrics.value(
            "serving_publish_generations", kind="delta") == 1.0
        sub = WeightSubscriber(s)
        sub.poll()
        assert sub.generation == 2
        s.close()
        chaos.configure(None)

    def test_trainer_restart_new_publisher_never_corrupts_base(self):
        """Found by the 3-process drive: a restarted trainer's FRESH
        publisher re-used generation numbers over the same KV, and a
        surviving subscriber applied its deltas against the OLD chain's
        trees — silently wrong weights. Pin the fix: the new publisher
        adopts the head (monotonic numbers) and stamps a new chain id, so
        the subscriber resyncs onto the new chain instead."""
        s = KVStoreServer()
        pub1 = WeightPublisher(s, keyframe_every=100, register=False)
        t = _tree(0)
        for i in (1, 2, 3):
            t = _drift(t, i)
            pub1.publish({"params": t}, i)
        sub = WeightSubscriber(s)
        sub.poll()
        assert sub.generation == 3

        # the trainer restarts: new publisher instance, DIVERGED state
        # (resumed from a checkpoint two steps back)
        t2 = _drift(_tree(0), 99)
        pub2 = WeightPublisher(s, keyframe_every=100, register=False)
        pub2.publish({"params": t2}, 10)
        assert pub2.generation == 4  # adopted head 3, not restarted at 1
        t2 = _drift(t2, 100)
        pub2.publish({"params": t2}, 11)  # a delta on the NEW chain

        out = sub.poll()
        assert out is not None and sub.generation == 5
        # bit-identical to the NEW publisher's reconstruction — the old
        # chain's trees never contaminated the result
        np.testing.assert_array_equal(
            out["dense"]["kernel"],
            np.asarray(pub2.reconstruction()["dense"]["kernel"]))
        # and the DEAD chain was GC'd, not leaked: gens 1-3 retired once
        # the new keyframe (gen 4) superseded them
        assert s.live_keys("/serving/manifest/") == [
            "/serving/manifest/4", "/serving/manifest/5"]
        assert s.live_keys("/serving/chunks/1/") == []
        s.close()

    def test_maybe_publish_cadence_and_swallow(self):
        s = KVStoreServer()
        pub = WeightPublisher(s, publish_every=3, register=False)
        assert pub.maybe_publish({"params": _tree(0)}, 1) is None
        assert pub.maybe_publish({"params": _tree(0)}, 3) == 1
        assert pub.maybe_publish({"params": _tree(0)}, 3) is None  # dedup
        s.close()
        # a dead KV makes maybe_publish log-and-continue, not raise
        from horovod_tpu.resilience.retry import RetryPolicy

        dead = KVStoreClient("127.0.0.1", 1, retry_policy=RetryPolicy(
            max_attempts=1, base_delay=0.0, deadline=0.2))
        pub2 = WeightPublisher(
            dead, publish_every=1, register=False,
            retry_policy=RetryPolicy(
                max_attempts=1, base_delay=0.0, deadline=0.2))
        assert pub2.maybe_publish({"params": _tree(0)}, 1) is None
        assert metrics.value("serving_publish_failures") == 1.0


# ------------------------------------------------------------- subscriber


@pytest.mark.serving
class TestSubscriber:
    def _published(self, n=3, keyframe_every=8, server=None):
        s = server or KVStoreServer()
        pub = WeightPublisher(
            s, keyframe_every=keyframe_every, register=False)
        t = _tree(0)
        trees = []
        for i in range(1, n + 1):
            t = _drift(t, i)
            pub.publish({"params": t}, i)
            trees.append(t)
        return s, pub, trees

    def test_poll_semantics(self):
        s, pub, trees = self._published(3)
        sub = WeightSubscriber(s)
        out = sub.poll()
        assert out is not None and sub.generation == 3 and sub.step == 3
        assert sub.poll() is None  # nothing new
        assert sub.lag() == 0
        assert sub.weights() is out
        s.close()

    def test_no_publication_yet(self):
        s = KVStoreServer()
        sub = WeightSubscriber(s)
        assert sub.poll() is None
        assert sub.generation == 0 and sub.staleness_seconds() is None
        s.close()

    def test_corrupt_chunk_never_applied_then_recovers(self):
        """A CRC-failing chunk (torn read, bitrot) is NEVER applied: the
        poll degrades to the old generation; once the bytes read clean
        again (transient corruption) the next poll advances normally."""
        s, pub, trees = self._published(2)
        sub = WeightSubscriber(s)
        sub.poll()
        t = _drift(trees[-1], 3)
        pub.publish({"params": t}, 3)
        key = "/serving/chunks/3/0"
        orig = s.get(key)
        s.put(key, b"garbage" + orig)
        assert sub.poll() is None
        assert sub.generation == 2 and sub.lag() == 1  # degraded, not torn
        assert metrics.value("serving_subscribe_errors") == 1.0
        s.put(key, orig)  # the re-read comes back clean
        out = sub.poll()
        assert out is not None and sub.generation == 3
        np.testing.assert_array_equal(
            out["dense"]["kernel"],
            np.asarray(pub.reconstruction()["dense"]["kernel"]))
        s.close()

    def test_lagging_past_gc_resyncs(self):
        """A subscriber that stalls while GC retires its position recovers
        through the keyframe — and serves bit-identical state."""
        s, pub, trees = self._published(2, keyframe_every=3)
        sub = WeightSubscriber(s)
        sub.poll()
        assert sub.generation == 2
        t = trees[-1]
        for i in range(3, 9):  # keyframes at 4, 7; GC retires 2,3
            t = _drift(t, i)
            pub.publish({"params": t}, i)
        out = sub.poll()
        assert out is not None and sub.generation == 8
        np.testing.assert_array_equal(
            out["dense"]["kernel"],
            np.asarray(pub.reconstruction()["dense"]["kernel"]))
        s.close()

    def test_partial_apply_still_returns_progress(self):
        """Review-found: gen2 applies, gen3 is corrupt and resync fails —
        the poll must hand the caller the gen2 tree it COMMITTED (the
        watermark already moved to gen2's publish time), not None."""
        s, pub, trees = self._published(1)
        sub = WeightSubscriber(s)
        sub.poll()
        t2 = _drift(trees[-1], 2)
        pub.publish({"params": t2}, 2)
        t3 = _drift(t2, 3)
        pub.publish({"params": t3}, 3)
        # corrupt gen 3 AND the keyframe so resync cannot win either
        s.put("/serving/chunks/3/0", b"xx")
        s.delete("/serving/chunks/1/0")
        out = sub.poll()
        assert out is not None  # gen 2 committed during this poll
        assert sub.generation == 2 and sub.lag() == 1
        np.testing.assert_allclose(
            out["dense"]["kernel"], t2["dense"]["kernel"], atol=2e-4)
        assert sub.poll() is None  # no further progress possible
        s.close()

    def test_publish_error_contract_covers_encode(self):
        """Review-found: a state whose published tree STRUCTURE changed
        between publishes must not escape maybe_publish as a raw
        TypeError/ValueError — the publisher re-roots with a keyframe (a
        delta against a mismatched base is meaningless)."""
        s = KVStoreServer()
        pub = WeightPublisher(s, keyframe_every=100, register=False)
        pub.publish({"params": {"w": np.ones(2048, np.float32)}}, 1)
        # the tree gains a leaf: delta encode fails → keyframe re-root
        grown = {"w": np.ones(2048, np.float32),
                 "b": np.zeros(4, np.float32)}
        gen = pub.publish({"params": grown}, 2)
        assert gen == 2 and pub.keyframe_generation == 2
        sub = WeightSubscriber(s)
        sub.poll()
        assert sub.generation == 2
        np.testing.assert_array_equal(sub.weights()["b"], grown["b"])
        s.close()

    def test_keyframe_unreachable_keeps_serving_stale(self):
        """Even the resync path failing must not crash the serving
        process: the old generation keeps serving and staleness grows."""
        s, pub, trees = self._published(2)
        sub = WeightSubscriber(s)
        sub.poll()
        t = _drift(trees[-1], 9)
        pub.publish({"params": t}, 3)
        # destroy the chain AND the keyframe: delta 3 corrupt, keyframe gone
        s.put("/serving/chunks/3/0", b"xx")
        s.delete("/serving/chunks/1/0")
        assert sub.poll() is None
        assert sub.generation == 2  # still serving the old weights
        assert sub.lag() == 1
        assert metrics.value("serving_subscribe_errors") == 1.0
        s.close()

    def test_staleness_watermark(self):
        s, pub, trees = self._published(1)
        sub = WeightSubscriber(s, stale_after=0.05)
        assert sub.stale()  # nothing applied yet
        sub.poll()
        assert not sub.stale()
        time.sleep(0.08)
        assert sub.stale()  # trainer went quiet past the watermark
        assert sub.staleness_seconds() > 0.05
        # a fresh publication un-stales on the next poll
        pub.publish({"params": _drift(trees[-1], 5)}, 2)
        sub.poll()
        assert not sub.stale()
        s.close()

    @pytest.mark.chaos
    def test_subscriber_stall_chaos_delays_poll(self):
        s, pub, trees = self._published(1)
        sub = WeightSubscriber(s)
        chaos.configure("subscriber_stall=0.05")
        t0 = time.monotonic()
        sub.poll()
        assert time.monotonic() - t0 >= 0.05
        assert metrics.value(
            "resilience_chaos_injected", site="subscriber_stall") >= 1.0
        s.close()
        chaos.configure(None)

    def test_http_transport_roundtrip(self):
        """The real deployment shape: subscriber in another process via
        HTTP + HMAC, served by the launcher's KV server."""
        s = KVStoreServer(secret="sek")
        port = s.start()
        client = KVStoreClient("127.0.0.1", port, secret="sek")
        pub = WeightPublisher(client, chunk_bytes=1024, register=False)
        t = _tree(0)
        pub.publish({"params": t}, 1)
        t2 = _drift(t, 1)
        pub.publish({"params": t2}, 2)
        sub = subscribe_weights("127.0.0.1", port, secret="sek")
        out = sub.wait_for_generation(2, timeout=10)
        np.testing.assert_allclose(
            out["dense"]["kernel"], t2["dense"]["kernel"], atol=2e-4)
        assert sub.step == 2
        s.close()

    def test_subscribe_weights_arg_validation(self):
        with pytest.raises(ValueError):
            subscribe_weights()
        with pytest.raises(ValueError):
            subscribe_weights("h", 1, store=KVStoreServer())


# ------------------------------------------------- preemption drain flush


@pytest.mark.serving
@pytest.mark.chaos
class TestPreemptFlush:
    def test_sigterm_drain_flushes_final_generation(self):
        """The satellite: SIGTERM → drain → final publication → emergency
        checkpoint. Subscribers hold the last good weights across the
        restart gap."""
        s = KVStoreServer()
        pub = WeightPublisher(s, publish_every=10)  # registered
        try:
            chaos.configure("sigterm_at_step=3")

            def step_fn(state, i):
                return {"params": {"w": state["params"]["w"] + 1.0}}

            with pytest.raises(loop.Preempted) as ei:
                loop.run(
                    step_fn, {"params": {"w": np.zeros(3, np.float32)}},
                    num_steps=100)
            assert ei.value.step == 3
            sub = WeightSubscriber(s)
            out = sub.poll()
            assert out is not None
            np.testing.assert_array_equal(out["w"], [3.0, 3.0, 3.0])
            assert metrics.value("serving_final_flushes") == 1.0
        finally:
            chaos.configure(None)
            s.close()

    def test_flush_failure_never_blocks_checkpoint(self, tmp_path):
        """A dead serving KV must not eat the preemption grace window or
        the emergency checkpoint."""
        from horovod_tpu.resilience.retry import RetryPolicy

        dead = KVStoreClient("127.0.0.1", 1, retry_policy=RetryPolicy(
            max_attempts=1, base_delay=0.0, deadline=0.2))
        from horovod_tpu.serving import active_publishers

        pub = WeightPublisher(
            dead, retry_policy=RetryPolicy(
                max_attempts=1, base_delay=0.0, deadline=0.2))
        assert pub in active_publishers()
        chaos.configure("sigterm_at_step=2")

        def step_fn(state, i):
            return {"params": {"w": state["params"]["w"] + 1.0}}

        ckpt = str(tmp_path / "ck")
        t0 = time.monotonic()
        with pytest.raises(loop.Preempted) as ei:
            loop.run(
                step_fn, {"params": {"w": np.zeros(2, np.float32)}},
                num_steps=100, checkpoint_dir=ckpt)
        assert time.monotonic() - t0 < 10
        assert ei.value.checkpoint_path is not None  # checkpoint still won
        assert metrics.value("serving_final_flushes") is None
        chaos.configure(None)


# ------------------------------------------------------------ fit callback


@pytest.mark.serving
def test_publish_callback_cadence_and_train_end():
    from horovod_tpu.callbacks import PublishCallback

    s = KVStoreServer()
    pub = WeightPublisher(s, register=False)
    cb = PublishCallback(pub, every=2)

    class Trainer:
        params = {"w": np.arange(4, dtype=np.float32)}

    cb.set_trainer(Trainer())
    for b in range(5):  # publishes after batches 2 and 4
        cb.on_batch_end(b)
        Trainer.params = {"w": Trainer.params["w"] + 1}
    assert pub.generation == 2
    cb.on_train_end()  # batch 5 unpublished → final flush
    assert pub.generation == 3
    sub = WeightSubscriber(s)
    out = sub.poll()
    np.testing.assert_array_equal(out["w"], np.arange(4) + 5.0)
    with pytest.raises(ValueError):
        PublishCallback(pub, every=0)
    s.close()


# --------------------------------------------------- e2e acceptance (mesh)


def _tiny_model():
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(2)(x)

    return Tiny()


def _batch_for(step, n=48):
    rng = np.random.RandomState(step)
    x = rng.rand(n, 8).astype(np.float32)
    y = (x.sum(axis=1) > 4).astype(np.int64)
    return x, y


def _make_builder(model):
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.training import (
        make_shardmap_train_step, shard_batch, softmax_xent,
    )

    def step_builder(world):
        tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
        step = make_shardmap_train_step(
            model, tx, loss_fn=softmax_xent, shard_optimizer=True,
            instrument=False)

        def step_fn(state, i):
            x, y = _batch_for(i)
            p, _, os_, loss = step(
                state["params"], {}, state["opt_state"],
                shard_batch(x), shard_batch(y))
            return {"params": p, "opt_state": os_}

        return step_fn

    return step_builder


def _fresh_state(model):
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.training import replicate

    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
    params = replicate(jax.tree_util.tree_map(jnp.array, params0))
    return {"params": params, "opt_state": tx.init(params)}


@pytest.mark.serving
@pytest.mark.chaos
@pytest.mark.elastic
def test_publish_subscribe_roundtrip_with_chaos_and_shrink():
    """THE acceptance pin. An 8-rank trainer publishes every committed
    step under ``publish_fail=1,kv_restart_at_step=3`` with an elastic
    8→6 shrink at step 3's boundary. The KV has no WAL, so the restart
    wipes it — the publisher re-roots with a keyframe and the subscriber
    resyncs. Every generation the subscriber applies reconstructs the
    trainer's consolidated weights; the final one is allclose to the final
    trained params."""
    import jax

    import horovod_tpu as hvd
    from horovod_tpu.resilience import elastic
    from horovod_tpu.training import host_snapshot

    model = _tiny_model()
    builder = _make_builder(model)
    server = KVStoreServer()
    pub = WeightPublisher(server, keyframe_every=100, register=False)
    sub = WeightSubscriber(server)
    coord = elastic.ElasticCoordinator(server=server)

    chaos.configure(
        "publish_fail=1,kv_restart_at_step=3,rank_fail=2,rank_fail_at_step=3")
    hvd.init()
    try:
        state = _fresh_state(model)
        final = elastic.run(
            builder, state, num_steps=5, snapshot_every=1,
            coordinator=coord, publisher=pub, publish_every=1)
        assert hvd.size() == 6  # shrunk, no rejoin armed

        # every armed charge fired exactly once
        for site in ("publish_fail", "kv_restart_at_step", "rank_fail"):
            assert metrics.value(
                "resilience_chaos_injected", site=site) == 1.0, site

        # >= 5 generations: steps 1..5 plus the post-resize republish
        assert pub.generation >= 5
        assert metrics.value(
            "serving_publish_generations", kind="delta") >= 2.0
        # the restart re-rooted the chain mid-run
        assert 1 < pub.keyframe_generation <= pub.generation

        tree = sub.wait_for_generation(pub.generation, timeout=10)
        assert sub.lag() == 0
        want = host_snapshot(final["params"])
        for got, w in zip(jax.tree_util.tree_leaves(tree),
                          jax.tree_util.tree_leaves(want)):
            np.testing.assert_allclose(got, w, atol=1e-3)
        # bit-identical to the publisher's tracked reconstruction
        for got, w in zip(
                jax.tree_util.tree_leaves(tree),
                jax.tree_util.tree_leaves(pub.reconstruction())):
            np.testing.assert_array_equal(got, w)
    finally:
        hvd.shutdown()
        coord.close()
        server.close()
        chaos.configure(None)


@pytest.mark.serving
@pytest.mark.slow
def test_twenty_generation_soak_with_wal_restarts():
    """Soak: 24 generations with a WAL'd KV restarted every 8 publishes;
    the chain survives every restart (no re-root needed) and a subscriber
    polling at arbitrary cadence ends bit-identical to the publisher."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        s = KVStoreServer(wal_path=os.path.join(d, "kv.wal"))
        pub = WeightPublisher(s, keyframe_every=5, register=False)
        sub = WeightSubscriber(s)
        t = _tree(0)
        for i in range(1, 25):
            t = _drift(t, i)
            pub.publish({"params": t}, i)
            if i % 8 == 0:
                s.restart()
            if i % 3 == 0:
                sub.poll()
        sub.poll()
        assert sub.generation == pub.generation == 24
        import jax

        for got, w in zip(
                jax.tree_util.tree_leaves(sub.weights()),
                jax.tree_util.tree_leaves(pub.reconstruction())):
            np.testing.assert_array_equal(got, w)
        s.close()


@pytest.mark.serving
@pytest.mark.slow
def test_bench_publish_ab_rung():
    """bench.py --publish-ab emits one JSON line whose measured wire-byte
    gauges equal the analytic byte model exactly."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--publish-ab", "--iters", "5", "--no-probe"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    d = json.loads(line)
    assert d["metric"] == "publish_ab_step_ratio"
    if d.get("skipped"):
        assert d["byte_model"]["delta_ratio_vs_checkpoint"] < 0.3
    else:
        assert d["publish_wire_bytes"]["key"] == \
            d["byte_model"]["keyframe_bytes"]
        assert d["publish_wire_bytes"]["delta"] == \
            d["byte_model"]["delta_bytes"]
        assert d["generations"] == d["subscriber_generation"]
