"""ZeRO-1 sharded gradient sync + sharded optimizer state
(``DistributedOptimizer(shard_optimizer=True)``).

The acceptance property: on the 8-device CPU mesh the sharded path's
parameter trajectory must match the allreduce path's over >= 10 steps
within fp tolerance — including with fp16 compression + error feedback —
while moving ~half the gradient bytes and cutting per-rank moment HBM by N.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.compression import Compression
from horovod_tpu.ops.collective import _smap, allreduce, Average


def _params():
    rng = np.random.RandomState(0)
    return {
        "w": jnp.asarray(rng.randn(5, 3).astype(np.float32) * 0.1),
        "b": jnp.zeros((7,), jnp.float32),
    }


def _data(n):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2 * n, 5), jnp.float32)
    y = jnp.asarray(rng.randn(2 * n, 3), jnp.float32)
    return x, y


def _loss(p, x, y):
    pred = x @ p["w"] + p["b"][:3][None]
    return jnp.mean((pred - y) ** 2)


def _make_step(hvd, dtx, opt_spec, ax):
    """Manual explicit-collective step over the optimizer surface: grads
    stay per-shard; the DistributedOptimizer performs the exchange."""
    mesh = hvd.mesh()

    def step(params, opt_state, x, y):
        l, grads = jax.value_and_grad(_loss)(params, x, y)
        upd, opt_state = dtx.update(grads, opt_state, params)
        params = optax.apply_updates(params, upd)
        return params, opt_state, allreduce(l, Average, axis=ax)

    return jax.jit(_smap(
        step, mesh, (P(), opt_spec, P(ax), P(ax)), (P(), opt_spec, P())
    ))


def test_sharded_matches_allreduce_trajectory(hvd):
    """Tentpole equivalence: 12 Adam steps, sharded vs allreduce, same
    data — parameter trajectories must agree to fp tolerance."""
    from horovod_tpu.training import shard_batch

    ax = hvd.data_axis()
    params = _params()
    x, y = _data(hvd.size())
    xs, ys = shard_batch(x), shard_batch(y)

    tx_ar = hvd.DistributedOptimizer(optax.adam(1e-2))
    tx_sh = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
    p_a = jax.tree_util.tree_map(jnp.array, params)
    p_b = jax.tree_util.tree_map(jnp.array, params)
    s_a, s_b = tx_ar.init(p_a), tx_sh.init(p_b)
    step_a = _make_step(hvd, tx_ar, P(), ax)
    step_b = _make_step(hvd, tx_sh, P(ax), ax)
    for _ in range(12):
        p_a, s_a, l_a = step_a(p_a, s_a, xs, ys)
        p_b, s_b, l_b = step_b(p_b, s_b, xs, ys)
    np.testing.assert_allclose(float(l_a), float(l_b), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p_a[k]), np.asarray(p_b[k]), rtol=2e-5, atol=1e-6)


def test_sharded_state_is_sharded_and_smaller(hvd):
    """Moment leaves carry a leading rank axis laid out P(data): per-rank
    shard HBM is 1/N of the replicated moments."""
    n = hvd.size()
    params = _params()
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
    state = tx.init(params)
    adam = state[0]
    total = sum(
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    padded = total + ((-total) % n)
    assert adam.mu["float32"].shape == (n, padded // n)
    assert adam.nu["float32"].shape == (n, padded // n)
    assert adam.count.shape == (n,)
    sh = adam.mu["float32"].sharding
    assert isinstance(sh, NamedSharding) and sh.spec[0] == hvd.data_axis()


def test_sharded_with_fp16_error_feedback_matches_simulation(hvd):
    """With fp16 compression + error feedback the sharded trajectory must
    match a pure-python per-rank simulation of the allreduce-EF wire
    (corrected = g + residual; wire carries bf16(corrected); residual keeps
    the rounding error) — the allreduce path's math, rank by rank."""
    from horovod_tpu.training import shard_batch

    ax = hvd.data_axis()
    n = hvd.size()
    params = _params()
    x, y = _data(n)
    xs, ys = shard_batch(x), shard_batch(y)

    tx = hvd.DistributedOptimizer(
        optax.sgd(0.1), shard_optimizer=True,
        compression=Compression.fp16, error_feedback=True)
    p_sh = jax.tree_util.tree_map(jnp.array, params)
    s_sh = tx.init(p_sh)
    step = _make_step(hvd, tx, P(ax), ax)

    # reference: simulate every rank of the allreduce-EF exchange
    def roundtrip(v):
        return np.asarray(
            jnp.asarray(v).astype(jnp.bfloat16).astype(jnp.float32))

    p_ref = jax.tree_util.tree_map(lambda v: np.asarray(v).copy(), params)
    res = [
        {k: np.zeros_like(v) for k, v in p_ref.items()} for _ in range(n)
    ]
    xn = np.asarray(x).reshape(n, 2, 5)
    yn = np.asarray(y).reshape(n, 2, 3)
    steps = 10
    for _ in range(steps):
        pj = {k: jnp.asarray(v) for k, v in p_ref.items()}
        gs = [
            jax.tree_util.tree_map(
                np.asarray,
                jax.grad(_loss)(pj, jnp.asarray(xn[r]), jnp.asarray(yn[r])),
            )
            for r in range(n)
        ]
        for k in p_ref:
            contrib = []
            for r in range(n):
                c = gs[r][k] + res[r][k]
                w = roundtrip(c)
                res[r][k] = c - w
                contrib.append(w)
            p_ref[k] = p_ref[k] - 0.1 * np.mean(contrib, axis=0)

    for _ in range(steps):
        p_sh, s_sh, _ = step(p_sh, s_sh, xs, ys)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p_sh[k]), p_ref[k], rtol=5e-3, atol=5e-5)


def test_grad_sync_bytes_sharded_half_of_allreduce(hvd):
    """grad_sync_bytes_per_step: sharded mode must report exactly half the
    allreduce mode's gradient bytes for the same model (modulo padding)."""
    from horovod_tpu.training import shard_batch

    hvd.metrics.reset()
    ax = hvd.data_axis()
    n = hvd.size()
    params = _params()
    x, y = _data(n)
    xs, ys = shard_batch(x), shard_batch(y)
    for sharded in (False, True):
        tx = hvd.DistributedOptimizer(
            optax.sgd(0.1), shard_optimizer=sharded)
        p = jax.tree_util.tree_map(jnp.array, params)
        s = tx.init(p)
        step = _make_step(hvd, tx, P(ax) if sharded else P(), ax)
        step(p, s, xs, ys)
    ar = hvd.metrics.value("grad_sync_bytes_per_step", mode="allreduce")
    sh = hvd.metrics.value("grad_sync_bytes_per_step", mode="sharded")
    ag = hvd.metrics.value("param_gather_bytes_per_step", mode="sharded")
    assert ar and sh and ag
    total = sum(int(np.prod(p.shape))
                for p in jax.tree_util.tree_leaves(params))
    padded = total + ((-total) % n)
    ring = (n - 1) / n
    assert ar == pytest.approx(2 * ring * 4 * total)
    assert sh == pytest.approx(ring * 4 * padded)
    assert ag == pytest.approx(ring * 4 * padded)
    assert sh <= 0.55 * ar  # the headline: gradient bytes ~halve


def test_builder_threads_sharded_path(hvd):
    """make_shardmap_train_step(shard_optimizer=True) trains a real flax
    model to the same trajectory as the plain allreduce builder."""
    import flax.linen as nn

    from horovod_tpu.training import (
        init_model, make_shardmap_train_step, replicate, shard_batch,
        softmax_xent,
    )

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    n = hvd.size()
    model = MLP()
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 6), jnp.float32)
    params0, _ = init_model(model, rng, sample)
    xs = shard_batch(np.random.RandomState(0).rand(2 * n, 6).astype(np.float32))
    ys = shard_batch(np.random.RandomState(1).randint(0, 4, 2 * n))

    def run(sharded):
        if sharded:
            tx = hvd.DistributedOptimizer(
                optax.adam(1e-2), shard_optimizer=True)
            step = make_shardmap_train_step(
                model, tx, loss_fn=softmax_xent, shard_optimizer=True,
                instrument=False)
        else:
            tx = optax.adam(1e-2)
            step = make_shardmap_train_step(
                model, tx, loss_fn=softmax_xent, instrument=False)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        opt_state = tx.init(params)
        if not sharded:
            opt_state = replicate(opt_state)
        stats = {}
        for _ in range(10):
            params, stats, opt_state, loss = step(
                params, stats, opt_state, xs, ys)
        return params, float(loss)

    p_a, l_a = run(False)
    p_b, l_b = run(True)
    assert l_a == pytest.approx(l_b, rel=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        p_a, p_b,
    )


def test_env_flag_enables_sharding(hvd, monkeypatch):
    monkeypatch.setenv("HOROVOD_SHARD_OPTIMIZER", "1")
    tx = hvd.DistributedOptimizer(optax.adam(1e-2))
    state = tx.init(_params())
    assert state[0].mu["float32"].ndim == 2  # [N, shard] — sharded layout


def test_eager_sharded_update_matches_allreduce(hvd):
    """Eager (no jit) sharded update: replicated and stacked per-rank
    gradients both produce the allreduce path's updates."""
    n = hvd.size()
    params = {"w": jnp.ones(4)}
    tx = hvd.DistributedOptimizer(optax.sgd(1.0), shard_optimizer=True)
    state = tx.init(params)
    # stacked per-rank grads (the eager single-controller per-rank model)
    g = np.stack([np.full(4, float(r)) for r in range(n)]).astype(np.float32)
    grads = {
        "w": jax.device_put(
            g, NamedSharding(hvd.mesh(), P(hvd.data_axis())))
    }
    upd, state = tx.update(grads, state, params)
    np.testing.assert_allclose(np.asarray(upd["w"]), -g.mean(axis=0),
                               rtol=1e-6)
    # replicated grads
    upd, state = tx.update({"w": jnp.full((4,), 2.0)}, state, params)
    np.testing.assert_allclose(np.asarray(upd["w"]), -2.0, rtol=1e-6)


def test_shard_optimizer_rejects_adasum(hvd):
    with pytest.raises(ValueError, match="Adasum"):
        hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Adasum, shard_optimizer=True)


def test_checkpoint_roundtrip_across_world_size(hvd, tmp_path):
    """Sharded moments survive save -> restore -> reshard to a different
    world size and back; updates continue identically."""
    from horovod_tpu import checkpoint

    params = _params()
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
    state = tx.init(params)
    g = {"w": jnp.full((5, 3), 0.5), "b": jnp.full((7,), -0.25)}
    for _ in range(3):
        _, state = tx.update(g, state, params)

    checkpoint.save(str(tmp_path), 7, {"opt": state, "params": params})
    loaded = checkpoint.restore(str(tmp_path), 7)

    st4 = hvd.reshard_optimizer_state(loaded["opt"], params, to_size=4)
    assert st4[0].mu["float32"].shape[0] == 4
    st8 = checkpoint.consolidate_opt_state(st4, params, to_size=8)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(st8)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    u1, _ = tx.update(g, state, params)
    u2, _ = tx.update(g, st8, params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(u1[k]), np.asarray(u2[k]), rtol=1e-6)


def test_reshard_preserves_ef_residual_mass(hvd):
    """Error-feedback residuals consolidate mass-preserving across a
    world-size change: the summed residual (total untransmitted gradient
    mass) is invariant."""
    params = _params()
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.1), shard_optimizer=True,
        compression=Compression.fp16, error_feedback=True)
    state = tx.init(params)
    g = {"w": jnp.full((5, 3), 1.0 + 2e-3), "b": jnp.full((7,), 1.0 + 2e-3)}
    for _ in range(2):
        _, state = tx.update(g, state, params)
    mass = {k: np.asarray(v).sum(axis=0)
            for k, v in state.residual.items()}
    assert any(np.abs(m).max() > 0 for m in mass.values())
    st4 = hvd.reshard_optimizer_state(state, params, to_size=4)
    for k, v in st4.residual.items():
        assert v.shape[0] == 4
        L = mass[k].shape[0]
        np.testing.assert_allclose(
            np.asarray(v).sum(axis=0)[:L], mass[k][:L], rtol=1e-5, atol=1e-7)


def _uneven_params():
    """25 fp32 elements: divisible by NEITHER 8 nor 6, with different
    padded lengths per world size (Lp8=32, Lp6=30) — the packing-sensitive
    case for cross-size consolidation."""
    rng = np.random.RandomState(3)
    return {
        "w": jnp.asarray(rng.randn(5, 3).astype(np.float32) * 0.1),
        "b": jnp.zeros((7,), jnp.float32),
        "v": jnp.asarray(rng.randn(3).astype(np.float32)),
    }


def test_reshard_uneven_8_6_8_roundtrip(hvd):
    """Uneven shards (satellite): param count 25 divides neither 8 nor 6,
    and the two world sizes pad to different flat lengths. The 8→6→8
    roundtrip must reproduce the original state exactly and updates must
    continue identically — the elastic shrink/regrow path depends on it."""
    from horovod_tpu import checkpoint

    params = _uneven_params()
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
    state = tx.init(params)
    g = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 0.3), params)
    for _ in range(3):
        _, state = tx.update(g, state, params)

    st6 = hvd.reshard_optimizer_state(state, params, to_size=6)
    assert st6[0].mu["float32"].shape == (6, 5)  # ceil(25/6)=5
    # the 6-way state is usable, not just storable: Adam's count re-tiles
    assert st6[0].count.shape == (6,)
    st8 = checkpoint.consolidate_opt_state(st6, params, to_size=8)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(st8)):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    u1, _ = tx.update(g, state, params)
    u2, _ = tx.update(g, st8, params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(u1[k]), np.asarray(u2[k]), rtol=1e-6)


def test_reshard_uneven_ef_residual_mass_8_6_8(hvd):
    """fp16 + error feedback across 8→6→8 on uneven shards: the summed
    residual (total untransmitted gradient mass) is invariant at every
    stop, so no gradient signal is created or destroyed by the resizes."""
    params = _uneven_params()
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.1), shard_optimizer=True,
        compression=Compression.fp16, error_feedback=True)
    state = tx.init(params)
    g = jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, 1.0 + 2e-3), params)
    for _ in range(2):
        _, state = tx.update(g, state, params)
    mass = {k: np.asarray(v).sum(axis=0)
            for k, v in state.residual.items()}
    assert any(np.abs(m).max() > 0 for m in mass.values())

    st6 = hvd.reshard_optimizer_state(state, params, to_size=6)
    for k, v in st6.residual.items():
        assert v.shape == (6, 30)  # pad(25, 6)
        L = 25
        np.testing.assert_allclose(
            np.asarray(v).sum(axis=0)[:L], mass[k][:L],
            rtol=1e-5, atol=1e-7)
    st8 = hvd.reshard_optimizer_state(st6, params, to_size=8)
    for k, v in st8.residual.items():
        assert v.shape == (8, 32)  # pad(25, 8)
        L = 25
        np.testing.assert_allclose(
            np.asarray(v).sum(axis=0)[:L], mass[k][:L],
            rtol=1e-5, atol=1e-7)
    # the roundtripped state still trains: one more sharded update runs
    _, st8b = tx.update(g, st8, params)
    assert isinstance(st8b.residual, dict)


def test_numerics_guard_state_reshard_8_4_8_roundtrip(hvd, tmp_path):
    """Satellite (ISSUE 9): the numerics-guard wrapper state — EWMA,
    loss scale, counters — threads through save → restore → reshard
    8→4→8 like ``_EFState``: the inner sharded moments + EF residuals
    re-pack, the guard scalars ride through untouched, and updates
    continue identically."""
    from horovod_tpu import checkpoint
    from horovod_tpu.resilience import numerics

    params = _params()
    tx = hvd.DistributedOptimizer(
        optax.adam(1e-2), shard_optimizer=True,
        compression=Compression.fp16, error_feedback=True,
        numerics_guard=True, loss_scale=8.0)
    state = tx.init(params)
    assert isinstance(state, numerics.NumericsGuardState)
    g = {"w": jnp.full((5, 3), 8.0 * 0.5), "b": jnp.full((7,), -8.0 * 0.25)}
    for _ in range(3):
        _, state = tx.update(g, state, params)
    v0 = numerics.verdict(state)
    assert v0["count"] == 3 and v0["loss_scale"] == 8.0

    checkpoint.save(str(tmp_path), 7, {"opt": state, "params": params})
    loaded = checkpoint.restore(str(tmp_path), 7)
    st4 = hvd.reshard_optimizer_state(loaded["opt"], params, to_size=4)
    assert isinstance(st4, numerics.NumericsGuardState)
    assert st4.inner.inner[0].mu["float32"].shape[0] == 4
    assert st4.inner.residual["float32"].shape[0] == 4
    # guard scalars are world-size independent: bit-equal through 8→4
    # (the per-rank fingerprint vector is diagnostic and re-inits at the
    # new size — everything else carries over exactly)
    v4 = numerics.verdict(st4)
    assert len(v4.pop("rank_norms")) == 4
    v0_scalar = dict(v0)
    v0_scalar.pop("rank_norms")
    assert v4 == v0_scalar
    st8 = checkpoint.consolidate_opt_state(st4, params, to_size=8)
    for a, b in zip(jax.tree_util.tree_leaves(state.inner.inner),
                    jax.tree_util.tree_leaves(st8.inner.inner)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    u1, _ = tx.update(g, state, params)
    u2, _ = tx.update(g, st8, params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(u1[k]), np.asarray(u2[k]), rtol=1e-6)


def test_broadcast_optimizer_state_threads_guard_scalars(hvd):
    """Satellite (ISSUE 9): broadcast_optimizer_state over a guarded
    sharded state still skips the [N, shard] moment leaves while the
    guard's replicated scalars broadcast cleanly."""
    from horovod_tpu.resilience import numerics

    hvd.metrics.reset()
    params = _params()
    tx = hvd.DistributedOptimizer(
        optax.adam(1e-2), shard_optimizer=True, numerics_guard=True)
    state = tx.init(params)
    g = {"w": jnp.full((5, 3), 0.5), "b": jnp.full((7,), -0.25)}
    _, state = tx.update(g, state, params)
    out = hvd.broadcast_optimizer_state(state)
    assert isinstance(out, numerics.NumericsGuardState)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(out)):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert hvd.metrics.value("broadcast_optimizer_state_sharded_skipped")
    assert numerics.verdict(out) == numerics.verdict(state)


def test_broadcast_optimizer_state_skips_sharded_leaves(hvd):
    """Sharded moment shards are per-rank state: broadcast must leave them
    untouched instead of blowing root's shard into every rank."""
    hvd.metrics.reset()
    params = _params()
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
    state = tx.init(params)
    g = {"w": jnp.full((5, 3), 0.5), "b": jnp.full((7,), -0.25)}
    _, state = tx.update(g, state, params)
    out = hvd.broadcast_optimizer_state(state)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(out)):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert hvd.metrics.value("broadcast_optimizer_state_sharded_skipped")
    # replicated state still broadcasts normally
    plain = hvd.DistributedOptimizer(optax.adam(1e-2))
    st = plain.init(params)
    out = hvd.broadcast_optimizer_state(st)
    for a, b in zip(jax.tree_util.tree_leaves(st),
                    jax.tree_util.tree_leaves(out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_sharded_global_jit_path(hvd):
    """Unbound global-jit (pjit) mode: the sharded update matches the
    allreduce optimizer on replicated gradients, and the [N, shard] state
    layout persists through the jitted step."""
    params = _params()
    tx_sh = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
    tx_ar = hvd.DistributedOptimizer(optax.adam(1e-2))
    s_sh, s_ar = tx_sh.init(params), tx_ar.init(params)
    g = {"w": jnp.full((5, 3), 0.5), "b": jnp.full((7,), -0.25)}

    @jax.jit
    def step(p, s, gg):
        u, s = tx_sh.update(gg, s, p)
        return optax.apply_updates(p, u), s

    p_sh, s_sh = step(params, s_sh, g)
    u_ar, _ = tx_ar.update(g, s_ar, params)
    p_ar = optax.apply_updates(params, u_ar)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p_sh[k]), np.asarray(p_ar[k]), rtol=1e-5, atol=1e-7)
    assert s_sh[0].mu["float32"].shape[0] == hvd.size()


def test_mixed_dtype_sharded_update(hvd):
    """A mixed f32/bf16 param tree packs into one flat buffer per dtype and
    round-trips the sharded update with dtypes and shapes preserved."""
    params = {
        "a": jnp.ones((3, 2), jnp.float32),
        "b": jnp.ones((5,), jnp.bfloat16),
        "c": jnp.ones((2, 2), jnp.float32),
    }
    tx = hvd.DistributedOptimizer(optax.sgd(1.0), shard_optimizer=True)
    state = tx.init(params)
    g = jax.tree_util.tree_map(lambda p: jnp.full(p.shape, 0.5, p.dtype),
                               params)
    upd, state = tx.update(g, state, params)
    for k, p in params.items():
        assert upd[k].dtype == p.dtype and upd[k].shape == p.shape
        np.testing.assert_allclose(
            np.asarray(upd[k], np.float32), -0.5, rtol=1e-2)


def test_consolidate_is_safe_on_plain_state(hvd):
    """consolidate_opt_state / reshard_optimizer_state must pass plain
    (non-sharded) optimizer states through untouched — 1-D moment leaves
    (e.g. a bias moment) must never be misread as per-rank scalars."""
    from horovod_tpu import checkpoint

    params = _params()  # has a 1-D [7] bias leaf
    tx = optax.adam(1e-2)
    state = tx.init(params)
    g = {"w": jnp.full((5, 3), 0.5), "b": jnp.full((7,), -0.25)}
    _, state = tx.update(g, state, params)
    out = checkpoint.consolidate_opt_state(state, params)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(out)):
        assert a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # plain error-feedback state (param-tree residual) passes through too
    dtx = hvd.DistributedOptimizer(
        optax.sgd(0.1), compression=Compression.fp16, error_feedback=True)
    st = dtx.init(params)
    _, st = dtx.update(g, st, params)
    out = checkpoint.consolidate_opt_state(st, params)
    for a, b in zip(jax.tree_util.tree_leaves(st),
                    jax.tree_util.tree_leaves(out)):
        assert a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_consolidate_same_world_size_is_noop(hvd):
    """Same-size consolidate must be a strict no-op — including the EF
    residuals (no cross-rank averaging on a plain restart)."""
    params = _params()
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.1), shard_optimizer=True,
        compression=Compression.fp16, error_feedback=True)
    state = tx.init(params)
    g = {"w": jnp.full((5, 3), 1.0 + 2e-3), "b": jnp.full((7,), 1.0 + 2e-3)}
    for _ in range(2):
        _, state = tx.update(g, state, params)
    out = hvd.reshard_optimizer_state(state, params, to_size=hvd.size())
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(out)):
        assert a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
