"""Low-bit gradient compression: int8 quantized collectives + PowerSGD
low-rank sync (``Compression.int8`` / ``Compression.powersgd(r)``).

Acceptance pins on the 8-device CPU mesh:

1. int8+EF and PowerSGD(rank=4)+EF Adam trajectories track the
   uncompressed trajectory within tolerance over >= 12 steps;
2. reported ``grad_sync_bytes_per_step`` for int8 is <= ~27% of fp32
   (incl. blockwise-scale overhead) and PowerSGD rank-4 <= 10% on the
   transformer-block tree;
3. both compose with ``shard_optimizer=True`` and survive an 8→4→8
   ``consolidate_opt_state`` reshard with EF-residual mass preserved.
"""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu.compression import (
    Compression,
    INT8_BLOCK,
    int8_roundtrip,
    quantize_blockwise,
)
from horovod_tpu.ops.collective import _smap, allreduce, Average

pytestmark = pytest.mark.compression


def _block_params():
    """A transformer-block-shaped tree: fat 2-D projections plus 1-D
    biases/layernorms — the shape mix the PowerSGD rank-4 ratio claim is
    made on."""
    rng = np.random.RandomState(0)
    d = 64

    def w(*shape):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.05)

    return {
        "attn": {"qkv": w(d, 3 * d), "proj": w(d, d),
                 "qkv_b": jnp.zeros((3 * d,), jnp.float32)},
        "mlp": {"up": w(d, 4 * d), "down": w(4 * d, d),
                "up_b": jnp.zeros((4 * d,), jnp.float32)},
        "ln": {"scale": jnp.ones((d,), jnp.float32),
               "bias": jnp.zeros((d,), jnp.float32)},
    }


#: the int8 trajectory/reshard tree: 40x30 = 1200 elements, above the
#: min-quantize floor so the wire genuinely quantizes
_INT8_SHAPE = (40, 30)
#: the PowerSGD trajectory tree: narrow enough (rank 4 of min-dim 12) that
#: a rank-4 factorization is a meaningful approximation — the regime
#: PowerSGD targets — while still truncating (rank < 12)
_PSGD_SHAPE = (16, 12)


def _small_params(shape=_INT8_SHAPE):
    rng = np.random.RandomState(1)
    din, dout = shape
    return {
        "w": jnp.asarray(rng.randn(din, dout).astype(np.float32) * 0.1),
        "b": jnp.zeros((dout,), jnp.float32),
    }


def _data(n, shape=_INT8_SHAPE):
    rng = np.random.RandomState(2)
    din, dout = shape
    x = jnp.asarray(rng.randn(2 * n, din), jnp.float32)
    y = jnp.asarray(rng.randn(2 * n, dout), jnp.float32)
    return x, y


def _loss(p, x, y):
    return jnp.mean((x @ p["w"] + p["b"][None] - y) ** 2)


def _make_step(hvd, dtx, opt_spec, ax):
    mesh = hvd.mesh()

    def step(params, opt_state, x, y):
        l, grads = jax.value_and_grad(_loss)(params, x, y)
        upd, opt_state = dtx.update(grads, opt_state, params)
        params = optax.apply_updates(params, upd)
        return params, opt_state, allreduce(l, Average, axis=ax)

    return jax.jit(_smap(
        step, mesh, (P(), opt_spec, P(ax), P(ax)), (P(), opt_spec, P())
    ))


def _run_trajectory(hvd, tx, opt_spec, steps=12, shape=_INT8_SHAPE):
    ax = hvd.data_axis()
    from horovod_tpu.training import shard_batch

    x, y = _data(hvd.size(), shape)
    xs, ys = shard_batch(x), shard_batch(y)
    p = jax.tree_util.tree_map(jnp.array, _small_params(shape))
    s = tx.init(p)
    step = _make_step(hvd, tx, opt_spec, ax)
    losses = []
    for _ in range(steps):
        p, s, l = step(p, s, xs, ys)
        losses.append(float(l))
    return p, losses


_FP32_BASELINE = {}


def _fp32_trajectory(hvd, steps=12, shape=_INT8_SHAPE):
    """The uncompressed Adam baseline several tests compare against —
    computed once per (steps, shape) (one less shard_map compile each)."""
    key = (steps, shape)
    if key not in _FP32_BASELINE:
        _FP32_BASELINE[key] = _run_trajectory(
            hvd, hvd.DistributedOptimizer(optax.adam(1e-2)), P(),
            steps=steps, shape=shape)
    return _FP32_BASELINE[key]


# ------------------------------------------------------------- quantization


def test_int8_roundtrip_error_bound(hvd):
    """Blockwise quantization error is bounded by half a quantization step
    per element: |x - rt(x)| <= block_maxabs / 127 (bf16 scale slack)."""
    rng = np.random.RandomState(0)
    x = rng.randn(3000).astype(np.float32)
    rt = np.asarray(int8_roundtrip(jnp.asarray(x)))
    assert (rt != x).any()  # above the min-quantize floor: genuinely lossy
    pad = np.zeros(((-len(x)) % INT8_BLOCK,), np.float32)
    blocks = np.concatenate([x, pad]).reshape(-1, INT8_BLOCK)
    bound = np.repeat(np.abs(blocks).max(axis=1) / 127, INT8_BLOCK)[:len(x)]
    assert (np.abs(rt - x) <= bound * 1.01).all()
    # all-zero input quantizes to exactly zero (no 0/0 in the scale)
    z = np.asarray(int8_roundtrip(jnp.zeros(2048, jnp.float32)))
    np.testing.assert_array_equal(z, 0.0)


def test_int8_compress_decompress_shapes(hvd):
    x = jnp.asarray(np.random.RandomState(1).randn(40, 40).astype(np.float32))
    c, ctx = Compression.int8.compress(x)
    assert c.dtype == jnp.int8
    scales = ctx[0]
    assert scales.dtype == jnp.bfloat16
    out = Compression.int8.decompress(c, ctx)
    assert out.shape == x.shape and out.dtype == x.dtype


def test_int8_passthrough_dtypes(hvd):
    """Integer and already-16-bit leaves pass through untouched, exactly
    as fp16 compression passes integers through — and so do float leaves
    below the min-quantize floor, where the ring's per-chunk block padding
    would cost more wire than fp32."""
    for v in (jnp.arange(5, dtype=jnp.int32),
              jnp.full((4,), 1.5, jnp.bfloat16),
              jnp.ones((10,), jnp.float32)):  # tiny bias: below the floor
        c, ctx = Compression.int8.compress(v)
        assert ctx is None and c is v
        assert Compression.int8.decompress(c, ctx) is v
    assert np.asarray(
        int8_roundtrip(jnp.full((10,), 1.0 + 2e-4)))[0] == np.float32(
            1.0 + 2e-4)


def test_wire_bytes_hooks(hvd):
    shape = (784, 512)
    n = 784 * 512
    assert Compression.none.wire_bytes(shape, jnp.float32) == 4 * n
    assert Compression.fp16.wire_bytes(shape, jnp.float32) == 2 * n
    assert Compression.fp16.wire_bytes((6,), jnp.int32) == 24
    assert Compression.int8.wire_bytes(shape, jnp.float32) == \
        n + -(-n // INT8_BLOCK) * 2
    assert Compression.int8.wire_bytes((6,), jnp.int32) == 24
    # below the min-quantize floor: billed dense (and sent dense)
    assert Compression.int8.wire_bytes((512,), jnp.float32) == 512 * 4
    ps = Compression.powersgd(4)
    assert ps.wire_bytes(shape, jnp.float32) == (784 + 512) * 4 * 4
    # 1-D leaves fall back to the int8 pricing (incl. its dense floor)
    assert ps.wire_bytes((2048,), jnp.float32) == 2048 + 8 * 2
    assert ps.wire_bytes((512,), jnp.float32) == 512 * 4
    # a tiny 2-D leaf fails the (d0+m)*r < d0*m crossover and bills dense
    assert not ps.factorizes((2, 3), jnp.float32)
    assert ps.wire_bytes((2, 3), jnp.float32) == 6 * 4


def test_legacy_compressor_falls_back_to_itemsize_probe(hvd):
    """A user compressor predating the wire_bytes hook is billed by the
    scalar-probe itemsize — the old behavior, kept as the fallback."""
    from horovod_tpu.optim import _tree_sync_wire_bytes

    class LegacyHalf:  # no wire_bytes attribute
        @staticmethod
        def compress(t):
            return t.astype(np.float16), t.dtype

        @staticmethod
        def decompress(t, ctx):
            return t.astype(ctx)

    grads = {"w": jnp.ones((64, 32), jnp.float32)}
    assert _tree_sync_wire_bytes(grads, LegacyHalf) == 2048 * 2
    # and a blockwise compressor is billed per leaf, not per element
    assert _tree_sync_wire_bytes(grads, Compression.int8) == 2048 + 8 * 2


# ------------------------------------------------------------- collectives


def test_int8_allreduce_matches_mean(hvd):
    """Eager replicated, eager stacked, and in-jit bound int8 allreduce all
    land within quantization tolerance of the exact mean."""
    n = hvd.size()
    ax = hvd.data_axis()
    rng = np.random.RandomState(3)
    x = rng.randn(n, 1500).astype(np.float32)
    tol = np.abs(x).max() / 127 * 1.5

    out = hvd_mod.allreduce(
        jnp.asarray(x[0]), op=hvd_mod.Average, compression=Compression.int8)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), x[0], atol=tol)

    xs = jax.device_put(
        jnp.asarray(x), NamedSharding(hvd_mod.mesh(), P(ax)))
    out = hvd_mod.allreduce(
        xs, op=hvd_mod.Average, compression=Compression.int8)
    np.testing.assert_allclose(np.asarray(out), x.mean(0), atol=tol)

    def step(v):
        v = jnp.squeeze(v, 0)
        return allreduce(v, Average, axis=ax, compression=Compression.int8)

    f = jax.jit(_smap(step, hvd_mod.mesh(), (P(ax),), P()))
    np.testing.assert_allclose(np.asarray(f(xs)), x.mean(0), atol=tol)
    # and the compiled program must carry s8 collectives — the wire saving
    # is real int8 on the interconnect, not a simulated cast
    hlo = f.lower(xs).compile().as_text()
    assert "s8[" in hlo and "all-to-all" in hlo


def test_int8_sum_op(hvd):
    n = hvd.size()
    x = jnp.full((2000,), 0.5, jnp.float32)
    out = hvd_mod.allreduce(
        x, op=hvd_mod.Sum, compression=Compression.int8)
    np.testing.assert_allclose(np.asarray(out), 0.5 * n, rtol=2e-2)


def test_allreduce_rejects_factorized(hvd):
    with pytest.raises(ValueError, match="PowerSGD"):
        hvd_mod.allreduce(
            jnp.ones(4), compression=Compression.powersgd(2))


# --------------------------------------------------- trajectory acceptance


def test_int8_ef_adam_trajectory_tracks_fp32(hvd):
    """Acceptance 1a: int8+EF Adam over 12 steps tracks the uncompressed
    trajectory within tolerance."""
    p0, l0 = _fp32_trajectory(hvd)
    p1, l1 = _run_trajectory(
        hvd, hvd.DistributedOptimizer(
            optax.adam(1e-2), compression=Compression.int8,
            error_feedback=True), P())
    assert abs(l1[-1] - l0[-1]) / l0[-1] < 0.02
    for k in p0:
        np.testing.assert_allclose(
            np.asarray(p1[k]), np.asarray(p0[k]), atol=0.05)


def test_powersgd_ef_adam_trajectory_tracks_fp32(hvd):
    """Acceptance 1b: PowerSGD(rank=4)+EF over 12 steps — rank-4
    truncation of a 16x12 gradient is genuinely lossy, so the tolerance is
    looser than int8's, but the loss must still track the fp32 descent."""
    p0, l0 = _fp32_trajectory(hvd, shape=_PSGD_SHAPE)
    p1, l1 = _run_trajectory(
        hvd, hvd.DistributedOptimizer(
            optax.adam(1e-2), compression=Compression.powersgd(4),
            error_feedback=True), P(), shape=_PSGD_SHAPE)
    assert l1[-1] < l1[0]                       # it descends
    assert abs(l1[-1] - l0[-1]) / l0[-1] < 0.25  # and tracks fp32


def test_powersgd_full_rank_is_exact(hvd):
    """rank >= min(d0, m) makes one power iteration a projection onto the
    full column space — the factor sync reproduces the matrix exactly
    (the warm-start invariant the trajectory tests build on)."""
    from horovod_tpu.optim import _psgd_factor_sync

    rng = np.random.RandomState(5)
    m2d = jnp.asarray(rng.randn(24, 8).astype(np.float32))
    q0 = jnp.asarray(rng.randn(8, 8).astype(np.float32))
    approx, qn = _psgd_factor_sync(m2d, q0, lambda x: x)
    np.testing.assert_allclose(
        np.asarray(approx), np.asarray(m2d), rtol=1e-4, atol=1e-5)
    assert qn.shape == (8, 8)


def test_powersgd_tiny_leaf_falls_back(hvd):
    """A tiny 2-D leaf fails the (d0+m)*r < d0*m wire crossover and must
    NOT be factorized: its Q slot is None and the update is exact
    (below the int8 floor it rides dense)."""
    from horovod_tpu.optim import _q_leaves

    params = {"w": jnp.ones((2, 3), jnp.float32)}
    tx = hvd.DistributedOptimizer(
        optax.sgd(1.0), compression=Compression.powersgd(4),
        error_feedback=True)
    s = tx.init(params)
    assert _q_leaves(s.q) == [None]
    u, s = tx.update({"w": jnp.full((2, 3), 0.5)}, s, params)
    np.testing.assert_allclose(np.asarray(u["w"]), -0.5, rtol=1e-6)


def test_compressed_sharded_trajectories_compose(hvd):
    """Acceptance 3 (trajectory half): int8 and PowerSGD compose with
    shard_optimizer=True — the sharded trajectory matches its non-sharded
    twin (PowerSGD exactly: same factors, same math; int8 within the
    one-requantize-leg difference) and tracks fp32."""
    ax = hvd.data_axis()
    _, l0 = _fp32_trajectory(hvd)

    _, li = _run_trajectory(
        hvd, hvd.DistributedOptimizer(
            optax.adam(1e-2), shard_optimizer=True,
            compression=Compression.int8, error_feedback=True), P(ax))
    assert abs(li[-1] - l0[-1]) / l0[-1] < 0.02

    _, l0n = _fp32_trajectory(hvd, shape=_PSGD_SHAPE)
    _, lp = _run_trajectory(
        hvd, hvd.DistributedOptimizer(
            optax.adam(1e-2), shard_optimizer=True,
            compression=Compression.powersgd(4), error_feedback=True),
        P(ax), shape=_PSGD_SHAPE)
    _, lp2 = _run_trajectory(
        hvd, hvd.DistributedOptimizer(
            optax.adam(1e-2), compression=Compression.powersgd(4),
            error_feedback=True), P(), shape=_PSGD_SHAPE)
    np.testing.assert_allclose(lp[-1], lp2[-1], rtol=1e-4)
    assert abs(lp[-1] - l0n[-1]) / l0n[-1] < 0.25


@pytest.mark.slow
def test_int8_ef_soak_50_steps(hvd):
    """Soak: EF keeps the int8 trajectory glued to fp32 over 50 steps."""
    _, l0 = _run_trajectory(
        hvd, hvd.DistributedOptimizer(optax.adam(1e-2)), P(), steps=50)
    _, l1 = _run_trajectory(
        hvd, hvd.DistributedOptimizer(
            optax.adam(1e-2), compression=Compression.int8,
            error_feedback=True), P(), steps=50)
    assert abs(l1[-1] - l0[-1]) / max(l0[-1], 1e-6) < 0.05


@pytest.mark.slow
def test_powersgd_ef_soak_50_steps(hvd):
    """Soak: the warm-started rank-4 factorization + EF keeps descending
    over 50 steps — the random quadratic's optimal update is full-rank, so
    rank-4 legitimately trails fp32; the pin is sustained convergence (EF
    keeps feeding the truncated mass back in), not parity."""
    _, l1 = _run_trajectory(
        hvd, hvd.DistributedOptimizer(
            optax.adam(1e-2), compression=Compression.powersgd(4),
            error_feedback=True), P(), steps=50, shape=_PSGD_SHAPE)
    assert l1[-1] < 0.3 * l1[0]       # sustained descent
    assert l1[-1] < l1[11] * 0.75     # still improving past step 12


# ------------------------------------------------------- wire-byte gauges


def test_wire_byte_gauges_int8_and_powersgd_ratios(hvd):
    """Acceptance 2: on the transformer-block tree the reported
    grad_sync_bytes_per_step is <= ~27% of fp32 for int8 (incl. scale
    overhead) and <= 10% for PowerSGD rank-4."""
    hvd.metrics.reset()
    params = _block_params()
    g = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 0.01), params)

    def gauge_for(compression, ef):
        tx = hvd.DistributedOptimizer(
            optax.sgd(0.1), compression=compression, error_feedback=ef)
        s = tx.init(params)
        tx.update(g, s, params)
        return hvd.metrics.value("grad_sync_bytes_per_step", mode="allreduce")

    fp32 = gauge_for(Compression.none, False)
    i8 = gauge_for(Compression.int8, True)
    ps = gauge_for(Compression.powersgd(4), True)
    assert fp32 and i8 and ps
    assert i8 / fp32 <= 0.27
    assert ps / fp32 <= 0.10
    # and the exact model: 1 byte/elt + bf16 scale per 256-block for
    # leaves above the min-quantize floor, dense fp32 below it
    from horovod_tpu.compression import MIN_QUANT_ELEMS

    wire = sum(
        (p.size + -(-p.size // INT8_BLOCK) * 2)
        if p.size >= MIN_QUANT_ELEMS else 4 * p.size
        for p in jax.tree_util.tree_leaves(params)
    )
    elems = sum(p.size for p in jax.tree_util.tree_leaves(params))
    ring2 = 2 * (hvd.size() - 1) / hvd.size()
    assert i8 == pytest.approx(ring2 * wire)
    assert fp32 == pytest.approx(ring2 * 4 * elems)


def test_sharded_int8_gauge_prices_blockwise(hvd):
    """The sharded (reduce-scatter) gauge prices the padded flat buffer at
    the blockwise int8 rate through the wire_bytes hook."""
    hvd.metrics.reset()
    n = hvd.size()
    params = _small_params()
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.1), shard_optimizer=True,
        compression=Compression.int8, error_feedback=True)
    s = tx.init(params)
    g = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 0.5), params)
    tx.update(g, s, params)
    got = hvd.metrics.value("grad_sync_bytes_per_step", mode="sharded")
    total = sum(p.size for p in jax.tree_util.tree_leaves(params))
    Lp = total + ((-total) % n)
    ring = (n - 1) / n
    assert got == pytest.approx(ring * (Lp + 2 * -(-Lp // INT8_BLOCK)))


# --------------------------------------------------- reshard / persistence


def test_int8_sharded_reshard_8_4_8_ef_mass(hvd, tmp_path):
    """Acceptance 3 (reshard half, int8): save → consolidate to 4 → back
    to 8; the summed EF residual (total untransmitted gradient mass) is
    invariant and updates continue identically."""
    from horovod_tpu import checkpoint

    params = _small_params()
    tx = hvd.DistributedOptimizer(
        optax.adam(1e-2), shard_optimizer=True,
        compression=Compression.int8, error_feedback=True)
    state = tx.init(params)
    g = jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, 1.0 + 2e-3), params)
    for _ in range(3):
        _, state = tx.update(g, state, params)
    mass = {k: np.asarray(v).sum(axis=0) for k, v in state.residual.items()}
    assert any(np.abs(m).max() > 0 for m in mass.values())

    total = sum(p.size for p in jax.tree_util.tree_leaves(params))
    checkpoint.save(str(tmp_path), 3, {"opt": state})
    loaded = checkpoint.restore(str(tmp_path), 3)["opt"]
    st4 = checkpoint.consolidate_opt_state(loaded, params, to_size=4)
    for k, v in st4.residual.items():
        assert v.shape[0] == 4
        np.testing.assert_allclose(
            np.asarray(v).sum(axis=0)[:total], mass[k][:total],
            rtol=1e-5, atol=1e-6)
    st8 = checkpoint.consolidate_opt_state(st4, params, to_size=8)
    u1, _ = tx.update(g, state, params)
    u2, _ = tx.update(g, st8, params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(u1[k]), np.asarray(u2[k]), rtol=1e-5, atol=1e-7)


def test_powersgd_sharded_reshard_8_4_8(hvd, tmp_path):
    """Acceptance 3 (reshard half, PowerSGD): moments, flat EF residuals
    AND the warm-started Q factors survive the 8→4→8 consolidate — Q rows
    re-tile (identical by construction) and updates continue identically."""
    from horovod_tpu import checkpoint
    from horovod_tpu.optim import _q_leaves

    params = _small_params()
    tx = hvd.DistributedOptimizer(
        optax.adam(1e-2), shard_optimizer=True,
        compression=Compression.powersgd(4), error_feedback=True)
    state = tx.init(params)
    g = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 0.3), params)
    for _ in range(3):
        _, state = tx.update(g, state, params)
    mass = {k: np.asarray(v).sum(axis=0) for k, v in state.residual.items()}
    assert any(np.abs(m).max() > 0 for m in mass.values())

    total = sum(p.size for p in jax.tree_util.tree_leaves(params))
    checkpoint.save(str(tmp_path), 3, {"opt": state})
    loaded = checkpoint.restore(str(tmp_path), 3)["opt"]
    st4 = checkpoint.consolidate_opt_state(loaded, params, to_size=4)
    q4 = [q for q in _q_leaves(st4.q) if q is not None]
    assert all(q.shape[0] == 4 for q in q4)
    for k, v in st4.residual.items():
        np.testing.assert_allclose(
            np.asarray(v).sum(axis=0)[:total], mass[k][:total],
            rtol=1e-5, atol=1e-6)
    st8 = checkpoint.consolidate_opt_state(st4, params, to_size=8)
    for a, b in zip(_q_leaves(state.q), _q_leaves(st8.q)):
        if a is None:
            assert b is None
            continue
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    u1, _ = tx.update(g, state, params)
    u2, _ = tx.update(g, st8, params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(u1[k]), np.asarray(u2[k]), rtol=1e-5, atol=1e-7)


def test_broadcast_optimizer_state_skips_powersgd_sharded(hvd):
    """Sharded PowerSGD state leaves (moments, residual, Q — all carrying
    the leading rank axis) are per-rank data: broadcast leaves them be."""
    params = _small_params()
    tx = hvd.DistributedOptimizer(
        optax.adam(1e-2), shard_optimizer=True,
        compression=Compression.powersgd(4), error_feedback=True)
    state = tx.init(params)
    g = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 0.5), params)
    _, state = tx.update(g, state, params)
    out = hvd.broadcast_optimizer_state(state)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(out)):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------- composition


def test_powersgd_requires_error_feedback(hvd):
    with pytest.raises(ValueError, match="error_feedback"):
        hvd.DistributedOptimizer(
            optax.sgd(0.1), compression=Compression.powersgd(4))


def test_quantized_rejects_predivide_and_adasum(hvd):
    with pytest.raises(ValueError, match="predivide"):
        hvd.DistributedOptimizer(
            optax.sgd(0.1), compression=Compression.int8,
            gradient_predivide_factor=2.0)
    with pytest.raises(ValueError, match="Adasum"):
        hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Adasum, compression=Compression.int8)


def test_compression_from_env(hvd, monkeypatch):
    monkeypatch.setenv("HOROVOD_COMPRESSION", "int8")
    tx = hvd.DistributedOptimizer(optax.sgd(1.0), error_feedback=True)
    p = {"w": jnp.full((1200,), 1.0 + 2e-3)}
    s = tx.init(p)
    _, s = tx.update({"w": jnp.full((1200,), 1.0 + 2e-3)}, s, p)
    assert np.abs(np.asarray(s.residual["w"])).max() > 0  # int8 was lossy

    monkeypatch.setenv("HOROVOD_COMPRESSION", "powersgd")
    monkeypatch.setenv("HOROVOD_POWERSGD_RANK", "2")
    from horovod_tpu.optim import _PowerSGDState, _q_leaves

    # env-resolved PowerSGD must work on call sites that never opted into
    # compression kwargs: it implies the error feedback it needs
    tx = hvd.DistributedOptimizer(optax.sgd(1.0))
    st = tx.init({"w": jnp.ones((8, 6))})
    assert isinstance(st, _PowerSGDState)
    assert [q.shape for q in _q_leaves(st.q) if q is not None] == [(6, 2)]

    monkeypatch.setenv("HOROVOD_COMPRESSION", "bogus")
    with pytest.raises(ValueError, match="bogus"):
        hvd.DistributedOptimizer(optax.sgd(1.0))


def test_gradient_accumulation_composes(hvd):
    """backward_passes_per_step > 1 accumulates locally, then the int8+EF
    exchange fires on the accumulated gradient."""
    tx = hvd.DistributedOptimizer(
        optax.sgd(1.0), compression=Compression.int8,
        error_feedback=True, backward_passes_per_step=2)
    p = {"w": jnp.zeros((1200,), jnp.float32)}
    s = tx.init(p)
    u1, s = tx.update({"w": jnp.ones(1200)}, s, p)
    np.testing.assert_allclose(np.asarray(u1["w"]), 0.0)  # accumulating
    u2, s = tx.update({"w": jnp.ones(1200)}, s, p)
    np.testing.assert_allclose(np.asarray(u2["w"]), -1.0, rtol=2e-2)


def test_eager_stacked_int8_update(hvd):
    """Eager per-rank stacked gradients through the non-sharded int8+EF
    optimizer: the applied update is the mean of the quantized
    contributions."""
    n = hvd.size()
    params = {"w": jnp.ones((40, 30), jnp.float32)}
    tx = hvd.DistributedOptimizer(
        optax.sgd(1.0), compression=Compression.int8, error_feedback=True)
    s = tx.init(params)
    g = np.stack(
        [np.full((40, 30), float(r), np.float32) for r in range(n)])
    grads = {"w": jax.device_put(
        g, NamedSharding(hvd.mesh(), P(hvd.data_axis())))}
    u, s = tx.update(grads, s, params)
    np.testing.assert_allclose(
        np.asarray(u["w"]), -g.mean(axis=0), atol=(n - 1) / 127 * 1.5)


def test_mixed_dtype_sharded_int8_update(hvd):
    """A mixed f32/bf16 tree under sharded int8: the f32 group rides the
    quantized ring (its flat buffer is above the quantize floor), the bf16
    group passes through uncompressed, dtypes and shapes survive."""
    params = {
        "a": jnp.ones((40, 30), jnp.float32),
        "b": jnp.ones((5,), jnp.bfloat16),
        "c": jnp.ones((2, 2), jnp.float32),
    }
    tx = hvd.DistributedOptimizer(
        optax.sgd(1.0), shard_optimizer=True, compression=Compression.int8)
    state = tx.init(params)
    g = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 0.5, p.dtype), params)
    upd, state = tx.update(g, state, params)
    for k, p in params.items():
        assert upd[k].dtype == p.dtype and upd[k].shape == p.shape
        np.testing.assert_allclose(
            np.asarray(upd[k], np.float32), -0.5, rtol=2e-2)


# ------------------------------------------------- hierarchical (2x4 mesh)


@pytest.fixture()
def hvd24():
    from horovod_tpu.ops.hierarchical import set_hierarchical
    from horovod_tpu.parallel.mesh import build_host_mesh

    mesh = build_host_mesh(local=4)
    hvd_mod.init(mesh=mesh)
    set_hierarchical(True)
    yield hvd_mod
    set_hierarchical(None)
    hvd_mod.shutdown()


def test_hier_int8_compresses_cross_hop_only(hvd24):
    """Two-axis int8 allreduce under HOROVOD_HIERARCHICAL_ALLREDUCE:
    the DCN ``cross`` hop rides the int8 ring while the local ICI
    reduce-scatter / all-gather stay full-width — pinned by the compiled
    HLO (the s8 exchange groups over cross, size 2; f32 legs over local,
    size 4) and by numeric equivalence with the flat mean."""
    mesh = hvd24.mesh()
    rng = np.random.RandomState(0)
    x = rng.randn(8, 48, 32).astype(np.float32)
    xs = jax.device_put(
        jnp.asarray(x), NamedSharding(mesh, P(("cross", "local"))))

    def step(v):
        v = jnp.squeeze(v, 0)
        return allreduce(v, Average, axis=("cross", "local"),
                         compression=Compression.int8)

    f = jax.jit(_smap(step, mesh, (P(("cross", "local")),), P()))
    out = np.asarray(f(xs))
    np.testing.assert_allclose(
        out, x.mean(0), atol=np.abs(x).max() / 127 * 2)
    hlo = f.lower(xs).compile().as_text()
    assert "s8[" in hlo
    # the int8 payloads exchange over the cross axis (group size 2): with
    # row-major (cross, local) device order those groups are {i, i+4}
    assert "{{0,4}" in hlo.replace(" ", "")
