"""Multi-process controller-protocol tests: two real processes negotiate
named tensors over the TCP transport (reference analog: every op test runs
under a 2-process launcher, SURVEY.md §4; transport role of
gloo_controller.cc)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent(
    """
    import os, sys, time
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE

    rank = int(sys.argv[1])
    port = int(sys.argv[2])
    os.environ["HOROVOD_CYCLE_TIME"] = "2"
    hvd.init()  # local 1-device mesh; data plane is local in this test
    core = NativeCore(rank=rank, size=2, coordinator_host="127.0.0.1",
                      coordinator_port=port)

    x = np.ones((1, 4), dtype=np.float32)

    # 1. both ranks ready at different times -> negotiation waits for all
    h1 = core.enqueue("g1", x, REQUEST_ALLREDUCE, op=1)
    if rank == 1:
        time.sleep(0.3)
    h2 = core.enqueue("g2", x, REQUEST_ALLREDUCE, op=1)
    h1.wait(timeout=15)
    h2.wait(timeout=15)
    print(f"rank{rank}: g1,g2 ok", flush=True)

    # 2. steady-state: same name over steps rides the response cache and the
    # TCP bitvector sync
    for step in range(5):
        h = core.enqueue("grad", x, REQUEST_ALLREDUCE, op=1)
        h.wait(timeout=15)
    print(f"rank{rank}: cache steps ok", flush=True)

    # 3. cross-rank validation: mismatched dtypes must produce an ERROR
    bad = x if rank == 0 else np.ones((1, 4), dtype=np.int32)
    h = core.enqueue("bad", bad, REQUEST_ALLREDUCE, op=1)
    try:
        h.wait(timeout=15)
        print(f"rank{rank}: ERROR-EXPECTED-BUT-OK", flush=True)
    except RuntimeError as e:
        assert "Mismatched data types" in str(e), e
        print(f"rank{rank}: mismatch detected ok", flush=True)

    core.shutdown()
    print(f"rank{rank}: done", flush=True)
    """
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_negotiation(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", str(script), str(r), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for r in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=90)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for r, out in enumerate(outs):
        assert f"rank{r}: g1,g2 ok" in out, out
        assert f"rank{r}: cache steps ok" in out, out
        assert f"rank{r}: mismatch detected ok" in out, out
        assert f"rank{r}: done" in out, out
        assert "ERROR-EXPECTED-BUT-OK" not in out, out
    assert all(p.returncode == 0 for p in procs), outs
