"""Transformer LM + sequence-parallel training tests: single-chip forward,
DP training, DP x SP training with ring attention (loss decreases and
matches the single-mesh run), and tensor-parallel pjit sharding."""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import TransformerTiny, transformer_param_specs
from horovod_tpu.parallel import SEQUENCE_AXIS, build_mesh, ring_attention
from horovod_tpu.training import make_sp_train_step, replicate


@pytest.fixture()
def lm_data():
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 1024, (4, 64)).astype(np.int32)
    # next-token targets computed globally BEFORE sharding
    targets = np.roll(tokens, -1, axis=1)
    return jnp.asarray(tokens), jnp.asarray(targets)


def test_forward_shapes():
    model = TransformerTiny(dtype=jnp.float32)
    tokens = jnp.zeros((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 32, 1024)
    assert logits.dtype == jnp.float32


def test_causality():
    # changing a future token must not change past logits
    model = TransformerTiny(dtype=jnp.float32)
    rng = np.random.RandomState(1)
    t1 = jnp.asarray(rng.randint(0, 1024, (1, 16)).astype(np.int32))
    t2 = t1.at[0, 10].set((t1[0, 10] + 7) % 1024)
    params = model.init(jax.random.PRNGKey(0), t1)["params"]
    l1 = model.apply({"params": params}, t1)
    l2 = model.apply({"params": params}, t2)
    np.testing.assert_allclose(
        np.asarray(l1[0, :10]), np.asarray(l2[0, :10]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_sp_train_step_loss_decreases(hvd, lm_data):
    hvd.shutdown()
    hvd.init(axes={"data": 2, SEQUENCE_AXIS: 4})
    tokens, targets = lm_data

    model = TransformerTiny(
        dtype=jnp.float32,
        attention_fn=functools.partial(
            ring_attention, axis_name=SEQUENCE_AXIS, block_k=8),
    )
    tx = optax.adam(1e-2)
    # init with the dense twin: attention_fn doesn't affect the param tree,
    # and ring attention needs the seq axis bound (shard_map) to trace
    params = TransformerTiny(dtype=jnp.float32).init(
        jax.random.PRNGKey(0), tokens[:1])["params"]
    params = replicate(params)
    opt_state = replicate(tx.init(params))

    mesh = hvd.mesh()
    sh = NamedSharding(mesh, P("data", SEQUENCE_AXIS))
    tokens = jax.device_put(tokens, sh)
    targets = jax.device_put(targets, sh)

    step = make_sp_train_step(model, tx, seq_axis=SEQUENCE_AXIS)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_sp_matches_dense_single_step(hvd, lm_data):
    # one SP step == one dense-attention step on the same data
    tokens, targets = lm_data

    hvd.shutdown()
    hvd.init(axes={"data": 1, SEQUENCE_AXIS: 8})
    model_sp = TransformerTiny(
        dtype=jnp.float32,
        attention_fn=functools.partial(
            ring_attention, axis_name=SEQUENCE_AXIS, block_k=8),
    )
    tx = optax.sgd(0.1)
    params = TransformerTiny(dtype=jnp.float32).init(
        jax.random.PRNGKey(0), tokens[:1])["params"]
    mesh = hvd.mesh()
    sh = NamedSharding(mesh, P("data", SEQUENCE_AXIS))
    # donate=False: the replicated params alias the originals (device_put
    # reuses the local shard), and the dense reference below still needs them
    step = make_sp_train_step(model_sp, tx, seq_axis=SEQUENCE_AXIS,
                              donate=False)
    p1, _, loss_sp = step(
        replicate(params), replicate(tx.init(params)),
        jax.device_put(tokens, sh), jax.device_put(targets, sh),
    )

    # dense single-device reference
    model_d = TransformerTiny(dtype=jnp.float32)

    def loss_fn(p):
        logits = model_d.apply({"params": p}, tokens)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], -1))

    loss_d, grads = jax.value_and_grad(loss_fn)(params)
    np.testing.assert_allclose(float(loss_sp), float(loss_d), rtol=1e-5)
    p2 = optax.apply_updates(params, jax.tree_util.tree_map(
        lambda g: -0.1 * g, grads))
    flat1 = jax.tree_util.tree_leaves(p1)
    flat2 = jax.tree_util.tree_leaves(p2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


@pytest.mark.parametrize("kv_heads", [None, 2])
def test_tensor_parallel_pjit_sharding(hvd, kv_heads):
    # TP the XLA way: annotate param shardings over the model axis, let the
    # compiler insert the collectives; result must match replicated
    # execution. kv_heads=2 also exercises the GQA q_proj/kv_proj specs.
    hvd.shutdown()
    hvd.init(axes={"data": 2, "model": 4})
    mesh = hvd.mesh()

    model = TransformerTiny(dtype=jnp.float32, kv_heads=kv_heads)
    rng = np.random.RandomState(2)
    tokens = jnp.asarray(rng.randint(0, 1024, (4, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), tokens[:1])["params"]
    if kv_heads:
        specs_probe = transformer_param_specs(params, model_axis="model")
        assert specs_probe["block0"]["q_proj"]["kernel"] == P(None, "model")
        assert specs_probe["block0"]["kv_proj"]["kernel"] == P(None, "model")

    specs = transformer_param_specs(params, model_axis="model")
    sharded_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    tokens_sh = jax.device_put(tokens, NamedSharding(mesh, P("data")))

    fwd = jax.jit(lambda p, t: model.apply({"params": p}, t))
    out_tp = fwd(sharded_params, tokens_sh)
    out_ref = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(out_tp), np.asarray(out_ref), rtol=2e-4, atol=2e-4
    )


def test_gqa_model_flash_matches_dense_attention():
    """kv_heads < heads: the GQA projections feed the attention stack; the
    flash and dense attention paths must agree on the same parameters, and
    training gradients must flow through the smaller kv projection."""
    import functools

    import optax

    from horovod_tpu.models import TransformerTiny
    from horovod_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 1024, (2, 32)).astype(np.int32))

    dense_m = TransformerTiny(dtype=jnp.float32, kv_heads=2)
    flash_m = TransformerTiny(
        dtype=jnp.float32, kv_heads=2,
        attention_fn=functools.partial(
            flash_attention, use_pallas=False, block_k=8),
    )
    params = dense_m.init(jax.random.PRNGKey(0), tokens)["params"]
    # GQA projections exist and are smaller than the fused qkv would be
    blk = params["block0"]
    assert "q_proj" in blk and "kv_proj" in blk and "qkv" not in blk
    # kv projection sized 2 * kv_heads * head_dim (vs 2 * dim fused)
    head_dim = 64 // 4
    assert blk["kv_proj"]["kernel"].shape[1] == 2 * 2 * head_dim

    out_d = dense_m.apply({"params": params}, tokens)
    out_f = flash_m.apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_f),
                               rtol=2e-4, atol=2e-4)

    def loss(p):
        logits = flash_m.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], tokens[:, 1:]
        ).mean()

    g = jax.grad(loss)(params)
    gnorm = float(
        sum((np.asarray(x) ** 2).sum()
            for x in jax.tree_util.tree_leaves(g))
    )
    assert np.isfinite(gnorm) and gnorm > 0


def test_rope_relative_shift_invariance():
    """RoPE's defining property: q.k dot products depend only on the
    position DIFFERENCE — shifting both positions by s leaves scores
    unchanged (what makes it safe across SP shard boundaries)."""
    from horovod_tpu.models.transformer import apply_rope

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 6, 2, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 6, 2, 16).astype(np.float32))
    pos = jnp.arange(6)[None, :]
    s1 = jnp.einsum("bqhd,bkhd->bhqk", apply_rope(q, pos),
                    apply_rope(k, pos))
    s2 = jnp.einsum("bqhd,bkhd->bhqk", apply_rope(q, pos + 137),
                    apply_rope(k, pos + 137))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                               rtol=1e-4, atol=1e-4)


def test_rope_model_no_pos_table_and_trains(hvd):
    model = TransformerTiny(dtype=jnp.float32, pos_embedding="rope")
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 1024, (2, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    assert "pos_embed" not in params  # rotary: no learned table
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 16, 1024)
    assert np.isfinite(np.asarray(logits)).all()

    with pytest.raises(ValueError, match="learned.*rope"):
        TransformerTiny(dtype=jnp.float32, pos_embedding="alibi").init(
            jax.random.PRNGKey(0), tokens)


def test_rope_sp_matches_dense_single_step(hvd, lm_data):
    """RoPE under sequence parallelism: per-shard global position offsets
    must phase K identically to the dense single-device run."""
    tokens, targets = lm_data

    hvd.shutdown()
    hvd.init(axes={"data": 1, SEQUENCE_AXIS: 8})
    model_sp = TransformerTiny(
        dtype=jnp.float32, pos_embedding="rope",
        attention_fn=functools.partial(
            ring_attention, axis_name=SEQUENCE_AXIS, block_k=8),
    )
    tx = optax.sgd(0.1)
    params = TransformerTiny(dtype=jnp.float32, pos_embedding="rope").init(
        jax.random.PRNGKey(0), tokens[:1])["params"]
    mesh = hvd.mesh()
    sh = NamedSharding(mesh, P("data", SEQUENCE_AXIS))
    step = make_sp_train_step(model_sp, tx, seq_axis=SEQUENCE_AXIS,
                              donate=False)
    _, _, loss_sp = step(
        replicate(params), replicate(tx.init(params)),
        jax.device_put(tokens, sh), jax.device_put(targets, sh),
    )

    model_d = TransformerTiny(dtype=jnp.float32, pos_embedding="rope")

    def loss_fn(p):
        logits = model_d.apply({"params": p}, tokens)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], -1))

    loss_d = loss_fn(params)
    np.testing.assert_allclose(float(loss_sp), float(loss_d), rtol=1e-5)


@pytest.mark.parametrize("variant", ["learned", "rope", "gqa"])
def test_generate_kv_cache_matches_full_forward(variant):
    """Greedy decode through the kv cache must reproduce the no-cache
    oracle (full forward over the prefix at every step, argmax)."""
    from horovod_tpu.models import generate

    kw = dict(dtype=jnp.float32, max_len=64)
    if variant == "rope":
        kw["pos_embedding"] = "rope"
    if variant == "gqa":
        kw["kv_heads"] = 2
    model = TransformerTiny(**kw)
    rng = np.random.RandomState(3)
    prompt = jnp.asarray(rng.randint(0, 1024, (2, 5)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]

    out = generate(model, params, prompt, max_new_tokens=6)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))

    # oracle: re-run the full prefix each step, take argmax of the last pos
    seq = np.asarray(prompt)
    for _ in range(6):
        logits = model.apply({"params": params}, jnp.asarray(seq))
        nxt = np.argmax(np.asarray(logits[:, -1]), axis=-1)
        seq = np.concatenate([seq, nxt[:, None].astype(np.int32)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), seq)


def test_generate_sampling_and_validation():
    from horovod_tpu.models import generate

    model = TransformerTiny(dtype=jnp.float32, max_len=16)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 1024, (1, 4)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]

    out = generate(model, params, prompt, max_new_tokens=4,
                   temperature=1.0, rng=jax.random.PRNGKey(7))
    assert out.shape == (1, 8)
    assert int(out.min()) >= 0 and int(out.max()) < 1024

    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt, max_new_tokens=2, temperature=0.5)
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, prompt, max_new_tokens=13)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(model, params, prompt, max_new_tokens=0)


def test_tp_train_step_matches_replicated_and_keeps_layout(hvd):
    """TP TRAINING via pjit layout annotations: params sharded over the
    model axis train to the same result as replicated execution, and the
    Megatron-style layout survives donated steps (grads/moments/updates all
    stay sharded — per-chip param+optimizer HBM divided by tp)."""
    hvd.shutdown()
    hvd.init(axes={"data": 2, "model": 4})
    mesh = hvd.mesh()
    try:
        model = TransformerTiny(dtype=jnp.float32)
        rng = np.random.RandomState(4)
        tokens = jnp.asarray(rng.randint(0, 1024, (4, 16)).astype(np.int32))
        targets = jnp.asarray(np.roll(np.asarray(tokens), -1, 1))
        params = model.init(jax.random.PRNGKey(0), tokens[:1])["params"]

        from horovod_tpu.training import make_jit_train_step

        def lm_xent(logits, tgts):
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(
                jnp.take_along_axis(logp, tgts[..., None], axis=-1))

        tx = hvd.DistributedOptimizer(optax.adam(0.01))
        step_r = make_jit_train_step(model, tx, loss_fn=lm_xent,
                                     donate=False)
        step_t = make_jit_train_step(model, tx, loss_fn=lm_xent,
                                     donate=True)

        specs = transformer_param_specs(params, model_axis="model")
        p_t = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            params, specs)
        opt_t = tx.init(p_t)  # moments inherit the TP layout from params
        p_r = replicate(params)
        opt_r = replicate(tx.init(params))
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, P("data")))
        tgt_sh = jax.device_put(targets, NamedSharding(mesh, P("data")))

        def tp_paths(tree):
            return {
                jax.tree_util.keystr(path)
                for path, l in jax.tree_util.tree_flatten_with_path(tree)[0]
                if getattr(l.sharding, "spec", None)
                and any(e == "model" for e in l.sharding.spec)
            }

        before = tp_paths(p_t)
        assert before, "no param leaf carries the model axis"

        for _ in range(3):
            p_r, _, opt_r, l_r = step_r(p_r, {}, opt_r, tok_sh, tgt_sh)
            p_t, _, opt_t, l_t = step_t(p_t, {}, opt_t, tok_sh, tgt_sh)
            np.testing.assert_allclose(float(l_r), float(l_t), rtol=1e-4)
        # TP reduces in a different order; adam's rsqrt amplifies the fp32
        # noise — tolerance covers reduction order, not semantics
        for a, b in zip(jax.tree_util.tree_leaves(p_r),
                        jax.tree_util.tree_leaves(p_t)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-3)
        # XLA may ADD model-axis layouts to small unannotated leaves (ln
        # scales); what must not happen is any original TP leaf losing it
        assert before <= tp_paths(p_t), "compiler dropped a TP layout"
        assert tp_paths(opt_t), "optimizer moments lost the TP layout"
    finally:
        hvd.shutdown()
        hvd.init()


def test_generate_ragged_prompts_match_per_row_oracle():
    """prompt_lens: each right-padded row decodes from its own length and
    must reproduce the single-row no-cache rollout exactly — pads never
    leak into attention."""
    from horovod_tpu.models import generate

    model = TransformerTiny(dtype=jnp.float32, max_len=64)
    rng = np.random.RandomState(9)
    lens = [3, 5, 2]
    t_max, new = 5, 4
    rows = [rng.randint(0, 1024, (l,)).astype(np.int32) for l in lens]
    prompt = np.full((3, t_max), 777, np.int32)  # junk padding
    for i, r in enumerate(rows):
        prompt[i, : len(r)] = r
    params = model.init(
        jax.random.PRNGKey(1), jnp.asarray(prompt[:1]))["params"]

    out = np.asarray(generate(
        model, params, jnp.asarray(prompt), max_new_tokens=new,
        prompt_lens=np.array(lens)))

    for i, r in enumerate(rows):
        seq = r[None, :]
        for _ in range(new):
            logits = model.apply({"params": params}, jnp.asarray(seq))
            nxt = np.argmax(np.asarray(logits[:, -1]), axis=-1)
            seq = np.concatenate(
                [seq, nxt[:, None].astype(np.int32)], axis=1)
        np.testing.assert_array_equal(
            out[i, : lens[i] + new], seq[0],
            err_msg=f"row {i} (len {lens[i]})")

    with pytest.raises(ValueError, match="prompt_lens"):
        generate(model, params, jnp.asarray(prompt), max_new_tokens=2,
                 prompt_lens=np.array([3, 5]))


def test_generate_prompt_lens_range_validated():
    from horovod_tpu.models import generate

    model = TransformerTiny(dtype=jnp.float32, max_len=32)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 1024, (2, 4)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    for bad in ([0, 4], [2, 6]):
        with pytest.raises(ValueError, match=r"\[1, 4\]"):
            generate(model, params, prompt, max_new_tokens=2,
                     prompt_lens=np.array(bad))


def _pp_dense_parity(S, interleaved_v, *, vocab, depth, seed):
    """Shared harness: PP-train one step of a real TransformerLM and assert
    loss + every updated parameter equals the dense single-device step."""
    import horovod_tpu as hvd_mod
    from horovod_tpu.models import TransformerLM
    from horovod_tpu.training import (
        make_transformer_pp_train_step, split_transformer_for_pp, token_xent,
    )

    hvd_mod.shutdown()
    hvd_mod.init(devices=jax.devices()[:S], axes={"pipe": S})
    try:
        model = TransformerLM(vocab=vocab, dim=32, depth=depth, heads=4,
                              max_len=64, dtype=jnp.float32)
        rng = np.random.RandomState(seed)
        M, mb, T = 4, 2, 12
        tokens = rng.randint(0, vocab, (M * mb, T)).astype(np.int32)
        targets = np.roll(tokens, -1, axis=1)
        params = model.init(
            jax.random.PRNGKey(seed), jnp.asarray(tokens[:1]))["params"]

        lr = 0.1
        tx = optax.sgd(lr)
        pp = split_transformer_for_pp(
            model, params, S, interleaved_v=interleaved_v)
        init_stages = (jax.vmap(jax.vmap(tx.init)) if interleaved_v > 1
                       else jax.vmap(tx.init))
        opt_state = {
            "embed": tx.init(pp["embed"]),
            "stages": init_stages(pp["stages"]),
            "head": tx.init(pp["head"]),
        }
        from jax.sharding import NamedSharding as NS

        mesh = hvd_mod.mesh()
        pp["stages"] = jax.tree_util.tree_map(
            lambda p: jax.device_put(p, NS(mesh, P("pipe"))), pp["stages"])
        opt_state["stages"] = jax.tree_util.tree_map(
            lambda s: jax.device_put(s, NS(mesh, P("pipe"))),
            opt_state["stages"])

        step = make_transformer_pp_train_step(
            model, tx, interleaved_v=interleaved_v, donate=False)
        new_pp, _, loss_pp = step(
            pp, opt_state,
            jnp.asarray(tokens).reshape(M, mb, T),
            jnp.asarray(targets).reshape(M, mb, T))

        def dense_loss(p):
            logits = model.apply({"params": p}, jnp.asarray(tokens))
            return token_xent(logits, jnp.asarray(targets))

        loss_d, grads = jax.value_and_grad(dense_loss)(params)
        np.testing.assert_allclose(float(loss_pp), float(loss_d), rtol=1e-5)
        dense_new = optax.apply_updates(
            params, jax.tree_util.tree_map(lambda g: -lr * g, grads))

        def assert_part(got, want, label):
            for path, a in jax.tree_util.tree_flatten_with_path(got)[0]:
                b = want
                for kk in path:
                    b = b[kk.key]
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
                    err_msg=f"{label} {jax.tree_util.keystr(path)}")

        assert_part(new_pp["embed"]["tok_embed"],
                    dense_new["tok_embed"], "tok_embed")
        assert_part(new_pp["embed"]["pos_embed"],
                    dense_new["pos_embed"], "pos_embed")
        assert_part(new_pp["head"]["ln_f"], dense_new["ln_f"], "ln_f")
        assert_part(new_pp["head"]["lm_head"], dense_new["lm_head"],
                    "lm_head")
        n_total = S * interleaved_v
        for k in range(n_total):
            if interleaved_v > 1:
                got = jax.tree_util.tree_map(
                    lambda p: p[k % S, k // S], new_pp["stages"])["b0"]
            else:
                got = jax.tree_util.tree_map(
                    lambda p: p[k], new_pp["stages"])["b0"]
            assert_part(got, dense_new[f"block{k}"], f"block{k}")
    finally:
        hvd_mod.shutdown()
        hvd_mod.init()


def test_transformer_pp_train_step_matches_dense():
    """PP training of the REAL TransformerLM (embed + blocks + head all
    trained): loss and one-step parameter updates must match the dense
    single-device step — pins the per-part gradient bookkeeping (stages /S,
    embed psum over the pipe, head replicated)."""
    _pp_dense_parity(4, 1, vocab=256, depth=4, seed=11)


def test_transformer_pp_interleaved_matches_dense():
    """Interleaved (circular) schedule: S=2 devices x v=2 wrap levels over
    4 blocks — same dense-oracle equality as the GPipe path."""
    _pp_dense_parity(2, 2, vocab=128, depth=4, seed=13)
