"""Build and run the native C++ unit tests (csrc/test/core_test.cc) from
the suite — wire format, fusion bin-packing, response cache, tensor queue,
GP autotuner. The reference has no C++ unit layer (SURVEY.md §4: its core
is only exercised through Python bindings); here a silent C++ bug would
surface as a cross-process hang, so the native layer gets its own tests."""

import pathlib
import subprocess

_CSRC = pathlib.Path(__file__).resolve().parents[1] / "csrc"


def test_native_core_unit_tests():
    r = subprocess.run(
        ["make", "-C", str(_CSRC), "test"],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 test(s) failed" in r.stdout, r.stdout
