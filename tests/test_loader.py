"""Input-pipeline tests: DistributedSampler-style index sharding + the
device-prefetching sharded loader (reference examples lean on
``torch.utils.data.distributed.DistributedSampler`` /
``tf.data .shard()`` — ``examples/pytorch_mnist.py:98-103``)."""

import numpy as np
import pytest

from horovod_tpu.data import ShardedLoader, shard_indices


def test_shard_indices_partition(hvd):
    n, size = 103, 4
    slices = [
        shard_indices(n, rank=r, size=size, shuffle=True, seed=7)
        for r in range(size)
    ]
    # equal lengths (padded), union covers everything
    assert len({len(s) for s in slices}) == 1
    union = set()
    for s in slices:
        union.update(s.tolist())
    assert union == set(range(n))
    # deterministic per (seed, epoch); different across epochs
    again = shard_indices(n, rank=0, size=size, shuffle=True, seed=7)
    np.testing.assert_array_equal(slices[0], again)
    e1 = shard_indices(n, rank=0, size=size, shuffle=True, seed=7, epoch=1)
    assert not np.array_equal(slices[0], e1)


def test_shard_indices_tiny_dataset_equal_lengths(hvd):
    """Pad amount can exceed n (n=1, size=4): tiling must still give every
    rank exactly `per` indices — unequal lengths desync collective step
    counts and stall the job."""
    for n, size in [(1, 4), (3, 7), (5, 2)]:
        slices = [
            shard_indices(n, rank=r, size=size, shuffle=False)
            for r in range(size)
        ]
        per = -(-n // size)
        assert [len(s) for s in slices] == [per] * size, (n, size, slices)
        union = set(i for s in slices for i in s.tolist())
        assert union == set(range(n))


def test_shard_indices_drop_last(hvd):
    slices = [
        shard_indices(10, rank=r, size=4, shuffle=False, drop_last=True)
        for r in range(4)
    ]
    assert all(len(s) == 2 for s in slices)
    flat = sorted(i for s in slices for i in s.tolist())
    assert flat == list(range(8))


@pytest.mark.parametrize("prefetch", [0, 2, 10])
def test_sharded_loader_round_trip(hvd, prefetch):
    import jax

    n, bs = 64, 16
    x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    y = np.arange(n, dtype=np.int32)
    loader = ShardedLoader(
        (x, y), bs, shuffle=True, seed=3, prefetch=prefetch
    )
    assert len(loader) == n // bs
    seen = []
    for xb, yb in loader:
        assert isinstance(xb, jax.Array)
        assert xb.shape == (bs, 3)
        assert xb.sharding.spec[0] is not None  # sharded over the data axis
        xb_np, yb_np = np.asarray(xb), np.asarray(yb)
        # rows stay paired with labels through shuffling and sharding
        np.testing.assert_array_equal(xb_np, x[yb_np])
        seen.extend(yb_np.tolist())
    assert sorted(seen) == list(range(n))
    # epoch reshuffle changes batch order deterministically
    first = [np.asarray(yb).tolist() for _, yb in loader]
    loader.set_epoch(1)
    second = [np.asarray(yb).tolist() for _, yb in loader]
    assert first != second
    assert sorted(sum(first, [])) == sorted(sum(second, []))


def test_sharded_loader_single_array_and_errors(hvd):
    import jax

    x = np.ones((32, 2), np.float32)
    loader = ShardedLoader(x, 8, shuffle=False)
    batches = list(loader)
    assert len(batches) == 4
    assert isinstance(batches[0], jax.Array)

    with pytest.raises(ValueError, match="disagree on dim 0"):
        ShardedLoader((x, np.ones((5,), np.float32)), 8)
    with pytest.raises(ValueError, match="divide"):
        list(ShardedLoader(x, 12))  # 12 % 8 devices != 0
    with pytest.raises(ValueError, match="batch_size"):
        ShardedLoader(x, 0)
    # drop_last=False with an indivisible tail fails at iterator start,
    # not mid-epoch on the tail device_put
    bad_tail = ShardedLoader(
        np.ones((36, 2), np.float32), 16, drop_last=False
    )
    with pytest.raises(ValueError, match="trailing batch"):
        list(bad_tail)
    # divisible tail works and is yielded
    ok_tail = ShardedLoader(
        np.ones((40, 2), np.float32), 16, drop_last=False, shuffle=False
    )
    shapes = [np.asarray(b).shape[0] for b in ok_tail]
    assert shapes == [16, 16, 8]


def test_shard_indices_seed_epoch_no_collision(hvd):
    """Satellite regression (ISSUE 15): RandomState(seed + epoch) made
    (seed=0, epoch=1) and (seed=1, epoch=0) the SAME stream; the mixed
    hash seeding must keep them distinct — and distinct again under a
    bumped replay_epoch."""
    a = shard_indices(103, rank=0, size=4, seed=0, epoch=1)
    b = shard_indices(103, rank=0, size=4, seed=1, epoch=0)
    assert not np.array_equal(a, b)
    base = shard_indices(103, rank=0, size=4, seed=0, epoch=0)
    replay = shard_indices(
        103, rank=0, size=4, seed=0, epoch=0, replay_epoch=1)
    assert not np.array_equal(base, replay)


def test_sharded_loader_set_epoch_while_iterating_raises(hvd):
    """Satellite (ISSUE 15): set_epoch mid-iteration used to silently
    change nothing (the order was already materialized at __iter__) —
    now the epoch snapshots at __iter__ and a live-iterator call
    raises."""
    x = np.ones((32, 2), np.float32)
    loader = ShardedLoader(x, 8, shuffle=False)
    it = iter(loader)
    next(it)
    with pytest.raises(RuntimeError, match="iterator is live"):
        loader.set_epoch(2)
    it.close()
    loader.set_epoch(2)  # legal again once the iterator closed
    assert len(list(loader)) == 4


def test_sharded_loader_drives_training(hvd):
    """End to end: loader batches feed a jitted DP train step and the loss
    decreases on a learnable teacher task."""
    import jax
    import jax.numpy as jnp
    import optax

    rng = np.random.RandomState(0)
    Wt = rng.randn(8, 4).astype(np.float32)
    X = rng.randn(128, 8).astype(np.float32)
    Y = np.argmax(X @ Wt, axis=1).astype(np.int32)

    import horovod_tpu as hvd_mod
    from horovod_tpu.training import replicate

    tx = hvd_mod.DistributedOptimizer(optax.sgd(0.5))
    params = replicate({"w": jnp.zeros((8, 4), jnp.float32)})
    opt_state = replicate(tx.init({"w": jnp.zeros((8, 4), jnp.float32)}))

    @jax.jit
    def step(p, s, xb, yb):
        def loss_fn(p_):
            logits = xb @ p_["w"]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()

        loss, g = jax.value_and_grad(loss_fn)(p)
        up, s = tx.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    loader = ShardedLoader((X, Y), 32, seed=1)
    losses = []
    for epoch in range(6):
        loader.set_epoch(epoch)
        for xb, yb in loader:
            params, opt_state, loss = step(params, opt_state, xb, yb)
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5
