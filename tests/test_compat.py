"""Framework version floors (VERDICT r3 Missing #5: the reference's CI
matrix has no analog here; these pin the supported-version floor testably)."""

import warnings

import pytest

from horovod_tpu import compat


def test_live_environment_meets_floors():
    """The baked-in jax/flax/optax (and TF/torch when imported) must satisfy
    the floors — a silent downgrade of the environment pins fails here."""
    import importlib

    live = {}
    for name in compat.MIN_VERSIONS:
        try:
            live[name] = importlib.import_module(name).__version__
        except ImportError:
            continue
    assert "jax" in live and "numpy" in live
    assert compat.check_versions(live) == []


def test_floor_violation_detected():
    probs = compat.check_versions({"jax": "0.4.13", "torch": "1.13.1"})
    assert len(probs) == 2
    assert any("jax 0.4.13" in p for p in probs)
    assert any("torch 1.13.1" in p for p in probs)


def test_version_parse_tolerates_local_suffixes():
    assert compat._parse("2.13.0+cpu") == [2, 13, 0]
    assert compat._parse("0.9") == [0, 9, 0]
    assert compat._parse("2.0.0rc1") == [2, 0, 0]


def test_init_warns_on_unsupported(monkeypatch, hvd):
    hvd.shutdown()
    monkeypatch.setitem(compat.MIN_VERSIONS, "jax", ("999.0.0", "the future"))
    with pytest.warns(RuntimeWarning, match="below the supported floor"):
        hvd.init()


def test_init_silent_when_supported(hvd):
    hvd.shutdown()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        hvd.init()
    assert not [x for x in w if "supported floor" in str(x.message)]
