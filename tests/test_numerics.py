"""Numerics guard (ISSUE 9): in-jit gradient/loss anomaly detection with
atomic step skip, dynamic loss scaling, bounded skip/replay, corrupting-rank
fingerprint quarantine + elastic eviction, and the poison-free publish gate.

Acceptance pins (all on the 8-device CPU mesh, deterministic chaos):

- ``grad_nan_at_step=3``: the step is skipped with weights AND
  error-feedback residuals bit-identical to pre-step, training resumes,
  and the trajectory matches a clean run that never saw the batch.
- ``grad_corrupt_rank=5:4``: rank 5 is named within one step, goes
  SUSPECT, and is evicted via the elastic 8→7 path.
- ``grad_spike`` during an active publish: the publisher rejects the
  generation and the subscriber's ``reconstruction`` still matches the
  last healthy commit.
"""

import os
import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

from horovod_tpu.compression import Compression
from horovod_tpu.observability import metrics
from horovod_tpu.resilience import chaos, health, loop, numerics
from horovod_tpu.resilience.health import HealthState

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_numerics():
    from horovod_tpu.analysis import sanitizer

    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    numerics.reset()
    sanitizer.reset()  # the fingerprint plane's fallback store
    yield
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.reset()
    numerics.reset()
    sanitizer.reset()


def _params():
    return {"w": jnp.ones(4, jnp.float32)}


def _g(v):
    return {"w": jnp.full(4, v, jnp.float32)}


# ------------------------------------------------------------- guard unit


@pytest.mark.numerics
class TestGuard:
    def test_good_step_matches_unguarded(self):
        tx = numerics.guard(optax.adam(1e-2))
        plain = optax.adam(1e-2)
        p = _params()
        sg, sp = tx.init(p), plain.init(p)
        for v in (0.5, -0.25, 0.1):
            ug, sg = tx.update(_g(v), sg, p)
            up, sp = plain.update(_g(v), sp, p)
            np.testing.assert_array_equal(
                np.asarray(ug["w"]), np.asarray(up["w"]))
        v = numerics.verdict(sg)
        assert v["count"] == 3 and v["bad_count"] == 0

    @pytest.mark.parametrize("poison", [np.nan, np.inf, -np.inf])
    def test_nonfinite_grads_skip_atomically(self, poison):
        tx = numerics.guard(optax.adam(1e-2))
        p = _params()
        st = tx.init(p)
        _, st = tx.update(_g(0.5), st, p)
        before = [np.asarray(l).copy()
                  for l in jax.tree_util.tree_leaves(st.inner)]
        u, st = tx.update(_g(poison), st, p)
        np.testing.assert_array_equal(np.asarray(u["w"]), 0.0)
        after = jax.tree_util.tree_leaves(st.inner)
        for a, b in zip(before, after):
            np.testing.assert_array_equal(a, np.asarray(b))
        v = numerics.verdict(st)
        assert v["bad_count"] == 1 and v["bad_streak"] == 1
        assert v["last_bad"] and not v["last_finite"]

    def test_nonfinite_loss_marks_bad(self):
        tx = numerics.guard(optax.sgd(0.1))
        p = _params()
        st = tx.init(p)
        u, st = tx.update(_g(0.5), st, p, loss=jnp.float32(np.nan))
        np.testing.assert_array_equal(np.asarray(u["w"]), 0.0)
        assert numerics.verdict(st)["bad_count"] == 1

    def test_spike_detected_after_warmup_only(self):
        tx = numerics.guard(optax.sgd(0.1), warmup=3, spike_factor=5.0)
        p = _params()
        st = tx.init(p)
        # a 100x "spike" INSIDE warmup passes (and is absorbed)
        u, st = tx.update(_g(0.5), st, p)
        u, st = tx.update(_g(50.0), st, p)
        assert numerics.verdict(st)["bad_count"] == 0
        for _ in range(3):
            u, st = tx.update(_g(0.5), st, p)
        ewma_before = numerics.verdict(st)["ewma"]
        u, st = tx.update(_g(500.0), st, p)
        v = numerics.verdict(st)
        assert v["bad_count"] == 1 and v["last_bad"]
        np.testing.assert_array_equal(np.asarray(u["w"]), 0.0)
        # the spike did NOT raise its own bar
        assert v["ewma"] == pytest.approx(ewma_before)
        # and a normal step afterwards resumes cleanly
        u, st = tx.update(_g(0.5), st, p)
        assert numerics.verdict(st)["bad_streak"] == 0
        assert np.all(np.asarray(u["w"]) != 0)

    def test_ewma_seeds_on_first_good_step_after_bad_start(self):
        """Review hardening: a BAD step 0 (chaos, loss-scale hunting)
        must not strand the EWMA baseline near 0 — the seed fires on the
        first GOOD norm, so the spike bar at warmup is the full
        spike_factor x baseline, not a fraction of it."""
        tx = numerics.guard(optax.sgd(0.1), warmup=2, spike_factor=10.0)
        p = _params()
        st = tx.init(p)
        _, st = tx.update(_g(np.nan), st, p)  # bad step 0
        _, st = tx.update(_g(0.5), st, p)     # first good: seeds EWMA
        assert numerics.verdict(st)["ewma"] == pytest.approx(1.0)
        # 3x the baseline after warmup is ordinary fluctuation, not a
        # spike (with a count==0-keyed seed the bar would sit far lower)
        _, st = tx.update(_g(0.5), st, p)
        u, st = tx.update(_g(1.5), st, p)
        assert numerics.verdict(st)["last_bad"] is False
        assert np.all(np.asarray(u["w"]) != 0)

    def test_bad_step_preserves_negative_zero_params(self):
        """Review hardening: the builders apply the discarded update as
        ``p + u``, and IEEE gives ``-0.0 + (+0.0) = +0.0`` — a sign-bit
        flip that breaks the bit-identical-skip contract. The guard
        discards with NEGATIVE zero (``p + (-0.0) = p`` for every p)."""
        tx = numerics.guard(optax.sgd(0.1))
        p = {"w": jnp.array([-0.0, 0.0, 1.0], jnp.float32)}
        st = tx.init(p)
        u, st = tx.update(
            {"w": jnp.full(3, np.nan, jnp.float32)}, st, p)
        got = np.asarray(optax.apply_updates(p, u)["w"])
        np.testing.assert_array_equal(got, np.asarray(p["w"]))
        assert np.signbit(got[0]) and not np.signbit(got[1])
        # a GOOD step still applies real updates
        u, st = tx.update(_g(0.5), st, p)
        assert np.all(np.asarray(u["w"]) != 0)

    def test_standalone_hook_feeds_gauges_without_fingerprint(self):
        """Review hardening: the troubleshooting contract is that
        HOROVOD_NUMERICS_GUARD=1 *alone* feeds the numerics_guard_*
        gauges and consumes fired chaos charges — without the elastic
        wrapper or the fingerprint plane. The standalone hook reads the
        verdict LAGGED (staged async copy, noted one boundary late) so a
        plain jitted loop keeps its dispatch pipeline."""
        numerics.configure(fingerprint=False)
        chaos.configure("grad_nan_at_step=1")
        tx = numerics.guard(optax.sgd(0.1))
        p = _params()
        st = tx.init(p)
        _, st = tx.update(_g(0.5), st, p)
        assert numerics.maybe_note_output(0, st) is None  # staged only
        _, st = tx.update(_g(0.5), st, p)  # count==1: injection fires
        v = numerics.maybe_note_output(1, st)
        assert v is not None and v["count"] == 1  # step 0, one late
        assert metrics.value("numerics_guard_bad_steps") == 0.0
        v = numerics.flush_staged()  # the last boundary's verdict
        assert v is not None and v["bad_count"] == 1
        assert metrics.value("numerics_guard_bad_steps") == 1.0
        assert chaos.grad_nan_step() is None  # consumed via the hook
        assert metrics.value(
            "resilience_chaos_injected", site="grad_nan_at_step") == 1.0
        assert numerics.flush_staged() is None  # drained

    def test_warmup_counts_good_steps_only(self):
        """Review hardening: the documented contract is `warmup` GOOD
        steps — bad steps don't feed the EWMA, so they must not count
        toward its baseline either. Two good steps after a bad start is
        still inside warmup=3: the 50x norm is absorbed, not flagged."""
        tx = numerics.guard(optax.sgd(0.1), warmup=3, spike_factor=5.0)
        p = _params()
        st = tx.init(p)
        _, st = tx.update(_g(np.nan), st, p)  # bad: not a warmup sample
        _, st = tx.update(_g(0.5), st, p)
        _, st = tx.update(_g(0.5), st, p)
        # total count is 3 (>= warmup) but only 2 good samples: unarmed
        u, st = tx.update(_g(25.0), st, p)
        v = numerics.verdict(st)
        assert v["bad_count"] == 1  # only the NaN step
        assert np.all(np.asarray(u["w"]) != 0)  # the 50x step applied
        # one more good sample arms it; the next blow-up is flagged
        _, st = tx.update(_g(0.5), st, p)
        u, st = tx.update(_g(500.0), st, p)
        v = numerics.verdict(st)
        assert v["last_bad"] and v["bad_count"] == 2
        np.testing.assert_array_equal(np.asarray(u["w"]), 0.0)

    def test_streak_counts_consecutive_bad(self):
        tx = numerics.guard(optax.sgd(0.1))
        p = _params()
        st = tx.init(p)
        for _ in range(3):
            _, st = tx.update(_g(np.nan), st, p)
        v = numerics.verdict(st)
        assert v["bad_streak"] == 3 and v["bad_count"] == 3
        _, st = tx.update(_g(0.5), st, p)
        assert numerics.verdict(st)["bad_streak"] == 0

    def test_int_leaves_ride_through(self):
        """Integer leaves are excluded from the norm (they cannot be
        non-finite) and the guarded update matches the unguarded one."""
        tx = numerics.guard(optax.sgd(1.0))
        plain = optax.sgd(1.0)
        p = {"w": jnp.ones(4), "steps": jnp.zeros((2,), jnp.int32)}
        sg, sp = tx.init(p), plain.init(p)
        g = {"w": jnp.full(4, 0.5), "steps": jnp.ones((2,), jnp.int32)}
        ug, sg = tx.update(g, sg, p)
        up, sp = plain.update(g, sp, p)
        for k in p:
            np.testing.assert_array_equal(
                np.asarray(ug[k]), np.asarray(up[k]))
        v = numerics.verdict(sg)
        assert v["bad_count"] == 0
        # only the float dtype contributes to the norm
        assert v["last_norm"] == pytest.approx(1.0)

    def test_per_dtype_norms_recorded(self):
        tx = numerics.guard(optax.sgd(1.0))
        p = {"a": jnp.ones((3,), jnp.float32), "b": jnp.ones((2,), jnp.bfloat16)}
        st = tx.init(p)
        g = {"a": jnp.full((3,), 2.0, jnp.float32),
             "b": jnp.full((2,), 1.0, jnp.bfloat16)}
        _, st = tx.update(g, st, p)
        v = numerics.verdict(st)
        assert set(v["per_dtype"]) == {"float32", "bfloat16"}
        assert v["per_dtype"]["float32"] == pytest.approx(np.sqrt(12.0))
        assert v["per_dtype"]["bfloat16"] == pytest.approx(np.sqrt(2.0))

    def test_loss_scale_unscales_and_backs_off(self):
        tx = numerics.guard(optax.sgd(0.1), loss_scale=16.0)
        p = _params()
        st = tx.init(p)
        assert float(np.asarray(numerics.current_scale(st))) == 16.0
        # gradients arrive scaled by 16 (the builder scaled the loss);
        # the applied update must be the UNSCALED sgd step
        u, st = tx.update(_g(16.0 * 0.5), st, p)
        np.testing.assert_allclose(np.asarray(u["w"]), -0.05, rtol=1e-6)
        # a bad step halves the scale
        _, st = tx.update(_g(np.inf), st, p)
        assert numerics.verdict(st)["loss_scale"] == 8.0

    def test_loss_scale_grows_after_interval(self):
        tx = numerics.guard(
            optax.sgd(0.1), loss_scale=4.0, growth_interval=3)
        p = _params()
        st = tx.init(p)
        for i in range(3):
            _, st = tx.update(_g(4.0 * 0.5), st, p)
        assert numerics.verdict(st)["loss_scale"] == 8.0
        # streak resets after growth: two more good steps keep it at 8
        for i in range(2):
            _, st = tx.update(_g(8.0 * 0.5), st, p)
        assert numerics.verdict(st)["loss_scale"] == 8.0

    def test_unguarded_state_has_no_verdict(self):
        st = optax.adam(1e-2).init(_params())
        assert numerics.verdict(st) is None
        assert numerics.note_step(0, st) is None
        assert float(np.asarray(numerics.current_scale(st))) == 1.0

    def test_distributed_optimizer_wraps_and_env_enables(
            self, hvd, monkeypatch):
        tx = hvd.DistributedOptimizer(optax.adam(1e-2), numerics_guard=True)
        assert numerics.is_guarded(tx)
        monkeypatch.setenv("HOROVOD_NUMERICS_GUARD", "1")
        assert numerics.is_guarded(hvd.DistributedOptimizer(optax.sgd(0.1)))
        monkeypatch.delenv("HOROVOD_NUMERICS_GUARD")
        assert not numerics.is_guarded(
            hvd.DistributedOptimizer(optax.sgd(0.1)))
        # loss_scale implies the guard
        assert numerics.is_guarded(
            hvd.DistributedOptimizer(optax.sgd(0.1), loss_scale="dynamic"))


# ------------------------------------------------- chaos charge accounting


@pytest.mark.numerics
@pytest.mark.chaos
class TestChaosCharges:
    def test_parse_grammar(self):
        cfg = chaos.parse_spec(
            "grad_nan_at_step=3,grad_spike_at_step=7:100.0,"
            "grad_corrupt_rank=5:4")
        assert cfg == {
            "grad_nan_at_step": 3,
            "grad_spike_at_step": (7, 100.0),
            "grad_corrupt_rank": (5, 4),
        }
        # scale defaults when omitted
        assert chaos.parse_spec("grad_spike_at_step=2")[
            "grad_spike_at_step"] == (2, 1e3)
        with pytest.raises(ValueError):
            chaos.parse_spec("grad_corrupt_rank=5")

    def test_nan_charge_fires_exactly_once(self):
        chaos.configure("grad_nan_at_step=1")
        tx = numerics.guard(optax.sgd(0.1))
        p = _params()
        st = tx.init(p)
        for i in range(4):
            _, st = tx.update(_g(0.5), st, p)
            numerics.note_step(i, st)
        v = numerics.verdict(st)
        assert v["bad_count"] == 1  # exactly one injection
        assert chaos.grad_nan_step() is None  # consumed
        # non-sticky evidence: the bit marks only the firing step, so a
        # checkpointed later state can never replay it into a fresh run
        assert v["chaos_fired"] == 0
        assert metrics.value(
            "resilience_chaos_injected", site="grad_nan_at_step") == 1.0

    def test_spike_charge_fires_exactly_once(self):
        chaos.configure("grad_spike_at_step=4:1000")
        tx = numerics.guard(optax.sgd(0.1), warmup=2)
        p = _params()
        st = tx.init(p)
        for i in range(6):
            _, st = tx.update(_g(0.5), st, p)
            numerics.note_step(i, st)
        v = numerics.verdict(st)
        assert v["bad_count"] == 1
        assert chaos.grad_spike() is None
        assert metrics.value(
            "resilience_chaos_injected", site="grad_spike_at_step") == 1.0

    def test_overlapping_nan_and_spike_charges_compose(self):
        """Review hardening: grad_nan and grad_spike armed at the SAME
        step compose (NaN × scale stays NaN). With a where-select
        overwrite the gradients came out a finite ×scale — inside the
        default warmup that is not even a BAD step — while the fired
        bitmask still told note_step the NaN path was exercised."""
        chaos.configure("grad_nan_at_step=1,grad_spike_at_step=1:100")
        tx = numerics.guard(optax.sgd(0.1))
        p = _params()
        st = tx.init(p)
        for i in range(3):
            _, st = tx.update(_g(0.5), st, p)
            numerics.note_step(i, st)
        v = numerics.verdict(st)
        # the step really went non-finite: the finiteness detector fired
        assert v["bad_count"] == 1
        assert chaos.grad_nan_step() is None  # both charges consumed
        assert chaos.grad_spike() is None
        assert metrics.value(
            "resilience_chaos_injected", site="grad_nan_at_step") == 1.0
        assert metrics.value(
            "resilience_chaos_injected", site="grad_spike_at_step") == 1.0

    def test_unfired_charge_stays_armed(self):
        """A charge whose step never arrives is NOT consumed — mirrors
        the PR-8 hardening."""
        chaos.configure("grad_nan_at_step=50")
        tx = numerics.guard(optax.sgd(0.1))
        p = _params()
        st = tx.init(p)
        for i in range(3):
            _, st = tx.update(_g(0.5), st, p)
            numerics.note_step(i, st)
        assert chaos.grad_nan_step() == 50  # still armed
        assert metrics.value(
            "resilience_chaos_injected", site="grad_nan_at_step") is None

    def test_restored_state_past_k_never_counts_a_phantom_injection(self):
        """Review hardening: a guard state restored with its counter
        already past K can never execute the traced `count == K`
        injection — note_step must NOT consume the charge or count an
        injection that never ran (chaos_fired is the evidence)."""
        tx = numerics.guard(optax.sgd(0.1))
        p = _params()
        st = tx.init(p)
        for i in range(5):
            _, st = tx.update(_g(0.5), st, p)  # no chaos armed: count=5
        chaos.configure("grad_nan_at_step=3")  # armed AFTER count passed 3
        _, st = tx.update(_g(0.5), st, p)
        numerics.note_step(5, st)
        assert chaos.grad_nan_step() == 3  # still armed
        assert metrics.value(
            "resilience_chaos_injected", site="grad_nan_at_step") is None
        assert numerics.verdict(st)["chaos_fired"] == 0

    def test_boundary_dedupes_consecutive_same_step(self):
        """Review hardening: an instrumented step inside the elastic
        wrapper drives the boundary twice per step — the second call for
        the same step must be a no-op (one publish, one cross-check),
        while a later (or rolled-back earlier) step still runs."""
        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        with _world(4):
            numerics.boundary(0)
            n0 = metrics.value("numerics_fingerprints_checked")
            numerics.boundary(0)  # duplicate: deduped
            assert metrics.value("numerics_fingerprints_checked") == n0
            numerics.boundary(1)
            assert metrics.value("numerics_fingerprints_checked") == n0 + 1
            numerics.boundary(0)  # rollback revisits step 0: runs again
            assert metrics.value("numerics_fingerprints_checked") == n0 + 2

    def test_republish_keeps_chaos_perturbation_sticky(self):
        """Review hardening: a second publish of the SAME step (two
        boundary hooks with diverged counters) must keep the perturbed
        victim record instead of overwriting it clean after the charge
        was consumed."""
        import json

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        chaos.configure("grad_corrupt_rank=2:0")
        with _world(4):
            numerics.publish_fingerprint(0)
            assert chaos.grad_corrupt() is None  # consumed
            numerics.publish_fingerprint(0)  # republish, charge gone
        rec = json.loads(store.get(numerics.fingerprint_key(0, 2)))
        assert rec["finite"] == 0  # still perturbed, not overwritten

    def test_corrupt_rank_stays_armed_in_one_rank_world(self):
        """grad_corrupt_rank targets a peer; a 1-rank world has none, so
        the charge must stay armed instead of counting a perturbation
        that cannot exist."""
        chaos.configure("grad_corrupt_rank=5:0")
        numerics.configure(fingerprint=True)
        numerics.publish_fingerprint(0)
        assert chaos.grad_corrupt() == (5, 0)  # world=1: still armed
        assert metrics.value(
            "resilience_chaos_injected", site="grad_corrupt_rank") is None
        assert numerics.cross_check_fingerprints(0) is None


# ------------------------------------------------- fingerprint plane


@pytest.mark.numerics
class TestFingerprints:
    def test_publish_perturbs_chaos_victim_and_cross_check_names_it(self):
        """Single-controller publish writes one record per rank; the
        armed grad_corrupt_rank charge perturbs ONLY the victim's copy
        (consumed on perturb), and the cross-check names it."""
        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        chaos.configure("grad_corrupt_rank=3:2")
        with _world(4):
            numerics.publish_fingerprint(
                2, {"step": 2, "finite": 1, "norm": 1.5, "per_dtype": {}})
            assert chaos.grad_corrupt() is None  # consumed by the perturb
            found = numerics.cross_check_fingerprints(2)
        assert found is not None and found[0]["rank"] == 3
        assert not found[0]["finite"]
        assert metrics.value(
            "resilience_chaos_injected", site="grad_corrupt_rank") == 1.0
        assert metrics.value("numerics_fingerprints_checked") == 1.0
        assert numerics.take_corrupt_ranks() == [3]

    def test_cross_check_flags_outlier_and_feeds_health(self):
        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        for r in range(8):
            rec = {"step": 1, "finite": 1, "norm": 1.0, "per_dtype": {}}
            if r == 5:
                rec["norm"] = 1e6  # SDC-flavored outlier, still finite
            store.put(
                numerics.fingerprint_key(1, r),
                __import__("json").dumps(rec).encode())
        with _world(8):
            found = numerics.cross_check_fingerprints(1)
        assert found is not None and found[0]["rank"] == 5
        assert numerics.take_corrupt_ranks() == [5]
        assert numerics.take_corrupt_ranks() == []  # popped
        assert health.health_state() == HealthState.SUSPECT
        assert "rank 5" in health.snapshot()["reason"]
        assert metrics.value("numerics_corrupt_ranks", rank=5) == 1.0
        assert metrics.value("resilience_numeric_corruptions") == 1.0

    def test_garbled_blob_is_a_verdict_not_an_absence(self):
        """Review hardening: a rank whose published fingerprint is
        unparseable bytes is judged like a non-finite record — garbled
        output often comes from the exact corrupt host this plane hunts,
        and dropping it would mark the step fully checked with the
        most-broken rank never examined."""
        import json

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        for r in range(4):
            blob = (
                b"\xff\x00 not json \xfe" if r == 2 else
                json.dumps(
                    {"step": 1, "finite": 1, "norm": 1.0}).encode()
            )
            store.put(numerics.fingerprint_key(1, r), blob)
        with _world(4):
            found = numerics.cross_check_fingerprints(1)
        assert found is not None and found[0]["rank"] == 2
        assert not found[0]["finite"]
        assert numerics.take_corrupt_ranks() == [2]
        # all 4 records were present (garbled ≠ missing): no deferral
        assert metrics.value("numerics_fingerprints_checked") == 1.0

    def test_schedule_divergence_defers_to_sanitizer(self):
        """A rank the PR-8 sanitizer already named at the same step is a
        control-flow bug, not data corruption — no numerics verdict."""
        from horovod_tpu.analysis import sanitizer

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        for r in range(4):
            rec = {"step": 3, "finite": 1 if r != 2 else 0,
                   "norm": 1.0 if r != 2 else None, "per_dtype": {}}
            store.put(
                numerics.fingerprint_key(3, r),
                __import__("json").dumps(rec).encode())
        old = sanitizer._last_divergence
        sanitizer._last_divergence = {"step": 3, "rank": 2, "op": "x"}
        try:
            with _world(4):
                assert numerics.cross_check_fingerprints(3) is None
        finally:
            sanitizer._last_divergence = old
        assert not numerics.quarantine_pending()

    def test_low_side_outlier_flagged_but_zero_sentinel_is_not(self):
        """Review hardening: a stuck-at-zero SDC rank (norm far BELOW the
        family median) is quarantined like a blow-up; an exact 0.0 is the
        default record's no-signal sentinel and never a verdict."""
        import json

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        norms = {0: 1.0, 1: 1.1, 2: 1e-9, 3: 0.9}
        for r, n in norms.items():
            store.put(
                numerics.fingerprint_key(1, r),
                json.dumps({"step": 1, "finite": 1, "norm": n}).encode())
        with _world(4):
            found = numerics.cross_check_fingerprints(1)
        assert found is not None and found[0]["rank"] == 2
        assert numerics.take_corrupt_ranks() == [2]
        # exact-zero sentinel: not flagged
        store2 = _Store()
        numerics.configure(kv=store2)
        for r, n in {0: 1.0, 1: 1.1, 2: 0.0, 3: 0.9}.items():
            store2.put(
                numerics.fingerprint_key(2, r),
                json.dumps({"step": 2, "finite": 1, "norm": n}).encode())
        with _world(4):
            assert numerics.cross_check_fingerprints(2) is None

    def test_set_step_first_call_does_not_preempt_real_record(self):
        """Review hardening: the very first set_step(0) fires BEFORE step
        0 executes — it must not publish a premature default record whose
        boundary dedupe then suppresses the real (possibly corrupt)
        step-0 fingerprint."""
        import json

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        numerics.set_step(0)  # InstrumentedStep's first call, pre-step
        assert store.get(numerics.fingerprint_key(0, 0)) is None
        # the step runs, goes non-finite; the policy layer notes it and
        # the elastic wrapper drives the boundary with the REAL record
        tx = numerics.guard(optax.sgd(0.1))
        p = _params()
        st = tx.init(p)
        _, st = tx.update(_g(np.nan), st, p)
        numerics.note_step(0, st)
        numerics.boundary(0)
        rec = json.loads(store.get(numerics.fingerprint_key(0, 0)))
        assert rec["finite"] == 0  # the real record, not the default

    def test_deferred_recheck_reports_each_finding_once(self):
        """Review hardening: a step kept pending by a missing peer must
        not re-strike health / re-quarantine the SAME finding on every
        retry boundary."""
        import json

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        with _world(4):
            for r in range(3):  # rank 3 never publishes (dead peer)
                store.put(
                    numerics.fingerprint_key(0, r),
                    json.dumps({
                        "step": 0, "finite": 1 if r != 2 else 0,
                        "norm": 1.0 if r != 2 else None}).encode())
            first = numerics.cross_check_fingerprints(0)
            assert first is not None and first[0]["rank"] == 2
            assert numerics.take_corrupt_ranks() == [2]
            # retries while rank 3 stays missing: no duplicate findings
            for b in range(1, 4):
                numerics.boundary(b)
        assert metrics.value("numerics_corrupt_ranks", rank=2) == 1.0
        assert metrics.value("resilience_numeric_corruptions") == 1.0
        assert not numerics.quarantine_pending()  # not re-quarantined
        # deferred rechecks do NOT inflate "steps checked": steps 1..3
        # each completed once (+3); step 0's four partial attempts
        # (initial + three rechecks, rank 3 still missing) added nothing
        assert metrics.value("numerics_fingerprints_checked") == 3.0

    def test_deferred_partial_family_defers_norm_verdict(self):
        """Review hardening: a median over a PARTIAL record set must not
        indict a healthy rank (2 of 8 landed — one corrupt at 600, one
        healthy at 0.5 → median 300 puts the HEALTHY rank below
        med/factor, and _flagged would then mute the real culprit
        forever); the norm-relative verdict waits for the complete
        check, which names the true outlier."""
        import json

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        with _world(8):
            for r, n in {2: 600.0, 5: 0.5}.items():
                store.put(
                    numerics.fingerprint_key(0, r),
                    json.dumps(
                        {"step": 0, "finite": 1, "norm": n}).encode())
            assert numerics.cross_check_fingerprints(0) is None
            assert not numerics.quarantine_pending()  # nobody misjudged
            for r in range(8):
                if r in (2, 5):
                    continue
                store.put(
                    numerics.fingerprint_key(0, r),
                    json.dumps(
                        {"step": 0, "finite": 1, "norm": 0.5}).encode())
            found = numerics.cross_check_fingerprints(0)
        assert found is not None and [f["rank"] for f in found] == [2]
        assert numerics.take_corrupt_ranks() == [2]

    def test_exhausted_budget_partial_family_never_convicts(self):
        """Review hardening: when the deferral budget runs out with only
        a sliver of the family landed (flaky KV), the norm-relative
        verdict must STAY silent — a 2-record "majority" of an 8-rank
        world has a partial median that can indict the healthy rank.
        Norm-relative verdicts require every expected record; only
        family-independent non-finite verdicts run on a partial set."""
        import json

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        with _world(8):
            for r, n in {2: 0.5, 5: 600.0}.items():
                store.put(
                    numerics.fingerprint_key(0, r),
                    json.dumps(
                        {"step": 0, "finite": 1, "norm": n}).encode())
            # burn the whole retry budget and one exhausted check on top
            for _ in range(numerics.PENDING_CHECK_ATTEMPTS + 1):
                assert numerics.cross_check_fingerprints(0) is None
        assert not numerics.quarantine_pending()
        assert health.health_state() == HealthState.HEALTHY

    def test_claimed_boundary_silences_instrumented_hook(self):
        """Review hardening: once the elastic wrapper claims the
        boundary, InstrumentedStep's set_step hook must not publish —
        two hooks with diverged counters double-publish every step."""
        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        numerics.claim_boundary()
        numerics.set_step(0)
        numerics.set_step(1)  # would publish boundary(0) if not claimed
        assert store.get(numerics.fingerprint_key(0, 0)) is None
        with _world(2):
            numerics.boundary(0)  # the owner still publishes
        assert store.get(numerics.fingerprint_key(0, 0)) is not None

    def test_boundary_noop_when_disabled(self):
        numerics.configure(fingerprint=False)
        assert numerics.boundary(0) is None
        numerics.set_step(1)  # must not publish anything either
        assert numerics._store().get(numerics.fingerprint_key(0, 0)) is None

    def test_multi_device_process_publishes_owned_device_ranks(self):
        """Pass-5 hardening: with several devices per process (a 2-host
        × 4-chip topology) each process publishes one record per OWNED
        device rank, indexed by DEVICE rank — keying by process rank
        misattributed a corrupt chip's norm to the wrong record and left
        the cross-check scanning process-rank keys."""
        import json
        from unittest import mock

        from horovod_tpu import basics

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        rec = {"step": 0, "finite": 1, "norm": 1.0, "per_dtype": {},
               "rank_norms": [float(r) + 1.0 for r in range(8)]}

        def _proc(prank):
            return [
                mock.patch.object(
                    basics, "is_initialized", return_value=True),
                mock.patch.object(basics, "size", return_value=8),
                mock.patch.object(basics, "process_size", return_value=2),
                mock.patch.object(
                    basics, "process_rank", return_value=prank),
            ]

        ps = _proc(1)
        for p in ps:
            p.start()
        try:
            numerics.publish_fingerprint(0, dict(rec))
        finally:
            for p in ps:
                p.stop()
        # process 1 owns device ranks 4..7 and publishes exactly those,
        # each carrying ITS OWN pre-reduction norm
        for r in range(4):
            assert store.get(numerics.fingerprint_key(0, r)) is None
        for r in range(4, 8):
            got = json.loads(store.get(numerics.fingerprint_key(0, r)))
            assert got["norm"] == float(r) + 1.0
        ps = _proc(0)
        for p in ps:
            p.start()
        try:
            numerics.publish_fingerprint(0, dict(rec))
            # rank 0 cross-checks all 8 DEVICE ranks, not 2 process ranks
            assert numerics.cross_check_fingerprints(0) is None
        finally:
            for p in ps:
                p.stop()
        assert metrics.value("numerics_fingerprints_checked") == 1.0

    def test_corrupt_charge_consumed_by_owning_process_only(self):
        """The grad_corrupt_rank victim is a DEVICE rank: only the
        process that owns it perturbs (and consumes the charge); other
        processes leave it armed."""
        import json
        from unittest import mock

        from horovod_tpu import basics

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        chaos.configure("grad_corrupt_rank=5:0")

        def _publish(prank):
            ps = [
                mock.patch.object(
                    basics, "is_initialized", return_value=True),
                mock.patch.object(basics, "size", return_value=8),
                mock.patch.object(basics, "process_size", return_value=2),
                mock.patch.object(
                    basics, "process_rank", return_value=prank),
            ]
            for p in ps:
                p.start()
            try:
                numerics.publish_fingerprint(0)
            finally:
                for p in ps:
                    p.stop()

        _publish(0)  # device rank 5 belongs to process 1, not 0
        assert chaos.grad_corrupt() == (5, 0)  # still armed
        _publish(1)
        assert chaos.grad_corrupt() is None  # consumed by the owner
        rec = json.loads(store.get(numerics.fingerprint_key(0, 5)))
        assert rec["finite"] == 0

    def test_release_boundary_restores_instrumented_hook(self):
        """Review hardening: a driver's boundary claim must be released
        when its run ends — a later standalone InstrumentedStep loop in
        the same process otherwise silently publishes nothing."""
        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        numerics.claim_boundary()
        numerics.set_step(0)
        numerics.set_step(1)
        assert store.get(numerics.fingerprint_key(0, 0)) is None
        numerics.release_boundary()
        numerics.set_step(2)  # publishes boundary(1) again
        assert store.get(numerics.fingerprint_key(1, 0)) is not None

    def test_impossible_corrupt_charge_warns_loudly(self, caplog):
        """Review hardening: grad_corrupt_rank=0 (the driver) or an
        out-of-range rank can never fire in a multi-rank world — warn
        loudly once instead of silently injecting nothing."""
        import logging

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        chaos.configure("grad_corrupt_rank=0:0")
        with _world(4), caplog.at_level(
                logging.WARNING,
                logger="horovod_tpu.resilience.numerics"):
            numerics.publish_fingerprint(0)
            numerics.publish_fingerprint(1)
        assert chaos.grad_corrupt() == (0, 0)  # armed, nothing fired
        hits = [r for r in caplog.records
                if "can never fire" in r.getMessage()]
        assert len(hits) == 1  # loud, and only once

    def test_multiprocess_corrupt_rank0_never_perturbed(self):
        """Review hardening: the MULTI-PROCESS branch must honor the
        never-rank-0 invariant too — process 0 perturbing its own record
        would quarantine the un-evictable driver and gate publication
        forever."""
        import json
        from unittest import mock

        from horovod_tpu import basics

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        chaos.configure("grad_corrupt_rank=0:0")
        ps = [
            mock.patch.object(basics, "is_initialized", return_value=True),
            mock.patch.object(basics, "size", return_value=8),
            mock.patch.object(basics, "process_size", return_value=2),
            mock.patch.object(basics, "process_rank", return_value=0),
        ]
        for p in ps:
            p.start()
        try:
            numerics.publish_fingerprint(0)
        finally:
            for p in ps:
                p.stop()
        assert chaos.grad_corrupt() == (0, 0)  # still armed
        rec = json.loads(store.get(numerics.fingerprint_key(0, 0)))
        assert rec["finite"] == 1  # NOT perturbed

    def test_rank0_quarantine_keeps_gate_closed(self):
        """Review hardening: a corrupt rank the coordinator cannot evict
        (rank 0, the driver) must stay quarantined — draining it would
        re-open publication of a corrupt trainer's weights."""
        from unittest import mock

        from horovod_tpu.resilience import elastic as _elastic

        er = _elastic.ElasticRun(lambda w: (lambda s, i: s))
        er._alive = [0, 1, 2, 3]
        er._devices = [object()] * 4
        er._coord = mock.Mock()
        er._coord.alive.return_value = [0, 1, 2, 3]
        numerics.requeue_corrupt_ranks([0])
        er._poll_membership(0)  # no WorldChanged, nothing evicted
        er._coord.mark_dead.assert_not_called()
        assert numerics.quarantine_pending()  # gate stays closed
        assert numerics.publish_gate_reason(
            None, {"w": np.ones(2)}) == "quarantine"
        er._poll_membership(1)  # idempotent: still gated, still no evict
        er._coord.mark_dead.assert_not_called()
        assert numerics.quarantine_pending()

    def test_evict_failure_requeues_quarantine(self):
        """Review hardening: a transient KV failure in mark_dead must
        not drain the verdict — the publish gate keys on
        quarantine_pending(), so a drained-but-unevicted rank would
        re-open publication from a fleet that still contains it. The
        eviction retries at the next boundary sweep."""
        from unittest import mock

        from horovod_tpu.resilience import elastic as _elastic

        er = _elastic.ElasticRun(lambda w: (lambda s, i: s))
        er._alive = [0, 1, 2, 3]
        er._devices = [object()] * 4
        er._coord = mock.Mock()
        er._coord.alive.return_value = [0, 1, 2, 3]
        er._coord.mark_dead.side_effect = OSError("kv down")
        numerics.requeue_corrupt_ranks([2])
        er._poll_membership(0)
        assert numerics.quarantine_pending()  # verdict preserved
        assert numerics.publish_gate_reason(
            None, {"w": np.ones(2)}) == "quarantine"
        # the KV heals: the next sweep evicts and drains the quarantine
        er._coord.mark_dead.side_effect = None
        er._poll_membership(1)
        er._coord.mark_dead.assert_called_with(2)
        assert not numerics.quarantine_pending()

    def test_instrumented_step_standalone_publishes_real_record(self):
        """Pass-5 hardening: an InstrumentedStep loop WITHOUT the
        elastic wrapper (nobody runs note_step) must publish each step's
        real verdict at the next boundary, not the 0.0-norm default."""
        import json

        from horovod_tpu import training

        store = _Store()
        numerics.configure(fingerprint=True, kv=store)
        tx = numerics.guard(optax.sgd(0.1))

        def step(params, opt_state, i):
            u, st = tx.update(_g(2.0), opt_state, params)
            return optax.apply_updates(params, u), st

        wrapped = training.InstrumentedStep(step)
        p, st = _params(), tx.init(_params())
        for i in range(3):
            p, st = wrapped(p, st, i)
        numerics.boundary(2)  # flush the final step
        for s in range(3):
            rec = json.loads(store.get(numerics.fingerprint_key(s, 0)))
            assert rec["step"] == s
            assert rec["norm"] == pytest.approx(4.0)  # ||2.0 * ones(4)||


class _Store:
    """Minimal put/get KV (the sanitizer _LocalStore surface)."""

    def __init__(self):
        self._d = {}

    def put(self, key, value, ttl=None):
        self._d[key] = value

    def get(self, key):
        return self._d.get(key)


class _world:
    """Pretend basics.is_initialized()/size() report an n-rank world
    without bringing up a mesh (fingerprint-plane unit tests)."""

    def __init__(self, n):
        self.n = n

    def __enter__(self):
        from unittest import mock

        from horovod_tpu import basics

        self._p = [
            mock.patch.object(basics, "is_initialized", return_value=True),
            mock.patch.object(basics, "size", return_value=self.n),
            mock.patch.object(basics, "process_rank", return_value=0),
            mock.patch.object(basics, "process_size", return_value=1),
        ]
        for p in self._p:
            p.start()
        return self

    def __exit__(self, *exc):
        for p in self._p:
            p.stop()
        return False


# ------------------------------------------- checkpoint + emergency gating


@pytest.mark.numerics
class TestCheckpointFiniteness:
    def test_is_valid_checkpoint_rejects_nonfinite(self, tmp_path):
        from horovod_tpu import checkpoint as ckpt

        d = str(tmp_path)
        ckpt.save(d, 1, {"w": np.ones(4, np.float32)})
        ckpt.save(d, 2, {"w": np.array([1, np.nan, 3, 4], np.float32)})
        assert ckpt.is_valid_checkpoint(os.path.join(d, "step_1"))
        assert not ckpt.is_valid_checkpoint(os.path.join(d, "step_2"))
        # resume falls back to the newest VALID (finite) checkpoint
        assert ckpt.latest_step(d) == 1
        assert ckpt.valid_steps(d) == [1]

    def test_finite_check_env_optout(self, tmp_path, monkeypatch):
        """A state that LEGITIMATELY carries non-finite leaves (an
        additive -inf attention-mask buffer) must not invalidate every
        checkpoint the run writes: HOROVOD_CHECKPOINT_FINITE_CHECK=0
        opts the poison sweep out while CRC validation still runs."""
        from horovod_tpu import checkpoint as ckpt

        d = str(tmp_path)
        ckpt.save(d, 1, {"mask": np.full(4, -np.inf, np.float32),
                         "w": np.ones(2, np.float32)})
        assert not ckpt.is_valid_checkpoint(os.path.join(d, "step_1"))
        monkeypatch.setenv(numerics.CKPT_FINITE_ENV, "0")
        assert ckpt.is_valid_checkpoint(os.path.join(d, "step_1"))
        assert ckpt.latest_step(d) == 1

    def test_all_nonfinite_escalates_loudly(self, tmp_path, caplog):
        """Review hardening: when EVERY checkpoint is rejected solely by
        the finiteness sweep, that is a config problem (a model that
        legitimately stores non-finite leaves invalidates everything it
        writes) — resume names the escape hatch at ERROR instead of
        silently restarting from scratch."""
        import logging

        from horovod_tpu import checkpoint as ckpt

        d = str(tmp_path)
        ckpt.save(d, 1, {"m": np.full(2, -np.inf, np.float32)})
        ckpt.save(d, 2, {"m": np.array([np.nan, 1.0], np.float32)})
        with caplog.at_level(logging.ERROR, logger="horovod_tpu"):
            assert ckpt.valid_steps(d) == []
            assert ckpt.latest_step(d) is None
        loud = [r for r in caplog.records
                if "HOROVOD_CHECKPOINT_FINITE_CHECK=0" in r.getMessage()]
        assert len(loud) == 2  # once per walk, not per checkpoint

    def test_mixed_corruption_does_not_blame_the_sweep(self, tmp_path,
                                                       caplog):
        """A directory holding torn archives alongside non-finite ones is
        real corruption territory — the config-problem escalation must
        not fire and point the operator at the wrong knob."""
        import logging

        from horovod_tpu import checkpoint as ckpt

        d = str(tmp_path)
        ckpt.save(d, 1, {"m": np.array([np.nan], np.float32)})
        ckpt.save(d, 2, {"m": np.ones(2, np.float32)})
        with open(os.path.join(d, "step_2", "arrays.npz"), "wb") as f:
            f.write(b"torn")
        with caplog.at_level(logging.ERROR, logger="horovod_tpu"):
            assert ckpt.latest_step(d) is None
        assert not [r for r in caplog.records
                    if "FINITE_CHECK" in r.getMessage()]

    def test_finite_optout_streams_without_materializing(self, tmp_path,
                                                         monkeypatch):
        """Review hardening: with HOROVOD_CHECKPOINT_FINITE_CHECK=0 only
        the streaming CRC check runs — validation must not np.load a
        multi-GB member onto a small-RAM resume host."""
        from horovod_tpu import checkpoint as ckpt

        d = str(tmp_path)
        ckpt.save(d, 1, {"w": np.ones(8, np.float32)})
        monkeypatch.setenv(numerics.CKPT_FINITE_ENV, "0")

        def boom(*a, **k):
            raise AssertionError("np.load materialized a member")

        monkeypatch.setattr(ckpt.np, "load", boom)
        assert ckpt.is_valid_checkpoint(os.path.join(d, "step_1"))
        # a torn archive still fails the streamed CRC
        with open(os.path.join(d, "step_1", "arrays.npz"), "r+b") as f:
            f.truncate(40)
        assert not ckpt.is_valid_checkpoint(os.path.join(d, "step_1"))

    def test_integer_and_object_leaves_unaffected(self, tmp_path):
        from horovod_tpu import checkpoint as ckpt

        d = str(tmp_path)
        ckpt.save(d, 3, {"i": np.arange(4), "s": "meta", "f": np.ones(2)})
        assert ckpt.latest_step(d) == 3
        out = ckpt.restore(d, 3)
        assert out["s"] == "meta"

    def test_emergency_checkpoint_skips_nonfinite_state(self, tmp_path):
        """The live state going NaN right before a preemption must NOT
        displace the newest valid checkpoint."""
        from horovod_tpu import checkpoint as ckpt

        d = str(tmp_path / "ck")

        def step_fn(st, i):
            if i == 2:
                return {"w": st["w"] * np.nan}
            return {"w": st["w"] + 1}

        chaos.configure("sigterm_at_step=3")
        with pytest.raises(loop.Preempted) as ei:
            loop.run(step_fn, {"w": np.zeros(2)}, num_steps=6,
                     checkpoint_dir=d, checkpoint_every=2)
        assert ei.value.step == 3
        assert ei.value.checkpoint_path is None  # nothing was written
        # the periodic step-2 checkpoint (still finite) is the newest valid
        assert ckpt.latest_step(d) == 2
        assert metrics.value(
            "resilience_emergency_checkpoint_skipped") == 1.0

    def test_emergency_checkpoint_still_written_when_finite(self, tmp_path):
        from horovod_tpu import checkpoint as ckpt

        d = str(tmp_path / "ck")
        chaos.configure("sigterm_at_step=2")
        with pytest.raises(loop.Preempted):
            loop.run(lambda st, i: {"w": st["w"] + 1}, {"w": np.zeros(2)},
                     num_steps=5, checkpoint_dir=d)
        assert ckpt.latest_step(d) == 2
        assert metrics.value(
            "resilience_emergency_checkpoint_skipped") is None


# ------------------------------------------------------ publish gate


@pytest.mark.numerics
@pytest.mark.serving
class TestPublishGate:
    def _pub(self):
        from horovod_tpu.run.rendezvous import KVStoreServer
        from horovod_tpu.serving import WeightPublisher

        s = KVStoreServer()
        return s, WeightPublisher(s, publish_every=0, register=False)

    def test_nonfinite_tree_rejected(self):
        from horovod_tpu.serving import PublishRejected

        s, pub = self._pub()
        try:
            pub.publish({"params": {"w": np.ones(4, np.float32)}}, 1)
            with pytest.raises(PublishRejected) as ei:
                pub.publish(
                    {"params": {"w": np.array([np.nan], np.float32)}}, 2)
            assert ei.value.reason == "nonfinite"
            assert pub.generation == 1
            assert metrics.value(
                "serving_publish_rejected", reason="nonfinite") == 1.0
        finally:
            s.close()

    def test_quarantine_blocks_until_cleared(self):
        from horovod_tpu.serving import PublishRejected

        s, pub = self._pub()
        try:
            numerics._quarantine.add(5)
            with pytest.raises(PublishRejected) as ei:
                pub.publish({"params": {"w": np.ones(2, np.float32)}}, 1)
            assert ei.value.reason == "quarantine"
            numerics.clear_quarantine()
            assert pub.publish(
                {"params": {"w": np.ones(2, np.float32)}}, 1) == 1
        finally:
            s.close()

    def test_gate_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_PUBLISH_NUMERICS_GATE", "0")
        s, pub = self._pub()
        try:
            assert pub.publish(
                {"params": {"w": np.array([np.nan], np.float32)}}, 1) == 1
        finally:
            s.close()

    def test_spike_mid_publish_keeps_subscriber_on_last_healthy(self):
        """Acceptance: a grad_spike marking the trainer's step BAD makes
        the publisher reject the next generation; the subscriber's view
        still matches the last healthy commit; publication resumes once
        the streak clears."""
        from horovod_tpu.serving import PublishRejected, WeightSubscriber

        s, pub = self._pub()
        try:
            tx = numerics.guard(optax.sgd(0.1), warmup=1, spike_factor=5.0)
            p = {"w": jnp.ones(4, jnp.float32)}
            st = tx.init(p)
            for _ in range(3):
                u, st = tx.update(_g(0.5), st, p)
                p = optax.apply_updates(p, u)
            state = {"params": p, "opt_state": st}
            assert pub.publish(state, 3) == 1
            sub = WeightSubscriber(s, scope=pub.scope)
            assert sub.poll() is not None
            np.testing.assert_array_equal(
                np.asarray(sub.weights()["w"]),
                np.asarray(pub.reconstruction()["w"]))
            healthy = np.asarray(sub.weights()["w"]).copy()

            # the spike: step goes BAD, update skipped, streak = 1
            u, st = tx.update(_g(500.0), st, p)
            p = optax.apply_updates(p, u)
            state = {"params": p, "opt_state": st}
            assert numerics.verdict(st)["bad_streak"] == 1
            with pytest.raises(PublishRejected) as ei:
                pub.publish(state, 4)
            assert ei.value.reason == "bad_step"
            sub.poll()
            assert sub.generation == 1  # still the last healthy commit
            np.testing.assert_array_equal(
                np.asarray(sub.weights()["w"]), healthy)
            assert metrics.value(
                "serving_publish_rejected", reason="bad_step") == 1.0

            # streak clears -> publication resumes
            u, st = tx.update(_g(0.5), st, p)
            p = optax.apply_updates(p, u)
            assert pub.publish({"params": p, "opt_state": st}, 5) == 2
            sub.poll()
            assert sub.generation == 2
        finally:
            s.close()


# --------------------------------------------------- in-step acceptance e2e


def _batch_for(step, n=48, epoch=0):
    rng = np.random.RandomState(1000 * epoch + step)
    x = rng.rand(n, 8).astype(np.float32)
    y = (x.sum(axis=1) > 4).astype(np.int64)
    return x, y


def _tiny_model():
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(2)(x)

    return Tiny()


def _guarded_step(hvd, model):
    from horovod_tpu.training import make_shardmap_train_step, softmax_xent

    tx = hvd.DistributedOptimizer(
        optax.adam(1e-2), shard_optimizer=True,
        compression=Compression.fp16, error_feedback=True,
        numerics_guard=True)
    step = make_shardmap_train_step(
        model, tx, loss_fn=softmax_xent, shard_optimizer=True,
        instrument=False, donate=False)
    return tx, step


@pytest.mark.numerics
@pytest.mark.chaos
def test_grad_nan_step_skipped_bit_identical_and_trajectory_matches(hvd):
    """THE acceptance pin: under ``grad_nan_at_step=3`` the poisoned step
    leaves params AND error-feedback residuals bit-identical, training
    resumes, and the final trajectory matches a clean run that never saw
    the bad batch."""
    from horovod_tpu.training import replicate, shard_batch

    model = _tiny_model()
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]

    def run(inject, batch_steps):
        chaos.configure("grad_nan_at_step=3" if inject else None)
        tx, step = _guarded_step(hvd, model)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        st = tx.init(params)
        snap = {}
        for i, bstep in enumerate(batch_steps):
            x, y = _batch_for(bstep)
            if inject and i == 3:
                snap["params"] = [
                    np.asarray(l).copy()
                    for l in jax.tree_util.tree_leaves(params)]
                snap["residual"] = {
                    k: np.asarray(v).copy()
                    for k, v in st.inner.residual.items()}
            params, _, st, loss = step(
                params, {}, st, shard_batch(x), shard_batch(y))
            numerics.note_step(i, st)
            if inject and i == 3:
                # bit-identical skip: params AND EF residuals untouched
                for a, b in zip(snap["params"],
                                jax.tree_util.tree_leaves(params)):
                    np.testing.assert_array_equal(a, np.asarray(b))
                for k, v in st.inner.residual.items():
                    np.testing.assert_array_equal(
                        snap["residual"][k], np.asarray(v))
                assert numerics.verdict(st)["last_bad"]
        return params, st

    p_chaos, st_chaos = run(True, [0, 1, 2, 3, 4, 5])
    v = numerics.verdict(st_chaos)
    assert v["bad_count"] == 1 and v["count"] == 6
    assert metrics.value(
        "resilience_chaos_injected", site="grad_nan_at_step") == 1.0

    # a clean run that never saw batch 3 lands on the same weights
    p_clean, _ = run(False, [0, 1, 2, 4, 5])
    for a, b in zip(jax.tree_util.tree_leaves(p_chaos),
                    jax.tree_util.tree_leaves(p_clean)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


@pytest.mark.numerics
def test_real_single_rank_corruption_localized_from_local_norms(hvd):
    """Review hardening (the big one): localization must work on REAL
    per-rank corruption, not just the chaos-perturbed record. One rank's
    batch shard carries NaN: the guard skips the step globally (the
    verdict is pmean-agreed), its gathered PRE-reduction local norms
    single out that rank, and the cross-check quarantines it alone —
    while a globally-bad step (every shard poisoned) quarantines NOBODY
    (majority-family rule: no healthy family to deviate from)."""
    from horovod_tpu.training import replicate, shard_batch

    model = _tiny_model()
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    store = _Store()
    numerics.configure(fingerprint=True, kv=store)
    tx, step = _guarded_step(hvd, model)
    params = replicate(jax.tree_util.tree_map(jnp.array, params0))
    st = tx.init(params)
    n = hvd.size()

    def poisoned(ranks):
        x, y = _batch_for(0, n=6 * n)
        x = x.copy()
        per = x.shape[0] // n
        for r in ranks:
            x[r * per:(r + 1) * per] = np.nan
        return shard_batch(x), shard_batch(y)

    # step 0: only rank 5's shard is poisoned
    xs, ys = poisoned([5])
    params, _, st, _ = step(params, {}, st, xs, ys)
    v = numerics.note_step(0, st)
    assert v["last_bad"]  # globally agreed skip
    assert v["rank_norms"][5] == -1.0  # the local view singles out 5
    assert all(rn > 0 for i, rn in enumerate(v["rank_norms"]) if i != 5)
    found = numerics.boundary(0)
    assert found is not None and [f["rank"] for f in found] == [5]
    assert numerics.take_corrupt_ranks() == [5]

    # step 1: EVERY shard poisoned — a bad batch, not rank corruption
    xs, ys = poisoned(list(range(n)))
    params, _, st, _ = step(params, {}, st, xs, ys)
    v = numerics.note_step(1, st)
    assert v["last_bad"]
    assert all(rn == -1.0 for rn in v["rank_norms"])
    assert numerics.boundary(1) is None
    assert not numerics.quarantine_pending()  # no 8->1 mass eviction


@pytest.mark.numerics
def test_cross_check_defers_missing_peer_then_flags_late_record(hvd):
    """Review hardening: a peer whose fingerprint has not landed must be
    re-checked at later boundaries, not silently dropped — the corrupt
    rank is often the slow one."""
    import json

    store = _Store()
    numerics.configure(fingerprint=True, kv=store)
    with _world(4):
        # ranks 0-2 published; rank 3 (the slow, corrupt one) has not
        for r in range(3):
            store.put(
                numerics.fingerprint_key(0, r),
                json.dumps(
                    {"step": 0, "finite": 1, "norm": 1.0}).encode())
        assert numerics.cross_check_fingerprints(0) is None
        # next boundary: rank 3's corrupt record finally lands
        store.put(
            numerics.fingerprint_key(0, 3),
            json.dumps({"step": 0, "finite": 0, "norm": None}).encode())
        found = numerics.boundary(1)
    assert found is not None and found[0] == {
        "step": 0, "rank": 3, "norm": None, "finite": False,
        "median_norm": 1.0,
    }
    assert numerics.take_corrupt_ranks() == [3]


@pytest.mark.numerics
@pytest.mark.chaos
@pytest.mark.elastic
def test_grad_corrupt_rank_quarantined_and_evicted():
    """THE acceptance pin: under ``grad_corrupt_rank=5:4`` rank 5 is
    named within one step, goes SUSPECT, and is evicted via the elastic
    8→7 path."""
    import horovod_tpu as hvd
    from horovod_tpu.resilience import elastic

    chaos.configure("grad_corrupt_rank=5:4")
    hvd.init()
    try:
        out = elastic.run(
            lambda world: (lambda st, i: {"w": st["w"] + 1}),
            {"w": np.zeros(1)}, num_steps=8)
        assert hvd.size() == 7  # rank 5 evicted, no relaunch
        np.testing.assert_allclose(out["w"], 8.0)
        assert metrics.value("numerics_corrupt_ranks", rank=5) == 1.0
        assert metrics.value("resilience_numeric_corruptions") == 1.0
        assert metrics.value(
            "resilience_chaos_injected", site="grad_corrupt_rank") == 1.0
        assert metrics.value(
            "resilience_elastic_membership_changes", kind="shrink") == 1.0
        # SUSPECT was entered naming the rank (beats may have recovered it)
        assert metrics.value(
            "resilience_health_transitions",
            **{"from": "HEALTHY", "to": "SUSPECT"}) >= 1.0
    finally:
        hvd.shutdown()


@pytest.mark.numerics
@pytest.mark.elastic
def test_same_size_membership_change_rebuilds_step():
    """Review hardening: the step cache keys on MEMBERSHIP, not world
    size — a quarantine eviction landing on the same sweep as a chaos
    rejoin keeps the count but re-forms the mesh over a different device
    set, so the step must be rebuilt (and the boundary claim released
    when the run ends)."""
    import horovod_tpu as hvd
    from horovod_tpu.resilience import elastic

    chaos.configure("rank_fail=1,rank_fail_at_step=2,rank_join_at_step=5")
    builds = []
    hvd.init()
    try:
        def builder(world):
            builds.append(world)

            def step_fn(st, i):
                if i == 4:
                    # flagged here so step 5's sweep evicts rank 3 in
                    # the SAME boundary the failed rank 7 rejoins
                    numerics.requeue_corrupt_ranks([3])
                return {"w": st["w"] + 1}

            return step_fn

        out = elastic.run(builder, {"w": np.zeros(1)}, num_steps=8)
        np.testing.assert_allclose(out["w"], 8.0)
        # 8 -> 7 (rank 7 fails) -> 7 (rank 3 out, rank 7 back): the last
        # transition keeps the size but MUST rebuild the step
        assert builds == [8, 7, 7]
        assert numerics._external_boundary is False  # claim released
    finally:
        hvd.shutdown()


@pytest.mark.numerics
@pytest.mark.elastic
def test_bad_streak_rolls_back_with_fresh_data(monkeypatch):
    """K consecutive bad steps trigger a bounded rollback to the
    committed snapshot; the replay draws FRESH batches via the bumped
    replay epoch and completes."""
    import horovod_tpu as hvd
    from horovod_tpu.resilience import elastic
    from horovod_tpu.training import replicate, shard_batch

    monkeypatch.setenv("HOROVOD_NUMERICS_MAX_BAD", "2")
    model = _tiny_model()
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    seen = []

    hvd.init()
    builds = []
    try:
        def builder(world):
            builds.append(world)
            tx, step = _guarded_step(hvd, model)

            def step_fn(state, i):
                epoch = numerics.replay_epoch()
                seen.append((i, epoch))
                x, y = _batch_for(i, epoch=epoch)
                if epoch == 0 and i >= 3:
                    x = x * np.nan  # a poisoned data shard
                p, _, st, _ = step(
                    state["params"], {}, state["opt_state"],
                    shard_batch(x), shard_batch(y))
                return {"params": p, "opt_state": st}

            return step_fn

        tx0, _ = _guarded_step(hvd, model)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        state = {"params": params, "opt_state": tx0.init(params)}
        out = elastic.run(builder, state, num_steps=6, snapshot_every=1)
        assert numerics.replay_epoch() == 1
        assert metrics.value("numerics_rollbacks") == 1.0
        # steps 3,4 went bad in epoch 0 -> rollback -> replay 3.. in epoch 1
        assert (3, 0) in seen and (4, 0) in seen and (3, 1) in seen
        assert numerics.tree_finite(out["params"])
        v = numerics.verdict(out["opt_state"])
        assert v["bad_streak"] == 0
        # pass-5 hardening: the rollback replays at the SAME world size,
        # so the compiled step is reused — not rebuilt (and recompiled)
        assert len(builds) == 1
    finally:
        hvd.shutdown()


@pytest.mark.numerics
@pytest.mark.elastic
def test_lagged_verdict_rolls_back_with_sparse_commits(monkeypatch):
    """Review hardening: with snapshot_every > 1 the elastic wrapper
    reads the guard verdict LAGGED on non-commit boundaries (staged
    async copy — the synchronous per-step device→host read fenced every
    step of the hot loop). The bad-streak rollback still fires (one step
    late at most) and commits stay gated on an EXACT same-step verdict."""
    import horovod_tpu as hvd
    from horovod_tpu.resilience import elastic
    from horovod_tpu.training import replicate, shard_batch

    monkeypatch.setenv("HOROVOD_NUMERICS_MAX_BAD", "2")
    model = _tiny_model()
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    seen = []

    hvd.init()
    try:
        def builder(world):
            tx, step = _guarded_step(hvd, model)

            def step_fn(state, i):
                epoch = numerics.replay_epoch()
                seen.append((i, epoch))
                x, y = _batch_for(i, epoch=epoch)
                if epoch == 0 and i >= 3:
                    x = x * np.nan
                p, _, st, _ = step(
                    state["params"], {}, state["opt_state"],
                    shard_batch(x), shard_batch(y))
                return {"params": p, "opt_state": st}

            return step_fn

        tx0, _ = _guarded_step(hvd, model)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        state = {"params": params, "opt_state": tx0.init(params)}
        out = elastic.run(builder, state, num_steps=6, snapshot_every=4)
        assert numerics.replay_epoch() == 1
        assert metrics.value("numerics_rollbacks") == 1.0
        # bad steps 3,4 in epoch 0; the replay re-runs them with fresh data
        assert (3, 0) in seen and (3, 1) in seen
        assert numerics.tree_finite(out["params"])
        assert numerics.verdict(out["opt_state"])["bad_streak"] == 0
    finally:
        hvd.shutdown()


@pytest.mark.numerics
@pytest.mark.elastic
def test_rollback_budget_exhaustion_is_fatal(monkeypatch):
    """Bad steps that survive every replay (the data is poisoned in every
    epoch) exhaust the rollback budget: FATAL + NumericsError."""
    import horovod_tpu as hvd
    from horovod_tpu.resilience import elastic

    monkeypatch.setenv("HOROVOD_NUMERICS_MAX_BAD", "1")
    monkeypatch.setenv("HOROVOD_NUMERICS_MAX_ROLLBACKS", "1")
    model = _tiny_model()
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]

    hvd.init()
    try:
        from horovod_tpu.training import replicate, shard_batch

        def builder(world):
            tx, step = _guarded_step(hvd, model)

            def step_fn(state, i):
                x, y = _batch_for(i)
                if i >= 1:
                    x = x * np.nan  # poisoned in EVERY epoch
                p, _, st, _ = step(
                    state["params"], {}, state["opt_state"],
                    shard_batch(x), shard_batch(y))
                return {"params": p, "opt_state": st}

            return step_fn

        tx0, _ = _guarded_step(hvd, model)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        state = {"params": params, "opt_state": tx0.init(params)}
        with pytest.raises(numerics.NumericsError):
            elastic.run(builder, state, num_steps=5, snapshot_every=1)
        assert health.health_state() == HealthState.FATAL
    finally:
        hvd.shutdown()


@pytest.mark.numerics
def test_jit_builder_loss_scaling_matches_unscaled(hvd):
    """make_jit_train_step with a guarded, loss-scaled optimizer: the
    loss is scaled inside the differentiated fn and the guard divides
    the grads back, so the trajectory matches the unguarded builder and
    the reported loss is the UNSCALED one."""
    from horovod_tpu.training import (
        make_jit_train_step, replicate, shard_batch, softmax_xent,
    )

    model = _tiny_model()
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]

    def run(guarded):
        if guarded:
            tx = hvd.DistributedOptimizer(
                optax.adam(1e-2), numerics_guard=True, loss_scale=64.0)
        else:
            tx = hvd.DistributedOptimizer(optax.adam(1e-2))
        step = make_jit_train_step(
            model, tx, loss_fn=softmax_xent, instrument=False,
            donate=False)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        st = tx.init(params)
        for i in range(5):
            x, y = _batch_for(i)
            params, _, st, loss = step(
                params, {}, st, shard_batch(x), shard_batch(y))
        return params, float(loss), st

    p_g, l_g, st_g = run(True)
    p_u, l_u, _ = run(False)
    assert l_g == pytest.approx(l_u, rel=1e-4)  # reported loss unscaled
    for a, b in zip(jax.tree_util.tree_leaves(p_g),
                    jax.tree_util.tree_leaves(p_u)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
    v = numerics.verdict(st_g)
    assert v["loss_scale"] == 64.0 and v["bad_count"] == 0


# -------------------------------------------------- reshard / broadcast


@pytest.mark.numerics
def test_loss_scale_with_guard_disabled_raises(hvd):
    """Review hardening: loss_scale lives in the guard state; an explicit
    numerics_guard=False alongside it would silently train unscaled."""
    with pytest.raises(ValueError, match="loss_scale"):
        hvd.DistributedOptimizer(
            optax.sgd(0.1), numerics_guard=False, loss_scale="dynamic")


@pytest.mark.numerics
@pytest.mark.elastic
def test_rollback_budget_resets_on_sound_progress(monkeypatch):
    """Review hardening: the rollback budget guards against rollbacks
    WITHOUT sound progress — two isolated incidents, each fully recovered
    with committed steps in between, must both be survivable even with a
    budget of 1."""
    import horovod_tpu as hvd
    from horovod_tpu.resilience import elastic
    from horovod_tpu.training import replicate, shard_batch

    monkeypatch.setenv("HOROVOD_NUMERICS_MAX_BAD", "1")
    monkeypatch.setenv("HOROVOD_NUMERICS_MAX_ROLLBACKS", "1")
    model = _tiny_model()
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]

    hvd.init()
    try:
        def builder(world):
            tx, step = _guarded_step(hvd, model)

            def step_fn(state, i):
                epoch = numerics.replay_epoch()
                x, y = _batch_for(i, epoch=epoch)
                # two isolated transient incidents: steps 2 and 6 are
                # poisoned only on their first serving (epoch-specific)
                if (i == 2 and epoch == 0) or (i == 6 and epoch == 1):
                    x = x * np.nan
                p, _, st, _ = step(
                    state["params"], {}, state["opt_state"],
                    shard_batch(x), shard_batch(y))
                return {"params": p, "opt_state": st}

            return step_fn

        tx0, _ = _guarded_step(hvd, model)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        state = {"params": params, "opt_state": tx0.init(params)}
        out = elastic.run(builder, state, num_steps=9, snapshot_every=1)
        assert metrics.value("numerics_rollbacks") == 2.0
        assert numerics.tree_finite(out["params"])
    finally:
        hvd.shutdown()


@pytest.mark.numerics
def test_tree_finite():
    assert numerics.tree_finite({"a": np.ones(3), "b": "meta", "c": 7})
    assert not numerics.tree_finite({"a": np.array([1.0, np.inf])})
    assert not numerics.tree_finite(
        {"a": {"b": jnp.array([np.nan], jnp.float32)}})
    # integer arrays cannot be non-finite
    assert numerics.tree_finite({"i": np.arange(5)})


# ------------------------------------------------------- CI/tooling guards


def test_every_chaos_charge_documented_in_fault_tolerance_table():
    """Tier-1 guard (satellite): every HOROVOD_CHAOS charge name parsed
    in chaos.py must appear in docs/fault_tolerance.md's chaos table —
    the drill catalog cannot silently drift from the harness (the same
    pattern as the PR-7 metric-catalog guard)."""
    keys = set(
        chaos._COUNT_KEYS + chaos._FLOAT_KEYS + chaos._INT_KEYS
        + chaos._STRUCT_KEYS
    )
    assert len(keys) >= 14, "suspiciously few chaos charges parsed"
    with open(os.path.join(_REPO, "docs", "fault_tolerance.md")) as f:
        doc = f.read()
    missing = sorted(k for k in keys if f"`{k}" not in doc)
    assert not missing, (
        "chaos charges parsed in chaos.py but absent from the "
        f"docs/fault_tolerance.md chaos table: {missing}"
    )


@pytest.mark.numerics
@pytest.mark.slow
def test_bench_numerics_ab_rung():
    """bench.py --numerics-ab emits one JSON line whose detection step —
    reported on the guard-count clock, the chaos charge's own grammar —
    equals the injected step exactly."""
    import json as _json
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--numerics-ab", "--iters", "10", "--no-probe"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    d = _json.loads(line)
    assert d["metric"] == "numerics_ab_step_ratio"
    if not d.get("skipped"):
        assert d["detected_at_step"] == d["injected"]["step"]
        assert d["bad_steps"] >= 1
        assert d["value"] > 0


def test_numerics_env_knobs_documented():
    """Every HOROVOD_NUMERICS_* env knob the module defines appears in
    the docs (fault_tolerance.md or troubleshooting.md)."""
    knobs = sorted(
        v for k, v in vars(numerics).items()
        if k.endswith("_ENV") and isinstance(v, str)
        and v.startswith("HOROVOD_")
    )
    docs = ""
    for name in ("fault_tolerance.md", "troubleshooting.md", "serving.md"):
        with open(os.path.join(_REPO, "docs", name)) as f:
            docs += f.read()
    missing = [k for k in knobs if k not in docs]
    assert not missing, f"undocumented numerics env knobs: {missing}"
