"""Black-box flight recorder + cross-rank hang forensics (ISSUE 14).

The acceptance pin: under ``HOROVOD_CHAOS=rank_hang_at_step=K`` on the
8-device CPU mesh, the live hang detector AND the offline
``tools/hvd_blackbox.py`` analysis of sidecar files alone both name the
hung rank and the exact collective signature ``(step, gen, seq)``; a
variant that SIGKILLs the hung process still diagnoses from the surviving
ranks' records. Plus unit coverage of the ring, the torn-tail-tolerant
sidecar, the verdict taxonomy, and the env-knob doc guard."""

import json
import os
import re
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from horovod_tpu.observability import flight, metrics, straggler
from horovod_tpu.run.rendezvous import InProcessKVStore
from horovod_tpu.resilience import chaos, health

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TESTS_DIR)


@pytest.fixture(autouse=True)
def _iso(monkeypatch):
    """Flight/chaos/health/metrics state is module-global: every test
    starts clean and leaves nothing armed (a stray watchdog thread or
    chaos charge would poison later tests)."""
    for var in ("HOROVOD_FLIGHT", "HOROVOD_FLIGHT_DIR",
                "HOROVOD_FLIGHT_MAX_EVENTS", "HOROVOD_FLIGHT_FLUSH_EVERY",
                "HOROVOD_FLIGHT_MAX_BYTES", "HOROVOD_HANG_TIMEOUT",
                "HOROVOD_HANG_TAIL", "HOROVOD_HANG_EVICT"):
        monkeypatch.delenv(var, raising=False)
    metrics.reset()
    metrics.set_enabled(True)
    flight.reset()
    chaos.configure(None)
    health.reset()
    straggler.reset()
    yield
    flight.reset()
    chaos.reset()
    health.reset()
    straggler.reset()
    metrics.reset()


# ------------------------------------------------------------- ring basics


def test_ring_is_bounded(monkeypatch):
    monkeypatch.setenv("HOROVOD_FLIGHT_MAX_EVENTS", "16")
    flight.reset()
    for i in range(40):
        flight.record("note", i=i)
    evs = flight.events()
    assert len(evs) == 16
    assert evs[0]["i"] == 24 and evs[-1]["i"] == 39  # oldest dropped
    assert metrics.value("flight_events", kind="note") == 40


def test_disabled_records_nothing(monkeypatch):
    monkeypatch.setenv("HOROVOD_FLIGHT", "0")
    flight.reset()
    assert flight.record("note") is None
    flight.collective_begin("allreduce", (0, 0, 0))
    flight.step_boundary(0)
    assert flight.events() == []


def test_collective_end_once_per_key():
    flight.collective_begin("allreduce", (0, 0, 0))
    flight.collective_end()
    flight.collective_end()  # grouped launches: one end per begin
    kinds = [(e.get("ph"), e.get("seq")) for e in flight.events()
             if e["kind"] == "collective"]
    assert kinds == [("b", 0), ("e", 0)]


# ------------------------------------------------------- sidecar durability


def test_sidecar_roundtrip_and_torn_tail(tmp_path):
    d = str(tmp_path)
    flight.configure(dir=d)
    for s in range(2):
        flight.step_boundary(s)
        for q in range(3):
            flight.collective_begin("allreduce", (s, 0, q))
            flight.collective_end()
    path = flight.flush()
    assert path == os.path.join(d, "flight-rank0.jsonl")
    # SIGKILL mid-write: a torn half line at the tail must not poison the
    # record (the rendezvous-WAL discipline)
    with open(path, "a") as f:
        f.write('{"t": 1.0, "kind": "collective", "ph": "b", "st')
    side = flight.load_sidecar(path)
    assert side["skipped"] == 1
    assert side["ranks"] == [0]
    colls = [e for e in side["events"] if e["kind"] == "collective"]
    assert len(colls) == 12  # 2 steps x 3 collectives x (b + e)
    verdict = flight.analyze_dir(d)
    assert verdict["verdict"] == "progressing"


def test_sidecar_compaction_bounds_the_file(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_FLIGHT_MAX_EVENTS", "32")
    monkeypatch.setenv("HOROVOD_FLIGHT_FLUSH_EVERY", "1")
    monkeypatch.setenv("HOROVOD_FLIGHT_MAX_BYTES", "4000")
    flight.reset()
    flight.configure(dir=str(tmp_path))
    for s in range(100):
        flight.collective_begin("allreduce", (s, 0, 0))
        flight.collective_end()
    flight.flush()
    path = flight.sidecar_path()
    assert os.path.getsize(path) < 2 * 4000  # bounded, not unbounded-append
    assert metrics.value("flight_sidecar_compactions") >= 1
    side = flight.load_sidecar(path)
    assert side["events"]  # still a loadable record after compaction
    assert flight.analyze_dir(str(tmp_path))["verdict"] == "progressing"


# ------------------------------------------------------- verdict taxonomy


def _stream(keys, *, end_last=True, op="allreduce", ops=None):
    """[(step, seq), ...] -> b/e event stream; the last begin is left
    unended when end_last=False (the parked state)."""
    out = []
    for i, (s, q) in enumerate(keys):
        o = ops[i] if ops else op
        out.append({"t": float(i), "kind": "collective", "ph": "b",
                    "op": o, "step": s, "gen": 0, "seq": q})
        if end_last or i < len(keys) - 1:
            out.append({"t": float(i) + 0.5, "kind": "collective",
                        "ph": "e", "op": o, "step": s, "gen": 0, "seq": q})
    return out


def test_analyze_rank_missing_names_signature():
    evs = {
        0: _stream([(0, 0), (0, 1), (1, 0)], end_last=False),
        1: _stream([(0, 0), (0, 1), (1, 0)], end_last=False),
        2: _stream([(0, 0), (0, 1)]),  # never arrived at (1, 0, 0)
    }
    v = flight.analyze(evs, expected=[0, 1, 2])
    assert v["verdict"] == "rank_missing"
    assert v["hung_ranks"] == [2]
    assert v["key"] == [1, 0, 0] and v["op"] == "allreduce"
    assert v["waiting"] == [0, 1]
    assert "rank(s) [2] missing" in flight.describe(v)


def test_analyze_missing_rank_with_no_record_at_all():
    evs = {0: _stream([(0, 0)], end_last=False)}
    v = flight.analyze(evs, expected=[0, 1])
    assert v["verdict"] == "rank_missing" and v["hung_ranks"] == [1]
    assert v["key"] == [0, 0, 0]


def test_analyze_missing_rank_after_survivors_moved_on():
    """Offline after an eviction/release: survivors progressed past the
    stuck collective — the verdict still names the FIRST signature the
    missing rank never joined, not the end-of-run frontier."""
    evs = {
        0: _stream([(0, 0), (0, 1), (1, 0), (1, 1), (2, 0)]),
        1: _stream([(0, 0), (0, 1)]),  # stopped before (1, 0, 0)
    }
    v = flight.analyze(evs, expected=[0, 1])
    assert v["verdict"] == "rank_missing" and v["hung_ranks"] == [1]
    assert v["key"] == [1, 0, 0]


def test_analyze_schedule_divergence_by_sched_hash():
    a = _stream([(0, 0), (1, 0)], end_last=False)
    b = _stream([(0, 0), (1, 0)], end_last=False)
    a.append({"t": 9.0, "kind": "sched", "step": 0, "hash": "aaaa", "n": 1})
    b.append({"t": 9.0, "kind": "sched", "step": 0, "hash": "bbbb", "n": 1})
    v = flight.analyze({0: a, 1: b}, expected=[0, 1])
    assert v["verdict"] == "schedule_divergence"
    assert v["hung_ranks"] == [1]
    assert "diverged" in flight.describe(v)


def test_analyze_schedule_divergence_by_forked_op():
    """Ranks parked at the SAME seq on DIFFERENT collectives: the
    schedules forked — stronger evidence than the (one-step-lagged)
    hashes."""
    a = _stream([(0, 0), (0, 1)], end_last=False,
                ops=["allreduce", "allreduce"])
    b = _stream([(0, 0), (0, 1)], end_last=False,
                ops=["allreduce", "allgather"])
    v = flight.analyze({0: a, 1: b}, expected=[0, 1])
    assert v["verdict"] == "schedule_divergence"
    assert v["hung_ranks"] == [1]


def test_analyze_all_parked_and_progressing():
    parked = {r: _stream([(0, 0)], end_last=False) for r in range(3)}
    v = flight.analyze(parked, expected=[0, 1, 2])
    assert v["verdict"] == "all_parked" and v["hung_ranks"] == []
    done = {r: _stream([(0, 0)]) for r in range(3)}
    assert flight.analyze(done, expected=[0, 1, 2])["verdict"] == \
        "progressing"
    assert flight.analyze({}, expected=[0])["verdict"] == "no_data"


def test_health_record_hang_goes_degraded_with_signature():
    health.record_hang(5, [3, 1, 7])
    snap = health.snapshot()
    assert snap["state"] == "DEGRADED"
    assert "rank 5" in snap["reason"] and "(3, 1, 7)" in snap["reason"]
    assert metrics.value("resilience_hangs", rank=5) == 1
    # flight ring mirrored the transition
    hs = [e for e in flight.events() if e["kind"] == "health"]
    assert hs and hs[-1]["dst"] == "DEGRADED"


# ------------------------------------------- the deterministic live drill


@pytest.mark.chaos
def test_rank_hang_drill_live_and_offline(tmp_path, monkeypatch):
    """THE acceptance pin (single-controller half). 8-device mesh,
    ``rank_hang_at_step=1``: rank 7 stops dispatching mid-step — the live
    watchdog names rank 7 and the exact ``(step, gen, seq)``, health goes
    DEGRADED with the signature in its reason, and the offline
    ``hvd_blackbox`` analysis of the sidecar files alone reaches the SAME
    verdict after the process state is gone."""
    d = str(tmp_path / "flight")
    monkeypatch.setenv("HOROVOD_FLIGHT_DIR", d)
    monkeypatch.setenv("HOROVOD_HANG_TIMEOUT", "0.25")
    flight.reset()
    chaos.configure("rank_hang_at_step=1,rank_hang_hold=8.0")

    import horovod_tpu as hvd
    from horovod_tpu.training import instrument_step

    hvd.init()
    try:
        def raw_step(x, n=3):
            for _ in range(n):
                x = hvd.allreduce(x)
            return x

        step = instrument_step(raw_step, examples_per_step=8)
        x = np.ones((8,), np.float32)
        t0 = time.monotonic()
        for _ in range(3):
            x = step(x)
        # the hold was released by the live diagnosis, not the 8 s budget
        assert time.monotonic() - t0 < 6.0
        for _ in range(100):  # the diagnosing watchdog is a thread
            if flight.last_hang() is not None:
                break
            time.sleep(0.02)
        v = flight.last_hang()
        assert v is not None and v["verdict"] == "rank_missing"
        assert v["hung_ranks"] == [7]
        assert v["key"][0] == 1 and v["key"][1] == 0  # step 1, gen 0
        assert v["key"][2] >= 1  # mid-step: the drill fires from seq 1 on
        assert v["op"] == "allreduce"
        assert v["waiting"] == [0, 1, 2, 3, 4, 5, 6]
        snap = health.snapshot()
        assert snap["state"] == "DEGRADED"
        assert "rank 7" in snap["reason"] and "missing" in snap["reason"]
        assert metrics.value("hang_watchdog_fired") >= 1
        assert metrics.value("hang_diagnosed", verdict="rank_missing") >= 1
        assert metrics.value(
            "resilience_chaos_injected", site="rank_hang_at_step") == 1
        live_key = list(v["key"])
    finally:
        hvd.shutdown()
        # this drill warms the shape-independent eager-kernel caches on
        # the full 8-mesh; later tests assert cold-cache compile counts
        from horovod_tpu.ops.collective import clear_eager_caches

        clear_eager_caches()

    # offline: the SAME verdict from the sidecar files alone
    off = flight.analyze_dir(d)
    assert off["verdict"] == "rank_missing"
    assert off["hung_ranks"] == [7]
    assert off["key"] == live_key and off["op"] == "allreduce"
    # and through the CLI (exit 3 = hang found, scriptable)
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "hvd_blackbox.py"),
         d],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 3, out.stderr
    assert "rank(s) [7] missing" in out.stdout
    assert f"(step, gen, seq)=({live_key[0]}, {live_key[1]}, " \
           f"{live_key[2]})" in out.stdout
    out_json = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "hvd_blackbox.py"),
         d, "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert json.loads(out_json.stdout)["hung_ranks"] == [7]


# ------------------------------------- the SIGKILL (dead-process) variant


_KILL_WORKER = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, {repo!r})
    from horovod_tpu.observability import flight

    rank = int(sys.argv[1])
    flight.configure(dir={flight_dir!r}, rank=rank, world=2)
    for step in range(3):
        flight.step_boundary(step)
        for seq in range(3):
            if rank == 1 and step == 1 and seq == 1:
                # "hangs": never begins (1, 0, 1); SIGKILLed while parked
                flight.flush()
                print("PARKED", flush=True)
                time.sleep(60)
            flight.collective_begin("allreduce", (step, 0, seq))
            flight.collective_end()
        flight.flush()
    print("DONE", flush=True)
""")


@pytest.mark.chaos
def test_sigkill_variant_diagnoses_from_surviving_records(tmp_path):
    """THE acceptance pin (dead-process half): the hung process is
    SIGKILLed mid-drill — no shutdown, no flush of anything after the
    park — and the offline analysis still names it and the exact
    signature from whatever its crash-durable sidecar (plus the
    survivors') retained."""
    d = str(tmp_path / "flight")
    os.makedirs(d)
    script = tmp_path / "worker.py"
    script.write_text(_KILL_WORKER.format(repo=_REPO, flight_dir=d))
    env = dict(os.environ)
    env.pop("HOROVOD_FLIGHT_DIR", None)
    p1 = subprocess.Popen(
        [sys.executable, str(script), "1"], env=env,
        stdout=subprocess.PIPE, text=True,
    )
    assert p1.stdout.readline().strip() == "PARKED"
    p1.kill()  # SIGKILL: no handlers, no flush path — the sidecar is all
    p1.wait(timeout=60)
    assert p1.returncode == -signal.SIGKILL
    p0 = subprocess.run(
        [sys.executable, str(script), "0"], env=env, timeout=120,
        capture_output=True, text=True,
    )
    assert "DONE" in p0.stdout

    v = flight.analyze_dir(d)
    assert v["verdict"] == "rank_missing"
    assert v["hung_ranks"] == [1]
    assert v["key"] == [1, 0, 1] and v["op"] == "allreduce"
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "hvd_blackbox.py"),
         d],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 3
    assert "rank(s) [1] missing" in out.stdout
    assert "(step, gen, seq)=(1, 0, 1)" in out.stdout


# --------------------------------------------- preemption drain satellite


@pytest.mark.chaos
def test_preempt_drain_flushes_flight_ring(tmp_path):
    """Satellite (ISSUE 14): the SIGTERM drain flushes the flight ring
    (and the trace sidecars) BEFORE the emergency checkpoint — a
    preempted run keeps its record, not only its weights."""
    from horovod_tpu.resilience import loop

    d = str(tmp_path / "flight")
    flight.configure(dir=d)
    flight.collective_begin("allreduce", (0, 0, 0))
    flight.collective_end()
    chaos.configure("sigterm_at_step=1")
    with pytest.raises(loop.Preempted):
        loop.run(lambda s, i: s, np.zeros(2), num_steps=4)
    side = flight.load_sidecar(os.path.join(d, "flight-rank0.jsonl"))
    kinds = [e["kind"] for e in side["events"]]
    assert "preempt" in kinds  # the drain reached the flight flush
    assert "collective" in kinds


# ----------------------------------------------------- watchdog lifecycle


def test_watchdog_does_not_fire_while_progressing():
    kv = InProcessKVStore()
    flight.configure(kv=kv, world=2)
    flight.arm_watchdog(timeout=0.15)
    try:
        for i in range(8):
            flight.collective_begin("allreduce", (0, 0, i))
            flight.collective_end()
            time.sleep(0.04)  # well under the timeout
        assert flight.last_hang() is None
        assert metrics.value("hang_watchdog_fired") is None
    finally:
        flight.disarm_watchdog()


def test_watchdog_fires_once_per_stall_and_rearms():
    kv = InProcessKVStore()
    flight.configure(kv=kv, world=2)
    flight.arm_watchdog(timeout=0.1)
    try:
        flight.collective_begin("allreduce", (0, 0, 0))
        flight.collective_end()
        time.sleep(0.5)  # stall >> timeout: exactly one firing
        assert metrics.value("hang_watchdog_fired") == 1
        # progress resumes -> the watchdog re-arms -> a second stall fires
        flight.collective_begin("allreduce", (0, 0, 1))
        flight.collective_end()
        time.sleep(0.5)
        assert metrics.value("hang_watchdog_fired") == 2
    finally:
        flight.disarm_watchdog()


def test_hang_evict_queues_rank(monkeypatch, tmp_path):
    """HOROVOD_HANG_EVICT=1: a diagnosed missing rank lands in the
    eviction queue the elastic membership sweep drains."""
    monkeypatch.setenv("HOROVOD_HANG_EVICT", "1")
    kv = InProcessKVStore()
    # rank pinned: this process pushes ONLY its own tail (the
    # multi-process convention), so the planted rank-1 tail survives
    flight.configure(kv=kv, world=2, rank=0)
    # rank 1's tail is behind rank 0's -> missing at (0, 0, 1)
    flight.step_boundary(0)  # the progress baseline the stall is against
    for seq in range(2):
        flight.collective_begin("allreduce", (0, 0, seq))
    kv.put(f"{flight.TAIL_SCOPE}/1", json.dumps({
        "rank": 1, "world": 2, "offset_s": 0.0, "generation": 0,
        "events": _stream([(0, 0)]),
    }).encode())
    flight.arm_watchdog(timeout=0.1)
    try:
        for _ in range(100):
            if flight.last_hang() is not None:
                break
            time.sleep(0.02)
        v = flight.last_hang()
        assert v is not None and v["hung_ranks"] == [1]
        assert flight.take_hung_ranks() == [1]
        assert flight.take_hung_ranks() == []  # drained
    finally:
        flight.disarm_watchdog()


# ------------------------------------------------------------- doc guards


def test_flight_env_knobs_documented():
    """CI guard (ISSUE 14 satellite): every HOROVOD_FLIGHT_* /
    HOROVOD_HANG_* literal in horovod_tpu/ must appear in the
    docs/observability.md knob table (metric-catalog-guard pattern); the
    flight_*/hang_* metric names are covered by
    test_metric_catalog_covers_every_emitted_name."""
    knob_re = re.compile(r"HOROVOD_(?:FLIGHT|HANG)(?:_[A-Z]+)*")
    knobs = set()
    for dirpath, _dirnames, filenames in os.walk(
            os.path.join(_REPO, "horovod_tpu")):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                knobs |= set(knob_re.findall(f.read()))
    assert {"HOROVOD_FLIGHT", "HOROVOD_FLIGHT_DIR", "HOROVOD_HANG_TIMEOUT",
            "HOROVOD_HANG_EVICT"} <= knobs
    with open(os.path.join(_REPO, "docs", "observability.md")) as f:
        doc = f.read()
    missing = sorted(k for k in knobs if k not in doc)
    assert not missing, (
        f"flight/hang env knobs named in code but absent from the "
        f"docs/observability.md knob table: {missing}"
    )
