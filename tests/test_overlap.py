"""Bucketed backward-pass gradient sync (``horovod_tpu.ops.overlap``).

Acceptance (ISSUE 10):

- ZeRO-1 bucketed and monolithic sync produce **bit-identical** Adam
  trajectories over 12 steps on the 8-device CPU mesh for none/fp16
  (packing is a permutation; the elementwise wire and the cross-rank sum
  commute with it — pinned exactly).
- allreduce-mode bucketed sync produces **bit-identical reduced
  gradients** per step; the full trajectory is pinned to 1e-6 (the two
  programs fuse the Adam elementwise math differently — XLA FMA
  contraction — a 1-ULP/step compiler artifact, not a sync difference;
  the gradient pin isolates the sync itself as exact).
- int8 wire: blockwise scales are layout-dependent, so bucketing
  legitimately re-rounds; trajectories track within quantization
  tolerance with error feedback keyed by bucket.
- interleaving pins: a ``sync_hook``-staged backward issues >= 2
  collectives BETWEEN backward compute fragments (jaxpr profile and
  optimized-HLO text), where the monolithic step issues 0.
- ``hvd.tuning.apply_xla_flags`` never clobbers user-set ``XLA_FLAGS``
  entries and withholds TPU-only flags on non-TPU targets (where they
  are a fatal parse error).
- CI guard: every ``HOROVOD_BUCKET_*`` / ``HOROVOD_OVERLAP*`` /
  ``HOROVOD_XLA_FLAGS*`` env knob in the source appears in the
  docs/performance.md knob table.
"""

import os
import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import tuning
from horovod_tpu.compression import Compression
from horovod_tpu.ops import overlap as ov
from horovod_tpu.ops.collective import _smap, allreduce, Average, Sum

pytestmark = pytest.mark.overlap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------------
# BucketPlan unit tests


class _S:
    def __init__(self, shape, dtype=np.float32):
        self.shape, self.dtype = shape, dtype


def test_plan_reverse_emission_order_and_split():
    # leaves declared [b, w]: backprop emits w's cotangent first, so the
    # plan iterates in reverse leaf order and w fills the first buckets
    leaves = [_S((33,)), _S((64, 33))]
    plan = ov.BucketPlan.build(leaves, n=8, bucket_bytes=4096)  # 1024 elems
    assert plan.buckets[0].segs[0].idx == 1
    assert plan.buckets[0].segs[0].start == 0
    # 64*33 = 2112 elems -> buckets of 1024, 1024, then 64 + the 33-elem b
    sizes = [b.L for b in plan.buckets]
    assert sizes == [1024, 1024, 64 + 33]
    # the boundary splits w: its last segment and b share the final bucket
    last = plan.buckets[-1]
    assert [s.idx for s in last.segs] == [1, 0]
    assert last.segs[0].start == 2048 and last.segs[0].stop == 2112
    # Lp pads to the axis size
    assert all(b.Lp % 8 == 0 for b in plan.buckets)


def test_plan_single_leaf_and_oversized_bucket():
    one = ov.BucketPlan.build([_S((5, 3))], n=8, bucket_bytes=1 << 30)
    assert len(one) == 1 and one.buckets[0].L == 15
    # a bucket capacity below one element still makes progress (1 elem min)
    tiny = ov.BucketPlan.build([_S((3,))], n=1, bucket_bytes=1)
    assert [b.L for b in tiny.buckets] == [1, 1, 1]


def test_plan_mixed_dtypes_stream_per_dtype():
    leaves = [_S((100,), np.float32), _S((100,), np.int32),
              _S((100,), jnp.bfloat16), _S((100,), np.float32)]
    plan = ov.BucketPlan.build(leaves, n=4, bucket_bytes=1 << 20)
    keys = [b.key for b in plan.buckets]
    assert keys == ["float32#0", "bfloat16#0", "int32#0"]
    # the two f32 leaves share one bucket; emission order is reversed
    f32 = plan.groups["float32#0"]
    assert [s.idx for s in f32.segs] == [3, 0]


def test_plan_boundaries_are_world_size_independent():
    leaves = [_S((1000,)), _S((500,))]
    a = ov.BucketPlan.build(leaves, n=2, bucket_bytes=1024)
    b = ov.BucketPlan.build(leaves, n=8, bucket_bytes=1024)
    assert [(x.key, x.segs, x.L) for x in a.buckets] == \
           [(x.key, x.segs, x.L) for x in b.buckets]
    assert [x.Lp for x in a.buckets] != [x.Lp for x in b.buckets] or all(
        x.L % 8 == 0 for x in a.buckets)


def test_pack_assemble_roundtrip_with_split_and_padding():
    rng = np.random.RandomState(0)
    leaves = [jnp.asarray(rng.randn(10).astype(np.float32)),
              jnp.arange(6, dtype=jnp.int32).reshape(2, 3),
              jnp.asarray(rng.randn(4, 5).astype(np.float32))]
    plan = ov.BucketPlan.build(leaves, n=4, bucket_bytes=32)
    flats = {k: ov.pack_group(leaves, b) for k, b in plan.groups.items()}
    for k, b in plan.groups.items():
        assert flats[k].shape == (b.Lp,)
    out = ov.assemble(
        flats, plan.groups, [l.shape for l in leaves],
        [l.dtype for l in leaves])
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resolve_bucket_bytes_env_and_kwargs(monkeypatch):
    monkeypatch.delenv("HOROVOD_OVERLAP", raising=False)
    monkeypatch.delenv("HOROVOD_BUCKET_BYTES", raising=False)
    monkeypatch.delenv("HOROVOD_FUSION_THRESHOLD", raising=False)
    assert ov.resolve_bucket_bytes(None, None) is None
    assert ov.resolve_bucket_bytes(True, None) == ov.DEFAULT_BUCKET_BYTES
    assert ov.resolve_bucket_bytes(None, 123) == 123  # bytes imply overlap
    monkeypatch.setenv("HOROVOD_OVERLAP", "1")
    assert ov.resolve_bucket_bytes(None, None) == ov.DEFAULT_BUCKET_BYTES
    # the explicit kwarg wins over the env
    assert ov.resolve_bucket_bytes(False, None) is None
    # HOROVOD_BUCKET_BYTES, then the existing fusion-threshold knob
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "2048")
    assert ov.resolve_bucket_bytes(True, None) == 2048
    monkeypatch.setenv("HOROVOD_BUCKET_BYTES", "4096")
    assert ov.resolve_bucket_bytes(True, None) == 4096


# --------------------------------------------------------------------------
# trajectory equivalence: bucketed vs monolithic


def _mk_params(uneven=False):
    rng = np.random.RandomState(0)
    d = 33 if uneven else 32  # 33: nothing divides the 8-way padding
    return {
        "w": jnp.asarray(rng.randn(64, d).astype(np.float32) * 0.1),
        "b": jnp.zeros((d,), jnp.float32),
    }


def _mk_batch(d):
    rng = np.random.RandomState(1)
    return (jnp.asarray(rng.randn(16, 64), jnp.float32),
            jnp.asarray(rng.randn(16, d), jnp.float32))


def _loss(p, x, y):
    return jnp.mean((x @ p["w"] + p["b"][None] - y) ** 2)


def _run_cell(hvd, *, overlap, shard, compression=None, ef=False,
              steps=12, bucket_bytes=4096, uneven=False):
    mesh, ax = hvd.mesh(), hvd.data_axis()
    params = _mk_params(uneven)
    x, y = _mk_batch(params["b"].shape[0])
    kw = dict(shard_optimizer=shard)
    if compression is not None:
        kw.update(compression=compression, error_feedback=ef)
    if overlap:
        kw.update(overlap=True, bucket_bytes=bucket_bytes)
    dtx = hvd.DistributedOptimizer(optax.adam(1e-2), **kw)
    p = jax.tree_util.tree_map(jnp.array, params)
    s = dtx.init(p)
    opt_spec = P(ax) if shard else P()

    def step(pp, ss, xx, yy):
        l, g = jax.value_and_grad(_loss)(pp, xx, yy)
        u, ss = dtx.update(g, ss, pp)
        return optax.apply_updates(pp, u), ss, allreduce(l, Average, axis=ax)

    sm = jax.jit(_smap(
        step, mesh, (P(), opt_spec, P(ax), P(ax)), (P(), opt_spec, P())))
    for _ in range(steps):
        p, s, l = sm(p, s, x, y)
    return p, s, float(l)


def _leaves_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


@pytest.mark.parametrize("comp,ef", [(None, False), (Compression.fp16, True)])
def test_zero1_bucketed_trajectory_bit_identical(hvd, comp, ef):
    pa, _, la = _run_cell(hvd, overlap=False, shard=True,
                          compression=comp, ef=ef)
    pb, sb, lb = _run_cell(hvd, overlap=True, shard=True,
                           compression=comp, ef=ef)
    assert _leaves_equal(pa, pb), "bucketed ZeRO-1 trajectory diverged"
    assert la == lb
    # the bucketed state really is bucketed: per-bucket [N, shard_k]
    # buffers under dtype#k keys
    keys = {
        k for path in map(str, [
            p for p, _ in jax.tree_util.tree_leaves_with_path(sb)
        ]) for k in re.findall(r"float32#\d+", path)
    }
    assert len(keys) >= 2, f"expected multiple buckets, saw {keys}"


@pytest.mark.parametrize("comp,ef", [(None, False), (Compression.fp16, True)])
def test_zero1_bucketed_uneven_padding_bit_identical(hvd, comp, ef):
    """Uneven leading dims (33-wide leaves: every bucket needs its own
    ZeRO padding) — the per-bucket zero padding is inert through Adam."""
    pa, _, _ = _run_cell(hvd, overlap=False, shard=True,
                         compression=comp, ef=ef, uneven=True)
    pb, _, _ = _run_cell(hvd, overlap=True, shard=True,
                         compression=comp, ef=ef, uneven=True)
    assert _leaves_equal(pa, pb)


def test_zero1_single_bucket_matches_monolithic(hvd):
    """One bucket larger than all gradients: the plan degenerates to the
    monolithic packing (modulo the dtype#0 key) — bit-identical."""
    pa, _, _ = _run_cell(hvd, overlap=False, shard=True)
    pb, sb, _ = _run_cell(hvd, overlap=True, shard=True,
                          bucket_bytes=1 << 30)
    assert _leaves_equal(pa, pb)
    paths = "".join(
        str(p) for p, _ in jax.tree_util.tree_leaves_with_path(sb))
    assert "float32#0" in paths and "float32#1" not in paths


def test_allreduce_bucketed_grads_bit_identical_trajectory_close(hvd):
    """Non-sharded mode: the bucketed reduced gradients are bit-identical
    to per-leaf allreduce every step (pinned directly); the 12-step
    trajectory is 1e-6-close — the residual difference is XLA fusing the
    Adam elementwise chain differently between the two programs (FMA
    contraction), not the sync."""
    mesh, ax = hvd.mesh(), hvd.data_axis()
    params = _mk_params()
    x, y = _mk_batch(32)

    def mono(p, xx, yy):
        g = jax.grad(_loss)(p, xx, yy)
        return jax.tree_util.tree_map(
            lambda t: allreduce(t, Average, axis=ax), g)

    def buck(p, xx, yy):
        g = jax.grad(_loss)(p, xx, yy)
        return ov.bucketed_allreduce(
            g, Average, axis=ax, bucket_bytes=4096)[0]

    ga = jax.jit(_smap(mono, mesh, (P(), P(ax), P(ax)), P()))(params, x, y)
    gb = jax.jit(_smap(buck, mesh, (P(), P(ax), P(ax)), P()))(params, x, y)
    assert _leaves_equal(ga, gb), "bucketed sync changed the gradients"

    pa, _, la = _run_cell(hvd, overlap=False, shard=False)
    pb, _, lb = _run_cell(hvd, overlap=True, shard=False)
    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=0)
    assert abs(la - lb) < 1e-6


def test_allreduce_fp16_bucketed_ef_keyed_by_bucket(hvd):
    """fp16 + EF, non-sharded: residuals ride the bucket-keyed flat
    layout and the trajectory tracks monolithic. Tolerance is an fp16
    ULP, not 1e-6: the non-sharded programs differ by 1 f32 ULP/step
    (XLA FMA fusion — see the `none` test), and once params differ at
    all, values near an fp16 rounding boundary round differently, so the
    divergence floor is the wire's own quantum (EF keeps it bounded)."""
    pa, _, _ = _run_cell(hvd, overlap=False, shard=False,
                         compression=Compression.fp16, ef=True)
    pb, sb, _ = _run_cell(hvd, overlap=True, shard=False,
                          compression=Compression.fp16, ef=True)
    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-3, rtol=0)
    res = sb.residual
    assert isinstance(res, dict) and all("#" in k for k in res)
    assert len(res) >= 2
    assert all(v.ndim == 1 for v in res.values())


@pytest.mark.parametrize("shard", [False, True])
def test_int8_bucketed_tracks_within_quantization_tolerance(hvd, shard):
    """int8's blockwise scales are layout-dependent: bucketing re-rounds,
    so bit-identicality is impossible by construction — the pin is that
    the EF-corrected trajectories track and converge together."""
    pa, _, la = _run_cell(hvd, overlap=False, shard=shard,
                          compression=Compression.int8, ef=True)
    pb, _, lb = _run_cell(hvd, overlap=True, shard=shard,
                          compression=Compression.int8, ef=True)
    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=0.05, rtol=0)
    assert abs(la - lb) < 5e-3


def test_mixed_dtype_tree_bucketed_sync_exact(hvd):
    """Mixed f32/bf16/i32 gradient tree through bucketed_allreduce: each
    dtype rides its own bucket stream, bit-equal to per-leaf allreduce."""
    mesh, ax = hvd.mesh(), hvd.data_axis()
    rng = np.random.RandomState(2)
    tree = {
        "f": jnp.asarray(rng.randn(40, 7).astype(np.float32)),
        "h": jnp.asarray(rng.randn(30).astype(np.float32)).astype(
            jnp.bfloat16),
        "i": jnp.arange(24, dtype=jnp.int32).reshape(6, 4),
    }

    def mono(t, seed):
        t = jax.tree_util.tree_map(lambda v: v + seed.astype(v.dtype), t)
        return jax.tree_util.tree_map(
            lambda v: allreduce(v, Sum, axis=ax), t)

    def buck(t, seed):
        t = jax.tree_util.tree_map(lambda v: v + seed.astype(v.dtype), t)
        return ov.bucketed_allreduce(t, Sum, axis=ax, bucket_bytes=64)[0]

    seed = jnp.arange(8, dtype=jnp.float32).reshape(8, 1) * 0
    # per-rank perturbation via the bound axis index
    def mk(fn):
        def inner(t, s):
            idx = jax.lax.axis_index(ax).astype(jnp.float32)
            return fn(t, idx * 0.5)
        return jax.jit(_smap(inner, mesh, (P(), P(ax)), P()))

    ra = mk(mono)(tree, seed)
    rb = mk(buck)(tree, seed)
    for k in tree:
        np.testing.assert_array_equal(
            np.asarray(ra[k]), np.asarray(rb[k]))


def test_eager_bucketed_allreduce_replicated_and_stacked(hvd):
    """Eager dispatch: replicated leaves and stacked [N, ...] per-rank
    leaves both reduce bit-equal to the per-leaf eager allreduce."""
    mesh, ax = hvd.mesh(), hvd.data_axis()
    rng = np.random.RandomState(3)
    rep = {"a": jnp.asarray(rng.randn(50).astype(np.float32)),
           "b": jnp.asarray(rng.randn(9, 3).astype(np.float32))}
    out, _ = ov.bucketed_allreduce(rep, Average, axis=ax, bucket_bytes=128)
    ref = jax.tree_util.tree_map(
        lambda v: allreduce(v, Average, axis=ax), rep)
    assert _leaves_equal(out, ref)
    # stacked per-rank values
    st = jax.device_put(
        jnp.asarray(rng.randn(8, 20).astype(np.float32)),
        NamedSharding(mesh, P(ax)))
    out2, _ = ov.bucketed_allreduce(
        {"s": st}, Average, axis=ax, bucket_bytes=32)
    ref2 = allreduce(st, Average, axis=ax)
    np.testing.assert_array_equal(np.asarray(out2["s"]), np.asarray(ref2))


def test_bucketed_sync_rejects_adasum_and_powersgd(hvd):
    from horovod_tpu.ops.collective import Adasum

    with pytest.raises(ValueError, match="Adasum"):
        hvd.DistributedOptimizer(
            optax.adam(1e-3), op=Adasum, overlap=True)
    with pytest.raises(ValueError, match="PowerSGD"):
        hvd.DistributedOptimizer(
            optax.adam(1e-3), compression=Compression.powersgd(2),
            error_feedback=True, overlap=True)
    with pytest.raises(ValueError, match="Adasum"):
        ov.bucketed_allreduce({"a": jnp.ones(4)}, Adasum)


def test_grad_sync_buckets_gauge(hvd):
    hvd.metrics.reset()
    _run_cell(hvd, overlap=True, shard=True, steps=1, bucket_bytes=4096)
    assert hvd.metrics.value("grad_sync_buckets", mode="sharded") >= 2
    _run_cell(hvd, overlap=False, shard=True, steps=1)
    assert hvd.metrics.value("grad_sync_buckets", mode="sharded") == 1


# --------------------------------------------------------------------------
# reshard: bucketed states across world sizes


def test_bucketed_state_reshards_8_4_8(hvd):
    params = _mk_params(uneven=True)
    dtx = hvd.DistributedOptimizer(
        optax.adam(1e-2), shard_optimizer=True,
        compression=Compression.fp16, error_feedback=True,
        overlap=True, bucket_bytes=4096)
    s8 = dtx.init(jax.tree_util.tree_map(jnp.array, params))
    s4 = hvd.reshard_optimizer_state(
        s8, params, to_size=4, bucket_bytes=4096)
    for v in s4.residual.values():
        assert v.shape[0] == 4
    back = hvd.reshard_optimizer_state(
        s4, params, to_size=8, bucket_bytes=4096)
    for (k, a), b in zip(
            sorted(s8.residual.items()),
            (v for _, v in sorted(back.residual.items()))):
        assert a.shape == b.shape
    # mass preservation: the summed residual is unchanged by the trip
    for k in s8.residual:
        np.testing.assert_allclose(
            np.asarray(s8.residual[k]).sum(),
            np.asarray(back.residual[k]).sum(), atol=1e-6)


def test_bucketed_reshard_ambiguous_tail_bucket_uses_key(hvd):
    """A tail bucket whose ZeRO padding makes it the SAME padded size as
    a full sibling (2044 f32 elems @ 4096-byte buckets → L=1024 and
    L=1020, both [8, 128] at n=8) must re-pack by its bucket KEY, not by
    shape guessing — otherwise the 1020-bucket resizes as if it were
    1024 long and the restored state mis-slices."""
    params = {"w": jnp.zeros((2044,), jnp.float32)}
    dtx = hvd.DistributedOptimizer(
        optax.adam(1e-2), shard_optimizer=True,
        compression=Compression.fp16, error_feedback=True,
        overlap=True, bucket_bytes=4096)
    s8 = dtx.init(params)
    assert {v.shape for v in s8.residual.values()} == {(8, 1024)}
    s4 = hvd.reshard_optimizer_state(
        s8, params, to_size=4, bucket_bytes=4096)
    # full bucket: pad(1024, 4)=1024 → [4, 1024]; tail: pad(1020, 4)=1020
    assert s4.residual["float32#0"].shape == (4, 1024)
    assert s4.residual["float32#1"].shape == (4, 1020)
    # and the inner [n, shard] buffers followed their keys too
    mu = jax.tree_util.tree_leaves(s4.inner)
    assert {(4, 256), (4, 255)} <= {tuple(x.shape) for x in mu}
    back = hvd.reshard_optimizer_state(
        s4, params, to_size=8, bucket_bytes=4096)
    assert {v.shape for v in back.residual.values()} == {(8, 1024)}


def test_reshard_plain_state_with_hash_in_param_names_passes_through(hvd):
    """'#' in a USER param name must not trip bucket-state detection:
    plain (non-sharded) states over such trees pass through untouched
    (the documented consolidate_opt_state contract) instead of raising
    the bucket-plan-mismatch error."""
    params = {"block#0": jnp.ones((5,), jnp.float32)}
    tx = optax.adam(1e-2)
    s = tx.init(params)
    out = hvd.reshard_optimizer_state(s, params, to_size=4)
    assert _leaves_equal(s, out)


def test_bucketed_state_reshard_wrong_bucket_bytes_raises(hvd):
    params = _mk_params()
    dtx = hvd.DistributedOptimizer(
        optax.adam(1e-2), shard_optimizer=True,
        compression=Compression.fp16, error_feedback=True,
        overlap=True, bucket_bytes=4096)
    s8 = dtx.init(jax.tree_util.tree_map(jnp.array, params))
    with pytest.raises(ValueError, match="HOROVOD_BUCKET_BYTES"):
        hvd.reshard_optimizer_state(
            s8, params, to_size=4, bucket_bytes=1024)


# --------------------------------------------------------------------------
# interleaving pins: the staged (custom_vjp hook) backward


def _hooked_and_mono_steps(hvd, n_blocks=3, width=32):
    mesh, ax = hvd.mesh(), hvd.data_axis()
    rng = np.random.RandomState(0)
    ws = [jnp.asarray(rng.randn(width, width).astype(np.float32) * 0.1)
          for _ in range(n_blocks)]
    x = jnp.asarray(rng.randn(16, width), jnp.float32)

    def block(w, h):
        return jnp.tanh(h @ w)

    sync = lambda gp: ov.bucketed_allreduce(  # noqa: E731
        gp, Average, axis=ax, bucket_bytes=1 << 20)[0]
    hooked_block = ov.sync_hook(block, sync)

    def loss_hooked(w_list, xx):
        h = xx
        for w in w_list:
            h = hooked_block(w, h)
        return jnp.mean(h ** 2)

    def loss_plain(w_list, xx):
        h = xx
        for w in w_list:
            h = block(w, h)
        return jnp.mean(h ** 2)

    def step_hooked(w_list, xx):
        return jax.grad(loss_hooked)(w_list, xx)

    def step_mono(w_list, xx):
        g = jax.grad(loss_plain)(w_list, xx)
        return jax.tree_util.tree_map(
            lambda t: allreduce(t, Average, axis=ax), g)

    smh = _smap(step_hooked, mesh, (P(), P(ax)), P())
    smm = _smap(step_mono, mesh, (P(), P(ax)), P())
    return smh, smm, ws, x


def test_sync_hook_interleaves_collectives_in_backward(hvd):
    """THE overlap pin: >= 2 collectives strictly between backward
    compute fragments in the staged step's jaxpr; 0 in the monolithic
    step; gradients bit-identical between the two."""
    from horovod_tpu.analysis import (
        collectives_before_last_compute, interleave_profile,
    )

    smh, smm, ws, x = _hooked_and_mono_steps(hvd)
    ph = interleave_profile(smh, ws, x)
    pm = interleave_profile(smm, ws, x)
    assert collectives_before_last_compute(ph) >= 2, ph
    assert collectives_before_last_compute(pm) == 0, pm
    gh = jax.jit(smh)(ws, x)
    gm = jax.jit(smm)(ws, x)
    assert _leaves_equal(gh, gm)


def test_sync_hook_interleaving_survives_compilation(hvd):
    """The optimized-HLO pin: after XLA's own scheduling, >= 2 all-reduce
    launches still sit before the last backward matmul — the
    optimization_barrier token threading makes the order a data
    dependency no scheduler may undo."""
    smh, _smm, ws, x = _hooked_and_mono_steps(hvd)
    txt = jax.jit(smh).lower(ws, x).compile().as_text()
    events = []
    for m in re.finditer(r"(all-reduce(?:-start)?|dot)\(", txt):
        events.append(m.group(1))
    last_dot = max(i for i, e in enumerate(events) if e == "dot")
    before = sum(1 for e in events[:last_dot] if e.startswith("all-reduce"))
    assert before >= 2, events


def test_sync_hook_barrier_off_still_correct(hvd):
    mesh, ax = hvd.mesh(), hvd.data_axis()
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(16, 16).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(8, 16), jnp.float32)

    def block(p, h):
        return h @ p

    hooked = ov.sync_hook(
        block, lambda g: allreduce(g, Average, axis=ax), barrier=False)

    def step(p, xx):
        return jax.grad(lambda q: jnp.sum(hooked(q, xx) ** 2))(p)

    def mono(p, xx):
        g = jax.grad(lambda q: jnp.sum(block(q, xx) ** 2))(p)
        return allreduce(g, Average, axis=ax)

    a = jax.jit(_smap(step, mesh, (P(), P(ax)), P()))(w, x)
    b = jax.jit(_smap(mono, mesh, (P(), P(ax)), P()))(w, x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_make_shardmap_train_step_overlap_schedule_and_equivalence(hvd):
    """Builder integration: overlap=True swaps the per-leaf allreduces
    for K bucket collectives (schedule extractor pin) and the loss
    trajectory matches the default step to fp tolerance."""
    import flax.linen as nn

    from horovod_tpu.analysis import collective_schedule
    from horovod_tpu.training import (
        make_shardmap_train_step, replicate, shard_batch, softmax_xent,
    )

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(64)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    model = MLP()
    x_np = np.random.RandomState(0).rand(32, 12, 12).astype(np.float32)
    y_np = np.random.RandomState(1).randint(0, 10, 32)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 12, 12), jnp.float32))
    params0 = variables.get("params", variables)

    def drive(overlap):
        tx = optax.adam(1e-3)
        step = make_shardmap_train_step(
            model, tx, loss_fn=softmax_xent, instrument=False,
            overlap=overlap, bucket_bytes=8192 if overlap else None)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        opt = replicate(tx.init(params))
        xs, ys = shard_batch(x_np), shard_batch(y_np)
        sched = collective_schedule(step, params, {}, opt, xs, ys)
        for _ in range(6):
            params, _stats, opt, loss = step(params, {}, opt, xs, ys)
        return sched, float(loss)

    sched_ov, loss_ov = drive(True)
    sched_mono, loss_mono = drive(False)
    n_ov = sched_ov.counts().get("psum", 0)
    n_mono = sched_mono.counts().get("psum", 0)
    # monolithic: one psum per gradient leaf (4) + stats/loss reductions;
    # bucketed: K buckets replace the per-leaf sync
    assert n_ov != n_mono
    assert n_ov >= 3  # >= 2 gradient buckets + the loss reduction
    assert abs(loss_ov - loss_mono) < 1e-5


def test_make_jit_train_step_accepts_overlap_on_cpu(hvd):
    """pjit-style overlap= arms the XLA flags; on a CPU target the
    TPU-only flags are withheld (they would be a fatal parse error), so
    the call is a clean no-op and the step still trains."""
    import flax.linen as nn

    from horovod_tpu.training import (
        make_jit_train_step, replicate, shard_batch, softmax_xent,
    )

    before = os.environ.get("XLA_FLAGS", "")

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(10)(x.reshape((x.shape[0], -1)))

    model = Tiny()
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8, 8), jnp.float32))
    params = replicate(variables.get("params", variables))
    tx = optax.sgd(1e-2)
    step = make_jit_train_step(
        model, tx, loss_fn=softmax_xent, instrument=False, overlap=True)
    opt = replicate(tx.init(params))
    xs = shard_batch(np.random.RandomState(0).rand(
        32, 8, 8).astype(np.float32))
    ys = shard_batch(np.random.RandomState(1).randint(0, 10, 32))
    params, _stats, opt, loss = step(params, {}, opt, xs, ys)
    assert np.isfinite(float(loss))
    assert os.environ.get("XLA_FLAGS", "") == before, (
        "TPU-only flags leaked into XLA_FLAGS on a CPU target"
    )


# --------------------------------------------------------------------------
# hvd.tuning


def test_tuning_applies_preset_idempotently_on_tpu_target():
    env = {"JAX_PLATFORMS": "tpu"}
    added, skipped = tuning.apply_xla_flags("overlap", env=env)
    assert added and not skipped
    assert all(f in env["XLA_FLAGS"] for f in added)
    again, skipped2 = tuning.apply_xla_flags("overlap", env=env)
    assert not again and len(skipped2) == len(added)


def test_tuning_never_clobbers_user_set_entries():
    user = "--xla_tpu_enable_latency_hiding_scheduler=false"
    env = {"JAX_PLATFORMS": "tpu", "XLA_FLAGS": user}
    added, skipped = tuning.apply_xla_flags("overlap", env=env)
    assert user in env["XLA_FLAGS"]
    assert env["XLA_FLAGS"].count("xla_tpu_enable_latency_hiding_scheduler") == 1
    assert any("latency_hiding" in f for f in skipped)
    assert all("latency_hiding" not in f for f in added)


def test_tuning_withholds_tpu_flags_on_cpu_target():
    """A --xla_tpu_* flag on a CPU jaxlib is a FATAL parse error, not a
    no-op — the preset must be withheld entirely."""
    env = {"JAX_PLATFORMS": "cpu"}
    added, skipped = tuning.apply_xla_flags("overlap", env=env)
    assert not added and skipped
    assert "XLA_FLAGS" not in env


def test_tuning_env_knob_and_unknown_preset():
    assert tuning.maybe_apply_from_env({}) == ([], [])
    env = {"JAX_PLATFORMS": "tpu",
           tuning.PRESET_ENV: "overlap"}
    added, _ = tuning.maybe_apply_from_env(env)
    assert added
    with pytest.raises(ValueError, match="unknown"):
        tuning.apply_xla_flags("warp-speed", env={})
    assert tuning.apply_xla_flags("none", env={}) == ([], [])


# --------------------------------------------------------------------------
# analytic model + bench rung


def test_overlap_step_time_model():
    import sys

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    from scaling_projection import overlap_step_time

    # K=1 degenerates to serial
    assert overlap_step_time(1.0, 0.5, 1)["overlapped_s"] == 1.5
    # balanced compute/comm, 8 buckets, no latency: max + min/K
    m = overlap_step_time(1.0, 1.0, 8)
    assert m["overlapped_s"] == pytest.approx(1.125)
    assert m["speedup"] == pytest.approx(2.0 / 1.125)
    # latency clamps at serial — overlap never loses in the model
    w = overlap_step_time(1e-6, 1e-5, 64, latency_s=1e-5)
    assert w["overlapped_s"] <= w["serial_s"]
    assert overlap_step_time(2.0, 1.0, 4)["bound"] == "compute"
    assert overlap_step_time(1.0, 2.0, 4)["bound"] == "comm"


def test_overlap_ab_byte_model_parity():
    import bench

    m = bench._overlap_model(8, 256 * 1024, 64)
    # bucketing moves the same gradient bytes as the monolithic packing
    assert m["bucketed_bytes"] == m["grad_bytes"]
    assert m["n_buckets"] >= 2
    assert m["projection_v4"]["serial_s"] >= m["projection_v4"]["overlapped_s"]


@pytest.mark.slow
def test_bench_overlap_ab_rung():
    """bench.py --overlap-ab emits ONE JSON line on the CPU mesh with
    the measured ratio, byte parity across modes, and the analytic
    model."""
    import json as _json
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--overlap-ab", "--iters", "6", "--no-probe"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    d = _json.loads(line)
    assert d["metric"] == "overlap_ab_step_ratio"
    if not d.get("skipped"):
        assert d["value"] > 0
        assert d["grad_sync_buckets"]["bucketed"] >= 2
        assert d["grad_sync_bytes_per_step"]["bucketed"] == pytest.approx(
            d["grad_sync_bytes_per_step"]["monolithic"], rel=0.01)
    assert d["overlap_model"]["bucketed_bytes"] == \
        d["overlap_model"]["grad_bytes"]


# --------------------------------------------------------------------------
# CI guard: every overlap env knob is in the docs knob table


def test_overlap_env_knobs_documented():
    """Every HOROVOD_BUCKET_* / HOROVOD_OVERLAP* / HOROVOD_XLA_FLAGS* /
    HOROVOD_PALLAS* / HOROVOD_SERVING_* / HOROVOD_ENGINE_* /
    HOROVOD_SLO_* / HOROVOD_REQTRACE* / HOROVOD_FLEET_* /
    HOROVOD_RETRY_ROUTE_* / HOROVOD_PREFIX_* / HOROVOD_SPEC_* /
    HOROVOD_KV_REPLICA* / HOROVOD_KV_FENC* / HOROVOD_FSDP_* /
    HOROVOD_TP_* env knob
    named in the source must appear in docs/performance.md's,
    docs/serving.md's, docs/observability.md's, docs/fault_tolerance.md's,
    or docs/running.md's knob tables
    (metric-catalog-guard pattern, PR 7/9)."""
    knob_re = re.compile(
        r"HOROVOD_(?:BUCKET_[A-Z]+(?:_[A-Z]+)*"
        r"|OVERLAP(?:_[A-Z]+)*"
        r"|PALLAS(?:_[A-Z]+)*"
        r"|SERVING_[A-Z]+(?:_[A-Z]+)*"
        r"|ENGINE_[A-Z]+(?:_[A-Z]+)*"
        r"|SLO(?:_[A-Z]+)*"
        r"|REQTRACE(?:_[A-Z]+)*"
        r"|FLEET_[A-Z]+(?:_[A-Z]+)*"
        r"|RETRY_ROUTE(?:_[A-Z]+)*"
        r"|PREFIX_[A-Z]+(?:_[A-Z]+)*"
        r"|SPEC_[A-Z]+(?:_[A-Z]+)*"
        r"|KV_REPLICA[A-Z]*(?:_[A-Z]+)*"
        r"|KV_FENC[A-Z]*(?:_[A-Z]+)*"
        r"|FSDP_[A-Z]+(?:_[A-Z]+)*"
        r"|TP_[A-Z]+(?:_[A-Z]+)*"
        r"|XLA_FLAGS_[A-Z]+(?:_[A-Z]+)*)")
    knobs = set()
    for dirpath, _dirnames, filenames in os.walk(
            os.path.join(_REPO, "horovod_tpu")):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                knobs |= set(knob_re.findall(f.read()))
    assert {"HOROVOD_BUCKET_BYTES", "HOROVOD_OVERLAP",
            "HOROVOD_OVERLAP_BARRIER", "HOROVOD_PALLAS",
            "HOROVOD_XLA_FLAGS_PRESET", "HOROVOD_ENGINE_PAGE_SIZE",
            "HOROVOD_SERVING_CANARY_FRACTION", "HOROVOD_SLO",
            "HOROVOD_SLO_FAST_WINDOW", "HOROVOD_REQTRACE"} <= knobs
    doc = ""
    for name in ("performance.md", "serving.md", "observability.md",
                 "fault_tolerance.md", "running.md"):
        with open(os.path.join(_REPO, "docs", name)) as f:
            doc += f.read()
    missing = sorted(k for k in knobs if k not in doc)
    assert not missing, (
        f"env knobs named in code but absent from the docs/performance.md "
        f"/ docs/serving.md / docs/observability.md / "
        f"docs/fault_tolerance.md / docs/running.md knob tables: {missing}"
    )
