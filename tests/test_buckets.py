"""Unit tests for the fixed fusion buckets (``core.py::_Buckets``) — the
XLA-side analog of the reference's FusionBufferManager
(``common/ops/collective_operations.cc`` MemcpyInFusionBuffer): launch
signatures must be arrival-independent so steady-state training replays a
compiled program set instead of recompiling arrival-dependent bins.

Pure-Python: no native core, no mesh.
"""

import time

from horovod_tpu.core import _Buckets


def _mk(threshold=100):
    return _Buckets(threshold)


def test_fixed_assignment_first_seen_order():
    b = _mk(threshold=100)
    assert b.bucket_of("a", 40) == 0
    assert b.bucket_of("b", 40) == 0
    assert b.bucket_of("c", 40) == 1  # 120 > 100 -> new bucket
    assert b.bucket_of("a", 40) == 0  # sticky
    assert b.members[0] == ["a", "b"]
    assert b.members[1] == ["c"]


def test_single_oversized_tensor_gets_its_own_bucket():
    b = _mk(threshold=10)
    assert b.bucket_of("big", 1000) == 0  # never an empty bucket
    assert b.bucket_of("big2", 1000) == 1


def test_complete_bucket_launches_in_member_order():
    b = _mk(threshold=100)
    b.add("a", 40, "item_a")
    bid, displaced = b.add("b", 40, "item_b")
    assert displaced is None
    items = b.take_complete(bid)
    assert items == ["item_a", "item_b"]
    assert b.pending == {}


def test_partial_bucket_held_until_complete():
    b = _mk(threshold=100)
    b.bucket_of("a", 40)
    b.bucket_of("b", 40)  # same bucket, not yet arrived
    bid, _ = b.add("a", 40, "item_a")
    assert b.take_complete(bid) is None  # b missing
    assert bid in b.pending


def test_repeat_name_drains_previous_generation():
    """A pipelined caller's next-step entry must NOT silently overwrite a
    held previous-generation item — the old generation is displaced for
    immediate launch so its handles complete."""
    b = _mk(threshold=100)
    b.add("a", 40, "a_gen1")
    bid, displaced = b.add("a", 40, "a_gen2")
    assert displaced == ["a_gen1"]
    assert b.pending[bid]["a"] == "a_gen2"


def test_deadline_flush_respects_age():
    b = _mk(threshold=100)
    b.add("a", 40, "item_a")
    assert b.take_partials(older_than=60.0) == []  # too young
    b.held_since[0] -= 120.0  # age it
    assert b.take_partials(older_than=60.0) == [["item_a"]]


def test_repeated_deadline_flush_prunes_absent_members():
    """An abandoned bucket-mate must not tax survivors with the deadline
    forever: after PRUNE_AFTER_FLUSHES consecutive deadline drains the
    absent names are pruned and survivors complete within a cycle again."""
    b = _mk(threshold=100)
    b.bucket_of("a", 40)
    b.bucket_of("gone", 40)  # same bucket, never enqueued again
    for i in range(_Buckets.PRUNE_AFTER_FLUSHES):
        bid, _ = b.add("a", 40, f"a_{i}")
        b.held_since[bid] -= 120.0
        assert b.take_partials(older_than=60.0) == [[f"a_{i}"]]
    # membership rebuilt without the absent name: next add completes
    assert b.members[0] == ["a"]
    assert "gone" not in b.assign
    bid, _ = b.add("a", 40, "a_fresh")
    assert b.take_complete(bid) == ["a_fresh"]
    # a pruned name that reappears is assigned afresh (open bucket)
    nb = b.bucket_of("gone", 40)
    assert b.assign["gone"] == nb


def test_complete_launch_resets_strikes():
    b = _mk(threshold=100)
    b.bucket_of("a", 40)
    b.bucket_of("b", 40)
    bid, _ = b.add("a", 40, "a_1")
    b.held_since[bid] -= 120.0
    assert b.take_partials(older_than=60.0) == [["a_1"]]
    assert b.flush_strikes[bid] == 1
    b.add("a", 40, "a_2")
    b.add("b", 40, "b_2")
    assert b.take_complete(bid) == ["a_2", "b_2"]
    assert bid not in b.flush_strikes


def test_late_new_name_opens_its_own_bucket():
    """A first-seen name arriving long after the registration burst (a
    per-epoch metric, say) must NOT join the established open bucket —
    it would stall on the deadline and strike-prune active mates."""
    b = _mk(threshold=1000)
    b.bucket_of("a", 40)
    b.bucket_of("b", 40)
    b.last_assign -= 2 * _Buckets.NEW_BUCKET_AFTER_S  # time passes
    bid = b.bucket_of("metric", 40)
    assert bid != b.assign["a"]
    assert b.members[bid] == ["metric"]
    # sole member: completes immediately
    bid2, _ = b.add("metric", 40, "m_item")
    assert b.take_complete(bid2) == ["m_item"]


def test_full_drain_takes_everything_without_strikes():
    b = _mk(threshold=100)
    b.bucket_of("a", 40)
    b.bucket_of("b", 40)
    b.add("a", 40, "a_1")
    assert b.take_partials() == [["a_1"]]  # shutdown-style drain
    assert b.flush_strikes == {}
