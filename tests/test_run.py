"""Launcher unit tests (reference ``test/test_run.py``: arg→env translation,
config-file merging, slot allocation, command construction with mocked exec,
process-tree kill semantics) plus a real 2-process localhost job."""

import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
from unittest import mock

import pytest

from horovod_tpu.run import config_parser, hosts, runner, safe_exec
from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer


# ---------------------------------------------------------------- hosts


def test_parse_hosts():
    infos = hosts.parse_hosts("h1:4,h2:2,h3")
    assert [(h.hostname, h.slots) for h in infos] == [
        ("h1", 4), ("h2", 2), ("h3", 1)
    ]


def test_parse_hostfile(tmp_path):
    f = tmp_path / "hostfile"
    f.write_text("h1 slots=4\n# comment\nh2 slots=2\nh3\n")
    infos = hosts.parse_hostfile(str(f))
    assert [(h.hostname, h.slots) for h in infos] == [
        ("h1", 4), ("h2", 2), ("h3", 1)
    ]


def test_allocate_coordinates():
    # 2 hosts x 2 slots: the reference's rank/local/cross math
    # (gloo_run.py:54-112)
    infos = hosts.parse_hosts("h1:2,h2:2")
    slots = hosts.allocate(infos, 4)
    assert [s.rank for s in slots] == [0, 1, 2, 3]
    assert [s.local_rank for s in slots] == [0, 1, 0, 1]
    assert all(s.local_size == 2 for s in slots)
    assert [s.cross_rank for s in slots] == [0, 0, 1, 1]
    assert all(s.cross_size == 2 for s in slots)
    assert all(s.size == 4 for s in slots)


def test_allocate_oversubscribe_rejected():
    with pytest.raises(ValueError, match="exceeds available slots"):
        hosts.allocate(hosts.parse_hosts("h1:2"), 3)


def test_slot_env():
    slots = hosts.allocate(hosts.parse_hosts("h1:2"), 2)
    env = hosts.slot_env(slots[1])
    assert env["HOROVOD_RANK"] == "1"
    assert env["HOROVOD_SIZE"] == "2"
    assert env["HOROVOD_LOCAL_RANK"] == "1"
    assert env["HVD_PROCESS_ID"] == "1"
    assert env["HVD_NUM_PROCESSES"] == "2"


# ---------------------------------------------------------------- args/env


def test_args_to_env():
    args = runner.parse_args(
        [
            "-np", "2",
            "--fusion-threshold-mb", "32",
            "--cycle-time-ms", "3.5",
            "--cache-capacity", "2048",
            "--timeline-filename", "/tmp/t.json",
            "--timeline-mark-cycles",
            "--stall-check-warning-time-seconds", "120",
            "--stall-check-shutdown-time-seconds", "240",
            "--autotune",
            "--autotune-log-file", "/tmp/a.csv",
            "--log-level", "INFO",
            "--native-core",
            "python", "train.py",
        ]
    )
    env = {}
    config_parser.set_env_from_args(env, args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HOROVOD_CYCLE_TIME"] == "3.5"
    assert env["HOROVOD_CACHE_CAPACITY"] == "2048"
    assert env["HOROVOD_TIMELINE"] == "/tmp/t.json"
    assert env["HOROVOD_TIMELINE_MARK_CYCLES"] == "1"
    assert float(env["HOROVOD_STALL_CHECK_TIME_SECONDS"]) == 120
    assert float(env["HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"]) == 240
    assert env["HOROVOD_AUTOTUNE"] == "1"
    assert env["HOROVOD_AUTOTUNE_LOG"] == "/tmp/a.csv"
    assert env["HOROVOD_LOG_LEVEL"] == "INFO"
    assert env["HOROVOD_NATIVE_CORE"] == "1"
    assert args.command == ["python", "train.py"]


def test_hierarchical_flags():
    # tri-state: unset -> no env; --x -> "1"; --no-x -> "0" (reference
    # horovodrun's mutually-exclusive group pairs, runner.py:295)
    args = runner.parse_args(["-np", "1", "x"])
    env = {}
    config_parser.set_env_from_args(env, args)
    assert "HOROVOD_HIERARCHICAL_ALLREDUCE" not in env
    assert "HOROVOD_HIERARCHICAL_ALLGATHER" not in env

    args = runner.parse_args(
        ["-np", "1", "--hierarchical-allreduce",
         "--no-hierarchical-allgather", "x"])
    env = {}
    config_parser.set_env_from_args(env, args)
    assert env["HOROVOD_HIERARCHICAL_ALLREDUCE"] == "1"
    assert env["HOROVOD_HIERARCHICAL_ALLGATHER"] == "0"

    with pytest.raises(SystemExit):
        runner.parse_args(["-np", "1", "--hierarchical-allreduce",
                           "--no-hierarchical-allreduce", "x"])


def test_no_stall_check_flag():
    args = runner.parse_args(["-np", "1", "--no-stall-check", "x"])
    env = {}
    config_parser.set_env_from_args(env, args)
    assert env["HOROVOD_STALL_CHECK_DISABLE"] == "1"
    assert "HOROVOD_STALL_CHECK_TIME_SECONDS" not in env


def test_validate_args():
    with pytest.raises(ValueError, match="cycle-time-ms"):
        runner.parse_args(["-np", "1", "--cycle-time-ms", "0", "x"])


# ---------------------------------------------------------------- config file


CONFIG_YAML = textwrap.dedent(
    """
    fusion_threshold_mb: 16
    cycle_time_ms: 2.5
    cache_capacity: 512
    timeline:
        filename: /tmp/conf_timeline.json
        mark_cycles: true
    stall_check:
        warning_time_seconds: 99
    autotune:
        enable: true
        log_file: /tmp/conf_autotune.csv
    library_options:
        log_level: DEBUG
    """
)


def test_config_file_applies(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(CONFIG_YAML)
    args = runner.parse_args(["-np", "1", "--config-file", str(cfg), "x"])
    assert args.fusion_threshold_mb == 16
    assert args.cycle_time_ms == 2.5
    assert args.cache_capacity == 512
    assert args.timeline_filename == "/tmp/conf_timeline.json"
    assert args.timeline_mark_cycles is True
    assert args.stall_check_warning_time_seconds == 99
    assert args.autotune is True
    assert args.autotune_log_file == "/tmp/conf_autotune.csv"
    assert args.log_level == "DEBUG"


def test_cli_overrides_config(tmp_path):
    # explicit CLI flags beat the config file (reference test_run.py:168-226)
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(CONFIG_YAML)
    args = runner.parse_args(
        ["-np", "1", "--config-file", str(cfg), "--cycle-time-ms", "7", "x"]
    )
    assert args.cycle_time_ms == 7
    assert args.fusion_threshold_mb == 16  # still from config


def test_config_unknown_key_rejected(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("nonsense_key: 1\n")
    with pytest.raises(ValueError, match="unknown config keys"):
        runner.parse_args(["-np", "1", "--config-file", str(cfg), "x"])


# ---------------------------------------------------------------- commands


def test_build_command_local():
    slot = hosts.allocate(hosts.parse_hosts("localhost:2"), 2)[1]
    argv, env = runner.build_command_for_slot(
        slot, ["python", "train.py"], {"A": "1"}, "127.0.0.1", 1234, 5678
    )
    assert argv == ["python", "train.py"]
    assert env["HVD_COORDINATOR_ADDR"] == "127.0.0.1:1234"
    assert env["HVD_CORE_COORD_ADDR"] == "127.0.0.1"
    assert env["HVD_CORE_COORD_PORT"] == "5678"
    assert env["HOROVOD_RANK"] == "1"


def test_build_command_remote_ssh():
    slot = hosts.allocate(hosts.parse_hosts("far-host:1"), 1)[0]
    argv, _ = runner.build_command_for_slot(
        slot, ["python", "train.py"], {}, "far-host", 1234, 5678, ssh_port=2222
    )
    assert argv[0] == "ssh"
    assert "-p" in argv and "2222" in argv
    assert argv[-2] == "far-host"
    remote = argv[-1]
    assert "HOROVOD_RANK=0" in remote
    assert "HVD_CORE_COORD_PORT=5678" in remote
    assert "python train.py" in remote


def test_launch_job_mocked_failure_kills_job():
    # one rank failing must terminate the whole job
    # (reference gloo_run.py:294-304)
    slots = hosts.allocate(hosts.parse_hosts("localhost:2"), 2)
    calls = []

    def fake_execute(argv, env=None, stdout_handler=None, stderr_handler=None,
                     event=None, shell=False):
        rank = int(env["HOROVOD_RANK"])
        calls.append(rank)
        if rank == 0:
            return 3  # fail fast
        assert event.wait(10), "rank 1 was never told to stop"
        return -signal.SIGTERM

    with mock.patch.object(runner.safe_exec, "execute", fake_execute):
        codes = runner.launch_job(slots, ["python", "train.py"], {})
    assert sorted(calls) == [0, 1]
    assert codes[0] == 3
    assert codes[1] == -signal.SIGTERM


# ---------------------------------------------------------------- safe_exec


def test_safe_exec_basic():
    rc = safe_exec.execute([sys.executable, "-c", "print('hi')"])
    assert rc == 0


def test_safe_exec_kills_process_tree():
    # parent spawns a grandchild; event-triggered kill must take down both
    # (reference safe_shell_exec.py middleman semantics)
    script = (
        "import subprocess, sys, time\n"
        "p = subprocess.Popen([sys.executable, '-c', "
        "'import time; print(\"G\", flush=True); time.sleep(60)'])\n"
        "print('child pid', p.pid, flush=True)\n"
        "time.sleep(60)\n"
    )
    lines = []
    event = threading.Event()

    def on_out(line):
        lines.append(line)
        if line.startswith("child pid"):
            event.set()  # kill as soon as the grandchild exists

    t0 = time.monotonic()
    rc = safe_exec.execute(
        [sys.executable, "-u", "-c", script],
        stdout_handler=on_out,
        event=event,
    )
    elapsed = time.monotonic() - t0
    assert rc == -signal.SIGTERM
    assert elapsed < 30
    # grandchild must be gone: its pid was printed
    pid = None
    for line in lines:
        if line.startswith("child pid"):
            pid = int(line.split()[-1])
    assert pid is not None
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        os.kill(pid, signal.SIGKILL)
        pytest.fail("grandchild survived tree kill")


# ---------------------------------------------------------------- KV store


def test_kv_store_roundtrip():
    server = KVStoreServer()
    port = server.start()
    try:
        client = KVStoreClient("127.0.0.1", port)
        assert client.get("missing") is None
        client.put("k1", b"v1")
        assert client.get("k1") == b"v1"
        assert server.get("k1") == b"v1"
        server.put("k2", b"v2")
        assert client.wait_for("k2", timeout=5) == b"v2"
    finally:
        server.stop()


def test_kv_store_auth():
    server = KVStoreServer(secret="s3cret")
    port = server.start()
    try:
        good = KVStoreClient("127.0.0.1", port, secret="s3cret")
        bad = KVStoreClient("127.0.0.1", port, secret="wrong")
        good.put("k", b"v")
        with pytest.raises(RuntimeError, match="403"):
            bad.put("k", b"x")
        with pytest.raises(RuntimeError, match="403"):
            bad.get("k")
        assert good.get("k") == b"v"
    finally:
        server.stop()


# ---------------------------------------------------------------- end-to-end


def test_programmatic_run_two_processes():
    # nested fn: cloudpickle serializes it by value, so the worker process
    # does not need this test module importable
    def worker_fn(x):
        import os

        rank = int(os.environ["HOROVOD_RANK"])
        size = int(os.environ["HOROVOD_SIZE"])
        return {"rank": rank, "size": size, "x2": x * 2}

    results = runner.run(worker_fn, args=(21,), np=2, timeout_s=120)
    assert results == [
        {"rank": 0, "size": 2, "x2": 42},
        {"rank": 1, "size": 2, "x2": 42},
    ]


def test_programmatic_run_propagates_worker_error():
    def boom():
        raise RuntimeError("worker exploded")

    with pytest.raises(RuntimeError, match="worker exploded"):
        runner.run(boom, np=1, timeout_s=120)


def test_cli_end_to_end(tmp_path):
    script = tmp_path / "job.py"
    script.write_text(
        "import os\n"
        "print('R', os.environ['HOROVOD_RANK'], 'of',"
        " os.environ['HOROVOD_SIZE'])\n"
    )
    out_dir = tmp_path / "logs"
    rc = runner.run_commandline(
        [
            "-np", "2",
            "--output-filename", str(out_dir),
            sys.executable, str(script),
        ]
    )
    assert rc == 0
    assert (out_dir / "rank.0.out").read_text().strip() == "R 0 of 2"
    assert (out_dir / "rank.1.out").read_text().strip() == "R 1 of 2"


def test_multihost_aliased_run(tmp_path):
    """-H localhost:1,127.0.0.1:1 — a 2-"host" aliased job (both resolve
    locally, like reference ``test/test_interactiverun.py:1-77``): distinct
    global ranks, per-host local/cross coordinates, and a real cross-process
    collective over the launcher-wired rendezvous."""
    def worker_fn():
        import os

        import jax

        jax.config.update("jax_platforms", "cpu")
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r = hvd.process_rank()
        out = np.asarray(hvd.allreduce(np.full((3,), float(r + 1)), hvd.Sum))
        return {
            "rank": int(os.environ["HOROVOD_RANK"]),
            "local_rank": hvd.local_rank(),
            "local_size": hvd.local_size(),
            "cross_rank": int(os.environ["HOROVOD_CROSS_RANK"]),
            "cross_size": int(os.environ["HOROVOD_CROSS_SIZE"]),
            "sum": out.tolist(),
        }

    results = runner.run(
        worker_fn, np=2, hosts="localhost:1,127.0.0.1:1", timeout_s=180
    )
    assert [r["rank"] for r in results] == [0, 1]
    # one slot per aliased "host": local 0-of-1 on each, cross 2 hosts
    assert all(r["local_rank"] == 0 and r["local_size"] == 1 for r in results)
    assert [r["cross_rank"] for r in results] == [0, 1]
    assert all(r["cross_size"] == 2 for r in results)
    # the collective really crossed both processes: 1 + 2 = 3
    assert all(r["sum"] == [3.0, 3.0, 3.0] for r in results)


def test_cli_failure_exit_code(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(7)\n")
    rc = runner.run_commandline(["-np", "1", sys.executable, str(script)])
    assert rc == 1


# ---------------------------------------------------------------- scheduler


def test_hosts_from_lsf_env(tmp_path):
    from horovod_tpu.run.hosts import hosts_from_scheduler_env

    hf = tmp_path / "lsb_hosts"
    hf.write_text("node1\nnode1\nnode2\nnode2\n")
    infos = hosts_from_scheduler_env({"LSB_DJOB_HOSTFILE": str(hf)})
    assert [(i.hostname, i.slots) for i in infos] == [
        ("node1", 2), ("node2", 2)]

    infos = hosts_from_scheduler_env({"LSB_HOSTS": "a a a b"})
    assert [(i.hostname, i.slots) for i in infos] == [("a", 3), ("b", 1)]


def test_hosts_from_slurm_env():
    from horovod_tpu.run.hosts import hosts_from_scheduler_env

    infos = hosts_from_scheduler_env({
        "SLURM_JOB_NODELIST": "tpu[01-03],gpu7",
        "SLURM_NTASKS_PER_NODE": "4",
    })
    assert [(i.hostname, i.slots) for i in infos] == [
        ("tpu01", 4), ("tpu02", 4), ("tpu03", 4), ("gpu7", 4)]


def test_hosts_env_empty_falls_back():
    from horovod_tpu.run.hosts import hosts_from_scheduler_env

    assert hosts_from_scheduler_env({}) is None


def test_hosts_slurm_tasks_per_node_format():
    from horovod_tpu.run.hosts import hosts_from_scheduler_env

    infos = hosts_from_scheduler_env({
        "SLURM_JOB_NODELIST": "n[1-3],m5",
        "SLURM_TASKS_PER_NODE": "2(x3),1",
    })
    assert [(i.hostname, i.slots) for i in infos] == [
        ("n1", 2), ("n2", 2), ("n3", 2), ("m5", 1)]


def test_hosts_lsf_unreadable_hostfile_falls_through(tmp_path):
    from horovod_tpu.run.hosts import hosts_from_scheduler_env

    infos = hosts_from_scheduler_env({
        "LSB_DJOB_HOSTFILE": str(tmp_path / "does_not_exist"),
        "LSB_HOSTS": "x x y",
    })
    assert [(i.hostname, i.slots) for i in infos] == [("x", 2), ("y", 1)]


def test_check_build_summary(capsys):
    """--check-build mirrors reference horovodrun --check-build
    (runner.py:115-151): honest availability flags, exit 0."""
    rc = runner.run_commandline(["--check-build"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[X] JAX / optax (native)" in out
    assert "[X] XLA" in out
    assert "[ ] NCCL" in out and "[ ] MPI" in out  # honest negatives


def test_mpi_flag_rejected(capsys):
    rc = runner.run_commandline(["--mpi", "-np", "1", "--", "python", "x.py"])
    assert rc == 2
    assert "no MPI by design" in capsys.readouterr().err


def test_gloo_flag_accepted():
    """--gloo parses as a compat no-op (the TCP controller fills the role)."""
    args = runner.parse_args(["--gloo", "-np", "2", "--", "python", "x.py"])
    assert args.use_gloo is True and args.np == 2
