"""Torch frontend tests — the analog of reference ``test/test_torch.py``
(single-process flavor: the 8-device CPU mesh gives replicated semantics,
i.e. every rank contributes the same value, so Sum multiplies by size and
Average is identity — the same local-arithmetic oracle pattern as
``test/common.py:33-66``)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")


@pytest.fixture()
def thvd(hvd):
    import horovod_tpu.torch as thvd

    return thvd


class TestOps:
    def test_allreduce_average(self, thvd):
        t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
        out = thvd.allreduce(t, name="tar.avg")
        assert torch.allclose(out, t)
        assert out.dtype == t.dtype

    def test_allreduce_sum(self, thvd):
        t = torch.ones(5)
        out = thvd.allreduce(t, op=thvd.Sum, name="tar.sum")
        assert torch.allclose(out, t * thvd.size())

    def test_allreduce_average_kwarg_conflict(self, thvd):
        with pytest.raises(ValueError):
            thvd.allreduce(torch.ones(2), average=True, op=thvd.Sum)

    def test_allreduce_inplace(self, thvd):
        t = torch.ones(4)
        r = thvd.allreduce_(t, op=thvd.Sum, name="tar.inp")
        assert r is t
        assert torch.allclose(t, torch.full((4,), float(thvd.size())))

    def test_allreduce_fp16_compression(self, thvd):
        t = torch.rand(8, dtype=torch.float32)
        out = thvd.allreduce(
            t, name="tar.fp16", compression=thvd.Compression.fp16
        )
        assert out.dtype == torch.float32
        assert torch.allclose(out, t, atol=1e-2)

    def test_allreduce_int_dtype(self, thvd):
        t = torch.arange(6, dtype=torch.int32)
        out = thvd.allreduce(t, op=thvd.Sum, name="tar.int")
        assert out.dtype == torch.int32
        assert torch.equal(out, t * thvd.size())

    def test_allreduce_grad(self, thvd):
        t = torch.rand(3, 3, requires_grad=True)
        out = thvd.allreduce(t, op=thvd.Sum, name="tar.grad")
        out.sum().backward()
        # d(sum over ranks)/dt via allreduce-of-grad: ones * size
        assert torch.allclose(t.grad, torch.full_like(t, float(thvd.size())))

    def test_allreduce_async(self, thvd):
        t = torch.ones(3)
        h = thvd.allreduce_async(t, op=thvd.Sum, name="tar.async")
        out = thvd.synchronize(h)
        assert torch.allclose(out, t * thvd.size())
        assert thvd.poll(h)

    def test_allreduce_async_inplace(self, thvd):
        t = torch.ones(3)
        h = thvd.allreduce_async_(t, op=thvd.Sum, name="tar.async.inp")
        out = thvd.synchronize(h)
        assert out is t
        assert torch.allclose(t, torch.full((3,), float(thvd.size())))

    def test_duplicate_name_rejected(self, thvd):
        t = torch.ones(2)
        h = thvd.allreduce_async(t, name="tar.dup")
        with pytest.raises(ValueError, match="[Dd]uplicate"):
            thvd.allreduce_async(t, name="tar.dup")
        thvd.synchronize(h)

    def test_grouped_allreduce(self, thvd):
        ts = [torch.full((2, 2), float(i + 1)) for i in range(3)]
        outs = thvd.grouped_allreduce(ts, op=thvd.Sum, name="tar.grp")
        for i, o in enumerate(outs):
            assert torch.allclose(o, ts[i] * thvd.size())

    def test_allgather(self, thvd):
        t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
        out = thvd.allgather(t, name="tag.basic")
        assert out.shape == (2 * thvd.size(), 3)
        for r in range(thvd.size()):
            assert torch.allclose(out[2 * r:2 * r + 2], t)

    def test_allgather_grad(self, thvd):
        t = torch.rand(2, 2, requires_grad=True)
        out = thvd.allgather(t, name="tag.grad")
        out.sum().backward()
        assert torch.allclose(t.grad, torch.full_like(t, float(thvd.size())))

    def test_broadcast(self, thvd):
        t = torch.arange(4, dtype=torch.float32)
        out = thvd.broadcast(t, root_rank=0, name="tbc.basic")
        assert torch.allclose(out, t)

    def test_broadcast_inplace(self, thvd):
        t = torch.ones(4)
        r = thvd.broadcast_(t, 0, name="tbc.inp")
        assert r is t

    def test_broadcast_bad_root(self, thvd):
        with pytest.raises(ValueError):
            thvd.broadcast(torch.ones(2), root_rank=thvd.size())

    def test_join(self, thvd):
        assert isinstance(thvd.join(), int)

    def test_broadcast_object(self, thvd):
        obj = {"lr": 0.1, "steps": [1, 2, 3]}
        out = thvd.broadcast_object(obj, root_rank=0)
        assert out == obj

    def test_allgather_object(self, thvd):
        outs = thvd.allgather_object({"r": 1})
        assert outs == [{"r": 1}] * thvd.size()


class TestDistributedOptimizer:
    def _model(self):
        torch.manual_seed(0)
        return torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2)
        )

    def test_train_step(self, thvd):
        model = self._model()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
        )
        x = torch.rand(16, 4)
        y = torch.randint(0, 2, (16,))
        before = [p.detach().clone() for p in model.parameters()]
        for _ in range(3):
            opt.zero_grad()
            loss = torch.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
        after = list(model.parameters())
        assert any(
            not torch.allclose(b, a.detach()) for b, a in zip(before, after)
        )

    def test_matches_local_sgd(self, thvd):
        # replicated data => allreduce-averaged grads == local grads, so the
        # wrapped optimizer must track plain SGD exactly.
        m1, m2 = self._model(), self._model()
        m2.load_state_dict(m1.state_dict())
        o1 = torch.optim.SGD(m1.parameters(), lr=0.05)
        o2 = thvd.DistributedOptimizer(
            torch.optim.SGD(m2.parameters(), lr=0.05),
            named_parameters=m2.named_parameters(),
        )
        x = torch.rand(8, 4)
        y = torch.randint(0, 2, (8,))
        for _ in range(2):
            for m, o in ((m1, o1), (m2, o2)):
                o.zero_grad()
                torch.nn.functional.cross_entropy(m(x), y).backward()
                o.step()
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            assert torch.allclose(p1, p2, atol=1e-6)

    def test_backward_passes_per_step(self, thvd):
        model = self._model()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2,
        )
        x = torch.rand(8, 4)
        y = torch.randint(0, 2, (8,))
        opt.zero_grad()
        torch.nn.functional.cross_entropy(model(x), y).backward()
        torch.nn.functional.cross_entropy(model(x), y).backward()
        opt.step()

    def test_too_many_backwards_raises(self, thvd):
        model = self._model()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
        )
        x = torch.rand(4, 4)
        y = torch.randint(0, 2, (4,))
        torch.nn.functional.cross_entropy(model(x), y).backward()
        with pytest.raises(AssertionError, match="backward_passes_per_step"):
            torch.nn.functional.cross_entropy(model(x), y).backward()
        # clean up pending handles so shutdown is clean
        opt.synchronize()

    def test_zero_grad_mid_step_raises(self, thvd):
        model = self._model()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
        )
        x = torch.rand(4, 4)
        y = torch.randint(0, 2, (4,))
        torch.nn.functional.cross_entropy(model(x), y).backward()
        with pytest.raises(AssertionError, match="zero_grad"):
            opt.zero_grad()
        opt.synchronize()

    def test_duplicate_names_rejected(self, thvd):
        model = self._model()
        named = list(model.named_parameters())
        named = [("same", p) for _, p in named]
        with pytest.raises(ValueError, match="unique"):
            thvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=named,
            )

    def test_synchronize_then_skip(self, thvd):
        model = self._model()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
        )
        x = torch.rand(4, 4)
        y = torch.randint(0, 2, (4,))
        opt.zero_grad()
        torch.nn.functional.cross_entropy(model(x), y).backward()
        opt.synchronize()
        with opt.skip_synchronize():
            opt.step()


class TestBroadcastState:
    def test_broadcast_parameters(self, thvd):
        model = torch.nn.Linear(3, 3)
        want = {k: v.detach().clone() for k, v in model.state_dict().items()}
        thvd.broadcast_parameters(model.state_dict(), root_rank=0)
        for k, v in model.state_dict().items():
            assert torch.allclose(v, want[k])

    def test_broadcast_optimizer_state(self, thvd):
        model = torch.nn.Linear(3, 3)
        opt = torch.optim.SGD(model.parameters(), lr=0.3, momentum=0.9)
        # materialize momentum buffers
        model(torch.rand(2, 3)).sum().backward()
        opt.step()
        thvd.broadcast_optimizer_state(opt, root_rank=0)
        sd = opt.state_dict()
        assert sd["param_groups"][0]["lr"] == pytest.approx(0.3)
        assert any(
            "momentum_buffer" in s for s in sd["state"].values()
        )

    def test_broadcast_optimizer_state_fresh(self, thvd):
        model = torch.nn.Linear(3, 3)
        opt = torch.optim.SGD(model.parameters(), lr=0.3, momentum=0.9)
        thvd.broadcast_optimizer_state(opt, root_rank=0)  # no state yet


class TestSyncBatchNorm:
    def test_matches_local_bn_replicated(self, thvd):
        # replicated data: global stats == local stats => SyncBatchNorm must
        # match plain BatchNorm exactly (reference test_torch.py sync-bn).
        torch.manual_seed(0)
        x = torch.rand(4, 3, 5, 5)
        bn = torch.nn.BatchNorm2d(3)
        sbn = thvd.SyncBatchNorm(3)
        sbn.load_state_dict(bn.state_dict())
        bn.train()
        sbn.train()
        y1, y2 = bn(x), sbn(x)
        assert torch.allclose(y1, y2, atol=1e-5)
        assert torch.allclose(
            bn.running_mean, sbn.running_mean, atol=1e-5
        )
        # running_var's unbiased n/(n-1) correction uses the GLOBAL count in
        # sync-BN (800 here) vs the local count (100) in plain BN — a real
        # semantic difference, bounded by var*momentum*(1/99 - 1/799).
        assert torch.allclose(bn.running_var, sbn.running_var, atol=1e-3)

    def test_backward_matches(self, thvd):
        torch.manual_seed(1)
        x = torch.rand(4, 3, 4, 4)
        x1 = x.clone().requires_grad_(True)
        x2 = x.clone().requires_grad_(True)
        bn = torch.nn.BatchNorm2d(3)
        sbn = thvd.SyncBatchNorm(3)
        sbn.load_state_dict(bn.state_dict())
        bn.train()
        sbn.train()
        bn(x1).pow(2).sum().backward()
        sbn(x2).pow(2).sum().backward()
        assert torch.allclose(x1.grad, x2.grad, atol=1e-4)
        assert torch.allclose(
            bn.weight.grad, sbn.weight.grad, atol=1e-4
        )
        assert torch.allclose(bn.bias.grad, sbn.bias.grad, atol=1e-4)

    def test_eval_uses_running_stats(self, thvd):
        sbn = thvd.SyncBatchNorm(2)
        sbn.eval()
        x = torch.rand(3, 2)
        out = sbn(x)
        assert out.shape == x.shape


class TestDistributedAdasumOptimizer:
    """Delta-style Adasum optimizer (reference ``torch/__init__.py:225-394``).
    Replicated single-controller semantics: every in-process rank holds the
    same tensors, and adasum over identical deltas is the identity, so the
    wrapped optimizer must reproduce the plain local optimizer exactly."""

    def _models(self):
        import copy

        torch.manual_seed(11)
        model = torch.nn.Linear(4, 2)
        return model, copy.deepcopy(model)

    def test_matches_local_sgd(self, thvd):
        model, ref = self._models()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            op=thvd.Adasum,
        )
        ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
        x = torch.randn(8, 4)
        for _ in range(3):
            opt.zero_grad()
            model(x).pow(2).mean().backward()
            opt.step()
            ref_opt.zero_grad()
            ref(x).pow(2).mean().backward()
            ref_opt.step()
        for p, q in zip(model.parameters(), ref.parameters()):
            assert torch.allclose(p, q, atol=1e-6), (p, q)

    def test_backward_passes_per_step_accumulates(self, thvd):
        model, ref = self._models()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters(),
            op=thvd.Adasum,
            backward_passes_per_step=2,
        )
        ref_opt = torch.optim.SGD(ref.parameters(), lr=0.05)
        x1, x2 = torch.randn(4, 4), torch.randn(4, 4)
        opt.zero_grad()
        model(x1).pow(2).mean().backward()
        model(x2).pow(2).mean().backward()  # grads accumulate locally
        opt.step()
        ref_opt.zero_grad()
        ref(x1).pow(2).mean().backward()
        ref(x2).pow(2).mean().backward()
        ref_opt.step()
        for p, q in zip(model.parameters(), ref.parameters()):
            assert torch.allclose(p, q, atol=1e-6), (p, q)

    def test_skip_synchronize_rejected(self, thvd):
        model, _ = self._models()
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            op=thvd.Adasum,
        )
        with pytest.raises(AssertionError):
            with opt.skip_synchronize():
                pass


class TestErrorFeedback:
    def test_requires_lossy_compression(self, thvd):
        model = torch.nn.Linear(4, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        with pytest.raises(ValueError, match="lossy"):
            thvd.DistributedOptimizer(
                opt, named_parameters=model.named_parameters(),
                error_feedback=True)

    def test_rejected_with_adasum(self, thvd):
        model = torch.nn.Linear(4, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        with pytest.raises(ValueError, match="Adasum"):
            thvd.DistributedOptimizer(
                opt, named_parameters=model.named_parameters(),
                op=thvd.Adasum, compression=thvd.Compression.fp16,
                error_feedback=True)

    def test_residual_tracks_fp16_rounding(self, thvd):
        """After one step the kept-back residual equals g - fp16(g) exactly
        (mirrors the optax EF test; replicated semantics make the reduced
        grad the fp16 roundtrip of the local grad)."""
        model = torch.nn.Linear(1, 1, bias=False)
        opt = torch.optim.SGD(model.parameters(), lr=0.0)
        opt = thvd.DistributedOptimizer(
            opt, named_parameters=model.named_parameters(),
            compression=thvd.Compression.fp16, error_feedback=True)
        g = 1.0 + 2.0 ** -12  # rounds away in fp16 (10 mantissa bits)
        x = torch.full((1, 1), 1.0)
        loss = (model(x) * g).sum()
        loss.backward()
        opt.step()
        (p,) = [p for pg in opt.param_groups for p in pg["params"]]
        resid = opt._ef_residual[p]
        expect = torch.full_like(resid, g) - torch.full_like(
            resid, g).half().float()
        assert float(expect.abs().max()) > 0  # fp16 actually rounded
        torch.testing.assert_close(resid, expect)
        # the reduced gradient written back is the fp16 roundtrip
        torch.testing.assert_close(
            p.grad, torch.full_like(p.grad, g).half().float())
        opt.zero_grad()

        # next step: residual folds back in; same raw grad now transmits
        # fp16(g + resid) and keeps the new (smaller) error
        loss = (model(x) * g).sum()
        loss.backward()
        opt.step()
        folded = torch.full_like(resid, g) + expect
        torch.testing.assert_close(
            opt._ef_residual[p], folded - folded.half().float())

        # the residual rides state_dict() through checkpoint/resume,
        # under its own key so inner lazy state init stays untouched
        sd = opt.state_dict()
        assert 0 in sd["ef_residual"]
        expect_resid = opt._ef_residual[p].clone()
        opt._ef_residual.clear()
        opt.load_state_dict(sd)
        torch.testing.assert_close(opt._ef_residual[p], expect_resid)

    def test_works_with_adam_lazy_state_init(self, thvd):
        """Residuals must NOT live in self.state[p]: Adam's lazy init
        checks `len(state) == 0` and crashes if the hook seeded it."""
        model = torch.nn.Linear(4, 2)
        opt = thvd.DistributedOptimizer(
            torch.optim.Adam(model.parameters(), lr=0.01),
            named_parameters=model.named_parameters(),
            compression=thvd.Compression.fp16, error_feedback=True)
        for _ in range(2):
            opt.zero_grad()
            loss = model(torch.randn(3, 4)).sum()
            loss.backward()
            opt.step()  # raised KeyError: 'exp_avg' before the fix
        assert len(opt._ef_residual) == 2  # weight + bias


class TestReduceScatter:
    def test_reducescatter_sum_and_async(self, thvd):
        """In-process eager convention (matches the jax surface,
        tests/test_ops.py::test_reducescatter): the replicated input's
        reduce-scatter comes back with every rank's block stacked
        [n, block]; block r = size * input[2r:2r+2] under Sum."""
        n = thvd.size()
        t = torch.arange(n * 2, dtype=torch.float32)
        out = thvd.reducescatter(t, op=thvd.Sum, name="trs")
        expect = (t * n).reshape(n, 2)
        torch.testing.assert_close(out, expect)

        h = thvd.reducescatter_async(t, op=thvd.Sum, name="trs.async")
        torch.testing.assert_close(thvd.synchronize(h), expect)
