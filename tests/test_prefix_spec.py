"""Serving hot-path (ISSUE 18): automatic prefix caching, speculative
decoding, and prefix-affinity fleet routing.

The acceptance pins:

- prefix-hit and speculative outputs are BIT-identical to ``generate()``
  for ragged batches with mid-flight joins — caching and speculation are
  pure memory/scheduling optimisations, never sampling changes;
- measured prefill-token savings and draft proposal/acceptance counts
  match the analytic ``tools/scaling_projection.py`` models EXACTLY on
  deterministic A/B workloads (a full-depth draft accepts 100% by
  construction);
- a page-aliasing churn soak never strands or double-frees a refcount,
  never mutates a shared page, and never leaks stale KV through a
  recycled page;
- the ``cache_evict_at_pass`` chaos charge forces victims to re-prefill
  with tokens bit-identical to the uninterrupted run;
- the fleet router prefers cache-warm replicas only BELOW the
  staleness/backpressure tiers.

Tier-1: deterministic, no sleeps; ``serving`` marker.
"""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from horovod_tpu.models.transformer import TransformerLM  # noqa: E402
from horovod_tpu.observability import metrics, reqtrace  # noqa: E402
from horovod_tpu.resilience import chaos, health  # noqa: E402
from horovod_tpu.run.rendezvous import KVStoreServer  # noqa: E402
from horovod_tpu.serving import (  # noqa: E402
    GenerationRollout,
    InferenceEngine,
    WeightPublisher,
    WeightSubscriber,
)
from horovod_tpu.serving.scheduler import (  # noqa: E402
    PrefixCache,
    Request,
    prefix_digests,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.serving


@pytest.fixture(autouse=True)
def _fresh():
    from horovod_tpu.serving import publisher as _pub_mod

    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()
    yield
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.reset()
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()


def _model(depth=2, vocab=97, dim=32, heads=4, max_len=64):
    return TransformerLM(vocab=vocab, dim=dim, depth=depth, heads=heads,
                         mlp_ratio=2, max_len=max_len, dtype=jnp.float32)


def _params(model, seed=0):
    return model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]


def _ragged_prompts(seed, lens, vocab=97):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=l).astype(np.int32) for l in lens]


def _engine(model, params, *, generation=1, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 64)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_seq_len", 48)
    eng = InferenceEngine(model, **kw)
    eng.set_weights(params, generation=generation)
    return eng


def _serve(eng, prompts, max_new, tag, **kw):
    reqs = [eng.submit(p, max_new, rid=f"{tag}-{i}", **kw)
            for i, p in enumerate(prompts)]
    eng.run_until_idle()
    return [np.asarray(r.generated) for r in reqs], reqs


# -------------------------------------------------------- digests + cache


class TestPrefixDigests:
    def test_chain_is_prefix_closed_and_content_keyed(self):
        rng = np.random.RandomState(0)
        p = rng.randint(1, 97, size=32).astype(np.int32)
        d32 = prefix_digests(p, 8)
        assert len(d32) == 4
        # a prompt sharing the first 16 tokens shares the first 2 digests
        q = np.concatenate([p[:16], rng.randint(1, 97, size=16)
                            ]).astype(np.int32)
        d_q = prefix_digests(q, 8)
        assert d_q[:2] == d32[:2] and d_q[2] != d32[2]
        # the chain keys CONTENT + POSITION: same block after a
        # different block hashes differently (no cross-prompt aliasing
        # of identical-but-shifted blocks)
        r = np.concatenate([p[8:16], p[8:16]]).astype(np.int32)
        d_r = prefix_digests(r, 8)
        assert d_r[0] != d32[1] and d_r[1] != d32[1]
        # partial trailing block contributes no digest
        assert len(prefix_digests(p[:19], 8)) == 2

    def test_cache_alignment_and_cap(self):
        c = PrefixCache(page_size=8, prefill_chunk=8)
        assert c.align_tokens == 8
        # the LAST prompt token must always prefill (it produces the
        # first-token logits): a fully-resident prompt still caps at
        # (len-1) // align pages
        assert c.max_hit_pages(16) == 1
        assert c.max_hit_pages(17) == 2
        assert c.max_hit_pages(8) == 0
        # lcm alignment: chunk 12 x page 8 -> hits in 24-token units
        c2 = PrefixCache(page_size=8, prefill_chunk=12)
        assert c2.align_tokens == 24 and c2.align_pages == 3
        assert c2.max_hit_pages(25) == 3
        assert c2.max_hit_pages(24) == 0

    def test_refcount_lru_and_acquire_pins(self):
        c = PrefixCache(page_size=8, prefill_chunk=8)
        assert c.insert(1, "a", 10) and c.insert(1, "b", 11)
        assert not c.insert(1, "a", 12)  # duplicate content
        assert c.evictable() == 2
        c.acquire([10])
        assert c.evictable() == 1  # pinned pages never evict
        assert c.evict(5) == [11]
        c.release([10])
        assert c.evict(5) == [10]
        assert c.resident_pages() == 0

    def test_lookup_is_longest_resident_run(self):
        c = PrefixCache(page_size=8, prefill_chunk=8)
        c.insert(1, "a", 10)
        c.insert(1, "c", 12)
        assert c.lookup(1, ["a", "b", "c"]) == [10]  # stops at the hole
        assert c.lookup(2, ["a"]) == []  # namespaced: other generation


# ------------------------------------------------------------- engine hits


class TestPrefixCacheParity:
    def test_warm_pass_bit_identical_with_exact_prefill_savings(self):
        from tools.scaling_projection import prefix_prefill_flops

        model = _model()
        params = _params(model)
        lens = (19, 8, 27, 12, 33)
        prompts = _ragged_prompts(3, lens)
        eng = _engine(model, params, prefix_cache=True)
        cold, _ = _serve(eng, prompts, 8, "cold")
        t_cold = metrics.value("serving_prefill_tokens")
        assert t_cold == sum(lens)
        warm, _ = _serve(eng, prompts, 8, "warm")
        for a, b in zip(warm, cold):
            np.testing.assert_array_equal(a, b)
        m = prefix_prefill_flops(list(lens), list(lens), page_size=8,
                                 prefill_chunk=8)
        assert metrics.value("serving_prefill_tokens") - t_cold \
            == m["cached_prefill_tokens"]
        assert m["saved_tokens"] > 0
        assert metrics.value("serving_prefix_hits") == sum(
            1 for h in m["hit_tokens_per_request"] if h)
        assert metrics.value("serving_prefix_pages_shared") is None \
            or metrics.value("serving_prefix_pages_shared") == 0  # idle

    def test_mid_flight_joins_hit_and_stay_identical(self):
        model = _model()
        params = _params(model)
        prompts = _ragged_prompts(7, (21, 9, 26, 17))
        eng = _engine(model, params, prefix_cache=True)
        base, _ = _serve(eng, prompts, 8, "cold")
        # resubmit with STAGGERED joins: two up front, two joining while
        # the first pair is mid-decode — hits alias live-traffic pages
        reqs = [eng.submit(p, 8, rid=f"j{i}")
                for i, p in enumerate(prompts[:2])]
        for _ in range(4):
            eng.step()
        reqs += [eng.submit(p, 8, rid=f"j{i+2}")
                 for i, p in enumerate(prompts[2:])]
        eng.run_until_idle()
        for r, want in zip(reqs, base):
            np.testing.assert_array_equal(np.asarray(r.generated), want)
        assert metrics.value("serving_prefix_hits") >= 3  # len-9 misses

    def test_prefix_cache_off_never_indexes(self):
        model = _model(depth=1)
        params = _params(model)
        prompts = _ragged_prompts(1, (17, 17))
        eng = _engine(model, params, prefix_cache=False)
        _serve(eng, prompts, 4, "a")
        _serve(eng, prompts, 4, "b")
        assert eng.scheduler.cached_page_count() == 0
        assert metrics.value("serving_prefix_hits") is None

    def test_generation_namespace_isolates_hits(self):
        """New weights must never serve KV computed by old weights: the
        index is keyed by generation, so a bump turns hits to misses."""
        model = _model(depth=1)
        params = _params(model)
        prompts = _ragged_prompts(2, (19,))
        eng = _engine(model, params, prefix_cache=True)
        base, _ = _serve(eng, prompts, 6, "g1")
        eng.set_weights(params, generation=2)
        warm, _ = _serve(eng, prompts, 6, "g2")
        np.testing.assert_array_equal(warm[0], base[0])  # same params
        assert metrics.value("serving_prefix_hits") is None
        assert metrics.value("serving_prefix_misses") == 2


# ------------------------------------------------------- admission credit


class TestAdmissionCredit:
    def test_fully_cached_prompt_admits_on_tight_pool_without_eviction(
            self):
        model = _model(depth=1)
        params = _params(model)
        prompt = _ragged_prompts(4, (24,))[0]
        # 5 allocatable pages; worst-case bill is 4 (24 prompt + 8 new)
        eng = _engine(model, params, num_pages=6, max_batch=1,
                      max_seq_len=32, prefix_cache=True)
        cold, _ = _serve(eng, [prompt], 8, "cold")
        assert eng.scheduler.cached_page_count() == 3  # full prompt pages
        assert eng.scheduler.free_page_count() == 2
        # worst 4 > free 2: only the 2-page prefix credit lets this in
        # without touching the LRU — no eviction may fire
        warm, _ = _serve(eng, [prompt], 8, "warm")
        np.testing.assert_array_equal(warm[0], cold[0])
        assert metrics.value("serving_prefix_hits") == 1
        assert metrics.value("serving_prefix_evictions") is None

    def test_backpressure_hint_scales_by_post_credit_reservation(self):
        model = _model(depth=1)
        params = _params(model)
        prompts = _ragged_prompts(9, (25, 25), vocab=97)
        eng = _engine(model, params, prefix_cache=True)
        _serve(eng, [prompts[0]], 6, "seed")  # caches 3 full pages
        sched = eng.scheduler
        # a real backlog (nothing stepped yet): the base hint is
        # queue-depth x TPOT, and only then can the credit bite
        backlog = [eng.submit(prompts[1], 6, rid=f"q{i}")
                   for i in range(4)]
        cached = Request("h-hit", prompts[0], 6)
        cold = Request("h-miss", prompts[1], 6)
        hinted = sched.backpressure_hint(cached)
        unhinted = sched.backpressure_hint(cold)
        eng.run_until_idle()
        assert all(r.error is None for r in backlog)
        assert hinted < unhinted  # credit shrinks the retry-after
        assert hinted > 0.0  # floored at one TPOT: it still needs a slot


# ---------------------------------------------------------- churn + aliasing


class TestAliasingChurnSoak:
    def _pool_invariants(self, eng):
        """Idle-engine page accounting: every page is exactly one of
        {free, cached-resident}; refcounts all zero; nothing stranded."""
        sched = eng.scheduler
        pc = sched._prefix
        free = set(sched._free_pages)
        resident = set(pc._key_of)
        assert not (free & resident), "page both free and cached"
        assert len(free) + len(resident) == eng.num_pages - 1, \
            "page leaked or double-freed"
        assert sched.pages_in_use() == 0
        assert all(v == 0 for v in pc._ref.values()), "stranded refcount"
        assert set(pc._lru) == resident, "LRU out of sync with index"

    def test_churn_soak_refcounts_cow_and_recycling(self):
        model = _model(depth=1)
        params = _params(model)
        rng = np.random.RandomState(11)
        # a TIGHT pool (11 allocatable, up to 10 held by live traffic) +
        # prompts sharing prefixes: every round mixes hits, misses,
        # LRU evictions under admission pressure, and page recycling
        eng = _engine(model, params, num_pages=12, max_batch=2,
                      max_seq_len=40, prefix_cache=True)
        stems = _ragged_prompts(12, (32, 32, 32))
        expected = {}
        for rnd in range(12):
            batch, rids = [], []
            for j in range(3):
                stem = stems[rng.randint(len(stems))]
                cut = int(rng.choice((9, 17, 25, 32)))
                p = stem[:cut]
                batch.append(p)
                rids.append(f"soak-{rnd}-{j}")
            # snapshot every cached page before the round, keyed by its
            # content digest: aliasing is copy-on-write by construction,
            # so a digest still mapped to the same page after the round
            # must hold byte-identical KV (an evicted page may be
            # recycled under a NEW digest — that is reuse, not mutation)
            pc = eng.scheduler._prefix
            mapping = dict(pc._by_key)
            resident = sorted(pc._key_of)
            before = {
                p: [np.asarray(leaf)[p]
                    for leaf in jax.tree_util.tree_leaves(eng._cache)]
                for p in resident}
            reqs = [eng.submit(p, 6, rid=r) for p, r in zip(batch, rids)]
            eng.run_until_idle()
            for p, r in zip(batch, reqs):
                key = p.tobytes()
                got = np.asarray(r.generated)
                if key not in expected:
                    expected[key] = got
                # recycled pages never leak stale KV: a repeat prompt
                # decodes bit-identically regardless of churn history
                np.testing.assert_array_equal(got, expected[key])
            leaves = jax.tree_util.tree_leaves(eng._cache)
            for key, page in mapping.items():
                if pc._by_key.get(key) != page:
                    continue  # evicted (and maybe recycled) — not shared
                for leaf, old in zip(leaves, before[page]):
                    np.testing.assert_array_equal(
                        np.asarray(leaf)[page], old)
            self._pool_invariants(eng)
        assert metrics.value("serving_prefix_hits", ) > 0
        assert metrics.value("serving_prefix_evictions") > 0  # pool churned


# ------------------------------------------------------- speculative decode


class TestSpeculativeDecoding:
    def test_full_depth_draft_pins_counters_and_parity(self):
        from tools.scaling_projection import spec_decode_tokens

        model = _model()
        params = _params(model)
        lens = (19, 8, 27, 12, 5)
        prompts = _ragged_prompts(3, lens)
        plain = _engine(model, params, prefix_cache=False)
        base, _ = _serve(plain, prompts, 10, "p")
        spec = _engine(model, params, prefix_cache=False,
                       draft_depth=model.depth, spec_lookahead=3)
        out, _ = _serve(spec, prompts, 10, "s")
        for a, b in zip(out, base):
            np.testing.assert_array_equal(a, b)
        # full-depth draft == target: acceptance is 100% and the
        # counters land EXACTLY on the analytic model
        m = spec_decode_tokens(10, 3, acceptance_rate=1.0,
                               n_requests=len(prompts))
        assert metrics.value("spec_proposed") == m["proposed"]
        assert metrics.value("spec_accepted") == m["accepted"]
        assert metrics.value("spec_rollbacks") is None

    def test_shallow_draft_parity_with_mid_flight_joins(self):
        model = _model()
        params = _params(model)
        prompts = _ragged_prompts(5, (21, 9, 26, 17, 6, 13))
        plain = _engine(model, params)
        base, _ = _serve(plain, prompts, 9, "p")
        spec = _engine(model, params, draft_depth=1, spec_lookahead=4)
        reqs = [spec.submit(p, 9, rid=f"s-{i}")
                for i, p in enumerate(prompts[:3])]
        for _ in range(5):
            spec.step()
        reqs += [spec.submit(p, 9, rid=f"s-{i+3}")
                 for i, p in enumerate(prompts[3:])]
        spec.run_until_idle()
        for r, want in zip(reqs, base):
            np.testing.assert_array_equal(np.asarray(r.generated), want)
        assert metrics.value("spec_proposed") > 0
        assert metrics.value("spec_rollbacks") > 0  # a 1-layer draft errs

    def test_spec_rides_prefix_cache_bit_identically(self):
        model = _model()
        params = _params(model)
        prompts = _ragged_prompts(8, (19, 25, 11))
        plain = _engine(model, params, prefix_cache=False)
        base, _ = _serve(plain, prompts, 10, "p")
        spec = _engine(model, params, prefix_cache=True,
                       draft_depth=1, spec_lookahead=3)
        cold, _ = _serve(spec, prompts, 10, "c")
        warm, _ = _serve(spec, prompts, 10, "w")
        for a, b, c in zip(warm, cold, base):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(b, c)
        assert metrics.value("serving_prefix_hits") >= 2

    def test_sampled_rows_fall_back_to_plain_decode(self):
        model = _model(depth=1)
        params = _params(model)
        prompts = _ragged_prompts(6, (12, 15))
        plain = _engine(model, params)
        base, _ = _serve(plain, prompts, 8, "t", temperature=0.7)
        spec = _engine(model, params, draft_depth=1, spec_lookahead=3)
        out, _ = _serve(spec, prompts, 8, "t", temperature=0.7)
        # rid-seeded sampling: plain and spec engines draw identically
        # BECAUSE temperature rows never speculate
        for a, b in zip(out, base):
            np.testing.assert_array_equal(a, b)
        assert metrics.value("spec_proposed") is None

    def test_stale_draft_generation_fences_off_speculation(self):
        model = _model(depth=1)
        params = _params(model)
        prompts = _ragged_prompts(2, (14,))
        plain = _engine(model, params)
        base, _ = _serve(plain, prompts, 8, "p")
        spec = _engine(model, params, draft_depth=1, spec_lookahead=3)
        # overwrite the auto-derived draft with a STALE generation: the
        # fence must fall back to plain decode, not verify old proposals
        spec.set_draft_weights(spec._subset_draft_params(
            jax.device_get(params)), generation=99, arm="stable")
        out, _ = _serve(spec, prompts, 8, "s")
        np.testing.assert_array_equal(out[0], base[0])
        assert metrics.value("spec_proposed") is None
        assert metrics.value(
            "serving_engine_steps", kind="spec_verify") is None

    def test_draft_must_be_truncation_of_target(self):
        model = _model(depth=2)
        with pytest.raises(ValueError, match="draft"):
            InferenceEngine(model, page_size=8, num_pages=16, max_batch=1,
                            prefill_chunk=8, max_seq_len=16, draft_depth=3)
        other = _model(depth=1, dim=16, heads=2)
        eng = _engine(model, _params(model), num_pages=16, max_batch=1,
                      max_seq_len=16, draft_depth=1)
        with pytest.raises(ValueError, match="truncation"):
            eng.set_draft_weights(
                jax.device_get(_params(other)), generation=1)


# ------------------------------------------------------------- chaos drill


@pytest.mark.chaos
class TestCacheEvictChaos:
    def test_forced_eviction_revictims_reprefill_bit_identical(self):
        model = _model()
        params = _params(model)
        prompts = _ragged_prompts(3, (19, 8, 27, 12))
        eng = _engine(model, params, prefix_cache=True)
        base, _ = _serve(eng, prompts, 10, "b")
        # fire the charge a few passes into the WARM run: hits are
        # aliased and mid-decode, so the drill hits live victims
        chaos.configure(f"cache_evict_at_pass={eng._step_count + 6}")
        out, _ = _serve(eng, prompts, 10, "v")
        for a, b in zip(out, base):
            np.testing.assert_array_equal(a, b)
        assert metrics.value("resilience_chaos_injected",
                             site="cache_evict_at_pass") == 1.0
        assert metrics.value("serving_prefix_hits") == 3  # len-8 misses
        assert metrics.value("serving_prefix_evictions") > 0
        assert eng.scheduler.pages_in_use() == 0
        # the charge is consumed: an idle follow-up run stays clean
        again, _ = _serve(eng, prompts, 10, "w")
        for a, b in zip(again, base):
            np.testing.assert_array_equal(a, b)
        assert metrics.value("resilience_chaos_injected",
                             site="cache_evict_at_pass") == 1.0

    def test_reqtrace_attributes_cached_tokens_and_spec_counts(self):
        model = _model(depth=1)
        params = _params(model)
        prompts = _ragged_prompts(4, (19,))
        eng = _engine(model, params, prefix_cache=True,
                      draft_depth=1, spec_lookahead=3)
        seen = []

        def _obs(req, summary):
            seen.append(summary)

        reqtrace.add_completion_observer(_obs)
        try:
            _serve(eng, prompts, 8, "a")
            _serve(eng, prompts, 8, "b")
        finally:
            reqtrace.remove_completion_observer(_obs)
        recs = [s for s in seen if str(s["rid"]).startswith("b-")]
        assert recs and recs[0]["cached_tokens"] == 16
        assert recs[0]["spec_proposed"] >= 3
        assert recs[0]["spec_accepted"] >= 0
        cold = [s for s in seen if str(s["rid"]).startswith("a-")]
        assert cold[0]["cached_tokens"] == 0


# ----------------------------------------------------------- fleet affinity


class TestFleetPrefixAffinity:
    def _router(self, model, params, n=3):
        from horovod_tpu.serving.fleet import FleetRouter

        router = FleetRouter()
        for i in range(n):
            router.add_replica(f"r{i}", _engine(model, params))
        return router

    def test_warm_replica_wins_the_tie(self):
        model = _model(depth=1)
        params = _params(model)
        prompt = _ragged_prompts(5, (19,))[0]
        router = self._router(model, params)
        try:
            warm = router.replica("r1")
            warm.engine.submit(prompt, 4, rid="seed")
            warm.engine.run_until_idle()
            order = [r.index for r in router.candidates(prompt=prompt)]
            assert order[0] == 1  # affinity breaks the load tie
            # no prompt -> stable index order (affinity never invents load)
            assert [r.index for r in router.candidates()] == [0, 1, 2]
        finally:
            router.close()

    def test_affinity_is_demoted_below_staleness(self):
        model = _model(depth=1)
        params = _params(model)
        prompt = _ragged_prompts(5, (19,))[0]
        router = self._router(model, params, n=2)
        try:
            warm = router.replica("r1")
            warm.engine.submit(prompt, 4, rid="seed")
            warm.engine.run_until_idle()
            warm.stale = lambda: True  # cache-warm but stale
            order = [r.index for r in router.candidates(prompt=prompt)]
            assert order == [0, 1]  # staleness dominates affinity
        finally:
            router.close()

    def test_status_blob_carries_block_summary(self):
        model = _model(depth=1)
        params = _params(model)
        prompt = _ragged_prompts(5, (19,))[0]
        router = self._router(model, params, n=1)
        try:
            r = router.replica("r0")
            r.engine.submit(prompt, 4, rid="seed")
            r.engine.run_until_idle()
            st = r.status()
            assert st["prefix_page_size"] == 8
            assert len(st["prefix_blocks"]) == 2
            # the summary is CONTENT digests — generation-free, so a
            # router can match prompts without knowing replica arms
            assert set(st["prefix_blocks"]) == set(
                prefix_digests(prompt, 8, limit=2))
        finally:
            router.close()


# ----------------------------------------------------------- analytic models


class TestScalingModels:
    def test_prefix_prefill_flops_properties(self):
        from tools.scaling_projection import prefix_prefill_flops

        m = prefix_prefill_flops([24, 8, 17], [24, 8, 17], page_size=8,
                                 prefill_chunk=8)
        # len 24 -> 2 pages (last token prefills); len 8 -> 0; 17 -> 2
        assert m["hit_tokens_per_request"] == [16, 0, 16]
        assert m["cold_prefill_tokens"] == 49
        assert m["cached_prefill_tokens"] == 17
        assert m["saved_tokens"] == 32
        assert m["prefill_token_ratio"] == pytest.approx(49 / 17)
        # chunk misalignment rounds DOWN to the lcm grid
        m2 = prefix_prefill_flops([32], [32], page_size=8,
                                  prefill_chunk=12)
        assert m2["alignment_tokens"] == 24
        assert m2["hit_tokens_per_request"] == [24]
        # partial residency never exceeds what is actually cached
        m3 = prefix_prefill_flops([32], [10], page_size=8,
                                  prefill_chunk=8)
        assert m3["hit_tokens_per_request"] == [8]
        f = prefix_prefill_flops([24], [24], page_size=8, prefill_chunk=8,
                                 params_per_token=1000)
        assert f["cold_prefill_flops"] == 2 * 1000 * 24

    def test_spec_decode_tokens_properties(self):
        from tools.scaling_projection import spec_decode_tokens

        m = spec_decode_tokens(10, 3, acceptance_rate=1.0, n_requests=5)
        # 9 decoded tokens per request (the first comes from prefill):
        # 2 spec iterations of 4, then 1 plain decode — fleet totals x5
        assert m["spec_iterations"] == 10 and m["plain_decodes"] == 5
        assert m["proposed"] == 30 and m["accepted"] == 30
        assert m["target_passes_spec"] == 15 < m["target_passes_plain"] == 45
        assert m["draft_passes"] == 40  # K proposals + 1 backfill, x2 x5
        # free drafts + full acceptance -> ratio = 9/3
        free = spec_decode_tokens(10, 3, acceptance_rate=1.0,
                                  draft_cost=0.0)
        assert free["decode_goodput_ratio"] == pytest.approx(3.0)
        # a draft as expensive as the target can only break even per
        # EXTRA forward: ratio stays below the free-draft bound
        costly = spec_decode_tokens(10, 3, acceptance_rate=1.0,
                                    draft_cost=1.0)
        assert costly["decode_goodput_ratio"] < 3.0
        part = spec_decode_tokens(10, 3, acceptance_rate=0.5)
        assert part["accepted"] < part["proposed"]
        assert part["expected_tokens_per_iteration"] == pytest.approx(
            1 + 0.5 + 0.25 + 0.125)
        with pytest.raises(ValueError):
            spec_decode_tokens(10, 0)


# ------------------------------------------------------------ e2e + bench


@pytest.mark.chaos
def test_e2e_canary_promote_with_caching_and_speculation(hvd, monkeypatch):
    """The ISSUE 18 drill: train on the 8-device mesh → publish G1/G2 →
    the fleet-side rollout canaries G2 on an engine running with BOTH the
    prefix cache and a draft-speculating decode → promotion under live
    traffic, tokens bit-identical to a plain engine on the same weights,
    and the training step's collective schedule byte-identical before and
    after (the hot-path machinery adds no training-side collectives)."""
    from horovod_tpu.analysis.schedule import collective_schedule
    from horovod_tpu.training import (
        make_shardmap_train_step,
        replicate,
        shard_batch,
        token_xent,
    )

    model = _model(depth=2, vocab=64, dim=32, heads=2, max_len=32)
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    tx = optax.adam(1e-2)
    step = make_shardmap_train_step(
        model, tx, loss_fn=token_xent, instrument=False, donate=False)
    rng = np.random.RandomState(0)
    toks = rng.randint(1, 64, size=(16, 9)).astype(np.int32)
    xs, ys = shard_batch(toks[:, :-1]), shard_batch(toks[:, 1:])
    params = replicate(jax.tree_util.tree_map(jnp.array, params0))
    opt_state = tx.init(params)

    server = KVStoreServer()
    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        sub = WeightSubscriber(server, device=True)
        eng = InferenceEngine(model, page_size=8, num_pages=32,
                              max_batch=2, prefill_chunk=8, max_seq_len=24,
                              prefix_cache=True, draft_depth=1,
                              spec_lookahead=3)
        roll = GenerationRollout(eng, sub, canary_fraction=1.0,
                                 min_canary_requests=2,
                                 max_latency_ratio=None)
        fp_before = collective_schedule(
            step, params, {}, opt_state, xs, ys).fingerprint()

        params, _, opt_state, _ = step(params, {}, opt_state, xs, ys)
        assert pub.publish({"params": params}, 1) == 1
        roll.poll()
        assert roll.stable_generation == 1
        params, _, opt_state, _ = step(params, {}, opt_state, xs, ys)
        assert pub.publish({"params": params}, 2) == 2
        roll.poll()
        assert roll.canary_generation == 2

        prompts = _ragged_prompts(5, (9, 14), vocab=64)
        reqs = [roll.submit(f"d-{i}", p, 6)
                for i, p in enumerate(prompts)]
        roll.drain()
        assert all(r.error is None for r in reqs)
        assert roll.stable_generation == 2  # promoted under traffic
        # a SECOND wave hits the canary-generation cache AND speculates;
        # a plain engine on the same weights must emit the same bits
        wave = [roll.submit(f"d2-{i}", p, 6)
                for i, p in enumerate(prompts)]
        roll.drain()
        assert metrics.value("serving_prefix_hits") >= 1
        assert metrics.value("spec_proposed") > 0
        plain = InferenceEngine(model, page_size=8, num_pages=32,
                                max_batch=2, prefill_chunk=8,
                                max_seq_len=24, prefix_cache=False)
        plain.set_weights(eng.arm_params("stable"), generation=2)
        want, _ = _serve(plain, prompts, 6, "ref")
        for r, w in zip(wave, want):
            np.testing.assert_array_equal(np.asarray(r.generated), w)

        fp_after = collective_schedule(
            step, params, {}, opt_state, xs, ys).fingerprint()
        assert fp_after == fp_before
    finally:
        server.close()


@pytest.mark.slow
def test_bench_prefix_ab_rung():
    """bench.py --prefix-ab emits ONE JSON line whose measured prefill
    token deltas match the analytic model EXACTLY."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--prefix-ab"],
        capture_output=True, text=True, env=env, timeout=600, cwd=_REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    d = json.loads(line)
    assert d["metric"] == "prefix_ab_prefill_ratio"
    assert d["parity"] == "token-identical"
    m = d["prefill_model"]
    assert d["measured_prefill_tokens"]["cold"] == m["cold_prefill_tokens"]
    assert d["measured_prefill_tokens"]["cached"] \
        == m["cached_prefill_tokens"]
    assert m["saved_tokens"] > 0


@pytest.mark.slow
def test_bench_spec_ab_rung():
    """bench.py --spec-ab emits ONE JSON line whose proposal/acceptance
    counters match the analytic model EXACTLY (full-depth draft)."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--spec-ab"],
        capture_output=True, text=True, env=env, timeout=600, cwd=_REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    d = json.loads(line)
    assert d["metric"] == "spec_ab_goodput_ratio"
    assert d["parity"] == "token-identical"
    m = d["spec_model"]
    assert d["measured"]["proposed"] == m["proposed"]
    assert d["measured"]["accepted"] == m["accepted"]
    assert m["accepted"] == m["proposed"]  # full-depth draft


def test_hvd_top_serving_pane_shows_hit_and_acceptance_rates():
    import importlib

    hvd_top = importlib.import_module("tools.hvd_top")
    model = _model(depth=1)
    params = _params(model)
    prompts = _ragged_prompts(4, (19, 19))
    eng = _engine(model, params, prefix_cache=True,
                  draft_depth=1, spec_lookahead=3)
    _serve(eng, prompts, 8, "a")
    _serve(eng, prompts, 8, "b")
    lines = hvd_top.serving_pane(
        hvd_top._single_rank_fleet(metrics.snapshot()))
    joined = "\n".join(lines)
    assert "prefix cache: hit rate" in joined
    assert "spec decode: acceptance" in joined
