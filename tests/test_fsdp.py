"""FSDP / ZeRO-3 gather-on-use + tensor-parallel serving (ISSUE 20).

The acceptance pins:

- a 12-step Adam trajectory on the 8-device mesh under
  ``make_shardmap_train_step(shard_params=True)`` is **bit-identical**
  (fp32) to the ZeRO-1 ``shard_optimizer=True`` baseline — the gradient
  leg is the parameter gather's transpose (the same reduce-scattered
  buffers ZeRO-1 sees), the vmapped optimizer island is fusion-fenced,
  and the update shards cross an identity ppermute so the apply add
  rounds exactly like ZeRO-1's post-all-gather add;
- the int8 gather wire (``HOROVOD_FSDP_WIRE=int8``) perturbs only
  forward parameter values — the trajectory stays tolerance-pinned;
- ``tools/scaling_projection.zero3_sync_bytes`` equals the live
  ``grad_sync_bytes_per_step{mode=zero3}`` /
  ``param_gather_bytes_per_step{mode=zero3}`` gauges, both wires;
- an 8 -> 4 -> 8 world-size roundtrip through ``fsdp_reshard_params`` +
  ``reshard_optimizer_state``/``consolidate_opt_state`` is lossless;
- the tp-sharded serving path (``tp_paged_decode_attention``, engine
  ``tp_axis=``) is token-identical to the single-chip engine on ragged
  batches, and ``tp_block_apply`` matches ``TransformerBlock.apply``.
"""

import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.join(_REPO, "tools") not in sys.path:
    sys.path.insert(0, os.path.join(_REPO, "tools"))


@pytest.fixture()
def hvd_tp():
    """2 x 4 ("data", "tp") mesh — the TP-through-serving configuration."""
    import horovod_tpu as hvd

    hvd.init(axes={"data": 2, "tp": 4})
    yield hvd
    hvd.shutdown()


def _mlp():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    return MLP()


def _data(n):
    from horovod_tpu.training import shard_batch

    xs = shard_batch(np.random.RandomState(0).rand(4 * n, 6).astype(np.float32))
    ys = shard_batch(np.random.RandomState(1).randint(0, 4, 4 * n))
    return xs, ys


def _run_zero1(hvd, model, params0, xs, ys, steps=12):
    from horovod_tpu.training import (
        make_shardmap_train_step, replicate, softmax_xent,
    )

    tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
    step = make_shardmap_train_step(
        model, tx, loss_fn=softmax_xent, shard_optimizer=True,
        instrument=False)
    params = replicate(jax.tree_util.tree_map(jnp.array, params0))
    opt_state = tx.init(params)
    stats = {}
    for _ in range(steps):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              xs, ys)
    return params, float(loss)


def _run_zero3(hvd, model, params0, xs, ys, steps=12):
    from horovod_tpu.training import (
        fsdp_shard_params, make_shardmap_train_step, softmax_xent,
    )

    tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_params=True)
    step = make_shardmap_train_step(
        model, tx, loss_fn=softmax_xent, shard_params=True,
        instrument=False)
    fp = hvd.fsdp_pack_params(jax.tree_util.tree_map(jnp.array, params0))
    fp = fsdp_shard_params(fp)
    opt_state = tx.init(fp)
    stats = {}
    for _ in range(steps):
        fp, stats, opt_state, loss = step(fp, stats, opt_state, xs, ys)
    return hvd.fsdp_unpack_params(fp), float(loss), fp, opt_state


def _leaves(tree):
    return sorted(
        jax.tree_util.tree_leaves_with_path(tree),
        key=lambda t: jax.tree_util.keystr(t[0]))


# --------------------------------------------------------- pack / unpack


def test_pack_unpack_roundtrip(hvd):
    params = {
        "a": jnp.asarray(
            np.random.RandomState(0).randn(17, 5).astype(np.float32)),
        "b": {"c": jnp.arange(11, dtype=jnp.bfloat16),
              "d": jnp.asarray(
                  np.random.RandomState(1).randn(33).astype(np.float32))},
    }
    fp = hvd.fsdp_pack_params(params)
    assert fp.num_shards == hvd.size()
    out = hvd.fsdp_unpack_params(fp)
    for (kp, a), (ko, b) in zip(_leaves(params), _leaves(out)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gather_params_matches_tree(hvd):
    params = {"w": jnp.asarray(
        np.random.RandomState(2).randn(37, 3).astype(np.float32))}
    fp = hvd.fsdp_pack_params(params)
    out = hvd.fsdp_gather_params(fp)  # eager/unbound: pure unpack
    np.testing.assert_array_equal(
        np.asarray(out["w"]), np.asarray(params["w"]))


# -------------------------------------------------- trajectory bit-identity


def test_zero3_trajectory_bit_identical_to_zero1(hvd):
    """The headline acceptance: 12 Adam steps, fp32, bitwise equal."""
    model = _mlp()
    from horovod_tpu.training import init_model

    params0, _ = init_model(model, jax.random.PRNGKey(0),
                            jnp.zeros((1, 6), jnp.float32))
    xs, ys = _data(hvd.size())
    p1, l1 = _run_zero1(hvd, model, params0, xs, ys)
    p3, l3, _, _ = _run_zero3(hvd, model, params0, xs, ys)
    assert l1 == l3  # losses exactly equal, not approx
    for (k1, a), (k3, b) in zip(_leaves(p1), _leaves(p3)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"ZeRO-3 diverged from ZeRO-1 at {jax.tree_util.keystr(k1)}")


@pytest.mark.compression
def test_zero3_int8_wire_trajectory_pinned(hvd, monkeypatch):
    """The int8 gather wire quantizes forward parameter values only; the
    12-step trajectory stays within a pinned envelope of the fp32 ZeRO-1
    baseline (measured ~0.035 max abs param drift at lr=1e-2)."""
    model = _mlp()
    from horovod_tpu.training import init_model

    params0, _ = init_model(model, jax.random.PRNGKey(0),
                            jnp.zeros((1, 6), jnp.float32))
    xs, ys = _data(hvd.size())
    p1, l1 = _run_zero1(hvd, model, params0, xs, ys)
    monkeypatch.setenv("HOROVOD_FSDP_WIRE", "int8")
    p8, l8, _, _ = _run_zero3(hvd, model, params0, xs, ys)
    assert l8 == pytest.approx(l1, abs=5e-3)
    for (k1, a), (k8, b) in zip(_leaves(p1), _leaves(p8)):
        assert float(np.abs(np.asarray(a) - np.asarray(b)).max()) < 0.1, (
            jax.tree_util.keystr(k1))


def test_fsdp_wire_env_rejects_unknown(hvd, monkeypatch):
    monkeypatch.setenv("HOROVOD_FSDP_WIRE", "fp8")
    params = {"w": jnp.ones((2048,), jnp.float32)}
    fp = hvd.fsdp_pack_params(params)
    with pytest.raises(ValueError, match="HOROVOD_FSDP_WIRE"):
        hvd.fsdp_gather_params(fp)  # wire resolved (and rejected) from env


# ------------------------------------------------------- byte-model pins


@pytest.mark.parametrize("wire", ["none", "int8"])
def test_zero3_gauges_match_analytic_model(hvd, monkeypatch, wire):
    """zero3_sync_bytes (tools/scaling_projection.py) must equal the live
    gauges _fsdp_update prices — same resolution, zero drift."""
    from scaling_projection import zero3_sync_bytes

    from horovod_tpu.training import init_model

    model = _mlp()
    params0, _ = init_model(model, jax.random.PRNGKey(0),
                            jnp.zeros((1, 6), jnp.float32))
    shapes = [tuple(l.shape) for l in jax.tree_util.tree_leaves(params0)]
    xs, ys = _data(hvd.size())
    if wire == "int8":
        monkeypatch.setenv("HOROVOD_FSDP_WIRE", "int8")
    hvd.metrics.reset()
    hvd.metrics.set_enabled(True)
    _run_zero3(hvd, model, params0, xs, ys, steps=1)
    m = zero3_sync_bytes(shapes, hvd.size(), wire=wire)
    grad = hvd.metrics.value("grad_sync_bytes_per_step", mode="zero3")
    gather = hvd.metrics.value("param_gather_bytes_per_step", mode="zero3")
    assert grad == pytest.approx(m["grad_reduce_scatter"])
    assert gather == pytest.approx(m["param_gather"])
    # the wire knob must not touch the gradient leg
    assert m["grad_reduce_scatter"] == pytest.approx(
        zero3_sync_bytes(shapes, hvd.size(), wire="none")
        ["grad_reduce_scatter"])


def test_zero3_byte_model_properties():
    """fp32 gather wire: ZeRO-3 always loses on pure wire bytes (3 legs vs
    ZeRO-1's 2); the int8 wire brings the gather legs under the fp32
    gradient leg."""
    from scaling_projection import zero3_sync_bytes

    shapes = [(784, 512), (512,), (512, 512), (512,), (512, 10), (10,)]
    f = zero3_sync_bytes(shapes, 8, wire="none")
    assert f["zero3_total"] == pytest.approx(
        f["param_gather"] + f["grad_reduce_scatter"])
    assert f["zero3_total"] > f["zero1_total"]
    assert f["param_gather"] == pytest.approx(2 * f["grad_reduce_scatter"])
    q = zero3_sync_bytes(shapes, 8, wire="int8")
    assert q["param_gather"] < f["param_gather"] / 3  # ~int8/fp32 + scales
    assert q["grad_reduce_scatter"] == f["grad_reduce_scatter"]
    assert q["zero3_total"] < f["zero1_total"]  # int8 wire beats ZeRO-1
    # degenerate single rank: nothing moves
    z = zero3_sync_bytes(shapes, 1)
    assert z["zero3_total"] == z["zero1_total"] == 0.0


# --------------------------------------------------- elastic reshard


def test_reshard_roundtrip_8_4_8(hvd):
    """Param shards and Adam state survive an 8 -> 4 -> 8 world-size
    roundtrip bit-exactly (the ZeRO-3 elastic/restore path)."""
    from horovod_tpu import checkpoint
    from horovod_tpu.training import init_model

    model = _mlp()
    params0, _ = init_model(model, jax.random.PRNGKey(0),
                            jnp.zeros((1, 6), jnp.float32))
    xs, ys = _data(hvd.size())
    _, _, fp8, state8 = _run_zero3(hvd, model, params0, xs, ys, steps=3)

    fp4 = hvd.fsdp_reshard_params(fp8, to_size=4)
    assert fp4.num_shards == 4
    st4 = hvd.reshard_optimizer_state(state8, fp8, to_size=4)
    fp8b = hvd.fsdp_reshard_params(fp4, to_size=8)
    st8b = checkpoint.consolidate_opt_state(st4, fp4, to_size=8)

    for k in fp8.shards:
        np.testing.assert_array_equal(
            np.asarray(fp8.shards[k]), np.asarray(fp8b.shards[k]))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        state8, st8b)
    # and the unpacked trees agree too (shard layout is an implementation
    # detail; the model the shards encode must be unchanged)
    for (_, a), (_, b) in zip(
            _leaves(hvd.fsdp_unpack_params(fp8)),
            _leaves(hvd.fsdp_unpack_params(fp4))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------- rejections


def test_shard_params_rejects_bad_compositions(hvd):
    from horovod_tpu.compression import Compression

    with pytest.raises(ValueError, match="Adasum"):
        hvd.DistributedOptimizer(
            optax.adam(1e-2), shard_params=True, op=hvd.Adasum)
    with pytest.raises(ValueError, match="HOROVOD_FSDP_WIRE"):
        hvd.DistributedOptimizer(
            optax.adam(1e-2), shard_params=True,
            compression=Compression.int8)
    with pytest.raises(ValueError, match="error_feedback"):
        hvd.DistributedOptimizer(
            optax.adam(1e-2), shard_params=True, error_feedback=True)
    with pytest.raises(ValueError, match="predivide"):
        hvd.DistributedOptimizer(
            optax.adam(1e-2), shard_params=True,
            gradient_predivide_factor=2.0)


def test_shard_params_update_rejects_plain_tree(hvd):
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_params=True)
    params = {"w": jnp.ones((64,), jnp.float32)}
    fp = hvd.fsdp_pack_params(params)
    state = tx.init(fp)
    with pytest.raises(TypeError, match="FsdpParams"):
        tx.update({"w": jnp.ones((64,), jnp.float32)}, state, fp)


def test_step_builder_rejects_guarded_zero3(hvd):
    from horovod_tpu.training import make_shardmap_train_step

    tx = hvd.DistributedOptimizer(
        hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True),
        numerics_guard=True)
    with pytest.raises(ValueError, match="numerics_guard"):
        make_shardmap_train_step(_mlp(), tx, shard_params=True)


def test_env_flag_enables_param_sharding(hvd, monkeypatch):
    monkeypatch.setenv("HOROVOD_SHARD_PARAMS", "1")
    tx = hvd.DistributedOptimizer(optax.adam(1e-2))
    params = {"w": jnp.ones((64,), jnp.float32)}
    fp = hvd.fsdp_pack_params(params)
    state = tx.init(fp)  # FsdpParams accepted -> ZeRO-3 layout
    assert state[0].mu["float32"].ndim == 2


# ------------------------------------------- dim-0 sharding observability


def test_indivisible_dim0_leaves_counted(hvd):
    """_shard_dim0_tree leaves non-divisible dim-0 leaves replicated; the
    fsdp_leaves_replicated{reason=indivisible} counter says how many."""
    from horovod_tpu.training import _shard_dim0_tree

    hvd.metrics.reset()
    hvd.metrics.set_enabled(True)
    tree = {
        "ok": jnp.ones((16, 4), jnp.float32),       # divisible -> sharded
        "bad": jnp.ones((9, 8), jnp.float32),       # 9 % 8 != 0
        "scalar": jnp.float32(1.0),                  # rank-0: not counted
    }
    _shard_dim0_tree(tree, None)
    assert hvd.metrics.value(
        "fsdp_leaves_replicated", reason="indivisible") == 1


# ------------------------------------------------------- tensor parallel


class TestTensorParallel:
    def test_tp_block_apply_matches_block(self, hvd_tp):
        """Explicit Megatron-split block == TransformerBlock.apply (the
        GSPMD reference) on the same params, two psums and all."""
        import flax.linen as nn  # noqa: F401

        from horovod_tpu.models.transformer import (
            TransformerBlock, default_attention, tp_block_apply,
        )
        from horovod_tpu.ops.collective import _smap

        dim, heads = 32, 4
        block = TransformerBlock(dim=dim, heads=heads, mlp_ratio=2,
                                 dtype=jnp.float32,
                                 attention_fn=default_attention)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 6, dim).astype(np.float32))
        bp = block.init(jax.random.PRNGKey(1), x)["params"]
        ref = block.apply({"params": bp}, x)

        fn = _smap(
            lambda p, t: tp_block_apply(p, t, heads=heads, axis="tp"),
            hvd_tp.mesh(), (P(), P()), P())
        got = jax.jit(fn)(bp, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-6)

    def test_tp_block_apply_rejects_gqa_and_indivisible(self, hvd_tp):
        from horovod_tpu.models.transformer import tp_block_apply
        from horovod_tpu.ops.collective import _smap

        x = jnp.ones((1, 4, 32), jnp.float32)
        with pytest.raises(ValueError, match="qkv"):
            jax.jit(_smap(
                lambda p, t: tp_block_apply(p, t, heads=4, axis="tp"),
                hvd_tp.mesh(), (P(), P()), P()))({"q_proj": {}}, x)
        bad = {"qkv": {"kernel": jnp.ones((32, 96), jnp.float32)},
               "ln1": {"scale": jnp.ones(32), "bias": jnp.zeros(32)}}
        with pytest.raises(ValueError, match="heads=6"):
            jax.jit(_smap(
                lambda p, t: tp_block_apply(p, t, heads=6, axis="tp"),
                hvd_tp.mesh(), (P(), P()), P()))(bad, x)

    def test_tp_paged_decode_attention_exact(self, hvd_tp):
        """Head-sharded paged decode == the single-chip kernel bitwise —
        heads are embarrassingly parallel (no collectives in the math)."""
        from horovod_tpu.ops.flash_attention import (
            paged_decode_attention, tp_paged_decode_attention,
        )

        rng = np.random.RandomState(0)
        b, h, hkv, d, page = 2, 4, 4, 8, 4
        n_pages, per_seq = 9, 3
        q = jnp.asarray(rng.randn(b, 1, h, d).astype(np.float32))
        k_pages = jnp.asarray(
            rng.randn(n_pages, page, hkv, d).astype(np.float32))
        v_pages = jnp.asarray(
            rng.randn(n_pages, page, hkv, d).astype(np.float32))
        table = jnp.asarray([[5, 2, 7], [1, 8, 3]], jnp.int32)
        start = jnp.asarray([5, 9], jnp.int32)
        ref = paged_decode_attention(q, k_pages, v_pages, table, start,
                                     page_size=page)
        got = tp_paged_decode_attention(q, k_pages, v_pages, table, start,
                                        page_size=page, axis="tp")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_tp_paged_decode_rejects_indivisible_heads(self, hvd_tp):
        from horovod_tpu.ops.flash_attention import (
            tp_paged_decode_attention,
        )

        q = jnp.ones((1, 1, 6, 8), jnp.float32)  # 6 % 4 != 0
        k = jnp.ones((4, 4, 6, 8), jnp.float32)
        v = jnp.ones((4, 4, 6, 8), jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            tp_paged_decode_attention(
                q, k, v, jnp.zeros((1, 2), jnp.int32),
                jnp.zeros((1,), jnp.int32), page_size=4, axis="tp")

    def test_tp_engine_token_identical_ragged(self, hvd_tp):
        """The acceptance pin: the tp-sharded engine (GSPMD params +
        head-sharded page pools + tp paged decode) produces exactly the
        single-chip engine's tokens on a ragged batch."""
        from horovod_tpu.models.transformer import TransformerLM
        from horovod_tpu.observability import metrics
        from horovod_tpu.serving import InferenceEngine

        metrics.reset()
        metrics.set_enabled(True)
        model = TransformerLM(vocab=97, dim=32, depth=2, heads=4,
                              mlp_ratio=2, max_len=64, dtype=jnp.float32)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
        rng = np.random.RandomState(42)
        prompts = [rng.randint(1, 97, size=l).astype(np.int32)
                   for l in (5, 11, 3, 8)]
        max_new = 5

        def run(tp_axis):
            eng = InferenceEngine(
                model, page_size=8, num_pages=40, max_batch=3,
                prefill_chunk=8, max_seq_len=32, tp_axis=tp_axis)
            eng.set_weights(params, generation=1)
            reqs = [eng.submit(p, max_new, rid=f"r{i}")
                    for i, p in enumerate(prompts)]
            eng.run_until_idle()
            assert all(r.error is None for r in reqs)
            return [np.asarray(r.generated) for r in reqs]

        plain = run(None)
        tp = run("tp")
        for a, b in zip(plain, tp):
            np.testing.assert_array_equal(a, b)

    def test_tp_engine_rejects_bad_axis_or_heads(self, hvd_tp):
        from horovod_tpu.models.transformer import TransformerLM
        from horovod_tpu.serving import InferenceEngine

        model = TransformerLM(vocab=97, dim=32, depth=1, heads=4,
                              mlp_ratio=2, max_len=64, dtype=jnp.float32)
        with pytest.raises(ValueError, match="not an axis"):
            InferenceEngine(model, page_size=8, num_pages=16, max_batch=1,
                            max_seq_len=32, tp_axis="model")
        gqa = TransformerLM(vocab=97, dim=32, depth=1, heads=4, kv_heads=2,
                            mlp_ratio=2, max_len=64, dtype=jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            InferenceEngine(gqa, page_size=8, num_pages=16, max_batch=1,
                            max_seq_len=32, tp_axis="tp")
