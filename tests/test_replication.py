"""Control-plane HA (ISSUE 19): replicated rendezvous KV with WAL
shipping, fenced failover, and client auto-reconnect.

The acceptance pin (:class:`TestFailoverDrill`): guarded training-style
weight publication + a fleet rollout decision log under
``HOROVOD_CHAOS=kv_kill_primary_at_step=3`` — the primary is
SIGKILL-modeled mid-drill, a warm standby is promoted within the client
retry deadline, no generation is lost or replayed, the publication head
and rollout log on the promoted standby are byte-identical to the dead
primary's WAL state, and the deposed primary restarted afterwards gets
HTTP 409 (fencing epoch pinned) instead of silently applying late
writes. Tier-1: everything local, leases <= 0.5 s, no sleeps > 0.3 s.
"""

import json
import os
import socket
import time

import numpy as np
import pytest

from horovod_tpu.observability import metrics
from horovod_tpu.resilience import chaos, health
from horovod_tpu.resilience.retry import RetryPolicy
from horovod_tpu.run import replication
from horovod_tpu.run.rendezvous import (
    FencedError,
    KVStoreClient,
    KVStoreServer,
    format_endpoints,
    parse_endpoints,
)
from horovod_tpu.serving import WeightPublisher, WeightSubscriber

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LOCAL = "127.0.0.1"


@pytest.fixture(autouse=True)
def _fresh():
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    yield
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.reset()


def _free_dead_port() -> int:
    """A port with nothing listening (bind, note, close)."""
    s = socket.socket()
    s.bind((LOCAL, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _pair(tmp_path, quorum=1):
    """primary + one warm standby wired with a sync replicator."""
    primary = KVStoreServer(wal_path=str(tmp_path / "primary.wal"))
    primary.start()
    standby = KVStoreServer(
        wal_path=str(tmp_path / "standby.wal"), role="standby")
    standby.start()
    sender = replication.ReplicationSender(
        [(LOCAL, standby.port)], quorum=quorum, timeout=2.0,
        primary_hint=f"{LOCAL}:{primary.port}")
    primary.attach_replicator(sender)
    return primary, standby, sender


def _policy(**kw):
    base = dict(scope="kv", max_attempts=10, base_delay=0.1,
                max_delay=0.4, multiplier=2.0, jitter=0.0, deadline=30.0)
    base.update(kw)
    return RetryPolicy(**base)


class TestReplicationStream:
    def test_wal_stream_ships_to_standby(self, tmp_path):
        """Every primary mutation (put/ttl/delete) arrives on the standby
        synchronously — append-before-ack to quorum 1 — and lands in the
        standby's own shipped WAL file."""
        primary, standby, sender = _pair(tmp_path)
        try:
            primary.put("/a", b"1")
            primary.put("/b", b"2", ttl=30.0)
            primary.put("/c", b"3")
            primary.delete("/c")
            assert standby.get("/a") == b"1"
            assert standby.get("/b") == b"2"
            assert standby.get("/c") is None
            assert sender.lag() == 0
            assert standby.applied_seq == sender.seq
            # the stream is durable on the standby side too
            shipped = (tmp_path / "standby.wal").read_bytes()
            assert b'"/a"' in shipped and b'"del"' in shipped
            assert metrics.value("rendezvous_replication_lag_entries") == 0.0
        finally:
            sender.close()
            standby.close()
            primary.close()

    def test_snapshot_bootstrap_for_late_joiner(self, tmp_path):
        """A standby joining after the primary has state receives the
        whole canonical state in one snapshot batch, then rides the
        incremental stream."""
        primary = KVStoreServer(wal_path=str(tmp_path / "p.wal"))
        primary.start()
        primary.put("/warm/a", b"A")
        primary.put("/warm/b", b"B")
        standby = KVStoreServer(
            wal_path=str(tmp_path / "s.wal"), role="standby")
        standby.start()
        sender = replication.ReplicationSender(
            [(LOCAL, standby.port)], quorum=1, timeout=2.0)
        try:
            sender.bootstrap(primary.state_records())
            assert standby.get("/warm/a") == b"A"
            assert standby.get("/warm/b") == b"B"
            primary.attach_replicator(sender)
            primary.put("/after", b"C")
            assert standby.get("/after") == b"C"
            assert standby.state_digest() == primary.state_digest()
        finally:
            sender.close()
            standby.close()
            primary.close()

    def test_duplicate_or_reordered_shipment_dropped(self):
        """At-least-once delivery guard: an append whose sequence number
        is at or behind the standby's applied position is dropped
        idempotently — a reordered late record must never regress a
        last-write-wins key to a stale value."""
        standby = KVStoreServer(role="standby")
        try:
            code, _ = standby.apply_replicated(
                b'{"op":"put","k":"/k","v":"bmV3"}\n', seq=2)  # "new"
            assert code == 200 and standby.applied_seq == 2
            code, _ = standby.apply_replicated(
                b'{"op":"put","k":"/k","v":"b2xk"}\n', seq=1)  # "old"
            assert code == 200  # acked, but not applied
            assert standby.get("/k") == b"new"
            assert standby.applied_seq == 2
        finally:
            standby.close()

    def test_shared_wal_standby_never_writes_live_log(self, tmp_path):
        """A standby pointed at the primary's OWN WAL path (shared
        filesystem) must not truncate or interleave into the live log the
        primary still appends to: the shipped stream stays in memory and
        the primary's WAL remains the durable copy, replayed verbatim at
        promotion."""
        wal = str(tmp_path / "shared.wal")
        primary = KVStoreServer(wal_path=wal)
        primary.start()
        primary.put("/pre", b"1")
        standby = KVStoreServer(wal_path=wal, role="standby")
        standby.start()
        sender = replication.ReplicationSender(
            [(LOCAL, standby.port)], quorum=1, timeout=2.0)
        try:
            primary.attach_replicator(sender)
            primary.put("/post", b"2")
            # the stream arrived in memory...
            assert standby.get("/pre") == b"1"
            assert standby.get("/post") == b"2"
            # ...but the live WAL was written by the primary alone: every
            # line is intact JSON (no snapshot truncation, no interleave)
            with open(wal, "rb") as f:
                for line in f:
                    json.loads(line)
            pre_state = primary.state_records()
            primary.kill()
            res = replication.promote(standby, reason="shared-fs drill")
            assert res.state == pre_state  # replayed from the owner's WAL
        finally:
            sender.close()
            standby.close()
            primary.close()

    def test_lag_counts_unreachable_standby(self):
        """A standby that cannot be reached is detached, not a wedge for
        the primary — and it shows up as an ever-growing
        ``rendezvous_replication_lag_entries`` (a detached standby is an
        infinitely lagging one)."""
        dead = _free_dead_port()
        sender = replication.ReplicationSender(
            [(LOCAL, dead)], quorum=1, timeout=0.3)
        try:
            for i in range(3):
                sender.ship(b'{"op":"put","k":"/x","v":""}\n')
            assert sender.lag() == 3
            assert metrics.value(
                "rendezvous_replication_lag_entries") == 3.0
        finally:
            sender.close()


class TestFencing:
    def test_deposed_primary_rejects_writes_409(self):
        """The tentpole's core safety rule: a server shown a newer
        fencing epoch deposes itself and 409s every later mutation — a
        deposed primary's late writes are NEVER silently applied."""
        s = KVStoreServer()
        s.start()
        client = KVStoreClient(LOCAL, s.port, retry_policy=_policy())
        try:
            client.put("/pre", b"ok")  # epoch 0: accepted
            client.note_epoch(3)  # a promotion elsewhere, learned out of band
            with pytest.raises(FencedError) as exc:
                client.put("/late", b"stale write")
            assert exc.value.epoch >= 3
            assert s.role == "deposed"
            assert s.get("/late") is None  # not applied
            assert s.get("/pre") == b"ok"  # reads keep serving
            # deletes are fenced through the same gate
            with pytest.raises(FencedError):
                client.delete("/pre")
            assert s.get("/pre") == b"ok"
        finally:
            s.close()

    def test_replication_stream_fenced(self):
        """A deposed primary cannot ship stale records either: a batch
        whose epoch is behind the receiver's is rejected 409, and a
        primary receiving a replication batch with a higher epoch
        deposes itself."""
        standby = KVStoreServer(role="standby")
        rec = b'{"op":"put","k":"/r","v":"","fe":2}\n'
        code, _ = standby.apply_replicated(rec, epoch=2, seq=1)
        assert code == 200 and standby.fencing_epoch == 2
        code, body = standby.apply_replicated(
            b'{"op":"put","k":"/old","v":""}\n', epoch=1, seq=2)
        assert code == 409 and b"replication fenced" in body
        assert standby.get("/old") is None

        primary = KVStoreServer()
        code, _ = primary.apply_replicated(rec, epoch=2, seq=1)
        assert code == 409
        assert primary.role == "deposed"  # evidence of a lost election
        standby.close()
        primary.close()

    def test_primary_deposed_when_standby_fences_stream(self):
        """A standby answering the replication stream with 409 is proof a
        newer regime exists: the shipping primary deposes itself on the
        spot, so clients still pointed at it get 409 on their next write
        instead of HTTP 200 for commits the new regime never sees."""
        primary = KVStoreServer()
        primary.start()
        standby = KVStoreServer(role="standby")
        standby.start()
        sender = replication.ReplicationSender(
            [(LOCAL, standby.port)], quorum=1, timeout=2.0)
        try:
            primary.attach_replicator(sender)
            # the standby adopts a newer regime out of band (a promotion
            # this primary never observed)
            standby.apply_replicated(b"", epoch=5, seq=0)
            primary.put("/x", b"1")  # shipped -> fenced 409 -> deposed
            assert sender.fenced and sender.fenced_epoch == 5
            assert primary.role == "deposed"
            client = KVStoreClient(
                LOCAL, primary.port, retry_policy=_policy())
            with pytest.raises(FencedError):
                client.put("/y", b"2")
            assert primary.get("/y") is None
        finally:
            sender.close()
            standby.close()
            primary.close()

    def test_standby_redirects_writes_to_primary(self, tmp_path):
        """A client pointed at a standby has its writes 307-redirected to
        the ``X-Hvd-Primary`` hint; the mutation lands on the primary and
        replicates back to the standby."""
        primary, standby, sender = _pair(tmp_path)
        client = KVStoreClient(LOCAL, standby.port, retry_policy=_policy())
        try:
            client.put("/via/standby", b"routed")
            assert primary.get("/via/standby") == b"routed"
            assert standby.get("/via/standby") == b"routed"
            # the client now knows the primary's address
            assert (LOCAL, primary.port) in client.endpoints
        finally:
            sender.close()
            standby.close()
            primary.close()


class TestWalLockAndPromotion:
    def test_standby_reads_shared_wal_without_stealing_lock(self, tmp_path):
        """Satellite: a standby pointed at a primary's WAL path replays
        it read-only WITHOUT taking the ``.lock`` — and its promotion
        attempt while the primary lives fails atomically, naming the
        holder's role and fencing epoch from the lock-file stamp."""
        wal = str(tmp_path / "shared.wal")
        primary = KVStoreServer(wal_path=wal)
        primary.put("/k", b"v")
        standby = KVStoreServer(wal_path=wal, role="standby")
        assert standby.get("/k") == b"v"  # replayed, read-only
        with pytest.raises(RuntimeError) as exc:
            standby.promote()
        assert "locked by another live KVStoreServer" in str(exc.value)
        assert "role=primary" in str(exc.value)
        assert primary.role == "primary"  # untouched

        # primary gone -> promotion acquires the lock atomically, bumps
        # the epoch, and re-stamps the lock file with the new regime
        primary.close()
        assert standby.promote() == 1
        assert standby.role == "primary"
        stamp = (tmp_path / "shared.wal.lock").read_text()
        assert "role=primary" in stamp and "fe=1" in stamp
        standby.close()

    def test_promotion_without_wal_keeps_replicated_state(self):
        """The runner wires local standbys WITHOUT a wal_path: promotion
        must come up from the replicated in-memory state (TTL leases
        re-armed like a replay), not wipe it — a promoted WAL-less
        standby that comes up empty is total coordination-state loss."""
        primary = KVStoreServer()
        primary.start()
        standby = KVStoreServer(role="standby")
        standby.start()
        sender = replication.ReplicationSender(
            [(LOCAL, standby.port)], quorum=1, timeout=2.0)
        try:
            primary.attach_replicator(sender)
            primary.put("/lease", b"alive", ttl=30.0)
            primary.put("/plain", b"x")
            pre = primary.state_records()
            primary.kill()
            res = replication.promote(standby, reason="wal-less")
            assert res.epoch == 1
            assert res.state == pre  # zero lost commits, no WAL involved
            assert standby.role == "primary"
            assert standby.get("/lease") == b"alive"  # TTL re-armed
            assert standby.get("/plain") == b"x"
        finally:
            sender.close()
            standby.close()
            primary.close()

    def test_promotion_restores_epoch_from_wal_and_rearms_ttl(self, tmp_path):
        """Promotion replays the shipped WAL like a restart: TTL leases
        are re-armed (not expired by elapsed wall time) and the fencing
        epoch marker survives a later re-open of the WAL."""
        primary, standby, sender = _pair(tmp_path)
        try:
            primary.put("/lease", b"alive", ttl=30.0)
            primary.put("/plain", b"x")
            pre = primary.state_records()
            primary.kill()
            res = replication.promote(standby, reason="test")
            assert res.epoch == 1
            assert res.state == pre  # zero lost commits, byte-identical
            assert standby.get("/lease") == b"alive"  # TTL re-armed
            assert metrics.value("rendezvous_failovers") == 1.0
        finally:
            sender.close()
            standby.close()
            primary.close()
        # a fresh server on the promoted standby's WAL restores epoch 1
        reopened = KVStoreServer(wal_path=str(tmp_path / "standby.wal"))
        assert reopened.fencing_epoch == 1
        reopened.close()


class TestClientFailover:
    def test_wait_for_deadline_survives_failover(self, tmp_path):
        """Satellite: an endpoint failover mid-``wait_for`` rotates to
        the next server but charges the reconnect against the ORIGINAL
        total deadline — never resets it."""
        dead = _free_dead_port()
        primary = KVStoreServer()
        primary.start()
        primary.put("/present", b"here")
        client = KVStoreClient(
            endpoints=[(LOCAL, dead), (LOCAL, primary.port)],
            retry_policy=_policy())
        try:
            # dead-first list: the wait rotates and still finds the key
            assert client.wait_for("/present", timeout=5.0) == b"here"
            assert client.failovers >= 1

            # every endpoint dead: the TOTAL deadline governs — elapsed
            # stays ~timeout even though each poll hit a refused connection
            c2 = KVStoreClient(
                endpoints=[(LOCAL, dead), (LOCAL, _free_dead_port())],
                retry_policy=_policy())
            t0 = time.monotonic()
            with pytest.raises(TimeoutError) as exc:
                c2.wait_for("/never", timeout=0.8, interval=0.05)
            elapsed = time.monotonic() - t0
            assert 0.7 <= elapsed < 2.5, elapsed
            assert "endpoints" in str(exc.value)
        finally:
            primary.close()

    def test_reads_fail_over_writes_resume_after_promotion(self, tmp_path):
        """Kill the primary: reads immediately fail over to the standby's
        replicated copy; once the standby is promoted, writes resume
        there and the client pins the new fencing epoch."""
        primary, standby, sender = _pair(tmp_path)
        client = KVStoreClient(
            endpoints=[(LOCAL, primary.port), (LOCAL, standby.port)],
            retry_policy=_policy())
        try:
            client.put("/before", b"1")
            primary.kill()
            assert client.get("/before") == b"1"  # standby serves reads
            assert client.failovers >= 1
            replication.promote(standby)
            client.put("/after", b"2")
            assert standby.get("/after") == b"2"
            assert client.fencing_epoch_seen == 1
        finally:
            sender.close()
            standby.close()
            primary.close()

    def test_kv_partition_chaos_forces_rotation(self, tmp_path):
        """``kv_partition=<s>`` blackholes the first-listed endpoint: a
        multi-endpoint client rides out the window on the standby, and
        the injection is counted."""
        primary, standby, sender = _pair(tmp_path)
        client = KVStoreClient(
            endpoints=[(LOCAL, primary.port), (LOCAL, standby.port)],
            retry_policy=_policy())
        try:
            client.put("/part", b"x")
            chaos.configure("kv_partition=0.15")
            assert client.get("/part") == b"x"  # served by the standby
            assert client.failovers >= 1
            assert metrics.value(
                "resilience_chaos_injected", site="kv_partition") >= 1.0
            time.sleep(0.2)
            assert not chaos.kv_partition_active()  # window self-cleared
        finally:
            chaos.configure(None)
            sender.close()
            standby.close()
            primary.close()


class TestChaosCharges:
    def test_kv_kill_primary_parse_and_consume(self):
        chaos.configure("kv_kill_primary_at_step=3,kv_partition=0.5")
        assert not chaos.take_kv_kill_primary(2)
        assert chaos.take_kv_kill_primary(3)
        assert not chaos.take_kv_kill_primary(3)  # fires once, consumed
        assert metrics.value(
            "resilience_chaos_injected",
            site="kv_kill_primary_at_step") == 1.0
        chaos.configure(None)
        assert not chaos.kv_partition_active()

    def test_publisher_kill_needs_killable_target(self):
        """The chaos contract is 'typos raise, not silently inject
        nothing': arming the kill against a publisher whose store (and
        chaos_primary) cannot be killed fails loudly."""

        class DictStore:
            def __init__(self):
                self.d = {}

            def put(self, k, v, ttl=None):
                self.d[k] = v

            def get(self, k):
                return self.d.get(k)

            def delete(self, k, tombstone=False):
                return self.d.pop(k, None) is not None

        pub = WeightPublisher(DictStore(), register=False)
        chaos.configure("kv_kill_primary_at_step=1")
        with pytest.raises(RuntimeError, match="chaos_primary"):
            pub.publish({"params": {"w": np.zeros(4, np.float32)}}, 1)
        chaos.configure(None)


def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTools:
    def _gauge(self, v):
        return {"type": "gauge", "help": "", "samples": {"": {
            "ranks": {"0": v}, "min": v, "mean": v, "max": v, "p99": v}}}

    def test_hvd_top_control_plane_pane(self):
        top = _load_tool("hvd_top")
        fleet = {
            "ranks": [0], "dead_ranks": [], "straggler": None,
            "metrics": {
                "rendezvous_role": self._gauge(0),
                "rendezvous_fencing_epoch": self._gauge(2),
                "rendezvous_replication_lag_entries": self._gauge(5),
                "rendezvous_failovers": self._gauge(2),
                "rendezvous_wal_records": self._gauge(41),
            },
        }
        out = top.render(fleet)
        assert "CONTROL PLANE:" in out
        assert "kv primary" in out
        assert "fencing epoch 2" in out
        assert "replication lag 5 entries" in out and "LAGGING" in out
        assert "failovers 2" in out
        assert "wal records 41" in out
        # deposed role carries its own warning line
        fleet["metrics"]["rendezvous_role"] = self._gauge(2)
        out = top.render(fleet)
        assert "kv deposed" in out and "DEPOSED" in out
        # no rendezvous series -> no pane
        assert "CONTROL PLANE:" not in top.render(
            {"ranks": [0], "dead_ranks": [], "straggler": None,
             "metrics": {"train_steps": self._gauge(3)}})

    def test_blackbox_annotates_hang_spanning_failover(self):
        bb = _load_tool("hvd_blackbox")
        rank_events = {
            0: [
                {"t": 1.0, "kind": "collective", "ph": "B",
                 "op": "allreduce", "step": 3, "gen": 0, "seq": 0},
                {"t": 2.5, "kind": "failover", "epoch": 1,
                 "reason": "primary lease expired"},
            ],
            1: [
                {"t": 1.1, "kind": "collective", "ph": "B",
                 "op": "allreduce", "step": 3, "gen": 0, "seq": 0},
            ],
        }
        note = bb.failover_annotation(
            rank_events, {"verdict": "rank_missing"})
        assert "control-plane loss" in note
        assert "epoch -> 1" in note and "lease expired" in note
        # a healthy verdict is not annotated
        assert bb.failover_annotation(
            rank_events, {"verdict": "progress"}) == ""
        # a hang with no failover in the record stays a peer-rank hang
        no_fo = {0: [rank_events[0][0]], 1: rank_events[1]}
        assert bb.failover_annotation(
            no_fo, {"verdict": "rank_missing"}) == ""
        # a failover long BEFORE the ranks' last progress is not blamed
        early = {
            0: [{"t": 0.5, "kind": "failover", "epoch": 1},
                rank_events[0][0]],
            1: rank_events[1],
        }
        assert bb.failover_annotation(
            early, {"verdict": "rank_missing"}) == ""


def _tree(seed=0):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(256).astype(np.float32)},
            "bias": rng.randn(7).astype(np.float32)}


def _drift(tree, seed, scale=0.01):
    rng = np.random.RandomState(1000 + seed)
    return {
        "dense": {"kernel": tree["dense"]["kernel"]
                  + scale * rng.randn(256).astype(np.float32)},
        "bias": tree["bias"] + scale * rng.randn(7).astype(np.float32),
    }


class TestFailoverDrill:
    def test_kill_primary_mid_publication_drill(self, tmp_path):
        """THE acceptance drill: weight publication + fleet rollout log
        under ``kv_kill_primary_at_step=3``. The standby is promoted by
        the lease monitor within the client retry deadline, the delta
        chain continues with no generation lost or replayed, the dead
        primary's WAL state is byte-for-byte present on the promoted
        standby, and the deposed primary restarted afterwards is fenced
        with 409."""
        primary, standby, sender = _pair(tmp_path)
        monitor = replication.FailoverMonitor(
            standby, (LOCAL, primary.port), lease=0.4, poll=0.1)
        monitor.start()
        client = KVStoreClient(
            endpoints=[(LOCAL, primary.port), (LOCAL, standby.port)],
            retry_policy=_policy())
        pub = WeightPublisher(client, keyframe_every=100, register=False)
        pub.chaos_primary = primary  # the drill's kill target

        t = _tree(0)
        try:
            # phase 1: two generations + a rollout decision, all acked
            # through the replication quorum
            client.put("/fleet/rollout/log/0001",
                       b"gen 1 promoted: canary clean", ttl=None)
            pub.publish({"params": t}, 1)
            t = _drift(t, 1)
            pub.publish({"params": t}, 2)
            pre_state = primary.state_records()

            # phase 2: the kill fires inside publish(step 3); the client
            # rides its retry policy while the lease expires and the
            # monitor promotes the standby
            chaos.configure("kv_kill_primary_at_step=3")
            t = _drift(t, 2)
            pub.publish({"params": t}, 3)
            assert metrics.value(
                "resilience_chaos_injected",
                site="kv_kill_primary_at_step") == 1.0
            assert standby.role == "primary"
            assert standby.fencing_epoch == 1
            assert monitor.result is not None
            assert metrics.value("rendezvous_failovers") == 1.0

            # phase 3: the chain continues on the promoted standby — no
            # re-root, so no generation was lost or replayed
            for step in (4, 5):
                t = _drift(t, step)
                pub.publish({"params": t}, step)
            assert pub.generation == 5
            assert pub.keyframe_generation == 1  # the chain never broke

            # zero lost commits: replay the DEAD primary's leftover WAL
            # (read-only, no lock steal) — it equals the pre-kill state,
            # and every one of its records is present byte-identically on
            # the promoted standby
            dead = KVStoreServer(
                wal_path=str(tmp_path / "primary.wal"), role="standby")
            dead_state = dead.state_records()
            dead.close()
            assert dead_state == pre_state
            # the commit-last head is byte-identical AT promotion (the
            # promoted regime took over exactly the dead primary's head);
            # it then legitimately advances as the chain continues
            promoted_at_takeover = set(monitor.result.state.splitlines())
            head_lines = [line for line in dead_state.splitlines()
                          if b'"/serving/head"' in line]
            assert head_lines and head_lines[0] in promoted_at_takeover
            # every other pre-kill record survives verbatim to the end
            promoted_lines = set(standby.state_records().splitlines())
            for line in dead_state.splitlines():
                if b'"/serving/head"' in line:
                    continue
                assert line in promoted_lines, line
            assert standby.get("/fleet/rollout/log/0001") == \
                b"gen 1 promoted: canary clean"

            # a subscriber reconstructs the post-failover weights exactly
            sub = WeightSubscriber(client)
            out = sub.poll()
            assert out is not None and sub.generation == 5
            np.testing.assert_allclose(
                out["dense"]["kernel"], t["dense"]["kernel"], atol=2e-4)

            # phase 4: the deposed primary comes back on its old WAL —
            # a client that saw the new regime fences its write with 409;
            # nothing is silently applied
            old = KVStoreServer(wal_path=str(tmp_path / "primary.wal"))
            old.start()
            fenced = KVStoreClient(
                LOCAL, old.port, retry_policy=_policy())
            fenced.note_epoch(monitor.result.epoch)
            with pytest.raises(FencedError) as exc:
                fenced.put("/late/write", b"from the old regime")
            assert exc.value.epoch >= 1
            assert old.role == "deposed"
            assert old.get("/late/write") is None
            old.close()
        finally:
            chaos.configure(None)
            monitor.stop()
            sender.close()
            standby.close()
            primary.close()

    def test_failover_flight_event_recorded(self, tmp_path):
        """The promotion writes a FAILOVER flight event (the offline
        forensics anchor hvd_blackbox keys on)."""
        from horovod_tpu.observability import flight

        flight.configure(on=True, dir=str(tmp_path))
        try:
            primary, standby, sender = _pair(tmp_path)
            primary.put("/k", b"v")
            primary.kill()
            replication.promote(standby, reason="drill")
            path = flight.flush()
            sender.close()
            standby.close()
            primary.close()
            events = [json.loads(line)
                      for line in open(path) if line.strip()]
            fo = [e for e in events if e.get("kind") == "failover"]
            assert fo and fo[-1]["epoch"] == 1
            assert fo[-1]["reason"] == "drill"
            assert fo[-1]["keys"] == 1
        finally:
            flight.configure(on=False, dir="")
