"""Test harness: force an 8-device virtual CPU mesh so collective semantics are
exercised without TPU hardware — the analog of the reference running every test
file under a 2-process localhost launcher (SURVEY.md §4,
``.buildkite/gen-pipeline.sh:124,232``). Must run before jax is imported."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Scrub sitecustomize TPU-plugin hooks (e.g. /root/.axon_site) from
# PYTHONPATH *once, here*: every subprocess-spawning test copies os.environ,
# and a child that inherits the hook can wedge in the plugin's backend init
# even under JAX_PLATFORMS=cpu when the TPU tunnel is unhealthy. The pytest
# process itself already started with the hook in sys.path; the in-process
# CPU pin below keeps it inert here. (Inlined from
# horovod_tpu.run.env_util.scrub_plugin_hooks to run before any package
# import.)
_pp = os.pathsep.join(
    p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if p and ".axon_site" not in p
)
if _pp:
    os.environ["PYTHONPATH"] = _pp
else:
    os.environ.pop("PYTHONPATH", None)

# Repo root on sys.path: tests import from examples/ (e.g. the Adasum
# steps-to-threshold helper), which a bare ``pytest`` invocation does not
# provide (only ``python -m pytest`` from the root does).
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# jax may already be imported by site customization; force the platform via
# config as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import subprocess  # noqa: E402
import pathlib  # noqa: E402

import pytest  # noqa: E402

_CSRC = pathlib.Path(__file__).resolve().parents[1] / "csrc"
if not (_CSRC / "libhvd_core.so").exists():
    subprocess.run(["make", "-C", str(_CSRC)], check=True)


def pytest_configure(config):
    # Tier-1 brushes the 870 s verify timeout, so every run reports its
    # slowest tests: regressions in runtime are visible in the log the
    # moment they land, not when the suite first times out. An explicit
    # --durations on the command line wins.
    if getattr(config.option, "durations", None) is None:
        config.option.durations = 15
        config.option.durations_min = 5.0


@pytest.fixture()
def hvd():
    import horovod_tpu as hvd

    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture()
def mesh8(hvd):
    return hvd.mesh()
