"""Test harness: force an 8-device virtual CPU mesh so collective semantics are
exercised without TPU hardware — the analog of the reference running every test
file under a 2-process localhost launcher (SURVEY.md §4,
``.buildkite/gen-pipeline.sh:124,232``). Must run before jax is imported."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# jax may already be imported by site customization; force the platform via
# config as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import subprocess  # noqa: E402
import pathlib  # noqa: E402

import pytest  # noqa: E402

_CSRC = pathlib.Path(__file__).resolve().parents[1] / "csrc"
if not (_CSRC / "libhvd_core.so").exists():
    subprocess.run(["make", "-C", str(_CSRC)], check=True)


@pytest.fixture()
def hvd():
    import horovod_tpu as hvd

    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture()
def mesh8(hvd):
    return hvd.mesh()
