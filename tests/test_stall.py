"""Stall-detection failure-mode test — analog of reference
``test/test_stall.py`` (rank>0 withholds a tensor; the coordinator must warn
within ``HOROVOD_STALL_CHECK_TIME_SECONDS``, listing the missing ranks)."""

import os
import socket
import subprocess
import sys
import textwrap

WORKER = textwrap.dedent(
    """
    import logging, os, sys, time
    logging.basicConfig(level=logging.DEBUG, stream=sys.stderr)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE

    rank = int(sys.argv[1])
    port = int(sys.argv[2])
    os.environ["HOROVOD_CYCLE_TIME"] = "2"
    os.environ["HOROVOD_STALL_CHECK_TIME_SECONDS"] = "1"
    hvd.init()
    core = NativeCore(rank=rank, size=2, coordinator_host="127.0.0.1",
                      coordinator_port=port)
    x = np.ones((4,), np.float32)

    # both ranks agree on 'warm'; only rank 0 submits 'missing'
    h = core.enqueue("warm", x, REQUEST_ALLREDUCE, op=1)
    h.wait(timeout=20)
    if rank == 0:
        hm = core.enqueue("missing", x, REQUEST_ALLREDUCE, op=1)
        time.sleep(3.5)   # > stall warning interval; rank 1 never joins in
        print("RANK0-WAITED", flush=True)
    else:
        time.sleep(3.5)
        hm = core.enqueue("missing", x, REQUEST_ALLREDUCE, op=1)
    hm.wait(timeout=20)
    print(f"rank{rank}: recovered after stall", flush=True)
    core.shutdown()
    """
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_stall_warning_and_recovery(tmp_path):
    script = tmp_path / "stall_worker.py"
    script.write_text(WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", str(script), str(r), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for r in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    # the coordinator (rank 0) must have warned, naming the missing rank,
    # and the job must still complete once rank 1 catches up
    assert "Stalled collective" in outs[0], outs[0]
    assert "missing" in outs[0]
    assert "missing ranks: 1" in outs[0], outs[0]
    for r, out in enumerate(outs):
        assert f"rank{r}: recovered after stall" in out, out
    assert all(p.returncode == 0 for p in procs), outs


DEATH_WORKER = textwrap.dedent(
    """
    import logging, os, sys, time
    logging.basicConfig(level=logging.DEBUG, stream=sys.stderr)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE

    rank = int(sys.argv[1])
    port = int(sys.argv[2])
    os.environ["HOROVOD_CYCLE_TIME"] = "2"
    os.environ["HOROVOD_STALL_CHECK_TIME_SECONDS"] = "1"
    os.environ["HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"] = "3"
    hvd.init()
    core = NativeCore(rank=rank, size=2, coordinator_host="127.0.0.1",
                      coordinator_port=port)
    x = np.ones((4,), np.float32)
    h = core.enqueue("warm", x, REQUEST_ALLREDUCE, op=1)
    h.wait(timeout=20)
    if rank == 1:
        os._exit(7)  # die abruptly mid-job: no shutdown, no socket close
    hm = core.enqueue("orphan", x, REQUEST_ALLREDUCE, op=1)
    try:
        # timeout far above the 3s stall-shutdown setting but a client-side
        # TimeoutError must FAIL the test: only the core's own abort
        # (RuntimeError from the shutdown error response) counts. 45s of
        # headroom: under full-suite machine load the abort has been
        # observed to take >20s to propagate, which is slow, not broken.
        hm.wait(timeout=45)
        print("RANK0-UNEXPECTED-COMPLETION", flush=True)
    except TimeoutError as e:
        # still a test failure (no RANK0-ABORTED line) but diagnosable
        print(f"RANK0-CLIENT-TIMEOUT: {e}", flush=True)
    except RuntimeError as e:
        print(f"RANK0-ABORTED: {type(e).__name__}: {e}", flush=True)
    core.shutdown()
    print("rank0: exited cleanly", flush=True)
    """
)


def test_worker_death_aborts_survivor(tmp_path):
    """Abrupt peer death mid-job (reference failure semantics, SURVEY §5.3):
    the survivor's pending collective must ABORT via the stall-shutdown
    path — never hang until an external timeout kills the job."""
    script = tmp_path / "death_worker.py"
    script.write_text(DEATH_WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", str(script), str(r), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for r in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    assert procs[1].returncode == 7  # the deliberate death
    assert "RANK0-ABORTED" in outs[0], outs[0]
    assert "rank0: exited cleanly" in outs[0], outs[0]
    assert procs[0].returncode == 0, outs[0]


COORD_DEATH_WORKER = textwrap.dedent(
    """
    import logging, os, sys, time
    logging.basicConfig(level=logging.DEBUG, stream=sys.stderr)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE

    rank = int(sys.argv[1])
    port = int(sys.argv[2])
    os.environ["HOROVOD_CYCLE_TIME"] = "2"
    # stall shutdown deliberately FAR above the pass deadline: the abort must
    # come from closed-socket detection, not the stall timeout
    os.environ["HOROVOD_STALL_CHECK_TIME_SECONDS"] = "30"
    os.environ["HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"] = "120"
    hvd.init()
    core = NativeCore(rank=rank, size=2, coordinator_host="127.0.0.1",
                      coordinator_port=port)
    x = np.ones((4,), np.float32)
    h = core.enqueue("warm", x, REQUEST_ALLREDUCE, op=1)
    h.wait(timeout=20)
    if rank == 0:
        os._exit(7)  # coordinator dies abruptly: no shutdown, no goodbye
    t0 = time.monotonic()
    hm = core.enqueue("orphan", x, REQUEST_ALLREDUCE, op=1)
    try:
        hm.wait(timeout=45)
        print("RANK1-UNEXPECTED-COMPLETION", flush=True)
    except TimeoutError as e:
        print(f"RANK1-CLIENT-TIMEOUT: {e}", flush=True)
    except RuntimeError as e:
        dt = time.monotonic() - t0
        print(f"RANK1-ABORTED after {dt:.1f}s: {e}", flush=True)
    core.shutdown()
    print("rank1: exited cleanly", flush=True)
    """
)


def test_coordinator_death_fails_fast(tmp_path):
    """Coordinator (process rank 0) death must abort workers promptly via
    closed-socket detection with a cause naming the coordinator — NOT via the
    stall timeout (set to 120s here; the reference relies on launcher-side
    kill instead, ``run/gloo_run.py:294-304``)."""
    script = tmp_path / "coord_death_worker.py"
    script.write_text(COORD_DEATH_WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", str(script), str(r), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for r in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    assert procs[0].returncode == 7  # the deliberate coordinator death
    assert "RANK1-ABORTED" in outs[1], outs[1]
    assert "coordinator" in outs[1], outs[1]  # cause names the coordinator
    assert "rank1: exited cleanly" in outs[1], outs[1]
    assert procs[1].returncode == 0, outs[1]
