"""Virtual-mesh dryrun at 16 and 32 devices (VERDICT r3 item 3).

``dryrun_multichip`` re-execs a CPU-pinned child with the requested device
count, so these exercise every sharding phase (DP, FSDP, DP×SP, TP, PP depth
8 + interleaved 16 stages, 3D, transformer-PP, EP with 16 experts,
hierarchical cross×local, weak scaling) at mesh sizes the 8-device suite
never reaches — axis factorings like 2×8 and 4×8 hit different collective
lowerings than 2×4."""

import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


@pytest.mark.slow
@pytest.mark.parametrize("n", [16, 32])
def test_dryrun_multichip_scale(n):
    import __graft_entry__ as ge

    ge.dryrun_multichip(n)  # raises on any phase failure
