"""Native control-plane core tests (reference analog: the C++ core is
exercised through the Python bindings, SURVEY.md §4)."""

import os
import time

import numpy as np
import pytest
import jax
from jax.sharding import NamedSharding, PartitionSpec as P


@pytest.fixture()
def hvd_core(monkeypatch, tmp_path):
    """init with the native core attached (single-process local controller)."""
    import horovod_tpu as hvd

    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2")
    hvd.shutdown()
    hvd.init(native_core=True)
    yield hvd
    hvd.shutdown()


def stacked(hvd, x):
    return jax.device_put(x, NamedSharding(hvd.mesh(), P(hvd.data_axis())))


def test_core_allreduce_roundtrip(hvd_core):
    hvd = hvd_core
    n = hvd.size()
    x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    h = hvd.allreduce_async(stacked(hvd, x), op=hvd.Sum, name="core.g0")
    out = hvd.synchronize(h)
    np.testing.assert_allclose(np.asarray(out), x.sum(axis=0))


def test_core_many_tensors_one_cycle(hvd_core):
    """Multiple small tensors negotiated in one cycle get fused into one
    grouped collective; results must still be per-tensor correct."""
    hvd = hvd_core
    n = hvd.size()
    xs = [
        np.random.RandomState(i).randn(n, 8).astype(np.float32)
        for i in range(6)
    ]
    handles = [
        hvd.allreduce_async(stacked(hvd, x), op=hvd.Sum, name=f"core.f{i}")
        for i, x in enumerate(xs)
    ]
    for h, x in zip(handles, xs):
        np.testing.assert_allclose(
            np.asarray(hvd.synchronize(h)), x.sum(axis=0), rtol=1e-5
        )


def test_core_steady_state_cache(hvd_core):
    """Same named tensor over multiple steps rides the response cache."""
    hvd = hvd_core
    n = hvd.size()
    for step in range(5):
        x = np.full((n, 2), float(step), dtype=np.float32)
        h = hvd.allreduce_async(stacked(hvd, x), op=hvd.Sum, name="core.grad")
        out = hvd.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), x.sum(axis=0))


def test_core_duplicate_name(hvd_core):
    hvd = hvd_core
    from horovod_tpu.basics import _state

    _state.core.cycle_time_ms = 500  # hold the cycle open
    n = hvd.size()
    x = stacked(hvd, np.ones((n, 2), dtype=np.float32))
    h = hvd.allreduce_async(x, op=hvd.Sum, name="core.dup")
    with pytest.raises(ValueError, match="Duplicate tensor name"):
        hvd.allreduce_async(x, op=hvd.Sum, name="core.dup")
    _state.core.cycle_time_ms = 2
    hvd.synchronize(h)


def test_core_broadcast_and_allgather(hvd_core):
    hvd = hvd_core
    n = hvd.size()
    xb = np.stack([np.full((3,), r, dtype=np.float32) for r in range(n)])
    hb = hvd.broadcast_async(stacked(hvd, xb), root_rank=2, name="core.b")
    np.testing.assert_array_equal(
        np.asarray(hvd.synchronize(hb)), np.full((3,), 2.0)
    )
    xg = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
    hg = hvd.allgather_async(stacked(hvd, xg), name="core.ag")
    np.testing.assert_array_equal(
        np.asarray(hvd.synchronize(hg)), xg.reshape(-1)
    )


def test_core_knobs(hvd_core):
    from horovod_tpu.basics import _state

    core = _state.core
    assert core.fusion_threshold == 64 * 1024 * 1024
    core.fusion_threshold = 1024
    assert core.fusion_threshold == 1024
    assert core.pending_count() == 0


def test_core_timeline(monkeypatch, tmp_path):
    import horovod_tpu as hvd

    tl = tmp_path / "timeline.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(tl))
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2")
    hvd.shutdown()
    hvd.init(native_core=True)
    n = hvd.size()
    x = stacked(hvd, np.ones((n, 2), dtype=np.float32))
    for i in range(3):
        hvd.synchronize(
            hvd.allreduce_async(x, op=hvd.Sum, name=f"tl.{i}")
        )
    hvd.shutdown()
    content = tl.read_text()
    assert "NEGOTIATE" in content
    assert "ALLREDUCE" in content
    assert "CYCLE_START" in content
    import json

    events = json.loads(content)
    assert isinstance(events, list) and len(events) > 5


def test_core_prescale_postscale(hvd_core):
    hvd = hvd_core
    n = hvd.size()
    x = np.ones((n, 2), dtype=np.float32)
    h = hvd.allreduce_async(
        stacked(hvd, x), op=hvd.Sum, name="core.scale",
        prescale_factor=2.0, postscale_factor=0.5,
    )
    np.testing.assert_allclose(
        np.asarray(hvd.synchronize(h)), np.full((2,), float(n))
    )


def test_core_multiprocess_requires_coordinator():
    from horovod_tpu.core import NativeCore

    with pytest.raises(ValueError, match="coordinator"):
        NativeCore(rank=0, size=2, coordinator_host=None)


def test_core_allgather_fusion(hvd_core):
    """Two named allgathers ready in one cycle fuse into ONE response (the
    reference fuses allgathers too, controller.cc:700-755) and launch as one
    grouped XLA program; per-rank size blocks concatenate on the wire."""
    hvd = hvd_core
    from horovod_tpu import core as core_mod

    core = hvd.basics._state.core
    core.cycle_time_ms = 150  # widen the window so both land in one cycle

    plans = []
    orig = core_mod.NativeCore._execute_one

    def spy(self, resp, handles):
        plans.append(
            (resp.response_type, list(resp.tensor_names),
             list(resp.tensor_sizes))
        )
        return orig(self, resp, handles)

    core_mod.NativeCore._execute_one = spy
    try:
        for attempt in range(4):
            ha = hvd.allgather_async(
                np.ones((2, 3), np.float32), name=f"ag{attempt}_a"
            )
            hb = hvd.allgather_async(
                np.full((1, 3), 2.0, np.float32), name=f"ag{attempt}_b"
            )
            out_a = np.asarray(hvd.synchronize(ha))
            out_b = np.asarray(hvd.synchronize(hb))
            if any(
                t == core_mod.REQUEST_ALLGATHER and len(names) == 2
                for t, names, _ in plans
            ):
                break
    finally:
        core_mod.NativeCore._execute_one = orig

    # replicated input on the 8-chip mesh: every chip contributes the array
    assert out_a.shape == (2 * hvd.size(), 3)
    assert out_b.shape == (1 * hvd.size(), 3)
    np.testing.assert_allclose(out_b, 2.0)
    fused = [
        sizes for t, names, sizes in plans
        if t == core_mod.REQUEST_ALLGATHER and len(names) == 2
    ]
    assert fused, f"allgather responses never fused: {plans}"
    # one per-rank size block per tensor (size_ entries each, single proc)
    assert len(fused[0]) == 2


def test_grouped_allgather_matches_per_tensor(hvd_core):
    hvd = hvd_core
    n = hvd.size()
    rng = np.random.RandomState(0)
    xs = [
        stacked(hvd, rng.randn(n, 2, 3).astype(np.float32)),
        stacked(hvd, rng.randn(n, 1, 3).astype(np.float32)),
    ]
    outs = hvd.grouped_allgather(xs)
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(o), np.asarray(hvd.allgather(x)))
