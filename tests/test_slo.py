"""Per-request tracing + SLO burn-rate plane + regression sentinel
(ISSUE 16).

The acceptance drill: train a tiny transformer LM on the 8-device mesh
under the numerics guard → publish G1/G2 → canary under traffic with a
``slow_decode`` chaos charge scoped to the canary arm → the canary's
TTFT objective burns while stable stays green → the rollout's SLO gate
auto-rolls back to G1 **naming the objective**, every request completes
(relabeled ones included, none stranded — verified through the flight
record's rid-correlated ``req_begin``/``req_end`` events), post-rollback
tokens are bit-identical to ``generate()`` under the healthy weights,
and the training step's collective-schedule fingerprint is byte-equal
before and after.

Plus unit pins for the multi-window burn math, the EWMA+MAD drift
verdicts, the reqtrace span lifecycle (trace lanes / flight events /
histograms / the ``serving_request_latency_seconds`` alias), the
``slow_decode`` charge grammar and arm scoping, ``hvd_blackbox``'s
stranded-request grouping, and the ``hvd_slo`` CLI's ``--trend`` diff
over synthetic ``BENCH_*.json`` files.

Tier-1: deterministic, no sleeps > 0.2s; ``slo`` marker.
"""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from horovod_tpu.models.transformer import TransformerLM, generate  # noqa: E402
from horovod_tpu.observability import (  # noqa: E402
    flight,
    metrics,
    regression,
    reqtrace,
    slo,
    trace,
)
from horovod_tpu.resilience import chaos, health  # noqa: E402
from horovod_tpu.run.rendezvous import KVStoreServer  # noqa: E402
from horovod_tpu.serving import (  # noqa: E402
    GenerationRollout,
    InferenceEngine,
    QueueFull,
    WeightPublisher,
    WeightSubscriber,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slo


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """reqtrace/slo/regression/flight/trace state is module-global:
    every test starts clean and leaves nothing armed."""
    for var in ("HOROVOD_SLO", "HOROVOD_SLO_FAST_WINDOW",
                "HOROVOD_SLO_SLOW_WINDOW", "HOROVOD_SLO_BURN_THRESHOLD",
                "HOROVOD_SLO_DRIFT_ALPHA", "HOROVOD_SLO_DRIFT_WARMUP",
                "HOROVOD_SLO_DRIFT_FACTOR", "HOROVOD_REQTRACE",
                "HOROVOD_REQTRACE_WINDOW", "HOROVOD_TIMELINE"):
        monkeypatch.delenv(var, raising=False)
    from horovod_tpu.serving import publisher as _pub_mod

    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    reqtrace.reset()
    slo.reset()
    regression.reset()
    flight.reset()
    trace.reset()
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()
    yield
    chaos.reset()
    reqtrace.reset()
    slo.reset()
    regression.reset()
    flight.reset()
    trace.reset()
    health.reset()
    metrics.reset()
    metrics.set_enabled(True)
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()


def _model(depth=1, vocab=97, dim=32, heads=4, max_len=64):
    return TransformerLM(vocab=vocab, dim=dim, depth=depth, heads=heads,
                         mlp_ratio=2, max_len=max_len, dtype=jnp.float32)


def _params(model, seed=0):
    return model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]


def _ragged_prompts(seed, lens, vocab=97):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=l).astype(np.int32) for l in lens]


def _reference_generate(model, params, prompts, max_new):
    tp = max(len(p) for p in prompts)
    pad = np.zeros((len(prompts), tp), np.int32)
    for i, p in enumerate(prompts):
        pad[i, :len(p)] = p
    lens = np.asarray([len(p) for p in prompts], np.int32)
    out = np.asarray(generate(
        model, params, pad, max_new_tokens=max_new, prompt_lens=lens))
    return [out[i, lens[i]:lens[i] + max_new] for i in range(len(prompts))]


# ------------------------------------------------------- burn-window math


class TestBurnMath:
    def test_spec_grammar(self):
        objs = slo.parse_spec(
            "ttft_p99<0.5s, tpot_p50<0.05, error_rate<0.02,"
            "step_time<2.0", fast=4, slow=8)
        by_name = {o.name: o for o in objs}
        o = by_name["ttft_p99"]
        assert (o.series, o.threshold, o.budget) == ("ttft", 0.5, 0.01)
        o = by_name["tpot_p50"]
        assert (o.series, o.threshold, o.budget) == ("tpot", 0.05, 0.5)
        # error_rate: the budget IS the threshold; samples are 1.0/0.0
        o = by_name["error_rate"]
        assert (o.series, o.threshold, o.budget) == ("error_rate", 0.5,
                                                     0.02)
        # no quantile suffix -> default 1% budget
        o = by_name["step_time"]
        assert (o.series, o.threshold, o.budget) == ("step_time", 2.0,
                                                     0.01)

    def test_spec_typos_fail_loudly(self):
        with pytest.raises(ValueError, match="unknown objective series"):
            slo.parse_spec("latency_p99<0.5", fast=4, slow=8)
        with pytest.raises(ValueError, match="name<threshold"):
            slo.parse_spec("ttft_p99=0.5", fast=4, slow=8)

    def test_burn_rate_is_exact_fraction_over_budget(self):
        (o,) = slo.parse_spec("e2e_p90<1.0", fast=4, slow=8)
        for v in (0.5, 2.0, 0.5, 0.5):  # 1 violation in 4
            o.observe(v)
        # frac 0.25 / budget 0.1 = 2.5, deterministic
        assert o.burn(o.fast) == pytest.approx(2.5)
        assert o.burn(o.slow) == pytest.approx(2.5)
        assert o.budget_remaining() == 0.0  # clamped: spent 2.5x

    def test_burning_requires_full_fast_window(self):
        reg = slo.SLORegistry("ttft_p99<0.1", fast_window=4,
                              slow_window=8)
        (o,) = reg.objectives
        for _ in range(3):
            reg.observe("ttft", 0.5)  # every sample violates
        assert not o.burning(reg.burn_threshold)  # cold start: no verdict
        reg.observe("ttft", 0.5)
        assert o.burning(reg.burn_threshold)
        assert health.health_state().name == "SUSPECT"
        assert "ttft_p99" in health.snapshot()["reason"]

    def test_strike_cadence_counted_in_observations(self):
        reg = slo.SLORegistry("error_rate<0.5", fast_window=4,
                              slow_window=4)
        for _ in range(8):
            reg.observe("error_rate", 1.0)
        # one strike on entry into burning (obs 4), one per fast-window
        # of observations while it stays burning (obs 8)
        assert metrics.value("resilience_slo_burns",
                             objective="error_rate") == 2.0

    def test_zero_budget_inf_published_as_sentinel(self):
        reg = slo.SLORegistry("error_rate<0", fast_window=2,
                              slow_window=2)
        reg.observe("error_rate", 1.0)
        reg.observe("error_rate", 1.0)
        (o,) = reg.objectives
        assert o.burn(o.fast) == float("inf")
        assert o.budget_remaining() == 0.0
        # the gauge carries the JSON-safe sentinel, not inf
        assert metrics.value("slo_burn_rate",
                             objective="error_rate") == -1.0
        assert metrics.value("slo_budget_remaining",
                             objective="error_rate") == 0.0

    def test_recovery_stops_burning(self):
        reg = slo.SLORegistry("ttft_p99<0.1", fast_window=4,
                              slow_window=4)
        (o,) = reg.objectives
        for _ in range(4):
            reg.observe("ttft", 0.5)
        assert o.burning(reg.burn_threshold)
        for _ in range(4):
            reg.observe("ttft", 0.01)
        assert not o.burning(reg.burn_threshold)
        assert metrics.value("slo_burn_rate",
                             objective="ttft_p99") == 0.0

    def test_gauge_sourced_series_sampled_per_step(self):
        metrics.gauge("data_wait_seconds_recent",
                      help="test").set(0.7)
        reg = slo.SLORegistry("data_wait<0.5", fast_window=2,
                              slow_window=2)
        reg.sample_gauges()
        st = reg.status()
        assert st[0]["observations"] == 1
        assert st[0]["fast_burn"] > 0

    def test_judge_canary_relative_to_stable_baseline(self):
        reg = slo.SLORegistry("ttft_p99<0.05", fast_window=4,
                              slow_window=8)
        canary = {"ttft": [0.2, 0.21, 0.22], "done": 3, "errors": 0}
        # stable even slower: a globally slow system does not indict
        # the canary
        slow_stable = {"ttft": [0.3, 0.31, 0.32], "done": 3, "errors": 0}
        assert reg.judge_canary(canary, slow_stable) is None
        fast_stable = {"ttft": [0.01, 0.012, 0.011], "done": 3,
                       "errors": 0}
        verdict = reg.judge_canary(canary, fast_stable)
        assert verdict is not None and verdict[0] == "ttft_p99"
        # no stable baseline (100%-canary drill): the burn alone decides
        verdict = reg.judge_canary(canary, {"ttft": [], "done": 0,
                                            "errors": 0})
        assert verdict is not None and verdict[0] == "ttft_p99"

    def test_judge_canary_error_rate(self):
        reg = slo.SLORegistry("error_rate<0.1", fast_window=4,
                              slow_window=8)
        assert reg.judge_canary(
            {"done": 10, "errors": 0}, {"done": 0, "errors": 0}) is None
        verdict = reg.judge_canary(
            {"done": 10, "errors": 5}, {"done": 0, "errors": 0})
        assert verdict is not None and verdict[0] == "error_rate"


# -------------------------------------------------- drift (EWMA + MAD)


class TestDrift:
    def test_warmup_then_drift_not_absorbed(self):
        b = regression.Baseline(alpha=0.2, warmup=3, factor=4.0)
        for _ in range(3):
            assert b.update(1.0)["state"] == "warmup"
        assert b.update(1.0)["state"] == "ok"
        ewma_before = b.ewma
        v = b.update(10.0)
        assert v["state"] == "drift"
        assert v["streak"] == 1
        # the baseline remembers what normal looked like
        assert b.ewma == ewma_before
        v = b.update(10.0)
        assert v["state"] == "drift" and v["streak"] == 2
        assert b.update(1.0)["state"] == "ok"
        assert b.streak == 0

    def test_relative_floor_absorbs_jitter(self):
        # a near-constant series (MAD -> 0) must not flag on +-10% noise
        b = regression.Baseline(alpha=0.2, warmup=3, factor=2.0)
        for v in (1.0, 1.0, 1.0, 1.1, 0.9, 1.05):
            assert b.update(v)["state"] in ("warmup", "ok")

    def test_track_publishes_drift_metrics(self):
        for _ in range(3):
            regression.track("x_step_seconds", 1.0, warmup=2, factor=4.0)
        assert metrics.value("regression_drift",
                             metric="x_step_seconds") == 0.0
        v = regression.track("x_step_seconds", 50.0)
        assert v["state"] == "drift"
        assert metrics.value("regression_drift",
                             metric="x_step_seconds") == 1.0
        assert metrics.value("regression_drift_events",
                             metric="x_step_seconds") == 1.0
        assert regression.verdicts()["x_step_seconds"]["state"] == "drift"
        regression.forget("x_step_seconds")
        assert regression.track("x_step_seconds", 50.0)["state"] == \
            "warmup"

    def test_trend_direction_aware(self):
        result = regression.trend([
            {"lm_step_seconds": 1.0, "lm_examples_per_sec": 100.0,
             "lm_loss": 2.0},
            {"lm_step_seconds": 1.2, "lm_examples_per_sec": 120.0,
             "lm_loss": 2.01},
        ], threshold=0.05)
        assert "lm_step_seconds" in result["regressed"]  # +20% time: bad
        assert "lm_examples_per_sec" not in result["regressed"]  # faster
        assert "lm_loss" not in result["regressed"]  # +0.5% < threshold
        rows = {r["metric"]: r for r in result["rows"]}
        assert rows["lm_examples_per_sec"]["direction"] == \
            "higher_is_better"
        assert rows["lm_step_seconds"]["delta_frac"] == \
            pytest.approx(0.2)

    def test_trend_throughput_drop_regresses(self):
        result = regression.trend([
            {"tokens_per_sec": 100.0}, {"tokens_per_sec": 100.0},
            {"tokens_per_sec": 80.0},
        ], threshold=0.05)
        assert result["regressed"] == ["tokens_per_sec"]

    def test_trend_needs_two_snapshots(self):
        with pytest.raises(ValueError):
            regression.trend([{"a": 1.0}])


# ------------------------------------------------------- hvd_slo CLI


class TestHvdSloCLI:
    def _bench(self, tmp_path, name, fields):
        p = tmp_path / name
        p.write_text(json.dumps(fields) + "\n")
        return str(p)

    def test_trend_json_exits_nonzero_on_regression(self, tmp_path,
                                                    capsys):
        from tools import hvd_slo

        a = self._bench(tmp_path, "BENCH_a.json",
                        {"transformer_lm_step_seconds": 1.0,
                         "transformer_lm_examples_per_sec": 100.0,
                         "config": "8xcpu"})
        b = self._bench(tmp_path, "BENCH_b.json",
                        {"transformer_lm_step_seconds": 1.5,
                         "transformer_lm_examples_per_sec": 101.0})
        rc = hvd_slo.main(["--trend", a, b, "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 4
        assert out["regressed"] == ["transformer_lm_step_seconds"]
        assert out["files"] == [a, b]

    def test_trend_clean_exits_zero(self, tmp_path, capsys):
        from tools import hvd_slo

        a = self._bench(tmp_path, "BENCH_a.json",
                        {"transformer_lm_step_seconds": 1.0})
        b = self._bench(tmp_path, "BENCH_b.json",
                        {"transformer_lm_step_seconds": 0.99})
        assert hvd_slo.main(["--trend", a, b]) == 0
        assert "0 metric(s) regressed" in capsys.readouterr().out

    def test_trend_needs_two_files(self, tmp_path, capsys):
        from tools import hvd_slo

        a = self._bench(tmp_path, "BENCH_a.json", {"x": 1.0})
        assert hvd_slo.main(["--trend", a]) == 1

    def test_slo_table_and_latency_rows_from_gauges(self):
        from tools import hvd_slo

        payload = {"metrics": {
            "slo_burn_rate": {"type": "gauge", "samples": {
                "objective=ttft_p99": {"min": 2.0, "mean": 2.0,
                                       "max": 2.0},
                "objective=error_rate": {"min": -1.0, "mean": -1.0,
                                         "max": -1.0},
            }},
            "slo_budget_remaining": {"type": "gauge", "samples": {
                "objective=ttft_p99": {"min": 0.0, "mean": 0.0,
                                       "max": 0.0},
            }},
            "reqtrace_ttft_p99": {"type": "gauge", "samples": {
                "arm=canary": {"min": 0.2, "mean": 0.2, "max": 0.2},
            }},
        }}
        rows = {r["objective"]: r
                for r in hvd_slo.slo_table(payload["metrics"])}
        assert rows["ttft_p99"]["burning"]  # burn 2.0 >= 1.0
        assert rows["error_rate"]["burning"]  # -1 = zero-budget violated
        lat = hvd_slo.latency_rows(payload["metrics"])
        assert lat == [{"arm": "canary", "ttft_p99": 0.2}]
        text = hvd_slo.render_live(payload)
        assert "BURNING" in text and "worst offender: error_rate" in text

    def test_hvd_top_slo_pane(self):
        from tools import hvd_top

        pane = hvd_top.slo_pane({
            "slo_burn_rate": {"type": "gauge", "samples": {
                "objective=ttft_p99": {"min": 3.0, "mean": 3.0,
                                       "max": 3.0},
            }},
            "slo_budget_remaining": {"type": "gauge", "samples": {
                "objective=ttft_p99": {"min": 0.1, "mean": 0.1,
                                       "max": 0.1},
            }},
        })
        text = "\n".join(pane)
        assert "ttft_p99" in text and "BURNING" in text


# -------------------------------------------------- reqtrace lifecycle


class TestReqtrace:
    def _engine(self, **kw):
        model = _model()
        eng = InferenceEngine(model, page_size=8, num_pages=24,
                              max_batch=2, prefill_chunk=8,
                              max_seq_len=24, **kw)
        eng.set_weights(_params(model), generation=1, arm="stable")
        return eng

    def test_histograms_alias_windows_and_quantile_gauges(self):
        eng = self._engine()
        prompts = _ragged_prompts(7, (5, 9))
        reqs = [eng.submit(p, 4, rid=f"r{i}")
                for i, p in enumerate(prompts)]
        eng.run_until_idle()
        assert all(r.error is None for r in reqs)
        assert metrics.value("reqtrace_e2e_seconds", arm="stable",
                             outcome="ok", generation="1")["count"] == 2
        # the scheduler's old latency family lives on as an alias of the
        # same (single) completion observation path
        assert metrics.value("serving_request_latency_seconds",
                             arm="stable")["count"] == 2
        assert metrics.value("reqtrace_ttft_seconds", arm="stable",
                             generation="1")["count"] == 2
        # 4 generated tokens per request -> 3 inter-token gaps each
        assert metrics.value("reqtrace_tpot_seconds", arm="stable",
                             generation="1")["count"] == 6
        assert metrics.value("reqtrace_queue_wait_seconds",
                             arm="stable")["count"] == 2
        assert metrics.value("reqtrace_ttft_p50", arm="stable") is not None
        assert metrics.value("reqtrace_tpot_p99", arm="stable") is not None
        # the windowed accounting the rollout gate reads
        assert reqtrace.arm_mark("stable") == 2
        w = reqtrace.arm_window("stable")
        assert w["done"] == 2 and w["errors"] == 0
        assert len(w["ttft"]) == 2 and len(w["tpot"]) == 2
        assert all(t > 0 for t in w["e2e"])
        # generation filter: nothing completed under generation 7
        assert reqtrace.arm_window("stable", generation=7)["done"] == 0
        assert reqtrace.live_requests() == []

    def test_flight_events_rid_correlated(self):
        eng = self._engine()
        reqs = [eng.submit(p, 2, rid=f"fl{i}")
                for i, p in enumerate(_ragged_prompts(9, (4, 6)))]
        eng.run_until_idle()
        assert all(r.error is None for r in reqs)
        flight.flush()
        evs = [e for e in flight.events() if e.get("kind") == "serve"]
        begun = {e["rid"] for e in evs if e.get("what") == "req_begin"}
        ended = {e["rid"] for e in evs if e.get("what") == "req_end"}
        assert begun == ended == {"fl0", "fl1"}
        assert all(e.get("outcome") == "ok" for e in evs
                   if e.get("what") == "req_end")

    def test_trace_lanes_per_request(self, monkeypatch, tmp_path):
        monkeypatch.setenv("HOROVOD_TIMELINE",
                           str(tmp_path / "timeline.json"))
        trace.reset()  # re-read HOROVOD_TIMELINE under the monkeypatch
        eng = self._engine()
        req = eng.submit(_ragged_prompts(3, (6,))[0], 3, rid="lane0")
        eng.run_until_idle()
        assert req.error is None
        lane = [e for e in trace.events() if e.get("pid") == "req:lane0"]
        names = [e["name"] for e in lane]
        for want in ("enqueue", "queue_wait", "admit", "first_token",
                     "request:ok"):
            assert want in names, names
        assert any(n.startswith("prefill[") for n in names)
        assert "decode_token" in names
        admit = next(e for e in lane if e["name"] == "admit")
        assert admit["args"]["pages"] >= 1

    def test_reqtrace_emission_gate(self, monkeypatch):
        """HOROVOD_REQTRACE=0 silences emission; the windowed accounting
        the rollout gate depends on still runs."""
        monkeypatch.setenv("HOROVOD_REQTRACE", "0")
        reqtrace.reset()
        eng = self._engine()
        req = eng.submit(_ragged_prompts(5, (5,))[0], 2, rid="quiet")
        eng.run_until_idle()
        assert req.error is None
        flight.flush()
        assert not [e for e in flight.events()
                    if e.get("what") == "req_begin"]
        assert reqtrace.arm_window("stable")["done"] == 1

    def test_rejected_requests_observed(self):
        eng = self._engine(max_queue=1)
        prompts = _ragged_prompts(11, (5, 5))
        eng.submit(prompts[0], 2, rid="kept")
        with pytest.raises(QueueFull):
            eng.submit(prompts[1], 2, rid="shed")
        eng.run_until_idle()
        s = metrics.value("reqtrace_e2e_seconds", arm="stable",
                          outcome="rejected", generation="-1")
        assert s["count"] == 1
        flight.flush()
        ends = {e["rid"]: e for e in flight.events()
                if e.get("what") == "req_end"}
        assert ends["shed"]["outcome"] == "rejected"
        assert reqtrace.live_requests() == []


# --------------------------------------------------- slow_decode chaos


class TestSlowDecodeChaos:
    def test_grammar(self):
        chaos.configure("slow_decode=0.05")
        assert chaos.slow_decode() == (0.05, None)
        chaos.configure("slow_decode=0.03:canary")
        assert chaos.slow_decode() == (0.03, "canary")
        # persistent: NOT consumed on read
        assert chaos.slow_decode() == (0.03, "canary")
        chaos.configure(None)
        assert chaos.slow_decode() is None

    def test_arm_scoped_and_counted(self):
        model = _model()
        eng = InferenceEngine(model, page_size=8, num_pages=24,
                              max_batch=2, prefill_chunk=8,
                              max_seq_len=24)
        eng.set_weights(_params(model), generation=1, arm="stable")
        # scoped to canary: stable passes do NOT inject
        chaos.configure("slow_decode=0.01:canary")
        r = eng.submit(_ragged_prompts(1, (5,))[0], 2, rid="s0")
        eng.run_until_idle()
        assert r.error is None
        assert metrics.value("resilience_chaos_injected",
                             site="slow_decode") is None
        # unscoped: every pass injects (and the request still completes
        # with identical tokens — the sleep is host-side only)
        want = list(np.asarray(r.generated))
        chaos.configure("slow_decode=0.01")
        r2 = eng.submit(_ragged_prompts(1, (5,))[0], 2, rid="s1")
        eng.run_until_idle()
        assert r2.error is None
        assert list(np.asarray(r2.generated)) == want
        assert metrics.value("resilience_chaos_injected",
                             site="slow_decode") >= 1.0


# ------------------------------------------- blackbox request grouping


class TestBlackboxRequests:
    def test_stranded_request_named(self):
        from tools import hvd_blackbox

        rank_events = {0: [
            {"t": 1.0, "kind": "serve", "what": "req_begin", "rid": "a",
             "arm": "stable"},
            {"t": 1.5, "kind": "serve", "what": "req_end", "rid": "a",
             "arm": "stable", "outcome": "ok"},
            {"t": 2.0, "kind": "serve", "what": "req_begin", "rid": "b",
             "arm": "canary"},
            {"t": 2.1, "kind": "serve", "what": "req_relabel",
             "rid": "b", "src": "canary", "dst": "stable"},
            {"t": 2.2, "kind": "collective", "ph": "B",
             "op": "allreduce", "step": 1, "gen": 0, "seq": 0},
        ]}
        lines = hvd_blackbox.request_summary(rank_events)
        assert lines[0] == \
            "requests in record: 2 begun, 1 completed, 1 STRANDED"
        # the relabel's destination arm wins for the stranded display
        assert "STRANDED request b on arm stable" in lines[1]

    def test_no_request_events_no_section(self):
        from tools import hvd_blackbox

        assert hvd_blackbox.request_summary({0: [
            {"t": 1.0, "kind": "step", "step": 3},
        ]}) == []


# -------------------------------------------------- rollout SLO gate


class TestRolloutSLOGate:
    def _stack(self, model, params, *, min_requests=2):
        s = KVStoreServer()
        pub = WeightPublisher(s, keyframe_every=8, register=False)
        sub = WeightSubscriber(s, device=True)
        eng = InferenceEngine(model, page_size=8, num_pages=40,
                              max_batch=2, prefill_chunk=8,
                              max_seq_len=24)
        events = []
        roll = GenerationRollout(
            eng, sub, canary_fraction=1.0,
            min_canary_requests=min_requests, max_latency_ratio=None,
            on_event=lambda e, g: events.append((e, g)))
        pub.publish({"params": params}, 1)
        roll.poll()
        assert roll.stable_generation == 1
        return s, pub, sub, eng, roll, events

    def test_latency_only_regression_rolls_back_naming_objective(self):
        """The new capability: a canary whose weights are HEALTHY but
        slow (pure latency regression) is caught by the declared
        objective and rolled back — the bespoke error-rate/latency-ratio
        pair could never see this."""
        slo.configure("ttft_p99<0.05", fast_window=256, slow_window=256)
        model = _model(depth=1)
        params = _params(model)
        s, pub, sub, eng, roll, events = self._stack(model, params)
        try:
            prompts = _ragged_prompts(17, (6, 9))
            # warm the compile caches on stable so healthy TTFTs are
            # well under the 50 ms objective
            warm = [roll.submit(f"warm-{i}", p, 2)
                    for i, p in enumerate(prompts)]
            roll.drain()
            assert all(r.error is None for r in warm)
            healthy = jax.device_get(pub.reconstruction())

            p2 = jax.tree_util.tree_map(
                lambda a: np.asarray(a) * 1.01, jax.device_get(params))
            pub.publish({"params": p2}, 2)
            roll.poll()
            assert roll.canary_generation == 2
            chaos.configure("slow_decode=0.15:canary")
            reqs = [roll.submit(f"slow-{i}", p, 2)
                    for i, p in enumerate(prompts)]
            roll.drain()
            # every request completed (none dropped by the rollback)
            assert all(r.error is None for r in reqs)
            assert roll.stable_generation == 1
            assert 2 in roll.vetoed
            assert ("rolled_back", 2) in events
            assert metrics.value(
                "serving_rollouts", outcome="rolled_back") == 1.0
            assert "slo objective 'ttft_p99'" in \
                health.snapshot()["reason"]
            assert metrics.value("resilience_slo_burns",
                                 objective="ttft_p99") == 1.0
            # stable params ARE the healthy commit, bit-equal
            for got, want in zip(
                jax.tree_util.tree_leaves(eng.arm_params("stable")),
                jax.tree_util.tree_leaves(healthy),
            ):
                np.testing.assert_array_equal(np.asarray(got),
                                              np.asarray(want))
            assert reqtrace.live_requests() == []

            # the charge cleared, the next healthy canary promotes
            # through the same evaluator
            chaos.configure(None)
            p3 = jax.tree_util.tree_map(
                lambda a: np.asarray(a) * 1.02, jax.device_get(params))
            pub.publish({"params": p3}, 3)
            roll.poll()
            assert roll.canary_generation == 3
            reqs = [roll.submit(f"ok-{i}", p, 2)
                    for i, p in enumerate(prompts)]
            roll.drain()
            assert all(r.error is None for r in reqs)
            assert roll.stable_generation == 3
            assert ("promoted", 3) in events
        finally:
            s.close()


# ----------------------------------------------------- the e2e drill


@pytest.mark.chaos
def test_e2e_slo_drill_train_publish_canary_burn_rollback(
        hvd, monkeypatch):
    """THE ISSUE-16 drill: guarded training on the 8-device mesh →
    publish G1/G2 → canary under traffic with ``slow_decode`` scoped to
    the canary arm → the canary's TTFT objective burns (stable stays
    green) → the SLO gate auto-rolls back to G1 naming ``ttft_p99`` →
    every request completes (relabeled included, none stranded in the
    flight record), post-rollback tokens are bit-identical to
    ``generate()`` under the healthy weights, and the training step's
    collective-schedule fingerprint is byte-equal before and after."""
    from horovod_tpu.analysis.schedule import collective_schedule
    from horovod_tpu.resilience import numerics
    from horovod_tpu.training import (
        make_shardmap_train_step,
        replicate,
        shard_batch,
        token_xent,
    )
    from tools import hvd_blackbox

    monkeypatch.setenv("HOROVOD_NUMERICS_WARMUP", "1")
    model = _model(depth=1, vocab=64, dim=32, heads=2, max_len=32)
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    tx = numerics.guard(optax.adam(1e-2))
    step = make_shardmap_train_step(
        model, tx, loss_fn=token_xent, instrument=False, donate=False)
    rng = np.random.RandomState(0)
    toks = rng.randint(1, 64, size=(16, 9)).astype(np.int32)
    xs, ys = shard_batch(toks[:, :-1]), shard_batch(toks[:, 1:])
    params = replicate(jax.tree_util.tree_map(jnp.array, params0))
    opt_state = tx.init(params)

    slo.configure("ttft_p99<0.05", fast_window=256, slow_window=256)
    server = KVStoreServer()
    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        sub = WeightSubscriber(server, device=True)
        eng = InferenceEngine(model, page_size=8, num_pages=24,
                              max_batch=2, prefill_chunk=8,
                              max_seq_len=24)
        roll = GenerationRollout(eng, sub, canary_fraction=1.0,
                                 min_canary_requests=2,
                                 max_latency_ratio=None)

        def train_one():
            nonlocal params, opt_state
            params, _, opt_state, _ = step(params, {}, opt_state, xs, ys)

        fp_before = collective_schedule(
            step, params, {}, opt_state, xs, ys).fingerprint()

        # G1 commits; warm the serving path on stable
        train_one()
        assert pub.publish(
            {"params": params, "opt_state": opt_state}, 1) == 1
        roll.poll()
        assert roll.stable_generation == 1
        healthy = jax.device_get(pub.reconstruction())
        prompts = _ragged_prompts(5, (6, 9), vocab=64)
        warm = [roll.submit(f"warm-{i}", p, 2)
                for i, p in enumerate(prompts)]
        roll.drain()
        assert all(r.error is None for r in warm)

        # G2 canaries under a canary-scoped latency injection: the
        # burn is attributed to the canary arm only
        train_one()
        assert pub.publish(
            {"params": params, "opt_state": opt_state}, 2) == 2
        roll.poll()
        assert roll.canary_generation == 2
        chaos.configure("slow_decode=0.15:canary")
        reqs = [roll.submit(f"drill-{i}", p, 2)
                for i, p in enumerate(prompts)]
        roll.drain()

        # the named verdict: rollback to G1, objective in the reason
        assert all(r.error is None for r in reqs)  # no request dropped
        assert roll.stable_generation == 1
        assert 2 in roll.vetoed
        assert metrics.value(
            "serving_rollouts", outcome="rolled_back") == 1.0
        assert "slo objective 'ttft_p99'" in health.snapshot()["reason"]
        assert metrics.value("resilience_slo_burns",
                             objective="ttft_p99") == 1.0
        # the canary's burn is visible in the per-arm histograms
        assert metrics.value("reqtrace_ttft_seconds", arm="canary",
                             generation="2")["count"] >= 2
        assert metrics.value("resilience_chaos_injected",
                             site="slow_decode") >= 1.0

        # nothing stranded: every req_begin in the flight record has
        # its rid-matched req_end (the hvd_blackbox grouping agrees)
        flight.flush()
        evs = [e for e in flight.events() if e.get("kind") == "serve"]
        begun = {e["rid"] for e in evs if e.get("what") == "req_begin"}
        ended = {e["rid"] for e in evs if e.get("what") == "req_end"}
        assert begun == ended and len(begun) == 4
        summary = hvd_blackbox.request_summary({0: evs})
        assert summary[0].endswith("0 STRANDED")
        assert reqtrace.live_requests() == []

        # token parity: post-rollback traffic decodes under G1 and is
        # bit-identical to generate() on the healthy weights
        chaos.configure(None)
        want = _reference_generate(model, healthy, prompts, 3)
        after = [roll.submit(f"after-{i}", p, 3)
                 for i, p in enumerate(prompts)]
        roll.drain()
        for req, ref in zip(after, want):
            assert req.error is None
            np.testing.assert_array_equal(np.asarray(req.generated), ref)
        # stable arm bit-equal to the healthy commit
        for got, ref in zip(
            jax.tree_util.tree_leaves(eng.arm_params("stable")),
            jax.tree_util.tree_leaves(healthy),
        ):
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(ref))

        # serving added no training-side collectives
        fp_after = collective_schedule(
            step, params, {}, opt_state, xs, ys).fingerprint()
        assert fp_after == fp_before
    finally:
        server.close()


# ------------------------------------------------- training-step wiring


def test_instrumented_step_feeds_slo_and_regression(hvd):
    """The training wrapper observes step_time into the SLO plane,
    polls the gauge-sourced series, and tracks the regression
    baselines per step."""
    from horovod_tpu import training

    slo.configure("step_time<100.0", fast_window=4, slow_window=4)
    calls = {"n": 0}

    def fake_step(params, batch):
        calls["n"] += 1
        return params

    wrapped = training.instrument_step(fake_step, name="toy",
                                       batch_arg=1)
    p = {"w": jnp.zeros((2,))}
    batch = np.zeros((8, 4), np.float32)
    for _ in range(3):
        p = wrapped(p, batch)
    assert calls["n"] == 3
    # step_time observations land in the registry (first dispatch has
    # no interval; the rest do)
    st = slo.status()
    assert st[0]["observations"] >= 1
    assert metrics.value("slo_burn_rate",
                         objective="step_time") == 0.0
    assert "toy_step_seconds" in regression.verdicts()
    assert "toy_examples_per_sec" in regression.verdicts()
