"""Pipeline- and expert-parallel tests on the virtual CPU mesh: outputs and
gradients must match the equivalent sequential/dense computation."""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as shard_map_fn
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as shard_map_fn

from horovod_tpu.parallel import (
    EXPERT_AXIS, PIPELINE_AXIS, build_mesh,
    expert_parallel_moe, make_stage_params, pipeline_apply, top1_dispatch,
)


# ------------------------------------------------------------------ pipeline


def stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _stages(n_stages, d, seed=0):
    rng = np.random.RandomState(seed)
    return [
        (jnp.asarray(rng.randn(d, d).astype(np.float32) * 0.5),
         jnp.asarray(rng.randn(d).astype(np.float32) * 0.1))
        for _ in range(n_stages)
    ]


def _sequential(stages, x_micro):
    outs = []
    for m in range(x_micro.shape[0]):
        h = x_micro[m]
        for p in stages:
            h = stage_fn(p, h)
        outs.append(h)
    return jnp.stack(outs)


def _pipe_run(mesh, stacked, x_micro, n_stages):
    def inner(stage_params, xm):
        local = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        out = pipeline_apply(stage_fn, local, xm, axis_name=PIPELINE_AXIS)
        return lax.psum(out, PIPELINE_AXIS)  # zeros except last stage

    return shard_map_fn(
        inner, mesh=mesh,
        in_specs=(P(PIPELINE_AXIS), P()), out_specs=P(),
        check_vma=False,
    )(stacked, x_micro)


@pytest.mark.parametrize("n_micro", [4, 7])
def test_pipeline_matches_sequential(n_micro):
    n_stages, d, mb = 4, 8, 3
    mesh = build_mesh({PIPELINE_AXIS: n_stages},
                      devices=jax.devices()[:n_stages])
    stages = _stages(n_stages, d)
    stacked = make_stage_params(stages)
    x = jnp.asarray(
        np.random.RandomState(1).randn(n_micro, mb, d).astype(np.float32))

    out = jax.jit(functools.partial(_pipe_run, mesh, n_stages=n_stages))(
        stacked, x)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grad_matches_sequential():
    n_stages, d, mb, n_micro = 4, 6, 2, 5
    mesh = build_mesh({PIPELINE_AXIS: n_stages},
                      devices=jax.devices()[:n_stages])
    stages = _stages(n_stages, d, seed=2)
    stacked = make_stage_params(stages)
    x = jnp.asarray(
        np.random.RandomState(3).randn(n_micro, mb, d).astype(np.float32))

    def loss_pipe(stacked_params):
        return (_pipe_run(mesh, stacked_params, x, n_stages) ** 2).sum()

    def loss_seq(stages_params):
        return (_sequential(stages_params, x) ** 2).sum()

    g1 = jax.jit(jax.grad(loss_pipe))(stacked)
    g2 = jax.grad(loss_seq)(stages)
    g2_stacked = make_stage_params(g2)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


# ------------------------------------------------------- interleaved pipeline


from horovod_tpu.parallel import (  # noqa: E402
    make_interleaved_stage_params, pipeline_apply_interleaved,
)


def _pipe_run_interleaved(mesh, stacked_vd, x_micro):
    def inner(stage_params, xm):
        local = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        out = pipeline_apply_interleaved(
            stage_fn, local, xm, axis_name=PIPELINE_AXIS
        )
        return lax.psum(out, PIPELINE_AXIS)  # zeros except last device

    return shard_map_fn(
        inner, mesh=mesh,
        in_specs=(P(PIPELINE_AXIS), P()), out_specs=P(),
        check_vma=False,
    )(stacked_vd, x_micro)


@pytest.mark.parametrize("n_dev,v,n_micro", [
    (4, 1, 5),   # v=1 degenerates to GPipe
    (4, 2, 4),
    (4, 2, 7),   # M not a multiple of S
    (2, 3, 5),
    (2, 2, 1),   # single microbatch
])
def test_interleaved_pipeline_matches_sequential(n_dev, v, n_micro):
    d, mb = 8, 3
    L = n_dev * v
    mesh = build_mesh({PIPELINE_AXIS: n_dev}, devices=jax.devices()[:n_dev])
    stages = _stages(L, d, seed=4)
    stacked = make_interleaved_stage_params(stages, n_dev)
    x = jnp.asarray(
        np.random.RandomState(5).randn(n_micro, mb, d).astype(np.float32))

    out = jax.jit(functools.partial(_pipe_run_interleaved, mesh))(stacked, x)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_interleaved_pipeline_grad_matches_sequential():
    n_dev, v, d, mb, n_micro = 2, 2, 6, 2, 4
    L = n_dev * v
    mesh = build_mesh({PIPELINE_AXIS: n_dev}, devices=jax.devices()[:n_dev])
    stages = _stages(L, d, seed=6)
    stacked = make_interleaved_stage_params(stages, n_dev)
    x = jnp.asarray(
        np.random.RandomState(7).randn(n_micro, mb, d).astype(np.float32))

    def loss_pipe(sp):
        return (_pipe_run_interleaved(mesh, sp, x) ** 2).sum()

    def loss_seq(stages_params):
        return (_sequential(stages_params, x) ** 2).sum()

    g1 = jax.jit(jax.grad(loss_pipe))(stacked)
    g2 = jax.grad(loss_seq)(stages)
    g2_il = make_interleaved_stage_params(g2, n_dev)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2_il)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_interleaved_stage_layout_errors():
    with pytest.raises(ValueError, match="divisible"):
        make_interleaved_stage_params(_stages(5, 4), 2)


# ------------------------------------------------------- pp train builder


@pytest.mark.parametrize("interleaved", [False, True])
def test_make_pp_train_step_trains(interleaved):
    """The productized PP step builder: stacked stage params + vmapped
    optimizer state over the pipe axis; loss decreases on a learnable
    teacher for both schedules."""
    import optax

    from horovod_tpu.parallel import make_interleaved_stage_params
    from horovod_tpu.training import make_pp_train_step

    import horovod_tpu as hvd

    S, v, d, mb, M = 4, 2, 8, 4, 6
    hvd.shutdown()
    hvd.init(axes={PIPELINE_AXIS: S}, devices=jax.devices()[:S])
    try:
        rng = np.random.RandomState(0)
        L = S * v if interleaved else S
        stage_list = [
            (jnp.asarray(rng.randn(d, d).astype(np.float32) * 0.3),
             jnp.asarray(np.zeros(d, np.float32)))
            for _ in range(L)
        ]
        stacked = (
            make_interleaved_stage_params(stage_list, S)
            if interleaved else make_stage_params(stage_list)
        )
        tx = optax.adam(3e-3)
        opt_state = jax.vmap(tx.init)(stacked)

        Wt = rng.randn(d, d).astype(np.float32)
        x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))
        y = jnp.tanh(x @ Wt)

        step = make_pp_train_step(
            stage_fn, tx, interleaved=interleaved, donate=False
        )
        losses = []
        for _ in range(30):
            stacked, opt_state, loss = step(stacked, opt_state, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses[::10]
    finally:
        hvd.shutdown()


# ----------------------------------------------------- 3D (DP x PP x TP)


def test_3d_parallel_train_step_matches_dense():
    """DP x PP x TP composed in ONE shard_map: batch sharded over `data`,
    stages over `pipe`, each stage's MLP hidden dim over `model`. Loss and
    parameter gradients must match the dense sequential model — shard_map
    autodiff inserts every backward collective (psum over model inside the
    stage, ppermute reversal through the pipeline scan, gradient psum over
    data from the pmean'd loss)."""
    from horovod_tpu.parallel import DATA_AXIS, MODEL_AXIS

    dp, S, tp = 2, 2, 2
    d, hid, mb, M = 4, 8, 6, 4  # hid sharded over tp
    mesh = build_mesh(
        {DATA_AXIS: dp, PIPELINE_AXIS: S, MODEL_AXIS: tp},
        devices=jax.devices()[: dp * S * tp],
    )
    rng = np.random.RandomState(0)
    w1 = jnp.asarray(rng.randn(S, d, hid).astype(np.float32) * 0.4)
    w2 = jnp.asarray(rng.randn(S, hid, d).astype(np.float32) * 0.4)
    x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))
    y = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))

    def tp_stage(p, h):
        a, b = p  # local shards: a [d, hid/tp], b [hid/tp, d]
        return lax.psum(jax.nn.relu(h @ a) @ b, MODEL_AXIS)

    def inner(w1_, w2_, xm, ym):
        local = (w1_[0], w2_[0])  # squeeze the pipe shard dim

        def loss_fn(lp):
            out = pipeline_apply(
                tp_stage, lp, xm, axis_name=PIPELINE_AXIS,
            )
            out = lax.psum(out, PIPELINE_AXIS)  # valid on last stage only
            return jnp.mean((out - ym) ** 2)  # this replica's batch shard

        loss, (g1, g2) = jax.value_and_grad(loss_fn)(local)
        # Per-device autodiff differentiates each device's own copy of the
        # replicated scalar, and psum's transpose is psum — so the S*tp
        # devices sharing one data replica over-count shard grads by
        # exactly S*tp. Normalize, then do the DP gradient exchange
        # (the framework's make_shardmap_train_step pattern).
        k = lax.psum(1, PIPELINE_AXIS) * lax.psum(1, MODEL_AXIS)
        loss = lax.pmean(loss, DATA_AXIS)
        g1 = lax.pmean(g1 / k, DATA_AXIS)
        g2 = lax.pmean(g2 / k, DATA_AXIS)
        return loss, g1[None], g2[None]  # restore the pipe shard dim

    specs_w1 = P(PIPELINE_AXIS, None, MODEL_AXIS)
    specs_w2 = P(PIPELINE_AXIS, MODEL_AXIS, None)
    spec_x = P(None, DATA_AXIS, None)
    loss, g1, g2 = jax.jit(shard_map_fn(
        inner, mesh=mesh,
        in_specs=(specs_w1, specs_w2, spec_x, spec_x),
        out_specs=(P(), specs_w1, specs_w2),
        check_vma=False,
    ))(w1, w2, x, y)

    # dense oracle: same math, no sharding
    def dense_loss(params):
        dw1, dw2 = params
        out = []
        for m in range(M):
            h = x[m]
            for s in range(S):
                h = jax.nn.relu(h @ dw1[s]) @ dw2[s]
            out.append(h)
        return jnp.mean((jnp.stack(out) - y) ** 2)

    ref_loss, (ref_g1, ref_g2) = jax.value_and_grad(dense_loss)((w1, w2))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(ref_g1),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(ref_g2),
                               rtol=1e-4, atol=1e-5)


# ----------------------------------------------------------------------- moe


def expert_fn(p, tokens):
    w1, w2 = p
    return jax.nn.relu(tokens @ w1) @ w2


def test_top1_dispatch_shapes_and_capacity():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(16, 4).astype(np.float32))
    dispatch, combine, aux = top1_dispatch(logits, capacity=3)
    assert dispatch.shape == (16, 4, 3)
    # every slot holds at most one token
    assert float(dispatch.sum(axis=0).max()) <= 1.0
    # each kept token has exactly one slot; dropped tokens none
    per_token = dispatch.sum(axis=(1, 2))
    assert set(np.asarray(per_token).tolist()) <= {0.0, 1.0}
    assert float(aux) > 0


def test_moe_matches_local_reference():
    n_shards, e_local, d, t = 4, 2, 8, 16
    e_total = n_shards * e_local
    mesh = build_mesh({EXPERT_AXIS: n_shards},
                      devices=jax.devices()[:n_shards])
    rng = np.random.RandomState(5)
    router = jnp.asarray(rng.randn(d, e_total).astype(np.float32) * 0.3)
    w1 = jnp.asarray(rng.randn(e_total, d, 2 * d).astype(np.float32) * 0.3)
    w2 = jnp.asarray(rng.randn(e_total, 2 * d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))

    # big capacity so nothing drops -> exact comparison possible
    cap_factor = float(e_total)  # capacity == t

    def inner(router, w1, w2, x):
        y, aux = expert_parallel_moe(
            router, (w1, w2), x, expert_fn,
            axis_name=EXPERT_AXIS, capacity_factor=cap_factor)
        return y, aux

    y, aux = jax.jit(shard_map_fn(
        inner, mesh=mesh,
        in_specs=(P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P()),
        out_specs=(P(), P()),
        check_vma=False,
    ))(router, w1, w2, x)

    # dense reference: every token through its argmax expert, gate-scaled
    gates = jax.nn.softmax(x @ router, axis=-1)
    idx = np.asarray(jnp.argmax(gates, axis=-1))
    ref = np.zeros((t, d), np.float32)
    for i in range(t):
        e = idx[i]
        ref[i] = float(gates[i, e]) * np.asarray(
            expert_fn((w1[e], w2[e]), x[i:i + 1])[0])
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)
    assert np.isfinite(float(aux))


def test_moe_capacity_drops_tokens():
    # tiny capacity: overflow tokens must come back as zeros, not garbage
    n_shards, e_local, d, t = 2, 1, 4, 12
    mesh = build_mesh({EXPERT_AXIS: n_shards},
                      devices=jax.devices()[:n_shards])
    rng = np.random.RandomState(7)
    router = jnp.asarray(np.zeros((d, 2), np.float32))  # uniform gates
    router = router.at[0, 0].set(5.0)  # push everyone to expert 0
    w1 = jnp.asarray(rng.randn(2, d, d).astype(np.float32))
    w2 = jnp.asarray(rng.randn(2, d, d).astype(np.float32))
    x = jnp.asarray(np.abs(rng.randn(t, d)).astype(np.float32))

    def inner(router, w1, w2, x):
        return expert_parallel_moe(
            router, (w1, w2), x, expert_fn,
            axis_name=EXPERT_AXIS, capacity_factor=0.5)[0]

    y = jax.jit(shard_map_fn(
        inner, mesh=mesh,
        in_specs=(P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P()),
        out_specs=P(),
        check_vma=False,
    ))(router, w1, w2, x)
    y = np.asarray(y)
    # capacity = ceil(12/2*0.5)=3 slots on expert 0 -> ≥ t-3-... some rows 0
    zero_rows = (np.abs(y).sum(axis=1) == 0).sum()
    assert zero_rows >= t - 4


def test_pipeline_fewer_microbatches_than_stages():
    n_stages, d, mb, n_micro = 4, 6, 2, 2
    mesh = build_mesh({PIPELINE_AXIS: n_stages},
                      devices=jax.devices()[:n_stages])
    stages = _stages(n_stages, d, seed=9)
    stacked = make_stage_params(stages)
    x = jnp.asarray(
        np.random.RandomState(9).randn(n_micro, mb, d).astype(np.float32))
    out = jax.jit(functools.partial(_pipe_run, mesh, n_stages=n_stages))(
        stacked, x)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_grad_flows_to_experts_and_router():
    n_shards, e_local, d, t = 2, 2, 4, 8
    e_total = n_shards * e_local
    mesh = build_mesh({EXPERT_AXIS: n_shards},
                      devices=jax.devices()[:n_shards])
    rng = np.random.RandomState(11)
    router = jnp.asarray(rng.randn(d, e_total).astype(np.float32) * 0.3)
    w1 = jnp.asarray(rng.randn(e_total, d, d).astype(np.float32) * 0.3)
    w2 = jnp.asarray(rng.randn(e_total, d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))

    smapped = shard_map_fn(
        lambda r, a, b, xx: expert_parallel_moe(
            r, (a, b), xx, expert_fn, axis_name=EXPERT_AXIS,
            capacity_factor=float(e_total)),
        mesh=mesh,
        in_specs=(P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )

    def loss(r, a, b):
        y, aux = smapped(r, a, b, x)
        return (y ** 2).sum() + 0.01 * aux

    gr, ga, gb = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(router, w1, w2)
    assert np.isfinite(np.asarray(gr)).all()
    # experts that received tokens must have nonzero grads
    assert float(jnp.abs(ga).sum()) > 0
    assert float(jnp.abs(gb).sum()) > 0
    # router grad flows through combine weights
    assert float(jnp.abs(gr).sum()) > 0


def test_top2_moe_matches_dense_mixture():
    """Top-2 (GShard default): with ample capacity, each token's output is
    the pair-renormalized mixture of its two best experts — checked against
    a dense per-token oracle through the sharded all_to_all path."""
    from horovod_tpu.parallel import top2_dispatch  # noqa: F401 (export)

    n_shards, e_local, d, t = 4, 2, 8, 16
    e_total = n_shards * e_local
    mesh = build_mesh({EXPERT_AXIS: n_shards},
                      devices=jax.devices()[:n_shards])
    rng = np.random.RandomState(7)
    router = jnp.asarray(rng.randn(d, e_total).astype(np.float32) * 0.3)
    w1 = jnp.asarray(rng.randn(e_total, d, 2 * d).astype(np.float32) * 0.3)
    w2 = jnp.asarray(rng.randn(e_total, 2 * d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))
    cap_factor = float(e_total)  # capacity == t, nothing drops

    def inner(router, w1, w2, x):
        return expert_parallel_moe(
            router, (w1, w2), x, expert_fn,
            axis_name=EXPERT_AXIS, capacity_factor=cap_factor,
            routing="top2")

    y, aux = jax.jit(shard_map_fn(
        inner, mesh=mesh,
        in_specs=(P(), P(EXPERT_AXIS), P(EXPERT_AXIS), P()),
        out_specs=(P(), P()),
        check_vma=False,
    ))(router, w1, w2, x)

    gates = np.asarray(jax.nn.softmax(x @ router, axis=-1))
    ref = np.zeros((t, d), np.float32)
    for i in range(t):
        order = np.argsort(-gates[i])
        e1, e2 = int(order[0]), int(order[1])
        g1, g2 = gates[i, e1], gates[i, e2]
        s = g1 + g2
        ref[i] = (
            g1 / s * np.asarray(expert_fn((w1[e1], w2[e1]), x[i:i+1])[0])
            + g2 / s * np.asarray(expert_fn((w1[e2], w2[e2]), x[i:i+1])[0])
        )
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)
    assert np.isfinite(float(aux))


def test_top2_capacity_drops_second_choices_first():
    """Under pressure, second choices drop before first choices (their
    buffer positions come after all first choices)."""
    from horovod_tpu.parallel import top2_dispatch

    t, e, cap = 6, 2, 6  # everyone: first choice e0, second e1
    logits = jnp.asarray(
        np.tile(np.array([[3.0, 1.0]], np.float32), (t, 1)))
    dispatch, combine, aux = top2_dispatch(logits, capacity=cap)
    # all 6 first choices (expert 0) kept; all 6 second choices fit too
    assert float(dispatch[:, 0].sum()) == t
    assert float(dispatch[:, 1].sum()) == t
    d2, _, _ = top2_dispatch(logits, capacity=3)
    # capacity 3: three first choices kept on expert 0, three seconds on e1
    assert float(d2[:, 0].sum()) == 3.0
    assert float(d2[:, 1].sum()) == 3.0

    # mixed: token 0..2 prefer e0 then e1; 3..5 prefer e1 then e0, cap 4:
    # each expert holds its 3 first choices + 1 second choice
    logits_m = jnp.asarray(np.array(
        [[3.0, 1.0]] * 3 + [[1.0, 3.0]] * 3, np.float32))
    dm, _, _ = top2_dispatch(logits_m, capacity=4)
    assert float(dm[:, 0].sum()) == 4.0 and float(dm[:, 1].sum()) == 4.0
    # the dropped seconds are the LAST tokens of each group
    assert float(dm[2, 1].sum()) == 0.0  # token 2's second choice dropped
    assert float(dm[5, 0].sum()) == 0.0


def test_top2_gradients_flow():
    from horovod_tpu.parallel import top2_dispatch

    def loss(logits):
        d, c, aux = top2_dispatch(logits, capacity=4)
        return jnp.sum(c) + aux

    g = jax.grad(loss)(jnp.asarray(
        np.random.RandomState(0).randn(8, 4).astype(np.float32)))
    assert np.isfinite(np.asarray(g)).all()


def test_moe_bad_routing_raises():
    import pytest as _pytest

    mesh = build_mesh({EXPERT_AXIS: 4}, devices=jax.devices()[:4])

    def inner(x):
        y, aux = expert_parallel_moe(
            jnp.zeros((8, 8)), (jnp.zeros((2, 8, 8)), jnp.zeros((2, 8, 8))),
            x, expert_fn, axis_name=EXPERT_AXIS, routing="top3")
        return y

    with _pytest.raises(ValueError, match="top1.*top2"):
        jax.jit(shard_map_fn(
            inner, mesh=mesh, in_specs=(P(),), out_specs=P(),
            check_vma=False,
        ))(jnp.zeros((8, 8)))
