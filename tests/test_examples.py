"""Examples-as-smoke-tests, the reference CI's pattern
(``.buildkite/gen-pipeline.sh:145-192`` runs every example script). Each
example runs as a subprocess on the virtual CPU mesh with tiny settings."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=420, check=True, cwd=None):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    if cwd is None and script.startswith("jax"):
        cwd = _REPO
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=cwd,
    )
    if not check:
        return proc
    assert proc.returncode == 0, (
        f"{script} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    return proc.stdout


def test_jax_mnist_example(tmp_path):
    out = _run("jax_mnist.py", "--epochs", "1", "--batch-size", "64",
               "--limit-steps", "3", "--checkpoint-dir", str(tmp_path))
    assert "loss" in out.lower()


def test_transformer_long_context_example():
    out = _run("transformer_long_context.py", "--seq-len", "256",
               "--steps", "2", "--depth", "2", "--dim", "64", "--dp", "2",
               "--vocab", "512")
    assert "tokens/s" in out


def test_pipeline_example():
    out = _run("jax_pipeline_transformer.py", "--steps", "4", "--dim", "32",
               "--hidden", "64", "--n-micro", "4", "--micro-batch", "4")
    assert "interleaved" in out and "ms/step" in out


def test_adasum_example():
    out = _run("adasum_small_model.py")
    assert "adasum" in out.lower()


def test_tf2_synthetic_benchmark_example():
    out = _run("tensorflow2_synthetic_benchmark.py", "--model", "tiny",
               "--batch-size", "8", "--num-warmup-batches", "1",
               "--num-batches-per-iter", "1", "--num-iters", "2",
               "--fp16-allreduce")
    assert "img/sec per worker" in out.lower()


def test_transformer_lm_benchmark_example():
    """tokens/s + (hardware-only) MFU harness for the transformer stack;
    8 virtual chips, flash attention + GQA exercised."""
    import json

    out = _run("transformer_lm_benchmark.py", "--dim", "32", "--depth", "2",
               "--heads", "4", "--kv-heads", "2", "--seq-len", "64",
               "--batch", "1", "--steps", "2", "--warmup", "1", "--flash")
    line = next(ln for ln in out.splitlines() if ln.startswith("{"))
    result = json.loads(line)
    assert result["metric"] == "transformer_lm_tokens_per_sec_per_chip"
    assert result["n_chips"] == 8 and result["value"] > 0
    assert result["flash"] is True


@pytest.mark.slow
def test_keras_mnist_example(tmp_path):
    # tmp cwd: the example writes its Keras checkpoint into the working dir
    out = _run("tensorflow2_keras_mnist.py", "--synthetic", "--epochs", "1",
               cwd=str(tmp_path))
    assert "warmup" in out.lower() or "epoch" in out.lower()


def test_transformer_lm_decode_benchmark():
    import json

    out = _run("transformer_lm_benchmark.py", "--mode", "decode",
               "--dim", "32", "--depth", "2", "--heads", "4",
               "--seq-len", "48", "--prompt-len", "32", "--batch", "1",
               "--steps", "1")
    result = json.loads(next(
        ln for ln in out.splitlines() if ln.startswith("{")))
    assert result["metric"] == "transformer_lm_decode_tokens_per_sec"
    assert result["new_tokens"] == 16 and result["value"] > 0


@pytest.mark.slow  # ~90 s 3-subprocess soak; resume/ckpt logic unit-covered in test_checkpoint/test_resilience
def test_imagenet_resnet50_example_with_resume(tmp_path):
    """Flagship end-to-end example (reference pytorch_imagenet_resnet50):
    train, async-checkpoint, then a second invocation resumes."""
    ck = str(tmp_path / "ck")
    out = _run("jax_imagenet_resnet50.py", "--epochs", "2",
               "--arch", "resnet18", "--batch-size", "1",
               "--image-size", "32", "--synthetic-examples", "64",
               "--limit-steps", "6", "--checkpoint-dir", ck,
               "--checkpoint-every", "3", "--fp16-allreduce",
               "--error-feedback", timeout=600)
    assert "done at step 6" in out
    out = _run("jax_imagenet_resnet50.py", "--epochs", "2",
               "--arch", "resnet18", "--batch-size", "1",
               "--image-size", "32", "--synthetic-examples", "64",
               "--limit-steps", "8", "--checkpoint-dir", ck,
               "--fp16-allreduce", "--error-feedback", timeout=600)
    assert "resumed from step 6" in out
    assert "done at step 8" in out

    # resuming with different optimizer flags must fail with a clear
    # message (the opt_state structure depends on them), not an opaque
    # optax crash
    proc = _run("jax_imagenet_resnet50.py", "--epochs", "2",
                "--arch", "resnet18", "--batch-size", "1",
                "--image-size", "32", "--synthetic-examples", "64",
                "--limit-steps", "9", "--checkpoint-dir", ck,
                timeout=600, check=False)
    assert proc.returncode != 0
    assert "resume with the same flags" in proc.stderr


def test_core_microbench_example():
    out = _run("core_microbench.py", "--tensors", "4", "--elems", "64",
               "--steps", "5")
    assert "fusion speedup" in out and "steps/s" in out


@pytest.mark.slow  # ~35 s subprocess e2e; tf frontend unit-covered in test_tensorflow/test_keras
def test_tf2_mnist_example(tmp_path):
    # tmp cwd: the example saves tf2_mnist_ckpt-* into the working dir
    out = _run("tensorflow2_mnist.py", "--synthetic", "--steps", "6",
               "--batch-size", "32", cwd=str(tmp_path))
    assert "loss" in out


@pytest.mark.slow  # ~24 s subprocess e2e; torch frontend unit-covered in test_torch
def test_pytorch_mnist_example():
    out = _run("pytorch_mnist.py", "--epochs", "1", "--batch-size", "256")
    assert "epoch 0: loss=" in out


@pytest.mark.slow  # ~24 s subprocess benchmark soak; torch allreduce path unit-covered in test_torch
def test_pytorch_synthetic_benchmark_example():
    out = _run("pytorch_synthetic_benchmark.py", "--batch-size", "4",
               "--num-iters", "2", "--num-warmup", "1")
    assert "Img/sec per rank" in out


@pytest.mark.slow  # ~28 s subprocess microbench soak; dlpack interop covered by the tf frontend tests
def test_tf2_dlpack_microbench_example():
    out = _run("tensorflow2_dlpack_microbench.py", "--size-mb", "0.25",
               "--iters", "5")
    assert "us/op" in out


@pytest.mark.slow  # ~92 s bench-ladder soak; rung argv parsing stays tier-1 in test_bench_merge
def test_e2e_control_plane_bench_example():
    """Tiny run of the control-plane e2e benchmark (examples double as the
    reference-CI-style smoke layer; full numbers live in docs/performance.md)."""
    import json

    out = _run("e2e_control_plane_bench.py", "--steps", "2", "--filters", "8",
               "--image-size", "32", "--batch-per-dev", "1", timeout=560)
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["metric"] == "control_plane_e2e"
    assert rec["n_grad_tensors"] >= 100
    assert rec["core_steps_per_sec"] > 0
