"""Elastic-aware deterministic input pipeline (ISSUE 15): the global
sample index's purity contract, cursor checkpoint/resume, NumericsRollback
fresh-batch replay, elastic exactly-once resharding, shard-store CRC
quarantine, prefetch-watchdog stall detection, and input-side straggler
attribution — all driven deterministically on the 8-device CPU mesh
(``pytest -m data``). Semantics: docs/data.md."""

import os
import re

import numpy as np
import pytest

from horovod_tpu.data import (
    ArrayShardStore,
    DataUnavailableError,
    GlobalSampleIndex,
    ResumableLoader,
    mix_seed,
    sampler,
    shard_indices,
)
from horovod_tpu.observability import metrics, straggler
from horovod_tpu.resilience import chaos, health, numerics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.data


@pytest.fixture(autouse=True)
def _fresh_data_plane():
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    numerics.reset()
    straggler.reset()
    sampler.reset()
    yield
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.reset()
    numerics.reset()
    straggler.reset()
    sampler.reset()


def _xy(n, feat=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, feat).astype(np.float32)
    y = np.arange(n, dtype=np.int32)  # labels ARE indices: draws visible
    return x, y


# -------------------------------------------------------- seed mixing


def test_mix_seed_no_epoch_seed_collision():
    """Satellite regression: RandomState(seed + epoch) made (seed=0,
    epoch=1) and (seed=1, epoch=0) identical streams; the hash mix must
    not."""
    assert mix_seed(0, 1) != mix_seed(1, 0)
    assert mix_seed(0, 0, 1) != mix_seed(0, 1, 0)
    assert mix_seed(0, 0, 1) != mix_seed(1, 0, 0)
    # and the fix reaches shard_indices / the epoch permutation
    a = shard_indices(101, rank=0, size=4, seed=0, epoch=1)
    b = shard_indices(101, rank=0, size=4, seed=1, epoch=0)
    assert not np.array_equal(a, b)
    # replay_epoch reshuffles the SAME epoch
    r0 = shard_indices(101, rank=0, size=4, seed=0, epoch=0)
    r1 = shard_indices(101, rank=0, size=4, seed=0, epoch=0,
                       replay_epoch=1)
    assert not np.array_equal(r0, r1)
    assert sorted(set(np.concatenate([
        shard_indices(101, rank=r, size=4, seed=0, epoch=0,
                      replay_epoch=1) for r in range(4)
    ]).tolist())) == list(range(101))


def test_mix_seed_deterministic():
    assert mix_seed(7, 3, 2) == mix_seed(7, 3, 2)
    assert 0 <= mix_seed(7, 3, 2) < 2 ** 32


# -------------------------------------------------- global sample index


def test_global_sample_index_purity_and_partition():
    gsi = GlobalSampleIndex(96, 24, seed=3)
    assert gsi.steps_per_epoch == 4
    # pure + deterministic
    np.testing.assert_array_equal(
        gsi.batch_indices(1, 2), GlobalSampleIndex(
            96, 24, seed=3).batch_indices(1, 2))
    # steps partition the selected epoch window
    allv = np.concatenate([gsi.batch_indices(0, s) for s in range(4)])
    assert sorted(allv.tolist()) == list(range(96))
    # rank slices partition each batch, at EVERY world size that divides
    b = gsi.batch_indices(0, 1)
    for size in (2, 3, 4, 6, 8, 12, 24):
        parts = [gsi.rank_indices(0, 1, r, size) for r in range(size)]
        assert sorted(np.concatenate(parts).tolist()) == sorted(b.tolist())
    # the GLOBAL batch never depends on the world size — the elastic
    # repartition invariant
    with pytest.raises(ValueError, match="divide"):
        gsi.rank_indices(0, 0, 0, 5)
    with pytest.raises(IndexError):
        gsi.batch_indices(0, 4)


def test_global_sample_index_replay_epoch_diverges():
    gsi = GlobalSampleIndex(64, 16, seed=0)
    a = gsi.batch_indices(2, 1, replay_epoch=0)
    b = gsi.batch_indices(2, 1, replay_epoch=1)
    assert not np.array_equal(a, b)
    # both still draw from the full epoch
    for replay in (0, 1):
        allv = np.concatenate(
            [gsi.batch_indices(2, s, replay) for s in range(4)])
        assert sorted(allv.tolist()) == list(range(64))


def test_global_sample_index_stream_and_advance():
    gsi = GlobalSampleIndex(32, 16, seed=1)
    keys = [(e, s) for e, s, _ in gsi.stream(0, 1, num_steps=4)]
    assert keys == [(0, 1), (1, 0), (1, 1), (2, 0)]
    assert gsi.advance(0, 1) == (1, 0)


# ------------------------------------------------------ resumable loader


def test_resumable_loader_matches_pure_index(hvd):
    n, bs = 96, 24
    x, y = _xy(n)
    gsi = GlobalSampleIndex(n, bs, seed=3)
    ref = [idx.tolist() for _, _, idx in gsi.stream(0, 0, num_steps=6)]
    loader = ResumableLoader((x, y), bs, seed=3, prefetch=2, name="pure")
    try:
        seen = []
        for _ in range(6):
            xb, yb = loader.next_batch()
            assert xb.shape == (bs, 4)
            assert xb.sharding.spec[0] is not None  # sharded over data
            idx = np.asarray(yb).tolist()
            np.testing.assert_array_equal(np.asarray(xb), x[idx])
            seen.append(idx)
        assert seen == ref
        # cursor crossed the epoch boundary: 4 steps/epoch
        assert loader.state()["epoch"] == 1
        assert loader.state()["step"] == 2
        # metrics moved
        assert metrics.value("input_batches") == 6.0
        assert metrics.value("data_cursor_epoch") == 1.0
    finally:
        loader.close()


def test_resumable_loader_restore_is_exact(hvd):
    """Cold restart: a FRESH loader restored to a mid-epoch cursor draws
    the identical remaining stream."""
    n, bs = 64, 16
    x, y = _xy(n)
    gsi = GlobalSampleIndex(n, bs, seed=11)
    ref = [idx.tolist() for _, _, idx in gsi.stream(0, 0, num_steps=8)]
    a = ResumableLoader((x, y), bs, seed=11, prefetch=2, name="a")
    head = [np.asarray(a.next_batch()[1]).tolist() for _ in range(5)]
    cursor = a.state()
    a.close()
    b = ResumableLoader((x, y), bs, seed=11, prefetch=0, name="b")
    b.restore(cursor)
    tail = [np.asarray(b.next_batch()[1]).tolist() for _ in range(3)]
    b.close()
    assert head + tail == ref


def test_resumable_loader_per_rank_mode_partitions():
    n, bs = 48, 12
    x, y = _xy(n)
    loaders = [
        ResumableLoader((x, y), bs, seed=2, rank=r, size=3, prefetch=0,
                        name=f"r{r}", register=False)
        for r in range(3)
    ]
    gsi = GlobalSampleIndex(n, bs, seed=2)
    for s in range(4):
        slices = []
        for ld in loaders:
            _, yb = ld.next_batch()
            assert yb.shape == (bs // 3,)
            slices.append(np.asarray(yb))
        assert sorted(np.concatenate(slices).tolist()) == \
            sorted(gsi.batch_indices(0, s).tolist())
    for ld in loaders:
        ld.close()


def test_resumable_loader_reshard_mid_epoch_exactly_once():
    """The per-rank repartition drill: 2 ranks consume half the epoch,
    then 'resize' to 1 survivor that re-binds (same cursor) and consumes
    the rest — union == epoch, no duplicates."""
    n, bs = 64, 16
    x, y = _xy(n)
    l0 = ResumableLoader((x, y), bs, seed=9, rank=0, size=2, prefetch=0,
                         name="re0", register=False)
    l1 = ResumableLoader((x, y), bs, seed=9, rank=1, size=2, prefetch=0,
                         name="re1", register=False)
    visited = []
    for _ in range(2):  # steps 0..1 at world 2
        for ld in (l0, l1):
            visited.extend(np.asarray(ld.next_batch()[1]).tolist())
    l0.reshard(rank=0, size=1, generation=2)
    for _ in range(2):  # steps 2..3 at world 1: full batches
        _, yb = l0.next_batch()
        assert yb.shape == (bs,)
        visited.extend(np.asarray(yb).tolist())
    assert sorted(visited) == list(range(n))
    with pytest.raises(RuntimeError, match="per-rank"):
        ResumableLoader((x, y), bs, prefetch=0, name="glob",
                        register=False).reshard(rank=0, size=1)
    l0.close()
    l1.close()


def test_loader_registry_pending_cursor_applies_on_register():
    """Cold-restart ordering: restore the checkpoint FIRST, build the
    loader after — the pending cursor applies at register time."""
    sampler.restore_state({"late": {"epoch": 2, "step": 1, "seed": 5}})
    x, y = _xy(32)
    ld = ResumableLoader((x, y), 16, seed=5, prefetch=0, name="late")
    try:
        assert ld.cursor() == (2, 1)
        assert sampler.export_state()["late"]["epoch"] == 2
    finally:
        ld.close()


# ------------------------------------------- acceptance: kill/resume


@pytest.mark.chaos
def test_kill_resume_mid_epoch_identical_remaining_stream(hvd, tmp_path):
    """Acceptance drill (ISSUE 15): train with checkpointing, SIGTERM
    mid-epoch, cold-restart resume — the remaining sample stream is
    IDENTICAL to an uninterrupted run, by exact index comparison."""
    from horovod_tpu.resilience import loop as rloop

    n, bs = 64, 16  # 4 steps/epoch; kill at step 5 = epoch 1, step 1
    x, y = _xy(n)
    ckpt = str(tmp_path / "ckpt")
    gsi = GlobalSampleIndex(n, bs, seed=11)
    ref = [idx.tolist() for _, _, idx in gsi.stream(0, 0, num_steps=8)]

    seen = []
    ld = ResumableLoader((x, y), bs, seed=11, prefetch=2, name="resume")
    chaos.configure("sigterm_at_step=5")

    def step_fn(state, i):
        _, yb = ld.next_batch()
        seen.append(np.asarray(yb).tolist())
        return state + 1

    with pytest.raises(SystemExit) as ei:
        rloop.run(step_fn, np.zeros(1), num_steps=8, checkpoint_dir=ckpt)
    assert ei.value.code == rloop.RESUMABLE_EXIT_CODE
    ld.close()
    assert seen == ref[:5]

    # cold restart: fresh registry, fresh loader, cursor restored from
    # the emergency checkpoint's data_cursor payload
    sampler.reset()
    chaos.configure(None)
    resumed = rloop.resume_state(ckpt)
    assert resumed is not None and resumed[0] == 5
    ld2 = ResumableLoader((x, y), bs, seed=11, prefetch=2, name="resume")
    assert ld2.cursor() == (1, 1)
    seen2 = []

    def step_fn2(state, i):
        _, yb = ld2.next_batch()
        seen2.append(np.asarray(yb).tolist())
        return state + 1

    rloop.run(step_fn2, np.zeros(1), num_steps=8, start_step=resumed[0])
    ld2.close()
    assert seen2 == ref[5:], "resumed stream diverged from the reference"


# ------------------------------- acceptance: numerics rollback replay


@pytest.mark.chaos
@pytest.mark.numerics
def test_numerics_rollback_replays_with_fresh_batches(hvd, monkeypatch):
    """Acceptance drill (ISSUE 15): a PR-9 NumericsRollback bumps the
    replay epoch; the replayed steps draw DIFFERENT (fresh) batches than
    the poisoned attempt — both pinned by exact index comparison — while
    the cursor rewinds with the committed snapshot."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.resilience import elastic
    from horovod_tpu.training import (
        make_shardmap_train_step, replicate, softmax_xent,
    )

    monkeypatch.setenv("HOROVOD_NUMERICS_MAX_BAD", "2")

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(2)(x)

    n, bs = 96, 16
    x, y = _xy(n, feat=8)
    y = (y % 2).astype(np.int32)
    ld = ResumableLoader((x, y), bs, seed=5, prefetch=2, name="numerics")
    model = Tiny()
    draws = []  # (step, replay_epoch, indices)

    def builder(world):
        tx = hvd.DistributedOptimizer(
            optax.adam(1e-2), shard_optimizer=True, numerics_guard=True)
        step = make_shardmap_train_step(
            model, tx, loss_fn=softmax_xent, shard_optimizer=True,
            instrument=False, donate=False)

        def step_fn(state, i):
            xb, yb = ld.next_batch()
            replay = ld.last_key[2]
            draws.append((i, replay, ld.last_indices.tolist()))
            xh = np.asarray(xb)
            if replay == 0 and i >= 3:
                xh = xh * np.nan  # the poisoned-data incident
            p, _, st, _ = step(state["params"], {}, state["opt_state"],
                               jnp.asarray(xh), yb)
            return {"params": p, "opt_state": st}

        return step_fn

    try:
        params0 = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
        tx0 = hvd.DistributedOptimizer(
            optax.adam(1e-2), shard_optimizer=True, numerics_guard=True)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        state = {"params": params, "opt_state": tx0.init(params)}
        out = elastic.run(builder, state, num_steps=6, snapshot_every=1)
        assert numerics.replay_epoch() == 1
        poisoned = {i: idx for i, r, idx in draws if r == 0}
        replayed = {i: idx for i, r, idx in draws if r == 1}
        # the rollback replayed the bad steps...
        assert 3 in poisoned and 3 in replayed
        # ...with genuinely FRESH batches (exact index comparison)...
        for i in replayed:
            if i in poisoned:
                assert replayed[i] != poisoned[i], i
        # ...that still come from the same epoch's sample set
        gsi = GlobalSampleIndex(n, bs, seed=5)
        assert replayed[3] == gsi.batch_indices(
            0, 3, replay_epoch=1).tolist()
        assert numerics.tree_finite(out["params"])
    finally:
        ld.close()


# ------------------------------------ acceptance: elastic exactly-once


@pytest.mark.chaos
@pytest.mark.elastic
def test_elastic_resize_mid_epoch_exactly_once(hvd):
    """Acceptance drill (ISSUE 15): 8→6 resize mid-epoch under
    HOROVOD_CHAOS=rank_fail=2 — the committed sample stream's union over
    the epoch equals the full epoch with no duplicates, the replayed
    step re-draws IDENTICAL indices (same replay epoch), the stream is
    pinned against a fresh same-seed run, and the loader is generation-
    fenced with the mesh."""
    from horovod_tpu.resilience import elastic

    chaos.configure("rank_fail=2,rank_fail_at_step=2")
    n, bs = 96, 24  # divides by 8 AND 6; 4 steps = one epoch
    x, y = _xy(n)
    ld = ResumableLoader((x, y), bs, seed=7, prefetch=2, name="elastic")
    draws = []   # every raw draw (step, indices, world)
    final = {}   # last draw per step = the committed logical stream

    def builder(world):
        def step_fn(state, i):
            _, yb = ld.next_batch()
            idx = np.asarray(yb).tolist()
            draws.append((i, idx, world))
            final[i] = idx
            return {"w": state["w"] + 1.0}

        return step_fn

    try:
        # snapshot_every=2: the resize at step 2's boundary rolls back to
        # committed step 2 == the boundary — and a second drill variant
        # below exercises a real replay
        elastic.run(builder, {"w": np.zeros(1)}, num_steps=4,
                    snapshot_every=1)
        worlds = sorted({w for _, _, w in draws})
        assert worlds == [6, 8], "resize did not happen"
        # exactly-once over the epoch on the committed stream
        allv = [v for i in range(4) for v in final[i]]
        assert sorted(allv) == list(range(n))
        # pinned against a fresh same-seed run
        gsi = GlobalSampleIndex(n, bs, seed=7)
        for i in range(4):
            assert final[i] == gsi.batch_indices(0, i).tolist()
        # any replayed step re-drew the SAME indices (no replay salt)
        from collections import Counter

        for i, k in Counter(i for i, _, _ in draws).items():
            if k > 1:
                assert len({tuple(idx) for j, idx, _ in draws
                            if j == i}) == 1
        # generation fence: loader moved with the mesh epoch
        assert ld.state()["generation"] == 2
        assert metrics.value("data_generation") == 2.0
    finally:
        ld.close()


@pytest.mark.chaos
@pytest.mark.elastic
def test_elastic_rollback_replay_redraws_identical_batches(hvd):
    """With sparse commits the resize REPLAYS steps: the loader cursor
    rewinds with the snapshot, so the replayed draw is bit-identical to
    the original (same (epoch, step, replay) key) — the exactly-once
    guarantee is over the logical stream, not raw read counts."""
    from collections import Counter

    from horovod_tpu.resilience import elastic

    chaos.configure("rank_fail=2,rank_fail_at_step=3")
    n, bs = 96, 24
    x, y = _xy(n)
    ld = ResumableLoader((x, y), bs, seed=13, prefetch=2, name="replay")
    draws = []

    def builder(world):
        def step_fn(state, i):
            _, yb = ld.next_batch()
            draws.append((i, np.asarray(yb).tolist()))
            return {"w": state["w"] + 1.0}

        return step_fn

    try:
        elastic.run(builder, {"w": np.zeros(1)}, num_steps=4,
                    snapshot_every=2)
        counts = Counter(i for i, _ in draws)
        replayed = [i for i, k in counts.items() if k > 1]
        assert replayed, "expected a replay with snapshot_every=2"
        for i in replayed:
            assert len({tuple(idx) for j, idx in draws if j == i}) == 1, \
                "replayed step drew different indices"
    finally:
        ld.close()


# ------------------------------------------------- shard store / chaos


def test_shard_store_roundtrip_and_crc(tmp_path):
    x, y = _xy(50)
    manifest = ArrayShardStore.write(str(tmp_path), (x, y), 16)
    assert [s["rows"] for s in manifest["shards"]] == [16, 16, 16, 2]
    store = ArrayShardStore(str(tmp_path))
    assert store.n_rows == 50 and store.n_shards == 4
    xs, ys = store.gather([0, 17, 33, 49])
    np.testing.assert_array_equal(ys, [0, 17, 33, 49])
    np.testing.assert_array_equal(xs, x[[0, 17, 33, 49]])
    assert store.shard_of(15) == 0 and store.shard_of(16) == 1
    with pytest.raises(IndexError):
        store.gather([50])
    # a loader runs straight off the store (host mode)
    ld = ResumableLoader(store, 10, seed=1, prefetch=0, device=False,
                         name="store", register=False)
    xb, yb = ld.next_batch()
    np.testing.assert_array_equal(xb, x[np.asarray(yb)])
    ld.close()


@pytest.mark.chaos
def test_shard_corrupt_quarantine_drill(tmp_path, hvd):
    """Acceptance drill (ISSUE 15): shard_corrupt → CRC mismatch →
    retries → quarantine; training CONTINUES past the shard with the
    substitution surfaced in metrics and health — never silently
    ignored, never a crash."""
    from horovod_tpu.observability import flight

    n, bs = 96, 24
    x, y = _xy(n)
    ArrayShardStore.write(str(tmp_path), (x, y), 16)
    chaos.configure("shard_corrupt=2:0")
    store = ArrayShardStore(str(tmp_path))
    ld = ResumableLoader(store, bs, seed=4, prefetch=2, name="corrupt")
    try:
        seen = []
        for _ in range(4):  # the full epoch: training continues
            xb, yb = ld.next_batch()
            assert xb.shape == (bs, 4)
            seen.extend(np.asarray(yb).tolist())
        assert store.quarantined() == [2]
        # the shard's rows [32, 48) were substituted, not served
        assert not (set(range(32, 48)) & set(seen))
        assert len(seen) == n  # static batch shapes held
        # surfaced: metrics + health SUSPECT naming the shard + flight
        # (>=: the prefetch thread speculates past the consumed batches)
        assert metrics.value("data_samples_substituted") >= 16.0
        assert metrics.value(
            "resilience_chaos_injected", site="shard_corrupt") >= 1.0
        assert metrics.value("data_quarantined_shards") == 1.0
        assert metrics.value("data_shard_retries", shard=2) >= 2.0
        assert health.health_state() >= health.HealthState.SUSPECT
        assert "shard-00002" in health.MONITOR.reason()
        assert any(
            e.get("event") == "shard_quarantined"
            for e in flight.events() if e["kind"] == "data"
        )
        # deterministic: the same epoch re-drawn substitutes identically
        ld2 = ResumableLoader(store, bs, seed=4, prefetch=0,
                              name="corrupt2", register=False)
        seen2 = []
        for _ in range(4):
            _, yb = ld2.next_batch()
            seen2.extend(np.asarray(yb).tolist())
        assert seen2 == seen
        ld2.close()
    finally:
        ld.close()


def test_all_shards_quarantined_raises(tmp_path):
    x, y = _xy(16)
    ArrayShardStore.write(str(tmp_path), (x, y), 16)  # ONE shard
    chaos.configure("shard_corrupt=0:0")
    store = ArrayShardStore(str(tmp_path))
    with pytest.raises(DataUnavailableError):
        store.gather([0, 1])


# --------------------------------------- data_stall drill + attribution


@pytest.mark.chaos
def test_data_stall_drill_names_rank_input_bound(hvd, monkeypatch):
    """Acceptance drill (ISSUE 15): HOROVOD_CHAOS=data_stall=3:1.0 —
    straggler attribution names rank 3 as *input-bound* (not compute),
    the flight recorder carries the stall event, and health goes
    SUSPECT."""
    from horovod_tpu.observability import flight

    monkeypatch.setenv("HOROVOD_DATA_WATCHDOG", "0.3")
    chaos.configure("data_stall=3:1.0")
    n, bs = 96, 24
    x, y = _xy(n, feat=8)
    ld = ResumableLoader((x, y), bs, seed=0, prefetch=1, name="stall")
    try:
        out = None
        for step in range(3):
            straggler.set_step(step)
            ld.next_batch()
            np.asarray(hvd.allreduce(
                np.ones((8, 8), np.float32), hvd.Sum))
            out = straggler.attribute()
        assert out is not None
        assert out["rank"] == 3
        assert out["cause"] == "input", out
        assert out["spread_seconds"] >= 0.5
        # health: SUSPECT (or DEGRADED if the stall strikes accumulated)
        # with the input-bound cause in the reason
        assert health.health_state() >= health.HealthState.SUSPECT
        assert "rank 3" in health.MONITOR.reason()
        assert "input-bound" in health.MONITOR.reason()
        # watchdog detected the stall (0.3s watchdog vs 1.0s stall)
        assert metrics.value("data_prefetch_stalls") >= 1.0
        assert metrics.value("resilience_input_stalls") >= 1.0
        assert metrics.value(
            "resilience_chaos_injected", site="data_stall") >= 1.0
        # flight recorder carries the stall event
        assert any(
            e.get("event") == "input_stall"
            for e in flight.events() if e["kind"] == "data"
        )
        # wait metrics fed the fleet signal
        assert metrics.value("data_wait_seconds_recent") is not None
    finally:
        ld.close()


def test_compute_bound_straggler_stays_compute(hvd):
    """rank_slow (a slow CHIP) must not be classified input-bound: the
    cause distinction is the whole point."""
    chaos.configure("rank_slow=2:0.08")
    out = None
    for step in range(3):
        straggler.set_step(step)
        np.asarray(hvd.allreduce(np.ones((4, 4), np.float32), hvd.Sum))
        out = straggler.attribute()
    assert out is not None and out["rank"] == 2
    assert out["cause"] == "compute"


def test_fleet_attribution_consumes_published_data_waits():
    """The fleet path: per-rank waits extracted from published snapshots
    classify the straggler input-bound on rank 0 (no local loader)."""
    records = []
    for q in range(3):
        records.append({
            "key": [0, 0, q], "op": "allreduce",
            "arrivals": {"0": 10.0 + q, "1": 10.3 + q},
        })
    merged = straggler.merge_arrival_exports([records])
    out = straggler.attribute(
        merged, expected_ranks=2, data_waits={1: 0.28})
    assert out is not None and out["rank"] == 1
    assert out["cause"] == "input"


# ------------------------------------------------ ShardedLoader fixes


def test_sharded_loader_set_epoch_mid_iteration_raises(hvd):
    from horovod_tpu.data import ShardedLoader

    x = np.ones((32, 2), np.float32)
    loader = ShardedLoader(x, 8, shuffle=False)
    it = iter(loader)
    next(it)
    with pytest.raises(RuntimeError, match="iterator is live"):
        loader.set_epoch(1)
    it.close()
    loader.set_epoch(1)  # fine once the iterator closed


def test_sharded_loader_epoch_snapshot_at_iter(hvd):
    from horovod_tpu.data import ShardedLoader

    x = np.zeros((32, 2), np.float32)
    y = np.arange(32, dtype=np.int32)
    loader = ShardedLoader((x, y), 8, seed=1)
    first = [np.asarray(b[1]).tolist() for b in loader]
    loader.set_epoch(1)
    second = [np.asarray(b[1]).tolist() for b in loader]
    assert first != second
    assert sorted(sum(first, [])) == sorted(sum(second, []))
    # the seed/epoch collision fix reaches ShardedLoader's order too
    a = ShardedLoader((x, y), 8, seed=0)
    a.set_epoch(1)
    b = ShardedLoader((x, y), 8, seed=1)
    assert [np.asarray(t[1]).tolist() for t in a] != \
        [np.asarray(t[1]).tolist() for t in b]


# ------------------------------------------------------ model + hvd_top


def test_input_step_time_model():
    import sys

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    from scaling_projection import input_step_time

    m = input_step_time(0.004, 0.002, 2)
    assert m["serial_s"] == pytest.approx(0.006)
    assert m["overlapped_s"] == pytest.approx(0.004)
    assert m["speedup"] == pytest.approx(1.5)
    assert m["bound"] == "compute"
    assert input_step_time(0.004, 0.002, 0)["speedup"] == 1.0
    assert input_step_time(0.001, 0.005, 4)["bound"] == "input"


def test_hvd_top_input_pane_renders():
    import sys

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import hvd_top

    fleet = {
        "ranks": [0, 1], "dead_ranks": [], "straggler": None,
        "metrics": {
            "data_wait_seconds_recent": {
                "type": "gauge", "help": "", "samples": {"": {
                    "ranks": {"0": 0.001, "1": 0.25},
                    "min": 0.001, "mean": 0.125, "max": 0.25, "p99": 0.25,
                }},
            },
            "input_examples_per_second": {
                "type": "gauge", "help": "", "samples": {"": {
                    "ranks": {"0": 9000.0, "1": 120.0},
                    "min": 120.0, "mean": 4560.0, "max": 9000.0,
                    "p99": 9000.0,
                }},
            },
            "data_quarantined_shards": {
                "type": "gauge", "help": "", "samples": {"": {
                    "ranks": {"0": 1.0}, "min": 1.0, "mean": 1.0,
                    "max": 1.0, "p99": 1.0,
                }},
            },
        },
    }
    text = hvd_top.render(fleet)
    assert "INPUT:" in text
    assert "quarantined shards 1" in text
    assert "per-rank wait" in text
    # and an input-free fleet renders no pane
    assert "INPUT:" not in hvd_top.render(
        {"ranks": [0], "dead_ranks": [], "straggler": None, "metrics": {}})


# --------------------------------------------------- CI/tooling guards


def test_data_env_knobs_documented():
    """Every HOROVOD_DATA_* / HOROVOD_PREFETCH_* env knob named in the
    source must appear in docs/data.md's knob table (the metric-catalog
    guard pattern, PR 7/9/10)."""
    knob_re = re.compile(
        r"HOROVOD_(?:DATA|PREFETCH)_[A-Z]+(?:_[A-Z]+)*")
    knobs = set()
    for dirpath, _dirnames, filenames in os.walk(
            os.path.join(_REPO, "horovod_tpu")):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                knobs |= set(knob_re.findall(f.read()))
    assert {"HOROVOD_DATA_WATCHDOG", "HOROVOD_PREFETCH_BATCHES",
            "HOROVOD_DATA_CACHE_SHARDS"} <= knobs
    with open(os.path.join(_REPO, "docs", "data.md")) as f:
        doc = f.read()
    missing = sorted(k for k in knobs if k not in doc)
    assert not missing, (
        f"env knobs named in code but absent from the docs/data.md "
        f"knob table: {missing}"
    )


@pytest.mark.slow
def test_bench_input_ab_rung():
    """bench.py --input-ab emits one JSON line: a measured ratio plus the
    analytic input_step_time model (the model alone when no device)."""
    import json as _json
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--input-ab", "--iters", "10", "--no-probe"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    d = _json.loads(line)
    assert d["metric"] == "input_ab_step_ratio"
    assert d["input_model"]["serial_s"] > d["input_model"]["overlapped_s"]
    if not d.get("skipped"):
        assert d["value"] > 1.0  # prefetch must win on a 2 ms load cost
        assert d["serial_step_s"] > d["overlapped_step_s"]


def test_data_chaos_charges_parse():
    spec = chaos.parse_spec("data_stall=3:0.5,shard_corrupt=2:1")
    assert spec["data_stall"] == (3, 0.5)
    assert spec["shard_corrupt"] == (2, 1)
    # shard_corrupt's read index defaults to 0
    assert chaos.parse_spec("shard_corrupt=4")["shard_corrupt"] == (4, 0)
    with pytest.raises(ValueError):
        chaos.parse_spec("data_stall=3")
