"""Keras frontend tests (reference ``test/test_keras.py``,
``test/test_tensorflow2_keras.py``): DistributedOptimizer inside
``model.fit``, broadcast/metric/LR callbacks, and ``load_model``
optimizer re-wrapping."""

import numpy as np
import pytest

keras = pytest.importorskip("keras")
tf = pytest.importorskip("tensorflow")

import horovod_tpu.keras as hvd  # noqa: E402


@pytest.fixture()
def khvd():
    hvd.init()
    yield hvd
    hvd.shutdown()


def _tiny_model():
    model = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        keras.layers.Dense(3, activation="relu"),
        keras.layers.Dense(1),
    ])
    return model


def _data(n=32):
    rng = np.random.RandomState(0)
    return rng.randn(n, 4).astype(np.float32), rng.randn(n, 1).astype(
        np.float32)


def test_distributed_optimizer_fit(khvd):
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.01))
    model.compile(optimizer=opt, loss="mse")
    x, y = _data()
    hist = model.fit(x, y, batch_size=8, epochs=2, verbose=0)
    losses = hist.history["loss"]
    assert np.isfinite(losses).all()
    assert losses[-1] <= losses[0] * 1.5  # training happened, didn't blow up


def test_distributed_optimizer_apply_gradients(khvd):
    # custom-loop path: apply_gradients funnels through apply
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.1))
    x, y = _data(8)
    with tf.GradientTape() as tape:
        loss = tf.reduce_mean((model(x) - y) ** 2)
    grads = tape.gradient(loss, model.trainable_variables)
    before = [v.numpy().copy() for v in model.trainable_variables]
    opt.apply_gradients(zip(grads, model.trainable_variables))
    after = [v.numpy() for v in model.trainable_variables]
    assert any(
        not np.allclose(b, a) for b, a in zip(before, after)
    )


def test_callbacks_fit(khvd):
    model = _tiny_model()
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.08, momentum=0.9)
    )
    model.compile(optimizer=opt, loss="mse")
    x, y = _data()
    cbs = [
        hvd.BroadcastGlobalVariablesCallback(0),
        hvd.MetricAverageCallback(),
        hvd.LearningRateWarmupCallback(warmup_epochs=2, steps_per_epoch=4),
    ]
    hist = model.fit(x, y, batch_size=8, epochs=3, verbose=0, callbacks=cbs)
    assert cbs[0].broadcast_done
    # after warmup the LR has ramped (nearly) back to the initial value;
    # the last adjustment happens at batch *begin* of the final warmup batch
    # (fraction (warmup_epochs-1 + (steps-1)/steps)/warmup_epochs), matching
    # the reference's on_batch_begin schedule (_keras/callbacks.py:118-127)
    lr = float(keras.ops.convert_to_numpy(model.optimizer.learning_rate))
    assert 0.08 * 0.8 < lr <= 0.08
    assert all(np.isfinite(v) for v in hist.history["loss"])


def test_lr_schedule_callback(khvd):
    model = _tiny_model()
    model.compile(
        optimizer=hvd.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.1)),
        loss="mse",
    )
    x, y = _data(16)
    cb = hvd.LearningRateScheduleCallback(
        multiplier=lambda epoch: 0.5 ** epoch, start_epoch=0,
        momentum_correction=False,
    )
    model.fit(x, y, batch_size=8, epochs=3, verbose=0, callbacks=[cb])
    lr = float(keras.ops.convert_to_numpy(model.optimizer.learning_rate))
    np.testing.assert_allclose(lr, 0.1 * 0.5 ** 2, rtol=1e-5)


def test_metric_average_callback_values(khvd):
    cb = hvd.MetricAverageCallback()
    logs = {"loss": 2.0, "acc": np.float32(0.5)}
    cb.on_epoch_end(0, logs)
    # replicated semantics: average over identical ranks is the identity
    np.testing.assert_allclose(logs["loss"], 2.0, rtol=1e-6)
    np.testing.assert_allclose(logs["acc"], 0.5, rtol=1e-6)


def test_load_model_rewraps_optimizer(khvd, tmp_path):
    model = _tiny_model()
    model.compile(optimizer=keras.optimizers.Adam(learning_rate=0.003),
                  loss="mse")
    x, y = _data(16)
    model.fit(x, y, batch_size=8, epochs=1, verbose=0)
    path = str(tmp_path / "model.keras")
    model.save(path)

    loaded = hvd.load_model(path)
    from horovod_tpu.keras import _DistributedOptimizerMixin

    assert isinstance(loaded.optimizer, _DistributedOptimizerMixin)
    lr = float(keras.ops.convert_to_numpy(loaded.optimizer.learning_rate))
    np.testing.assert_allclose(lr, 0.003, rtol=1e-5)
    loaded.fit(x, y, batch_size=8, epochs=1, verbose=0)


def test_broadcast_global_variables(khvd):
    model = _tiny_model()
    model.compile(optimizer=keras.optimizers.SGD(0.01), loss="mse")
    hvd.broadcast_global_variables(0, model=model)  # no-op correctness
    assert all(np.isfinite(w.numpy()).all() for w in model.weights)


def test_allreduce_numpy_value(khvd):
    out = hvd.allreduce(np.float32(3.0), op=hvd.Average)
    np.testing.assert_allclose(out, 3.0, rtol=1e-6)


def test_load_model_custom_objects_and_optimizer(khvd, tmp_path):
    """Reference test_keras.py:96-168: load_model with custom optimizer
    classes (shadowed through custom_objects the reference's way) and
    custom objects (here a custom activation)."""

    def myact(x):
        return keras.ops.relu(x) * 1.5

    class MySGD(keras.optimizers.SGD):
        pass

    model = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        keras.layers.Dense(3, activation=myact),
        keras.layers.Dense(1),
    ])
    model.compile(optimizer=MySGD(learning_rate=0.02), loss="mse")
    x, y = _data(16)
    model.fit(x, y, batch_size=8, epochs=1, verbose=0)
    path = str(tmp_path / "model_custom.keras")
    model.save(path)

    loaded = hvd.load_model(
        path, custom_optimizers=[MySGD], custom_objects={"myact": myact})
    from horovod_tpu.keras import _DistributedOptimizerMixin

    assert isinstance(loaded.optimizer, _DistributedOptimizerMixin)
    assert isinstance(loaded.optimizer, MySGD)
    lr = float(keras.ops.convert_to_numpy(loaded.optimizer.learning_rate))
    np.testing.assert_allclose(lr, 0.02, rtol=1e-5)
    # the custom activation survived the round trip and still trains
    loaded.fit(x, y, batch_size=8, epochs=1, verbose=0)
