"""hvdlint rule engine + runtime schedule sanitizer
(``horovod_tpu.analysis``).

Acceptance (ISSUE 8): a seeded defect for every ``HVD0xx`` rule is
caught; the repo self-lints clean (zero unwaived findings) via the same
``tools/hvdlint.py --json`` invocation CI uses; the sanitizer names the
divergent rank AND the first divergent op under
``HOROVOD_CHAOS=schedule_diverge_at_step=K`` on the 8-device CPU mesh,
within one step.
"""

import json
import os
import pathlib
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.analysis.lint import (
    RULES,
    Waiver,
    lint_paths,
    lint_source,
    load_waivers,
)

pytestmark = pytest.mark.analysis

ROOT = pathlib.Path(__file__).resolve().parents[1]


def _rules_of(findings):
    return [f.rule for f in findings]


def _lint(src: str):
    return lint_source(textwrap.dedent(src), "seeded.py")


# --------------------------------------------------------------------------
# seeded defects: one per rule


def test_hvd001_collective_under_rank_guard():
    findings = _lint(
        """
        import horovod_tpu as hvd

        def broken(x):
            if hvd.rank() == 0:
                return hvd.allreduce(x)
            return x
        """
    )
    assert "HVD001" in _rules_of(findings)
    f = next(f for f in findings if f.rule == "HVD001")
    assert "allreduce" in f.message and "rank" in f.message
    assert f.hint  # every finding carries a fix hint


def test_hvd001_rank_dependent_early_exit():
    findings = _lint(
        """
        import horovod_tpu as hvd

        def broken(x):
            if hvd.rank() != 0:
                return x
            y = x * 2
            return hvd.broadcast(y)
        """
    )
    assert "HVD001" in _rules_of(findings)
    assert "early exit" in findings[0].message


def test_hvd001_clean_patterns():
    findings = _lint(
        """
        import horovod_tpu as hvd

        def fine(x):
            y = hvd.allreduce(x)          # unconditional: fine
            if hvd.rank() == 0:
                print("coordinator", y)   # rank-guarded IO: fine
            return y

        def also_fine(x):
            if hvd.rank() != 0:
                return None
            return x * 2                  # no collective after the exit
        """
    )
    assert "HVD001" not in _rules_of(findings)


def test_hvd002_collective_in_data_dependent_loop():
    findings = _lint(
        """
        import horovod_tpu as hvd

        def broken(x, tol):
            while float(x.mean()) > tol:
                x = hvd.allreduce(x)
            return x

        def broken2(x, n):
            for _ in range(int(n.item())):
                x = hvd.allreduce(x)
            return x
        """
    )
    assert _rules_of(findings).count("HVD002") == 2


def test_hvd002_static_loops_clean():
    findings = _lint(
        """
        import horovod_tpu as hvd

        def fine(x):
            for _ in range(10):
                x = hvd.allreduce(x)
            while True:
                x = hvd.allreduce(x)
            return x
        """
    )
    assert "HVD002" not in _rules_of(findings)


def test_hvd003_host_sync_in_jit():
    findings = _lint(
        """
        import jax

        @jax.jit
        def broken(x):
            return float(x.sum())

        def also_broken(x):
            v = x.mean().item()
            return v

        jitted = jax.jit(also_broken)
        """
    )
    rules = _rules_of(findings)
    assert rules.count("HVD003") == 2
    msgs = " | ".join(f.message for f in findings)
    assert "float()" in msgs and ".item()" in msgs


def test_hvd003_outside_jit_clean():
    findings = _lint(
        """
        def driver(x):
            return float(x.sum())  # not traced: a host read is fine
        """
    )
    assert "HVD003" not in _rules_of(findings)


def test_hvd004_wall_clock_and_rng_in_traced_fn():
    findings = _lint(
        """
        import time
        import random
        import numpy as np
        import jax

        @jax.jit
        def broken(x):
            return x * time.time() + random.random() + np.random.rand()
        """
    )
    assert _rules_of(findings).count("HVD004") == 3


def test_hvd005_unguarded_thread_write():
    findings = _lint(
        """
        import threading

        _registry = {}
        _count = 0

        def _loop():
            global _count
            _count += 1                # unguarded global write
            _registry["x"] = _count    # unguarded item write

        t = threading.Thread(target=_loop)
        """
    )
    assert _rules_of(findings).count("HVD005") == 2


def test_hvd005_locked_write_clean():
    findings = _lint(
        """
        import threading

        _registry = {}
        _lock = threading.Lock()

        def _loop():
            with _lock:
                _registry["x"] = 1

        def _sweep_locked():
            _registry.clear()  # *_locked convention: caller holds it

        t = threading.Thread(target=_loop)
        u = threading.Timer(1.0, _sweep_locked)
        """
    )
    assert "HVD005" not in _rules_of(findings)


def test_hvd005_reachability_via_call_graph():
    findings = _lint(
        """
        import threading

        _state = []

        def _helper():
            _state.append(1)  # reachable from the timer via _loop

        def _loop():
            _helper()

        t = threading.Timer(5.0, _loop)
        """
    )
    assert "HVD005" in _rules_of(findings)


def test_hvd006_broad_swallows_flagged_narrow_ok():
    findings = _lint(
        """
        def broken():
            try:
                risky()
            except:
                pass

        def also_broken():
            try:
                risky()
            except Exception:
                pass

        def fine():
            try:
                risky()
            except OSError:
                pass  # narrow + explicit: a declared decision

        def also_fine():
            try:
                risky()
            except Exception as e:
                log.debug("risky failed: %s", e)
        """
    )
    assert _rules_of(findings).count("HVD006") == 2


# --------------------------------------------------------------------------
# waivers


def test_inline_waiver_suppresses():
    findings = _lint(
        """
        def broken():
            try:
                risky()
            except Exception:
                pass  # hvdlint: waive=HVD006 teardown is best-effort
        """
    )
    assert "HVD006" not in _rules_of(findings)


def test_inline_waiver_line_above():
    findings = _lint(
        """
        import horovod_tpu as hvd

        def fine(x, n):
            for _ in range(int(n.item())):
                # hvdlint: waive=HVD002 bound is broadcast beforehand
                x = hvd.allreduce(x)
            return x
        """
    )
    assert "HVD002" not in _rules_of(findings)


def test_central_waiver_matching(tmp_path):
    wfile = tmp_path / "waivers.txt"
    wfile.write_text(
        "# comment\n"
        "HVD006 pkg/mod.py known best-effort teardown\n"
    )
    waivers = load_waivers(str(wfile))
    assert len(waivers) == 1
    src = "try:\n    f()\nexcept Exception:\n    pass\n"
    bad = tmp_path / "pkg"
    bad.mkdir()
    (bad / "mod.py").write_text(src)
    (bad / "other.py").write_text(src)
    findings = lint_paths([str(bad)], waivers)
    assert len(findings) == 1  # other.py survives, mod.py waived
    assert findings[0].path.endswith("other.py")


def test_waiver_requires_reason(tmp_path):
    wfile = tmp_path / "waivers.txt"
    wfile.write_text("HVD006 pkg/mod.py\n")
    with pytest.raises(ValueError, match="reason is mandatory"):
        load_waivers(str(wfile))


def test_waiver_unknown_rule(tmp_path):
    wfile = tmp_path / "waivers.txt"
    wfile.write_text("HVD099 pkg/mod.py because\n")
    with pytest.raises(ValueError, match="unknown rule"):
        load_waivers(str(wfile))


def test_line_scoped_waiver():
    w = Waiver("HVD006", "a.py", 3, "why")
    from horovod_tpu.analysis.lint import Finding

    hit = Finding("HVD006", "a.py", 3, 0, "m", "h")
    miss = Finding("HVD006", "a.py", 9, 0, "m", "h")
    assert w.matches(hit) and not w.matches(miss)


def test_syntax_error_reported_not_raised():
    findings = lint_source("def broken(:\n", "bad.py")
    assert findings and findings[0].rule == "HVD000"


def test_every_rule_has_catalog_entry():
    """Findings must be explainable: each rule carries a summary and a
    non-empty fix hint, and docs/static_analysis.md documents each id."""
    doc = (ROOT / "docs" / "static_analysis.md").read_text(encoding="utf-8")
    for rule, (summary, hint) in RULES.items():
        assert summary and hint
        assert rule in doc, f"{rule} missing from docs/static_analysis.md"


# --------------------------------------------------------------------------
# CI self-lint: the repo is clean under the checked-in waivers


def test_self_lint_clean():
    """Run the real CLI the way CI does: `tools/hvdlint.py --json` over
    horovod_tpu/, tools/ and examples/ against the checked-in waivers
    file. ANY new finding fails tier-1 — fix it or waive it with a
    reason."""
    proc = subprocess.run(
        [
            sys.executable,
            str(ROOT / "tools" / "hvdlint.py"),
            "--json",
            str(ROOT / "horovod_tpu"),
            str(ROOT / "tools"),
            str(ROOT / "examples"),
        ],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=str(ROOT),
    )
    findings = json.loads(proc.stdout)
    assert findings == [], (
        "hvdlint found new unwaived findings:\n"
        + "\n".join(
            f"{f['path']}:{f['line']}: {f['rule']} {f['message']}"
            for f in findings
        )
    )
    assert proc.returncode == 0


def test_cli_json_reports_seeded_defect(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import horovod_tpu as hvd\n"
        "def broken(x):\n"
        "    if hvd.rank() == 0:\n"
        "        return hvd.allreduce(x)\n"
        "    return x\n"
    )
    proc = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "hvdlint.py"), "--json",
         str(bad)],
        capture_output=True, text=True, timeout=60, cwd=str(ROOT),
    )
    assert proc.returncode == 1
    findings = json.loads(proc.stdout)
    assert findings and findings[0]["rule"] == "HVD001"
    assert findings[0]["line"] == 4


# --------------------------------------------------------------------------
# runtime schedule sanitizer


@pytest.fixture()
def sanitize():
    from horovod_tpu.analysis import sanitizer
    from horovod_tpu.resilience import chaos, health

    sanitizer.reset()
    sanitizer.configure(True)
    yield sanitizer
    sanitizer.reset()
    chaos.reset()
    health.reset()


def test_sanitizer_disabled_is_noop():
    from horovod_tpu.analysis import sanitizer

    sanitizer.reset()
    try:
        assert not sanitizer.enabled()
        sanitizer.record("allreduce", ())  # must not record anything
        sanitizer.set_step(1)
        assert sanitizer.flush() is None
    finally:
        sanitizer.reset()


class _T:
    """Shape/dtype stand-in for a dispatched tensor."""

    def __init__(self, shape, dtype="float32"):
        self.shape = tuple(shape)
        self.dtype = dtype


def test_sanitizer_identical_schedules_clean(sanitize):
    sanitize.configure(world=4)
    for step in range(3):
        sanitize.set_step(step)
        sanitize.record("allreduce", (_T((8, 4)),), axis="data")
        sanitize.record("allgather", (_T((2, 3)),), axis="data")
    sanitize.flush()
    assert sanitize.last_divergence() is None


def test_sanitizer_chaos_names_rank_and_op(sanitize):
    """The deterministic divergence: at step 1 the highest rank's record
    is perturbed; the cross-check must name rank 3 and the first op."""
    from horovod_tpu.resilience import chaos, health

    chaos.configure("schedule_diverge_at_step=1")
    sanitize.configure(world=4)
    detected_at = None
    for step in range(4):
        sanitize.set_step(step)  # flushes step-1 → detection within 1 step
        if sanitize.last_divergence() and detected_at is None:
            detected_at = step
        sanitize.record("allreduce", (_T((128,)),), axis="data")
        sanitize.record("broadcast", (_T((4, 4)),), axis="data")
    div = sanitize.last_divergence()
    assert div is not None
    assert div["rank"] == 3  # never rank 0, like rank_fail
    assert div["step"] == 1
    assert div["op_index"] == 0
    assert "allreduce" in div["op"]
    assert detected_at == 2, "divergence at step 1 must surface by step 2"
    # health machine: SUSPECT naming the rank and the op
    snap = health.snapshot()
    assert snap["state"] == "SUSPECT"
    assert "rank 3" in snap["reason"] and "allreduce" in snap["reason"]


def test_sanitizer_divergence_metric(sanitize):
    from horovod_tpu.observability import metrics
    from horovod_tpu.resilience import chaos

    before = metrics.value("sanitizer_schedule_divergence", rank=2) or 0
    chaos.configure("schedule_diverge_at_step=0")
    sanitize.configure(world=3)
    sanitize.set_step(0)
    sanitize.record("allreduce", (_T((16,)),), axis="data")
    sanitize.flush()
    assert sanitize.last_divergence()["rank"] == 2
    after = metrics.value("sanitizer_schedule_divergence", rank=2)
    assert after == before + 1
    assert metrics.value("sanitizer_steps_checked") >= 1


def test_sanitizer_hash_sensitivity(sanitize):
    """Shape, dtype, axis, and op order all perturb the rolling hash."""
    from horovod_tpu.analysis import sanitizer as s

    def digest(ops):
        s.reset()
        s.configure(True, world=2)
        s.set_step(0)
        for op, shape, dtype, axis in ops:
            s.record(op, (_T(shape, dtype),), axis=axis)
        s.publish(0)
        blob = s._store().get(s.schedule_key(0, 0))
        return json.loads(blob)["hash"]

    base = [("allreduce", (8,), "float32", "data")]
    assert digest(base) == digest(base)
    assert digest(base) != digest([("allgather", (8,), "float32", "data")])
    assert digest(base) != digest([("allreduce", (9,), "float32", "data")])
    assert digest(base) != digest([("allreduce", (8,), "int8", "data")])
    assert digest(base) != digest([("allreduce", (8,), "float32", "x")])
    two = base + [("broadcast", (2,), "float32", "data")]
    assert digest(two) != digest(list(reversed(two)))


def test_sanitizer_ring_cap_still_hashes(sanitize, monkeypatch):
    """Past HOROVOD_SANITIZE_MAX_OPS the diagnostic ring stops growing
    but the hash keeps rolling — count divergence is still detected."""
    monkeypatch.setenv("HOROVOD_SANITIZE_MAX_OPS", "8")
    sanitize.configure(world=2)
    sanitize.set_step(0)
    for i in range(20):
        sanitize.record("allreduce", (_T((i + 1,)),), axis="data")
    sanitize.publish(0)
    blob = json.loads(sanitize._store().get(sanitize.schedule_key(0, 0)))
    assert blob["n"] == 20 and len(blob["ops"]) == 8
    assert blob["dropped"] == 12


def test_sanitizer_publishes_to_real_kv(sanitize):
    """With a rendezvous KVStoreServer wired in, records land under
    /sanitize/<step>/<rank> with a TTL — the fleet-visible spelling."""
    from horovod_tpu.run.rendezvous import KVStoreServer

    server = KVStoreServer()
    try:
        sanitize.configure(world=2, kv=server)
        sanitize.set_step(0)
        sanitize.record("allreduce", (_T((4,)),), axis="data")
        sanitize.set_step(1)
        blob = server.get("/sanitize/0/1")
        assert blob is not None
        rec = json.loads(blob)
        assert rec["n"] == 1 and rec["ops"][0][0] == "allreduce"
    finally:
        server.close()


def test_sanitizer_defers_missing_peer_then_detects(sanitize):
    """The multi-process race: rank 0 reaches the boundary before the
    (divergent, often slow) peer's publication lands. The step must be
    re-checked at a later boundary, not dropped."""
    sanitize.configure(world=2)
    store = sanitize._store()
    mine = {"hash": "aaa", "n": 1, "dropped": 0,
            "ops": [["allreduce", "data", [[[4], "float32"]]]]}
    store.put(sanitize.schedule_key(0, 0), json.dumps(mine).encode())
    assert sanitize.cross_check(0) is None  # peer missing: deferred
    assert 0 in sanitize._pending_checks
    theirs = dict(mine, hash="bbb",
                  ops=[["allgather", "data", [[[4], "float32"]]]])
    store.put(sanitize.schedule_key(0, 1), json.dumps(theirs).encode())
    # a later boundary retries the pending step
    sanitize.set_step(5)
    div = sanitize.last_divergence()
    assert div is not None and div["step"] == 0 and div["rank"] == 1
    assert 0 not in sanitize._pending_checks


def test_sanitizer_pending_check_budget_expires(sanitize):
    """A peer that never publishes stops being retried after the budget
    — that silence is the heartbeat layer's finding, not a schedule
    verdict."""
    sanitize.configure(world=2)
    store = sanitize._store()
    mine = {"hash": "aaa", "n": 1, "dropped": 0, "ops": []}
    store.put(sanitize.schedule_key(0, 0), json.dumps(mine).encode())
    for _ in range(sanitize.PENDING_CHECK_ATTEMPTS):
        assert sanitize.cross_check(0) is None
    assert 0 not in sanitize._pending_checks


def test_sanitizer_one_rank_world_does_not_consume_chaos(sanitize):
    """With world == 1 no perturbation is possible; the charge must stay
    armed and uncounted (resilience_chaos_injected counts injections that
    FIRED)."""
    from horovod_tpu.resilience import chaos

    chaos.configure("schedule_diverge_at_step=0")
    sanitize.configure(world=1)
    sanitize.set_step(0)
    sanitize.record("allreduce", (_T((4,)),), axis="data")
    sanitize.flush()
    assert sanitize.last_divergence() is None
    # the charge is still armed — nothing consumed it
    assert chaos.take_schedule_diverge(0) is True


def test_sanitizer_shutdown_flushes_final_step(hvd):
    """A divergence at the LAST step has no next boundary; shutdown must
    flush and name it."""
    import jax.numpy as jnp

    from horovod_tpu.analysis import sanitizer
    from horovod_tpu.resilience import chaos, health

    sanitizer.reset()
    health.reset()
    try:
        sanitizer.configure(True)
        chaos.configure("schedule_diverge_at_step=0")
        sanitizer.set_step(0)
        hvd.allreduce(jnp.ones((8, 2), jnp.float32))
        assert sanitizer.last_divergence() is None  # not yet published
        hvd.shutdown()
        div = sanitizer.last_divergence()
        assert div is not None and div["step"] == 0 and div["rank"] == 7
    finally:
        sanitizer.reset()
        chaos.reset()
        health.reset()


def test_sanitizer_kv_client_from_launcher_env(sanitize, monkeypatch):
    """In a launched job the sanitizer wires a KVStoreClient from
    HVD_RUN_KV_ADDR/PORT (the fleet-metrics convention) without explicit
    configure — records arrive on the real server over HTTP."""
    from horovod_tpu.run.rendezvous import KVStoreServer

    server = KVStoreServer()
    server.start()
    try:
        monkeypatch.setenv("HVD_RUN_KV_ADDR", "127.0.0.1")
        monkeypatch.setenv("HVD_RUN_KV_PORT", str(server.port))
        sanitize.reset()
        sanitize.configure(True, world=2)
        sanitize.set_step(0)
        sanitize.record("allreduce", (_T((4,)),), axis="data")
        sanitize.set_step(1)
        rec = json.loads(server.get("/sanitize/0/1"))
        assert rec["ops"][0][0] == "allreduce"
    finally:
        server.close()


def test_sanitizer_e2e_real_collectives(hvd):
    """End-to-end on the 8-device CPU mesh: real eager collectives feed
    the ring through _record_eager_op; the chaos charge at step 1 is
    named (rank 7 = world-1) with the first divergent op, within one
    step."""
    import jax.numpy as jnp

    from horovod_tpu.analysis import sanitizer
    from horovod_tpu.resilience import chaos, health

    sanitizer.reset()
    health.reset()
    try:
        sanitizer.configure(True)
        chaos.configure("schedule_diverge_at_step=1")
        x = jnp.ones((8, 4), jnp.float32)
        for step in range(3):
            sanitizer.set_step(step)
            hvd.allreduce(x)
            hvd.allgather(jnp.ones((2, 3), jnp.float32))
        div = sanitizer.last_divergence()
        assert div is not None and div["step"] == 1
        assert div["rank"] == hvd.size() - 1 == 7
        assert "allreduce" in div["op"]
        assert health.health_state().name == "SUSPECT"
        assert "rank 7" in health.snapshot()["reason"]
    finally:
        sanitizer.reset()
        chaos.reset()
        health.reset()


def test_sanitizer_instrumented_step_boundary(hvd):
    """InstrumentedStep owns the step boundary: wrapping a step fn that
    dispatches an eager collective is enough — no manual set_step."""
    import jax.numpy as jnp

    from horovod_tpu.analysis import sanitizer
    from horovod_tpu.resilience import chaos, health
    from horovod_tpu.training import instrument_step

    sanitizer.reset()
    health.reset()
    try:
        sanitizer.configure(True)
        chaos.configure("schedule_diverge_at_step=0")
        x = jnp.ones((8, 2), jnp.float32)

        def step(v):
            return hvd.allreduce(v)

        wrapped = instrument_step(step, name="sanity")
        for _ in range(3):
            wrapped(x)
        sanitizer.flush()
        div = sanitizer.last_divergence()
        assert div is not None and div["rank"] == 7
    finally:
        sanitizer.reset()
        chaos.reset()
        health.reset()
