"""Elastic world-size training (ISSUE 4): KV heartbeats with TTL,
generation-numbered epochs, in-process mesh re-formation, ZeRO-1 state
reshard, rollback to the last committed snapshot, and the launcher's
min/max-workers band.

The acceptance pin: an 8-rank CPU-mesh run under
``HOROVOD_CHAOS=rank_fail=2`` continues at world size 6 without relaunch,
its post-resize trajectory matches a fresh 6-rank run restored from the
rollback snapshot (allclose), a later rejoin restores world size 8, and the
``resilience_elastic_*`` metrics record both transitions. Tier-1: single
process, deterministic chaos, no sleeps > 0.2s.
"""

import os
import signal
import threading
import time
from unittest import mock

import numpy as np
import pytest

from horovod_tpu.observability import metrics
from horovod_tpu.resilience import chaos, elastic, health, loop
from horovod_tpu.resilience.health import HealthState
from horovod_tpu.run.rendezvous import (
    DeadRankError,
    KVStoreClient,
    KVStoreServer,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_resilience():
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    yield
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.reset()


# ------------------------------------------- KV heartbeat TTL / dead ranks


class TestKVHeartbeats:
    def test_ttl_key_expires_to_tombstone(self):
        s = KVStoreServer()
        s.put("/e/hb/3", b"1", ttl=0.05)
        assert s.get("/e/hb/3") == b"1"
        time.sleep(0.08)
        assert s.get("/e/hb/3") is None
        assert "/e/hb/3" in s.dead_keys()

    def test_refresh_clears_tombstone(self):
        s = KVStoreServer()
        s.put("/e/hb/2", b"1", ttl=0.05)
        time.sleep(0.08)
        assert "/e/hb/2" in s.dead_keys()
        s.put("/e/hb/2", b"1", ttl=5.0)  # the rank rejoined
        assert "/e/hb/2" not in s.dead_keys()
        assert s.get("/e/hb/2") == b"1"

    def test_wait_for_dead_heartbeat_fast_fails(self):
        """The satellite fix: a key owned by a dead rank must surface
        DeadRankError with the rank id immediately — not burn the whole
        deadline."""
        s = KVStoreServer()
        s.put("/e/hb/5", b"1", ttl=0.05)
        time.sleep(0.08)
        t0 = time.monotonic()
        with pytest.raises(DeadRankError) as ei:
            s.wait_for(["/e/ack/7/5"], timeout=30, hb_scope="/e/hb")
        assert ei.value.rank == 5
        assert time.monotonic() - t0 < 5  # nowhere near the 30s deadline

    def test_wait_for_tombstoned_key_itself(self):
        s = KVStoreServer()
        s.put("/e/hb/4", b"1", ttl=0.05)
        time.sleep(0.08)
        with pytest.raises(DeadRankError) as ei:
            s.wait_for(["/e/hb/4"], timeout=30)
        assert ei.value.rank == 4

    def test_wait_for_mid_wait_death(self):
        """A rank dying WHILE others wait on its key also fails fast: TTL
        expiry is re-swept on every wakeup."""
        s = KVStoreServer()
        s.put("/e/hb/6", b"1", ttl=0.15)
        err = []

        def waiter():
            try:
                s.wait_for(["/e/ack/1/6"], timeout=30, hb_scope="/e/hb")
            except BaseException as e:
                err.append(e)

        t = threading.Thread(target=waiter)
        t.start()
        t.join(timeout=5)
        assert not t.is_alive()
        assert isinstance(err[0], DeadRankError) and err[0].rank == 6

    def test_wait_for_plain_timeout_unchanged(self):
        s = KVStoreServer()
        with pytest.raises(TimeoutError):
            s.wait_for(["/never"], timeout=0.1)

    def test_wait_for_satisfied_returns_values(self):
        s = KVStoreServer()
        s.put("/a/1", b"x")
        s.put("/a/2", b"y")
        got = s.wait_for(["/a/1", "/a/2"], timeout=1)
        assert got == {"/a/1": b"x", "/a/2": b"y"}

    def test_client_wait_for_raises_dead_rank_over_http(self):
        """End-to-end over the wire: GET on an expired heartbeat key
        answers 410 Gone with the owner rank; the client maps it to
        DeadRankError."""
        server = KVStoreServer()
        server.start()
        try:
            server.put("/e/hb/2", b"1", ttl=0.05)
            time.sleep(0.08)
            client = KVStoreClient("127.0.0.1", server.port)
            t0 = time.monotonic()
            with pytest.raises(DeadRankError) as ei:
                client.wait_for("/e/hb/2", timeout=30)
            assert ei.value.rank == 2
            assert time.monotonic() - t0 < 5
        finally:
            server.stop()

    def test_client_put_with_ttl(self):
        server = KVStoreServer()
        server.start()
        try:
            client = KVStoreClient("127.0.0.1", server.port)
            client.heartbeat(3, scope="e/hb", ttl=0.05)
            assert server.get("/e/hb/3") == b"1"
            time.sleep(0.08)
            assert server.get("/e/hb/3") is None
            assert "/e/hb/3" in server.dead_keys()
        finally:
            server.stop()


# ------------------------------------------------------ elastic coordinator


class TestElasticCoordinator:
    def test_liveness_mark_dead_rejoin(self):
        c = elastic.ElasticCoordinator(ttl=5.0)
        try:
            c.heartbeat_all(range(4))
            assert c.alive() == [0, 1, 2, 3]
            c.mark_dead(3)
            c.mark_dead(2)
            assert c.alive() == [0, 1]
            c.heartbeat(2)  # rejoin = heartbeat resumes
            assert c.alive() == [0, 1, 2]
        finally:
            c.close()

    def test_generation_record_and_metrics(self):
        c = elastic.ElasticCoordinator(ttl=5.0)
        try:
            c.heartbeat_all(range(3))
            g = c.begin_generation([0, 1, 2])
            assert g == 1
            rec = c.membership()
            assert rec == {"generation": 1, "ranks": [0, 1, 2]}
            assert metrics.value("resilience_elastic_generation") == 1.0
            assert metrics.value("resilience_elastic_world_size") == 3.0
            g2 = c.begin_generation([0, 1])
            assert g2 == 2
            assert metrics.value("resilience_elastic_world_size") == 2.0
        finally:
            c.close()

    def test_barrier_completes_on_full_acks(self):
        c = elastic.ElasticCoordinator(ttl=5.0)
        try:
            c.heartbeat_all(range(3))
            g = c.begin_generation([0, 1, 2])
            for r in (0, 1, 2):
                c.ack(g, r)
            c.await_acks(g, [0, 1, 2], timeout=2)  # returns, no raise
        finally:
            c.close()

    def test_begin_generation_prunes_prior_ack_keys(self):
        """Ack-barrier keys are per-generation names: opening G+1 retires
        G's acks so the store does not grow by world_size keys per
        resize forever."""
        c = elastic.ElasticCoordinator(ttl=5.0)
        try:
            c.heartbeat_all(range(3))
            g1 = c.begin_generation([0, 1, 2])
            for r in (0, 1, 2):
                c.ack(g1, r)
            g2 = c.begin_generation([0, 1])
            acks = c.server.live_keys("/elastic/ack/")
            assert acks == []  # g1's barrier resolved; its keys retired
            c.ack(g2, 0)
            assert c.server.live_keys("/elastic/ack/") == [
                f"/elastic/ack/{g2}/0"]
        finally:
            c.close()

    def test_barrier_fast_fails_on_dead_member(self):
        """A member dying mid-barrier surfaces DeadRankError with its rank
        instead of the barrier timing out."""
        c = elastic.ElasticCoordinator(ttl=5.0)
        try:
            c.heartbeat_all(range(3))
            g = c.begin_generation([0, 1, 2])
            c.ack(g, 0)
            c.ack(g, 1)
            c.mark_dead(2)
            t0 = time.monotonic()
            with pytest.raises(DeadRankError) as ei:
                c.await_acks(g, [0, 1, 2], timeout=30)
            assert ei.value.rank == 2
            assert time.monotonic() - t0 < 5
        finally:
            c.close()


# ----------------------------------------------------- chaos rank charges


class TestElasticChaos:
    def test_parse_rank_keys(self):
        cfg = chaos.parse_spec(
            "rank_fail=2,rank_fail_at_step=3,rank_join_at_step=6")
        assert cfg == {
            "rank_fail": 2, "rank_fail_at_step": 3, "rank_join_at_step": 6,
        }

    @pytest.mark.chaos
    def test_rank_fail_fires_at_its_step_once(self):
        chaos.configure("rank_fail=2,rank_fail_at_step=3")
        assert chaos.take_rank_fail(0) == 0
        assert chaos.take_rank_fail(2) == 0
        assert chaos.take_rank_fail(3) == 2
        assert chaos.take_rank_fail(3) == 0  # consumed
        assert chaos.take_rank_fail(4) == 0
        assert metrics.value(
            "resilience_chaos_injected", site="rank_fail") == 1.0

    @pytest.mark.chaos
    def test_rank_fail_defaults_to_step_one(self):
        chaos.configure("rank_fail=1")
        assert chaos.take_rank_fail(0) == 0
        assert chaos.take_rank_fail(1) == 1

    @pytest.mark.chaos
    def test_rank_join_consumed_once(self):
        chaos.configure("rank_join_at_step=5")
        assert not chaos.take_rank_join(4)
        assert chaos.take_rank_join(6)
        assert not chaos.take_rank_join(7)
        assert metrics.value(
            "resilience_chaos_injected", site="rank_join_at_step") == 1.0


# --------------------------------------------- double-SIGTERM signal latch


@pytest.mark.chaos
def test_double_sigterm_single_drain_valid_checkpoint(hvd, tmp_path):
    """Satellite fix: a second SIGTERM landing DURING the emergency
    checkpoint write must be latched — no drain re-entry, no torn npz. The
    second signal is delivered from inside the save itself (the worst
    window), and the checkpoint must still validate."""
    from horovod_tpu import checkpoint as ckpt

    d = str(tmp_path / "ck")
    real_save = ckpt.save
    drains = []

    def noisy_save(directory, step, state, **kw):
        os.kill(os.getpid(), signal.SIGTERM)  # supervisor escalates mid-save
        time.sleep(0)  # give the handler its bytecode boundary
        return real_save(directory, step, state, **kw)

    def counting_drain(state, timeout_s=None):
        drains.append(1)

    chaos.configure("sigterm_at_step=2")
    with mock.patch.object(loop, "_drain", counting_drain), \
            mock.patch("horovod_tpu.checkpoint.save", noisy_save):
        with pytest.raises(loop.Preempted) as ei:
            loop.run(
                lambda st, i: {"w": st["w"] + 1}, {"w": np.zeros(2)},
                num_steps=5, checkpoint_dir=d,
            )
    assert ei.value.step == 2
    assert len(drains) == 1  # no re-entry into the drain path
    assert ckpt.latest_step(d) == 2  # the npz survived, CRC-valid
    assert metrics.value("resilience_preemptions") == 1.0
    assert metrics.value("resilience_extra_preempt_signals") == 1.0


def test_preempt_is_not_reentrant():
    """The drain/checkpoint sequence runs exactly once per preemption even
    when the loop has multiple paths into _preempt."""
    chaos.configure("sigterm_at_step=1")
    with pytest.raises(loop.Preempted):
        loop.run(lambda st, i: st, {}, num_steps=3)
    assert metrics.value("resilience_preemptions") == 1.0
    chaos.configure(None)


# ------------------------------------------- shutdown -> init idempotence


def test_reinit_on_new_mesh_clears_stale_kernel_caches():
    """Satellite fix: a live-process shutdown() → init() cycle is
    idempotent — re-init on an EQUAL mesh keeps the compiled-eager-kernel
    caches warm, while re-init on a DIFFERENT mesh (the elastic resize)
    drops the old mesh's stale entries. This is the primitive the elastic
    resize stands on."""
    import jax

    import horovod_tpu as hvd
    from horovod_tpu.ops import collective as C

    def cached_kernels():
        return sum(
            f.cache_info().currsize
            for f in (C._eager_allreduce_fn, C._eager_fused_allreduce_fn,
                      C._eager_allgather_fn, C._eager_broadcast_fn,
                      C._eager_reducescatter_fn)
        )

    hvd.init()
    try:
        assert hvd.size() == 8
        out = hvd.allreduce(np.ones((4,), np.float32))
        np.testing.assert_allclose(np.asarray(out), 1.0)
        assert cached_kernels() >= 1

        # same-mesh cycle: the caches stay warm (no recompile per cycle)
        hvd.shutdown()
        warm = cached_kernels()
        assert warm >= 1
        hvd.init()
        assert cached_kernels() == warm

        # different mesh: the stale-keyed entries are dropped at init
        hvd.shutdown()
        hvd.init(devices=jax.devices()[:6])
        assert cached_kernels() == 0
        assert hvd.size() == 6
        out = hvd.allreduce(np.full((4,), 2.0, np.float32))
        np.testing.assert_allclose(np.asarray(out), 2.0)

        hvd.shutdown()
        hvd.init()
        assert hvd.size() == 8
    finally:
        hvd.shutdown()


def test_atexit_registered_once():
    import horovod_tpu as hvd
    from horovod_tpu import basics

    registered = []
    with mock.patch.object(
        basics.atexit, "register",
        side_effect=lambda fn: registered.append(fn),
    ):
        was = basics._atexit_registered
        try:
            basics._atexit_registered = False
            hvd.init()
            hvd.shutdown()
            hvd.init()
            hvd.shutdown()
        finally:
            basics._atexit_registered = was
    assert len(registered) == 1  # one handler per process, not per init


def test_stale_collective_name_does_not_poison_reinit():
    import horovod_tpu as hvd
    from horovod_tpu.ops.collective import _register_name, _outstanding_names

    hvd.init()
    try:
        _register_name("grad/w0")  # an async op left outstanding at death
        hvd.shutdown()
        assert "grad/w0" not in _outstanding_names
        hvd.init()
        _register_name("grad/w0")  # must not raise DUPLICATE_NAME
        from horovod_tpu.ops.collective import _release_name

        _release_name("grad/w0")
    finally:
        hvd.shutdown()


# -------------------------------------------------- health feed


def test_record_rank_lost_strikes_and_counts():
    health.record_rank_lost(5)
    assert health.health_state() == HealthState.SUSPECT
    assert "rank 5" in health.snapshot()["reason"]
    assert metrics.value("resilience_rank_lost") == 1.0
    health.beat()
    assert health.health_state() == HealthState.HEALTHY


# -------------------------------------------------- launcher elastic band


def test_host_strike_decay_readmits():
    from horovod_tpu.run.runner import HostStrikes

    s = HostStrikes(limit=1, decay_s=0.05)
    s.strike("h1")
    assert s.blacklisted("h1")
    time.sleep(0.08)
    assert not s.blacklisted("h1")  # strikes decayed: re-admitted
    # permanent by default
    s2 = HostStrikes(limit=1, decay_s=0)
    s2.strike("h2")
    time.sleep(0.08)
    assert s2.blacklisted("h2")


def test_parse_args_min_max_workers():
    from horovod_tpu.run.runner import parse_args

    args = parse_args([
        "-np", "4", "--min-workers", "2", "--max-workers", "6",
        "--", "python", "train.py",
    ])
    assert args.min_workers == 2
    assert args.max_workers == 6


def test_launch_job_min_workers_tolerates_dead_slot(monkeypatch):
    """The elastic floor: a permanently failed slot is abandoned — the
    survivors run to completion instead of being SIGTERMed."""
    from horovod_tpu.run import hosts, runner

    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_BASE_DELAY", "0.01")
    monkeypatch.setenv("HOROVOD_RETRY_WORKER_RESTART_MAX_DELAY", "0.02")
    slots = hosts.allocate(hosts.parse_hosts("localhost:2"), 2)

    def fake_execute(argv, env=None, stdout_handler=None,
                     stderr_handler=None, event=None, shell=False):
        if env.get("HOROVOD_RANK") == "1":
            return 1  # permanent death
        # the survivor outlives the failure and completes
        time.sleep(0.1)
        return 0 if not (event and event.is_set()) else 143

    with mock.patch.object(runner.safe_exec, "execute", fake_execute):
        codes = runner.launch_job(
            slots, ["python", "train.py"], {}, min_workers=1)
    assert codes == [0, 1]  # survivor finished; dead slot recorded
    assert metrics.value(
        "resilience_elastic_slots_abandoned", host="localhost") == 1.0


def test_launch_job_below_min_workers_still_kills(monkeypatch):
    from horovod_tpu.run import hosts, runner

    slots = hosts.allocate(hosts.parse_hosts("localhost:2"), 2)

    def fake_execute(argv, env=None, stdout_handler=None,
                     stderr_handler=None, event=None, shell=False):
        if env.get("HOROVOD_RANK") == "1":
            return 1
        # survivor blocks until the teardown event fires
        if event:
            event.wait(5)
        return 143 if (event and event.is_set()) else 0

    with mock.patch.object(runner.safe_exec, "execute", fake_execute):
        codes = runner.launch_job(
            slots, ["python", "train.py"], {}, min_workers=2)
    assert codes[1] == 1
    assert codes[0] == 143  # torn down: the floor was broken


def test_launch_job_exports_elastic_band(monkeypatch):
    from horovod_tpu.run import hosts, runner

    slots = hosts.allocate(hosts.parse_hosts("localhost:1"), 1)
    seen = {}

    def fake_execute(argv, env=None, stdout_handler=None,
                     stderr_handler=None, event=None, shell=False):
        seen.update(env)
        return 0

    with mock.patch.object(runner.safe_exec, "execute", fake_execute):
        runner.launch_job(
            slots, ["python", "t.py"], {}, min_workers=1, max_workers=4)
    assert seen.get("HOROVOD_ELASTIC_MIN_WORKERS") == "1"
    assert seen.get("HOROVOD_ELASTIC_MAX_WORKERS") == "4"

    # an operator-exported cap is honored, not clobbered by the default
    seen.clear()
    with mock.patch.object(runner.safe_exec, "execute", fake_execute):
        runner.launch_job(
            slots, ["python", "t.py"],
            {"HOROVOD_ELASTIC_MAX_WORKERS": "2"})
    assert seen.get("HOROVOD_ELASTIC_MAX_WORKERS") == "2"


@pytest.mark.elastic
def test_unknown_rank_heartbeat_is_ignored():
    """A heartbeat for a rank this controller has no device for (shared
    store, stray key) must be ignored — not IndexError the resize."""
    import horovod_tpu as hvd

    coord = elastic.ElasticCoordinator(ttl=5.0)
    hvd.init()
    try:
        coord.heartbeat(40)  # no such device
        out = elastic.run(
            lambda world: (lambda st, i: {"w": st["w"] + 1}),
            {"w": np.zeros(1)}, num_steps=3, coordinator=coord)
        np.testing.assert_allclose(out["w"], 3.0)
        assert hvd.size() == 8  # the stray rank never joined
    finally:
        hvd.shutdown()
        coord.close()


# -------------------------------------------------- window watcher


def test_watcher_counts_elastic_resize_lines():
    import sys as _sys

    _sys.path.insert(0, os.path.join(_REPO, "tools"))
    import tpu_window_watcher as w

    text = (
        "[t] elastic: resized to world size 6 (generation 2, ...)\n"
        "noise\n"
        "[t] elastic: resized to world size 8 (generation 3, ...)\n"
    )
    assert w.count_elastic_resizes(text) == 2
    assert w.count_elastic_resizes("") == 0
    assert w.count_elastic_resizes(None) == 0


def test_watcher_extends_budget_on_elastic_resize(tmp_path):
    """run_rung must treat a mid-rung elastic resize as healthy progress:
    a child that logs the resize line and only finishes after the original
    budget still succeeds (bounded extension), instead of being killed as
    a wedge."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(_REPO, "tools"))
    import tpu_window_watcher as w

    child = (
        "import sys, time\n"
        "print('elastic: resized to world size 6 (generation 2)',"
        " file=sys.stderr, flush=True)\n"
        "time.sleep(1.5)\n"
        "print('{\"metric\": \"m\", \"value\": 1, \"platform\": \"tpu\"}',"
        " flush=True)\n"
    )
    data = w.run_rung(
        "elastic_probe", [_sys.executable, "-c", child], 1, str(tmp_path))
    assert data is not None and data["value"] == 1
    assert not w.run_rung.last_timed_out


# ---------------------------------------------------- elastic training e2e


def _tiny_model():
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(2)(x)

    return Tiny()


def _batch_for(step, n=48):
    rng = np.random.RandomState(step)
    x = rng.rand(n, 8).astype(np.float32)
    y = (x.sum(axis=1) > 4).astype(np.int64)
    return x, y


def _make_builder(model):
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.training import (
        make_shardmap_train_step, shard_batch, softmax_xent,
    )

    def step_builder(world):
        tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
        step = make_shardmap_train_step(
            model, tx, loss_fn=softmax_xent, shard_optimizer=True,
            instrument=False)

        def step_fn(state, i):
            x, y = _batch_for(i)
            p, _, os_, loss = step(
                state["params"], {}, state["opt_state"],
                shard_batch(x), shard_batch(y))
            return {"params": p, "opt_state": os_}

        return step_fn

    return step_builder


def _fresh_state(model):
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.training import replicate

    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_optimizer=True)
    params = replicate(jax.tree_util.tree_map(jnp.array, params0))
    return {"params": params, "opt_state": tx.init(params)}


@pytest.mark.elastic
@pytest.mark.chaos
def test_elastic_shrink_matches_fresh_run_then_rejoins():
    """THE acceptance pin. 8-rank run, ``rank_fail=2`` at step 3's
    boundary: continues at world size 6 in the same process, the
    post-resize trajectory matches a fresh 6-rank run restored from the
    rollback snapshot, ``rank_join_at_step=6`` grows back to 8, and the
    generation/membership metrics record both transitions."""
    import jax

    import horovod_tpu as hvd
    from horovod_tpu import checkpoint as ckpt
    from horovod_tpu.training import host_snapshot

    model = _tiny_model()
    builder = _make_builder(model)

    chaos.configure(
        "rank_fail=2,rank_fail_at_step=3,rank_join_at_step=6")
    hvd.init()
    try:
        state = _fresh_state(model)
        final = elastic.run(
            builder, state, num_steps=9, snapshot_every=1)
        assert hvd.size() == 8  # rejoined
        p_elastic = np.asarray(
            jax.tree_util.tree_leaves(final["params"])[0])

        # metrics recorded both transitions
        assert metrics.value("resilience_elastic_generation") == 3.0
        assert metrics.value(
            "resilience_elastic_membership_changes", kind="shrink") == 1.0
        assert metrics.value(
            "resilience_elastic_membership_changes", kind="grow") == 1.0
        assert metrics.value("resilience_elastic_world_size") == 8.0
        assert metrics.value("resilience_rank_lost") == 2.0
        hist = metrics.value("resilience_elastic_resize_seconds")
        assert hist["count"] == 2
        assert metrics.value(
            "resilience_chaos_injected", site="rank_fail") == 1.0

        # reference: the same schedule driven by hand — 8-rank steps 0..3,
        # snapshot, fresh 6-rank formation restored from it for 3..6,
        # snapshot, back to 8 for 6..9
        chaos.configure(None)
        hvd.shutdown()
        hvd.init()
        st = _fresh_state(model)
        fn8 = builder(8)
        for i in range(3):
            st = fn8(st, i)
        snap = host_snapshot(st)
        hvd.shutdown()
        hvd.init(devices=jax.devices()[:6])
        st6 = dict(snap)
        st6["opt_state"] = ckpt.consolidate_opt_state(
            st6["opt_state"], st6["params"], to_size=6)
        fn6 = builder(6)
        for i in range(3, 6):
            st6 = fn6(st6, i)
        snap6 = host_snapshot(st6)
        hvd.shutdown()
        hvd.init()
        st8 = dict(snap6)
        st8["opt_state"] = ckpt.consolidate_opt_state(
            st8["opt_state"], st8["params"], to_size=8)
        fn8b = builder(8)
        for i in range(6, 9):
            st8 = fn8b(st8, i)
        p_ref = np.asarray(jax.tree_util.tree_leaves(st8["params"])[0])
        np.testing.assert_allclose(p_elastic, p_ref, rtol=1e-5, atol=1e-6)
    finally:
        hvd.shutdown()


@pytest.mark.elastic
@pytest.mark.chaos
def test_elastic_world_too_small_checkpoints_and_raises(tmp_path):
    """Falling below min_workers is not survivable: the driver writes an
    emergency checkpoint of the last committed snapshot and raises."""
    import horovod_tpu as hvd
    from horovod_tpu import checkpoint as ckpt

    model = _tiny_model()
    builder = _make_builder(model)
    d = str(tmp_path / "ck")

    chaos.configure("rank_fail=3,rank_fail_at_step=2")
    hvd.init()
    try:
        state = _fresh_state(model)
        with pytest.raises(elastic.WorldTooSmall) as ei:
            elastic.run(
                builder, state, num_steps=6, min_workers=7,
                checkpoint_dir=d)
        assert ei.value.alive == 5
        assert ei.value.min_workers == 7
        # last committed snapshot (step 2) was emergency-checkpointed
        assert ckpt.latest_step(d) == 2
    finally:
        hvd.shutdown()


@pytest.mark.elastic
def test_min_workers_enforced_at_initial_formation():
    """The admissible band applies from step 0: a host that cannot field
    min_workers errors immediately instead of silently training small."""
    import horovod_tpu as hvd

    hvd.init()
    try:
        with pytest.raises(elastic.WorldTooSmall) as ei:
            elastic.run(
                lambda world: (lambda st, i: st), {"w": np.zeros(1)},
                num_steps=3, min_workers=9)  # only 8 devices exist
        assert ei.value.alive == 8
        assert ei.value.min_workers == 9
    finally:
        hvd.shutdown()


@pytest.mark.elastic
def test_elastic_no_faults_is_a_plain_run():
    """Without chaos/membership churn, elastic.run degrades to the plain
    loop: one generation, full world, correct arithmetic."""
    import horovod_tpu as hvd

    hvd.init()
    try:
        calls = []

        def builder(world):
            calls.append(world)

            def fn(st, i):
                return {"w": st["w"] + world}

            return fn

        out = elastic.run(builder, {"w": np.zeros(2)}, num_steps=4)
        np.testing.assert_allclose(out["w"], 32.0)  # 4 steps x world 8
        assert calls == [8]
        assert metrics.value("resilience_elastic_generation") == 1.0
    finally:
        hvd.shutdown()


@pytest.mark.elastic
@pytest.mark.chaos
def test_elastic_rollback_replays_uncommitted_steps():
    """With snapshot_every=2, a death detected at step 3 rolls back to the
    last committed step 2 and replays — the rollback metric records it."""
    import horovod_tpu as hvd

    seen = []

    def builder(world):
        def fn(st, i):
            seen.append((world, i))
            return {"w": st["w"] + 1}

        return fn

    chaos.configure("rank_fail=1,rank_fail_at_step=3")
    hvd.init()
    try:
        out = elastic.run(
            builder, {"w": np.zeros(1)}, num_steps=5, snapshot_every=2)
        # 8-world ran steps 0,1,2; death at step-3 boundary rolled back to
        # committed step 2, so 7-world replays 2 then runs 3,4
        assert (8, 2) in seen and (7, 2) in seen
        np.testing.assert_allclose(out["w"], 5.0)  # exactly-once effect
        assert metrics.value("resilience_elastic_rollback_steps") == 1.0
    finally:
        hvd.shutdown()


@pytest.mark.elastic
@pytest.mark.chaos
def test_join_charge_survives_until_someone_failed():
    """Regression: rank_join armed at (or before) the fail step must not
    be consumed while nobody has failed yet — the charge waits for the
    shrink, then fires on the next boundary and regrows the world."""
    import horovod_tpu as hvd

    chaos.configure("rank_fail=1,rank_fail_at_step=2,rank_join_at_step=2")
    hvd.init()
    try:
        out = elastic.run(
            lambda world: (lambda st, i: {"w": st["w"] + 1}),
            {"w": np.zeros(1)}, num_steps=5)
        assert hvd.size() == 8  # shrank to 7, then the join charge fired
        np.testing.assert_allclose(out["w"], 5.0)
        assert metrics.value(
            "resilience_elastic_membership_changes", kind="shrink") == 1.0
        assert metrics.value(
            "resilience_elastic_membership_changes", kind="grow") == 1.0
    finally:
        hvd.shutdown()


@pytest.mark.elastic
@pytest.mark.chaos
def test_elastic_sigterm_preemption_still_exits_resumable(tmp_path):
    """The preemption protocol composes: SIGTERM inside an elastic run
    still drains, emergency-checkpoints, and raises Preempted (exit 75)."""
    import horovod_tpu as hvd
    from horovod_tpu import checkpoint as ckpt

    d = str(tmp_path / "ck")
    chaos.configure("sigterm_at_step=2")
    hvd.init()
    try:
        def builder(world):
            return lambda st, i: {"w": st["w"] + 1}

        with pytest.raises(loop.Preempted) as ei:
            elastic.run(
                builder, {"w": np.zeros(1)}, num_steps=5,
                checkpoint_dir=d)
        assert ei.value.code == loop.RESUMABLE_EXIT_CODE
        assert ckpt.latest_step(d) == 2
    finally:
        hvd.shutdown()


@pytest.mark.elastic
@pytest.mark.chaos
def test_clock_reestimated_after_elastic_resize():
    """Satellite (ISSUE 14): the elastic driver re-estimates the clock
    offset against the coordinator's KV at every epoch boundary — pinned
    end to end here: after a rank_fail shrink, the stored estimate
    carries the POST-resize generation, a real error bound, and the
    mirrored clock gauges (previously asserted nowhere end-to-end)."""
    import horovod_tpu as hvd
    from horovod_tpu.observability import clock

    model = _tiny_model()
    builder = _make_builder(model)
    chaos.configure("rank_fail=2,rank_fail_at_step=2")
    clock.reset()
    hvd.init()
    try:
        state = _fresh_state(model)
        elastic.run(builder, state, num_steps=4, snapshot_every=1)
        assert hvd.size() == 6  # the shrink happened (48 % 6 == 0)
        info = clock.info()
        # formation is generation 1; the post-shrink epoch re-estimated
        # under generation 2 (a resize is exactly when the host set — and
        # the skew picture — may have changed)
        assert info["generation"] == 2
        assert clock.error_bound() is not None
        assert info["age_s"] is not None
        assert metrics.value(
            "observability_clock_offset_seconds") is not None
        assert metrics.value(
            "observability_clock_error_seconds") is not None
    finally:
        hvd.shutdown()
        clock.reset()
