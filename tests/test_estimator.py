"""Estimator workflow tests (reference ``test/test_spark_keras.py``,
``test_spark_torch.py``: estimator plumbing over a mocked/local fabric):
store staging, single-process keras fit/transform, and a real 2-process
torch fit through the launcher."""

import os
import sys

import numpy as np
import pandas as pd
import pytest

from horovod_tpu.data import LocalStore
from horovod_tpu.estimator import KerasEstimator, TorchEstimator


def _teacher_df(n=256, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    y = X @ w + 0.01 * rng.randn(n).astype(np.float32)
    df = pd.DataFrame({f"f{i}": X[:, i] for i in range(d)})
    df["label"] = y
    return df


def test_local_store_roundtrip(tmp_path):
    store = LocalStore(str(tmp_path))
    df = _teacher_df(32)
    p = store.get_train_data_path("runA")
    store.write_dataframe(df, p)
    assert store.exists(p)
    back = store.read_dataframe(p)
    pd.testing.assert_frame_equal(df.reset_index(drop=True), back)
    assert store.get_checkpoint_path("runA").startswith(str(tmp_path))
    store.delete(store.get_run_path("runA"))
    assert not store.exists(p)


def test_keras_estimator_fit_transform(hvd, tmp_path):
    keras = pytest.importorskip("keras")
    df = _teacher_df()
    est = KerasEstimator(
        model=keras.Sequential([
            keras.layers.Input((4,)), keras.layers.Dense(1)]),
        optimizer=keras.optimizers.SGD(0.05),
        loss="mse",
        feature_cols=[f"f{i}" for i in range(4)],
        label_cols=["label"],
        batch_size=32, epochs=6, num_proc=1,
        store=LocalStore(str(tmp_path)), validation=0.1,
    )
    model = est.fit(df)
    assert model.history_["loss"][-1] < model.history_["loss"][0]
    out = model.transform(df.head(10))
    assert "label_pred" in out.columns
    err = np.abs(out["label_pred"].to_numpy() - out["label"].to_numpy())
    assert err.mean() < 1.5  # teacher is learnable; loose bound


def test_torch_estimator_fit_transform_single(hvd, tmp_path):
    torch = pytest.importorskip("torch")
    df = _teacher_df(seed=1)
    model = torch.nn.Sequential(torch.nn.Linear(4, 1))
    est = TorchEstimator(
        model=model,
        optimizer=torch.optim.SGD(model.parameters(), lr=0.05),
        loss=torch.nn.functional.mse_loss,
        feature_cols=[f"f{i}" for i in range(4)],
        label_cols=["label"],
        batch_size=32, epochs=6, num_proc=1,
        store=LocalStore(str(tmp_path)),
    )
    trained = est.fit(df)
    assert trained.history_[-1] < trained.history_[0]
    out = trained.transform(df.head(8))
    assert out["label_pred"].notna().all()


@pytest.mark.slow
def test_torch_estimator_two_process(tmp_path):
    torch = pytest.importorskip("torch")
    df = _teacher_df(seed=2)
    model = torch.nn.Sequential(torch.nn.Linear(4, 1))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    est = TorchEstimator(
        model=model,
        optimizer=torch.optim.SGD(model.parameters(), lr=0.05),
        loss=torch.nn.functional.mse_loss,
        feature_cols=[f"f{i}" for i in range(4)],
        label_cols=["label"],
        batch_size=32, epochs=4, num_proc=2,
        store=LocalStore(str(tmp_path)), env=env,
    )
    trained = est.fit(df)
    assert trained.history_[-1] < trained.history_[0]
    out = trained.transform(df.head(8))
    assert out["label_pred"].notna().all()


def test_spark_module_gated():
    import horovod_tpu.spark as sp

    with pytest.raises(ImportError, match="pyspark"):
        sp.run(lambda: 0)
    # estimators remain usable on pandas frames without pyspark
    assert sp.KerasEstimator is not None
