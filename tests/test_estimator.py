"""Estimator workflow tests (reference ``test/test_spark_keras.py``,
``test_spark_torch.py``: estimator plumbing over a mocked/local fabric):
store staging, single-process keras fit/transform, and a real 2-process
torch fit through the launcher."""

import os
import sys

import numpy as np
import pandas as pd
import pytest

from horovod_tpu.data import LocalStore
from horovod_tpu.estimator import KerasEstimator, TorchEstimator


def _teacher_df(n=256, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    y = X @ w + 0.01 * rng.randn(n).astype(np.float32)
    df = pd.DataFrame({f"f{i}": X[:, i] for i in range(d)})
    df["label"] = y
    return df


def test_local_store_roundtrip(tmp_path):
    store = LocalStore(str(tmp_path))
    df = _teacher_df(32)
    p = store.get_train_data_path("runA")
    store.write_dataframe(df, p)
    assert store.exists(p)
    back = store.read_dataframe(p)
    pd.testing.assert_frame_equal(df.reset_index(drop=True), back)
    assert store.get_checkpoint_path("runA").startswith(str(tmp_path))
    store.delete(store.get_run_path("runA"))
    assert not store.exists(p)


def test_keras_estimator_fit_transform(hvd, tmp_path):
    keras = pytest.importorskip("keras")
    df = _teacher_df()
    est = KerasEstimator(
        model=keras.Sequential([
            keras.layers.Input((4,)), keras.layers.Dense(1)]),
        optimizer=keras.optimizers.SGD(0.05),
        loss="mse",
        feature_cols=[f"f{i}" for i in range(4)],
        label_cols=["label"],
        batch_size=32, epochs=6, num_proc=1,
        store=LocalStore(str(tmp_path)), validation=0.1,
    )
    model = est.fit(df)
    assert model.history_["loss"][-1] < model.history_["loss"][0]
    out = model.transform(df.head(10))
    assert "label_pred" in out.columns
    err = np.abs(out["label_pred"].to_numpy() - out["label"].to_numpy())
    assert err.mean() < 1.5  # teacher is learnable; loose bound


def test_torch_estimator_fit_transform_single(hvd, tmp_path):
    torch = pytest.importorskip("torch")
    df = _teacher_df(seed=1)
    model = torch.nn.Sequential(torch.nn.Linear(4, 1))
    est = TorchEstimator(
        model=model,
        optimizer=torch.optim.SGD(model.parameters(), lr=0.05),
        loss=torch.nn.functional.mse_loss,
        feature_cols=[f"f{i}" for i in range(4)],
        label_cols=["label"],
        batch_size=32, epochs=6, num_proc=1,
        store=LocalStore(str(tmp_path)),
    )
    trained = est.fit(df)
    assert trained.history_[-1] < trained.history_[0]
    out = trained.transform(df.head(8))
    assert out["label_pred"].notna().all()


@pytest.mark.slow
def test_torch_estimator_two_process(tmp_path):
    torch = pytest.importorskip("torch")
    df = _teacher_df(seed=2)
    model = torch.nn.Sequential(torch.nn.Linear(4, 1))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    est = TorchEstimator(
        model=model,
        optimizer=torch.optim.SGD(model.parameters(), lr=0.05),
        loss=torch.nn.functional.mse_loss,
        feature_cols=[f"f{i}" for i in range(4)],
        label_cols=["label"],
        batch_size=32, epochs=4, num_proc=2,
        store=LocalStore(str(tmp_path)), env=env,
    )
    trained = est.fit(df)
    assert trained.history_[-1] < trained.history_[0]
    out = trained.transform(df.head(8))
    assert out["label_pred"].notna().all()


def test_spark_module_gated():
    import horovod_tpu.spark as sp

    with pytest.raises(ImportError, match="pyspark"):
        sp.run(lambda: 0)
    # estimators remain usable on pandas frames without pyspark
    assert sp.KerasEstimator is not None


class FakeBarrierCtx:
    """Mimics the two pyspark.BarrierTaskContext methods the barrier slot
    uses: partitionId() and allGather(str)."""

    def __init__(self, idx, gathers=None):
        self.idx = idx
        self.gathers = list(gathers) if gathers is not None else None
        self.sent = []

    def partitionId(self):
        return self.idx

    def allGather(self, message):
        self.sent.append(message)
        if self.gathers is None:  # single-task job: echo
            return [message]
        return self.gathers.pop(0)


def test_spark_barrier_slot_rank_grouping(monkeypatch):
    """Host-major rank assignment + coordinator env, driven through the
    executor-side body with a scripted 4-task / 2-host barrier context
    (reference spark/runner.py:194-221 host-hash grouping)."""
    import socket

    import horovod_tpu.spark as sp

    monkeypatch.setattr(socket, "gethostname", lambda: "hostB")
    ctx = FakeBarrierCtx(
        idx=3,
        gathers=[
            ["0:hostA", "1:hostB", "2:hostA", "3:hostB"],
            ["0:hostA:12345", "1:hostA:0", "2:hostB:0", "3:hostB:0"],
        ],
    )
    saved = dict(os.environ)
    try:
        def fn():
            return {
                k: os.environ[k]
                for k in (
                    "HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
                    "HOROVOD_LOCAL_SIZE", "HOROVOD_CROSS_RANK",
                    "HOROVOD_CROSS_SIZE", "HVD_COORDINATOR_ADDR",
                )
            }

        ((rank, env),) = list(sp._run_barrier_slot(ctx, fn, (), {}))
    finally:
        os.environ.clear()
        os.environ.update(saved)
    # partitions (0,2) on hostA get ranks 0-1; (1,3) on hostB get 2-3
    assert rank == 3
    assert env["HOROVOD_RANK"] == "3"
    assert env["HOROVOD_SIZE"] == "4"
    assert env["HOROVOD_LOCAL_RANK"] == "1"
    assert env["HOROVOD_LOCAL_SIZE"] == "2"
    assert env["HOROVOD_CROSS_RANK"] == "1"
    assert env["HOROVOD_CROSS_SIZE"] == "2"
    # coordinator is rank 0's host:port from the second allGather
    assert env["HVD_COORDINATOR_ADDR"] == "hostA:12345"
    # the slot announced itself correctly in both gathers
    assert ctx.sent[0] == "3:hostB"
    assert ctx.sent[1].startswith("3:hostB:")


def test_spark_barrier_slot_single_task_runs_fn():
    """A 1-task barrier job actually runs fn with the framework usable."""
    import horovod_tpu as hvd
    import horovod_tpu.spark as sp

    saved = dict(os.environ)
    try:
        def fn(a, b=1):
            hvd.init()
            out = float(np.asarray(hvd.allreduce(np.ones(2), hvd.Sum))[0])
            hvd.shutdown()
            return a + b + out

        ((rank, result),) = list(
            sp._run_barrier_slot(FakeBarrierCtx(0), fn, (10,), {"b": 2})
        )
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert rank == 0
    assert result == 10 + 2 + 8.0  # sum over the 8 virtual chips... 1 proc


def test_spark_submodule_import_aliases():
    """Reference import paths horovod.spark.{keras,torch} keep working and
    resolve to the SPARK-FACING estimators (the ones whose fit() accepts a
    Spark DataFrame via toPandas), not the pandas-only engine classes."""
    import horovod_tpu.spark as hspark
    from horovod_tpu.spark.keras import KerasEstimator as KE
    from horovod_tpu.spark.torch import TorchEstimator as TE
    from horovod_tpu import estimator as engine

    assert KE is hspark.KerasEstimator and TE is hspark.TorchEstimator
    assert KE is not engine.KerasEstimator  # Spark veneer, not the engine
    assert issubclass(KE, engine.KerasEstimator)
    assert issubclass(TE, engine.TorchEstimator)
