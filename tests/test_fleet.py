"""Fleet serving tier: replica failover, health-aware hedged routing,
and fleet-wide coordinated rollback (ISSUE 17).

The two acceptance drills:

- **Failover**: 3 replicas under traffic, ``replica_kill`` fired
  mid-flight → every request completes exactly once (the flight
  record's rid-correlated grouping shows 0 STRANDED, the fleet counter
  shows one completion per request), the victim's in-flight requests
  re-route, and the health plane records the lost replica.
- **Fleet-wide rollback**: guarded training on the 8-device mesh →
  publish G1/G2 → fleet canary under traffic with ``slow_decode``
  scoped to ONE replica's canary arm → the fleet-merged TTFT window
  burns → ONE generation-fenced rollback decision through the
  rendezvous KV rolls back ALL replicas to G−1 (the vetoed generation
  serves nowhere), post-rollback tokens are bit-identical to
  ``generate()`` on the healthy weights on every replica, and the
  training step's collective-schedule fingerprint is byte-equal before
  and after.

Plus unit pins for the ``replica_kill`` / ``replica_stale`` chaos
grammar (and ``slow_decode``'s ``<arm>@<replica>`` scoping), the
backpressure ``retry_after_s`` hint, stale-replica last-resort
demotion + the PR-12 staleness→health 503 path through the router,
the ROUTE retry scope (``HOROVOD_RETRY_ROUTE_*``) with per-rid
deterministic backoff, :class:`FleetSaturated` exhaustion, hedging
(loser cancelled, gate windows unpolluted), graceful drain
(quiesce → finish → tombstoned lease), fleet promotion through the
commit-last decision log, and ``hvd_top``'s FLEET-SERVING pane.

Tier-1: deterministic, no sleeps > 0.2s; ``serving`` marker.
"""

import dataclasses
import json
import os
import urllib.error
import urllib.request
import zlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from horovod_tpu.models.transformer import TransformerLM, generate  # noqa: E402
from horovod_tpu.observability import (  # noqa: E402
    exporters,
    flight,
    metrics,
    regression,
    reqtrace,
    slo,
    trace,
)
from horovod_tpu.resilience import chaos, health  # noqa: E402
from horovod_tpu.resilience.retry import RetryPolicy  # noqa: E402
from horovod_tpu.run.rendezvous import KVStoreServer  # noqa: E402
from horovod_tpu.serving import (  # noqa: E402
    FleetRollout,
    FleetRouter,
    FleetSaturated,
    InferenceEngine,
    QueueFull,
    Request,
    WeightPublisher,
    WeightSubscriber,
)
from horovod_tpu.serving.scheduler import DEFAULT_BACKPRESSURE_TPOT  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.serving


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """reqtrace/slo/flight/trace/chaos state is module-global: every
    test starts clean and leaves nothing armed (the test_slo idiom,
    plus the fleet knobs)."""
    for var in ("HOROVOD_SLO", "HOROVOD_SLO_FAST_WINDOW",
                "HOROVOD_SLO_SLOW_WINDOW", "HOROVOD_SLO_BURN_THRESHOLD",
                "HOROVOD_REQTRACE", "HOROVOD_REQTRACE_WINDOW",
                "HOROVOD_TIMELINE", "HOROVOD_FLEET_HEDGE_AFTER",
                "HOROVOD_FLEET_STATUS_TTL",
                "HOROVOD_RETRY_ROUTE_MAX_ATTEMPTS",
                "HOROVOD_RETRY_ROUTE_BASE_DELAY",
                "HOROVOD_RETRY_ROUTE_DEADLINE"):
        monkeypatch.delenv(var, raising=False)
    from horovod_tpu.serving import publisher as _pub_mod

    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    reqtrace.reset()
    slo.reset()
    regression.reset()
    flight.reset()
    trace.reset()
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()
    yield
    chaos.reset()
    reqtrace.reset()
    slo.reset()
    regression.reset()
    flight.reset()
    trace.reset()
    health.reset()
    metrics.reset()
    metrics.set_enabled(True)
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()


def _model(depth=1, vocab=97, dim=32, heads=4, max_len=64):
    return TransformerLM(vocab=vocab, dim=dim, depth=depth, heads=heads,
                         mlp_ratio=2, max_len=max_len, dtype=jnp.float32)


def _params(model, seed=0):
    return model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]


def _ragged_prompts(seed, lens, vocab=97):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=l).astype(np.int32) for l in lens]


def _reference_generate(model, params, prompts, max_new):
    tp = max(len(p) for p in prompts)
    pad = np.zeros((len(prompts), tp), np.int32)
    for i, p in enumerate(prompts):
        pad[i, :len(p)] = p
    lens = np.asarray([len(p) for p in prompts], np.int32)
    out = np.asarray(generate(
        model, params, pad, max_new_tokens=max_new, prompt_lens=lens))
    return [out[i, lens[i]:lens[i] + max_new] for i in range(len(prompts))]


def _engine(model, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 24)
    kw.setdefault("max_batch", 2)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_seq_len", 32)
    return InferenceEngine(model, **kw)


def _fleet(server, model, n=3, *, hedge_after=0.0, retry_policy=None,
           engine_kw=None, **roll_kw):
    """Router + N replicas (each its own subscriber) + fleet rollout."""
    router = FleetRouter(store=server, hedge_after=hedge_after,
                         retry_policy=retry_policy)
    for i in range(n):
        sub = WeightSubscriber(server, device=True)
        router.add_replica(f"r{i}", _engine(model, **(engine_kw or {})),
                           sub)
    roll_kw.setdefault("canary_fraction", 1.0)
    roll_kw.setdefault("max_latency_ratio", None)
    roll = FleetRollout(router, server, **roll_kw)
    return router, roll


# ------------------------------------------------------- chaos grammar


@pytest.mark.chaos
class TestReplicaChaosGrammar:
    def test_replica_kill_default_boundary_and_consumption(self):
        chaos.configure("replica_kill=2")
        assert chaos.take_replica_kill(0) is None
        assert chaos.take_replica_kill(1) == 2
        # consumed: fires exactly once
        assert chaos.take_replica_kill(2) is None
        assert metrics.value("resilience_chaos_injected",
                             site="replica_kill") == 1.0

    def test_replica_kill_at_pump(self):
        chaos.configure("replica_kill=1:3")
        assert chaos.take_replica_kill(2) is None
        assert chaos.take_replica_kill(3) == 1

    def test_replica_stale_is_persistent(self):
        chaos.configure("replica_stale=0:45")
        assert chaos.replica_stale() == (0, 45.0)
        # NOT consumed on read: staleness is a condition, not an event
        assert chaos.replica_stale() == (0, 45.0)

    def test_replica_stale_requires_seconds(self):
        with pytest.raises(ValueError):
            chaos.configure("replica_stale=1")

    def test_slow_decode_replica_scope(self):
        chaos.configure("slow_decode=0.1:canary@r1")
        assert chaos.slow_decode() == (0.1, "canary@r1")


# ------------------------------------------------- backpressure hints


def test_queue_full_carries_deterministic_retry_after(hvd):
    """Satellite: an engine-level ``QueueFull`` carries a
    ``retry_after_s`` hint (queue depth × recent TPOT, with the
    documented default before any completion lands) and the hint rides
    the ``fleet_backpressure_hint_seconds`` gauge."""
    model = _model()
    eng = _engine(model, max_queue=1)
    eng.set_weights(_params(model), generation=1, arm="stable")
    prompts = _ragged_prompts(0, (5, 6))
    eng.submit(Request("a", prompts[0], 2))
    with pytest.raises(QueueFull) as ei:
        eng.submit(Request("b", prompts[1], 2))
    # no completions yet: the hint is depth(1) x the default TPOT
    assert ei.value.retry_after_s == pytest.approx(
        DEFAULT_BACKPRESSURE_TPOT)
    assert metrics.value("fleet_backpressure_hint_seconds") == \
        pytest.approx(DEFAULT_BACKPRESSURE_TPOT)
    assert eng.scheduler.backpressure_hint() == pytest.approx(
        max(1, eng.scheduler.queue_depth()) * DEFAULT_BACKPRESSURE_TPOT)


def test_fleet_saturated_after_route_budget(hvd):
    """The router retries a fully saturated fleet under the ROUTE
    policy, then raises :class:`FleetSaturated` carrying the
    fleet-minimum ``retry_after_s`` hint."""
    model = _model()
    server = KVStoreServer()
    router = None
    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        policy = RetryPolicy(scope="route", max_attempts=2,
                             base_delay=0.005, max_delay=0.01,
                             deadline=0.5, seed=0)
        router, _roll = _fleet(server, model, n=1, retry_policy=policy,
                               engine_kw={"max_queue": 1},
                               min_canary_requests=2)
        assert pub.publish({"params": _params(model)}, 1) == 1
        router.pump()
        prompts = _ragged_prompts(1, (5, 6))
        ok = router.submit("fits", prompts[0], 2)
        with pytest.raises(FleetSaturated) as ei:
            router.submit("overflow", prompts[1], 2)
        assert isinstance(ei.value, QueueFull)  # callers catch one type
        assert ei.value.retry_after_s == pytest.approx(
            DEFAULT_BACKPRESSURE_TPOT)
        assert metrics.value("fleet_requests", arm="stable",
                             outcome="rejected") == 1.0
        router.drain()
        assert ok.error is None
    finally:
        if router is not None:
            router.close()
        server.close()


# ----------------------------------------------------- ROUTE env scope


def test_route_retry_env_scope_and_seeded_backoff(monkeypatch):
    """Satellite: the router's retry policy reads the shared
    ``HOROVOD_RETRY_ROUTE_*`` scope, and the per-request backoff
    schedule is deterministic (seeded from the rid's crc32)."""
    monkeypatch.setenv("HOROVOD_RETRY_ROUTE_MAX_ATTEMPTS", "7")
    monkeypatch.setenv("HOROVOD_RETRY_ROUTE_BASE_DELAY", "0.125")
    router = FleetRouter()
    try:
        assert router._policy.scope == "route"
        assert router._policy.max_attempts == 7
        assert router._policy.base_delay == 0.125
        seed = zlib.crc32(b"rid-1")
        a = list(dataclasses.replace(router._policy, seed=seed).delays())
        b = list(dataclasses.replace(router._policy, seed=seed).delays())
        assert a == b and len(a) == 6
    finally:
        router.close()


# ------------------------------------- failover drill (exactly once)


@pytest.mark.chaos
def test_fleet_failover_exactly_once(hvd):
    """THE kill drill: 3 replicas under traffic, ``replica_kill`` fires
    mid-flight → the victim's in-flight requests re-route, every
    request completes exactly once (0 STRANDED, no double-completion),
    tokens stay bit-identical to ``generate()``, and the health plane
    records the lost replica."""
    from tools import hvd_blackbox

    model = _model()
    server = KVStoreServer()
    router = None
    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        router, roll = _fleet(server, model, n=3, min_canary_requests=2)
        assert pub.publish({"params": _params(model)}, 1) == 1
        router.pump()
        assert roll.stable_generation == 1
        for r in router.replicas:
            assert r.engine.arm_generation("stable") == 1
            assert r.applied_epoch == roll.epoch

        prompts = _ragged_prompts(5, (6, 9, 5, 7))
        want = _reference_generate(model, _params(model), prompts, 3)

        # healthy traffic spreads over every replica, token-identical
        reqs = [router.submit(f"q-{i}", p, 3)
                for i, p in enumerate(prompts)]
        router.drain()
        assert all(q.error is None for q in reqs)
        assert sorted({q.replica for q in reqs}) == ["r0", "r1", "r2"]
        for q, ref in zip(reqs, want):
            np.testing.assert_array_equal(np.asarray(q.generated), ref)

        # kill replica 0 at the next pump boundary, requests in flight
        chaos.configure("replica_kill=0:1")
        reqs2 = [router.submit(f"k-{i}", p, 3)
                 for i, p in enumerate(prompts)]
        router.drain()
        assert all(q.error is None for q in reqs2)
        for q, ref in zip(reqs2, want):
            np.testing.assert_array_equal(np.asarray(q.generated), ref)
        assert router.replica("r0").dead
        assert "r0" not in {q.replica for q in reqs2}
        assert metrics.value("fleet_requests_failed_over") == 2.0
        assert metrics.value("fleet_requests", arm="stable",
                             outcome="ok") == 8.0
        assert metrics.value("resilience_replicas_lost") == 1.0
        assert health.snapshot()["strikes"] >= 1
        assert metrics.value("resilience_chaos_injected",
                             site="replica_kill") == 1.0

        # nothing stranded, nothing double-completed: the flight
        # record's rid-correlated grouping agrees
        flight.flush()
        evs = [e for e in flight.events() if e.get("kind") == "serve"]
        summary = hvd_blackbox.request_summary({0: evs})
        # 8 fleet requests + the victim's 2 abandoned copies, which the
        # kill path closes as cancelled rather than stranding their
        # reqtrace entries forever
        assert "10 begun, 10 completed, 0 STRANDED" in summary[0]
        assert reqtrace.live_requests() == []
        dead_evs = [e for e in flight.events()
                    if e.get("what") == "replica_dead"]
        assert len(dead_evs) == 1 and dead_evs[0]["replica"] == "r0"
    finally:
        if router is not None:
            router.close()
        server.close()


# --------------------------- staleness: demotion + the 503 health path


@pytest.mark.chaos
def test_stale_replica_last_resort_and_health_503(hvd):
    """Satellite: a stale replica is demoted to last resort (it only
    takes traffic once every fresh replica rejected), and the PR-12
    staleness→health path fires per replica THROUGH the router — the
    ``/health`` endpoint answers 503 while the forced staleness holds
    and recovers when it clears."""
    model = _model()
    server = KVStoreServer()
    router = None
    http = exporters.start_http_server(0, host="127.0.0.1")
    url = f"http://127.0.0.1:{http.server_port}/health"

    def _health_code():
        try:
            with urllib.request.urlopen(url) as resp:
                return resp.status
        except urllib.error.HTTPError as e:
            return e.code

    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        router, _roll = _fleet(server, model, n=2,
                               engine_kw={"max_queue": 2},
                               min_canary_requests=2)
        assert pub.publish({"params": _params(model)}, 1) == 1
        router.pump()
        assert _health_code() == 200

        chaos.configure("replica_stale=0:120")
        router.pump()
        r0 = router.replica("r0")
        assert r0.stale() and r0.staleness_seconds() == 120.0
        assert health.snapshot()["state"] == "DEGRADED"
        assert "stale" in health.snapshot()["reason"]
        assert _health_code() == 503
        assert metrics.value("fleet_serving_replica_state",
                             replica="r0") == 1.0  # STATE_STALE
        assert metrics.value("fleet_serving_replica_state",
                             replica="r1") == 0.0
        assert metrics.value("resilience_chaos_injected",
                             site="replica_stale") >= 1.0

        # routing: fresh r1 absorbs traffic until it is full; only then
        # does the stale r0 take a request (last resort, not never)
        prompts = _ragged_prompts(2, (5, 6, 7))
        reqs = [router.submit(f"s-{i}", p, 2)
                for i, p in enumerate(prompts)]
        first_copy = [q.copies[0][0].id for q in reqs]
        assert first_copy == ["r1", "r1", "r0"]
        router.drain()
        assert all(q.error is None for q in reqs)

        # staleness clears -> immediate recovery through the same path
        chaos.configure(None)
        router.pump()
        assert not router.replica("r0").stale()
        assert health.snapshot()["state"] == "HEALTHY"
        assert _health_code() == 200
    finally:
        exporters.stop_http_server()
        if router is not None:
            router.close()
        server.close()


# --------------------------------------------------------- hedging


def test_hedge_duplicates_slow_request_loser_cancelled(hvd):
    """Satellite: after ``hedge_after`` a still-running request is
    duplicated onto the next-best replica; the first copy to finish
    wins, the loser is cancelled (NOT counted as a served completion),
    and hedges are counted separately from failovers."""
    import time

    model = _model()
    server = KVStoreServer()
    router = None
    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        router, _roll = _fleet(server, model, n=2, hedge_after=1e-4,
                               min_canary_requests=2)
        assert pub.publish({"params": _params(model)}, 1) == 1
        router.pump()

        (prompt,) = _ragged_prompts(3, (6,))
        freq = router.submit("h-0", prompt, 3)
        time.sleep(0.01)  # > hedge_after: the next pump hedges
        router.pump()
        assert freq.hedged
        assert [r.id for r, _ in freq.copies] == ["r0", "r1"]
        assert metrics.value("fleet_requests_hedged") == 1.0
        router.drain()
        assert freq.error is None
        # the primary started decoding first: it wins, the hedge copy
        # is cancelled mid-flight on the other replica
        assert freq.replica == "r0"
        loser = freq.copies[1][1]
        assert loser.error is not None
        assert str(loser.error).startswith("cancelled")
        # exactly one fleet-level completion; the cancelled loser never
        # reaches the gate windows or the error-rate SLO series
        assert metrics.value("fleet_requests", arm="stable",
                             outcome="ok") == 1.0
        win = router.merged_window("stable")
        assert win["done"] == 1 and win["errors"] == 0
        assert metrics.value("fleet_requests_failed_over") is None
    finally:
        if router is not None:
            router.close()
        server.close()


# ------------------------------------------------------- graceful drain


def test_drain_replica_quiesce_finish_deregister(hvd):
    """Drain protocol: quiesce (no new routes), finish in-flight work,
    deregister — the KV lease is *tombstoned* (drained cleanly), not
    expired, and subsequent traffic routes around the drained
    replica."""
    model = _model()
    server = KVStoreServer()
    router = None
    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        router, _roll = _fleet(server, model, n=2, min_canary_requests=2)
        assert pub.publish({"params": _params(model)}, 1) == 1
        router.pump()
        r0 = router.replica("r0")
        assert server.get(r0.lease_key) is not None

        prompts = _ragged_prompts(4, (6, 7))
        inflight = router.submit("d-0", prompts[0], 3)
        assert inflight.copies[0][0].id == "r0"
        router.drain_replica("r0")
        assert inflight.error is None and inflight.done  # finished, not shed
        assert r0.deregistered and r0.engine.scheduler.idle()
        assert r0.state_code() == 4  # STATE_DRAINED
        # tombstoned lease: readers see "dead", not "never written"
        assert server.get(r0.lease_key) is None
        assert server._get_with_liveness(r0.lease_key)[1] is True
        assert server.get(r0.status_key) is None

        assert [r.id for r in router.candidates("stable")] == ["r1"]
        after = router.submit("d-1", prompts[1], 2)
        router.drain()
        assert after.error is None and after.replica == "r1"
    finally:
        if router is not None:
            router.close()
        server.close()


# ------------------------------------- fleet rollout: promote path


def test_fleet_promotion_one_decision_commit_last(hvd):
    """A healthy canary promotes fleet-wide through ONE decision: the
    epoch log lands before the head pointer (commit-last), every
    replica applies strictly behind its ``applied_epoch`` fence, and
    the per-arm/rollout gauges track the state machine."""
    events = []
    model = _model()
    server = KVStoreServer()
    router = None
    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        router, roll = _fleet(server, model, n=3, min_canary_requests=4,
                              on_event=lambda e, g: events.append((e, g)))
        p1 = _params(model)
        assert pub.publish({"params": p1}, 1) == 1
        router.pump()
        assert roll.stable_generation == 1 and roll.epoch == 1

        p2 = jax.tree_util.tree_map(lambda a: a + 0.01, p1)
        assert pub.publish({"params": p2}, 2) == 2
        router.pump()
        assert roll.canary_generation == 2 and roll.epoch == 2
        for r in router.replicas:
            assert r.engine.arm_generation("canary") == 2

        prompts = _ragged_prompts(6, (6, 9, 5, 7))
        reqs = [router.submit(f"p-{i}", p, 2)
                for i, p in enumerate(prompts)]
        router.drain()
        assert all(q.error is None for q in reqs)
        assert roll.stable_generation == 2
        assert roll.canary_generation is None
        assert ("promoted", 2) in events
        for r in router.replicas:
            assert r.engine.arm_generation("stable") == 2
            assert r.applied_epoch == 3  # bootstrap, canary, promote

        # the decision log through the KV: commit-last head agrees
        head = json.loads(server.get("/fleetserve/rollout/epoch"))
        assert head["epoch"] == 3 == roll.head_epoch()
        last = json.loads(server.get("/fleetserve/rollout/decision/3"))
        assert last["action"] == "promote" and last["generation"] == 2
        assert metrics.value("fleet_serving_decisions",
                             action="promote") == 1.0
        assert metrics.value("fleet_serving_rollouts",
                             outcome="promoted") == 1.0
        assert metrics.value("fleet_serving_stable_generation") == 2.0
        assert metrics.value("fleet_serving_canary_generation") == -1.0
        assert metrics.value("fleet_serving_rollout_state") == 0.0
        assert metrics.value("fleet_serving_rollout_epoch") == 3.0
    finally:
        if router is not None:
            router.close()
        server.close()


# ----------------------------------------- THE fleet rollback drill


@pytest.mark.chaos
def test_e2e_fleet_rollback_drill(hvd, monkeypatch):
    """THE ISSUE-17 drill: guarded training on the 8-device mesh →
    publish G1/G2 → fleet-wide canary with ``slow_decode`` scoped to
    ONE replica's canary arm (``canary@r1``) → the fleet-merged TTFT
    window burns → one KV-coordinated rollback rolls ALL replicas back
    to G1 naming the objective; the vetoed generation serves nowhere,
    every request completed, post-rollback tokens are bit-identical to
    ``generate()`` on the healthy weights on every replica, and the
    training step's collective-schedule fingerprint is byte-equal
    before and after."""
    from horovod_tpu.analysis.schedule import collective_schedule
    from horovod_tpu.resilience import numerics
    from horovod_tpu.training import (
        make_shardmap_train_step,
        replicate,
        shard_batch,
        token_xent,
    )
    from tools import hvd_blackbox

    monkeypatch.setenv("HOROVOD_NUMERICS_WARMUP", "1")
    model = _model(depth=1, vocab=64, dim=32, heads=2, max_len=32)
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    tx = numerics.guard(optax.adam(1e-2))
    step = make_shardmap_train_step(
        model, tx, loss_fn=token_xent, instrument=False, donate=False)
    rng = np.random.RandomState(0)
    toks = rng.randint(1, 64, size=(16, 9)).astype(np.int32)
    xs, ys = shard_batch(toks[:, :-1]), shard_batch(toks[:, 1:])
    params = replicate(jax.tree_util.tree_map(jnp.array, params0))
    opt_state = tx.init(params)

    slo.configure("ttft_p99<0.05", fast_window=256, slow_window=256)
    server = KVStoreServer()
    router = None
    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        router, roll = _fleet(server, model, n=3, min_canary_requests=6,
                              engine_kw={"max_seq_len": 24})

        def train_one():
            nonlocal params, opt_state
            params, _, opt_state, _ = step(params, {}, opt_state, xs, ys)

        fp_before = collective_schedule(
            step, params, {}, opt_state, xs, ys).fingerprint()

        # G1 commits and bootstraps the whole fleet
        train_one()
        assert pub.publish(
            {"params": params, "opt_state": opt_state}, 1) == 1
        router.pump()
        assert roll.stable_generation == 1
        healthy = jax.device_get(pub.reconstruction())
        prompts = _ragged_prompts(5, (6, 9, 5, 7, 8, 6), vocab=64)
        warm = [router.submit(f"warm-{i}", p, 2)
                for i, p in enumerate(prompts)]
        router.drain()
        assert all(w.error is None for w in warm)
        assert sorted({w.replica for w in warm}) == ["r0", "r1", "r2"]

        # G2 canaries fleet-wide; ONE replica's canary arm decodes slow
        train_one()
        assert pub.publish(
            {"params": params, "opt_state": opt_state}, 2) == 2
        router.pump()
        assert roll.canary_generation == 2
        for r in router.replicas:
            assert r.engine.arm_generation("canary") == 2
        chaos.configure("slow_decode=0.15:canary@r1")
        reqs = [router.submit(f"drill-{i}", p, 2)
                for i, p in enumerate(prompts)]
        router.drain()

        # one fleet-wide verdict: ALL replicas back to G1, objective
        # named, the vetoed generation serving nowhere
        assert all(q.error is None for q in reqs)  # nothing dropped
        assert roll.stable_generation == 1
        assert 2 in roll.vetoed and roll.canary_generation is None
        router.pump()  # drained canary arms release on the next step
        for r in router.replicas:
            assert r.engine.arm_generation("canary") is None
            assert r.engine.arm_generation("stable") == 1
            assert r.applied_epoch == 3  # bootstrap, canary, rollback
        last = json.loads(server.get("/fleetserve/rollout/decision/3"))
        assert last["action"] == "rollback" and last["generation"] == 2
        assert "ttft_p99" in health.snapshot()["reason"]
        assert metrics.value("resilience_slo_burns",
                             objective="ttft_p99") == 1.0
        assert metrics.value("fleet_serving_rollouts",
                             outcome="rolled_back") == 1.0
        assert metrics.value("fleet_serving_decisions",
                             action="rollback") == 1.0
        assert metrics.value("resilience_chaos_injected",
                             site="slow_decode") >= 1.0

        # every request completed exactly once across the fleet
        flight.flush()
        evs = [e for e in flight.events() if e.get("kind") == "serve"]
        summary = hvd_blackbox.request_summary({0: evs})
        assert summary[0].endswith("0 STRANDED")
        assert reqtrace.live_requests() == []

        # post-rollback traffic decodes under G1, bit-identical to
        # generate() on the healthy commit — on EVERY replica
        chaos.configure(None)
        want = _reference_generate(model, healthy, prompts, 3)
        after = [router.submit(f"after-{i}", p, 3)
                 for i, p in enumerate(prompts)]
        router.drain()
        assert sorted({q.replica for q in after}) == ["r0", "r1", "r2"]
        for q, ref in zip(after, want):
            assert q.error is None
            np.testing.assert_array_equal(np.asarray(q.generated), ref)
        for r in router.replicas:
            for got, ref in zip(
                jax.tree_util.tree_leaves(
                    r.engine.arm_params("stable")),
                jax.tree_util.tree_leaves(healthy),
            ):
                np.testing.assert_array_equal(np.asarray(got),
                                              np.asarray(ref))

        # serving added no training-side collectives
        fp_after = collective_schedule(
            step, params, {}, opt_state, xs, ys).fingerprint()
        assert fp_after == fp_before
    finally:
        if router is not None:
            router.close()
        server.close()


# ------------------------------------------------ hvd_top: fleet pane


def test_hvd_top_fleet_serving_pane():
    """Satellite: hvd_top renders a FLEET-SERVING pane — rollout
    epoch/generations, hedge/failover counts, the backpressure hint,
    per-arm outcomes, and one row per replica — and omits it when no
    fleet-serving series exist."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "hvd_top", os.path.join(_REPO, "tools", "hvd_top.py"))
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)

    def g(v):
        return {"samples": {"": {"ranks": {"0": v}, "min": v, "mean": v,
                                 "max": v, "p99": v}}, "type": "gauge",
                "help": ""}

    def lg(samples):
        return {
            "type": "gauge", "help": "",
            "samples": {
                k: {"ranks": {"0": v}, "min": v, "mean": v, "max": v,
                    "p99": v}
                for k, v in samples.items()
            },
        }

    def c(samples):
        return {
            "type": "counter", "help": "",
            "samples": {
                k: {"ranks": {"0": v}, "min": v, "mean": v, "max": v,
                    "p99": v}
                for k, v in samples.items()
            },
        }

    fleet = {
        "collected_at": 0.0, "ranks": [0], "dead_ranks": [],
        "straggler": None,
        "metrics": {
            "fleet_serving_rollout_epoch": g(3),
            "fleet_serving_stable_generation": g(2),
            "fleet_serving_canary_generation": g(-1),
            "fleet_backpressure_hint_seconds": g(0.04),
            "fleet_requests_hedged": c({"": 2}),
            "fleet_requests_failed_over": c({"": 1}),
            "fleet_requests": c({
                "arm=stable,outcome=ok": 40,
                "arm=canary,outcome=ok": 7,
                "arm=stable,outcome=rejected": 1,
            }),
            "fleet_serving_replica_state": lg({
                "replica=r0": 0, "replica=r1": 3}),
            "fleet_serving_replica_queue_depth": lg({
                "replica=r0": 2, "replica=r1": 0}),
            "fleet_serving_replica_pages_in_use": lg({
                "replica=r0": 6, "replica=r1": 0}),
            "fleet_serving_replica_staleness_seconds": lg({
                "replica=r0": 1.5}),
        },
    }
    out = top.render(fleet)
    assert "FLEET-SERVING:" in out
    assert "rollout epoch 3" in out
    assert "stable gen 2" in out and "canary gen -1" in out
    assert "hedged 2" in out and "failed over 1" in out
    assert "backpressure hint 0.04s" in out
    assert "requests arm=canary: ok=7" in out
    assert "requests arm=stable: ok=40 rejected=1" in out
    assert "replica r0: queue 2, pages 6, staleness 1.5s, " \
           "state healthy" in out
    assert "replica r1:" in out and "state dead" in out
    # no fleet-serving series -> no pane
    assert "FLEET-SERVING:" not in top.render(
        {"ranks": [0], "dead_ranks": [], "straggler": None,
         "metrics": {"train_steps": g(3)}})
