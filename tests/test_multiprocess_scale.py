"""Control-plane scale test: the full negotiation protocol at np=8.

VERDICT r3 item 3: the controller's O(ranks) gather/bcast and the cache
bitvector sync had only run at np<=4. Historically (in the reference) the
protocol bugs surface at higher/odd rank counts: displacement math in
allgather, multi-word bitvectors (>64 cached entries), join bookkeeping with
many live ranks, and the tuned-parameter broadcast. One np=8 launcher run
covers all four, with >64 named tensors so the cache bitvector spans two
uint64 words (reference ``response_cache.cc`` capacity bits).
"""

import os

import numpy as np
import pytest

from horovod_tpu.run import runner

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


def _worker_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [_REPO_ROOT, _TESTS_DIR, env.get("PYTHONPATH", "")]
    )
    return env


def _setup_worker():
    """Common worker env: 1-chip CPU pin + fast cycles (mirrors
    test_native_core_e2e._setup_worker, minus the timeline)."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["HOROVOD_CYCLE_TIME"] = "2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    assert hvd.basics._state.core is not None, "native core not attached"
    return hvd


def _eight_proc_protocol():
    import numpy as np

    from horovod_tpu.core import REQUEST_ALLREDUCE

    hvd = _setup_worker()
    core = hvd.basics._state.core
    r = hvd.process_rank()
    out = {"rank": r, "size": hvd.size()}

    # --- 1. >64 named tensors x 3 steps: step 1 negotiates by name, steps
    # 2-3 ride the cache bitvector AND across TWO uint64 words at np=8 ---
    n_names = 80
    x = np.full((4,), float(r + 1), np.float32)
    want = float(sum(range(1, 9)))  # Sum over 8 ranks of (r+1)
    ok_steps = 0
    for step in range(3):
        hs = [core.enqueue(f"t{i}", x, REQUEST_ALLREDUCE, op=1)
              for i in range(n_names)]
        vals = [np.asarray(h.wait(timeout=120)) for h in hs]
        if all(np.allclose(v, want) for v in vals):
            ok_steps += 1
    out["ok_steps"] = ok_steps

    # --- 2. allgather displacement math with 8 distinct row counts ---
    g = np.full((r + 1, 2), float(r), np.float32)  # rank r contributes r+1 rows
    gathered = np.asarray(hvd.allgather(g))
    rows = []
    for rr in range(8):
        rows.extend([[float(rr)] * 2] * (rr + 1))
    out["gather_ok"] = bool(np.allclose(gathered, np.asarray(rows)))

    # --- 3. join at np=8: rank 7 joins; the other 7 reduce ---
    if r == 7:
        out["join_rank"] = int(hvd.join())
    else:
        h = core.enqueue("joined_t", x, REQUEST_ALLREDUCE, op=1)
        v = np.asarray(h.wait(timeout=120))
        # 7 live ranks: sum over r=0..6 of (r+1) = 28; rank 7 backfills zeros
        out["join_sum_ok"] = bool(np.allclose(v, 28.0))
        out["join_rank"] = int(hvd.join())
    return out


@pytest.mark.slow
def test_eight_process_protocol():
    out = runner.run(
        _eight_proc_protocol, np=8, env=_worker_env(), timeout_s=600,
        use_native_core=True
    )
    assert len(out) == 8
    for r, res in enumerate(out):
        assert res["rank"] == r and res["size"] == 8
        assert res["ok_steps"] == 3, res
        assert res["gather_ok"], res
        if r != 7:
            assert res["join_sum_ok"], res
        # join handle reports the last rank to join, consistent everywhere
    last = {res["join_rank"] for res in out}
    assert len(last) == 1, out


def _eight_proc_autotune():
    import os

    os.environ["HOROVOD_AUTOTUNE"] = "1"
    os.environ["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"] = "1"
    os.environ["HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"] = "2"
    os.environ["HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"] = "3"
    import numpy as np

    from horovod_tpu.core import REQUEST_ALLREDUCE

    hvd = _setup_worker()
    core = hvd.basics._state.core
    r = hvd.process_rank()
    x = np.ones((64,), np.float32)
    # FIXED step count on every rank: breaking early on a local
    # autotune_active() read desyncs the job (ranks see the flip on
    # different steps and stop enqueueing while peers still wait)
    for step in range(40):
        hs = [core.enqueue(f"a{i}", x, REQUEST_ALLREDUCE, op=1)
              for i in range(8)]
        for h in hs:
            h.wait(timeout=120)
    # tuned values must have been broadcast: every rank applies the same
    # (cycle, fusion) pair chosen by rank 0's GP search
    return {
        "rank": r,
        "active": core.autotune_active(),
        "cycle": core.cycle_time_ms,
        "fusion": core.fusion_threshold,
        "cache": core.cache_enabled(),
    }


@pytest.mark.slow
def test_eight_process_autotune_broadcast():
    out = runner.run(
        _eight_proc_autotune, np=8, env=_worker_env(), timeout_s=600,
        use_native_core=True
    )
    assert len(out) == 8
    assert not any(res["active"] for res in out), out  # search converged
    cycles = {round(res["cycle"], 3) for res in out}
    fusions = {res["fusion"] for res in out}
    caches = {res["cache"] for res in out}
    assert len(cycles) == 1, out
    assert len(fusions) == 1, out
    assert len(caches) == 1, out


def _two_proc_hier_toggle():
    import numpy as np

    from horovod_tpu.core import REQUEST_ALLREDUCE
    from horovod_tpu.ops import hierarchical

    hvd = _setup_worker()
    core = hvd.basics._state.core
    r = hvd.process_rank()
    x = np.ones((8,), np.float32)
    out = {
        "rank": r,
        "before": hierarchical.enabled(),
        "applied_before": core.hier_allreduce(),
    }
    for _ in range(3):  # steady state first
        hs = [core.enqueue(f"h{i}", x, REQUEST_ALLREDUCE, op=1)
              for i in range(4)]
        for h in hs:
            h.wait(timeout=120)
    # rank 0 injects a mid-run retune; it rides the NEXT cycle's negotiated
    # broadcast, so both ranks apply it at the same cycle boundary (workers
    # may not call this — it is a coordinator no-op there)
    core.set_autotuned_params(hier_allreduce=1, hier_allgather=1)
    landed_at = -1
    for step in range(20):
        hs = [core.enqueue(f"h{i}", x, REQUEST_ALLREDUCE, op=1)
              for i in range(4)]
        for h in hs:
            h.wait(timeout=120)
        if landed_at < 0 and hierarchical.enabled():
            landed_at = step
    out["after"] = hierarchical.enabled()
    out["allgather_after"] = hierarchical.allgather_enabled()
    out["applied_after"] = core.hier_allreduce()
    out["landed_at"] = landed_at
    hierarchical.set_hierarchical(None)
    hierarchical.set_hierarchical_allgather(None)
    return out


@pytest.mark.slow
def test_two_process_hier_toggle_broadcast():
    """VERDICT r4 item 3: the hierarchical strategy pair is a tuned
    parameter. A rank-0 mid-run retune must ride the coordinator broadcast
    and flip ops/hierarchical's strategy on EVERY rank at a cycle boundary
    (reference parameter_manager.cc:44-60 + operations.cc:455-469)."""
    out = runner.run(
        _two_proc_hier_toggle, np=2, env=_worker_env(), timeout_s=300,
        use_native_core=True,
    )
    assert len(out) == 2
    for res in out:
        assert res["before"] is False and res["applied_before"] == -1, res
        assert res["after"] is True, res
        assert res["allgather_after"] is True, res
        assert res["applied_after"] == 1, res
        assert res["landed_at"] >= 0, res


def _two_proc_rejoin_cache():
    import numpy as np

    from horovod_tpu.core import REQUEST_ALLREDUCE

    hvd = _setup_worker()
    core = hvd.basics._state.core
    r = hvd.process_rank()
    out = {"rank": r}
    x = np.ones((4,), np.float32)
    for _ in range(2):  # steady state on a warm-up name
        core.enqueue("w", x, REQUEST_ALLREDUCE, op=1).wait(timeout=120)

    # a MULTI-DIM tensor first negotiated while rank 1 is joined: rank 1
    # caches it from the broadcast with a reconstructed request — the
    # response carries the true shape, so the key matches the live ranks'
    u = np.ones((2, 3), np.float32)
    if r == 1:
        out["join_rank"] = int(hvd.join())
    else:
        for _ in range(2):  # negotiate, then cache-hit with rank 1 joined
            v = np.asarray(
                core.enqueue("u", u, REQUEST_ALLREDUCE, op=1).wait(timeout=120)
            )
        out["joined_sum_ok"] = bool(np.allclose(v, 1.0))  # rank 1 backfilled 0
        out["join_rank"] = int(hvd.join())

    # post-rejoin: BOTH ranks enqueue u. A shape-faithful cache means rank
    # 1's first pop is a HIT (hit counter advances); a flat-shape
    # reconstruction would be INVALID and renegotiate (counter stalls).
    hits_before = core.cache_hit_count()
    v = np.asarray(core.enqueue("u", u, REQUEST_ALLREDUCE, op=1).wait(timeout=120))
    out["post_rejoin_sum_ok"] = bool(np.allclose(v, 2.0))
    out["hit_delta"] = core.cache_hit_count() - hits_before
    return out


@pytest.mark.slow
def test_two_process_rejoin_cache_hits_without_renegotiation():
    """VERDICT r4 item 6: a joined rank reconstructs cache entries from the
    response broadcast; the response now carries the TRUE shape, so the
    post-rejoin enqueue cache-HITs instead of invalidating and renegotiating
    (reference response_cache.h:45-167 keys on shape)."""
    out = runner.run(
        _two_proc_rejoin_cache, np=2, env=_worker_env(), timeout_s=300,
        use_native_core=True,
    )
    assert len(out) == 2
    for res in out:
        assert res["post_rejoin_sum_ok"], res
        # the first post-rejoin pop of "u" is a globally-agreed HIT on BOTH
        # ranks — rank 1 never negotiated "u" by name
        assert res["hit_delta"] >= 1, res
    assert out[0]["joined_sum_ok"], out


def _eight_proc_reorder_soak():
    import numpy as np

    hvd = _setup_worker()
    r = hvd.process_rank()
    n_tensors, rounds = 32, 3
    rank_sum = sum(i + 1 for i in range(8))  # 36
    out = {"rank": r, "bad": []}
    for rnd in range(rounds):
        order = np.random.RandomState(1000 * rnd + r).permutation(n_tensors)
        handles = {}
        for i in order:
            shape = [(3,), (2, 2), (5,), (1,)][i % 4]
            val = np.full(shape, float((r + 1) * (i + 1) * (rnd + 1)),
                          np.float32)
            handles[int(i)] = hvd.allreduce_async(
                val, op=hvd.Sum, name=f"soak8.{i}")
        for i, h in handles.items():
            got = np.asarray(h.wait(timeout=150))
            expect = np.full([(3,), (2, 2), (5,), (1,)][i % 4],
                             float(rank_sum * (i + 1) * (rnd + 1)),
                             np.float32)
            if not np.array_equal(got, expect):
                out["bad"].append((int(i), got.tolist()))
    return out


def _eight_proc_resnet_e2e():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.core import REQUEST_ALLREDUCE
    from horovod_tpu.models import ResNet50

    hvd = _setup_worker()
    core = hvd.basics._state.core
    core.cycle_time_ms = 10  # batch the 161-name burst into few cycles
    r, n = hvd.process_rank(), hvd.process_size()

    # identical init everywhere; train=False keeps BatchNorm on its running
    # stats so per-rank gradient averaging is MATHEMATICALLY identical to
    # the full-batch gradient (train=True batch stats are shard-dependent)
    model = ResNet50(num_classes=10, num_filters=4, dtype=jnp.float32)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3), jnp.float32),
        train=False)
    params0, batch_stats = variables["params"], variables.get(
        "batch_stats", {})

    rs = np.random.RandomState(0)
    batch = 2 * n
    X = rs.rand(batch, 16, 16, 3).astype(np.float32)
    Y = rs.randint(0, 10, batch)

    def loss_fn(p, x, y):
        logits = model.apply(
            {"params": p, "batch_stats": batch_stats}, x, train=False)
        oh = jax.nn.one_hot(y, 10)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * oh, axis=-1))

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    def run_steps(params, x, y, *, distributed, steps=3, lr=0.1):
        losses = []
        leaves, treedef = jax.tree_util.tree_flatten(params)
        names = [f"r50.{i}" for i in range(len(leaves))]
        for _ in range(steps):
            loss, grads = grad_fn(params, x, y)
            gl, _ = jax.tree_util.tree_flatten(grads)
            if distributed:
                # the reference's canonical flow: every gradient leaf (and
                # the scalar loss, for job-wide metrics) enqueued BY NAME
                # through the background negotiation cycle
                hs = [
                    core.enqueue(nm, np.asarray(g), REQUEST_ALLREDUCE, op=0)
                    for nm, g in zip(names, gl)
                ]
                hl = core.enqueue(
                    "r50.loss", np.asarray(loss), REQUEST_ALLREDUCE, op=0)
                gl = [np.asarray(h.wait(timeout=300)) for h in hs]
                # equal shards: the rank-averaged loss IS the full-batch loss
                loss = hl.wait(timeout=300)
            leaves = [
                l - lr * jnp.asarray(g)
                for l, g in zip(jax.tree_util.tree_leaves(params), gl)
            ]
            params = jax.tree_util.tree_unflatten(treedef, leaves)
            losses.append(float(loss))
        return losses

    # distributed: each rank owns a distinct equal shard
    Xr, Yr = X[r::n], Y[r::n]
    dist_losses = run_steps(params0, Xr, Yr, distributed=True)
    # single-process reference: full batch, no exchange (every rank computes
    # it — deterministic, so it doubles as a cross-rank consistency check)
    full_losses = run_steps(params0, X, Y, distributed=False)
    return {
        "rank": r,
        "n_grad_tensors": len(jax.tree_util.tree_leaves(params0)),
        "dist_losses": dist_losses,
        "full_losses": full_losses,
    }


@pytest.mark.slow
def test_eight_process_resnet50_core_e2e_loss_parity():
    """VERDICT r4 item 5: the protocol at np=8 with a REAL model — all
    ~161 ResNet-50 gradient leaves enqueued by name through the core each
    step. The per-rank distributed loss must track the single-process
    full-batch loss (equal shards + mean loss => gradient averaging is the
    full-batch gradient). Reference canonical config:
    .buildkite/gen-pipeline.sh:124 scaled to 8 ranks."""
    out = runner.run(
        _eight_proc_resnet_e2e, np=8, env=_worker_env(), timeout_s=900,
        use_native_core=True,
    )
    assert len(out) == 8
    ref = out[0]
    assert ref["n_grad_tensors"] >= 100, ref["n_grad_tensors"]
    for res in out:
        # distributed losses identical on every rank (same reduced grads)
        np.testing.assert_allclose(
            res["dist_losses"], ref["dist_losses"], rtol=1e-5)
        # and equal to the single-process full-batch run
        np.testing.assert_allclose(
            res["dist_losses"], res["full_losses"], rtol=2e-3)
    # training actually moved
    assert ref["dist_losses"][-1] < ref["dist_losses"][0], ref


@pytest.mark.slow
def test_eight_process_reorder_soak():
    """The np=2 reorder soak scaled to 8 ranks x 3 rounds: 8 distinct
    enqueue orders per round stress the coordinator's ordering guarantee
    and the cache bitvector AND under real cross-process skew (this class
    of protocol stress is what exposed the np=8 cache-toggle deadlock)."""
    out = runner.run(
        _eight_proc_reorder_soak, np=8, env=_worker_env(), timeout_s=600,
        use_native_core=True,
    )
    assert len(out) == 8
    for res in out:
        assert res["bad"] == [], res
