"""Observability layer: metrics registry semantics, exporter formats,
instrumentation hooks (eager ops, native-core cycle callback), the merged
host+native chrome-trace timeline, and the import-side-effect guard — plus
the ISSUE 7 fleet plane: cross-rank snapshot aggregation over the
rendezvous KV, clock-offset estimation, correlated per-rank collective
traces, and deterministic straggler attribution.

No reference analog — upstream Horovod's only observability surface is the
chrome Timeline; the queryable registry is this rebuild's addition
(ISSUE 1). Tier-1: everything here runs on the 8-device CPU mesh."""

import json
import os
import re
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from horovod_tpu.observability import (
    aggregate,
    clock,
    exporters,
    metrics,
    straggler,
    trace,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Every test sees an empty default registry, a clean trace buffer, and
    an unsynchronized fleet layer."""
    metrics.reset()
    metrics.set_enabled(True)
    trace.reset()
    straggler.reset()
    clock.reset()
    aggregate.set_aggregator(None)
    yield
    metrics.reset()
    metrics.set_enabled(True)
    trace.reset()
    straggler.reset()
    clock.reset()
    aggregate.set_aggregator(None)


# ------------------------------------------------------------ registry


def test_counter_semantics():
    c = metrics.counter("requests")
    c.inc()
    c.inc(4)
    assert metrics.counter("requests").value == 5.0
    with pytest.raises(ValueError, match=">= 0"):
        c.inc(-1)


def test_labeled_children_are_distinct():
    metrics.counter("allreduce_bytes", rank=0).inc(100)
    metrics.counter("allreduce_bytes", rank=1).inc(7)
    metrics.counter("allreduce_bytes").inc(1)  # unlabeled child coexists
    snap = metrics.snapshot()["allreduce_bytes"]
    assert snap["type"] == "counter"
    assert snap["samples"]["rank=0"] == 100.0
    assert snap["samples"]["rank=1"] == 7.0
    assert snap["samples"][""] == 1.0
    assert metrics.value("allreduce_bytes", rank=1) == 7.0
    assert metrics.value("allreduce_bytes", rank=9) is None


def test_gauge_set_inc():
    g = metrics.gauge("util")
    g.set(0.5)
    g.inc(0.25)
    g.dec(0.5)
    assert abs(metrics.value("util") - 0.25) < 1e-12


def test_histogram_buckets():
    h = metrics.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    s = metrics.value("lat")
    assert s["count"] == 5
    assert abs(s["sum"] - 5.605) < 1e-9
    # cumulative, prometheus-style, with the implicit +Inf tail
    assert s["buckets"]["0.01"] == 1
    assert s["buckets"]["0.1"] == 3
    assert s["buckets"]["1.0"] == 4
    assert s["buckets"]["+Inf"] == 5
    h.observe(float("nan"))  # must not poison sum/count
    assert metrics.value("lat")["count"] == 5


def test_kind_conflict_raises():
    metrics.counter("x").inc()
    with pytest.raises(ValueError, match="already registered"):
        metrics.gauge("x")


def test_disabled_is_noop():
    metrics.set_enabled(False)
    c = metrics.counter("never")
    c.inc(100)
    h = metrics.histogram("never_h")
    h.observe(1.0)
    metrics.set_enabled(True)
    assert "never" not in metrics.snapshot()
    assert metrics.value("never") is None


def test_thread_safety_smoke():
    n_threads, n_inc = 8, 2000

    def worker():
        for _ in range(n_inc):
            metrics.counter("contended").inc()
            metrics.histogram("contended_h", buckets=(1, 2)).observe(1)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert metrics.value("contended") == n_threads * n_inc
    assert metrics.value("contended_h")["count"] == n_threads * n_inc


def test_summary_renders():
    metrics.counter("a").inc(2)
    metrics.histogram("b").observe(0.01)
    out = metrics.summary()
    assert "a" in out and "b" in out and "count=1" in out


# ------------------------------------------------------------ exporters

_PROM_LINE = re.compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+-]+(inf|nan)?)$"
)


def test_prometheus_exposition_parses():
    metrics.counter("allreduce_count").inc(3)
    metrics.counter("allreduce_bytes", rank=0).inc(1024)
    metrics.gauge("train_mfu").set(0.41)
    metrics.histogram("cycle", buckets=(0.5, 1.5)).observe(1.0)
    text = exporters.to_prometheus()
    assert text.endswith("\n")
    for line in text.rstrip("\n").splitlines():
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
    assert "allreduce_count 3" in text
    assert 'allreduce_bytes{rank="0"} 1024' in text
    assert 'cycle_bucket{le="+Inf"} 1' in text
    assert "cycle_sum 1" in text
    assert "cycle_count 1" in text
    assert "# TYPE cycle histogram" in text


def test_prometheus_nonfinite_samples_render():
    """inf/nan samples must render as exposition spellings, not crash the
    scrape handler (int(inf) raises)."""
    metrics.gauge("pos").set(float("inf"))
    metrics.gauge("neg").set(float("-inf"))
    metrics.gauge("nan").set(float("nan"))
    metrics.histogram("h", buckets=(1.0,)).observe(float("inf"))
    text = exporters.to_prometheus()
    assert "pos +Inf" in text
    assert "neg -Inf" in text
    assert "nan NaN" in text
    assert "h_sum +Inf" in text


def test_trace_recording_gate():
    """set_recording(False) (what init() applies on ranks != 0) silences
    span/instant recording even with HOROVOD_TIMELINE set; the buffer cap
    drops rather than grows past MAX_BUFFERED_EVENTS."""
    os.environ["HOROVOD_TIMELINE"] = "/tmp/_never_written.json"
    try:
        trace.reset()
        trace.set_recording(False)
        with trace.span("t", "x"):
            pass
        trace.instant("t", "y")
        assert trace.events() == []
        trace.set_recording(True)
        with trace.span("t", "x"):
            pass
        assert len(trace.events()) == 1
    finally:
        del os.environ["HOROVOD_TIMELINE"]
        trace.reset()


def test_json_exporter_roundtrips():
    metrics.counter("c", job="x").inc(2)
    data = json.loads(exporters.to_json())
    assert data["c"]["samples"]["job=x"] == 2.0


def test_http_endpoint_serves_both_formats():
    metrics.counter("served").inc(9)
    server = exporters.start_http_server(0, host="127.0.0.1")
    try:
        port = server.server_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            body = r.read().decode()
            assert "served 9" in body
            assert r.headers["Content-Type"].startswith("text/plain")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=10
        ) as r:
            assert json.load(r)["served"]["samples"][""] == 9.0
    finally:
        exporters.stop_http_server()


# ------------------------------------------- instrumentation: eager ops


def test_eager_allreduce_feeds_registry(hvd):
    out = hvd.allreduce(np.ones((8, 4), np.float32), op=hvd.Sum)
    out2 = hvd.allreduce(np.ones((8, 4), np.float32), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out))
    assert metrics.value("allreduce_count") == 2
    assert metrics.value("allreduce_bytes") == 2 * 8 * 4 * 4
    # same (mesh, axis, shape) twice: first lookup compiles, second hits
    assert metrics.value("eager_compile_cache_misses", kind="allreduce") >= 1
    assert metrics.value("eager_compile_cache_hits", kind="allreduce") >= 1


def test_grouped_and_other_ops_feed_registry(hvd):
    hvd.grouped_allreduce(
        [np.ones((4,), np.float32), np.ones((2, 2), np.float32)], hvd.Sum
    )
    hvd.allgather(np.ones((2, 3), np.float32))
    hvd.reducescatter(np.ones((8, 2), np.float32), hvd.Sum)
    assert metrics.value("allreduce_tensors") == 2
    assert metrics.value("allreduce_bytes") == 4 * 4 + 4 * 4
    assert metrics.value("allgather_count") == 1
    assert metrics.value("reducescatter_count") == 1


def test_train_step_instrumentation(hvd):
    import optax

    from horovod_tpu import models
    from horovod_tpu.training import (
        init_model, make_jit_train_step, replicate, shard_batch,
    )

    model = models.MLP(features=(8, 4))
    tx = optax.sgd(0.1)
    import jax
    import jax.numpy as jnp

    params, batch_stats = init_model(
        model, jax.random.PRNGKey(0), jnp.zeros((1, 6), jnp.float32)
    )
    params = replicate(params)
    opt_state = replicate(tx.init(params))
    step = make_jit_train_step(model, tx)
    images = shard_batch(np.random.RandomState(0).rand(16, 6).astype("f"))
    labels = shard_batch(np.random.RandomState(1).randint(0, 4, 16))
    for _ in range(3):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
    assert metrics.value("train_steps") == 3
    assert metrics.value("train_examples") == 3 * 16
    # interval histogram needs 2+ calls
    assert metrics.value("train_step_seconds")["count"] == 2
    assert metrics.value("train_examples_per_sec") > 0


# -------------------------------- instrumentation: native-core cycle path


def test_core_cycle_metrics_and_merged_timeline(monkeypatch, tmp_path):
    """The acceptance loop of ISSUE 1 in-process: named async allreduces
    through the native core populate the cycle-latency histogram and cache
    counters, and shutdown merges host spans into the native chrome-trace
    file — one valid-JSON Perfetto load with both pid lanes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd

    timeline = str(tmp_path / "merged_timeline.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", timeline)
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2")
    hvd.shutdown()
    trace.reset()  # re-read HOROVOD_TIMELINE under the monkeypatch
    hvd.init(native_core=True)
    try:
        x = jax.device_put(
            np.ones((hvd.size(), 4), np.float32),
            NamedSharding(hvd.mesh(), P(hvd.data_axis())),
        )
        for step in range(4):
            h = hvd.allreduce_async(x, op=hvd.Sum, name="grad")
            out = hvd.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), np.full((4,), 8.0))
    finally:
        hvd.shutdown()

    hist = metrics.value("core_cycle_latency_seconds")
    assert hist is not None and hist["count"] >= 1 and hist["sum"] > 0
    assert metrics.value("core_enqueued_tensors") == 4
    # steps 2..4 of the same name ride the response cache
    assert metrics.value("core_cache_hits") >= 1
    assert metrics.value("core_cycles") >= 1

    with open(timeline) as f:
        events = json.load(f)  # valid JSON or this throws
    pids = {str(e.get("pid")) for e in events}
    assert trace.HOST_PID in pids, pids  # host spans present
    assert "0" in pids, pids  # native-core events present
    host = [e for e in events if e.get("pid") == trace.HOST_PID]
    assert any(e.get("tid") == "enqueue" for e in host)
    assert any(e.get("tid") == "cycle" for e in host)


# -------------------------------------------------- import side effects


def test_metrics_import_has_no_jax_side_effects():
    """The registry must stay importable from collection-time contexts
    (pytest collecting under ``JAX_PLATFORMS=cpu``): importing it — even
    through the ``horovod_tpu`` package, which imports jax the library —
    must not initialize any JAX device backend, and using the registry and
    exporters must not either."""
    code = (
        "import horovod_tpu.observability.metrics as m\n"
        "import horovod_tpu.observability.exporters as e\n"
        "import horovod_tpu.observability.trace as t\n"
        "m.counter('x', rank=0).inc(3)\n"
        "m.histogram('h').observe(0.1)\n"
        "e.to_prometheus(); e.to_json()\n"
        "import sys\n"
        "jax = sys.modules.get('jax')\n"
        "if jax is not None:\n"
        "    from jax._src import xla_bridge\n"
        "    backends = getattr(xla_bridge, '_backends', None)\n"
        "    assert not backends, (\n"
        "        'observability import initialized a JAX backend: %r'\n"
        "        % backends)\n"
        "print('CLEAN')\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=_REPO, env=env, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "CLEAN" in out.stdout


# ------------------------------------------------- satellite: trace ring


def test_trace_ring_caps_and_counts_drops(monkeypatch):
    """The span buffer is a capped ring (HOROVOD_TRACE_MAX_SPANS): when
    full the OLDEST events are evicted (a soak keeps its newest window),
    the trace_spans_dropped counter records the loss, and flush appends a
    visible marker."""
    monkeypatch.setenv("HOROVOD_TIMELINE", "/tmp/_ring_never.json")
    monkeypatch.setenv("HOROVOD_TRACE_MAX_SPANS", "10")
    trace.reset()  # re-read both env knobs
    for i in range(15):
        trace.instant("t", f"ev{i}")
    evs = trace.events()
    assert len(evs) == 10
    names = [e["name"] for e in evs]
    assert "ev0" not in names and "ev4" not in names  # oldest gone
    assert "ev14" in names  # newest kept
    assert trace.dropped() == 5
    assert metrics.value("trace_spans_dropped") == 5
    out = str(trace.flush("/tmp/_ring_flush.json"))
    try:
        with open(out) as f:
            flushed = json.load(f)
        assert any("5 oldest events dropped" in e.get("name", "")
                   for e in flushed)
    finally:
        os.unlink(out)


# ------------------------------------------ satellite: exporter escaping


def test_prometheus_label_escaping():
    """Backslash/quote/newline in label values must render per the
    exposition format — a raw newline would terminate the sample line
    mid-way and corrupt every series after it."""
    metrics.counter("esc", path="a\\b").inc()
    metrics.counter("esc", msg='say "hi"').inc(2)
    metrics.counter("esc", txt="line1\nline2").inc(3)
    metrics.histogram("esc_h", buckets=(1.0,), q='x"y').observe(0.5)
    text = exporters.to_prometheus()
    assert "\n\n" not in text  # no sample line got split by a raw newline
    for line in text.rstrip("\n").splitlines():
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
    assert r'esc{path="a\\b"} 1' in text
    assert r'esc{msg="say \"hi\""} 2' in text
    assert r'esc{txt="line1\nline2"} 3' in text
    # labeled histogram keeps its explicit TYPE line + labeled expansion
    assert "# TYPE esc_h histogram" in text
    assert r'esc_h_bucket{q="x\"y",le="1.0"} 1' in text


# --------------------------------------------------- fleet: clock offsets


def test_clock_offset_estimation_synthetic():
    """A remote clock running 5s ahead estimates to offset ~= 5 with the
    half-RTT error bound."""
    import time as _time

    off, err = clock.estimate_offset(lambda: _time.monotonic() + 5.0)
    assert abs(off - 5.0) <= max(err, 1e-3)
    assert 0 <= err < 0.1


def test_clock_refresh_against_kv_server_and_http_client():
    """In-process and HTTP-probed offsets against the SAME KV server are
    both ~0 (same host clock), gauges land, and the trace clock_sync
    metadata is attached for the merge tool."""
    from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer

    server = KVStoreServer()
    try:
        off, err = clock.refresh_from_kv(server, rank=0)
        assert abs(off) < 0.05 and err < 0.05
        assert metrics.value("observability_clock_offset_seconds") == off
        assert metrics.value("observability_clock_error_seconds") == err
        server.start()
        client = KVStoreClient("127.0.0.1", server.port)
        off2, err2 = clock.refresh_from_kv(client, rank=1)
        assert abs(off2) < 0.5 and err2 < 0.5
        assert clock.info()["offset_s"] == off2
    finally:
        server.close()


def test_merge_rank_traces_applies_offsets(tmp_path):
    """Two rank files whose clock_sync metadata says their epochs are 1s
    apart merge onto one timebase: equal local ts land 1s apart, host
    lanes are renamed per rank, and correlation args survive."""
    def write(path, rank, epoch_ns, offset_s):
        events = [
            {"ph": "i", "pid": trace.HOST_PID, "tid": "meta",
             "name": "clock_sync", "ts": 0.0,
             "args": {"rank": rank, "epoch_monotonic_ns": epoch_ns,
                      "offset_s": offset_s, "error_s": 0.001}},
            {"ph": "X", "pid": f"rank{rank}", "tid": "allreduce",
             "name": "allreduce s0.0", "ts": 100.0, "dur": 5.0,
             "args": {"step": 0, "gen": 0, "seq": 0, "rank": rank}},
            {"ph": "X", "pid": trace.HOST_PID, "tid": "eager",
             "name": "allreduce:", "ts": 100.0, "dur": 5.0},
        ]
        with open(path, "w") as f:
            json.dump(events, f)

    p0 = tmp_path / "t0.json"
    p1 = tmp_path / "t1.json"
    write(p0, 0, epoch_ns=0, offset_s=0.0)
    write(p1, 1, epoch_ns=1_000_000_000, offset_s=0.0)  # epoch 1s later
    out = tmp_path / "merged.json"
    merged = clock.merge_rank_traces([str(p0), str(p1)], str(out))
    with open(out) as f:
        assert json.load(f) == merged
    assert not any(e.get("name") == "clock_sync" for e in merged)
    r0 = [e for e in merged if e.get("pid") == "rank0"][0]
    r1 = [e for e in merged if e.get("pid") == "rank1"][0]
    assert r1["ts"] - r0["ts"] == pytest.approx(1e6)  # the 1s skew
    assert {e.get("pid") for e in merged} >= {
        "rank0", "rank1", "rank0-host", "rank1-host"}
    assert r1["args"]["seq"] == r0["args"]["seq"] == 0


# ----------------------------------------------- fleet: aggregation plane


def _rank_payload(rank, count, hist=None):
    snap = {
        "allreduce_count": {
            "type": "counter", "help": "", "samples": {"": count}},
    }
    if hist is not None:
        snap["lat"] = {"type": "histogram", "help": "", "samples": {"": hist}}
    return json.dumps({
        "rank": rank, "clock": None, "metrics": snap, "arrivals": [],
    }).encode()


def test_fleet_aggregation_stats_and_rank_series():
    """Rank snapshots in the KV merge into min/mean/max/p99 fleet series
    plus rank-labeled raw series; histograms merge bucket-wise with an
    estimated p99."""
    from horovod_tpu.run.rendezvous import KVStoreServer

    server = KVStoreServer()
    try:
        h0 = {"buckets": {"0.1": 9, "1.0": 10, "+Inf": 10},
              "sum": 1.0, "count": 10}
        h1 = {"buckets": {"0.1": 0, "1.0": 90, "+Inf": 90},
              "sum": 50.0, "count": 90}
        server.put("/obs/snap/0", _rank_payload(0, 10, h0), ttl=30)
        server.put("/obs/snap/1", _rank_payload(1, 30, h1), ttl=30)
        server.put("/obs/snap/2", _rank_payload(2, 20), ttl=30)
        agg = aggregate.FleetAggregator(server)
        out = agg.collect()
        assert out["ranks"] == [0, 1, 2] and out["dead_ranks"] == []
        s = out["metrics"]["allreduce_count"]["samples"][""]
        assert s["min"] == 10 and s["max"] == 30 and s["mean"] == 20
        assert s["p99"] == pytest.approx(29.8)  # interpolated over 3 ranks
        assert s["ranks"] == {"0": 10.0, "1": 30.0, "2": 20.0}
        hl = out["metrics"]["lat"]["samples"][""]
        assert hl["count"] == 100 and hl["sum"] == 51.0
        assert hl["buckets"]["1.0"] == 100
        assert hl["p99"] == 1.0  # 99th falls in the merged 1.0 bucket
        prom = aggregate.to_prometheus_fleet(out)
        assert 'fleet_allreduce_count{stat="max"} 30' in prom
        assert 'allreduce_count{rank="1"} 30' in prom
        assert "# TYPE fleet_lat histogram" in prom
        assert 'fleet_lat_bucket{le="1.0"} 100' in prom
        assert 'fleet_rank_alive{rank="2"} 1' in prom
        # registry mirrors
        assert metrics.value("fleet_ranks") == 3
        assert metrics.value("fleet_aggregations") == 1
    finally:
        server.close()


def test_fleet_prometheus_help_lines():
    """Satellite (ISSUE 14): the fleet exporter emits a # HELP line beside
    every # TYPE — the merged families (carrying the per-process help text
    through) AND the fleet synthetics — so a Prometheus UI explains fleet
    series exactly like local ones."""
    from horovod_tpu.run.rendezvous import KVStoreServer

    server = KVStoreServer()
    try:
        snap = {
            "steps": {"type": "counter", "help": "steps dispatched",
                      "samples": {"": 7}},
            "lat": {"type": "histogram", "help": "step latency",
                    "samples": {"": {"buckets": {"+Inf": 1}, "sum": 0.1,
                                     "count": 1}}},
        }
        server.put("/obs/snap/0", json.dumps(
            {"rank": 0, "clock": None, "metrics": snap, "arrivals": [
                {"key": [0, 0, q], "op": "allreduce",
                 "arrivals": {"0": 1.0 + q, "1": 2.0 + q}}
                for q in range(3)
            ]}).encode(), ttl=30)
        server.put("/obs/snap/1", json.dumps(
            {"rank": 1, "clock": None, "metrics": snap, "arrivals": []}
        ).encode(), ttl=30)
        agg = aggregate.FleetAggregator(server, world=2)
        prom = aggregate.to_prometheus_fleet(agg.collect())
        # every # TYPE line has a # HELP sibling for the same family
        typed = re.findall(r"^# TYPE (\S+)", prom, re.M)
        helped = set(re.findall(r"^# HELP (\S+)", prom, re.M))
        missing = [n for n in typed if n not in helped]
        assert not missing, f"# TYPE families without # HELP: {missing}"
        # the per-process help text rides through, suffixed for the fleet
        assert "# HELP fleet_steps steps dispatched " \
               "(min/mean/max/p99 across ranks)" in prom
        assert "# HELP fleet_lat step latency (fleet-merged across ranks)" \
            in prom
        assert "# HELP steps steps dispatched" in prom
        # synthetics documented too (straggler block present: the arrival
        # spread above is attributed to rank 1)
        assert "# HELP fleet_rank_alive " in prom
        assert "# HELP fleet_straggler_detected_rank " in prom
        assert "# HELP fleet_straggler_detected_spread_seconds " in prom
    finally:
        from horovod_tpu.resilience import health

        health.reset()
        server.close()


def test_fleet_dead_rank_surfaced_not_dropped():
    """A rank whose snapshot lease expired shows up DEAD (surfaced, with
    fleet_rank_alive 0), never silently absent — both through the server
    store and through a probing HTTP client."""
    import time as _time

    from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer

    server = KVStoreServer()
    try:
        server.put("/obs/snap/0", _rank_payload(0, 5), ttl=30)
        server.put("/obs/snap/1", _rank_payload(1, 7), ttl=0.05)
        agg = aggregate.FleetAggregator(server)
        assert agg.collect()["ranks"] == [0, 1]
        _time.sleep(0.15)
        out = agg.collect()
        assert out["ranks"] == [0]
        assert out["dead_ranks"] == [1]
        assert metrics.value("fleet_dead_ranks") == 1
        prom = aggregate.to_prometheus_fleet(out)
        assert 'fleet_rank_alive{rank="1"} 0' in prom
        # client path: probe ranks 0..world-1, 410 Gone -> dead
        server.start()
        client = KVStoreClient("127.0.0.1", server.port)
        out2 = aggregate.FleetAggregator(
            client, world=2, register=False).collect()
        assert out2["ranks"] == [0] and out2["dead_ranks"] == [1]
    finally:
        server.close()


def test_publisher_payload_roundtrip(hvd):
    """MetricsPublisher ships this process's registry + arrival ring; the
    aggregator reconstructs rank-labeled values from it."""
    from horovod_tpu.run.rendezvous import KVStoreServer

    hvd.allreduce(np.ones((4,), np.float32), hvd.Sum)
    server = KVStoreServer()
    try:
        pub = aggregate.MetricsPublisher(server, rank=0, interval=5.0)
        pub.publish_once()
        assert metrics.value("fleet_snapshots_published") == 1
        out = aggregate.FleetAggregator(server).collect()
        s = out["metrics"]["allreduce_count"]["samples"][""]
        assert s["ranks"]["0"] == 1.0
        # the arrival ring rode along (1 collective, 8 simulated ranks)
        assert out["straggler"] is None  # no spread without chaos
    finally:
        server.close()


def test_fleet_http_endpoint(hvd):
    """/fleet and /fleet.json serve the registered aggregator's merged
    view; 404 without one."""
    from horovod_tpu.run.rendezvous import KVStoreServer

    server = KVStoreServer()
    http = exporters.start_http_server(0, host="127.0.0.1")
    try:
        port = http.server_port
        with pytest.raises(urllib.request.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet", timeout=10)
        pub = aggregate.MetricsPublisher(server, rank=0, interval=5.0)
        metrics.counter("served_fleet").inc(4)
        pub.publish_once()
        aggregate.FleetAggregator(server)  # registers as default
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet", timeout=10
        ) as r:
            body = r.read().decode()
            assert 'served_fleet{rank="0"} 4' in body
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet.json", timeout=10
        ) as r:
            data = json.load(r)
            assert data["ranks"] == [0]
    finally:
        exporters.stop_http_server()
        server.close()


# --------------------------------- straggler attribution (ISSUE 7 e2e)


def test_rank_slow_chaos_parse():
    from horovod_tpu.resilience import chaos

    assert chaos.parse_spec("rank_slow=3:0.2") == {"rank_slow": (3, 0.2)}
    with pytest.raises(ValueError, match="rank_slow"):
        chaos.parse_spec("rank_slow=3")
    with pytest.raises(ValueError, match="unknown chaos site"):
        chaos.parse_spec("rank_sloow=3:0.2")
    chaos.configure("rank_slow=3:0.2")
    try:
        assert chaos.rank_slow() == (3, 0.2)
        assert chaos.rank_slow() == (3, 0.2)  # persistent, not consumed
    finally:
        chaos.configure(None)


def test_straggler_e2e_deterministic(hvd, monkeypatch, tmp_path):
    """ISSUE 7 acceptance: under HOROVOD_CHAOS=rank_slow=3:0.2 on the
    8-device CPU mesh, the aggregator's straggler_rank names rank 3 within
    2 steps, health transitions to SUSPECT, and the merged skew-corrected
    trace contains the same collective's spans from >= 2 ranks sharing one
    (step, seq) correlation key."""
    from horovod_tpu.resilience import chaos, health
    from horovod_tpu.run.rendezvous import KVStoreServer

    timeline = str(tmp_path / "fleet_timeline.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", timeline)
    monkeypatch.setenv("HOROVOD_CHAOS", "rank_slow=3:0.2")
    trace.reset()  # re-read HOROVOD_TIMELINE under the monkeypatch
    chaos.reset()  # re-read HOROVOD_CHAOS under the monkeypatch
    health.reset()
    server = KVStoreServer()
    try:
        clock.refresh_from_kv(server, rank=0)
        pub = aggregate.MetricsPublisher(server, rank=0, interval=60.0)
        agg = aggregate.FleetAggregator(server, register=False)
        detected_at = None
        for step in range(2):
            straggler.set_step(step)
            hvd.allreduce(np.ones((4,), np.float32), hvd.Sum)
            hvd.allreduce(np.ones((8,), np.float32), hvd.Sum)
            pub.publish_once()
            out = agg.collect()
            if out["straggler"] is not None and detected_at is None:
                detected_at = step
                assert out["straggler"]["rank"] == 3
                assert out["straggler"]["spread_seconds"] >= 0.15
        assert detected_at is not None and detected_at <= 1
        assert metrics.value("straggler_rank") == 3
        assert metrics.value(
            "collective_arrival_spread_seconds")["count"] == 4
        assert metrics.value("straggler_collectives", rank=3) == 4
        # persistent straggler fed the health machine: SUSPECT, rank named;
        # collectives 3 and 4 of the streak each strike (re-strike per
        # collective so step-completion beats cannot hide a persistent but
        # progressing straggler)
        assert health.health_state() == health.HealthState.SUSPECT
        assert "rank 3 straggling" in health.MONITOR.reason()
        assert metrics.value("resilience_stragglers") == 2
        assert metrics.value(
            "resilience_chaos_injected", site="rank_slow") == 4
    finally:
        chaos.configure(None)  # never leak the charge into later tests
        health.reset()
        server.close()

    # the flushed + merged trace: one collective -> a row per rank, tied
    # together by the (step, gen, seq) args, skew-correction applied
    flushed = trace.flush(timeline)
    assert flushed == timeline
    merged_path = str(tmp_path / "merged.json")
    merged = clock.merge_rank_traces([timeline], merged_path)
    by_key = {}
    for e in merged:
        a = e.get("args") or {}
        pid = str(e.get("pid", ""))
        if "seq" in a and pid.startswith("rank") and "-host" not in pid:
            by_key.setdefault(
                (a["step"], a["gen"], a["seq"]), set()).add(pid)
    assert by_key, "no correlated collective spans in the merged trace"
    assert all(len(pids) == 8 for pids in by_key.values())
    assert len(by_key) == 4  # 2 steps x 2 collectives, seq reset per step
    assert {k[2] for k in by_key} == {0, 1}
    # rank 3's bar is the short one: it arrived last, everyone else waited
    r3 = [e for e in merged if e.get("pid") == "rank3"
          and "seq" in (e.get("args") or {})]
    r0 = [e for e in merged if e.get("pid") == "rank0"
          and "seq" in (e.get("args") or {})]
    assert max(e["dur"] for e in r3) < 1e3  # rank3 waits ~nothing (us)
    assert min(e["dur"] for e in r0) > 0.15e6  # others wait >= the delay


def test_straggler_below_threshold_is_quiet(hvd):
    """No chaos, simulated arrivals are equal: spread ~0, nobody flagged,
    health untouched."""
    from horovod_tpu.resilience import health

    health.reset()
    straggler.set_step(0)
    hvd.allreduce(np.ones((4,), np.float32), hvd.Sum)
    assert straggler.attribute() is None
    assert metrics.value("straggler_rank") == -1
    assert health.health_state() == health.HealthState.HEALTHY


# ------------------------------------------------ satellite: hvd_top view


def _load_hvd_top():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "hvd_top", os.path.join(_REPO, "tools", "hvd_top.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_hvd_top_renders_fleet_and_straggler():
    top = _load_hvd_top()
    fleet = {
        "ranks": [0, 1], "dead_ranks": [2],
        "metrics": {
            "train_steps": {"type": "counter", "samples": {"": {
                "ranks": {"0": 10, "1": 12},
                "min": 10, "mean": 11, "max": 12, "p99": 12}}},
            "lat": {"type": "histogram", "samples": {"": {
                "buckets": {"+Inf": 3}, "sum": 0.3, "count": 3,
                "p99": 0.1}}},
        },
        "straggler": {"rank": 1, "spread_seconds": 0.2, "op": "allreduce",
                      "key": [3, 0, 1], "streak": 4},
    }
    out = top.render(fleet)
    assert "2 rank(s) reporting" in out and "DEAD: [2]" in out
    assert "STRAGGLER: rank 1 trailing by 200.0 ms" in out
    assert "train_steps" in out and "12" in out
    assert "lat" in out and "n=3" in out
    # filter narrows the table
    assert "train_steps" not in top.render(fleet, name_filter="lat")


def test_hvd_top_serving_pane():
    """Satellite (ISSUE 14): hvd_top renders a serving pane — subscriber
    lag/staleness, queue depth, admission rejections, per-arm request
    outcomes — from the fleet metrics, and omits it when no serving
    series exist."""
    top = _load_hvd_top()

    def g(v):
        return {"samples": {"": {"ranks": {"0": v}, "min": v, "mean": v,
                                 "max": v, "p99": v}}, "type": "gauge",
                "help": ""}

    def c(samples):
        return {
            "type": "counter", "help": "",
            "samples": {
                k: {"ranks": {"0": v}, "min": v, "mean": v, "max": v,
                    "p99": v}
                for k, v in samples.items()
            },
        }

    fleet = {
        "collected_at": 0.0, "ranks": [0], "dead_ranks": [],
        "straggler": None,
        "metrics": {
            "serving_subscriber_lag": g(2),
            "serving_staleness_seconds": g(7.5),
            "serving_queue_depth": g(5),
            "serving_admission_rejected": c({"reason=queue_full": 4}),
            "serving_requests": c({
                "arm=stable,outcome=ok": 90,
                "arm=canary,outcome=ok": 9,
                "arm=canary,outcome=error": 1,
            }),
        },
    }
    out = top.render(fleet)
    assert "SERVING:" in out
    assert "lag 2 gen(s)" in out
    assert "staleness 7.5s" in out
    assert "queue depth 5" in out
    assert "rejected 4 (queue_full=4)" in out
    assert "requests arm=canary: error=1 ok=9" in out
    assert "requests arm=stable: ok=90" in out
    # no serving series -> no pane
    assert "SERVING:" not in top.render(
        {"ranks": [0], "dead_ranks": [], "straggler": None,
         "metrics": {"train_steps": g(3)}})


def test_hvd_top_scrapes_live_endpoint(hvd):
    """--once --json against the real rank-0 endpoint (fleet registered ->
    fleet view; else single-process fallback)."""
    from horovod_tpu.run.rendezvous import KVStoreServer

    top = _load_hvd_top()
    server = KVStoreServer()
    http = exporters.start_http_server(0, host="127.0.0.1")
    try:
        url = f"http://127.0.0.1:{http.server_port}"
        metrics.counter("topped").inc(3)
        fleet, is_fleet = top.fetch(url)
        assert not is_fleet  # no aggregator yet: /metrics.json fallback
        assert fleet["metrics"]["topped"]["samples"][""]["ranks"]["0"] == 3
        pub = aggregate.MetricsPublisher(server, rank=0, interval=5.0)
        pub.publish_once()
        aggregate.FleetAggregator(server)
        fleet, is_fleet = top.fetch(url)
        assert is_fleet
        assert "topped" in top.render(fleet)
    finally:
        exporters.stop_http_server()
        server.close()


# ------------------------------- satellite: metric-catalog drift guard


_METRIC_LITERAL_RE = re.compile(
    r'\b(?:metrics|_metrics)\s*\.\s*(?:counter|gauge|histogram)\(\s*'
    r'"([A-Za-z_][A-Za-z0-9_]*)"'
)


def test_metric_catalog_covers_every_emitted_name():
    """Every metric name emitted as a literal through
    counter(/gauge(/histogram( anywhere under horovod_tpu/ must appear in
    the docs/observability.md catalog — the catalog cannot silently drift
    from the code again. (f-string-templated families like train_* are
    documented by pattern and exempt by construction.)"""
    names = set()
    for dirpath, _dirnames, filenames in os.walk(
        os.path.join(_REPO, "horovod_tpu")
    ):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                names |= set(_METRIC_LITERAL_RE.findall(f.read()))
    assert len(names) > 40, "guard regex found suspiciously few metrics"
    with open(os.path.join(_REPO, "docs", "observability.md")) as f:
        catalog = f.read()
    missing = sorted(n for n in names if n not in catalog)
    assert not missing, (
        "metric names emitted in code but absent from the "
        f"docs/observability.md catalog: {missing}"
    )


def test_straggler_partial_arrivals_deferred_until_complete():
    """Fleet attribution must not score a key while a rank's arrival —
    most likely the straggler's own — is still in flight: the partial set
    is deferred (not remembered as seen), and the SAME key attributes
    correctly once the late snapshot lands."""
    early = [{"key": [0, 0, 0], "op": "allreduce", "arrivals": {"0": 10.0}}]
    late = [{"key": [0, 0, 0], "op": "allreduce",
             "arrivals": {"1": 10.3}}]
    # first pass: only rank 0's snapshot arrived -> deferred, no verdict
    assert straggler.attribute(
        straggler.merge_arrival_exports([early]), expected_ranks=2
    ) is None
    assert metrics.value("collective_arrival_spread_seconds") is None
    # second pass: rank 1's (straggling) arrival landed -> attributed
    out = straggler.attribute(
        straggler.merge_arrival_exports([early, late]), expected_ranks=2
    )
    assert out is not None and out["rank"] == 1
    assert out["spread_seconds"] == pytest.approx(0.3)
    # and the finalized key never double-counts on a repeated pass
    straggler.attribute(
        straggler.merge_arrival_exports([early, late]), expected_ranks=2
    )
    assert metrics.value(
        "collective_arrival_spread_seconds")["count"] == 1


def test_attribution_processes_records_in_temporal_order():
    """Post-resize keys (gen bumped, step rolled back) sort temporally
    AFTER leftover pre-resize keys: an old healthy key in the same pass
    must not wipe the attribution the newer straggling keys build."""
    recs = []
    # pre-resize healthy key: gen 0, step 5 — temporally OLDEST
    recs.append({"key": [5, 0, 0], "op": "allreduce",
                 "arrivals": {"0": 1.0, "1": 1.0}})
    # post-resize: rank 1 trails 0.3s at 3 consecutive gen-1 collectives
    for q in range(3):
        recs.append({"key": [0, 1, q], "op": "allreduce",
                     "arrivals": {"0": 10.0 + q, "1": 10.3 + q}})
    out = straggler.attribute(
        straggler.merge_arrival_exports([recs]), expected_ranks=2)
    assert out is not None and out["rank"] == 1 and out["streak"] == 3
    assert metrics.value("straggler_rank") == 1  # not wiped to -1
    from horovod_tpu.resilience import health

    try:
        assert health.health_state() == health.HealthState.SUSPECT
    finally:
        health.reset()


def test_merge_uses_newest_clock_sync(tmp_path):
    """trace.flush appends one clock_sync per flush; a sidecar reused
    across shutdown/init cycles must be shifted by the NEWEST epoch, not
    the first run's stale one."""
    events = [
        {"ph": "i", "pid": trace.HOST_PID, "tid": "meta",
         "name": "clock_sync", "ts": 0.0,
         "args": {"rank": 1, "epoch_monotonic_ns": 0, "offset_s": 0.0}},
        {"ph": "i", "pid": trace.HOST_PID, "tid": "meta",
         "name": "clock_sync", "ts": 0.0,
         "args": {"rank": 1, "epoch_monotonic_ns": 100_000_000_000,
                  "offset_s": 0.0}},
        {"ph": "X", "pid": "rank1", "tid": "allreduce", "name": "x",
         "ts": 50.0, "dur": 1.0},
    ]
    p = tmp_path / "t.json"
    with open(p, "w") as f:
        json.dump(events, f)
    ref = [{"ph": "i", "pid": trace.HOST_PID, "tid": "meta",
            "name": "clock_sync", "ts": 0.0,
            "args": {"rank": 0, "epoch_monotonic_ns": 100_000_000_000,
                     "offset_s": 0.0}},
           {"ph": "X", "pid": "rank0", "tid": "allreduce", "name": "y",
            "ts": 50.0, "dur": 1.0}]
    p0 = tmp_path / "t0.json"
    with open(p0, "w") as f:
        json.dump(ref, f)
    merged = clock.merge_rank_traces([str(p0), str(p)])
    r0 = [e for e in merged if e.get("pid") == "rank0"][0]
    r1 = [e for e in merged if e.get("pid") == "rank1"][0]
    # same epoch under the NEWEST meta -> aligned; the stale first meta
    # would have shifted rank1 by the full 100s inter-run gap
    assert r1["ts"] == pytest.approx(r0["ts"])


def test_aggregator_defers_keys_until_full_world_reported():
    """With world known, a collect() racing the straggler's own (late)
    snapshot must defer the key — not finalize it against the
    published-so-far subset and then skip the decisive arrival forever."""
    from horovod_tpu.run.rendezvous import KVStoreServer

    def payload(rank, arrivals):
        return json.dumps({
            "rank": rank, "clock": None, "metrics": {},
            "arrivals": [{"key": [0, 0, 0], "op": "allreduce",
                          "arrivals": arrivals}],
        }).encode()

    server = KVStoreServer()
    try:
        server.put("/obs/snap/0", payload(0, {"0": 10.0}), ttl=30)
        server.put("/obs/snap/1", payload(1, {"1": 10.01}), ttl=30)
        agg = aggregate.FleetAggregator(server, world=3, register=False)
        assert agg.collect()["straggler"] is None  # deferred, not scored
        server.put("/obs/snap/2", payload(2, {"2": 10.3}), ttl=30)
        out = agg.collect()
        assert out["straggler"] is not None
        assert out["straggler"]["rank"] == 2
        assert out["straggler"]["spread_seconds"] == pytest.approx(0.3)
    finally:
        server.close()
