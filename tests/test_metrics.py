"""Observability layer: metrics registry semantics, exporter formats,
instrumentation hooks (eager ops, native-core cycle callback), the merged
host+native chrome-trace timeline, and the import-side-effect guard.

No reference analog — upstream Horovod's only observability surface is the
chrome Timeline; the queryable registry is this rebuild's addition
(ISSUE 1). Tier-1: everything here runs on the 8-device CPU mesh."""

import json
import os
import re
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from horovod_tpu.observability import exporters, metrics, trace

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Every test sees an empty default registry and a clean trace buffer."""
    metrics.reset()
    metrics.set_enabled(True)
    trace.reset()
    yield
    metrics.reset()
    metrics.set_enabled(True)
    trace.reset()


# ------------------------------------------------------------ registry


def test_counter_semantics():
    c = metrics.counter("requests")
    c.inc()
    c.inc(4)
    assert metrics.counter("requests").value == 5.0
    with pytest.raises(ValueError, match=">= 0"):
        c.inc(-1)


def test_labeled_children_are_distinct():
    metrics.counter("allreduce_bytes", rank=0).inc(100)
    metrics.counter("allreduce_bytes", rank=1).inc(7)
    metrics.counter("allreduce_bytes").inc(1)  # unlabeled child coexists
    snap = metrics.snapshot()["allreduce_bytes"]
    assert snap["type"] == "counter"
    assert snap["samples"]["rank=0"] == 100.0
    assert snap["samples"]["rank=1"] == 7.0
    assert snap["samples"][""] == 1.0
    assert metrics.value("allreduce_bytes", rank=1) == 7.0
    assert metrics.value("allreduce_bytes", rank=9) is None


def test_gauge_set_inc():
    g = metrics.gauge("util")
    g.set(0.5)
    g.inc(0.25)
    g.dec(0.5)
    assert abs(metrics.value("util") - 0.25) < 1e-12


def test_histogram_buckets():
    h = metrics.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    s = metrics.value("lat")
    assert s["count"] == 5
    assert abs(s["sum"] - 5.605) < 1e-9
    # cumulative, prometheus-style, with the implicit +Inf tail
    assert s["buckets"]["0.01"] == 1
    assert s["buckets"]["0.1"] == 3
    assert s["buckets"]["1.0"] == 4
    assert s["buckets"]["+Inf"] == 5
    h.observe(float("nan"))  # must not poison sum/count
    assert metrics.value("lat")["count"] == 5


def test_kind_conflict_raises():
    metrics.counter("x").inc()
    with pytest.raises(ValueError, match="already registered"):
        metrics.gauge("x")


def test_disabled_is_noop():
    metrics.set_enabled(False)
    c = metrics.counter("never")
    c.inc(100)
    h = metrics.histogram("never_h")
    h.observe(1.0)
    metrics.set_enabled(True)
    assert "never" not in metrics.snapshot()
    assert metrics.value("never") is None


def test_thread_safety_smoke():
    n_threads, n_inc = 8, 2000

    def worker():
        for _ in range(n_inc):
            metrics.counter("contended").inc()
            metrics.histogram("contended_h", buckets=(1, 2)).observe(1)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert metrics.value("contended") == n_threads * n_inc
    assert metrics.value("contended_h")["count"] == n_threads * n_inc


def test_summary_renders():
    metrics.counter("a").inc(2)
    metrics.histogram("b").observe(0.01)
    out = metrics.summary()
    assert "a" in out and "b" in out and "count=1" in out


# ------------------------------------------------------------ exporters

_PROM_LINE = re.compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+-]+(inf|nan)?)$"
)


def test_prometheus_exposition_parses():
    metrics.counter("allreduce_count").inc(3)
    metrics.counter("allreduce_bytes", rank=0).inc(1024)
    metrics.gauge("train_mfu").set(0.41)
    metrics.histogram("cycle", buckets=(0.5, 1.5)).observe(1.0)
    text = exporters.to_prometheus()
    assert text.endswith("\n")
    for line in text.rstrip("\n").splitlines():
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
    assert "allreduce_count 3" in text
    assert 'allreduce_bytes{rank="0"} 1024' in text
    assert 'cycle_bucket{le="+Inf"} 1' in text
    assert "cycle_sum 1" in text
    assert "cycle_count 1" in text
    assert "# TYPE cycle histogram" in text


def test_prometheus_nonfinite_samples_render():
    """inf/nan samples must render as exposition spellings, not crash the
    scrape handler (int(inf) raises)."""
    metrics.gauge("pos").set(float("inf"))
    metrics.gauge("neg").set(float("-inf"))
    metrics.gauge("nan").set(float("nan"))
    metrics.histogram("h", buckets=(1.0,)).observe(float("inf"))
    text = exporters.to_prometheus()
    assert "pos +Inf" in text
    assert "neg -Inf" in text
    assert "nan NaN" in text
    assert "h_sum +Inf" in text


def test_trace_recording_gate():
    """set_recording(False) (what init() applies on ranks != 0) silences
    span/instant recording even with HOROVOD_TIMELINE set; the buffer cap
    drops rather than grows past MAX_BUFFERED_EVENTS."""
    os.environ["HOROVOD_TIMELINE"] = "/tmp/_never_written.json"
    try:
        trace.reset()
        trace.set_recording(False)
        with trace.span("t", "x"):
            pass
        trace.instant("t", "y")
        assert trace.events() == []
        trace.set_recording(True)
        with trace.span("t", "x"):
            pass
        assert len(trace.events()) == 1
    finally:
        del os.environ["HOROVOD_TIMELINE"]
        trace.reset()


def test_json_exporter_roundtrips():
    metrics.counter("c", job="x").inc(2)
    data = json.loads(exporters.to_json())
    assert data["c"]["samples"]["job=x"] == 2.0


def test_http_endpoint_serves_both_formats():
    metrics.counter("served").inc(9)
    server = exporters.start_http_server(0, host="127.0.0.1")
    try:
        port = server.server_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            body = r.read().decode()
            assert "served 9" in body
            assert r.headers["Content-Type"].startswith("text/plain")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=10
        ) as r:
            assert json.load(r)["served"]["samples"][""] == 9.0
    finally:
        exporters.stop_http_server()


# ------------------------------------------- instrumentation: eager ops


def test_eager_allreduce_feeds_registry(hvd):
    out = hvd.allreduce(np.ones((8, 4), np.float32), op=hvd.Sum)
    out2 = hvd.allreduce(np.ones((8, 4), np.float32), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out))
    assert metrics.value("allreduce_count") == 2
    assert metrics.value("allreduce_bytes") == 2 * 8 * 4 * 4
    # same (mesh, axis, shape) twice: first lookup compiles, second hits
    assert metrics.value("eager_compile_cache_misses", kind="allreduce") >= 1
    assert metrics.value("eager_compile_cache_hits", kind="allreduce") >= 1


def test_grouped_and_other_ops_feed_registry(hvd):
    hvd.grouped_allreduce(
        [np.ones((4,), np.float32), np.ones((2, 2), np.float32)], hvd.Sum
    )
    hvd.allgather(np.ones((2, 3), np.float32))
    hvd.reducescatter(np.ones((8, 2), np.float32), hvd.Sum)
    assert metrics.value("allreduce_tensors") == 2
    assert metrics.value("allreduce_bytes") == 4 * 4 + 4 * 4
    assert metrics.value("allgather_count") == 1
    assert metrics.value("reducescatter_count") == 1


def test_train_step_instrumentation(hvd):
    import optax

    from horovod_tpu import models
    from horovod_tpu.training import (
        init_model, make_jit_train_step, replicate, shard_batch,
    )

    model = models.MLP(features=(8, 4))
    tx = optax.sgd(0.1)
    import jax
    import jax.numpy as jnp

    params, batch_stats = init_model(
        model, jax.random.PRNGKey(0), jnp.zeros((1, 6), jnp.float32)
    )
    params = replicate(params)
    opt_state = replicate(tx.init(params))
    step = make_jit_train_step(model, tx)
    images = shard_batch(np.random.RandomState(0).rand(16, 6).astype("f"))
    labels = shard_batch(np.random.RandomState(1).randint(0, 4, 16))
    for _ in range(3):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
    assert metrics.value("train_steps") == 3
    assert metrics.value("train_examples") == 3 * 16
    # interval histogram needs 2+ calls
    assert metrics.value("train_step_seconds")["count"] == 2
    assert metrics.value("train_examples_per_sec") > 0


# -------------------------------- instrumentation: native-core cycle path


def test_core_cycle_metrics_and_merged_timeline(monkeypatch, tmp_path):
    """The acceptance loop of ISSUE 1 in-process: named async allreduces
    through the native core populate the cycle-latency histogram and cache
    counters, and shutdown merges host spans into the native chrome-trace
    file — one valid-JSON Perfetto load with both pid lanes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd

    timeline = str(tmp_path / "merged_timeline.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", timeline)
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2")
    hvd.shutdown()
    trace.reset()  # re-read HOROVOD_TIMELINE under the monkeypatch
    hvd.init(native_core=True)
    try:
        x = jax.device_put(
            np.ones((hvd.size(), 4), np.float32),
            NamedSharding(hvd.mesh(), P(hvd.data_axis())),
        )
        for step in range(4):
            h = hvd.allreduce_async(x, op=hvd.Sum, name="grad")
            out = hvd.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), np.full((4,), 8.0))
    finally:
        hvd.shutdown()

    hist = metrics.value("core_cycle_latency_seconds")
    assert hist is not None and hist["count"] >= 1 and hist["sum"] > 0
    assert metrics.value("core_enqueued_tensors") == 4
    # steps 2..4 of the same name ride the response cache
    assert metrics.value("core_cache_hits") >= 1
    assert metrics.value("core_cycles") >= 1

    with open(timeline) as f:
        events = json.load(f)  # valid JSON or this throws
    pids = {str(e.get("pid")) for e in events}
    assert trace.HOST_PID in pids, pids  # host spans present
    assert "0" in pids, pids  # native-core events present
    host = [e for e in events if e.get("pid") == trace.HOST_PID]
    assert any(e.get("tid") == "enqueue" for e in host)
    assert any(e.get("tid") == "cycle" for e in host)


# -------------------------------------------------- import side effects


def test_metrics_import_has_no_jax_side_effects():
    """The registry must stay importable from collection-time contexts
    (pytest collecting under ``JAX_PLATFORMS=cpu``): importing it — even
    through the ``horovod_tpu`` package, which imports jax the library —
    must not initialize any JAX device backend, and using the registry and
    exporters must not either."""
    code = (
        "import horovod_tpu.observability.metrics as m\n"
        "import horovod_tpu.observability.exporters as e\n"
        "import horovod_tpu.observability.trace as t\n"
        "m.counter('x', rank=0).inc(3)\n"
        "m.histogram('h').observe(0.1)\n"
        "e.to_prometheus(); e.to_json()\n"
        "import sys\n"
        "jax = sys.modules.get('jax')\n"
        "if jax is not None:\n"
        "    from jax._src import xla_bridge\n"
        "    backends = getattr(xla_bridge, '_backends', None)\n"
        "    assert not backends, (\n"
        "        'observability import initialized a JAX backend: %r'\n"
        "        % backends)\n"
        "print('CLEAN')\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=_REPO, env=env, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "CLEAN" in out.stdout
