"""Identity/bootstrap tests (reference ``test/test_tensorflow.py`` rank/size
checks + ``horovod/common/basics.py`` surface)."""

import numpy as np
import pytest


def test_init_idempotent(hvd):
    hvd.init()
    hvd.init()
    assert hvd.is_initialized()


def test_size_rank(hvd):
    assert hvd.size() == 8
    assert hvd.rank() == 0
    assert hvd.local_size() == 8
    assert hvd.local_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.is_homogeneous()


def test_local_rank_from_launcher_env(monkeypatch):
    """Two slots on one host (-H host:2) must get distinct local ranks from
    the launcher-exported HOROVOD_LOCAL_RANK (reference ``basics.py:108-122``,
    ``run/gloo_run.py:54-112``)."""
    import horovod_tpu as hvd
    from horovod_tpu.run.hosts import get_host_assignments, slot_env

    slots = get_host_assignments("localhost:2", None, 2)
    envs = [slot_env(s) for s in slots]
    assert [e["HOROVOD_LOCAL_RANK"] for e in envs] == ["0", "1"]

    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_LOCAL_RANK", envs[1]["HOROVOD_LOCAL_RANK"])
    monkeypatch.setenv("HOROVOD_LOCAL_SIZE", envs[1]["HOROVOD_LOCAL_SIZE"])
    hvd.init()
    assert hvd.local_rank() == 1
    assert hvd.local_size() == 2  # processes on host, not chips
    assert hvd.local_rank() < hvd.local_size()
    assert hvd.local_chip_count() == 8  # tiling factor unchanged
    hvd.shutdown()


def test_scrub_plugin_hooks():
    """CPU-pinned child envs must not inherit sitecustomize TPU-plugin hooks
    (wedged-tunnel failure mode: backend init hangs despite JAX_PLATFORMS=cpu)."""
    import os

    from horovod_tpu.run.env_util import scrub_plugin_hooks, strip_plugin_hooks

    sep = os.pathsep
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": sep.join(["/root/.axon_site", "/repo", "/tests"]),
    }
    scrub_plugin_hooks(env)
    assert env["PYTHONPATH"] == sep.join(["/repo", "/tests"])

    # hook is the only entry -> PYTHONPATH removed entirely
    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/.axon_site"}
    scrub_plugin_hooks(env)
    assert "PYTHONPATH" not in env

    # not CPU-pinned -> untouched (a TPU child needs the hook to reach chips)
    env = {"PYTHONPATH": "/root/.axon_site"}
    scrub_plugin_hooks(env)
    assert env["PYTHONPATH"] == "/root/.axon_site"
    scrub_plugin_hooks(env, force=True)
    assert "PYTHONPATH" not in env

    assert strip_plugin_hooks("") == ""


def test_install_sigterm_exit_runs_finalizers():
    """Benchmark/tool children convert a watchdog's SIGTERM into
    SystemExit(143) so ``finally`` blocks (and the JAX client teardown)
    actually run — the kernel default would terminate with no cleanup,
    which has wedged the tunnel TPU for subsequent probes."""
    import os
    import signal
    import subprocess
    import sys

    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from horovod_tpu.run.env_util import install_sigterm_exit\n"
        "install_sigterm_exit()\n"
        "import time\n"
        "try:\n"
        "    print('READY', flush=True)\n"
        "    time.sleep(60)\n"
        "finally:\n"
        "    print('FINALLY-RAN', flush=True)\n"
    ) % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    assert "READY" in proc.stdout.readline()
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 143
    assert "FINALLY-RAN" in out


def test_builds(hvd):
    assert hvd.xla_built()
    assert not hvd.mpi_built()
    assert not hvd.nccl_built()
    assert not hvd.gloo_built()
    assert not hvd.mpi_threads_supported()


def test_uninitialized_raises():
    import horovod_tpu as hvd

    hvd.shutdown()
    with pytest.raises(RuntimeError, match="not been initialized"):
        hvd.size()


def test_mesh_axes(hvd):
    m = hvd.mesh()
    assert hvd.data_axis() in m.axis_names
    assert m.shape[hvd.data_axis()] == 8


def test_custom_mesh_axes():
    import horovod_tpu as hvd
    from horovod_tpu.parallel import build_mesh

    hvd.shutdown()
    m = build_mesh(axes={"data": -1, "model": 2})
    hvd.init(mesh=m)
    assert hvd.size() == 4
    assert hvd.mesh().shape["model"] == 2
    hvd.shutdown()


def test_build_mesh_errors():
    from horovod_tpu.parallel import build_mesh

    with pytest.raises(ValueError, match="at most one"):
        build_mesh(axes={"data": -1, "model": -1})
    with pytest.raises(ValueError, match="not divisible"):
        build_mesh(axes={"data": -1, "model": 3})
    with pytest.raises(ValueError, match="!= device count"):
        build_mesh(axes={"data": 3})


def test_mesh_and_axes_mutually_exclusive():
    import jax
    import numpy as np
    import horovod_tpu as hvd

    hvd.shutdown()
    m = jax.sharding.Mesh(np.asarray(jax.devices()), ("model",))
    with pytest.raises(ValueError, match="not both"):
        hvd.init(mesh=m, axes={"data": -1})
    # a custom mesh without a 'data' axis falls back to its first axis
    hvd.init(mesh=m)
    assert hvd.data_axis() == "model"
    assert hvd.size() == 8
    hvd.shutdown()


def test_controller_enabled_flags(hvd):
    """Runtime controller queries (reference basics.py:151-179): gloo mode
    (the no-MPI TCP-controller role) answers enabled, MPI never."""
    assert hvd.gloo_enabled() is True
    assert hvd.mpi_enabled() is False
    thvd = pytest.importorskip("horovod_tpu.torch")
    assert thvd.gloo_enabled() and not thvd.mpi_enabled()


def test_compat_utils(hvd):
    assert hvd.num_rank_is_power_2(8) and not hvd.num_rank_is_power_2(6)
    assert not hvd.num_rank_is_power_2(0)
    assert hvd.gpu_available() is False  # TPU framework, honestly
    assert hvd.gpu_available("tensorflow") is False  # reference signature
