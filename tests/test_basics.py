"""Identity/bootstrap tests (reference ``test/test_tensorflow.py`` rank/size
checks + ``horovod/common/basics.py`` surface)."""

import numpy as np
import pytest


def test_init_idempotent(hvd):
    hvd.init()
    hvd.init()
    assert hvd.is_initialized()


def test_size_rank(hvd):
    assert hvd.size() == 8
    assert hvd.rank() == 0
    assert hvd.local_size() == 8
    assert hvd.local_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.is_homogeneous()


def test_builds(hvd):
    assert hvd.xla_built()
    assert not hvd.mpi_built()
    assert not hvd.nccl_built()
    assert not hvd.gloo_built()
    assert not hvd.mpi_threads_supported()


def test_uninitialized_raises():
    import horovod_tpu as hvd

    hvd.shutdown()
    with pytest.raises(RuntimeError, match="not been initialized"):
        hvd.size()


def test_mesh_axes(hvd):
    m = hvd.mesh()
    assert hvd.data_axis() in m.axis_names
    assert m.shape[hvd.data_axis()] == 8


def test_custom_mesh_axes():
    import horovod_tpu as hvd
    from horovod_tpu.parallel import build_mesh

    hvd.shutdown()
    m = build_mesh(axes={"data": -1, "model": 2})
    hvd.init(mesh=m)
    assert hvd.size() == 4
    assert hvd.mesh().shape["model"] == 2
    hvd.shutdown()


def test_build_mesh_errors():
    from horovod_tpu.parallel import build_mesh

    with pytest.raises(ValueError, match="at most one"):
        build_mesh(axes={"data": -1, "model": -1})
    with pytest.raises(ValueError, match="not divisible"):
        build_mesh(axes={"data": -1, "model": 3})
    with pytest.raises(ValueError, match="!= device count"):
        build_mesh(axes={"data": 3})


def test_mesh_and_axes_mutually_exclusive():
    import jax
    import numpy as np
    import horovod_tpu as hvd

    hvd.shutdown()
    m = jax.sharding.Mesh(np.asarray(jax.devices()), ("model",))
    with pytest.raises(ValueError, match="not both"):
        hvd.init(mesh=m, axes={"data": -1})
    # a custom mesh without a 'data' axis falls back to its first axis
    hvd.init(mesh=m)
    assert hvd.data_axis() == "model"
    assert hvd.size() == 8
    hvd.shutdown()
