"""Collective-schedule extraction (``horovod_tpu.analysis.schedule``).

Acceptance (ISSUE 8): pinned fingerprints for every cell of the
{allreduce, ZeRO-1} × {none, fp16, int8, powersgd} × {flat, hierarchical}
sync-mode matrix — the exact schedule-equivalence harness the coming
SyncPipeline refactor (ROADMAP item 5) must pass cell-by-cell — plus the
static analyses: branch-divergent ``lax.cond`` collectives flagged,
``while``-loop collectives flagged, recursion through
pjit/shard_map/scan.

Regenerating the pins (ONLY after an intentional schedule change, with
the diff reviewed)::

    HVD_REGEN_FINGERPRINTS=1 python -m pytest tests/test_schedule.py -q
"""

import json
import os
import pathlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.analysis import (
    Schedule,
    ScheduleDivergence,
    assert_same_schedule,
    collective_schedule,
    diff_schedules,
)
from horovod_tpu.analysis.schedule import schedule_of_jaxpr
from horovod_tpu.compression import Compression
from horovod_tpu.ops.collective import _smap, allreduce, Average

pytestmark = pytest.mark.analysis

FINGERPRINT_FILE = (
    pathlib.Path(__file__).parent / "data" / "schedule_fingerprints.json"
)
REGEN = os.environ.get("HVD_REGEN_FINGERPRINTS", "0") == "1"


# --------------------------------------------------------------------------
# extraction basics


def test_psum_allgather_sequence(hvd, mesh8):
    def fn(v):
        s = lax.psum(v, "data")
        g = lax.all_gather(v, "data", axis=0, tiled=True)
        return s.sum() + g.sum()

    sm = jax.jit(_smap(fn, mesh8, (P("data"),), P()))
    sched = collective_schedule(sm, jnp.ones((8, 4), jnp.float32))
    assert [op.primitive for op in sched.ops] == ["psum", "all_gather"]
    assert sched.ops[0].axes == ("data",)
    assert sched.ops[0].shape == (1, 4)
    assert sched.ops[0].dtype == "float32"
    assert "shard_map" in sched.ops[0].context
    assert not sched.issues


def test_fingerprint_deterministic_and_shape_sensitive(hvd, mesh8):
    def fn(v):
        return lax.psum(v, "data")

    sm = _smap(fn, mesh8, (P("data"),), P())
    a = collective_schedule(sm, jnp.ones((8, 4), jnp.float32))
    b = collective_schedule(sm, jnp.ones((8, 4), jnp.float32))
    c = collective_schedule(sm, jnp.ones((8, 6), jnp.float32))
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != c.fingerprint()
    assert_same_schedule(a, b)
    with pytest.raises(ScheduleDivergence):
        assert_same_schedule(a, c)


def test_grad_backward_collectives_extracted(hvd, mesh8):
    """The backward pass's psum (grad of a sharded loss) is part of the
    schedule — extraction must recurse through the grad-built jaxpr."""

    def step(w, x):
        def loss(w):
            return lax.psum(jnp.sum((x @ w) ** 2), "data")

        return jax.grad(loss)(w)

    sm = _smap(step, mesh8, (P(), P("data")), P())
    sched = collective_schedule(
        sm, jnp.ones((4, 3), jnp.float32), jnp.ones((8, 4), jnp.float32)
    )
    assert sched.counts().get("psum", 0) >= 1


def test_scan_body_collective_contextualized(hvd, mesh8):
    def fn(v):
        def body(c, _):
            return c + lax.psum(v, "data").sum(), None

        c, _ = lax.scan(body, 0.0, None, length=3)
        return c

    sm = _smap(fn, mesh8, (P("data"),), P())
    sched = collective_schedule(sm, jnp.ones((8, 2), jnp.float32))
    assert len(sched.ops) == 1
    assert any("scan[3]" in c for c in sched.ops[0].context)


def test_cond_equal_branches_clean(hvd, mesh8):
    def fn(v, p):
        return lax.cond(
            p,
            lambda a: lax.psum(a, "data") * 2.0,
            lambda a: lax.psum(a, "data") + 1.0,
            v,
        )

    sm = _smap(fn, mesh8, (P("data"), P()), P("data"))
    sched = collective_schedule(sm, jnp.ones((8, 2), jnp.float32), True)
    assert not sched.issues
    assert sched.counts() == {"psum": 1}


def test_cond_divergent_branches_flagged(hvd, mesh8):
    """The static divergence check: one branch reduces, the other
    doesn't — ranks disagreeing on the predicate would deadlock."""

    def fn(v, p):
        return lax.cond(
            p, lambda a: lax.psum(a, "data"), lambda a: a * 2.0, v
        )

    sm = _smap(fn, mesh8, (P("data"), P()), P("data"))
    sched = collective_schedule(sm, jnp.ones((8, 2), jnp.float32), True)
    assert sched.issues and "branch-divergent" in sched.issues[0]
    assert "deadlock" in sched.issues[0]
    with pytest.raises(ScheduleDivergence, match="branch-divergent"):
        collective_schedule(
            sm, jnp.ones((8, 2), jnp.float32), True, strict=True
        )


def test_cond_equal_length_divergence_perturbs_fingerprint(hvd, mesh8):
    """Equal-COUNT but different-signature branches must still perturb
    the fingerprint (a pin-only equivalence harness would otherwise pass
    a refactor that introduced them)."""

    def clean(v, p):
        return lax.cond(
            p, lambda a: lax.psum(a, "data"),
            lambda a: lax.psum(a, "data") * 2.0, v
        )

    def divergent(v, p):
        return lax.cond(
            p, lambda a: lax.psum(a, "data"),
            lambda a: lax.pmax(a, "data") * 2.0, v
        )

    x = jnp.ones((8, 2), jnp.float32)
    a = collective_schedule(
        _smap(clean, mesh8, (P("data"), P()), P("data")), x, True
    )
    b = collective_schedule(
        _smap(divergent, mesh8, (P("data"), P()), P("data")), x, True
    )
    assert not a.issues and b.issues
    assert len(a.ops) == len(b.ops) == 1
    assert a.fingerprint() != b.fingerprint()
    assert any("!divergent" in c for c in b.ops[0].context)


def test_while_collective_flagged(hvd, mesh8):
    def fn(v):
        def cond(c):
            return c[0] < 3

        def body(c):
            i, acc = c
            return i + 1, acc + lax.psum(v, "data").sum()

        return lax.while_loop(cond, body, (0, 0.0))[1]

    sm = _smap(fn, mesh8, (P("data"),), P())
    sched = collective_schedule(sm, jnp.ones((8, 2), jnp.float32))
    assert sched.issues and "while_loop" in sched.issues[0]
    assert any("while" in op.context for op in sched.ops)


def test_diff_schedules_names_first_divergence(hvd, mesh8):
    def one(v):
        return lax.psum(v, "data")

    def two(v):
        return lax.all_gather(
            lax.psum(v, "data"), "data", axis=0, tiled=True
        )

    x = jnp.ones((8, 2), jnp.float32)
    a = collective_schedule(_smap(one, mesh8, (P("data"),), P()), x)
    b = collective_schedule(
        _smap(two, mesh8, (P("data"),), P("data")), x
    )
    d = diff_schedules(a, b)
    assert d is not None and d["index"] == 1
    assert "extra" in d["reason"]
    assert diff_schedules(a, a) is None


def test_instrumented_step_unwrapped(hvd, mesh8):
    """collective_schedule sees through the InstrumentedStep wrapper the
    train-step builders apply."""
    from horovod_tpu.training import instrument_step

    def fn(v):
        return lax.psum(v, "data")

    sm = jax.jit(_smap(fn, mesh8, (P("data"),), P()))
    wrapped = instrument_step(sm)
    sched = collective_schedule(wrapped, jnp.ones((8, 2), jnp.float32))
    assert sched.counts() == {"psum": 1}


def test_schedule_json_roundtrip(hvd, mesh8):
    def fn(v):
        return lax.psum(v, "data")

    sched = collective_schedule(
        _smap(fn, mesh8, (P("data"),), P()), jnp.ones((8, 2), jnp.float32)
    )
    blob = sched.to_json()
    assert blob["fingerprint"] == sched.fingerprint()
    assert blob["ops"][0][0] == "psum"


# --------------------------------------------------------------------------
# the sync-mode matrix: pinned fingerprints


def _matrix_params():
    rng = np.random.RandomState(0)
    # w is 2048 elements — above MIN_QUANT_ELEMS (1024), so int8 cells
    # exercise the quantized ring; b (32) stays below the floor and rides
    # uncompressed beside it (the mixed-tree case).
    return {
        "w": jnp.asarray(rng.randn(64, 32).astype(np.float32) * 0.1),
        "b": jnp.zeros((32,), jnp.float32),
    }


def _matrix_loss(p, x, y):
    return jnp.mean((x @ p["w"] + p["b"][None] - y) ** 2)


_COMPRESSIONS = {
    "none": lambda: Compression.none,
    "fp16": lambda: Compression.fp16,
    "int8": lambda: Compression.int8,
    "powersgd": lambda: Compression.powersgd(2),
}


def _build_cell(sync: str, comp_name: str, overlap: bool = False):
    comp = _COMPRESSIONS[comp_name]()
    ef = comp_name != "none"
    if overlap:
        # 4096-byte buckets split w (2048 f32) into two full buckets with
        # b riding a third — int8 cells get two >=floor quantized buckets
        # beside an uncompressed small one (the mixed case)
        kw = dict(overlap=True, bucket_bytes=4096)
    else:
        # explicit False (not unset): the monolithic cells must stay
        # monolithic even under HOROVOD_OVERLAP=1 in the environment
        kw = dict(overlap=False)
    dtx = hvd.DistributedOptimizer(
        optax.adam(1e-2),
        compression=comp,
        error_feedback=ef,
        shard_optimizer=(sync == "zero1"),
        **kw,
    )
    p = _matrix_params()
    s = dtx.init(p)
    ax = hvd.data_axis()
    mesh = hvd.mesh()
    opt_spec = P(ax) if sync == "zero1" else P()

    def step(pp, ss, x, y):
        l, g = jax.value_and_grad(_matrix_loss)(pp, x, y)
        u, ss = dtx.update(g, ss, pp)
        pp = optax.apply_updates(pp, u)
        return pp, ss, allreduce(l, Average, axis=ax)

    sm = _smap(
        step, mesh, (P(), opt_spec, P(ax), P(ax)), (P(), opt_spec, P())
    )
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, 64), jnp.float32)
    y = jnp.asarray(rng.randn(16, 32), jnp.float32)
    return sm, (p, s, x, y)


def _check_cell(key: str, sched: Schedule, pins: dict):
    entry = {
        "fingerprint": sched.fingerprint(),
        "ops": [op.to_json() for op in sched.ops],
        "issues": list(sched.issues),
    }
    if REGEN:
        pins[key] = entry
        return
    assert key in pins, (
        f"no pinned fingerprint for cell {key}; regenerate with "
        f"HVD_REGEN_FINGERPRINTS=1 after reviewing the schedule"
    )
    pinned = pins[key]
    assert entry["ops"] == pinned["ops"], (
        f"collective schedule changed for {key}:\n"
        f"  pinned: {pinned['ops']}\n  got:    {entry['ops']}\n"
        f"an intentional change must be re-pinned with "
        f"HVD_REGEN_FINGERPRINTS=1"
    )
    assert entry["fingerprint"] == pinned["fingerprint"]
    assert not sched.issues, sched.issues


def _load_pins() -> dict:
    if REGEN and not FINGERPRINT_FILE.exists():
        return {}
    with open(FINGERPRINT_FILE, encoding="utf-8") as f:
        return json.load(f)


def _save_pins(pins: dict) -> None:
    FINGERPRINT_FILE.parent.mkdir(parents=True, exist_ok=True)
    with open(FINGERPRINT_FILE, "w", encoding="utf-8") as f:
        json.dump(pins, f, indent=1, sort_keys=True)
        f.write("\n")


def test_matrix_fingerprints_flat(hvd):
    """8 flat cells: {allreduce, ZeRO-1} × {none, fp16, int8, powersgd}
    on the 1-axis 8-device mesh, schedules pinned exactly."""
    pins = _load_pins()
    scheds = {}
    for sync in ("allreduce", "zero1"):
        for comp in ("none", "fp16", "int8", "powersgd"):
            fn, args = _build_cell(sync, comp)
            sched = collective_schedule(fn, *args)
            scheds[f"{sync}|{comp}|flat"] = sched
            _check_cell(f"{sync}|{comp}|flat", sched, pins)
    if REGEN:
        _save_pins(pins)
    # structural cross-checks (fingerprint-independent, so they hold even
    # across a re-pin): ZeRO-1 swaps the gradient allreduce for a
    # reduce-scatter + all-gather pair, and int8 cells really move s8
    assert scheds["zero1|none|flat"].counts().get("reduce_scatter", 0) >= 1
    assert scheds["zero1|none|flat"].counts().get("all_gather", 0) >= 1
    int8_ops = scheds["allreduce|int8|flat"].ops
    assert any(op.dtype == "int8" for op in int8_ops), (
        "int8 cell carries no s8 collective — the quantized ring is not "
        "being traced"
    )
    with pytest.raises(ScheduleDivergence):
        assert_same_schedule(
            scheds["allreduce|none|flat"], scheds["zero1|none|flat"]
        )


def test_matrix_fingerprints_overlap(hvd):
    """ISSUE 10: the bucketed (overlap) cells {allreduce, ZeRO-1} ×
    {none, int8} on the flat mesh — pinned like the monolithic 16, with
    structural pins that the bucketed step issues K interleaved
    collectives rather than one: ZeRO-1 swaps the single per-dtype
    reduce-scatter for one PER BUCKET (the update still returns through
    a single trailing all-gather), allreduce mode swaps the per-leaf
    psums for per-bucket flat psums."""
    pins = _load_pins()
    scheds = {}
    for sync in ("allreduce", "zero1"):
        for comp in ("none", "int8"):
            fn, args = _build_cell(sync, comp, overlap=True)
            sched = collective_schedule(fn, *args)
            scheds[f"{sync}|{comp}"] = sched
            _check_cell(f"{sync}|{comp}|flat|overlap", sched, pins)
    if REGEN:
        _save_pins(pins)
    # K interleaved collectives, not one: >= 2 gradient buckets
    z = scheds["zero1|none"].counts()
    assert z.get("reduce_scatter", 0) + z.get("psum_scatter", 0) >= 2
    assert z.get("all_gather", 0) == 1, (
        "bucketed ZeRO-1 must keep the SINGLE trailing all-gather"
    )
    a = scheds["allreduce|none"].counts()
    assert a.get("psum", 0) >= 4  # 3 gradient buckets + the loss psum
    assert any(
        op.dtype == "int8" for op in scheds["zero1|int8"].ops
    ), "overlap int8 cell carries no s8 collective"
    # and the overlap cells really diverge from the monolithic pins
    assert pins["zero1|none|flat"]["fingerprint"] != \
        scheds["zero1|none"].fingerprint()


def test_overlap_false_cells_pin_byte_identical_defaults(hvd):
    """The default path provably didn't move: an explicit
    ``overlap=False`` build reproduces the SAME pinned fingerprints as
    the original 16 cells (kwarg plumbing cannot leak into the
    monolithic schedule)."""
    pins = _load_pins()
    for sync in ("allreduce", "zero1"):
        for comp in ("none", "int8"):
            fn, args = _build_cell(sync, comp, overlap=False)
            sched = collective_schedule(fn, *args)
            assert sched.fingerprint() == \
                pins[f"{sync}|{comp}|flat"]["fingerprint"], (
                    f"monolithic cell {sync}|{comp} moved"
                )


def test_matrix_fingerprints_hierarchical():
    """8 hierarchical cells: same sync×compression grid over the 2×4
    (cross, local) host mesh with HOROVOD_HIERARCHICAL_ALLREDUCE on."""
    from horovod_tpu.parallel.mesh import build_host_mesh
    from horovod_tpu.ops.hierarchical import set_hierarchical

    hvd.init(mesh=build_host_mesh(local=4))
    set_hierarchical(True)
    try:
        pins = _load_pins()
        for sync in ("allreduce", "zero1"):
            for comp in ("none", "fp16", "int8", "powersgd"):
                fn, args = _build_cell(sync, comp)
                sched = collective_schedule(fn, *args)
                _check_cell(f"{sync}|{comp}|hier", sched, pins)
        if REGEN:
            _save_pins(pins)
    finally:
        set_hierarchical(None)
        hvd.shutdown()


def _build_zero3_cell():
    """ZeRO-3 gather-on-use cell over the SAME matrix params: the gather
    wire is env-resolved (HOROVOD_FSDP_WIRE), so the caller sets it
    before building."""
    from horovod_tpu import optim as _optim

    dtx = hvd.DistributedOptimizer(optax.adam(1e-2), shard_params=True)
    fp = hvd.fsdp_pack_params(_matrix_params())
    s = dtx.init(fp)
    ax = hvd.data_axis()
    mesh = hvd.mesh()

    def step(fpp, ss, x, y):
        def loss(f):
            return _matrix_loss(_optim.fsdp_gather_params(f), x, y)

        l, g = jax.value_and_grad(jax.checkpoint(loss))(fpp)
        u, ss = dtx.update(g, ss, fpp)
        fpp = optax.apply_updates(fpp, u)
        return fpp, ss, allreduce(l, Average, axis=ax)

    sm = _smap(
        step, mesh, (P(ax), P(ax), P(ax), P(ax)), (P(ax), P(ax), P())
    )
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, 64), jnp.float32)
    y = jnp.asarray(rng.randn(16, 32), jnp.float32)
    return sm, (fp, s, x, y)


def test_matrix_fingerprints_zero3(hvd, monkeypatch):
    """ISSUE 20: the ZeRO-3 cells {none, int8 gather wire} on the flat
    mesh. Structural pins: the step carries the param all-gathers
    (forward + checkpoint re-gather) AND the gather-transpose
    reduce-scatter; the int8 cell really moves s8 on the gather legs."""
    pins = _load_pins()
    scheds = {}
    for wire in ("none", "int8"):
        monkeypatch.setenv("HOROVOD_FSDP_WIRE", wire)
        fn, args = _build_zero3_cell()
        sched = collective_schedule(fn, *args)
        scheds[wire] = sched
        _check_cell(f"zero3|{wire}|flat", sched, pins)
    if REGEN:
        _save_pins(pins)
    c = scheds["none"].counts()
    # one fp32 group: forward gather + backward re-gather
    assert c.get("all_gather", 0) >= 2
    assert c.get("reduce_scatter", 0) + c.get("psum_scatter", 0) >= 1
    assert any(op.dtype == "int8" for op in scheds["int8"].ops), (
        "int8 gather-wire cell carries no s8 collective"
    )
    # the wire changes the schedule (quantized gather kernel), never the
    # gradient leg — both cells keep the same scatter count
    cq = scheds["int8"].counts()
    assert (cq.get("reduce_scatter", 0) + cq.get("psum_scatter", 0)
            == c.get("reduce_scatter", 0) + c.get("psum_scatter", 0))


def test_matrix_fingerprints_zero3_hierarchical(monkeypatch):
    """The ZeRO-3 cells over the 2×4 (cross, local) host mesh with
    hierarchical collectives on — the gather rides the routed ICI/DCN
    composition."""
    from horovod_tpu.parallel.mesh import build_host_mesh
    from horovod_tpu.ops.hierarchical import set_hierarchical

    hvd.init(mesh=build_host_mesh(local=4))
    set_hierarchical(True)
    try:
        pins = _load_pins()
        for wire in ("none", "int8"):
            monkeypatch.setenv("HOROVOD_FSDP_WIRE", wire)
            fn, args = _build_zero3_cell()
            sched = collective_schedule(fn, *args)
            _check_cell(f"zero3|{wire}|hier", sched, pins)
        if REGEN:
            _save_pins(pins)
    finally:
        set_hierarchical(None)
        hvd.shutdown()


def test_tp_block_schedule():
    """ISSUE 20: the tensor-parallel block cell on the 2×4 ("data", "tp")
    mesh — the Megatron split's whole point pinned structurally: exactly
    TWO psums per block (one after the attention projection, one after
    mlp_down), nothing else on the wire."""
    from horovod_tpu.models.transformer import (
        TransformerBlock, default_attention, tp_block_apply,
    )

    hvd.init(axes={"data": 2, "tp": 4})
    try:
        pins = _load_pins()
        dim, heads = 32, 4
        block = TransformerBlock(dim=dim, heads=heads, mlp_ratio=2,
                                 dtype=jnp.float32,
                                 attention_fn=default_attention)
        x = jnp.asarray(
            np.random.RandomState(0).randn(2, 6, dim).astype(np.float32))
        bp = block.init(jax.random.PRNGKey(1), x)["params"]
        fn = _smap(
            lambda p, t: tp_block_apply(p, t, heads=heads, axis="tp"),
            hvd.mesh(), (P(), P()), P())
        sched = collective_schedule(fn, bp, x)
        _check_cell("tp|block|flat", sched, pins)
        if REGEN:
            _save_pins(pins)
        assert sched.counts().get("psum", 0) == 2, (
            "tp_block_apply must cost exactly two psums per block"
        )
        assert sum(sched.counts().values()) == 2, (
            "tp_block_apply must issue nothing but its two psums"
        )
    finally:
        hvd.shutdown()


def test_matrix_equivalence_harness_is_exact(hvd):
    """The property the SyncPipeline refactor will lean on: rebuilding
    the SAME cell twice yields the identical schedule, compared op-by-op
    by assert_same_schedule (not just hash equality)."""
    fn_a, args_a = _build_cell("zero1", "int8")
    fn_b, args_b = _build_cell("zero1", "int8")
    a = collective_schedule(fn_a, *args_a)
    b = collective_schedule(fn_b, *args_b)
    assert_same_schedule(a, b)
    assert a.fingerprint() == b.fingerprint()
