"""Serving engine (ISSUE 13): continuous-batching inference plane on
subscribed weights — paged KV cache, chunked prefill, admission
backpressure, int8 wire ingest on device, staleness → /health 503, and the
canary/promotion/rollback generation rollout.

The acceptance pin: train a tiny transformer LM on the 8-device mesh under
a numerics guard → publish generations → the engine serves them under
continuous batching → a ``grad_spike`` trips the publish gate (the
poisoned generation never reaches the KV) and a gate-less trainer's
poisoned generation is caught by the serving-metrics canary instead —
auto-rollback to G−1 with the engine's weights allclose to the last
healthy commit, and the training step's collective-schedule fingerprint
byte-identical before and after serving (the engine adds no
training-side collectives; the full pinned 20-cell matrix is re-verified
every tier-1 run by ``test_schedule.py``).

Tier-1: deterministic, no sleeps > 0.2s; ``serving`` marker.
"""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from horovod_tpu.models.transformer import TransformerLM, generate  # noqa: E402
from horovod_tpu.observability import metrics  # noqa: E402
from horovod_tpu.resilience import chaos, health  # noqa: E402
from horovod_tpu.run.rendezvous import KVStoreServer  # noqa: E402
from horovod_tpu.serving import (  # noqa: E402
    GenerationRollout,
    InferenceEngine,
    QueueFull,
    WeightPublisher,
    WeightSubscriber,
    protocol,
)
from horovod_tpu.serving.engine import note_subscriber_health  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.serving


@pytest.fixture(autouse=True)
def _fresh():
    from horovod_tpu.serving import publisher as _pub_mod

    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.configure(None)
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()
    yield
    metrics.reset()
    metrics.set_enabled(True)
    health.reset()
    chaos.reset()
    with _pub_mod._ACTIVE_LOCK:
        _pub_mod._ACTIVE.clear()


def _model(depth=2, vocab=97, dim=32, heads=4, max_len=64):
    return TransformerLM(vocab=vocab, dim=dim, depth=depth, heads=heads,
                         mlp_ratio=2, max_len=max_len, dtype=jnp.float32)


def _params(model, seed=0):
    return model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]


def _ragged_prompts(seed, lens, vocab=97):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=l).astype(np.int32) for l in lens]


def _reference_generate(model, params, prompts, max_new):
    """generate() over one right-padded ragged batch; returns each row's
    generated run."""
    tp = max(len(p) for p in prompts)
    pad = np.zeros((len(prompts), tp), np.int32)
    for i, p in enumerate(prompts):
        pad[i, :len(p)] = p
    lens = np.asarray([len(p) for p in prompts], np.int32)
    out = np.asarray(generate(
        model, params, pad, max_new_tokens=max_new, prompt_lens=lens))
    return [out[i, lens[i]:lens[i] + max_new] for i in range(len(prompts))]


# ------------------------------------------------------------ paged cache


class TestPagedAttention:
    def test_paged_gather_matches_contiguous_decode(self):
        from horovod_tpu.ops.flash_attention import (
            decode_attention,
            paged_decode_attention,
        )

        rng = np.random.RandomState(0)
        b, h, hkv, d, page = 2, 4, 2, 8, 4
        n_pages, per_seq = 9, 3
        L = per_seq * page
        q = jnp.asarray(rng.randn(b, 1, h, d).astype(np.float32))
        cache_k = rng.randn(b, L, hkv, d).astype(np.float32)
        cache_v = rng.randn(b, L, hkv, d).astype(np.float32)
        # scatter the contiguous cache into a shuffled page pool
        k_pages = np.zeros((n_pages, page, hkv, d), np.float32)
        v_pages = np.zeros((n_pages, page, hkv, d), np.float32)
        table = np.array([[5, 2, 7], [1, 8, 3]], np.int32)
        for row in range(b):
            for j in range(per_seq):
                pg = table[row, j]
                k_pages[pg] = cache_k[row, j * page:(j + 1) * page]
                v_pages[pg] = cache_v[row, j * page:(j + 1) * page]
        start = jnp.asarray([5, 9], jnp.int32)
        ref = decode_attention(
            q, jnp.asarray(cache_k), jnp.asarray(cache_v), start)
        got = paged_decode_attention(
            q, jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(table), start, page_size=page)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_models_decode_attention_alias_still_works(self):
        from horovod_tpu.models.transformer import _decode_attention

        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 1, 2, 4).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 8, 2, 4).astype(np.float32))
        v = jnp.asarray(rng.randn(1, 8, 2, 4).astype(np.float32))
        out = _decode_attention(q, k, v, jnp.asarray([3], jnp.int32))
        assert out.shape == (1, 1, 2, 4)
        assert np.all(np.isfinite(np.asarray(out)))


# ------------------------------------------------- engine ↔ generate parity


class TestEngineParity:
    def test_greedy_token_identical_to_generate_ragged(self):
        """Acceptance: greedy decode through the paged engine is
        token-identical to models.transformer.generate for a ragged batch
        that overflows the slot count (5 requests through 3 slots —
        sequences join and leave mid-flight by construction)."""
        model = _model()
        params = _params(model)
        prompts = _ragged_prompts(42, (5, 11, 3, 8, 14))
        max_new = 6
        want = _reference_generate(model, params, prompts, max_new)
        eng = InferenceEngine(model, page_size=8, num_pages=40, max_batch=3,
                              prefill_chunk=8, max_seq_len=32)
        eng.set_weights(params, generation=1)
        reqs = [eng.submit(p, max_new, rid=f"r{i}")
                for i, p in enumerate(prompts)]
        eng.run_until_idle()
        for r, w in zip(reqs, want):
            assert r.error is None
            np.testing.assert_array_equal(np.asarray(r.generated), w)
            np.testing.assert_array_equal(
                r.tokens, np.concatenate([r.prompt, w]))
        # everything freed afterwards
        assert eng.scheduler.idle()
        assert eng.scheduler.pages_in_use() == 0

    def test_staggered_joins_leave_tokens_unchanged(self):
        """Sequences submitted while others are mid-decode produce the
        same tokens as the all-at-once reference — batch composition is
        not observable per row."""
        model = _model(depth=1)
        params = _params(model)
        prompts = _ragged_prompts(7, (9, 4, 13, 6))
        max_new = 5
        want = _reference_generate(model, params, prompts, max_new)
        eng = InferenceEngine(model, page_size=8, num_pages=40, max_batch=4,
                              prefill_chunk=8, max_seq_len=32)
        eng.set_weights(params, generation=1)
        first = [eng.submit(p, max_new, rid=f"a{i}")
                 for i, p in enumerate(prompts[:2])]
        for _ in range(3):  # first pair mid-flight
            eng.step()
        late = [eng.submit(p, max_new, rid=f"b{i}")
                for i, p in enumerate(prompts[2:])]
        eng.run_until_idle()
        for r, w in zip(first + late, want):
            assert r.error is None
            np.testing.assert_array_equal(np.asarray(r.generated), w)

    def test_long_prompt_prefill_is_chunked(self):
        """A prompt longer than prefill_chunk takes several prefill
        iterations and still matches generate()."""
        model = _model(depth=1)
        params = _params(model)
        prompts = _ragged_prompts(3, (21,))
        max_new = 4
        want = _reference_generate(model, params, prompts, max_new)
        eng = InferenceEngine(model, page_size=8, num_pages=16, max_batch=2,
                              prefill_chunk=8, max_seq_len=32)
        eng.set_weights(params, generation=1)
        req = eng.submit(prompts[0], max_new, rid="long")
        eng.run_until_idle()
        np.testing.assert_array_equal(np.asarray(req.generated), want[0])
        assert metrics.value("serving_engine_steps", kind="prefill") >= 3
        assert metrics.value(
            "serving_prefill_tokens") == float(len(prompts[0]))

    def test_engine_adds_no_training_side_collectives(self):
        """The compiled engine step contains ZERO collectives — serving
        shares a host with training without perturbing any schedule
        fingerprint."""
        from horovod_tpu.analysis.schedule import collective_schedule

        model = _model(depth=1)
        params = _params(model)
        eng = InferenceEngine(model, page_size=8, num_pages=16, max_batch=2,
                              prefill_chunk=8, max_seq_len=32)
        eng.set_weights(params, generation=1)
        b, c = eng.max_batch, eng.prefill_chunk
        sched = collective_schedule(
            lambda *a: eng._apply(*a),
            eng.arm_params("stable"), eng._cache,
            jnp.zeros((b, c), jnp.int32), jnp.zeros((b, c), jnp.int32),
            jnp.zeros((b, eng.pages_per_seq), jnp.int32))
        assert len(sched.ops) == 0


# --------------------------------------------------- admission / backpressure


class TestAdmission:
    def test_page_pool_exhaustion_backpressures_until_free(self):
        """A head-of-line request that cannot reserve its worst-case pages
        waits in the queue (never evicts an admitted sequence) and admits
        the moment the finishing sequence frees them."""
        model = _model(depth=1)
        params = _params(model)
        # pool: 5 allocatable pages of 8; each request needs 3
        eng = InferenceEngine(model, page_size=8, num_pages=6, max_batch=2,
                              prefill_chunk=8, max_seq_len=24)
        eng.set_weights(params, generation=1)
        prompts = _ragged_prompts(11, (10, 10))
        r1 = eng.submit(prompts[0], 8, rid="one")
        r2 = eng.submit(prompts[1], 8, rid="two")
        eng.step()
        # only one fits: 3 + 3 > 5 pages
        assert eng.scheduler.pages_in_use() == 3
        assert eng.scheduler.queue_depth() == 1
        assert metrics.value("serving_queue_depth") == 1.0
        eng.run_until_idle()
        assert r1.error is None and r2.error is None
        assert eng.scheduler.pages_in_use() == 0
        assert metrics.value("serving_sequences_admitted") == 2.0

    def test_queue_full_rejects_with_metric(self):
        model = _model(depth=1)
        params = _params(model)
        eng = InferenceEngine(model, page_size=8, num_pages=16, max_batch=1,
                              prefill_chunk=8, max_seq_len=16, max_queue=2)
        eng.set_weights(params, generation=1)
        p = _ragged_prompts(5, (4, 4, 4))
        eng.submit(p[0], 2, rid="q0")
        eng.submit(p[1], 2, rid="q1")
        with pytest.raises(QueueFull):
            eng.submit(p[2], 2, rid="q2")
        assert metrics.value(
            "serving_admission_rejected", reason="queue_full") == 1.0
        eng.run_until_idle()

    def test_oversized_request_rejected_loudly(self):
        model = _model(depth=1)
        eng = InferenceEngine(model, page_size=8, num_pages=16, max_batch=1,
                              prefill_chunk=8, max_seq_len=16)
        eng.set_weights(_params(model), generation=1)
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.submit(np.ones(14, np.int32), 8, rid="big")

    @pytest.mark.chaos
    def test_request_burst_charge_overflows_queue_once(self):
        """``HOROVOD_CHAOS=request_burst=N``: N synthetic requests hit the
        queue at one iteration boundary; the overflow is counted, the
        charge fires exactly once, and the engine drains the admitted
        remainder without error."""
        model = _model(depth=1)
        params = _params(model)
        eng = InferenceEngine(model, page_size=8, num_pages=40, max_batch=2,
                              prefill_chunk=8, max_seq_len=16, max_queue=3)
        eng.set_weights(params, generation=1)
        chaos.configure("request_burst=6")
        eng.step()
        assert metrics.value(
            "resilience_chaos_injected", site="request_burst") == 1.0
        assert metrics.value(
            "serving_admission_rejected", reason="queue_full") == 3.0
        eng.run_until_idle()
        # a second boundary does not re-fire the consumed charge
        eng.step()
        assert metrics.value(
            "resilience_chaos_injected", site="request_burst") == 1.0


# -------------------------------------------- wire ingest / device decode


class TestDeviceDecode:
    def test_device_decode_bit_identical_to_host(self):
        """protocol.decode(device=True) lands int8 delta leaves on device
        (scale + int8 buffers, dequant-accumulate in XLA) and the result
        is BIT-identical to the host decode — the publisher-reconstruction
        contract survives the engine's ingest mode."""
        rng = np.random.RandomState(0)
        t0 = {"w": rng.randn(4096).astype(np.float32).reshape(64, 64),
              "b": rng.randn(7).astype(np.float32),
              "n": np.int32(3)}
        t1 = {"w": t0["w"] + 0.01 * rng.randn(64, 64).astype(np.float32),
              "b": t0["b"] + 0.1, "n": np.int32(4)}
        key_payload, _ = protocol.encode(t0)
        base_host = protocol.decode(key_payload)
        base_dev = protocol.decode(key_payload, device=True)
        delta_payload, info = protocol.encode(t1, base_host)
        assert info["kind"] == "delta"
        host = protocol.decode(delta_payload, base_host)
        dev = protocol.decode(delta_payload, base_dev, device=True)
        assert isinstance(dev["w"], jax.Array)
        for k in ("w", "b", "n"):
            np.testing.assert_array_equal(np.asarray(dev[k]),
                                          np.asarray(host[k]))

    def test_poisoned_chain_reroots_with_keyframe_on_next_publish(
            self, monkeypatch):
        """Once a non-finite generation is on the chain (gate disabled),
        a delta against it could never recover (NaN absorbs deltas) — the
        next healthy publish must re-root with a keyframe so subscribers
        escape the poison."""
        monkeypatch.setenv("HOROVOD_PUBLISH_NUMERICS_GATE", "0")
        s = KVStoreServer()
        try:
            pub = WeightPublisher(s, keyframe_every=8, register=False)
            sub = WeightSubscriber(s)
            w = np.arange(2048, dtype=np.float32)
            pub.publish({"params": {"w": w}}, 1)
            pub.publish({"params": {"w": w * np.nan}}, 2)
            gen = pub.publish({"params": {"w": w + 1}}, 3)
            assert gen == 3
            assert pub.keyframe_generation == 3  # re-rooted, not a delta
            sub.poll()
            np.testing.assert_array_equal(sub.weights()["w"], w + 1)
        finally:
            s.close()

    def test_device_subscriber_matches_publisher_reconstruction(self):
        s = KVStoreServer()
        try:
            pub = WeightPublisher(s, keyframe_every=4, register=False)
            sub = WeightSubscriber(s, device=True)
            rng = np.random.RandomState(1)
            w = rng.randn(2048).astype(np.float32)
            for step in range(3):
                w = w + rng.randn(2048).astype(np.float32) * 0.01
                pub.publish({"params": {"w": w}}, step)
                sub.poll()
            assert sub.generation == 3
            got = sub.weights()["w"]
            assert isinstance(got, jax.Array)
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(pub.reconstruction()["w"]))
        finally:
            s.close()


# ------------------------------------------------- staleness → health plane


class TestStalenessHealth:
    def test_stale_subscriber_degrades_health_with_lag_in_reason(self):
        s = KVStoreServer()
        try:
            pub = WeightPublisher(s, register=False)
            sub = WeightSubscriber(s, stale_after=0.05)
            pub.publish({"params": {"w": np.ones(4, np.float32)}}, 1)
            sub.poll()
            note_subscriber_health(sub)
            assert health.health_state() == health.HealthState.HEALTHY
            # age the served generation past the watermark and open a lag
            sub._published_at -= 10.0
            pub.publish({"params": {"w": np.ones(4, np.float32) * 2}}, 2)
            sub._head_seen = 2  # observed head without applying
            note_subscriber_health(sub)
            snap = health.snapshot()
            assert snap["value"] >= int(health.HealthState.DEGRADED)
            assert "stale" in snap["reason"]
            assert "1 generation" in snap["reason"]
            assert metrics.value("serving_subscriber_lag") == 1.0
            assert metrics.value("serving_staleness_seconds") > 5.0
            assert metrics.value("resilience_serving_stale") == 1.0
            # catching up clears the condition IMMEDIATELY (observable
            # state, not stall evidence)
            sub.poll()
            note_subscriber_health(sub)
            assert health.health_state() == health.HealthState.HEALTHY
        finally:
            s.close()

    def test_serving_fresh_never_clears_foreign_degradation(self):
        health.record_retry_exhausted("kv")
        assert health.health_state() == health.HealthState.DEGRADED
        health.record_serving_fresh()
        assert health.health_state() == health.HealthState.DEGRADED

    def test_beat_recovery_drops_staleness_ownership(self):
        """Review pin: once beats recover a staleness-owned DEGRADED, the
        ownership claim is gone — a later foreign degradation must not be
        clearable by record_serving_fresh, and a FATAL keeps its own
        reason on /health even while the weights stay stale."""
        health.record_serving_stale(2, 60.0)
        for _ in range(health.MONITOR.recovery_beats):
            health.beat()
        assert health.health_state() == health.HealthState.HEALTHY
        health.record_retry_exhausted("kv")
        health.record_serving_fresh()
        assert health.health_state() == health.HealthState.DEGRADED
        health.record_fatal("publisher chain corrupt")
        health.record_serving_stale(3, 120.0)
        assert health.MONITOR.reason() == "publisher chain corrupt"

    @pytest.mark.chaos
    def test_subscriber_stall_serves_g_minus_k_without_dropping_sequences(
            self):
        """Acceptance (satellite): under ``subscriber_stall`` the engine
        keeps serving G−k per the degrade-don't-crash contract, in-flight
        sequences complete, and the lag clears on catch-up."""
        model = _model(depth=1)
        params = _params(model)
        s = KVStoreServer()
        try:
            pub = WeightPublisher(s, keyframe_every=8, register=False)
            pub.publish({"params": params}, 1)
            chaos.configure("subscriber_stall=0.05")
            sub = WeightSubscriber(s, device=True)
            eng = InferenceEngine(model, page_size=8, num_pages=24,
                                  max_batch=2, prefill_chunk=8,
                                  max_seq_len=24, subscriber=sub)
            assert eng.poll_weights() == 1
            # trainer races ahead; the engine does NOT poll mid-request
            p2 = jax.tree_util.tree_map(lambda a: np.asarray(a) * 1.01,
                                        jax.device_get(params))
            pub.publish({"params": p2}, 2)
            pub.publish({"params": p2}, 3)
            prompts = _ragged_prompts(9, (6, 9))
            reqs = [eng.submit(p, 4, rid=f"s{i}")
                    for i, p in enumerate(prompts)]
            eng.run_until_idle()
            for r in reqs:
                assert r.error is None and len(r.generated) == 4
            assert eng.arm_generation("stable") == 1  # still G−k
            assert eng.poll_weights() == 3  # catch-up applies the chain
            assert metrics.value(
                "resilience_chaos_injected", site="subscriber_stall") >= 1.0
        finally:
            s.close()


# ------------------------------------------------------------- the rollout


def _canary_rid(roll, i):
    return f"canary-seed-{i}"


class TestRollout:
    def _serve_stack(self, model, params, *, fraction=1.0, min_requests=2):
        s = KVStoreServer()
        pub = WeightPublisher(s, keyframe_every=8, register=False)
        sub = WeightSubscriber(s, device=True)
        eng = InferenceEngine(model, page_size=8, num_pages=40, max_batch=2,
                              prefill_chunk=8, max_seq_len=24)
        events = []
        roll = GenerationRollout(
            eng, sub, canary_fraction=fraction,
            min_canary_requests=min_requests, max_latency_ratio=None,
            on_event=lambda e, g: events.append((e, g)))
        pub.publish({"params": params}, 1)
        roll.poll()
        assert roll.stable_generation == 1
        return s, pub, sub, eng, roll, events

    def test_healthy_generation_canaries_then_promotes(self):
        model = _model(depth=1)
        params = _params(model)
        s, pub, sub, eng, roll, events = self._serve_stack(model, params)
        try:
            p2 = jax.tree_util.tree_map(
                lambda a: np.asarray(a) * 1.01, jax.device_get(params))
            pub.publish({"params": p2}, 2)
            roll.poll()
            assert roll.canary_generation == 2
            assert metrics.value("serving_rollout_state") == 1.0
            prompts = _ragged_prompts(21, (5, 7, 4))
            reqs = [roll.submit(_canary_rid(roll, i), p, 3)
                    for i, p in enumerate(prompts)]
            roll.drain()
            assert all(r.error is None for r in reqs)
            assert roll.stable_generation == 2
            assert roll.canary_generation is None
            assert eng.arm_generation("stable") == 2
            assert eng.arm_generation("canary") is None
            assert ("canary_started", 2) in events
            assert ("promoted", 2) in events
            assert metrics.value(
                "serving_rollouts", outcome="promoted") == 1.0
        finally:
            s.close()

    def test_poisoned_generation_rolls_back_to_stable(self, monkeypatch):
        """A generation a gate-less trainer shipped (non-finite weights)
        errors every canary request → auto-rollback to G−1, generation
        vetoed forever, stable arm untouched and allclose to the last
        healthy commit."""
        monkeypatch.setenv("HOROVOD_PUBLISH_NUMERICS_GATE", "0")
        model = _model(depth=1)
        params = _params(model)
        s, pub, sub, eng, roll, events = self._serve_stack(model, params)
        try:
            healthy = jax.device_get(pub.reconstruction())
            poisoned = jax.tree_util.tree_map(
                lambda a: np.asarray(a) * np.nan, jax.device_get(params))
            pub.publish({"params": poisoned}, 2)
            roll.poll()
            assert roll.canary_generation == 2
            prompts = _ragged_prompts(31, (5, 6))
            reqs = [roll.submit(_canary_rid(roll, i), p, 3)
                    for i, p in enumerate(prompts)]
            roll.drain()
            assert all(r.error == "non-finite logits" for r in reqs)
            assert roll.stable_generation == 1
            assert 2 in roll.vetoed
            assert ("rolled_back", 2) in events
            assert metrics.value(
                "serving_rollouts", outcome="rolled_back") == 1.0
            # stable params ARE the last healthy commit
            for got, want in zip(
                jax.tree_util.tree_leaves(eng.arm_params("stable")),
                jax.tree_util.tree_leaves(healthy),
            ):
                np.testing.assert_array_equal(np.asarray(got),
                                              np.asarray(want))
            # the vetoed generation never re-canaries; the next healthy
            # one does, and serving still works end to end
            roll.poll()
            assert roll.canary_generation is None
            p3 = jax.tree_util.tree_map(
                lambda a: np.asarray(a) * 1.01, healthy)
            pub.publish({"params": p3}, 3)
            roll.poll()
            assert roll.canary_generation == 3
            reqs = [roll.submit(_canary_rid(roll, 10 + i), p, 2)
                    for i, p in enumerate(prompts)]
            roll.drain()
            assert all(r.error is None for r in reqs)
            assert roll.stable_generation == 3
        finally:
            s.close()

    def test_promotion_mid_flight_drains_old_stable_coherently(self):
        """Review pin: promoting a canary while a STABLE sequence is
        mid-decode must not swap its weights — the in-flight sequence
        parks on a drain arm and its tokens stay identical to generate()
        under the OLD generation."""
        model = _model(depth=1)
        p1 = _params(model, seed=0)
        p2 = jax.tree_util.tree_map(
            lambda a: np.asarray(a) * 1.5, jax.device_get(p1))
        prompts = _ragged_prompts(13, (9,))
        want_old = _reference_generate(model, p1, prompts, 8)
        eng = InferenceEngine(model, page_size=8, num_pages=24, max_batch=2,
                              prefill_chunk=8, max_seq_len=24)
        eng.set_weights(p1, generation=1, arm="stable")
        req = eng.submit(prompts[0], 8, rid="inflight")
        for _ in range(4):  # mid-decode
            eng.step()
        eng.set_weights(p2, generation=2, arm="canary")
        eng.promote_canary()
        assert eng.arm_generation("stable") == 2
        eng.run_until_idle()
        assert req.error is None
        np.testing.assert_array_equal(np.asarray(req.generated), want_old[0])
        assert not [a for a in eng._arms if "drain" in a]  # released

    def test_run_until_idle_without_weights_raises_loudly(self):
        model = _model(depth=1)
        eng = InferenceEngine(model, page_size=8, num_pages=16, max_batch=1,
                              prefill_chunk=8, max_seq_len=16)
        eng.submit(np.asarray([1, 2], np.int32), 2, rid="w0")
        with pytest.raises(RuntimeError, match="no weights installed"):
            eng.run_until_idle()

    def test_route_is_deterministic_split(self):
        model = _model(depth=1)
        params = _params(model)
        s, pub, sub, eng, roll, _ = self._serve_stack(
            model, params, fraction=0.5)
        try:
            p2 = jax.tree_util.tree_map(
                lambda a: np.asarray(a) * 1.01, jax.device_get(params))
            pub.publish({"params": p2}, 2)
            roll.poll()
            arms = {roll.route(f"rid-{i}") for i in range(64)}
            assert arms == {"stable", "canary"}
            for i in range(64):  # same rid → same arm, always
                assert roll.route(f"rid-{i}") == roll.route(f"rid-{i}")
        finally:
            s.close()


# ----------------------------------------------------------- acceptance e2e


@pytest.mark.chaos
def test_e2e_train_publish_serve_canary_rollback(hvd, monkeypatch):
    """THE acceptance drill: train on the 8-device mesh under the numerics
    guard → publish generations → serve under continuous batching →
    (a) a grad_spike trips the publish gate so the poisoned generation
    never arrives (PublishRejected — gate leg), (b) a gate-less trainer's
    poisoned generation is caught by the serving-metrics canary and
    auto-rolled back to G−1 with the engine allclose to the last healthy
    commit (metrics leg), and the training step's collective schedule is
    byte-identical before and after serving (the engine adds no
    training-side collectives; the pinned 20-cell fingerprint matrix is
    separately re-verified by test_schedule.py every run)."""
    from horovod_tpu.analysis.schedule import collective_schedule
    from horovod_tpu.resilience import numerics
    from horovod_tpu.serving import PublishRejected
    from horovod_tpu.training import (
        make_shardmap_train_step,
        replicate,
        shard_batch,
        token_xent,
    )

    monkeypatch.setenv("HOROVOD_NUMERICS_WARMUP", "1")
    monkeypatch.setenv("HOROVOD_NUMERICS_SPIKE_FACTOR", "5.0")
    model = _model(depth=1, vocab=64, dim=32, heads=2, max_len=32)
    params0 = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    # the spike charge compiles INTO the guarded step at trace time
    chaos.configure("grad_spike_at_step=3:500")
    tx = numerics.guard(optax.adam(1e-2))
    step = make_shardmap_train_step(
        model, tx, loss_fn=token_xent, instrument=False, donate=False)
    rng = np.random.RandomState(0)
    toks = rng.randint(1, 64, size=(16, 9)).astype(np.int32)
    xs, ys = shard_batch(toks[:, :-1]), shard_batch(toks[:, 1:])
    params = replicate(jax.tree_util.tree_map(jnp.array, params0))
    opt_state = tx.init(params)

    server = KVStoreServer()
    try:
        pub = WeightPublisher(server, keyframe_every=8, register=False)
        sub = WeightSubscriber(server, device=True)
        eng = InferenceEngine(model, page_size=8, num_pages=24, max_batch=2,
                              prefill_chunk=8, max_seq_len=24)
        roll = GenerationRollout(eng, sub, canary_fraction=1.0,
                                 min_canary_requests=2,
                                 max_latency_ratio=None)

        def train_one():
            nonlocal params, opt_state
            params, _, opt_state, loss = step(params, {}, opt_state, xs, ys)
            return loss

        fp_before = collective_schedule(
            step, params, {}, opt_state, xs, ys).fingerprint()

        # healthy steps 0..2 → G1 (keyframe) + G2 (int8 delta) commit
        train_one()
        assert pub.publish(
            {"params": params, "opt_state": opt_state}, 1) == 1
        roll.poll()
        assert roll.stable_generation == 1
        train_one()
        assert pub.publish(
            {"params": params, "opt_state": opt_state}, 2) == 2
        roll.poll()
        assert roll.canary_generation == 2
        prompts = _ragged_prompts(5, (6, 9), vocab=64)
        reqs = [roll.submit(f"e2e-{i}", p, 4)
                for i, p in enumerate(prompts)]
        roll.drain()
        assert all(r.error is None for r in reqs)
        assert roll.stable_generation == 2  # promoted under traffic
        train_one()

        # the spike: guard step 3 goes BAD in-jit → publish gate refuses,
        # the poisoned generation NEVER reaches the KV head
        train_one()
        assert numerics.verdict(opt_state)["bad_streak"] >= 1
        with pytest.raises(PublishRejected) as ei:
            pub.publish({"params": params, "opt_state": opt_state}, 4)
        assert ei.value.reason == "bad_step"
        roll.poll()
        assert roll.stable_generation == 2  # nothing new arrived
        assert metrics.value(
            "serving_publish_rejected", reason="bad_step") == 1.0

        # streak clears → G3 commits; capture the last healthy commit
        train_one()
        assert numerics.verdict(opt_state)["bad_streak"] == 0
        assert pub.publish(
            {"params": params, "opt_state": opt_state}, 5) == 3
        roll.poll()
        reqs = [roll.submit(f"e2e2-{i}", p, 4)
                for i, p in enumerate(prompts)]
        roll.drain()
        assert roll.stable_generation == 3
        healthy = jax.device_get(pub.reconstruction())

        # metrics leg: a GATE-LESS trainer ships the poison → the canary
        # catches it and auto-rolls back to G−1
        monkeypatch.setenv("HOROVOD_PUBLISH_NUMERICS_GATE", "0")
        poisoned = jax.tree_util.tree_map(
            lambda a: np.asarray(a) * np.nan, jax.device_get(params))
        assert pub.publish({"params": poisoned}, 6) == 4
        roll.poll()
        assert roll.canary_generation == 4
        reqs = [roll.submit(f"e2e3-{i}", p, 3)
                for i, p in enumerate(prompts)]
        roll.drain()
        assert all(r.error == "non-finite logits" for r in reqs)
        assert roll.stable_generation == 3
        assert 4 in roll.vetoed
        assert metrics.value(
            "serving_rollouts", outcome="rolled_back") == 1.0
        for got, want in zip(
            jax.tree_util.tree_leaves(eng.arm_params("stable")),
            jax.tree_util.tree_leaves(healthy),
        ):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=0, atol=0)

        # the engine added no training-side collectives: the training
        # step's schedule fingerprint is byte-identical after serving
        fp_after = collective_schedule(
            step, params, {}, opt_state, xs, ys).fingerprint()
        assert fp_after == fp_before
    finally:
        server.close()


# ------------------------------------------------------------ bench + model


def test_serving_goodput_model_properties():
    from tools.scaling_projection import serving_goodput

    # uniform, batch-aligned workload: no padding waste → ratio 1.0
    out = serving_goodput([16, 16, 16, 16], 8, max_batch=4,
                          prefill_chunk=16)
    assert out["goodput_ratio"] == pytest.approx(1.0)
    # ragged prompts: static pays the padding, continuous does not
    ragged = serving_goodput([4, 16, 7, 12], 8, max_batch=4,
                             prefill_chunk=4)
    assert ragged["goodput_ratio"] > 1.0
    assert ragged["continuous_slot_tokens"] < ragged["static_slot_tokens"]
    # chunk rounding is charged to the continuous arm honestly
    chunky = serving_goodput([1], 1, max_batch=1, prefill_chunk=16)
    assert chunky["continuous_slot_tokens"] == 17


@pytest.mark.slow
def test_bench_serving_ab_rung():
    """bench.py --serving-ab emits ONE JSON line with a measured ratio,
    token-identical parity, and the analytic slot-token model."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--serving-ab"],
        capture_output=True, text=True, env=env, timeout=600, cwd=_REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    d = json.loads(line)
    assert d["metric"] == "serving_ab_goodput_ratio"
    assert d["parity"] == "token-identical"
    assert d["goodput_model"]["goodput_ratio"] > 1.0
    assert d["value"] is None or d["value"] > 0
