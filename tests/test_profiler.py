"""Profiler (XLA device timeline) tests."""

import os

import pytest
import jax.numpy as jnp


def test_timeline_captures_trace(hvd, tmp_path):
    import horovod_tpu.profiler as profiler

    d = str(tmp_path / "trace")
    with profiler.timeline(d):
        with profiler.annotate("allreduce_phase"):
            out = hvd.allreduce(jnp.ones((8, 8)), op=hvd.Sum)
        float(out.sum())
    # jax profiler writes plugins/profile/<ts>/*.xplane.pb
    found = []
    for root, _dirs, files in os.walk(d):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane trace written under {d}"


def test_timeline_double_start_raises(hvd, tmp_path):
    import horovod_tpu.profiler as profiler

    with profiler.timeline(str(tmp_path / "t1")):
        with pytest.raises(RuntimeError, match="already active"):
            profiler.start_timeline(str(tmp_path / "t2"))
    with pytest.raises(RuntimeError, match="no active timeline"):
        profiler.stop_timeline()
