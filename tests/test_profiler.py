"""Profiler (XLA device timeline) tests."""

import os

import pytest
import jax.numpy as jnp


def test_timeline_captures_trace(hvd, tmp_path):
    import horovod_tpu.profiler as profiler

    d = str(tmp_path / "trace")
    with profiler.timeline(d):
        with profiler.annotate("allreduce_phase"):
            out = hvd.allreduce(jnp.ones((8, 8)), op=hvd.Sum)
        float(out.sum())
    # jax profiler writes plugins/profile/<ts>/*.xplane.pb
    found = []
    for root, _dirs, files in os.walk(d):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane trace written under {d}"


def test_timeline_double_start_raises(hvd, tmp_path):
    import horovod_tpu.profiler as profiler

    with profiler.timeline(str(tmp_path / "t1")):
        with pytest.raises(RuntimeError, match="already active"):
            profiler.start_timeline(str(tmp_path / "t2"))
    with pytest.raises(RuntimeError, match="no active timeline"):
        profiler.stop_timeline()


def _counting_step():
    """A run_one whose return records when it was fenced: the value only
    becomes a float through ``float()``, so the order of ``fenced`` entries
    is the order timed_steps drained them."""
    calls = []

    class Scalar:
        def __init__(self, i):
            self.i = i

        def __float__(self):
            calls.append(self.i)
            return float(self.i)

    counter = iter(range(1000))

    def run_one():
        return Scalar(next(counter))

    return run_one, calls


def test_timed_steps_n_less_than_lag():
    """Fewer steps than the pipeline lag: the loop never pops in-flight
    work, so everything must come from the final drain — all values
    returned, in dispatch order."""
    from horovod_tpu.profiler import timed_steps

    run_one, fence_order = _counting_step()
    fenced, dt = timed_steps(run_one, 2, lag=5)
    assert fenced == [0.0, 1.0]
    assert fence_order == [0, 1]
    assert dt >= 0.0


def test_timed_steps_lag_zero_is_fully_synchronous():
    """lag=0 degenerates to fence-every-step: each scalar is fetched
    before the next dispatch (no overlap), still n values in order."""
    from horovod_tpu.profiler import timed_steps

    fence_log = []  # (step, dispatch count AT FENCE TIME)
    dispatched = []

    class Scalar:
        def __init__(self, i):
            self.i = i

        def __float__(self):
            fence_log.append((self.i, len(dispatched)))
            return float(self.i)

    def run_one():
        dispatched.append(len(dispatched))
        return Scalar(dispatched[-1])

    fenced, _ = timed_steps(run_one, 4, lag=0)
    assert fenced == [0.0, 1.0, 2.0, 3.0]
    # step i was fenced before step i+1 was dispatched
    assert fence_log == [(0, 1), (1, 2), (2, 3), (3, 4)]


def test_timed_steps_zero_steps():
    from horovod_tpu.profiler import timed_steps

    run_one, _ = _counting_step()
    fenced, dt = timed_steps(run_one, 0)
    assert fenced == [] and dt >= 0.0


def test_timed_steps_keeps_lag_in_flight():
    """With n > lag the steady-state loop holds exactly ``lag`` scalars in
    flight: when step i is fenced, steps up through i+lag have already been
    dispatched (the overlap that keeps the device pipeline full)."""
    from horovod_tpu.profiler import timed_steps

    lag = 2
    fence_log = []  # (step, dispatch count AT FENCE TIME)
    dispatched = []

    class Scalar:
        def __init__(self, i):
            self.i = i

        def __float__(self):
            fence_log.append((self.i, len(dispatched)))
            return float(self.i)

    def run_one():
        dispatched.append(len(dispatched))
        return Scalar(dispatched[-1])

    fenced, _ = timed_steps(run_one, 6, lag=lag)
    assert fenced == [float(i) for i in range(6)]
    # while the loop is still dispatching, fencing step i happens only
    # after i+lag+1 dispatches (the deque held lag+1 before the pop)
    for i, n_at_fence in fence_log[: 6 - lag]:
        assert n_at_fence == i + lag + 1
