"""Autotuner tests (reference ParameterManager C9 + Bayesian optimization
C10): the native core samples (cycle time, fusion threshold) configurations
scored by bytes/sec, logs a CSV, converges to the best, and — multi-process —
the coordinator's tuned parameters propagate over the wire."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest


@pytest.fixture()
def autotune_env(monkeypatch, tmp_path):
    log = tmp_path / "autotune.csv"
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_LOG", str(log))
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "2")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "4")
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "1")
    return log


def test_autotune_single_process_converges(autotune_env, hvd):
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE

    core = NativeCore(rank=0, size=1)
    try:
        assert core.autotune_active()
        x = np.ones((64,), np.float32)
        # (1 warmup + 4 search) samples x 2 steps each = 10 scored cycles
        for step in range(30):
            h = core.enqueue(f"g{step % 3}", x, REQUEST_ALLREDUCE, op=1)
            h.wait(timeout=30)
            if not core.autotune_active():
                break
        assert not core.autotune_active(), "autotune search never finished"
        assert core.autotune_samples() >= 5
        assert core.autotune_best_score() > 0
        # locked-in best must respect the search bounds
        assert 1.0 <= core.cycle_time_ms <= 100.0
        assert 0 <= core.fusion_threshold <= 64 * 1024 * 1024
    finally:
        core.shutdown()
    text = autotune_env.read_text()
    lines = text.strip().splitlines()
    assert lines[0] == (
        "sample,cycle_time_ms,fusion_threshold_bytes,cache_enabled,"
        "hier_allreduce,hier_allgather,score_bytes_per_sec"
    )
    assert any(line.startswith("best,") for line in lines)
    assert len(lines) >= 6  # header + 5 samples + best


def test_autotune_categorical_dims(autotune_env, hvd, monkeypatch):
    """The GP search space is 5-D: (fusion, cycle, cache-enabled,
    hierarchical-allreduce, hierarchical-allgather) — every categorical dim
    rides the ResponseList like the scalars (reference
    parameter_manager.cc:44-60 tunes the same hierarchical pair). The cache
    bit is applied by the controller; the hierarchical pair by the Python
    data plane (ops/hierarchical) at the same cycle boundary."""
    monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "8")
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE
    from horovod_tpu.ops import hierarchical

    core = NativeCore(rank=0, size=1)
    try:
        x = np.ones((64,), np.float32)
        for step in range(60):
            h = core.enqueue(f"g{step % 3}", x, REQUEST_ALLREDUCE, op=1)
            h.wait(timeout=30)
            if not core.autotune_active():
                break
        assert not core.autotune_active()
        lines = autotune_env.read_text().strip().splitlines()
        samples = [ln for ln in lines[1:] if not ln.startswith("best,")]
        cache_col = [int(ln.split(",")[3]) for ln in samples]
        hier_ar_col = [int(ln.split(",")[4]) for ln in samples]
        hier_ag_col = [int(ln.split(",")[5]) for ln in samples]
        # the categorical dims are sampled and logged every round. (Whether
        # BOTH values appear depends on noisy timing scores steering the
        # EI argmax — asserting {0,1} exactly would flake under load; the
        # behavioral proof that the toggles are real lives in
        # test_cache_disabled_still_negotiates, the applied-value checks
        # below, and test_two_process_hier_toggle_broadcast.)
        assert len(cache_col) >= 5 and set(cache_col) <= {0, 1}, cache_col
        assert set(hier_ar_col) <= {0, 1}, hier_ar_col
        assert set(hier_ag_col) <= {0, 1}, hier_ag_col
        best = [ln for ln in lines if ln.startswith("best,")][0]
        best_cache = int(best.split(",")[3])
        best_hier_ar = int(best.split(",")[4])
        best_hier_ag = int(best.split(",")[5])
        # a few cycles after lock-in the broadcast values are applied — the
        # cache bit on the controller, the hierarchical pair in the Python
        # strategy globals
        import time

        deadline = time.time() + 5
        while time.time() < deadline:
            if (
                core.cache_enabled() == bool(best_cache)
                and core.hier_allreduce() == best_hier_ar
                and core.hier_allgather() == best_hier_ag
            ):
                break
            time.sleep(0.05)
        assert core.cache_enabled() == bool(best_cache)
        assert core.hier_allreduce() == best_hier_ar
        assert core.hier_allgather() == best_hier_ag
        # one more negotiated op so the exec callback carries the final pair
        h = core.enqueue("g_final", x, REQUEST_ALLREDUCE, op=1)
        h.wait(timeout=30)
        assert hierarchical.enabled() == bool(best_hier_ar)
        assert hierarchical.allgather_enabled() == bool(best_hier_ag)
    finally:
        core.shutdown()
        hierarchical.set_hierarchical(None)
        hierarchical.set_hierarchical_allgather(None)


def test_cache_disabled_still_negotiates(hvd, monkeypatch, tmp_path):
    """With the cache forced off every step renegotiates by name list —
    results stay correct (the toggle changes the protocol path, not the
    data plane)."""
    monkeypatch.delenv("HOROVOD_AUTOTUNE", raising=False)
    from horovod_tpu import core as core_mod
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE

    core = NativeCore(rank=0, size=1)
    try:
        assert core.cache_enabled()  # default on
        core.set_cache_enabled(False)
        assert not core.cache_enabled()
        x = np.arange(8, dtype=np.float32)
        for step in range(4):
            h = core.enqueue("same_name", x, REQUEST_ALLREDUCE, op=1)
            out = np.asarray(h.wait(timeout=30))
        np.testing.assert_allclose(out, x * hvd.size())
        core.set_cache_enabled(True)
        assert core.cache_enabled()
    finally:
        core.shutdown()


def test_autotune_off_by_default(hvd, tmp_path, monkeypatch):
    monkeypatch.delenv("HOROVOD_AUTOTUNE", raising=False)
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE

    core = NativeCore(rank=0, size=1)
    try:
        assert not core.autotune_active()
        h = core.enqueue("t", np.ones((4,), np.float32), REQUEST_ALLREDUCE, op=1)
        h.wait(timeout=30)
        assert core.autotune_samples() == 0
    finally:
        core.shutdown()


WORKER = textwrap.dedent(
    """
    import os, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE

    rank = int(sys.argv[1])
    port = int(sys.argv[2])
    os.environ["HOROVOD_CYCLE_TIME"] = "1"
    os.environ["HOROVOD_AUTOTUNE"] = "1"
    os.environ["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"] = "1"
    os.environ["HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"] = "2"
    os.environ["HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"] = "3"
    hvd.init()
    core = NativeCore(rank=rank, size=2, coordinator_host="127.0.0.1",
                      coordinator_port=port)
    x = np.ones((128,), np.float32)
    default_cycle = 1.0
    saw_tuned = False
    for step in range(40):
        h = core.enqueue(f"g{step % 2}", x, REQUEST_ALLREDUCE, op=1)
        h.wait(timeout=30)
        if abs(core.cycle_time_ms - default_cycle) > 1e-9:
            saw_tuned = True
    # worker (rank 1) runs no tuner of its own: any parameter change there
    # proves coordinator->worker propagation over the ResponseList wire
    print(f"rank{rank}: saw_tuned={saw_tuned} cycle={core.cycle_time_ms:.3f} "
          f"fusion={core.fusion_threshold}", flush=True)
    print(f"rank{rank}: cache_enabled={core.cache_enabled()}", flush=True)
    core.shutdown()
    print(f"rank{rank}: done", flush=True)
    """
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_autotune_params_propagate_to_workers(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", str(script), str(r), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for r in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for r, out in enumerate(outs):
        assert f"rank{r}: done" in out, out
        assert f"rank{r}: saw_tuned=True" in out, out
    # the categorical cache dim rides the same broadcast: after the search
    # both ranks must hold the SAME applied toggle (whatever the GP chose)
    cache_vals = {
        line.split("cache_enabled=")[1]
        for out in outs
        for line in out.splitlines()
        if "cache_enabled=" in line
    }
    assert len(cache_vals) == 1, outs
    assert all(p.returncode == 0 for p in procs), outs
