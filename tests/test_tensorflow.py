"""TensorFlow frontend tests, modeled on the reference's pattern of computing
the collective and comparing with local arithmetic plus explicit gradient
checks (``test/test_tensorflow.py:60-455``). Replicated semantics apply:
every in-process "rank" holds the same TF tensor, so Sum scales by size and
Average is the identity — the same invariant the reference asserts when all
ranks feed identical data."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.tensorflow as hvd  # noqa: E402


@pytest.fixture()
def tfhvd():
    hvd.init()
    yield hvd
    hvd.shutdown()


def test_allreduce_sum_and_average(tfhvd):
    x = tf.constant(np.random.RandomState(0).randn(3, 4).astype(np.float32))
    out = hvd.allreduce(x, op=hvd.Sum)
    np.testing.assert_allclose(out.numpy(), x.numpy() * hvd.size(), rtol=1e-6)
    out = hvd.allreduce(x, op=hvd.Average)
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-6)


def test_allreduce_prescale_postscale(tfhvd):
    x = tf.ones((2, 2), tf.float32)
    out = hvd.allreduce(x, op=hvd.Sum, prescale_factor=2.0,
                        postscale_factor=0.5)
    np.testing.assert_allclose(out.numpy(), np.full((2, 2), hvd.size()))


def test_allreduce_fp16_compression(tfhvd):
    x = tf.constant(np.random.RandomState(1).randn(8).astype(np.float32))
    out = hvd.allreduce(x, op=hvd.Average, compression=hvd.Compression.fp16)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-2)


def test_allreduce_indexed_slices(tfhvd):
    # IndexedSlices lower to allgather of values+indices
    # (reference tensorflow/__init__.py:78-93)
    n = hvd.size()
    values = tf.constant([[1.0, 2.0], [3.0, 4.0]])
    indices = tf.constant([0, 3], dtype=tf.int64)
    s = tf.IndexedSlices(values, indices, dense_shape=tf.constant([5, 2]))
    out = hvd.allreduce(s, op=hvd.Average)
    assert isinstance(out, tf.IndexedSlices)
    assert out.values.shape[0] == 2 * n
    np.testing.assert_allclose(
        out.values.numpy(), np.tile(values.numpy(), (n, 1)) / n, rtol=1e-6
    )
    np.testing.assert_array_equal(
        out.indices.numpy(), np.tile(indices.numpy(), n)
    )


def test_allreduce_indexed_slices_as_dense(tfhvd):
    values = tf.constant([[1.0, 2.0]])
    s = tf.IndexedSlices(values, tf.constant([1], dtype=tf.int64),
                         dense_shape=tf.constant([3, 2]))
    out = hvd.allreduce(s, op=hvd.Sum, sparse_as_dense=True)
    expected = np.zeros((3, 2), np.float32)
    expected[1] = values.numpy() * hvd.size()
    np.testing.assert_allclose(out.numpy(), expected)


def test_allgather(tfhvd):
    n = hvd.size()
    x = tf.constant(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = hvd.allgather(x)
    assert out.shape[0] == 2 * n
    np.testing.assert_allclose(out.numpy(), np.tile(x.numpy(), (n, 1)))


def test_broadcast(tfhvd):
    x = tf.constant([1.0, 2.0, 3.0])
    out = hvd.broadcast(x, root_rank=0)
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_broadcast_variables(tfhvd):
    v = tf.Variable([1.0, 2.0])
    w = tf.Variable([[3.0]])
    hvd.broadcast_variables([v, w], root_rank=0)
    np.testing.assert_allclose(v.numpy(), [1.0, 2.0])
    np.testing.assert_allclose(w.numpy(), [[3.0]])


def test_allreduce_grad(tfhvd):
    # grad of allreduce is allreduce of the upstream gradient
    # (reference test_tensorflow.py:381-455)
    x = tf.Variable(np.ones((3,), np.float32))
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.allreduce(x, op=hvd.Sum))
    g = tape.gradient(y, x)
    # grad of allreduce IS allreduce of the upstream grad (reference
    # mpi_ops.py:110-143): Sum of identical ones -> size
    np.testing.assert_allclose(g.numpy(), np.full((3,), hvd.size()))

    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.allreduce(x, op=hvd.Average))
    g = tape.gradient(y, x)
    np.testing.assert_allclose(g.numpy(), np.ones((3,)), rtol=1e-6)


def test_broadcast_grad(tfhvd):
    x = tf.Variable(np.ones((2,), np.float32))
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.broadcast(x, root_rank=0))
    g = tape.gradient(y, x)
    # root rank receives the summed gradient (rank()==0 in-process)
    np.testing.assert_allclose(g.numpy(), np.full((2,), hvd.size()))


def test_distributed_gradient_tape(tfhvd):
    w = tf.Variable(2.0)
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = w * w
    g = tape.gradient(loss, w)
    np.testing.assert_allclose(float(g), 4.0, rtol=1e-6)


def test_allreduce_inside_tf_function(tfhvd):
    # the graph-mode bridge (tf.py_function) — the reference's AsyncOpKernel
    # boundary analog
    @tf.function
    def f(t):
        return hvd.allreduce(t, op=hvd.Sum)

    x = tf.ones((4,), tf.float32)
    np.testing.assert_allclose(f(x).numpy(), np.full((4,), hvd.size()))


def test_allreduce_xla_compiled(tfhvd):
    # single-process graphs lower to pure TF math (scale/tile/identity), so
    # jit_compile=True works — no EagerPyFunc in the cluster
    @tf.function(jit_compile=True)
    def f(t):
        return hvd.allreduce(t, op=hvd.Sum)

    x = tf.ones((4,), tf.float32)
    np.testing.assert_allclose(f(x).numpy(), np.full((4,), hvd.size()))


def test_allgather_broadcast_xla_compiled(tfhvd):
    @tf.function(jit_compile=True)
    def f(t):
        return hvd.allgather(t), hvd.broadcast(t, root_rank=0)

    x = tf.ones((2, 3), tf.float32)
    g, b = f(x)
    assert g.shape[0] == 2 * hvd.size()
    np.testing.assert_allclose(b.numpy(), x.numpy())


def test_keras_fit_jit_compile(tfhvd):
    keras = pytest.importorskip("keras")
    import horovod_tpu.keras as hk

    model = keras.Sequential([
        keras.layers.Input(shape=(4,)), keras.layers.Dense(1)
    ])
    model.compile(
        optimizer=hk.DistributedOptimizer(keras.optimizers.SGD(0.01)),
        loss="mse", jit_compile=True,
    )
    x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(16, 1).astype(np.float32)
    hist = model.fit(x, y, batch_size=8, epochs=1, verbose=0)
    assert np.isfinite(hist.history["loss"]).all()


def test_rank_size_exports(tfhvd):
    assert hvd.size() >= 1
    assert 0 <= hvd.rank() < hvd.size()
    assert hvd.xla_built()
    assert not hvd.nccl_built()


def test_adasum_distributed_optimizer_delta(tfhvd):
    """op=Adasum selects the delta-style wrapper; with replicated ranks the
    reduced delta equals the local delta, so it must track the plain
    optimizer exactly (reference ``tensorflow/__init__.py:317-411``)."""
    tf.random.set_seed(5)
    w_init = tf.random.normal([4, 2])
    v = tf.Variable(w_init)
    v_ref = tf.Variable(w_init)
    opt = hvd.DistributedOptimizer(
        tf.optimizers.SGD(0.1), op=hvd.Adasum, backward_passes_per_step=2
    )
    ref_opt = tf.optimizers.SGD(0.1)
    x = tf.random.normal([8, 4])
    for _ in range(4):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean(tf.square(tf.matmul(x, v)))
        (g,) = tape.gradient(loss, [v])
        opt.apply_gradients([(g, v)])
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean(tf.square(tf.matmul(x, v_ref)))
        (g,) = tape.gradient(loss, [v_ref])
        ref_opt.apply_gradients([(g, v_ref)])
    np.testing.assert_allclose(v.numpy(), v_ref.numpy(), rtol=1e-5, atol=1e-6)


def test_dlpack_zero_copy_bridge_on_single_chip_mesh():
    """On a 1-chip mesh the eager TF bridge must cross via dlpack — no host
    copy in either direction (reference's in-graph kernels read device
    buffers directly, tensorflow/mpi_ops.cc:286-473; dlpack is the
    cross-runtime equivalent)."""
    import jax

    from horovod_tpu.tensorflow import mpi_ops

    hvd.shutdown()
    hvd.init(devices=jax.devices()[:1])
    try:
        calls = {"n": 0}
        orig = jax.dlpack.from_dlpack

        def spy(x):
            calls["n"] += 1
            return orig(x)

        jax.dlpack.from_dlpack = spy
        try:
            x = tf.constant(np.arange(12, dtype=np.float32).reshape(3, 4))
            out = hvd.allreduce(x, op=hvd.Sum)
        finally:
            jax.dlpack.from_dlpack = orig
        np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-6)
        assert calls["n"] >= 1, "dlpack import path not taken on 1-chip mesh"
        # boundary-only round trip is also copy-free
        a = mpi_ops._tf_to_jax(x)
        assert isinstance(a, jax.Array)
        t2 = mpi_ops._jax_to_tf(a)
        assert isinstance(t2, tf.Tensor)
        np.testing.assert_allclose(t2.numpy(), x.numpy())
    finally:
        hvd.shutdown()


def test_allgather_grad(tfhvd):
    """Gradient of allgather is the local slice of the upstream gradient
    (reference test_tensorflow.py:680-797): position-weighted sum makes a
    wrong-slice regression visible."""
    x = tf.Variable(np.ones((2, 3), np.float32))
    with tf.GradientTape() as tape:
        g = hvd.allgather(x)  # [size*2, 3] replicated contributions
        w = tf.range(tf.shape(g)[0], dtype=tf.float32)[:, None]
        y = tf.reduce_sum(g * w)
    grad = tape.gradient(y, x).numpy()
    # reference HorovodAllgatherGrad: SUM upstream grads across ranks, then
    # take this rank's slice — replicated ranks make that size * slice.
    # rank()==0 in-process: our slice is rows [0, 2) of the gathered dim.
    expect = hvd.size() * np.tile(
        np.arange(2, dtype=np.float32)[:, None], (1, 3))
    np.testing.assert_allclose(grad, expect, rtol=1e-6)
