"""The bench.py artifact-merge layer — the path the end-of-round driver
actually exercises (a number banked by the round-long watcher at hour 2 must
survive a chip wedged at hour 12; VERDICT r4 item 1). Pure-host logic: no
backend, no subprocesses.

Reference analog: the published-number reporting path of
``examples/tensorflow2_synthetic_benchmark.py`` (it prints its img/s at the
end of a healthy run; this rebuild additionally has to survive UNhealthy
runs, hence the artifact indirection these tests pin).
"""

import argparse
import json
import os
import sys
import time

import bench

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))
from scaling_projection import _resolve_mfu  # noqa: E402


def _write(art_dir, name, data, age_s=0):
    path = os.path.join(art_dir, name)
    with open(path, "w") as f:
        json.dump(data, f)
    if age_s:
        past = time.time() - age_s
        os.utime(path, (past, past))
    return path


def _art(rung, value, rc=0, **kw):
    d = {"metric": kw.pop("metric", f"{rung}_metric"), "value": value,
         "_rung": rung, "_rc": rc}
    d.update(kw)
    return d


def test_best_artifacts_selection(tmp_path):
    art = str(tmp_path)
    # throughput rungs keep the max across captures
    _write(art, "mfu_1.json", _art("mfu", 80.0, mfu_vs_peak=0.40))
    _write(art, "mfu_2.json", _art("mfu", 100.75, mfu_vs_peak=0.51))
    _write(art, "lm_1.json", _art("lm", 9000.0, mfu=0.3))
    _write(art, "lm_2.json", _art("lm", 11000.0, mfu=0.35))
    # cpe2e is a RATIO: the median across captures is reported (an outlier
    # window must not become the round's number), with the capture count
    _write(art, "cpe2e_1.json", _art("cpe2e", 0.61))
    _write(art, "cpe2e_2.json", _art("cpe2e", 0.93))
    _write(art, "cpe2e_3.json", _art("cpe2e", 5.0))
    # resnet artifacts merge only for the benchmarked model
    _write(art, "resnet_1.json",
           _art("resnet", 400.0, metric="resnet50_images_per_sec_per_chip"))
    _write(art, "resnet_2.json",
           _art("resnet", 999.0, metric="resnet101_images_per_sec_per_chip"))
    # failed / valueless / stale captures never win
    _write(art, "mfu_bad.json", _art("mfu", 500.0, rc=1))
    _write(art, "lm_bad.json", _art("lm", None))
    _write(art, "mfu_stale.json", _art("mfu", 900.0, mfu_vs_peak=0.9),
           age_s=14 * 3600)

    # a rung child that lost the chip mid-window and fell back to CPU
    # completes rc==0 with a plausible value — but is NOT a hardware number
    _write(art, "cpe2e_cpu.json", _art("cpe2e", 1.86, platform="cpu"))
    _write(art, "lm_cpu.json", _art("lm", 99000.0, device_kind="cpu"))

    best = bench._best_artifacts(art, "resnet50")
    assert best["mfu"]["value"] == 100.75
    assert best["lm"]["value"] == 11000.0
    assert best["cpe2e"]["value"] == 0.93  # median of [0.61, 0.93, 5.0]
    assert best["cpe2e"]["captures"] == 3
    assert best["resnet"]["value"] == 400.0


def test_emit_merged_aux_fields_without_resnet(tmp_path, capsys):
    """A partial ladder still records hardware numbers: no img/s rung, but
    every other completed rung lands in the single JSON line — including
    the watcher's probe statistics, which make the skip self-documenting."""
    _write(str(tmp_path), "watch_summary.json",
           {"probes": 64, "healthy": 2, "healthy_at": []})
    args = argparse.Namespace(model="resnet50", artifacts=str(tmp_path))
    best = {
        "mfu": _art("mfu", 100.75, mfu_vs_peak=0.5114,
                    device_kind="TPU v5 lite"),
        "lm": _art("lm", 11000.0, mfu=0.35),
        "cpe2e": _art("cpe2e", 0.93),
        "flash": _art("flash", 1.8, equivalent=True, speedup_vs_scan=2.2),
        "trace": _art("trace", 0.5, trace_dir="/tmp/tr"),
    }
    bench._emit_merged(args, best, "tpu-unavailable-all-probe-windows")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] is None
    assert out["skipped"] == "tpu-unavailable-all-probe-windows"
    assert out["watcher_probes"] == 64
    assert out["watcher_healthy_windows"] == 2
    assert out["bf16_matmul_tflops"] == 100.75
    assert out["bf16_matmul_mfu"] == 0.5114
    assert out["transformer_lm_tokens_per_sec_per_chip"] == 11000.0
    assert out["transformer_lm_mfu"] == 0.35
    assert out["control_plane_core_vs_injit_onchip"] == 0.93
    assert out["flash_attention_onchip_ok"] is True
    assert out["xla_trace_dir"] == "/tmp/tr"


def test_emit_merged_resnet_primary(capsys):
    args = argparse.Namespace(model="resnet50")
    res = _art("resnet", 412.5, metric="resnet50_images_per_sec_per_chip",
               unit="img/s/chip", vs_baseline=3.98)
    res["_captured_at"] = "2026-07-31T03:20:00Z"
    bench._emit_merged(args, {"resnet": res}, None)
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 412.5
    assert out["captured_at"] == "2026-07-31T03:20:00Z"
    assert "skipped" not in out
    assert not any(k.startswith("_") for k in out)


def test_sync_evidence_curates_and_rewrites_table(tmp_path):
    """tools/sync_evidence.py copies hardware artifacts into the evidence
    dir and rewrites the captures table between its markers, best per
    rung, skipping CPU fallbacks and failures."""
    import subprocess
    import sys as _sys

    art = tmp_path / "watch"
    art.mkdir()
    _write(str(art), "mfu_1.json",
           _art("mfu", 100.75, mfu_vs_peak=0.51, device_kind="TPU v5 lite",
                _captured_at="2026-07-31T03:17:08Z"))
    _write(str(art), "lm_cpu.json", _art("lm", 9.0, device_kind="cpu"))
    # stale artifact (cross-round contamination guard: same 13h policy as
    # bench._best_artifacts, which sync_evidence reuses)
    _write(str(art), "mfu_stale.json",
           _art("mfu", 999.0, mfu_vs_peak=0.9, device_kind="TPU v5 lite"),
           age_s=14 * 3600)
    doc = tmp_path / "hw.md"
    doc.write_text("head\n<!-- captures:begin -->\nold\n"
                   "<!-- captures:end -->\ntail\n")
    out = subprocess.run(
        [_sys.executable, os.path.join(_REPO, "tools", "sync_evidence.py"),
         "--round", "99", "--artifacts", str(art), "--doc", str(doc),
         "--evidence-dir", str(tmp_path / "evidence")],
        capture_output=True, text=True, cwd=_REPO)
    assert out.returncode == 0, out.stderr
    text = doc.read_text()
    assert "100.75 TFLOP/s" in text and "old" not in text
    assert "999" not in text  # stale capture not published
    table = text.split("captures:begin")[1].split("captures:end")[0]
    assert "tok/s" not in table  # CPU-fallback lm row not published
    assert os.path.exists(str(tmp_path / "evidence" / "r99" / "mfu_1.json"))


def test_resolve_mfu_prefers_measured(tmp_path):
    art = str(tmp_path)
    _write(art, "mfu_a.json", _art("mfu", 80.0, mfu_vs_peak=0.40,
                                   device_kind="TPU v5 lite"))
    _write(art, "mfu_b.json", _art("mfu", 100.0, mfu_vs_peak=0.51,
                                   device_kind="TPU v5 lite"))
    frac, source = _resolve_mfu(art)
    assert frac == 0.51
    assert source.startswith("measured:mfu_b.json")


def test_resolve_mfu_default_without_artifacts(tmp_path):
    frac, source = _resolve_mfu(str(tmp_path / "nothing"))
    assert frac == 0.4
    assert source == "assumed-default"


def test_run_rung_recovers_flushed_result_from_killed_child(tmp_path):
    """bench.py prints its headline img/s line BEFORE the optional trace
    capture; a child the watchdog kills mid-extras must still yield the
    completed measurement (recovered from flushed partial stdout, artifact
    marked _timed_out), and the kill must set last_timed_out so callers
    breathe before re-probing. A fast rc!=0 failure does neither."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(_REPO, "tools"))
    import tpu_window_watcher as w

    art = str(tmp_path)
    code = ("import json,time;"
            "print(json.dumps({'metric':'m','value':42.0}),flush=True);"
            "time.sleep(60)")
    r = w.run_rung("resnet", [_sys.executable, "-c", code], 5, art)
    assert r is not None and r["value"] == 42.0
    assert r["_rc"] == 0 and r["_timed_out"] is True
    assert w.run_rung.last_timed_out is True

    r2 = w.run_rung("mfu", [_sys.executable, "-c", "import sys;sys.exit(3)"],
                    30, art)
    assert r2 is None
    assert w.run_rung.last_timed_out is False

    # a rc==0 CPU-fallback completion is NOT a capture: the ladder must
    # keep retrying the rung on a later genuinely-healthy window
    code_cpu = ("import json;"
                "print(json.dumps({'metric':'m','value':7.0,"
                "'platform':'cpu'}))")
    r3 = w.run_rung("lm", [_sys.executable, "-c", code_cpu], 30, art)
    assert r3 is None


def test_every_ladder_rung_argv_parses(tmp_path):
    """A flag typo in a rung command would burn an entire healthy TPU
    window at runtime; appending --help makes argparse validate the full
    argv (unknown flags error before the help action exits 0) without
    touching any backend. The trace rung is a -c snippet (no argparse)."""
    import subprocess
    import sys as _sys

    _sys.path.insert(0, os.path.join(_REPO, "tools"))
    import tpu_window_watcher as w

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for name, cmd, _cap in w.build_rungs(str(tmp_path)):
        if cmd[1] == "-c":
            continue
        out = subprocess.run(cmd + ["--help"], capture_output=True,
                             text=True, cwd=_REPO, env=env, timeout=120)
        assert out.returncode == 0, f"rung {name}: {out.stderr[-300:]}"


def test_supervise_child_recovers_and_skips(capsys):
    """bench.py's --no-probe parent: a timed-out child whose flushed stdout
    carries a complete result line yields that measurement (timed_out
    marker); one with no line yields the structured skip; a clean child's
    last line passes through."""
    import subprocess
    import sys as _sys

    def spawn(code):
        return subprocess.Popen([_sys.executable, "-c", code],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)

    # flushed result, then hang -> recovered with timed_out
    rc = bench._supervise_child(
        spawn("import json,time;"
              "print(json.dumps({'metric':'m','value':5.0}),flush=True);"
              "time.sleep(60)"), 3, "resnet50")
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and out["value"] == 5.0 and out["timed_out"] is True

    # hang with no output -> structured skip
    bench._supervise_child(spawn("import time;time.sleep(60)"), 3,
                           "resnet50")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] is None
    assert out["skipped"] == "tpu-wedged-during-run"

    # clean exit -> last JSON line passes through verbatim
    bench._supervise_child(
        spawn("import json;print(json.dumps({'metric':'m','value':7.0}))"),
        30, "resnet50")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 7.0 and "timed_out" not in out


def test_wait_for_watcher_rung_lease(tmp_path):
    """The ACTIVE lease records its own watchdog budget ("<pid> <timeout>");
    bench derives staleness from THAT instead of a hardwired 1100 s — a
    lease older than its recorded budget (+reap slack), one naming a dead
    pid, or a bare malformed lease must all release the wait immediately."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(_REPO, "tools"))
    import tpu_window_watcher as w

    art = str(tmp_path)
    active = w.rung_active_file(art)

    def elapsed():
        t0 = time.time()
        bench._wait_for_watcher_rung(w, art, deadline=time.time() + 600)
        return time.time() - t0

    # stale: a 30 s-budget lease aged 300 s is leftover, not a live rung
    # (under the old fixed 1100 s threshold this would have blocked)
    with open(active, "w") as f:
        f.write(f"{os.getpid()} 30")
    past = time.time() - 300
    os.utime(active, (past, past))
    assert elapsed() < 5

    # fresh lease, dead pid -> rung child already gone
    with open(active, "w") as f:
        f.write("4194300 900")
    assert elapsed() < 5

    # partially-written lease (no pid yet)
    with open(active, "w") as f:
        f.write("")
    assert elapsed() < 5


def test_run_rung_lease_records_timeout(tmp_path, monkeypatch):
    """run_rung writes "<pid> <timeout_s>" so bench can derive staleness;
    captured via the child's own view of the lease file."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(_REPO, "tools"))
    import tpu_window_watcher as w

    art = str(tmp_path)
    code = (
        "import json,os;"
        f"lease=open(os.path.join({art!r},'ACTIVE')).read();"
        "print(json.dumps({'metric':'m','value':1.0,'lease':lease}))"
    )
    r = w.run_rung("mfu", [_sys.executable, "-c", code], 77, art)
    assert r is not None
    pid_s, timeout_s = r["lease"].split()
    assert int(pid_s) > 0
    assert timeout_s == "77"
    assert not os.path.exists(w.rung_active_file(art))  # released


def test_artifact_ok_policy(tmp_path):
    import sys as _sys

    _sys.path.insert(0, os.path.join(_REPO, "tools"))
    from tpu_window_watcher import artifact_ok

    assert artifact_ok({"value": 1.0, "_rc": 0, "platform": "tpu"})
    assert artifact_ok({"value": 1.0})  # platform-less host logic tests
    assert not artifact_ok({"value": 1.0, "_rc": 1})
    assert not artifact_ok({"value": None, "_rc": 0})
    assert not artifact_ok({"value": 1.0, "platform": "cpu"})
    assert not artifact_ok({"value": 1.0, "device_kind": "cpu"})


def test_resolve_mfu_ignores_failed_captures(tmp_path):
    """run_rung persists rc!=0 captures too ('a failure report is
    evidence'); a crashed probe's utilization must not become 'measured'."""
    art = str(tmp_path)
    _write(art, "mfu_crashed.json",
           _art("mfu", 180.0, rc=1, mfu_vs_peak=0.91))
    frac, source = _resolve_mfu(art)
    assert (frac, source) == (0.4, "assumed-default")
