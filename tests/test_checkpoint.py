"""Checkpoint helper tests (reference pattern: rank-0 write + broadcast
restore, SURVEY §5.4)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import checkpoint as ckpt


def _state(step):
    return {
        "params": {"w": jnp.full((2, 3), float(step)), "b": jnp.zeros(3)},
        "step": step,
        "meta": {"lr": 0.1, "note": "hello"},
    }


class TestSaveRestore:
    def test_roundtrip(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save(d, 5, _state(5))
        out = ckpt.restore(d, 5)
        np.testing.assert_allclose(np.asarray(out["params"]["w"]), 5.0)
        assert out["step"] == 5
        assert out["meta"] == {"lr": 0.1, "note": "hello"}

    def test_latest_step_discovery(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        for s in (1, 3, 2):
            ckpt.save(d, s, _state(s))
        assert ckpt.latest_step(d) == 3
        out = ckpt.restore(d)  # default: latest
        assert out["step"] == 3

    def test_no_checkpoints(self, hvd, tmp_path):
        assert ckpt.latest_step(str(tmp_path / "none")) is None
        with pytest.raises(FileNotFoundError):
            ckpt.restore(str(tmp_path / "none"))

    def test_overwrite_requires_force(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save(d, 1, _state(1))
        with pytest.raises(FileExistsError):
            ckpt.save(d, 1, _state(1))
        ckpt.save(d, 1, _state(7), force=True)
        assert ckpt.restore(d, 1)["step"] == 7

    def test_partial_write_not_visible(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save(d, 1, _state(1))
        # simulate a crashed writer: leftover temp dir must be invisible
        os.makedirs(os.path.join(d, ".tmp_step_9_junk"))
        assert ckpt.latest_step(d) == 1


class TestManager:
    def test_rotation_keeps_last_n(self, hvd, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
        for s in range(5):
            mgr.save(s, _state(s))
        assert mgr.latest_step() == 4
        kept = sorted(
            int(n.split("_")[1])
            for n in os.listdir(str(tmp_path / "ck"))
            if n.startswith("step_")
        )
        assert kept == [3, 4]
        assert mgr.restore()["step"] == 4


class TestAsyncSave:
    def test_async_roundtrip(self, hvd, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, _state(1), asynchronous=True)
        mgr.wait_until_finished()
        out = mgr.restore(1)
        np.testing.assert_array_equal(out["params"]["w"], _state(1)["params"]["w"])
        assert out["step"] == 1

    def test_async_snapshot_is_taken_at_call(self, hvd, tmp_path):
        """Mutating the (host) state after save() must not leak into the
        checkpoint: the snapshot happens synchronously at the call."""
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        state = {"w": np.ones(4), "step": 1}
        mgr.save(1, state, asynchronous=True)
        state["w"][:] = 99.0
        mgr.wait_until_finished()
        np.testing.assert_array_equal(mgr.restore(1)["w"], np.ones(4))

    def test_async_failure_raises_at_fence(self, hvd, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, _state(1))
        mgr.save(1, _state(2), asynchronous=True)  # exists, no force
        with pytest.raises((FileExistsError, RuntimeError)):
            mgr.wait_until_finished()
        # manager stays usable and the original checkpoint is intact
        assert mgr.restore(1)["step"] == 1

    def test_next_save_fences_pending(self, hvd, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
        for s in range(4):
            mgr.save(s, _state(s), asynchronous=True)
        mgr.wait_until_finished()
        assert mgr.latest_step() == 3
        kept = sorted(
            int(n.split("_")[1])
            for n in os.listdir(str(tmp_path / "ck"))
            if n.startswith("step_")
        )
        assert kept == [2, 3]


class TestResumeEquivalence:
    def test_resume_reproduces_uninterrupted_run(self, hvd, tmp_path):
        """Preemption drill (SURVEY §5.4): params after [train 10] must equal
        params after [train 6, checkpoint, restore, train 4] bit-for-bit —
        deterministic data keys the comparison."""
        import optax
        from horovod_tpu.training import replicate, shard_batch

        tx = hvd.DistributedOptimizer(optax.adam(0.01))
        rng = np.random.RandomState(0)
        w0 = rng.randn(8, 4).astype(np.float32)

        import jax

        @jax.jit
        def step(p, s, x):
            def loss_fn(p):
                return jnp.mean((jnp.tanh(x @ p["w"]) - 0.1) ** 2)

            loss, g = jax.value_and_grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            return optax.apply_updates(p, u), s, loss

        def batches():
            r = np.random.RandomState(1)
            return [
                shard_batch(r.randn(hvd.size() * 2, 8).astype(np.float32))
                for _ in range(10)
            ]

        def fresh():
            p = replicate({"w": jnp.asarray(w0)})
            return p, replicate(tx.init({"w": jnp.asarray(w0)}))

        # uninterrupted
        p, s = fresh()
        for x in batches():
            p, s, _ = step(p, s, x)
        w_full = np.asarray(p["w"])

        # interrupted at step 6 + resumed
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        p, s = fresh()
        xs = batches()
        for i, x in enumerate(xs[:6]):
            p, s, _ = step(p, s, x)
        mgr.save(6, {"params": p, "opt": s}, asynchronous=True)
        del p, s  # "preemption"
        restored = mgr.restore()
        p, s = restored["params"], restored["opt"]
        for x in xs[6:]:
            p, s, _ = step(p, s, x)

        np.testing.assert_array_equal(np.asarray(p["w"]), w_full)
