"""Checkpoint helper tests (reference pattern: rank-0 write + broadcast
restore, SURVEY §5.4)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import checkpoint as ckpt


def _state(step):
    return {
        "params": {"w": jnp.full((2, 3), float(step)), "b": jnp.zeros(3)},
        "step": step,
        "meta": {"lr": 0.1, "note": "hello"},
    }


class TestSaveRestore:
    def test_roundtrip(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save(d, 5, _state(5))
        out = ckpt.restore(d, 5)
        np.testing.assert_allclose(np.asarray(out["params"]["w"]), 5.0)
        assert out["step"] == 5
        assert out["meta"] == {"lr": 0.1, "note": "hello"}

    def test_latest_step_discovery(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        for s in (1, 3, 2):
            ckpt.save(d, s, _state(s))
        assert ckpt.latest_step(d) == 3
        out = ckpt.restore(d)  # default: latest
        assert out["step"] == 3

    def test_no_checkpoints(self, hvd, tmp_path):
        assert ckpt.latest_step(str(tmp_path / "none")) is None
        with pytest.raises(FileNotFoundError):
            ckpt.restore(str(tmp_path / "none"))

    def test_overwrite_requires_force(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save(d, 1, _state(1))
        with pytest.raises(FileExistsError):
            ckpt.save(d, 1, _state(1))
        ckpt.save(d, 1, _state(7), force=True)
        assert ckpt.restore(d, 1)["step"] == 7

    def test_partial_write_not_visible(self, hvd, tmp_path):
        d = str(tmp_path / "ck")
        ckpt.save(d, 1, _state(1))
        # simulate a crashed writer: leftover temp dir must be invisible
        os.makedirs(os.path.join(d, ".tmp_step_9_junk"))
        assert ckpt.latest_step(d) == 1


class TestManager:
    def test_rotation_keeps_last_n(self, hvd, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
        for s in range(5):
            mgr.save(s, _state(s))
        assert mgr.latest_step() == 4
        kept = sorted(
            int(n.split("_")[1])
            for n in os.listdir(str(tmp_path / "ck"))
            if n.startswith("step_")
        )
        assert kept == [3, 4]
        assert mgr.restore()["step"] == 4


class TestAsyncSave:
    def test_async_roundtrip(self, hvd, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, _state(1), asynchronous=True)
        mgr.wait_until_finished()
        out = mgr.restore(1)
        np.testing.assert_array_equal(out["params"]["w"], _state(1)["params"]["w"])
        assert out["step"] == 1

    def test_async_snapshot_is_taken_at_call(self, hvd, tmp_path):
        """Mutating the (host) state after save() must not leak into the
        checkpoint: the snapshot happens synchronously at the call."""
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        state = {"w": np.ones(4), "step": 1}
        mgr.save(1, state, asynchronous=True)
        state["w"][:] = 99.0
        mgr.wait_until_finished()
        np.testing.assert_array_equal(mgr.restore(1)["w"], np.ones(4))

    def test_async_failure_raises_at_fence(self, hvd, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, _state(1))
        mgr.save(1, _state(2), asynchronous=True)  # exists, no force
        with pytest.raises((FileExistsError, RuntimeError)):
            mgr.wait_until_finished()
        # manager stays usable and the original checkpoint is intact
        assert mgr.restore(1)["step"] == 1

    def test_next_save_fences_pending(self, hvd, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
        for s in range(4):
            mgr.save(s, _state(s), asynchronous=True)
        mgr.wait_until_finished()
        assert mgr.latest_step() == 3
        kept = sorted(
            int(n.split("_")[1])
            for n in os.listdir(str(tmp_path / "ck"))
            if n.startswith("step_")
        )
        assert kept == [2, 3]
