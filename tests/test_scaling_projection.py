"""Scaling-projection tool: HLO comm-byte extraction + end-to-end run.

The virtual CPU mesh cannot measure scaling efficiency (all devices share
one host core); `tools/scaling_projection.py` provides the relative signal
instead — comm bytes and FLOPs from the COMPILED step, rolled into the ring
roofline. These tests pin the extraction against ground truth (gradient
bytes == 4 B x param count for the fp32-gradient DP step)."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, os.path.join(_REPO, "tools"))
from scaling_projection import comm_bytes_from_hlo  # noqa: E402


def test_comm_bytes_extraction():
    hlo = """
  %ar0 = f32[1000,512] all-reduce(f32[1000,512] %p0), replica_groups={}
  %ar1 = bf16[256] all-reduce(bf16[256] %p1), replica_groups={}
  %t = (f32[10], s32[4]) all-reduce(%a, %b)
  %ag = f32[64,8] all-gather(f32[8,8] %p2), dimensions={0}
  %other = f32[999] add(f32[999] %x, f32[999] %y)
"""
    want = 1000 * 512 * 4 + 256 * 2 + (10 * 4 + 4 * 4) + 64 * 8 * 4
    assert comm_bytes_from_hlo(hlo) == want


@pytest.mark.slow
def test_projection_end_to_end():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "scaling_projection.py"),
         "--model", "resnet50", "--image-size", "64", "--batch-per-chip", "2",
         "--chips", "8"],
        capture_output=True, text=True, timeout=900, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    # the DP step allreduces every fp32 gradient exactly once: comm bytes
    # must equal 4 B x params to within a few % (loss/batch-stat scalars)
    assert abs(rec["comm_bytes_per_step"] - 4 * rec["params"]) \
        < 0.05 * 4 * rec["params"], rec
    eff = rec["projection"]["8"]
    assert 0.0 < eff["efficiency_serial"] <= 1.0
    assert eff["efficiency_overlapped"] >= eff["efficiency_serial"]
